"""Transformer stack (reference python/paddle/nn/layer/transformer.py:68
MultiHeadAttention, :387-950 TransformerEncoder/Decoder(Layer), Transformer).

TPU-native core: attention goes through the `fused_attention` op
(paddle_tpu.ops.flash_attention — XLA-fused now, Pallas blockwise kernel
behind the same op type), shaped (B, H, S, D) for MXU-friendly einsums.
"""
from __future__ import annotations

import collections

import numpy as np

from ..fluid.dygraph.layers import Layer
from . import functional as F
from .layers_common import Dropout, LayerNorm, Linear

__all__ = ["MultiHeadAttention", "TransformerEncoderLayer",
           "TransformerEncoder", "TransformerDecoderLayer",
           "TransformerDecoder", "Transformer"]


def _convert_attention_mask(attn_mask, dtype="float32"):
    """bool mask (True=keep) -> additive float mask, like the reference."""
    if attn_mask is None:
        return None
    from .. import tensor as T
    if attn_mask.dtype == "bool":
        zeros = T.zeros_like(T.cast(attn_mask, dtype))
        neg = T.full_like(zeros, -1e9)
        return T.where(attn_mask, zeros, neg)
    return attn_mask


class MultiHeadAttention(Layer):
    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None,
                 vdim=None, need_weights=False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        if need_weights:
            raise NotImplementedError(
                "need_weights=True (returning attention probabilities) is "
                "incompatible with the fused attention kernel; use the "
                "reference sdpa path in paddle_tpu.ops.flash_attention")
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _split_heads(self, x):
        from .. import tensor as T
        b, s = x.shape[0], x.shape[1]
        x = T.reshape(x, [b, s, self.num_heads, self.head_dim])
        return T.transpose(x, [0, 2, 1, 3])

    def _merge_heads(self, x):
        from .. import tensor as T
        b, s = x.shape[0], x.shape[2]
        x = T.transpose(x, [0, 2, 1, 3])
        return T.reshape(x, [b, s, self.embed_dim])

    def gen_cache(self, key, value=None, type=None):
        if type == MultiHeadAttention.StaticCache:
            k = self._split_heads(self.k_proj(key))
            v = self._split_heads(self.v_proj(value if value is not None
                                              else key))
            return self.StaticCache(k, v)
        k = self._split_heads(self.k_proj(key))
        v = self._split_heads(self.v_proj(key))
        return self.Cache(k, v)

    def attention_preproj(self, query, key=None, value=None,
                          attn_mask=None, cache=None):
        """Attention WITHOUT the output projection, shaped (B, S, D) —
        the encoder layer's fused epilogue folds out_proj's GEMM into
        its epilogue-fused Pallas program (ops/pallas_block.py), so the
        projection must stay outside the attention op. Returns
        (pre-projection output, new_cache)."""
        from ..ops.flash_attention import scaled_dot_product_attention
        from .. import tensor as T
        key = query if key is None else key
        value = key if value is None else value
        q = self._split_heads(self.q_proj(query))
        if isinstance(cache, self.StaticCache):
            k, v = cache.k, cache.v
            new_cache = cache
        else:
            k = self._split_heads(self.k_proj(key))
            v = self._split_heads(self.v_proj(value))
            if isinstance(cache, self.Cache):
                k = T.concat([cache.k, k], axis=2)
                v = T.concat([cache.v, v], axis=2)
                new_cache = self.Cache(k, v)
            else:
                new_cache = None
        mask = _convert_attention_mask(attn_mask)
        out = scaled_dot_product_attention(
            q, k, v, attn_mask=mask, dropout_p=self.dropout,
            training=self.training)
        return self._merge_heads(out), new_cache

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        out, new_cache = self.attention_preproj(query, key, value,
                                                attn_mask, cache)
        out = self.out_proj(out)
        if cache is not None:
            return out, new_cache
        return out


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self._config = dict(d_model=d_model, nhead=nhead,
                            dim_feedforward=dim_feedforward, dropout=dropout,
                            activation=activation, attn_dropout=attn_dropout,
                            act_dropout=act_dropout,
                            normalize_before=normalize_before,
                            weight_attr=weight_attr, bias_attr=bias_attr)
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout0 = Dropout(dropout)
        self.dropout1 = Dropout(act_dropout)
        self.dropout2 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def _ffn(self, src):
        """linear1 -> activation -> (act_dropout) -> linear2. When the
        activation dropout is off and shapes are MXU-aligned, the whole
        chain runs as ONE Pallas kernel (fluid/ops fused_ffn: the 4H
        intermediate never reaches HBM — the round-5 BERT audit put
        this tier at ~19% of the train step)."""
        act_name = self._config["activation"]
        act_drop = self.dropout1.p if self.training else 0.0
        if act_name in ("gelu", "relu") and act_drop == 0.0 \
                and self.linear1.bias is not None \
                and self.linear2.bias is not None:
            from ..common_ops import run_op
            return run_op(
                "fused_ffn",
                {"X": src, "W1": self.linear1.weight,
                 "B1": self.linear1.bias, "W2": self.linear2.weight,
                 "B2": self.linear2.bias},
                {"activation": act_name})
        return self.linear2(self.dropout1(self.activation(
            self.linear1(src))))

    def _epilogue(self, src, residual, norm, drop):
        """dropout(src) + residual, then LN — the post-LN path runs the
        fused Pallas kernel (one HBM round-trip instead of three;
        fluid/ops fused_dropout_add_ln)."""
        from ..common_ops import run_op
        return run_op(
            "fused_dropout_add_ln",
            {"X": src, "Residual": residual,
             "Scale": norm.weight, "Bias": norm.bias},
            {"dropout_p": drop.p if self.training else 0.0,
             "epsilon": norm._epsilon})

    def _attn_sublayer(self, src, src_mask, cache):
        """Self-attention + its epilogue. Post-LN with a biased
        out-projection runs the whole epilogue — projection GEMM +
        dropout + residual-add + LN — as ONE epilogue-fused program
        (fluid/ops fused_out_ln, ops/pallas_block.py, autobench-gated);
        other configurations keep the composed path."""
        from .. import tensor as T
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        attn = self.self_attn
        if not self.normalize_before and attn.out_proj.bias is not None:
            pre, new_cache = attn.attention_preproj(src, src, src,
                                                    src_mask, cache)
            from ..common_ops import run_op
            out = run_op(
                "fused_out_ln",
                {"X": pre, "W": attn.out_proj.weight,
                 "B": attn.out_proj.bias, "Residual": residual,
                 "Scale": self.norm1.weight, "Bias": self.norm1.bias},
                {"dropout_p": self.dropout0.p if self.training else 0.0,
                 "epsilon": self.norm1._epsilon})
            return out, new_cache
        if cache is not None:
            src, new_cache = attn(src, src, src, src_mask, cache)
        else:
            src = attn(src, src, src, src_mask)
            new_cache = None
        if not self.normalize_before:
            src = self._epilogue(src, residual, self.norm1, self.dropout0)
        else:
            src = T.add(residual, self.dropout0(src))
        return src, new_cache

    def _ffn_sublayer(self, src):
        """FFN + its epilogue. With a gelu/relu activation, no act
        dropout and biased linears, the whole sub-block — (pre)norm +
        linear1 + act + linear2 + dropout + residual (+ postnorm) —
        runs as ONE epilogue-fused program (fluid/ops fused_ffn_block,
        autobench-gated)."""
        from .. import tensor as T
        residual = src
        act_name = self._config["activation"]
        act_drop = self.dropout1.p if self.training else 0.0
        if act_name in ("gelu", "relu") and act_drop == 0.0 \
                and self.linear1.bias is not None \
                and self.linear2.bias is not None:
            from ..common_ops import run_op
            return run_op(
                "fused_ffn_block",
                {"X": src, "W1": self.linear1.weight,
                 "B1": self.linear1.bias, "W2": self.linear2.weight,
                 "B2": self.linear2.bias, "Residual": residual,
                 "Scale": self.norm2.weight, "Bias": self.norm2.bias},
                {"activation": act_name,
                 "norm": "pre" if self.normalize_before else "post",
                 "dropout_p": self.dropout2.p if self.training else 0.0,
                 "epsilon": self.norm2._epsilon})
        if self.normalize_before:
            src = self.norm2(src)
        src = self._ffn(src)
        if not self.normalize_before:
            return self._epilogue(src, residual, self.norm2,
                                  self.dropout2)
        return T.add(residual, self.dropout2(src))

    def forward(self, src, src_mask=None, cache=None):
        src, new_cache = self._attn_sublayer(src, src_mask, cache)
        src = self._ffn_sublayer(src)
        return src if cache is None else (src, new_cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        from .layers_common import LayerList
        self.layers = LayerList(
            [encoder_layer] +
            [_clone_layer(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is not None:
                output, c = layer(output, src_mask, cache[i])
                new_caches.append(c)
            else:
                output = layer(output, src_mask)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self._config = dict(d_model=d_model, nhead=nhead,
                            dim_feedforward=dim_feedforward, dropout=dropout,
                            activation=activation, attn_dropout=attn_dropout,
                            act_dropout=act_dropout,
                            normalize_before=normalize_before,
                            weight_attr=weight_attr, bias_attr=bias_attr)
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(act_dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        from .. import tensor as T
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
            incr_cache = None
        else:
            tgt, incr_cache = self.self_attn(tgt, tgt, tgt, tgt_mask,
                                             cache[0])
        tgt = T.add(residual, self.dropout1(tgt))
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
            static_cache = None
        else:
            tgt, static_cache = self.cross_attn(tgt, memory, memory,
                                                memory_mask, cache[1])
        tgt = T.add(residual, self.dropout2(tgt))
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout3(self.activation(self.linear1(tgt))))
        tgt = T.add(residual, tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        if cache is None:
            return tgt
        return tgt, (incr_cache, static_cache)

    def gen_cache(self, memory):
        incr = self.self_attn.gen_cache(memory)
        static = self.cross_attn.gen_cache(
            memory, memory, type=MultiHeadAttention.StaticCache)
        return incr, static


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        from .layers_common import LayerList
        self.layers = LayerList(
            [decoder_layer] +
            [_clone_layer(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        output = tgt
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                output = layer(output, memory, tgt_mask, memory_mask)
            else:
                output, c = layer(output, memory, tgt_mask, memory_mask,
                                  cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        return [layer.gen_cache(memory) for layer in self.layers]


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        self.d_model = d_model
        self.nhead = nhead
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers,
                                              enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers,
                                              dec_norm)

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        from .. import tensor as T
        import numpy as np
        m = np.triu(np.full((length, length), -1e9, "float32"), k=1)
        from ..tensor.creation import to_tensor
        return to_tensor(m)


def _clone_layer(layer):
    """Fresh layer of the same config with its OWN parameters (deepcopy
    would alias param names in static mode and share init in eager)."""
    return type(layer)(**layer._config)
