"""paddle.nn.initializer (reference python/paddle/nn/initializer/)."""
from ..fluid.initializer import (
    Constant, Normal, TruncatedNormal, Uniform, Xavier, MSRA, Bilinear,
    NumpyArrayInitializer)

XavierNormal = lambda fan_in=None, fan_out=None, name=None: Xavier(
    uniform=False, fan_in=fan_in, fan_out=fan_out)
XavierUniform = lambda fan_in=None, fan_out=None, name=None: Xavier(
    uniform=True, fan_in=fan_in, fan_out=fan_out)
KaimingNormal = lambda fan_in=None, name=None: MSRA(uniform=False,
                                                    fan_in=fan_in)
KaimingUniform = lambda fan_in=None, name=None: MSRA(uniform=True,
                                                     fan_in=fan_in)
Assign = NumpyArrayInitializer

__all__ = ["Constant", "Normal", "TruncatedNormal", "Uniform", "Xavier",
           "MSRA", "Bilinear", "XavierNormal", "XavierUniform",
           "KaimingNormal", "KaimingUniform", "Assign"]
