"""Beam-search decoding (reference operators/beam_search_op.cc +
beam_search_decode_op.cc + python BeamSearchDecoder in
fluid/layers/rnn.py).

TPU redesign: the reference threads LoD beams through per-step ops; here
the whole decode is ONE `lax.scan` with static [batch, beam] state —
jit-able, MXU-batched, no ragged tensors. Finished beams are frozen by
masking their continuation scores so only the EOS row survives.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["beam_search", "greedy_decode"]

_NEG = -1e9


def greedy_decode(logits_fn: Callable, prompt, max_new_tokens: int,
                  eos_id: int | None = None):
    """Reference sequential greedy decode: full-context recompute each
    step, argmax, stop on EOS/max_new_tokens.

    logits_fn(ids [1, T] int32) -> logits [1, T, V]. O(T^2) per token —
    this is the CORRECTNESS oracle the serving tier's paged-KV decode
    (paddle_tpu.serving) is tested token-for-token against, and a
    dependency-free decode for scripts that don't need a KV cache.

    Returns the generated tokens as a python list (prompt excluded).
    """
    ids = np.asarray(prompt, np.int32).reshape(1, -1)
    out: list[int] = []
    for _ in range(max_new_tokens):
        logits = logits_fn(jnp.asarray(ids))
        tok = int(jnp.argmax(logits[0, -1]))
        out.append(tok)
        if eos_id is not None and tok == eos_id:
            break
        ids = np.concatenate([ids, [[tok]]], axis=1)
    return out


def beam_search(step_fn: Callable, batch_size: int, beam_size: int,
                max_len: int, bos_id: int, eos_id: int, init_state=None,
                length_penalty: float = 0.0):
    """Decode `max_len` steps of width-`beam_size` beam search.

    step_fn(tokens [B*K] int32, state) -> (log_probs [B*K, V], new_state)
      state leaves must keep their shapes across steps (scan carry);
      row i of the batch dim corresponds to beam (i // K, i % K).

    Returns (sequences [B, K, max_len] int32, scores [B, K]) sorted best
    beam first, where sequences hold post-BOS tokens padded with eos_id.
    """
    B, K = batch_size, beam_size

    tokens0 = jnp.full((B * K,), bos_id, jnp.int32)
    # only beam 0 is live at t=0 (all beams start identical)
    scores0 = jnp.tile(
        jnp.asarray([0.0] + [_NEG] * (K - 1), jnp.float32), (B,))
    finished0 = jnp.zeros((B * K,), bool)
    seqs0 = jnp.full((B * K, max_len), eos_id, jnp.int32)

    def step(carry, t):
        tokens, scores, finished, seqs, state = carry
        logp, state = step_fn(tokens, state)
        V = logp.shape[-1]
        # frozen beams may only "emit" EOS at no cost
        eos_only = jnp.full((V,), _NEG).at[eos_id].set(0.0)
        logp = jnp.where(finished[:, None], eos_only[None, :], logp)
        cand = scores[:, None] + logp                     # [B*K, V]
        cand = cand.reshape(B, K * V)
        top_s, top_i = jax.lax.top_k(cand, K)             # [B, K]
        src_beam = top_i // V                             # beam index
        tok = (top_i % V).astype(jnp.int32)
        flat_src = (jnp.arange(B)[:, None] * K + src_beam).reshape(-1)
        seqs = seqs[flat_src].at[:, t].set(tok.reshape(-1))
        finished = finished[flat_src] | (tok.reshape(-1) == eos_id)
        carry = (tok.reshape(-1), top_s.reshape(-1), finished, seqs,
                 jax.tree_util.tree_map(lambda s: s[flat_src]
                                        if hasattr(s, "shape") and
                                        s.shape[:1] == (B * K,) else s,
                                        state))
        return carry, None

    if init_state is None:
        init_state = ()
    (tokens, scores, finished, seqs, _), _ = jax.lax.scan(
        step, (tokens0, scores0, finished0, seqs0, init_state),
        jnp.arange(max_len))

    scores = scores.reshape(B, K)
    seqs = seqs.reshape(B, K, max_len)
    if length_penalty:
        lengths = jnp.sum(seqs != eos_id, axis=-1).astype(jnp.float32)
        scores = scores / jnp.power(jnp.maximum(lengths, 1.0),
                                    length_penalty)
    order = jnp.argsort(-scores, axis=-1)
    seqs = jnp.take_along_axis(seqs, order[..., None], axis=1)
    scores = jnp.take_along_axis(scores, order, axis=1)
    return seqs, scores
