"""paddle.nn Layer classes (reference python/paddle/nn/layer/*).

All classes work in both eager and static-graph modes: parameters are created
through LayerHelper (eager Tensors in dygraph, Program Parameters in static),
and the forward composes nn.functional ops.
"""
from __future__ import annotations

import numpy as np

from ..fluid.dygraph.layers import Layer
from ..fluid.initializer import ConstantInitializer, NormalInitializer, XavierInitializer
from ..fluid.param_attr import ParamAttr
from . import functional as F

__all__ = [
    "Linear", "Conv2D", "Conv2DTranspose", "MaxPool2D", "AvgPool2D",
    "AdaptiveAvgPool2D", "AdaptiveMaxPool2D", "BatchNorm", "BatchNorm1D",
    "BatchNorm2D", "BatchNorm3D", "SyncBatchNorm", "LayerNorm", "GroupNorm",
    "InstanceNorm2D", "Embedding", "Dropout", "Dropout2D", "Flatten", "ReLU",
    "ReLU6", "GELU", "Sigmoid", "Tanh", "LeakyReLU", "ELU", "SELU", "Silu",
    "Swish", "Mish", "Hardswish", "Hardsigmoid", "Hardtanh", "PReLU",
    "Softmax", "LogSoftmax", "Softplus", "Softsign", "Sequential",
    "LayerList", "ParameterList", "CrossEntropyLoss", "MSELoss", "L1Loss",
    "NLLLoss", "BCELoss", "BCEWithLogitsLoss", "KLDivLoss", "SmoothL1Loss",
    "MarginRankingLoss", "Pad2D", "Upsample", "UpsamplingNearest2D",
    "Identity", "Conv3D", "MaxPool3D", "AvgPool3D", "CTCLoss",
    "HSigmoidLoss",
]


class Identity(Layer):
    def forward(self, x):
        return x


class Linear(Layer):
    """y = xW + b (reference python/paddle/nn/layer/common.py Linear)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self._dtype = "float32"
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierInitializer())
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True) \
            if bias_attr is not False else None

    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        p = super().create_parameter(shape, attr, dtype, is_bias,
                                     default_initializer)
        return p

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)


class Conv2D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__()
        k = [kernel_size] * 2 if isinstance(kernel_size, int) \
            else list(kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        import math
        std = math.sqrt(2.0 / (k[0] * k[1] * in_channels))
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups] + k, attr=weight_attr,
            default_initializer=NormalInitializer(0.0, std))
        self.bias = self.create_parameter([out_channels], attr=bias_attr,
                                          is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class Conv2DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__()
        k = [kernel_size] * 2 if isinstance(kernel_size, int) \
            else list(kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        self.weight = self.create_parameter(
            [in_channels, out_channels // groups] + k, attr=weight_attr)
        self.bias = self.create_parameter([out_channels], attr=bias_attr,
                                          is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, dilation=self._dilation,
                                  groups=self._groups,
                                  data_format=self._data_format)


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NCHW", name=None):
        super().__init__()
        self._k, self._s, self._p = kernel_size, stride, padding
        self._ceil = ceil_mode
        self._df = data_format

    def forward(self, x):
        return F.max_pool2d(x, self._k, self._s, self._p, self._ceil,
                            data_format=self._df)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._k, self._s, self._p = kernel_size, stride, padding
        self._ceil, self._excl = ceil_mode, exclusive
        self._df = data_format

    def forward(self, x):
        return F.avg_pool2d(x, self._k, self._s, self._p, self._ceil,
                            self._excl, data_format=self._df)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self._os = output_size
        self._df = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self._os, data_format=self._df)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._os = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self._os)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._momentum, self._epsilon = momentum, epsilon
        self._data_format = "NCHW" if data_format in ("NCHW", "NCL", "NCDHW") \
            else "NHWC"
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=ConstantInitializer(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True)
        mean = self.create_parameter(
            [num_features], attr=ParamAttr(trainable=False),
            default_initializer=ConstantInitializer(0.0))
        variance = self.create_parameter(
            [num_features], attr=ParamAttr(trainable=False),
            default_initializer=ConstantInitializer(1.0))
        # running stats are buffers, not trainable params
        self.register_buffer("_mean", mean)
        self.register_buffer("_variance", variance)

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format)


class BatchNorm(_BatchNormBase):
    """1.x-style BatchNorm layer (num_channels first arg)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", **kwargs):
        super().__init__(num_channels, momentum, epsilon, param_attr,
                         bias_attr, data_layout)
        self._act = act

    def forward(self, x):
        y = super().forward(x)
        if self._act:
            from ..common_ops import run_op
            y = run_op(self._act, {"X": y})
        return y


BatchNorm1D = _BatchNormBase
BatchNorm2D = _BatchNormBase
BatchNorm3D = _BatchNormBase


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm.

    Under jit with a batch-sharded input (the executor's DP path /
    TrainStep with a mesh), the mean/var reductions are GLOBAL by SPMD
    semantics — XLA inserts the cross-replica psum, replacing the
    reference's explicit ncclAllReduce (sync_batch_norm_op.cu.h:190);
    tests/test_advice_fixes.py pins this behavior on the 8-device mesh.
    In eager multi-PROCESS mode there is no sharded computation to hook,
    so stats are per-process — forward warns once in that case."""

    _warned = False

    def forward(self, x):
        import jax
        if jax.process_count() > 1 and not isinstance(
                getattr(x, "_value", x), jax.core.Tracer):
            if not SyncBatchNorm._warned:
                import warnings
                warnings.warn(
                    "SyncBatchNorm in eager multi-process mode computes "
                    "per-process statistics; run under a jitted "
                    "data-parallel step for global stats")
                SyncBatchNorm._warned = True
        return super().forward(x)

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        """Swap every _BatchNormBase sublayer for SyncBatchNorm, keeping
        params/buffers (reference nn/layer/norm.py convert_sync_batchnorm
        — previously returned the layer unchanged)."""
        if isinstance(layer, _BatchNormBase) and not isinstance(
                layer, SyncBatchNorm):
            new = SyncBatchNorm.__new__(SyncBatchNorm)
            new.__dict__.update(layer.__dict__)  # shares params/buffers
            return new
        for name, sub in list(layer.named_children()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        ns = [normalized_shape] if isinstance(normalized_shape, int) \
            else list(normalized_shape)
        self._normalized_shape = ns
        self._epsilon = epsilon
        n = int(np.prod(ns))
        self.weight = self.create_parameter(
            [n], attr=weight_attr,
            default_initializer=ConstantInitializer(1.0)) \
            if weight_attr is not False else None
        self.bias = self.create_parameter([n], attr=bias_attr, is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._groups = num_groups
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [num_channels], attr=weight_attr,
            default_initializer=ConstantInitializer(1.0))
        self.bias = self.create_parameter([num_channels], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._groups, self._epsilon, self.weight,
                            self.bias)


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self.scale = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=ConstantInitializer(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias,
                               eps=self._epsilon)


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._padding_idx = padding_idx
        self._sparse = sparse
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=NormalInitializer(0.0, 1.0))

    def forward(self, x):
        return F.embedding(x, self.weight, self._padding_idx, self._sparse)


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p, self.mode = p, mode

    def forward(self, x):
        return F.dropout(x, self.p, training=self.training, mode=self.mode)


Dropout2D = Dropout


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self._start, self._stop = start_axis, stop_axis

    def forward(self, x):
        from .. import tensor as T
        return T.flatten(x, self._start, self._stop)


def _act_layer(name, fn, *fields):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            self._args, self._kwargs = args, kwargs

        def forward(self, x):
            return fn(x, *self._args, **self._kwargs)
    _Act.__name__ = name
    return _Act


ReLU = _act_layer("ReLU", F.relu)
ReLU6 = _act_layer("ReLU6", F.relu6)
GELU = _act_layer("GELU", F.gelu)
Sigmoid = _act_layer("Sigmoid", F.sigmoid)
Tanh = _act_layer("Tanh", F.tanh)
LeakyReLU = _act_layer("LeakyReLU", F.leaky_relu)
ELU = _act_layer("ELU", F.elu)
SELU = _act_layer("SELU", F.selu)
Silu = _act_layer("Silu", F.silu)
Swish = _act_layer("Swish", F.swish)
Mish = _act_layer("Mish", F.mish)
Hardswish = _act_layer("Hardswish", F.hardswish)
Hardsigmoid = _act_layer("Hardsigmoid", F.hardsigmoid)
Hardtanh = _act_layer("Hardtanh", F.hardtanh)
Softplus = _act_layer("Softplus", F.softplus)
Softsign = _act_layer("Softsign", F.softsign)
LogSoftmax = _act_layer("LogSoftmax", F.log_softmax)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, self._axis)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=ConstantInitializer(init))

    def forward(self, x):
        return F.prelu(x, self.weight)


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and \
                layers[0] and isinstance(layers[0][0], (list, tuple)):
            for name, l in layers[0]:
                self.add_sublayer(name, l)
        else:
            for i, l in enumerate(layers):
                if isinstance(l, tuple):
                    self.add_sublayer(l[0], l[1])
                else:
                    self.add_sublayer(str(i), l)

    def forward(self, x):
        for l in self._sub_layers.values():
            x = l(x)
        return x

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        for i, l in enumerate(sublayers or []):
            self.add_sublayer(str(i), l)

    def append(self, l):
        self.add_sublayer(str(len(self._sub_layers)), l)
        return self

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return list(self._sub_layers.values())[idx]
        return self._sub_layers[str(idx if idx >= 0
                                    else len(self._sub_layers) + idx)]

    def __iter__(self):
        return iter(self._sub_layers.values())

    def __len__(self):
        return len(self._sub_layers)


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        for i, p in enumerate(parameters or []):
            self.add_parameter(str(i), p)

    def append(self, p):
        self.add_parameter(str(len(self._parameters)), p)
        return self

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __iter__(self):
        return iter(self._parameters.values())

    def __len__(self):
        return len(self._parameters)


# -- loss layers -------------------------------------------------------------

class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True, name=None):
        super().__init__()
        self._cfg = dict(weight=weight, ignore_index=ignore_index,
                         reduction=reduction, soft_label=soft_label,
                         axis=axis, use_softmax=use_softmax)

    def forward(self, input, label):
        return F.cross_entropy(input, label, **self._cfg)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self._reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self._reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 name=None):
        super().__init__()
        self._w, self._ii, self._red = weight, ignore_index, reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self._w, self._ii, self._red)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self._w, self._red = weight, reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self._w, self._red)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None,
                 name=None):
        super().__init__()
        self._w, self._red, self._pw = weight, reduction, pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, self._w,
                                                  self._red, self._pw)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._red = reduction

    def forward(self, input, label):
        return F.kl_div(input, label, self._red)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self._red, self._delta = reduction, delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self._red, self._delta)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self._m, self._red = margin, reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self._m, self._red)


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self._p = padding if isinstance(padding, (list, tuple)) \
            else [padding] * 4
        self._mode, self._value, self._df = mode, value, data_format

    def forward(self, x):
        return F.pad(x, self._p, self._mode, self._value, self._df)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self._size, self._sf, self._mode = size, scale_factor, mode

    def forward(self, x):
        return F.interpolate(x, self._size, self._sf, self._mode)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest")


class Conv3D(Layer):
    """3D convolution, NCDHW (reference nn/layer/conv.py Conv3D)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__()
        k = [kernel_size] * 3 if isinstance(kernel_size, int) \
            else list(kernel_size)
        self._stride, self._padding = stride, padding
        self._dilation, self._groups = dilation, groups
        self._data_format = data_format
        import math
        std = math.sqrt(2.0 / (k[0] * k[1] * k[2] * in_channels))
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups] + k, attr=weight_attr,
            default_initializer=NormalInitializer(0.0, std))
        self.bias = self.create_parameter([out_channels], attr=bias_attr,
                                          is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 ceil_mode=False, return_mask=False, data_format="NCDHW",
                 name=None):
        super().__init__()
        self._k, self._s, self._p = kernel_size, stride, padding
        self._ceil = ceil_mode

    def forward(self, x):
        return F.max_pool3d(x, self._k, self._s, self._p, self._ceil)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 ceil_mode=False, exclusive=True, divisor_override=None,
                 data_format="NCDHW", name=None):
        super().__init__()
        self._k, self._s, self._p = kernel_size, stride, padding
        self._ceil, self._excl = ceil_mode, exclusive

    def forward(self, x):
        return F.avg_pool3d(x, self._k, self._s, self._p, self._ceil,
                            self._excl)


class CTCLoss(Layer):
    """CTC loss layer (reference nn/layer/loss.py CTCLoss). Takes RAW
    logits [B, T, C] (softmax inside, warp-ctc convention)."""

    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self._blank, self._reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          blank=self._blank, reduction=self._reduction)


class HSigmoidLoss(Layer):
    """Hierarchical softmax (reference nn/layer/loss.py HSigmoidLoss)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if is_custom:
            raise NotImplementedError(
                "custom trees: pass path tables to F.hsigmoid_loss")
        self._num_classes = num_classes
        self.weight = self.create_parameter(
            [num_classes - 1, feature_size], attr=weight_attr)
        self.bias = self.create_parameter([num_classes - 1],
                                          attr=bias_attr, is_bias=True) \
            if bias_attr is not False else None

    def forward(self, input, label):
        return F.hsigmoid_loss(input, label, self._num_classes,
                               self.weight, self.bias)
