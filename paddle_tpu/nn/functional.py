"""paddle.nn.functional (reference python/paddle/nn/functional/)."""
from __future__ import annotations

import numpy as np

from ..common_ops import run_op, run_op_multi

__all__ = [
    "linear", "conv2d", "conv2d_transpose", "max_pool2d", "avg_pool2d",
    "adaptive_avg_pool2d", "adaptive_max_pool2d", "relu", "relu6", "gelu",
    "sigmoid", "tanh", "softmax", "log_softmax", "leaky_relu", "elu", "selu",
    "silu", "swish", "mish", "hardswish", "hardsigmoid", "hardtanh",
    "hardshrink", "softshrink", "tanhshrink", "softplus", "softsign",
    "prelu", "dropout", "embedding", "layer_norm", "batch_norm",
    "instance_norm", "group_norm", "cross_entropy", "softmax_with_cross_entropy",
    "mse_loss", "l1_loss", "nll_loss", "kl_div", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "smooth_l1_loss", "one_hot", "pad",
    "label_smooth", "normalize", "sigmoid_focal_loss", "square_error_cost",
    "log_loss", "margin_ranking_loss", "unfold", "fold", "interpolate", "upsample",
    "conv3d", "max_pool3d", "avg_pool3d", "ctc_loss", "hsigmoid_loss",
]


def linear(x, weight, bias=None, name=None):
    out = run_op("matmul_v2", {"X": x, "Y": weight}, {})
    if bias is not None:
        out = run_op("elementwise_add", {"X": out, "Y": bias}, {"axis": -1})
    return out


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    s = [stride, stride] if isinstance(stride, int) else list(stride)
    p = [padding, padding] if isinstance(padding, int) else list(padding)
    d = [dilation, dilation] if isinstance(dilation, int) else list(dilation)
    algo = "EXPLICIT"
    if isinstance(padding, str):
        algo, p = padding.upper(), [0, 0]
    out = run_op("conv2d", {"Input": x, "Filter": weight},
                 {"strides": s, "paddings": p, "dilations": d,
                  "groups": groups, "padding_algorithm": algo,
                  "data_format": data_format}, out_slot="Output")
    if bias is not None:
        axis = 1 if data_format == "NCHW" else -1
        out = run_op("elementwise_add", {"X": out, "Y": bias},
                     {"axis": axis})
    return out


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     output_size=None, data_format="NCHW", name=None):
    s = [stride, stride] if isinstance(stride, int) else list(stride)
    p = [padding, padding] if isinstance(padding, int) else list(padding)
    d = [dilation, dilation] if isinstance(dilation, int) else list(dilation)
    op = [output_padding] * 2 if isinstance(output_padding, int) \
        else list(output_padding)
    if output_size is not None:
        # output_size disambiguates the stride-ambiguous output shape:
        # convert to output_padding over the default (reference
        # conv_transpose_op.cc)
        hw = x.shape[1:3] if data_format == "NHWC" else x.shape[2:]
        os_ = [output_size] * 2 if isinstance(output_size, int) \
            else list(output_size)
        for i in (0, 1):
            k_eff = d[i] * (weight.shape[2 + i] - 1) + 1
            default = (hw[i] - 1) * s[i] + k_eff - 2 * p[i]
            op[i] = os_[i] - default
            if not 0 <= op[i] < s[i]:
                raise ValueError(
                    f"output_size[{i}]={os_[i]} unreachable: must be in "
                    f"[{default}, {default + s[i] - 1}]")
    out = run_op("conv2d_transpose", {"Input": x, "Filter": weight},
                 {"strides": s, "paddings": p, "dilations": d,
                  "output_padding": op, "groups": groups,
                  "data_format": data_format},
                 out_slot="Output")
    if bias is not None:
        axis = 1 if data_format == "NCHW" else -1
        out = run_op("elementwise_add", {"X": out, "Y": bias},
                     {"axis": axis})
    return out


def _pool2d(x, pooling_type, kernel_size, stride, padding, ceil_mode,
            exclusive=True, adaptive=False, global_pool=False,
            data_format="NCHW"):
    k = [kernel_size] * 2 if isinstance(kernel_size, int) else list(kernel_size)
    s = k if stride is None else (
        [stride] * 2 if isinstance(stride, int) else list(stride))
    p = [padding] * 2 if isinstance(padding, int) else list(padding)
    return run_op("pool2d", {"X": x},
                  {"pooling_type": pooling_type, "ksize": k, "strides": s,
                   "paddings": p, "global_pooling": global_pool,
                   "ceil_mode": ceil_mode, "exclusive": exclusive,
                   "adaptive": adaptive, "data_format": data_format})


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW", name=None):
    return _pool2d(x, "max", kernel_size, stride, padding, ceil_mode,
                   data_format=data_format)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool2d(x, "avg", kernel_size, stride, padding, ceil_mode,
                   exclusive, data_format=data_format)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    os_ = [output_size] * 2 if isinstance(output_size, int) \
        else list(output_size)
    return _pool2d(x, "avg", os_, None, 0, False, adaptive=True,
                   data_format=data_format)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    os_ = [output_size] * 2 if isinstance(output_size, int) \
        else list(output_size)
    return _pool2d(x, "max", os_, None, 0, False, adaptive=True)


def _unary(op_type, **default_attrs):
    def fn(x, name=None, **kw):
        attrs = dict(default_attrs)
        for k, v in kw.items():
            attrs[k] = v
        return run_op(op_type, {"X": x}, attrs)
    fn.__name__ = op_type
    return fn


relu = _unary("relu")
relu6 = _unary("relu6")
sigmoid = _unary("sigmoid")
tanh = _unary("tanh")
silu = _unary("silu")
mish = _unary("mish")
softplus = _unary("softplus")
softsign = _unary("softsign")
tanhshrink = _unary("tanh_shrink")


def gelu(x, approximate=False, name=None):
    return run_op("gelu", {"X": x}, {"approximate": approximate})


def leaky_relu(x, negative_slope=0.01, name=None):
    return run_op("leaky_relu", {"X": x}, {"alpha": negative_slope})


def elu(x, alpha=1.0, name=None):
    return run_op("elu", {"X": x}, {"alpha": alpha})


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return run_op("selu", {"X": x}, {"scale": scale, "alpha": alpha})


def swish(x, name=None):
    return run_op("swish", {"X": x}, {"beta": 1.0})


def hardswish(x, name=None):
    return run_op("hard_swish", {"X": x})


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return run_op("hard_sigmoid", {"X": x},
                  {"slope": slope, "offset": offset})


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return run_op("hard_tanh", {"X": x}, {"t_min": min, "t_max": max})


def hardshrink(x, threshold=0.5, name=None):
    return run_op("hard_shrink", {"X": x}, {"threshold": threshold})


def softshrink(x, threshold=0.5, name=None):
    return run_op("softshrink", {"X": x}, {"lambda": threshold})


def prelu(x, weight, name=None):
    pos = relu(x)
    neg = run_op("elementwise_mul",
                 {"X": run_op("relu", {"X": run_op(
                     "scale", {"X": x}, {"scale": -1.0})}),
                  "Y": weight}, {"axis": 1})
    return run_op("elementwise_sub", {"X": pos, "Y": neg}, {"axis": -1})


def softmax(x, axis=-1, dtype=None, name=None):
    return run_op("softmax", {"X": x}, {"axis": int(axis)})


def log_softmax(x, axis=-1, dtype=None, name=None):
    return run_op("log_softmax", {"X": x}, {"axis": int(axis)})


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    res = run_op_multi("dropout", {"X": x},
                       {"dropout_prob": float(p), "is_test": not training,
                        "dropout_implementation": mode},
                       {"Out": 1, "Mask": 1})
    return res["Out"][0]


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    return run_op("lookup_table_v2", {"Ids": x, "W": weight},
                  {"padding_idx": -1 if padding_idx is None
                   else int(padding_idx), "is_sparse": sparse})


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    ns = [normalized_shape] if isinstance(normalized_shape, int) \
        else list(normalized_shape)
    begin = len(x.shape) - len(ns)
    ins = {"X": x}
    if weight is not None:
        ins["Scale"] = weight
    if bias is not None:
        ins["Bias"] = bias
    res = run_op_multi("layer_norm", ins,
                       {"epsilon": epsilon, "begin_norm_axis": begin},
                       {"Y": 1, "Mean": 1, "Variance": 1})
    return res["Y"][0]


def batch_norm(x, running_mean, running_var, weight, bias, training=False,
               momentum=0.9, epsilon=1e-5, data_format="NCHW", name=None):
    res = run_op_multi(
        "batch_norm",
        {"X": x, "Scale": weight, "Bias": bias, "Mean": running_mean,
         "Variance": running_var},
        {"momentum": momentum, "epsilon": epsilon, "is_test": not training,
         "data_layout": data_format},
        {"Y": 1, "MeanOut": 1, "VarianceOut": 1, "SavedMean": 1,
         "SavedVariance": 1})
    # eager: write back running stats (functional update)
    mo, vo = res["MeanOut"][0], res["VarianceOut"][0]
    if hasattr(running_mean, "_set_value") and mo is not None and training:
        running_mean._set_value(mo._value)
        running_var._set_value(vo._value)
    return res["Y"][0]


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    ins = {"X": x}
    if weight is not None:
        ins["Scale"] = weight
    if bias is not None:
        ins["Bias"] = bias
    res = run_op_multi("instance_norm", ins, {"epsilon": eps},
                       {"Y": 1, "SavedMean": 1, "SavedVariance": 1})
    return res["Y"][0]


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    ins = {"X": x}
    if weight is not None:
        ins["Scale"] = weight
    if bias is not None:
        ins["Bias"] = bias
    res = run_op_multi("group_norm", ins,
                       {"epsilon": epsilon, "groups": num_groups},
                       {"Y": 1, "Mean": 1, "Variance": 1})
    return res["Y"][0]


def one_hot(x, num_classes, name=None):
    return run_op("one_hot_v2", {"X": x}, {"depth": int(num_classes)},
                  stop_gradient=True)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    ins = {"X": label}
    if prior_dist is not None:
        ins["PriorDist"] = prior_dist
    return run_op("label_smooth", ins, {"epsilon": float(epsilon)})


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    if len(pad) == 4 and len(x.shape) == 4:
        return run_op("pad2d", {"X": x},
                      {"paddings": [int(p) for p in pad], "mode": mode,
                       "pad_value": float(value), "data_format": data_format})
    full = [0] * (2 * len(x.shape))
    # paddle's pad spec is last-dim-first pairs like torch
    nd = len(x.shape)
    for i in range(len(pad) // 2):
        dim = nd - 1 - i
        full[2 * dim] = int(pad[2 * i])
        full[2 * dim + 1] = int(pad[2 * i + 1])
    return run_op("pad", {"X": x},
                  {"paddings": full, "pad_value": float(value)})


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    from .. import tensor as T
    n = run_op("p_norm", {"X": x},
               {"porder": float(p), "axis": int(axis), "keepdim": True,
                "epsilon": epsilon})
    n = T.clip(n, min=epsilon)
    return run_op("elementwise_div", {"X": x, "Y": n}, {"axis": -1})


# -- losses ------------------------------------------------------------------

def _reduce_loss(loss, reduction):
    from . import functional as F
    if reduction == "mean":
        return run_op("mean", {"X": loss})
    if reduction == "sum":
        return run_op("reduce_sum", {"X": loss},
                      {"dim": [0], "keep_dim": False, "reduce_all": True})
    return loss


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, name=None):
    if use_softmax:
        res = run_op_multi(
            "softmax_with_cross_entropy",
            {"Logits": input, "Label": label},
            {"soft_label": soft_label, "ignore_index": int(ignore_index),
             "axis": int(axis), "numeric_stable_mode": True},
            {"Loss": 1, "Softmax": 1})
        loss = res["Loss"][0]
    else:
        loss = run_op("cross_entropy", {"X": input, "Label": label},
                      {"soft_label": soft_label,
                       "ignore_index": int(ignore_index)}, out_slot="Y")
    return _reduce_loss(loss, reduction)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    res = run_op_multi("softmax_with_cross_entropy",
                       {"Logits": logits, "Label": label},
                       {"soft_label": soft_label,
                        "ignore_index": int(ignore_index), "axis": int(axis),
                        "numeric_stable_mode": numeric_stable_mode},
                       {"Loss": 1, "Softmax": 1})
    if return_softmax:
        return res["Loss"][0], res["Softmax"][0]
    return res["Loss"][0]


def mse_loss(input, label, reduction="mean", name=None):
    loss = run_op("mse_loss", {"X": input, "Y": label})
    return _reduce_loss(loss, reduction)


def l1_loss(input, label, reduction="mean", name=None):
    d = run_op("elementwise_sub", {"X": input, "Y": label}, {"axis": -1})
    loss = run_op("abs", {"X": d})
    return _reduce_loss(loss, reduction)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    ins = {"X": input, "Label": label}
    if weight is not None:
        ins["Weight"] = weight
    res = run_op_multi("nll_loss", ins,
                       {"reduction": reduction,
                        "ignore_index": int(ignore_index)},
                       {"Out": 1, "Total_weight": 1})
    return res["Out"][0]


def kl_div(input, label, reduction="mean", name=None):
    return run_op("kldiv_loss", {"X": input, "Target": label},
                  {"reduction": reduction})


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    loss = run_op("bce_loss", {"X": input, "Label": label})
    if weight is not None:
        loss = run_op("elementwise_mul", {"X": loss, "Y": weight},
                      {"axis": -1})
    return _reduce_loss(loss, reduction)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    loss = run_op("sigmoid_cross_entropy_with_logits",
                  {"X": logit, "Label": label}, {})
    if weight is not None:
        loss = run_op("elementwise_mul", {"X": loss, "Y": weight},
                      {"axis": -1})
    return _reduce_loss(loss, reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    res = run_op_multi("huber_loss", {"X": input, "Y": label},
                       {"delta": float(delta)}, {"Out": 1, "Residual": 1})
    return _reduce_loss(res["Out"][0], reduction)


def square_error_cost(input, label):
    return run_op("squared_error_cost", {"X": input, "Y": label})


def log_loss(input, label, epsilon=1e-4, name=None):
    from .. import tensor as T
    p = T.clip(input, min=epsilon, max=1 - epsilon)
    one = T.ones_like(p)
    return run_op("bce_loss", {"X": p, "Label": label})


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    from .. import tensor as T
    p = sigmoid(logit)
    ce = binary_cross_entropy_with_logits(logit, label, reduction="none")
    p_t = run_op("elementwise_add",
                 {"X": run_op("elementwise_mul", {"X": p, "Y": label},
                              {"axis": -1}),
                  "Y": run_op("elementwise_mul",
                              {"X": run_op("scale", {"X": p},
                                           {"scale": -1.0, "bias": 1.0}),
                               "Y": run_op("scale", {"X": label},
                                           {"scale": -1.0, "bias": 1.0})},
                              {"axis": -1})}, {"axis": -1})
    mod = T.pow(run_op("scale", {"X": p_t}, {"scale": -1.0, "bias": 1.0}),
                gamma)
    loss = run_op("elementwise_mul", {"X": ce, "Y": mod}, {"axis": -1})
    if alpha >= 0:
        a_t = run_op("scale", {"X": label},
                     {"scale": 2 * alpha - 1.0, "bias": 1.0 - alpha})
        loss = run_op("elementwise_mul", {"X": loss, "Y": a_t}, {"axis": -1})
    if normalizer is not None:
        loss = run_op("elementwise_div", {"X": loss, "Y": normalizer},
                      {"axis": -1})
    return _reduce_loss(loss, reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    from .. import tensor as T
    d = run_op("elementwise_sub", {"X": other, "Y": input}, {"axis": -1})
    loss = T.clip(run_op("elementwise_mul", {"X": d, "Y": label},
                         {"axis": -1}).__add__(margin) if False else
                  run_op("scale",
                         {"X": run_op("elementwise_mul", {"X": d, "Y": label},
                                      {"axis": -1})},
                         {"scale": 1.0, "bias": float(margin)}), min=0.0)
    return _reduce_loss(loss, reduction)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    k = [kernel_sizes] * 2 if isinstance(kernel_sizes, int) \
        else list(kernel_sizes)
    s = [strides] * 2 if isinstance(strides, int) else list(strides)
    d = [dilations] * 2 if isinstance(dilations, int) else list(dilations)
    p = [paddings] * 4 if isinstance(paddings, int) else list(paddings)
    if len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]
    return run_op("unfold", {"X": x},
                  {"kernel_sizes": k, "strides": s, "paddings": p,
                   "dilations": d}, out_slot="Y")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0,
         dilations=1, name=None):
    k = [kernel_sizes] * 2 if isinstance(kernel_sizes, int) \
        else list(kernel_sizes)
    s = [strides] * 2 if isinstance(strides, int) else list(strides)
    d = [dilations] * 2 if isinstance(dilations, int) else list(dilations)
    p = [paddings] * 4 if isinstance(paddings, int) else list(paddings)
    if len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]
    os_ = [output_sizes] * 2 if isinstance(output_sizes, int) \
        else list(output_sizes)
    return run_op("fold", {"X": x},
                  {"output_sizes": os_, "kernel_sizes": k, "strides": s,
                   "paddings": p, "dilations": d}, out_slot="Y")


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    mode = mode.lower()
    op = {"nearest": "nearest_interp_v2", "bilinear": "bilinear_interp_v2",
          "trilinear": "trilinear_interp_v2",
          "bicubic": "bicubic_interp_v2"}.get(mode)
    if op is None:
        raise ValueError(f"unknown interpolate mode {mode!r}")
    attrs = {"align_corners": align_corners, "align_mode": align_mode}
    if size is not None:
        dims = list(int(v) for v in size)
        if len(dims) == 3:
            attrs.update(out_d=dims[0], out_h=dims[1], out_w=dims[2])
        else:
            attrs.update(out_h=dims[0], out_w=dims[1])
    else:
        attrs["scale"] = scale_factor if isinstance(
            scale_factor, (list, tuple)) else [float(scale_factor)]
    return run_op(op, {"X": x}, attrs)


upsample = interpolate


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, data_format="NCDHW", name=None):
    to3 = lambda v: [v] * 3 if isinstance(v, int) else list(v)
    out = run_op("conv3d", {"Input": x, "Filter": weight},
                 {"strides": to3(stride), "paddings": to3(padding),
                  "dilations": to3(dilation), "groups": groups,
                  "data_format": data_format}, out_slot="Output")
    if bias is not None:
        out = run_op("elementwise_add", {"X": out, "Y": bias}, {"axis": 1})
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NCDHW", name=None):
    to3 = lambda v: [v] * 3 if isinstance(v, int) else list(v)
    return run_op("pool3d", {"X": x},
                  {"pooling_type": "max", "ksize": to3(kernel_size),
                   "strides": to3(stride if stride is not None
                                  else kernel_size),
                   "paddings": to3(padding), "ceil_mode": ceil_mode,
                   "data_format": data_format})


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, data_format="NCDHW", name=None):
    to3 = lambda v: [v] * 3 if isinstance(v, int) else list(v)
    return run_op("pool3d", {"X": x},
                  {"pooling_type": "avg", "ksize": to3(kernel_size),
                   "strides": to3(stride if stride is not None
                                  else kernel_size),
                   "paddings": to3(padding), "ceil_mode": ceil_mode,
                   "exclusive": exclusive, "data_format": data_format})


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC loss (reference paddle.nn.functional.ctc_loss over warpctc).
    log_probs: [B, T, C] RAW logits in this build (softmax applied inside
    the op, warp-ctc convention); labels: [B, L]."""
    loss = run_op("warpctc",
                  {"Logits": log_probs, "Label": labels,
                   "LogitsLength": input_lengths,
                   "LabelLength": label_lengths},
                  {"blank": blank, "norm_by_times": norm_by_times},
                  out_slot="Loss",
                  extra_outs=("WarpCTCGrad",))
    return _reduce_loss(loss, reduction)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  reduction="mean", name=None):
    """Hierarchical softmax loss (reference F.hsigmoid_loss)."""
    ins = {"X": input, "Label": label, "W": weight}
    if bias is not None:
        ins["Bias"] = bias
    loss = run_op("hierarchical_sigmoid", ins,
                  {"num_classes": num_classes}, out_slot="Out",
                  extra_outs=("PreOut", "W_Out"))
    return _reduce_loss(loss, reduction)
