"""nn.MoELayer — eager/static Mixture-of-Experts feed-forward layer.

API parity target: paddle.incubate's MoE layer family (absent at the
reference's vintage; the fleet strategy bag already carries the
`expert_parallel` flag). Built on the fused `moe_ffn` op (fluid/ops/
nn_ops.py) whose kernel is parallel/moe.py — so the tape differentiates it
(auto-vjp) and the same layer works in dygraph and static graphs. Under a
mesh with an "ep" axis (parallel.moe.moe_context), the expert buffers
shard over ep and dispatch rides all_to_all.
"""
from __future__ import annotations

import math

from ..common_ops import run_op_multi
from ..fluid.dygraph.layers import Layer
from ..fluid.initializer import XavierInitializer

__all__ = ["MoELayer"]


class MoELayer(Layer):
    """Routed FFN: y, aux = moe(x) with x: [..., d_model].

    Args:
      d_model: token width.
      d_hidden: per-expert hidden width.
      num_experts: expert count E (shardable over the "ep" mesh axis).
      top_k: experts per token (1 = Switch, 2 = GShard).
      capacity_factor: static buffer slack; overflow tokens are dropped to
        keep shapes static (their residual path still carries them).
    """

    def __init__(self, d_model: int, num_experts: int, d_hidden: int = None,
                 top_k: int = 1, capacity_factor: float = 1.25,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        d_hidden = d_hidden or 4 * d_model
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        init = XavierInitializer()
        self.gate_weight = self.create_parameter(
            [d_model, num_experts], attr=weight_attr,
            default_initializer=init)
        self.w_up = self.create_parameter(
            [num_experts, d_model, d_hidden], attr=weight_attr,
            default_initializer=init)
        self.b_up = self.create_parameter(
            [num_experts, d_hidden], attr=bias_attr, is_bias=True)
        self.w_down = self.create_parameter(
            [num_experts, d_hidden, d_model], attr=weight_attr,
            default_initializer=init)
        self.b_down = self.create_parameter(
            [num_experts, d_model], attr=bias_attr, is_bias=True)

    def forward(self, x):
        outs = run_op_multi(
            "moe_ffn",
            {"X": x, "Gate": self.gate_weight, "WUp": self.w_up,
             "BUp": self.b_up, "WDown": self.w_down, "BDown": self.b_down},
            attrs={"top_k": self.top_k,
                   "capacity_factor": self.capacity_factor},
            out_slots={"Out": "float32", "AuxLoss": "float32"})
        return outs["Out"][0], outs["AuxLoss"][0]
