"""Recurrent layers (reference python/paddle/nn/layer/rnn.py:401,1074 —
SimpleRNNCell/LSTMCell/GRUCell, RNN/BiRNN wrappers, SimpleRNN/LSTM/GRU).

The multi-layer LSTM/GRU/SimpleRNN classes dispatch to the single `rnn` op
(fluid/ops/sequence_ops.py) — one lax.scan over time per direction, so the
whole network jits into one XLA computation instead of per-step op chains;
variable lengths are handled by masking, not LoD."""
from __future__ import annotations

import math

import numpy as np

from ..common_ops import run_op_multi
from ..fluid.dygraph.layers import Layer
from ..fluid.dygraph.varbase import Tensor
from . import functional as F
from .initializer import Uniform

__all__ = ["RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN",
           "BiRNN", "SimpleRNN", "LSTM", "GRU"]


class RNNCellBase(Layer):
    def _make(self, shape, std):
        return self.create_parameter(
            shape, default_initializer=Uniform(-std, std))

    def get_initial_states(self, batch_ref, shape=None, dtype="float32"):
        import paddle_tpu as paddle
        B = batch_ref.shape[0]
        # state_shape is either one shape tuple (H,) or a tuple of shape
        # tuples ((H,), (H,)) for multi-state cells like LSTM
        if self.state_shape and isinstance(self.state_shape[0],
                                           (tuple, list)):
            return tuple(paddle.zeros([B, s[-1]], dtype)
                         for s in self.state_shape)
        return paddle.zeros([B, self.state_shape[-1]], dtype)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = self._make([hidden_size, input_size], std)
        self.weight_hh = self._make([hidden_size, hidden_size], std)
        self.bias_ih = self._make([hidden_size], std)
        self.bias_hh = self._make([hidden_size], std)
        self.state_shape = (hidden_size,)

    def forward(self, inputs, states=None):
        import paddle_tpu as paddle
        h = states if states is not None else \
            self.get_initial_states(inputs)
        z = paddle.add(
            F.linear(inputs, paddle.t(self.weight_ih), self.bias_ih),
            F.linear(h, paddle.t(self.weight_hh), self.bias_hh))
        out = paddle.tanh(z) if self.activation == "tanh" else F.relu(z)
        return out, out


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = self._make([4 * hidden_size, input_size], std)
        self.weight_hh = self._make([4 * hidden_size, hidden_size], std)
        self.bias_ih = self._make([4 * hidden_size], std)
        self.bias_hh = self._make([4 * hidden_size], std)
        self.state_shape = ((hidden_size,), (hidden_size,))

    def forward(self, inputs, states=None):
        import paddle_tpu as paddle
        h, c = states if states is not None else \
            self.get_initial_states(inputs)
        g = paddle.add(
            F.linear(inputs, paddle.t(self.weight_ih), self.bias_ih),
            F.linear(h, paddle.t(self.weight_hh), self.bias_hh))
        i, f, gg, o = paddle.split(g, 4, axis=-1)
        c2 = paddle.add(paddle.multiply(F.sigmoid(f), c),
                        paddle.multiply(F.sigmoid(i), paddle.tanh(gg)))
        h2 = paddle.multiply(F.sigmoid(o), paddle.tanh(c2))
        return h2, (h2, c2)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = self._make([3 * hidden_size, input_size], std)
        self.weight_hh = self._make([3 * hidden_size, hidden_size], std)
        self.bias_ih = self._make([3 * hidden_size], std)
        self.bias_hh = self._make([3 * hidden_size], std)
        self.state_shape = (hidden_size,)

    def forward(self, inputs, states=None):
        import paddle_tpu as paddle
        h = states if states is not None else \
            self.get_initial_states(inputs)
        xw = F.linear(inputs, paddle.t(self.weight_ih), self.bias_ih)
        hw = F.linear(h, paddle.t(self.weight_hh), self.bias_hh)
        xr, xz, xn = paddle.split(xw, 3, axis=-1)
        hr, hz, hn = paddle.split(hw, 3, axis=-1)
        r = F.sigmoid(paddle.add(xr, hr))
        z = F.sigmoid(paddle.add(xz, hz))
        n = paddle.tanh(paddle.add(xn, paddle.multiply(r, hn)))
        one_minus_z = paddle.scale(z, -1.0, bias=1.0)
        h2 = paddle.add(paddle.multiply(z, h),
                        paddle.multiply(one_minus_z, n))
        return h2, h2


class RNN(Layer):
    """Python-loop cell runner (reference nn/layer/rnn.py RNN): unrolls
    time steps; fine for short sequences / eager use — the fused LSTM/GRU
    classes below are the jit-friendly path."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        import paddle_tpu as paddle
        t_axis = 0 if self.time_major else 1
        T = inputs.shape[t_axis]
        steps = list(range(T))
        if self.is_reverse:
            steps = steps[::-1]
        states = initial_states
        if sequence_length is not None and states is None and \
                hasattr(self.cell, "get_initial_states"):
            # materialise zeros so step-0 masking has an "old" state
            batch_ref = inputs if not self.time_major else \
                paddle.transpose(inputs, [1, 0, 2])
            states = self.cell.get_initial_states(batch_ref)
        outs = [None] * T
        for t in steps:
            xt = paddle.squeeze(paddle.slice(inputs, [t_axis], [t], [t + 1]),
                                axis=[t_axis])
            y, new_states = self.cell(xt, states)
            if sequence_length is not None:
                keep = self._keep_mask(sequence_length, t, y)
                y = paddle.multiply(y, keep)
                # states may still be None for custom cells without
                # get_initial_states: blend against implicit zeros so
                # padded first steps don't leak state
                states = self._blend(new_states, states, keep)
            else:
                states = new_states
            outs[t] = y
        outp = paddle.stack(outs, axis=t_axis)
        return outp, states

    @staticmethod
    def _keep_mask(sequence_length, t, like):
        """[B, 1] float mask: 1 where step t is within the sequence
        (padded steps must not advance states nor emit output — matches
        the fused rnn op's masking)."""
        import paddle_tpu as paddle
        lens = paddle.cast(sequence_length, "float32")
        tt = paddle.full_like(lens, float(t))
        return paddle.unsqueeze(
            paddle.cast(paddle.less_than(tt, lens), "float32"), 1)

    @classmethod
    def _blend(cls, new, old, keep):
        import paddle_tpu as paddle
        if isinstance(new, (tuple, list)):
            old = old if isinstance(old, (tuple, list)) \
                else [None] * len(new)
            return tuple(cls._blend(n, o, keep) for n, o in zip(new, old))
        if old is None:  # implicit zero initial state
            return paddle.multiply(new, keep)
        inv = paddle.scale(keep, -1.0, bias=1.0)
        return paddle.add(paddle.multiply(new, keep),
                          paddle.multiply(old, inv))


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        import paddle_tpu as paddle
        s_fw, s_bw = initial_states if initial_states is not None \
            else (None, None)
        o_fw, st_fw = self.rnn_fw(inputs, s_fw, sequence_length)
        o_bw, st_bw = self.rnn_bw(inputs, s_bw, sequence_length)
        return paddle.concat([o_fw, o_bw], axis=-1), (st_fw, st_bw)


class _RNNBase(Layer):
    _MODE = "LSTM"
    _GATES = 4

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        if direction not in ("forward", "bidirect", "bidirectional"):
            raise ValueError(f"unknown direction {direction!r}")
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.bidirect = direction != "forward"
        self.time_major = time_major
        self.dropout = dropout
        ndir = 2 if self.bidirect else 1
        std = 1.0 / math.sqrt(hidden_size)
        from ..fluid.ops.sequence_ops import rnn_weight_shapes
        self.weights = []
        for i, shape in enumerate(rnn_weight_shapes(
                self._MODE, input_size, hidden_size, num_layers, ndir)):
            p = self.create_parameter(
                list(shape), default_initializer=Uniform(-std, std))
            self.add_parameter(f"w_{i}", p)
            self.weights.append(p)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        import paddle_tpu as paddle
        v = inputs
        if self.time_major:
            v = paddle.transpose(v, [1, 0, 2])
        ins = {"Input": [v], "WeightList": list(self.weights)}
        if initial_states is not None:
            states = initial_states if isinstance(initial_states,
                                                  (list, tuple)) \
                else [initial_states]
            ins["PreState"] = list(states)
        if sequence_length is not None:
            ins["SequenceLength"] = [sequence_length]
        res = run_op_multi(
            "rnn", ins,
            {"mode": self._MODE, "hidden_size": self.hidden_size,
             "num_layers": self.num_layers, "is_bidirec": self.bidirect,
             "dropout_prob": self.dropout, "is_test": not self.training},
            out_slots={"Out": 1, "State": 2})
        outp = res["Out"][0]
        if self.time_major:
            outp = paddle.transpose(outp, [1, 0, 2])
        h_n, c_n = res["State"]
        if self._MODE == "LSTM":
            return outp, (h_n, c_n)
        return outp, h_n


class LSTM(_RNNBase):
    _MODE, _GATES = "LSTM", 4


class GRU(_RNNBase):
    _MODE, _GATES = "GRU", 3


class SimpleRNN(_RNNBase):
    _MODE, _GATES = "RNN_TANH", 1

    def __init__(self, input_size, hidden_size, num_layers=1,
                 activation="tanh", **kwargs):
        self._MODE = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(input_size, hidden_size, num_layers, **kwargs)
