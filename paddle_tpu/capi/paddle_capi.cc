/* C API implementation: embeds CPython and drives
 * paddle_tpu.inference.Predictor (see paddle_c_api.h for the design
 * stance; reference equivalents inference/capi/pd_predictor.cc and the
 * C++-only train demo fluid/train/demo/demo_trainer.cc).
 *
 * Build (native/__init__.py build_capi does this automatically):
 *   g++ -O3 -shared -fPIC paddle_capi.cc $(python3-config --includes)
 *       -lpython3.x -o libpaddle_tpu_capi.so
 */
#include "paddle_c_api.h"

#include <Python.h>

#include <cstring>
#include <string>
#include <vector>

namespace {

std::string g_last_error;

void set_err(const std::string &msg) { g_last_error = msg; }

void set_err_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  std::string msg = "python error";
  if (value != nullptr) {
    PyObject *s = PyObject_Str(value);
    if (s != nullptr) {
      msg = PyUnicode_AsUTF8(s);
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  set_err(msg);
}

const char *np_dtype_name(PD_DataType t) {
  switch (t) {
    case PD_FLOAT32: return "float32";
    case PD_INT32: return "int32";
    case PD_INT64: return "int64";
  }
  return "float32";
}

size_t dtype_size(PD_DataType t) {
  return t == PD_FLOAT32 ? 4 : (t == PD_INT32 ? 4 : 8);
}

void ensure_python() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
  }
}

}  // namespace

struct PD_Predictor {
  PyObject *predictor = nullptr;   // paddle_tpu.inference.Predictor
  PyObject *np = nullptr;          // numpy module
  // output buffers stay alive until the next run/delete
  std::vector<std::vector<char>> out_buffers;
};

extern "C" {

PD_Predictor *PD_NewPredictor(const char *model_dir) {
  ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  PD_Predictor *p = nullptr;
  PyObject *mod = nullptr, *np = nullptr, *cfg = nullptr, *pred = nullptr;
  do {
    mod = PyImport_ImportModule("paddle_tpu.inference");
    if (!mod) { set_err_from_python(); break; }
    np = PyImport_ImportModule("numpy");
    if (!np) { set_err_from_python(); break; }
    cfg = PyObject_CallMethod(mod, "Config", "s", model_dir);
    if (!cfg) { set_err_from_python(); break; }
    pred = PyObject_CallMethod(mod, "create_predictor", "O", cfg);
    if (!pred) {
      PyErr_Clear();
      PyObject *cls = PyObject_GetAttrString(mod, "Predictor");
      if (cls) {
        pred = PyObject_CallFunctionObjArgs(cls, cfg, nullptr);
        Py_DECREF(cls);
      }
    }
    if (!pred) { set_err_from_python(); break; }
    p = new PD_Predictor();
    p->predictor = pred;
    p->np = np;
    np = nullptr;
    pred = nullptr;
  } while (false);
  Py_XDECREF(mod);
  Py_XDECREF(np);
  Py_XDECREF(cfg);
  Py_XDECREF(pred);
  PyGILState_Release(gil);
  return p;
}

void PD_DeletePredictor(PD_Predictor *p) {
  if (p == nullptr) return;
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(p->predictor);
  Py_XDECREF(p->np);
  PyGILState_Release(gil);
  delete p;
}

static int name_count(PD_Predictor *p, const char *method) {
  PyGILState_STATE gil = PyGILState_Ensure();
  int n = -1;
  PyObject *names = PyObject_CallMethod(p->predictor, method, nullptr);
  if (names != nullptr) {
    n = static_cast<int>(PyList_Size(names));
    Py_DECREF(names);
  } else {
    set_err_from_python();
  }
  PyGILState_Release(gil);
  return n;
}

int PD_GetInputNum(PD_Predictor *p) {
  return name_count(p, "get_input_names");
}

int PD_GetOutputNum(PD_Predictor *p) {
  return name_count(p, "get_output_names");
}

int PD_PredictorRun(PD_Predictor *p, const PD_Tensor *inputs,
                    int n_inputs, PD_Tensor *outputs, int max_outputs) {
  if (p == nullptr || p->predictor == nullptr) {
    set_err("null predictor");
    return 1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = 1;
  PyObject *arr_list = nullptr, *result = nullptr;
  do {
    arr_list = PyList_New(n_inputs);
    if (!arr_list) { set_err_from_python(); break; }
    bool ok = true;
    for (int i = 0; i < n_inputs; ++i) {
      const PD_Tensor &t = inputs[i];
      size_t numel = 1;
      for (int d = 0; d < t.ndim; ++d) numel *= t.shape[d];
      PyObject *mv = PyMemoryView_FromMemory(
          reinterpret_cast<char *>(const_cast<void *>(t.data)),
          numel * dtype_size(t.dtype), PyBUF_READ);
      if (!mv) { set_err_from_python(); ok = false; break; }
      PyObject *flat = PyObject_CallMethod(
          p->np, "frombuffer", "Os", mv, np_dtype_name(t.dtype));
      Py_DECREF(mv);
      if (!flat) { set_err_from_python(); ok = false; break; }
      PyObject *shape = PyTuple_New(t.ndim);
      for (int d = 0; d < t.ndim; ++d)
        PyTuple_SET_ITEM(shape, d, PyLong_FromLongLong(t.shape[d]));
      PyObject *arr = PyObject_CallMethod(flat, "reshape", "O", shape);
      Py_DECREF(flat);
      Py_DECREF(shape);
      if (!arr) { set_err_from_python(); ok = false; break; }
      PyList_SET_ITEM(arr_list, i, arr);  // steals
    }
    if (!ok) break;
    result = PyObject_CallMethod(p->predictor, "run", "O", arr_list);
    if (!result) { set_err_from_python(); break; }
    if (!PyList_Check(result)) { set_err("run() did not return a list");
      break; }
    int n_out = static_cast<int>(PyList_Size(result));
    if (n_out > max_outputs) n_out = max_outputs;
    p->out_buffers.clear();
    p->out_buffers.resize(n_out);
    ok = true;
    for (int i = 0; i < n_out; ++i) {
      PyObject *a = PyList_GET_ITEM(result, i);  // borrowed
      // contiguous fp32/int bytes via numpy: np.ascontiguousarray
      PyObject *ca = PyObject_CallMethod(p->np, "ascontiguousarray",
                                         "O", a);
      if (!ca) { set_err_from_python(); ok = false; break; }
      PyObject *dt = PyObject_GetAttrString(ca, "dtype");
      PyObject *dt_name = dt ? PyObject_GetAttrString(dt, "name") : nullptr;
      std::string dname = dt_name ? PyUnicode_AsUTF8(dt_name) : "float32";
      Py_XDECREF(dt);
      Py_XDECREF(dt_name);
      PD_DataType out_t = PD_FLOAT32;
      if (dname == "int32") out_t = PD_INT32;
      else if (dname == "int64") out_t = PD_INT64;
      else if (dname != "float32") {
        PyObject *cast = PyObject_CallMethod(ca, "astype", "s", "float32");
        Py_DECREF(ca);
        if (!cast) { set_err_from_python(); ok = false; break; }
        ca = cast;
      }
      PyObject *shape = PyObject_GetAttrString(ca, "shape");
      int nd = static_cast<int>(PyTuple_Size(shape));
      if (nd > 8) {
        // the fixed shape[8] cannot represent this output; truncating
        // would desync declared shape vs buffer length
        Py_DECREF(shape);
        Py_DECREF(ca);
        set_err("output ndim > 8 unsupported by PD_Tensor");
        ok = false;
        break;
      }
      outputs[i].ndim = nd;
      size_t numel = 1;
      for (int d = 0; d < outputs[i].ndim; ++d) {
        outputs[i].shape[d] = PyLong_AsLongLong(
            PyTuple_GET_ITEM(shape, d));
        numel *= outputs[i].shape[d];
      }
      Py_DECREF(shape);
      outputs[i].dtype = out_t;
      PyObject *bytes = PyObject_CallMethod(ca, "tobytes", nullptr);
      Py_DECREF(ca);
      if (!bytes) { set_err_from_python(); ok = false; break; }
      char *buf = nullptr;
      Py_ssize_t len = 0;
      if (PyBytes_AsStringAndSize(bytes, &buf, &len) != 0) {
        Py_DECREF(bytes);
        set_err_from_python();
        ok = false;
        break;
      }
      p->out_buffers[i].assign(buf, buf + len);
      Py_DECREF(bytes);
      outputs[i].data = p->out_buffers[i].data();
    }
    if (!ok) break;
    rc = 0;
  } while (false);
  Py_XDECREF(arr_list);
  Py_XDECREF(result);
  PyGILState_Release(gil);
  return rc;
}

const char *PD_GetLastError(void) { return g_last_error.c_str(); }

/* ---- PD_Trainer: the C-only training loop (reference
 * fluid/train/demo/demo_trainer.cc) over capi/train_host.py. ---- */

struct PD_Trainer {
  PyObject *trainer = nullptr;  // paddle_tpu.capi.train_host.CTrainer
  PyObject *np = nullptr;
};

static PyObject *tensor_to_ndarray(PyObject *np, const PD_Tensor &t) {
  size_t numel = 1;
  for (int d = 0; d < t.ndim; ++d) numel *= t.shape[d];
  PyObject *mv = PyMemoryView_FromMemory(
      reinterpret_cast<char *>(const_cast<void *>(t.data)),
      numel * dtype_size(t.dtype), PyBUF_READ);
  if (!mv) return nullptr;
  PyObject *flat = PyObject_CallMethod(np, "frombuffer", "Os", mv,
                                       np_dtype_name(t.dtype));
  Py_DECREF(mv);
  if (!flat) return nullptr;
  PyObject *shape = PyTuple_New(t.ndim);
  for (int d = 0; d < t.ndim; ++d)
    PyTuple_SET_ITEM(shape, d, PyLong_FromLongLong(t.shape[d]));
  PyObject *arr = PyObject_CallMethod(flat, "reshape", "O", shape);
  Py_DECREF(flat);
  Py_DECREF(shape);
  return arr;
}

PD_Trainer *PD_NewTrainer(const char *model_dir) {
  ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  PD_Trainer *t = nullptr;
  PyObject *mod = nullptr, *np = nullptr, *tr = nullptr;
  do {
    mod = PyImport_ImportModule("paddle_tpu.capi.train_host");
    if (!mod) { set_err_from_python(); break; }
    np = PyImport_ImportModule("numpy");
    if (!np) { set_err_from_python(); break; }
    tr = PyObject_CallMethod(mod, "create_trainer", "s", model_dir);
    if (!tr) { set_err_from_python(); break; }
    t = new PD_Trainer();
    t->trainer = tr;
    t->np = np;
    tr = nullptr;
    np = nullptr;
  } while (false);
  Py_XDECREF(mod);
  Py_XDECREF(np);
  Py_XDECREF(tr);
  PyGILState_Release(gil);
  return t;
}

void PD_DeleteTrainer(PD_Trainer *t) {
  if (t == nullptr) return;
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(t->trainer);
  Py_XDECREF(t->np);
  PyGILState_Release(gil);
  delete t;
}

int PD_TrainerFeedNum(PD_Trainer *t) {
  if (t == nullptr || t->trainer == nullptr) {
    set_err("null trainer");
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int n = -1;
  PyObject *names = PyObject_CallMethod(t->trainer, "get_feed_names",
                                        nullptr);
  if (names) {
    n = static_cast<int>(PyList_Size(names));
    Py_DECREF(names);
  } else {
    set_err_from_python();
  }
  PyGILState_Release(gil);
  return n;
}

int PD_TrainerRun(PD_Trainer *t, const PD_Tensor *feeds, int n_feeds,
                  float *loss) {
  if (t == nullptr || t->trainer == nullptr) {
    set_err("null trainer");
    return 1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = 1;
  PyObject *args = nullptr, *result = nullptr;
  do {
    args = PyTuple_New(n_feeds);
    if (!args) { set_err_from_python(); break; }
    bool ok = true;
    for (int i = 0; i < n_feeds; ++i) {
      PyObject *arr = tensor_to_ndarray(t->np, feeds[i]);
      if (!arr) { set_err_from_python(); ok = false; break; }
      PyTuple_SET_ITEM(args, i, arr);  // steals
    }
    if (!ok) break;
    PyObject *run = PyObject_GetAttrString(t->trainer, "run");
    if (!run) { set_err_from_python(); break; }
    result = PyObject_CallObject(run, args);
    Py_DECREF(run);
    if (!result) { set_err_from_python(); break; }
    if (loss != nullptr && PyList_Check(result) &&
        PyList_Size(result) > 0) {
      PyObject *first = PyList_GET_ITEM(result, 0);  // borrowed
      PyObject *item = PyObject_CallMethod(first, "item", "i", 0);
      if (!item) { set_err_from_python(); break; }
      *loss = static_cast<float>(PyFloat_AsDouble(item));
      Py_DECREF(item);
    }
    rc = 0;
  } while (false);
  Py_XDECREF(args);
  Py_XDECREF(result);
  PyGILState_Release(gil);
  return rc;
}

int PD_TrainerSave(PD_Trainer *t, const char *dirname) {
  if (t == nullptr || t->trainer == nullptr) {
    set_err("null trainer");
    return 1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = 1;
  PyObject *r = PyObject_CallMethod(t->trainer, "save", "s", dirname);
  if (r) {
    rc = 0;
    Py_DECREF(r);
  } else {
    set_err_from_python();
  }
  PyGILState_Release(gil);
  return rc;
}

}  // extern "C"
