/* C inference API (reference paddle/fluid/inference/capi/c_api.h +
 * framework/c/c_api.h): a stable C ABI over the predictor so non-Python
 * hosts (C, C++, Go, R via cgo/FFI) can load and run exported models.
 *
 * This build's predictor core is Python-native (SURVEY §7 stance); the C
 * library embeds the interpreter once per process (Py_Initialize) and
 * marshals tensors by pointer — the same deploy pattern as the
 * reference's C++-only train/infer demos, with libpython in place of
 * libpaddle_fluid. Thread-safety: calls are serialized on the GIL.
 */
#ifndef PADDLE_TPU_C_API_H
#define PADDLE_TPU_C_API_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct PD_Predictor PD_Predictor;

typedef enum {
  PD_FLOAT32 = 0,
  PD_INT32 = 1,
  PD_INT64 = 2,
} PD_DataType;

typedef struct {
  const void *data;   /* caller-owned for inputs */
  int64_t shape[8];
  int ndim;
  PD_DataType dtype;
} PD_Tensor;

/* Load an exported inference model (save_inference_model / jit.save
 * directory). Returns NULL on failure; PD_GetLastError() explains. */
PD_Predictor *PD_NewPredictor(const char *model_dir);

void PD_DeletePredictor(PD_Predictor *p);

int PD_GetInputNum(PD_Predictor *p);
int PD_GetOutputNum(PD_Predictor *p);

/* Run with n_inputs tensors (model feed order). On success outputs[i]
 * is filled for min(PD_GetOutputNum, max_outputs) tensors whose data
 * pointers stay valid until the next PD_PredictorRun/Delete on this
 * predictor. Returns 0 on success, nonzero on error. */
int PD_PredictorRun(PD_Predictor *p, const PD_Tensor *inputs,
                    int n_inputs, PD_Tensor *outputs, int max_outputs);

const char *PD_GetLastError(void);

/* ---- training without Python on the host side (reference
 * fluid/train/demo/demo_trainer.cc): load a directory written by
 * fluid.io.save_train_model (startup.program + main.program with
 * backward/optimizer ops + optional params/) and drive train steps. */

typedef struct PD_Trainer PD_Trainer;

PD_Trainer *PD_NewTrainer(const char *model_dir);

void PD_DeleteTrainer(PD_Trainer *t);

int PD_TrainerFeedNum(PD_Trainer *t);

/* One optimizer step on the given feeds (model feed order). On success
 * *loss receives the first fetch (the loss) as float. Returns 0 on
 * success, nonzero on error. */
int PD_TrainerRun(PD_Trainer *t, const PD_Tensor *feeds, int n_feeds,
                  float *loss);

/* Persist the trained parameters (fluid.io.save_persistables layout,
 * reloadable from Python or PD_NewTrainer's params/ dir). */
int PD_TrainerSave(PD_Trainer *t, const char *dirname);

#ifdef __cplusplus
}
#endif
#endif /* PADDLE_TPU_C_API_H */
