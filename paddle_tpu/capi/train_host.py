"""Python host for the C-API *training* surface (reference
fluid/train/demo/demo_trainer.cc:1 — load a saved Program and train with
no Python on the user's side; the embedded interpreter here is an
implementation detail behind the C ABI, mirroring how the reference
embeds its C++ runtime behind libpaddle_fluid).

Format (written by fluid.io.save_train_model): a directory with
  startup.program / main.program  — serialized Program blobs
  params/                         — persistables (optional, resume)
  meta of feed/fetch names embedded in the main program blob.
"""
from __future__ import annotations

import os

import numpy as np

__all__ = ["create_trainer", "CTrainer"]


class CTrainer:
    def __init__(self, model_dir: str):
        from ..fluid.executor import Executor
        from ..fluid.proto import deserialize_program
        from ..fluid.scope import Scope

        with open(os.path.join(model_dir, "main.program"), "rb") as f:
            self.main, meta = deserialize_program(f.read())
        with open(os.path.join(model_dir, "startup.program"), "rb") as f:
            self.startup, _ = deserialize_program(f.read())
        self.feed_names = list(meta.get("feed_names", []))
        self.fetch_names = list(meta.get("fetch_names", []))
        self.scope = Scope()
        self.exe = Executor()
        from ..fluid.scope import scope_guard
        self._guard = scope_guard
        with scope_guard(self.scope):
            self.exe.run(self.startup)
            params_dir = os.path.join(model_dir, "params")
            if os.path.isdir(params_dir):
                from ..fluid import io as fio
                fio.load_persistables(self.exe, params_dir, self.main)

    def get_feed_names(self):
        return self.feed_names

    def run(self, *arrays):
        """arrays align with feed_names; returns the fetch values
        (loss first) as float32 numpy arrays."""
        feed = {n: np.asarray(a) for n, a in zip(self.feed_names, arrays)}
        with self._guard(self.scope):
            outs = self.exe.run(self.main, feed=feed,
                                fetch_list=self.fetch_names)
        return [np.asarray(o, np.float32).ravel() for o in outs]

    def save(self, dirname: str):
        from ..fluid import io as fio
        os.makedirs(dirname, exist_ok=True)
        with self._guard(self.scope):
            fio.save_persistables(self.exe, dirname, self.main)


def create_trainer(model_dir: str) -> CTrainer:
    return CTrainer(model_dir)
