"""C API build helper (header + impl live beside this file; see
paddle_c_api.h for the design stance — reference inference/capi/ +
framework/c/c_api.h).

`build_capi()` compiles libpaddle_tpu_capi.so on demand (g++ +
libpython), cached and mtime-invalidated like native/__init__.py.
C/C++/Go/R hosts link it and include paddle_c_api.h.
"""
from __future__ import annotations

import os
import subprocess
import sysconfig
import threading

__all__ = ["build_capi", "header_path"]

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_DIR, "build")
_lock = threading.Lock()


def header_path() -> str:
    return os.path.join(_DIR, "paddle_c_api.h")


def build_capi() -> str | None:
    """Compile (if stale) the C API shared library; returns its path, or
    None when no toolchain is available."""
    src = os.path.join(_DIR, "paddle_capi.cc")
    so = os.path.join(_BUILD, "libpaddle_tpu_capi.so")
    with _lock:
        if os.path.exists(so) and \
                os.path.getmtime(so) >= os.path.getmtime(src):
            return so
        os.makedirs(_BUILD, exist_ok=True)
        inc = sysconfig.get_paths()["include"]
        libdir = sysconfig.get_config_var("LIBDIR") or ""
        ver = sysconfig.get_config_var("LDVERSION") or \
            sysconfig.get_python_version()
        import tempfile
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_BUILD)
        os.close(fd)
        try:
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                 f"-I{inc}", f"-I{_DIR}", src,
                 f"-L{libdir}", f"-lpython{ver}", "-o", tmp],
                check=True, capture_output=True, text=True)
            os.replace(tmp, so)
            return so
        except (subprocess.CalledProcessError, FileNotFoundError):
            if os.path.exists(tmp):
                os.unlink(tmp)
            return None
