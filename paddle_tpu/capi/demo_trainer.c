/* C-only training demo (reference fluid/train/demo/demo_trainer.cc:1):
 * load a program pair saved by fluid.io.save_train_model and run SGD
 * steps with data generated in C — no Python in this translation unit.
 *
 * Usage: demo_trainer <model_dir> <steps>
 * Prints "first_loss <f>\nlast_loss <f>" and exits 0 when the loss
 * dropped, 2 otherwise. Built and executed by tests/test_capi.py.
 */
#include <stdio.h>
#include <stdlib.h>

#include "paddle_c_api.h"

/* tiny deterministic LCG so the demo needs no libs */
static unsigned int rng_state = 12345u;
static float frand(void) {
  rng_state = rng_state * 1664525u + 1013904223u;
  return ((float)(rng_state >> 8) / (float)(1u << 24)) * 2.0f - 1.0f;
}

int main(int argc, char **argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: %s <model_dir> <steps>\n", argv[0]);
    return 1;
  }
  int steps = atoi(argv[2]);
  PD_Trainer *t = PD_NewTrainer(argv[1]);
  if (t == NULL) {
    fprintf(stderr, "PD_NewTrainer: %s\n", PD_GetLastError());
    return 1;
  }
  if (PD_TrainerFeedNum(t) != 2) {
    fprintf(stderr, "expected 2 feeds, got %d\n", PD_TrainerFeedNum(t));
    return 1;
  }
  const float w_true[4] = {0.5f, -1.25f, 2.0f, 0.75f};
  enum { B = 32 };
  float xbuf[B * 4], ybuf[B];
  float first = 0.0f, last = 0.0f;
  for (int s = 0; s < steps; ++s) {
    for (int i = 0; i < B; ++i) {
      float acc = 0.0f;
      for (int d = 0; d < 4; ++d) {
        xbuf[i * 4 + d] = frand();
        acc += xbuf[i * 4 + d] * w_true[d];
      }
      ybuf[i] = acc;
    }
    PD_Tensor feeds[2];
    feeds[0].data = xbuf;
    feeds[0].ndim = 2;
    feeds[0].shape[0] = B;
    feeds[0].shape[1] = 4;
    feeds[0].dtype = PD_FLOAT32;
    feeds[1].data = ybuf;
    feeds[1].ndim = 2;
    feeds[1].shape[0] = B;
    feeds[1].shape[1] = 1;
    feeds[1].dtype = PD_FLOAT32;
    float loss = 0.0f;
    if (PD_TrainerRun(t, feeds, 2, &loss) != 0) {
      fprintf(stderr, "PD_TrainerRun: %s\n", PD_GetLastError());
      PD_DeleteTrainer(t);
      return 1;
    }
    if (s == 0) first = loss;
    last = loss;
  }
  printf("first_loss %g\nlast_loss %g\n", first, last);
  if (argc > 3 && PD_TrainerSave(t, argv[3]) != 0) {
    fprintf(stderr, "PD_TrainerSave: %s\n", PD_GetLastError());
    PD_DeleteTrainer(t);
    return 1;
  }
  PD_DeleteTrainer(t);
  return last < first * 0.1f ? 0 : 2;
}
