"""Inference API — Config + Predictor
(reference paddle/fluid/inference/api/analysis_predictor.h:82,
analysis_config.cc, ZeroCopyTensor; python surface
paddle.inference.create_predictor).

TPU redesign of the analysis stack: the reference runs ~30 IR fuse passes
then a NaiveExecutor op loop; here the feed->fetch-pruned Program is traced
ONCE into a single jitted XLA computation (fusion/memory planning are the
compiler's job — SURVEY §7), cached per input signature, with params held
as device arrays in a private scope.
"""
from __future__ import annotations

import os
import threading
from typing import Sequence

import numpy as np

__all__ = ["Config", "Predictor", "create_predictor", "PredictorTensor"]


class Config:
    """Subset of the reference AnalysisConfig surface that is meaningful
    on TPU; GPU/MKLDNN/TensorRT switches are accepted and recorded as
    no-ops for API compatibility."""

    def __init__(self, model_dir=None, prog_file=None, params_file=None):
        self._model_dir = model_dir or (os.path.dirname(prog_file)
                                        if prog_file else None)
        self._model_filename = os.path.basename(prog_file) \
            if prog_file else None
        self._params_filename = os.path.basename(params_file) \
            if params_file else None
        self._use_bf16 = False
        self._memory_optim = True
        self._ir_optim = True
        self._glog_info = True
        self._warmup = True

    # -- reference switches (recorded; XLA owns the machinery) ----------
    def set_model(self, model_path, params_file=None):
        """set_model(dir) or set_model(prog_file, params_file) — the
        two-argument reference form passes FILE paths."""
        if params_file is not None:
            self._model_dir = os.path.dirname(model_path) or "."
            self._model_filename = os.path.basename(model_path)
            self._params_filename = os.path.basename(params_file)
        else:
            self._model_dir = model_path

    def model_dir(self):
        return self._model_dir

    def enable_memory_optim(self, flag=True):
        self._memory_optim = flag

    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag

    def disable_glog_info(self):
        self._glog_info = False

    def enable_use_gpu(self, *a, **k):  # accepted for parity; TPU build
        pass

    def disable_gpu(self):
        pass

    def set_cpu_math_library_num_threads(self, n):
        pass

    def enable_bf16(self, flag=True):
        """TPU-native switch: run inference compute in bfloat16 (MXU)."""
        self._use_bf16 = flag

    def switch_use_feed_fetch_ops(self, flag):
        pass


def _upcast(a):
    """Host-side output convention: bf16 compute results surface as f32."""
    return a.astype(np.float32) if a.dtype.name == "bfloat16" else a


class PredictorTensor:
    """ZeroCopyTensor equivalent (reference
    inference/api/analysis_predictor.h:120 ZeroCopy path): the handle may
    hold a *device-side* jax array after ``run()``; ``copy_to_cpu`` is the
    one host synchronization, so a caller that chains predictions and
    fetches only what it needs never pays a per-step device round-trip."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def copy_from_cpu(self, arr):
        self._value = np.ascontiguousarray(arr)

    def copy_to_cpu(self):
        from ..fluid import core
        return _upcast(core.batched_to_numpy([self._value])[0])

    def reshape(self, shape):
        if self._value is not None:
            self._value = self._value.reshape(shape)

    @property
    def shape(self):
        return None if self._value is None else tuple(self._value.shape)


class Predictor:
    def __init__(self, config: Config):
        from ..fluid import core
        from ..fluid.executor import Executor
        from ..fluid.io import load_inference_model
        from ..fluid.scope import Scope, scope_guard

        if not config.model_dir():
            raise ValueError("Config has no model_dir/prog_file")
        self._config = config
        self._scope = Scope()
        self._exe = Executor(core.default_place())
        with scope_guard(self._scope):
            self._program, feeds, fetch_vars = load_inference_model(
                config.model_dir(), self._exe,
                model_filename=config._model_filename,
                params_filename=config._params_filename)
        self._feed_names = list(feeds)
        self._fetch_vars = fetch_vars
        self._fetch_names = [v.name for v in fetch_vars]
        # int8-stored weights (slim post-training quantization) are
        # reconstructed into the scope on load
        from ..slim.quantization import load_quantized_weights
        load_quantized_weights(config.model_dir(), self._scope)
        self._inputs = {n: PredictorTensor(n) for n in self._feed_names}
        self._outputs = {n: PredictorTensor(n) for n in self._fetch_names}
        # one predictor, many threads: the handle tensors are shared
        # mutable state, so run() (set inputs -> execute -> set outputs)
        # must be atomic or two concurrent callers interleave buffers
        # (reference semantics: one ZeroCopy predictor per thread, but a
        # lock is cheaper than a clone and the jit cache is shared)
        self._run_lock = threading.RLock()
        if config._use_bf16:
            # real bf16 inference: params live in HBM as bf16, matmuls hit
            # the MXU at full rate; outputs are cast back to fp32 in run()
            import jax.numpy as jnp
            for name in self._scope.local_var_names():
                v = self._scope.find_var(name)
                if hasattr(v, "dtype") and v.dtype == jnp.float32:
                    self._scope.set(name, v.astype(jnp.bfloat16))

    # -- handle API (reference ZeroCopy path) ---------------------------
    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return list(self._fetch_names)

    def get_input_handle(self, name):
        return self._inputs[name]

    def get_input_tensor(self, name):  # old-API alias
        return self._inputs[name]

    def get_output_handle(self, name):
        return self._outputs[name]

    def get_output_tensor(self, name):
        return self._outputs[name]

    def run(self, inputs: Sequence[np.ndarray] | None = None):
        """Positional-inputs convenience (returns list of np arrays) or
        handle-style (copy_from_cpu then run() with no args).

        Thread-safe: concurrent run() calls serialize on an internal
        lock (handle-style callers that copy_from_cpu OUTSIDE run()
        from several threads still race by construction — use
        positional inputs or one predictor per thread for that)."""
        with self._run_lock:
            return self._run_locked(inputs)

    def _run_locked(self, inputs):
        from ..fluid.scope import scope_guard
        if inputs is not None:
            if len(inputs) != len(self._feed_names):
                raise ValueError(
                    f"run() got {len(inputs)} inputs, model expects "
                    f"{len(self._feed_names)}: {self._feed_names}")
            for n, a in zip(self._feed_names, inputs):
                self._inputs[n].copy_from_cpu(np.asarray(a))
        feed = {n: self._inputs[n]._value for n in self._feed_names}
        missing = [n for n, v in feed.items() if v is None]
        if missing:
            raise ValueError(
                f"inputs {missing} not set — copy_from_cpu them or pass "
                f"positional inputs to run()")
        if self._config._use_bf16:
            import jax.numpy as jnp
            feed = {n: (v.astype(jnp.bfloat16)
                        if v.dtype == np.float32 else v)
                    for n, v in feed.items()}
        # the executor compiles+caches per input signature — no separate
        # warmup pass needed. Outputs stay DEVICE-SIDE here (ZeroCopyRun
        # semantics): the handle's copy_to_cpu is the one sync point. The
        # positional convenience API below converts with a single batched
        # sync (core.batched_to_numpy) rather than one blocked fetch per
        # output — on the tunneled TPU runtime each blocked fetch costs a
        # full relay round-trip (~100 ms, see README "runtime notes").
        with scope_guard(self._scope):
            outs = self._exe.run(self._program, feed=dict(feed),
                                 fetch_list=self._fetch_names,
                                 return_numpy=False)
        for n, v in zip(self._fetch_names, outs):
            self._outputs[n]._value = v
        if inputs is None:
            return True  # handle-style ZeroCopyRun: fetch via handles
        from ..fluid import core
        return [_upcast(a) for a in core.batched_to_numpy(outs)]

    def clone(self):
        return Predictor(self._config)

    def clear_intermediate_tensor(self):
        pass

    def try_shrink_memory(self):
        self._exe._cache.clear()


def create_predictor(config: Config) -> Predictor:
    """reference paddle_infer.create_predictor."""
    return Predictor(config)
