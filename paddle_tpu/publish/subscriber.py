"""Version subscriber: registry announce → zero-downtime hot swap.

A serving process runs one ``VersionSubscriber`` per engine (or per
swap callback — the router's fleet rollout plugs in as ``swap_fn``).
Two watch transports, same behavior:

  * ``endpoint=`` — stream ``pub_watch`` version-announce frames over
    the mux wire from whichever server hosts the registry verbs (the
    PSServer when publishing is wired there, or a standalone
    RegistryServer), with the same reconnect-and-resync loop the PS
    hot-row invalidation subscriber uses;
  * file mode — poll ``registry.reload()`` on the shared publish
    root, for single-host deployments with no registry endpoint.

The swap itself is the engine's existing two-phase warm start:
``read_checkpoint`` does the disk read + device upload OFF the step
lock, ``adopt_checkpoint`` flips one reference under it — in-flight
generations finish on the old weights' tokens-so-far, new prefills
see the new version, and the wire never observes a pause. A version
whose swap raises (missing params, torn manifest) is memoized as
failed and never retried, so one bad publication cannot wedge the
subscriber loop; the registry's NEXT announce (e.g. the rollback)
proceeds normally.
"""
from __future__ import annotations

import os
import threading
import time

from ..observability import flight as _flight, registry as _obs
from .registry import RegistryClient, VersionRegistry

__all__ = ["VersionSubscriber"]

_SWAP_SECONDS = _obs.histogram(
    "paddle_tpu_publish_swap_seconds",
    "hot-swap wall time per phase: load = off-lock disk+device, "
    "flip = under the step lock (the only instant traffic could "
    "notice — must stay ~0)", ["phase"])
_LAG = _obs.gauge(
    "paddle_tpu_publish_subscriber_lag_versions",
    "registry latest minus the newest version this subscriber has "
    "adopted (0 = caught up)", ["root"])


class VersionSubscriber:
    """Watches a publish root and hot-swaps an engine (or calls a
    custom ``swap_fn(version, record)``) on every publication or
    rollback announce, newest-wins."""

    def __init__(self, root: str, engine=None, swap_fn=None,
                 registry: VersionRegistry | None = None,
                 endpoint: str | None = None, secret: str | None = None,
                 kinds=("gpt-decode",), poll: float | None = None):
        if engine is None and swap_fn is None:
            raise ValueError("VersionSubscriber needs an engine or a "
                             "swap_fn")
        self.root = root
        self.engine = engine
        self._swap_fn = swap_fn
        self.registry = registry or VersionRegistry(root)
        self.endpoint = endpoint
        self.secret = secret
        self.kinds = frozenset(kinds) if kinds else None
        self.poll = float(os.environ.get("PADDLE_TPU_PUBLISH_POLL",
                                         "0.5") or 0.5) \
            if poll is None else float(poll)
        self._lock = threading.Lock()
        self.current_version = 0
        self.swaps = 0
        self.failed_versions: set[int] = set()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._client: RegistryClient | None = None

    # -- swap ----------------------------------------------------------
    def _swap(self, version: int, rec: dict) -> bool:
        if self._swap_fn is not None:
            self._swap_fn(version, rec)
            return True
        t0 = time.perf_counter()
        self.engine.warm_start(self.root, step=version,
                               version=version)
        _SWAP_SECONDS.labels(phase="load").observe(
            time.perf_counter() - t0)
        return True

    def maybe_swap(self, rec: dict | None = None) -> bool:
        """Adopt the registry's latest (or ``rec``) if it is new,
        matches our kinds, and hasn't already failed. Returns True
        when a swap happened. Serialized — announce storms collapse to
        newest-wins because each swap re-reads the latest pointer."""
        if rec is None:
            rec = self.registry.record_latest()
        if not rec:
            return False
        version = int(rec.get("version", 0))
        with self._lock:
            if not version or version == self.current_version \
                    or version in self.failed_versions:
                self._set_lag()
                return False
            if self.kinds and rec.get("kind") not in self.kinds:
                return False
            try:
                self._swap(version, rec)
            except Exception:
                self.failed_versions.add(version)
                _flight.record("publish", "swap_failed",
                               root=self.root, version=version)
                self._set_lag()
                return False
            self.current_version = version
            self.swaps += 1
            self._set_lag()
        _flight.record("publish", "swap", root=self.root,
                       version=version, step=rec.get("step"),
                       kind=rec.get("kind"))
        return True

    def _set_lag(self):
        # called under self._lock
        lag = max(0, self.registry.latest() - self.current_version)
        _LAG.labels(root=self.root).set(lag)

    # -- watch loops ---------------------------------------------------
    def _poll_loop(self):
        while not self._stop.wait(self.poll):
            try:
                self.registry.reload(missing_ok=True)
                self.maybe_swap()
            except Exception:
                continue  # transient fs error: next tick retries

    def start(self) -> "VersionSubscriber":
        """Catch up to the current latest, then watch. Endpoint mode
        streams announces (RegistryClient.watch reconnects on its
        own); file mode polls reload()."""
        self.registry.reload(missing_ok=True)
        self.maybe_swap()
        if self.endpoint:
            self._client = RegistryClient(self.endpoint,
                                          secret=self.secret)
            self._client.watch(
                lambda rec: self.maybe_swap(rec), stop=self._stop)
        else:
            self._thread = threading.Thread(
                target=self._poll_loop, daemon=True,
                name="publish-subscriber")
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self._client is not None:
            self._client.close()
            self._client = None
