"""Publication exporters: train-side state → numbered servable versions.

``Publisher`` is the commit path every producer shares. One published
version is one step in a dedicated ``CheckpointStore`` under the
publish root (version number == manifest step), so consecutive
publications dedup at the chunk level — a training interval that
touched 1% of a table re-references ~99% of its chunks — and the
manifest rename is the data commit. The registry record (latest
pointer + parity digest) lands strictly AFTER the manifest: a
publisher killed anywhere in between leaves a dangling manifest no
subscriber can see, and the previous version stays servable
bit-for-bit.

``PSExporter`` closes the loop from PS training: the server's
``after_commit`` hook feeds ``note_commit`` (counters only — the push
path never does publication IO), and a background thread publishes
when any cadence knob fires (every N applied mutations, every T
seconds, every R rows touched). The table export runs under the
server's apply lock (same consistency contract as snapshots:
``export_state`` copies, so the lock covers the memcpy, not the chunk
IO); the chunk+manifest write happens off-lock.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
import time

import numpy as np

from ..checkpoint.store import CheckpointStore
from ..observability import flight as _flight, registry as _obs
from .registry import VersionRegistry

__all__ = ["Publisher", "PSExporter", "parity_digest"]

_DEDUP_RATIO = _obs.gauge(
    "paddle_tpu_publish_dedup_ratio",
    "chunk dedup of the newest publication: fraction of its chunks "
    "re-referenced from earlier versions (1.0 = nothing rewritten)")
_PUBLISH_SECONDS = _obs.histogram(
    "paddle_tpu_publish_seconds",
    "wall time of one version publication (export + chunks + "
    "manifest + registry commit)", ["kind"])


def parity_digest(payload: dict) -> str:
    """Digest of a committed manifest's content identity: every
    array's name, dtype/shape, and chunk-hash sequence, canonically
    ordered. Two versions with equal digests restore bit-for-bit
    equal state — the registry stores it so a subscriber (or the
    kill-mid-publication drill) can verify what it serves without
    re-reading chunk data."""
    ident = {name: {"dtype": rec["dtype"],
                    "shape": rec["shape"],
                    "chunks": [c["h"] for c in rec["chunks"]]}
             for name, rec in payload["arrays"].items()}
    body = json.dumps(ident, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")
    return hashlib.sha256(body).hexdigest()


class Publisher:
    """Versioned publication front over one publish root: a
    CheckpointStore for the data and a VersionRegistry for the
    pointers. Thread-safe; one instance may serve several producers
    (dense trainer + PS exporter publishing distinct kinds)."""

    def __init__(self, root: str, registry: VersionRegistry | None = None,
                 store: CheckpointStore | None = None,
                 keep: int | None = None, run: str = ""):
        self.root = root
        if keep is None:
            keep = int(os.environ.get("PADDLE_TPU_PUBLISH_KEEP", "4")
                       or 0) or 4
        self.store = store or CheckpointStore(root, keep=keep)
        self.registry = registry or VersionRegistry(root)
        self.run = run
        self._lock = threading.Lock()
        self.published = 0
        self.last_version = 0
        self.last_dedup_ratio = 0.0

    def publish_arrays(self, arrays: dict, *, step: int, kind: str,
                       meta: dict | None = None) -> dict:
        """Publish one version from name→array state. Returns the
        committed registry record (version, step, kind, digest,
        dedup)."""
        t0 = time.perf_counter()
        with self._lock:
            version = self.registry.next_version()
            c = self.store.chunks
            w0, d0 = c.chunks_written, c.dedup_hits
            self.store.save(arrays, step=version,
                            meta=dict(meta or {}, kind=kind,
                                      step=int(step)))
            # the manifest for `version` is now durable — a crash from
            # here on leaves it dangling (invisible) until the registry
            # record below commits, never a half-published version
            written = c.chunks_written - w0
            total = written + (c.dedup_hits - d0)
            ratio = (1.0 - written / total) if total else 1.0
            payload = self.store.latest_manifest(version)
            digest = parity_digest(payload)
            rec = self.registry.publish(
                version, step=step, kind=kind, digest=digest,
                run=self.run, extra={"dedup": round(ratio, 4)})
            self.published += 1
            self.last_version = version
            self.last_dedup_ratio = ratio
        _DEDUP_RATIO.set(ratio)
        dt = time.perf_counter() - t0
        _PUBLISH_SECONDS.labels(kind=kind).observe(dt)
        _flight.record("publish", "export", root=self.root,
                       version=version, step=int(step), kind=kind,
                       dedup=round(ratio, 4), seconds=round(dt, 6))
        return rec

    def publish_model(self, model, *, step: int) -> dict:
        """Publish a GPTDecodeModel's weights in the exact layout
        ``Engine.warm_start`` restores: tree-path-keyed arrays plus the
        gpt-decode meta (kind + cfg) ``read_checkpoint`` validates."""
        import jax

        arrays = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                model.params)[0]:
            arrays[jax.tree_util.keystr(path)] = np.asarray(leaf)
        return self.publish_arrays(
            arrays, step=step, kind="gpt-decode",
            meta={"cfg": dataclasses.asdict(model.cfg)})


class PSExporter:
    """Continuous publication off a live PSServer. The server's
    ``_after_commit`` calls ``note_commit`` per applied mutation
    (cheap: counters + event). The exporter thread wakes when a knob's
    threshold is crossed — steps (applied mutations), seconds, or rows
    touched — exports every table under the apply lock, and publishes
    through the shared ``Publisher`` off-lock."""

    def __init__(self, server, publisher: Publisher,
                 every_steps: int | None = None,
                 every_seconds: float | None = None,
                 every_rows: int | None = None):
        env = os.environ.get
        self.server = server
        self.publisher = publisher
        self.every_steps = int(env("PADDLE_TPU_PUBLISH_EVERY_STEPS",
                                   "0") or 0) \
            if every_steps is None else int(every_steps)
        self.every_seconds = float(
            env("PADDLE_TPU_PUBLISH_EVERY_SECONDS", "0") or 0) \
            if every_seconds is None else float(every_seconds)
        self.every_rows = int(env("PADDLE_TPU_PUBLISH_EVERY_ROWS",
                                  "0") or 0) \
            if every_rows is None else int(every_rows)
        self._lock = threading.Lock()
        self._steps = 0           # mutations since last publication
        self._rows = 0            # rows touched since last publication
        self._kick = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.last_publish_unix = 0.0

    def note_commit(self, op: str, rows: int = 0):
        """Called from the server's after_commit hook — push-path hot,
        so this only counts and (maybe) sets the wake event."""
        with self._lock:
            self._steps += 1
            self._rows += int(rows)
            due = (self.every_steps
                   and self._steps >= self.every_steps) \
                or (self.every_rows and self._rows >= self.every_rows)
        if due:
            self._kick.set()

    def note_rows(self, rows: int):
        """Row accounting for the every_rows knob — called from the
        push apply path with the request's key count (after_commit
        only sees the op name)."""
        with self._lock:
            self._rows += int(rows)
            due = bool(self.every_rows
                       and self._rows >= self.every_rows)
        if due:
            self._kick.set()

    def _due(self) -> bool:
        with self._lock:
            if self._steps == 0:
                return False
            if self.every_steps and self._steps >= self.every_steps:
                return True
            if self.every_rows and self._rows >= self.every_rows:
                return True
        return bool(self.every_seconds
                    and time.time() - self.last_publish_unix
                    >= self.every_seconds)

    def publish_now(self) -> dict | None:
        """One publication cycle (also the thread body's work unit).
        Returns the registry record, or None when the server holds no
        tables yet."""
        srv = self.server
        with self._lock:
            steps, self._steps = self._steps, 0
            self._rows = 0
        # export under the apply lock: same instant for every table,
        # and never interleaved with a push's apply+journal pair
        with srv._apply_lock:
            arrays = {}
            meta_tables = {}
            with srv._tables_lock:
                items = list(srv.tables.items())
            for name, t in items:
                st = t.export_state()
                arrays[f"k:{name}"] = st["keys"]
                arrays[f"r:{name}"] = st["rows"]
                meta_tables[name] = {"dim": st["dim"],
                                     "init_std": st["init_std"],
                                     "seed": st["seed"]}
            with srv._snap_lock:
                mutations = srv._mutations
        if not arrays:
            return None
        rec = self.publisher.publish_arrays(
            arrays, step=mutations, kind="ps-table",
            meta={"endpoint": srv.endpoint, "tables": meta_tables,
                  "interval_steps": steps})
        self.last_publish_unix = time.time()
        return rec

    def _loop(self):
        while not self._stop.is_set():
            wait = 0.05 if not self.every_seconds else \
                min(self.every_seconds / 4, 1.0)
            self._kick.wait(wait)
            self._kick.clear()
            if self._stop.is_set():
                return
            if self._due():
                try:
                    self.publish_now()
                except Exception:
                    # publication must never take the shard down; the
                    # next cadence tick retries (previous version is
                    # still the registry's latest)
                    _flight.record("publish", "export_failed",
                                   root=self.publisher.root,
                                   endpoint=self.server.endpoint)

    def start(self) -> "PSExporter":
        if self._thread is None:
            self.last_publish_unix = time.time()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="ps-publisher")
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._kick.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
