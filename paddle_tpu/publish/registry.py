"""Durable model-version registry with a streamed watch API.

The registry is the publication pipeline's COMMIT LOG: the exporter
writes a content-addressed version manifest through the checkpoint
store (manifest rename = the version's data commit), then records it
here — latest/pinned/rollback pointers plus per-version metadata
(training step, source run, parity digest). The registry file itself
commits the same way a checkpoint manifest does (canonical JSON, CRC,
tmp + fsync + os.replace), so a publisher killed at ANY byte leaves
the previous version authoritative: a manifest without a registry
record is invisible, a registry record always points at a committed
manifest.

Pointers:
  latest   — what subscribers should serve (rollback REWINDS it)
  pinned   — the operator-blessed fallback; the router rolls a failed
             fleet rollout back to it (docs/ONLINE_LEARNING.md)

Watch API: `registry_dispatch` serves the `pub_*` verbs over the PR-11
mux wire — `pub_watch` is a dispatch GENERATOR whose version-announce
frames ride the same F_STREAM machinery as the PS hot-row
invalidations (bounded per-subscriber queue, keepalive frames, cancel
via F_CANCEL -> GeneratorExit). The verbs are hosted by the PSServer
when publishing is wired there, or by the standalone RegistryServer.
Cross-process publishers are picked up by `reload()` (the watch loop
re-reads the file on idle), so the wire and the file agree on one
source of truth.
"""
from __future__ import annotations

import json
import os
import queue
import threading
import time
import zlib

from ..observability import flight as _flight, registry as _obs

__all__ = ["RegistryError", "VersionRegistry", "registry_dispatch",
           "RegistryServer", "RegistryClient", "PUB_READ_OPS"]

FORMAT = "paddle-tpu-pubreg-v1"
REGISTRY_NAME = "REGISTRY.json"

_PUBLICATIONS = _obs.counter(
    "paddle_tpu_publish_publications_total",
    "model versions committed to the registry, by manifest kind",
    ["kind"], always=True)
_ROLLBACKS = _obs.counter(
    "paddle_tpu_publish_rollbacks_total",
    "registry rollbacks (latest rewound to an older version)",
    always=True)

# pub_* verbs that never mutate the registry — dedup-exempt on any
# hosting server (a replayed pub_watch must open a fresh stream)
PUB_READ_OPS = frozenset({"pub_latest", "pub_get", "pub_list",
                          "pub_watch"})


class RegistryError(RuntimeError):
    """No committed registry, or the file on disk is unreadable."""


def _canonical(payload: dict) -> bytes:
    return json.dumps(payload, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")


class _WatchSub:
    """One watcher's announce feed: a bounded queue; overflow keeps a
    'behind' flag so a slow watcher resyncs from the latest record
    instead of stalling publications or silently losing the newest."""

    def __init__(self, maxsize: int):
        self.q: queue.Queue = queue.Queue(maxsize)
        self.behind = False
        self.lock = threading.Lock()


class VersionRegistry:
    """File-backed registry under a publish root. Thread-safe; shared
    by the exporter (publish), the rollout coordinator (pin/rollback)
    and any number of watchers (in-process queues + `reload()` for
    records committed by other processes)."""

    def __init__(self, root: str):
        self.root = root
        self.path = os.path.join(root, REGISTRY_NAME)
        self._lock = threading.RLock()
        self._state: dict = {"latest": 0, "pinned": 0, "rollbacks": 0,
                             "versions": {}}
        self._subs: dict[int, _WatchSub] = {}
        self._sub_seq = 0
        self._queue_max = int(os.environ.get(
            "PADDLE_TPU_PUBLISH_WATCH_QUEUE", "256") or 0)
        # commit protocol state: snapshots are numbered under _lock,
        # the file write runs with NO lock held (newest snapshot wins)
        self._io_cond = threading.Condition()
        self._io_gen = 0          # last snapshot taken
        self._io_written = 0      # last snapshot durably on disk
        self._io_busy = False
        self.reload(missing_ok=True)

    # -- durability ----------------------------------------------------
    def _snapshot_locked(self) -> tuple[int, bytes]:
        """Serialize the current state to commit-ready doc bytes and
        stamp it with a monotonically increasing generation. Caller
        holds ``_lock``; the returned doc is written by ``_write_doc``
        AFTER the lock is released — holding a mutex across file I/O
        would stall every reader behind an fsync."""
        payload = self._state
        body = _canonical(payload)
        doc = json.dumps({"format": FORMAT,
                          "crc32": zlib.crc32(body) & 0xFFFFFFFF,
                          "payload": payload}).encode("utf-8")
        self._io_gen += 1
        return self._io_gen, doc

    def _write_doc(self, gen: int, doc: bytes) -> None:
        """Commit one snapshot, lock-free: single-flight with
        newest-generation-wins. A writer that arrives while an older
        snapshot is in flight waits for it; a writer whose snapshot
        was superseded on disk skips entirely — its mutation is
        already contained in the newer doc. The rename is the commit
        point, exactly like a checkpoint manifest."""
        with self._io_cond:
            while self._io_busy and self._io_written < gen:
                self._io_cond.wait(1.0)
            if self._io_written >= gen:
                return            # a newer snapshot already landed
            self._io_busy = True
        try:
            os.makedirs(self.root, exist_ok=True)
            tmp = f"{self.path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(doc)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        finally:
            with self._io_cond:
                self._io_busy = False
                if gen > self._io_written:
                    self._io_written = gen
                self._io_cond.notify_all()

    @staticmethod
    def _load_file(path: str) -> dict:
        with open(path, "rb") as f:
            doc = json.loads(f.read().decode("utf-8"))
        if doc.get("format") != FORMAT:
            raise RegistryError(f"{path}: not a {FORMAT} registry")
        payload = doc["payload"]
        crc = zlib.crc32(_canonical(payload)) & 0xFFFFFFFF
        if crc != int(doc.get("crc32", -1)):
            raise RegistryError(f"{path}: CRC mismatch")
        return payload

    def reload(self, missing_ok: bool = False) -> bool:
        """Re-read the file (cross-process publications). Returns True
        when `latest` moved; newly-visible records are announced to
        in-process watchers. A torn/corrupt file keeps the in-memory
        state (the previous commit stays authoritative)."""
        try:
            payload = self._load_file(self.path)
        except FileNotFoundError:
            if missing_ok:
                return False
            raise RegistryError(f"no registry under {self.root}")
        except (RegistryError, OSError, ValueError, KeyError):
            return False
        with self._lock:
            moved = int(payload.get("latest", 0)) \
                != int(self._state.get("latest", 0))
            self._state = payload
            rec = self._record_locked(int(payload.get("latest", 0)))
        if moved and rec is not None:
            self._announce(rec)
        return moved

    # -- queries -------------------------------------------------------
    def latest(self) -> int:
        with self._lock:
            return int(self._state["latest"])

    def pinned(self) -> int:
        with self._lock:
            return int(self._state["pinned"])

    def rollbacks(self) -> int:
        with self._lock:
            return int(self._state.get("rollbacks", 0))

    def _record_locked(self, version: int) -> dict | None:
        rec = self._state["versions"].get(str(version))
        if rec is None:
            return None
        return dict(rec, version=int(version),
                    pinned=int(self._state["pinned"]))

    def get(self, version: int) -> dict | None:
        with self._lock:
            return self._record_locked(int(version))

    def record_latest(self) -> dict | None:
        with self._lock:
            return self._record_locked(int(self._state["latest"]))

    def versions(self) -> list[dict]:
        with self._lock:
            return [self._record_locked(int(v))
                    for v in sorted(self._state["versions"],
                                    key=int)]

    def next_version(self) -> int:
        with self._lock:
            known = [int(v) for v in self._state["versions"]]
            return max([int(self._state["latest"])] + known) + 1

    # -- mutations -----------------------------------------------------
    def publish(self, version: int, *, step: int, kind: str,
                digest: str = "", run: str = "",
                extra: dict | None = None) -> dict:
        """Commit one published version: record + move `latest`. The
        caller must have committed the version's manifest FIRST — this
        is the visibility flip, done after the data is durable."""
        with self._lock:
            version = int(version)
            rec = {"step": int(step), "kind": str(kind),
                   "digest": str(digest), "run": str(run),
                   "unix": time.time()}
            if extra:
                rec["extra"] = extra
            self._state["versions"][str(version)] = rec
            self._state["latest"] = version
            gen, doc = self._snapshot_locked()
            out = self._record_locked(version)
        self._write_doc(gen, doc)
        _PUBLICATIONS.labels(kind=str(kind)).inc()
        _flight.record("publish", "publish", root=self.root,
                       version=version, step=int(step), kind=kind)
        self._announce(out)
        return out

    def pin(self, version: int) -> dict:
        with self._lock:
            rec = self._record_locked(int(version))
            if rec is None:
                raise RegistryError(f"cannot pin unknown version "
                                    f"{version}")
            self._state["pinned"] = int(version)
            gen, doc = self._snapshot_locked()
            out = self._record_locked(int(version))
        self._write_doc(gen, doc)
        return out

    def rollback(self, to: int | None = None) -> dict:
        """Rewind `latest` to `to` (default: the pinned version, else
        the newest version older than latest). Announced to watchers
        like a publication — subscribers swap DOWN the same way they
        swap up."""
        with self._lock:
            latest = int(self._state["latest"])
            if to is None:
                to = int(self._state["pinned"]) or 0
            if not to:
                older = [int(v) for v in self._state["versions"]
                         if int(v) < latest]
                to = max(older) if older else 0
            rec = self._record_locked(int(to))
            if rec is None:
                raise RegistryError(
                    f"no rollback target (asked {to}, latest {latest})")
            self._state["latest"] = int(to)
            self._state["rollbacks"] = \
                int(self._state.get("rollbacks", 0)) + 1
            gen, doc = self._snapshot_locked()
            out = self._record_locked(int(to))
        self._write_doc(gen, doc)
        _ROLLBACKS.inc()
        _flight.record("publish", "rollback", root=self.root,
                       to=int(to), was=latest)
        self._announce(out)
        return out

    # -- watch fan-out -------------------------------------------------
    def watch_queue(self) -> tuple[int, _WatchSub]:
        with self._lock:
            self._sub_seq += 1
            sid = self._sub_seq
            sub = _WatchSub(self._queue_max)
            self._subs[sid] = sub
            return sid, sub

    def unwatch(self, sid: int):
        with self._lock:
            self._subs.pop(sid, None)

    def _announce(self, rec: dict):
        with self._lock:
            subs = list(self._subs.values())
        for s in subs:
            try:
                s.q.put_nowait(dict(rec))
            except queue.Full:
                with s.lock:
                    s.behind = True


def registry_dispatch(reg: VersionRegistry, req: dict,
                      keepalive: float = 5.0):
    """The pub_* verb switch, shared by every server that hosts a
    registry (PSServer when publishing is wired, RegistryServer
    standalone). Returns a reply dict — or, for pub_watch, a dispatch
    generator the RPC layer streams as server-push frames."""
    op = req["op"]
    if op == "pub_latest":
        reg.reload(missing_ok=True)
        return {"latest": reg.latest(), "pinned": reg.pinned(),
                "record": reg.record_latest()}
    if op == "pub_get":
        return {"record": reg.get(int(req["version"]))}
    if op == "pub_list":
        return {"versions": reg.versions(), "latest": reg.latest(),
                "pinned": reg.pinned(),
                "rollbacks": reg.rollbacks()}
    if op == "pub_publish":
        rec = reg.publish(int(req["version"]),
                          step=int(req.get("step", 0)),
                          kind=str(req.get("kind", "")),
                          digest=str(req.get("digest", "")),
                          run=str(req.get("run", "")),
                          extra=req.get("extra"))
        return {"record": rec}
    if op == "pub_pin":
        return {"record": reg.pin(int(req["version"]))}
    if op == "pub_rollback":
        to = req.get("to")
        return {"record": reg.rollback(None if to is None
                                       else int(to))}
    if op == "pub_watch":
        return _watch_stream(reg, keepalive)
    raise ValueError(f"unknown publish op {op!r}")


def _watch_stream(reg: VersionRegistry, keepalive: float):
    """pub_watch dispatch generator: subscribe ack (carrying the
    current latest so a late joiner can catch up immediately), then
    one announce frame per publication/rollback. Keepalives every few
    seconds keep the stream's cancel check live while nothing
    publishes — and double as the reload tick that surfaces versions
    committed by OTHER processes into this wire."""
    sid, sub = reg.watch_queue()
    try:
        yield {"subscribed": True, "latest": reg.latest(),
               "record": reg.record_latest()}
        while True:
            with sub.lock:
                behind, sub.behind = sub.behind, False
            if behind:
                # overflow: resync from the authoritative pointer
                # instead of replaying a lost backlog
                rec = reg.record_latest()
                if rec is not None:
                    yield dict(rec, resync=True)
            try:
                ev = sub.q.get(timeout=keepalive)
            except queue.Empty:
                reg.reload(missing_ok=True)  # cross-process publishers
                yield {"keepalive": True, "latest": reg.latest()}
                continue
            yield ev
    finally:
        reg.unwatch(sid)


class RegistryServer:
    """Standalone registry endpoint over the mux wire — for
    deployments where the publisher is not a PSServer (e.g. a dense
    trainer publishing straight from its host loop). Serves exactly
    `registry_dispatch` plus ping."""

    READ_OPS = frozenset(PUB_READ_OPS | {"ping"})

    def __init__(self, root: str, endpoint: str = "127.0.0.1:0",
                 secret: str | None = None,
                 registry: VersionRegistry | None = None):
        import socketserver

        from ..distributed.fleet.runtime.rpc import (RpcServerState,
                                                     serve_connection)
        self.registry = registry or VersionRegistry(root)
        self._rpc = RpcServerState(read_ops=self.READ_OPS,
                                   secret=secret)
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                serve_connection(self.request, outer._dispatch,
                                 outer._rpc)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        host, port = endpoint.rsplit(":", 1)
        self._server = Server((host, int(port)), Handler)
        self.endpoint = f"{host}:{self._server.server_address[1]}"
        self._thread: threading.Thread | None = None

    def _dispatch(self, req: dict):
        if req.get("op") == "ping":
            return {"ok": True, "latest": self.registry.latest()}
        return registry_dispatch(self.registry, req)

    def start(self) -> "RegistryServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="publish-registry")
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class RegistryClient:
    """Thin pub_* client over the multiplexed RpcClient — works
    against a RegistryServer or a publish-wired PSServer alike."""

    def __init__(self, endpoint: str, secret: str | None = None,
                 timeout: float | None = None):
        from ..distributed.fleet.runtime.rpc import RpcClient
        self._rpc = RpcClient(endpoint, secret=secret,
                              timeout=timeout if timeout is not None
                              else 30.0)

    def latest(self) -> dict:
        return self._rpc.call({"op": "pub_latest"})

    def get(self, version: int) -> dict | None:
        return self._rpc.call({"op": "pub_get",
                               "version": int(version)}).get("record")

    def list(self) -> dict:
        return self._rpc.call({"op": "pub_list"})

    def publish(self, version: int, *, step: int, kind: str,
                digest: str = "", run: str = "",
                extra: dict | None = None) -> dict:
        return self._rpc.call({"op": "pub_publish",
                               "version": int(version),
                               "step": int(step), "kind": kind,
                               "digest": digest, "run": run,
                               "extra": extra})["record"]

    def pin(self, version: int) -> dict:
        return self._rpc.call({"op": "pub_pin",
                               "version": int(version)})["record"]

    def rollback(self, to: int | None = None) -> dict:
        return self._rpc.call({"op": "pub_rollback",
                               "to": to})["record"]

    def watch(self, on_record, stop: threading.Event | None = None,
              keepalive_timeout: float = 30.0) -> threading.Event:
        """Stream version announces: ``on_record(rec)`` fires per
        publication/rollback from a background thread (rec carries
        version/step/kind/digest/pinned). Returns a stop Event; a
        broken stream re-subscribes with backoff — the subscribe ack's
        current-latest record is re-delivered so a watcher that missed
        announces while disconnected catches up."""
        stop = stop or threading.Event()

        def loop():
            while not stop.is_set():
                gen = None
                try:
                    gen = self._rpc.call_stream(
                        {"op": "pub_watch"}, timeout=30.0,
                        stream_timeout=keepalive_timeout)
                    for ev in gen:
                        if stop.is_set():
                            return
                        if not isinstance(ev, dict):
                            continue
                        rec = ev.get("record") \
                            if ev.get("subscribed") else ev
                        if isinstance(rec, dict) \
                                and rec.get("version"):
                            on_record(rec)
                except Exception:
                    pass     # registry host down: re-subscribe
                finally:
                    if gen is not None:
                        try:
                            gen.close()
                        except Exception:
                            pass
                stop.wait(0.5)

        threading.Thread(target=loop, daemon=True,
                         name="publish-watch").start()
        return stop

    def close(self):
        self._rpc.close()
