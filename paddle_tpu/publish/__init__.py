"""paddle_tpu.publish — the online-learning loop's publication tier.

Closes train→serve continuously (reference: the ads-scale
Communicator/BoxPS loop): exporters route PS/base and dense trainer
state through the content-addressed checkpoint store into numbered
version manifests, a durable registry tracks latest/pinned/rollback
pointers and streams version announces over the mux wire, and
subscribers hot-swap serving engines mid-traffic with the two-phase
read/adopt warm start. See docs/ONLINE_LEARNING.md.
"""
from .exporter import PSExporter, Publisher, parity_digest
from .registry import (PUB_READ_OPS, RegistryClient, RegistryError,
                       RegistryServer, VersionRegistry,
                       registry_dispatch)
from .subscriber import VersionSubscriber

__all__ = [
    "Publisher", "PSExporter", "parity_digest",
    "VersionRegistry", "RegistryServer", "RegistryClient",
    "RegistryError", "registry_dispatch", "PUB_READ_OPS",
    "VersionSubscriber",
]
