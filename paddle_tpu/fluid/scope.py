"""Scope: host-side name -> device-array store.

Capability parity with the reference Scope/Variable
(/root/reference/paddle/fluid/framework/scope.h:46), redesigned: values are
jax.Arrays (XLA device buffers) or host objects; there is no allocator to
manage — XLA owns device memory. Parent-chain lookup is preserved for local
scopes (used by control flow and tests).
"""
from __future__ import annotations

from typing import Any

import numpy as np


class Scope:
    def __init__(self, parent: "Scope | None" = None):
        self._vars: dict[str, Any] = {}
        self.parent = parent
        self._kids: list[Scope] = []

    def new_scope(self) -> "Scope":
        kid = Scope(self)
        self._kids.append(kid)
        return kid

    def var(self, name: str, value=None):
        """Create (or get) a variable slot in *this* scope."""
        if name not in self._vars:
            self._vars[name] = value
        return self._vars[name]

    def find_var(self, name: str):
        s: Scope | None = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s.parent
        return None

    def has(self, name: str) -> bool:
        s: Scope | None = self
        while s is not None:
            if name in s._vars:
                return True
            s = s.parent
        return False

    def set(self, name: str, value):
        """Set in the scope that owns `name`, else locally."""
        s: Scope | None = self
        while s is not None:
            if name in s._vars:
                s._vars[name] = value
                return
            s = s.parent
        self._vars[name] = value

    def erase(self, name: str):
        self._vars.pop(name, None)

    def local_var_names(self) -> list[str]:
        return list(self._vars)

    def drop_kids(self):
        self._kids.clear()

    def numpy(self, name: str) -> np.ndarray:
        v = self.find_var(name)
        if v is None:
            raise KeyError(name)
        return np.asarray(v)


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


import contextlib


@contextlib.contextmanager
def scope_guard(scope: Scope):
    global _global_scope
    old, _global_scope = _global_scope, scope
    try:
        yield
    finally:
        _global_scope = old
