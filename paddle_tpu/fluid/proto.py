"""Program serialisation (reference framework/framework.proto ProgramDesc).

The wire format mirrors the reference schema shape — program{blocks{vars,ops}}
with typed attrs and Block-ref attrs stored as block indices — encoded for now
with a versioned pickle header (a protoc-generated encoder can swap in behind
the same serialize/deserialize API without touching callers).
"""
from __future__ import annotations

import pickle

from .framework import Block, Operator, Program, Parameter, Variable

MAGIC = b"PTPU0001"


def program_to_dict(program: Program) -> dict:
    blocks = []
    for b in program.blocks:
        vars_ = []
        for v in b.vars.values():
            vars_.append({
                "name": v.name, "shape": v.shape, "dtype": v.dtype,
                "type": v.type, "persistable": v.persistable,
                "stop_gradient": v.stop_gradient, "is_data": v.is_data,
                "is_parameter": isinstance(v, Parameter),
                "trainable": getattr(v, "trainable", False),
            })
        ops = []
        for op in b.ops:
            attrs = {}
            for k, val in op.attrs.items():
                if isinstance(val, Block):
                    attrs[k] = {"__block__": val.idx}
                else:
                    attrs[k] = val
            ops.append({"type": op.type, "inputs": op.inputs,
                        "outputs": op.outputs, "attrs": attrs})
        blocks.append({"idx": b.idx, "parent_idx": b.parent_idx,
                       "vars": vars_, "ops": ops})
    return {"blocks": blocks, "random_seed": program.random_seed,
            "is_test": program._is_test}


def program_from_dict(d: dict) -> Program:
    p = Program.__new__(Program)
    p.random_seed = d.get("random_seed", 0)
    p._is_test = d.get("is_test", False)
    p._pipeline_opt = None
    p._sharding_info = None
    p._version = 0
    p._analysis_cache = None
    p.current_block_idx = 0
    p.blocks = []
    for bd in d["blocks"]:
        b = Block(p, bd["idx"], bd["parent_idx"])
        p.blocks.append(b)
    for bd, b in zip(d["blocks"], p.blocks):
        for vd in bd["vars"]:
            cls = Parameter if vd.get("is_parameter") else Variable
            if cls is Parameter:
                v = Parameter(b, vd["name"], vd["shape"], vd["dtype"],
                              trainable=vd.get("trainable", True))
            else:
                v = Variable(b, vd["name"], shape=vd["shape"],
                             dtype=vd["dtype"], type=vd.get("type", "dense"),
                             persistable=vd.get("persistable", False),
                             stop_gradient=vd.get("stop_gradient", False),
                             is_data=vd.get("is_data", False))
            b.vars[v.name] = v
        for od in bd["ops"]:
            op = Operator.__new__(Operator)
            op.block = b
            op.type = od["type"]
            op.inputs = {k: list(v) for k, v in od["inputs"].items()}
            op.outputs = {k: list(v) for k, v in od["outputs"].items()}
            op.attrs = {}
            for k, val in od["attrs"].items():
                if isinstance(val, dict) and "__block__" in val:
                    op.attrs[k] = p.blocks[val["__block__"]]
                else:
                    op.attrs[k] = val
            b.ops.append(op)
    return p


def serialize_program(program: Program, meta: dict | None = None) -> bytes:
    # stamp current op versions so old binaries can detect programs that
    # rely on newer op semantics (reference op_version_registry.h via
    # framework.proto:184-211)
    from .op_version import get_op_version_map
    meta = dict(meta or {})
    used = {op.type for b in program.blocks for op in b.ops}
    meta.setdefault("op_versions",
                    {k: v for k, v in get_op_version_map().items()
                     if k in used})
    payload = {"program": program_to_dict(program), "meta": meta}
    return MAGIC + pickle.dumps(payload, protocol=4)


def deserialize_program(data: bytes):
    if not data.startswith(MAGIC):
        raise ValueError("not a paddle_tpu program blob")
    payload = pickle.loads(data[len(MAGIC):])
    meta = payload.get("meta", {})
    from .op_version import check_compatibility
    check_compatibility(meta.get("op_versions"))
    return program_from_dict(payload["program"]), meta
