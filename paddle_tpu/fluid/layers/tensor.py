"""Tensor-building layers (reference python/paddle/fluid/layers/tensor.py)."""
from __future__ import annotations

import numpy as np

from ..framework import Variable, default_main_program
from ..layer_helper import LayerHelper

__all__ = [
    "data", "create_tensor", "create_parameter", "create_global_var", "cast",
    "concat", "sums", "assign", "fill_constant", "ones", "zeros",
    "ones_like", "zeros_like", "reshape", "transpose", "split", "stack",
    "squeeze", "unsqueeze", "expand", "gather", "scatter", "slice", "shape",
    "reduce_sum", "reduce_mean", "reduce_max", "reduce_min", "argmax",
    "argmin", "topk", "flatten", "mean", "mul", "elementwise_add",
    "elementwise_sub", "elementwise_mul", "elementwise_div",
    "elementwise_max", "elementwise_min", "elementwise_pow",
    "elementwise_mod", "elementwise_floordiv", "scale", "clip",
    "cross_entropy", "softmax_with_cross_entropy", "accuracy", "range",
    "increment", "equal", "less_than", "greater_than", "where", "cond",
    "while_loop", "create_array", "array_write", "array_read",
    "array_length", "tensor_array_to_tensor", "StaticRNN",
]


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=False,
         type=None, stop_gradient=True):
    """Graph input (reference layers/io.py data / paddle.static.data).
    lod_level accepted for parity; ragged data must arrive dense+mask."""
    if append_batch_size:
        shape = [-1] + list(shape)
    block = default_main_program().global_block()
    return block.create_var(name=name, shape=shape, dtype=dtype, is_data=True,
                            stop_gradient=stop_gradient)


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.main_program.current_block().create_var(
        name=name or helper.name, dtype=dtype, persistable=persistable)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    helper = LayerHelper("create_parameter", name=name)
    from ..param_attr import ParamAttr
    attr = attr or ParamAttr(name=name)
    return helper.create_parameter(attr, shape, dtype, is_bias,
                                   default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    helper = LayerHelper("global_var", name=name)
    return helper.create_global_variable(name=name, shape=shape, dtype=dtype,
                                         persistable=persistable, value=value)


def _single_out_op(helper_name, op_type, inputs, attrs=None, dtype=None,
                   out_slot="Out", name=None, extra_outs=()):
    """One primary output (dtype inferred from the first input) plus
    optional auxiliary output slots as (slot, dtype) pairs."""
    helper = LayerHelper(helper_name, name=name)
    first = next(v[0] for v in inputs.values() if v)
    out = helper.create_variable_for_type_inference(
        dtype or (first.dtype if isinstance(first, Variable) else "float32"))
    outputs = {out_slot: [out]}
    extras = []
    for slot, edtype in extra_outs:
        ev = helper.create_variable_for_type_inference(edtype, True)
        outputs[slot] = [ev]
        extras.append(ev)
    helper.append_op(type=op_type, inputs=inputs, outputs=outputs,
                     attrs=attrs or {})
    return (out, *extras) if extras else out


def cast(x, dtype):
    from .. import core
    return _single_out_op("cast", "cast", {"X": [x]},
                          {"in_dtype": x.dtype,
                           "out_dtype": core.convert_dtype(dtype)},
                          dtype=dtype)


def concat(input, axis=0, name=None):
    return _single_out_op("concat", "concat", {"X": list(input)},
                          {"axis": axis})


def sums(input, out=None):
    helper = LayerHelper("sum")
    if out is None:
        out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op(type="sum", inputs={"X": list(input)},
                     outputs={"Out": [out]})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, np.ndarray) or np.isscalar(input):
        arr = np.asarray(input)
        if output is None:
            output = helper.create_variable_for_type_inference(str(arr.dtype))
        attrs = {"shape": list(arr.shape) or [1], "dtype": str(arr.dtype)}
        if arr.dtype in (np.float32, np.float64):
            attrs["fp32_values"] = [float(v) for v in arr.flatten()]
        else:
            attrs["int64_values"] = [int(v) for v in arr.flatten()]
        helper.append_op(type="assign_value", outputs={"Out": [output]},
                         attrs=attrs)
        return output
    if output is None:
        output = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="assign", inputs={"X": [input]},
                     outputs={"Out": [output]})
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None, name=None):
    helper = LayerHelper("fill_constant", name=name)
    if out is None:
        out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="fill_constant", outputs={"Out": [out]},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "value": float(value)})
    return out


def ones(shape, dtype="float32", force_cpu=False):
    return fill_constant(shape, dtype, 1.0)


def zeros(shape, dtype="float32", force_cpu=False):
    return fill_constant(shape, dtype, 0.0)


def ones_like(x, out=None):
    return _single_out_op("ones_like", "fill_any_like", {"X": [x]},
                          {"value": 1.0})


def zeros_like(x, out=None):
    return _single_out_op("zeros_like", "fill_zeros_like", {"X": [x]})


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op(type="reshape2", inputs={"X": [x]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"shape": list(shape)})
    return helper.append_activation(out, act)


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op(type="transpose2", inputs={"X": [x]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"axis": list(perm)})
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    if isinstance(num_or_sections, int):
        n, sections = num_or_sections, []
    else:
        n, sections = len(num_or_sections), list(num_or_sections)
    outs = [helper.create_variable_for_type_inference(input.dtype)
            for _ in range(n)]
    helper.append_op(type="split", inputs={"X": [input]},
                     outputs={"Out": outs},
                     attrs={"axis": dim, "num": n if not sections else 0,
                            "sections": sections})
    return outs


def stack(x, axis=0):
    helper = LayerHelper("stack")
    out = helper.create_variable_for_type_inference(x[0].dtype)
    helper.append_op(type="stack", inputs={"X": list(x)},
                     outputs={"Y": [out]}, attrs={"axis": axis})
    return out


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze2", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op(type="squeeze2", inputs={"X": [input]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"axes": list(axes)})
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze2", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op(type="unsqueeze2", inputs={"X": [input]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"axes": list(axes)})
    return out


def expand(x, expand_times, name=None):
    return _single_out_op("expand", "expand", {"X": [x]},
                          {"expand_times": list(expand_times)})


def gather(input, index, overwrite=True):
    return _single_out_op("gather", "gather",
                          {"X": [input], "Index": [index]})


def scatter(input, index, updates, name=None, overwrite=True):
    return _single_out_op("scatter", "scatter",
                          {"X": [input], "Ids": [index], "Updates": [updates]},
                          {"overwrite": overwrite})


def slice(input, axes, starts, ends):
    return _single_out_op("slice", "slice", {"Input": [input]},
                          {"axes": list(axes), "starts": list(starts),
                           "ends": list(ends)})


def shape(input):
    return _single_out_op("shape", "shape", {"Input": [input]},
                          dtype="int32")


def _reduce(name):
    def fn(input, dim=None, keep_dim=False, name_=None):
        if dim is None:
            attrs = {"dim": [0], "keep_dim": keep_dim, "reduce_all": True}
        else:
            d = dim if isinstance(dim, (list, tuple)) else [dim]
            attrs = {"dim": list(d), "keep_dim": keep_dim,
                     "reduce_all": False}
        return _single_out_op(name, name, {"X": [input]}, attrs)
    fn.__name__ = name
    return fn


reduce_sum = _reduce("reduce_sum")
reduce_mean = _reduce("reduce_mean")
reduce_max = _reduce("reduce_max")
reduce_min = _reduce("reduce_min")
reduce_prod = _reduce("reduce_prod")


def argmax(x, axis=0):
    return _single_out_op("arg_max", "arg_max", {"X": [x]}, {"axis": axis},
                          dtype="int64")


def argmin(x, axis=0):
    return _single_out_op("arg_min", "arg_min", {"X": [x]}, {"axis": axis},
                          dtype="int64")


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="top_k_v2", inputs={"X": [input]},
                     outputs={"Out": [values], "Indices": [indices]},
                     attrs={"k": k, "axis": -1})
    return values, indices


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op(type="flatten2", inputs={"X": [x]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"axis": axis})
    return out


def mean(x, name=None):
    return _single_out_op("mean", "mean", {"X": [x]})


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    return _single_out_op("mul", "mul", {"X": [x], "Y": [y]},
                          {"x_num_col_dims": x_num_col_dims,
                           "y_num_col_dims": y_num_col_dims})


def _elementwise(name):
    def fn(x, y, axis=-1, act=None, name_=None):
        helper = LayerHelper(name)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(type=name, inputs={"X": [x], "Y": [y]},
                         outputs={"Out": [out]}, attrs={"axis": axis})
        return helper.append_activation(out, act)
    fn.__name__ = name
    return fn


elementwise_add = _elementwise("elementwise_add")
elementwise_sub = _elementwise("elementwise_sub")
elementwise_mul = _elementwise("elementwise_mul")
elementwise_div = _elementwise("elementwise_div")
elementwise_max = _elementwise("elementwise_max")
elementwise_min = _elementwise("elementwise_min")
elementwise_pow = _elementwise("elementwise_pow")
elementwise_mod = _elementwise("elementwise_mod")
elementwise_floordiv = _elementwise("elementwise_floordiv")


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="scale", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"scale": scale, "bias": bias,
                            "bias_after_scale": bias_after_scale})
    return helper.append_activation(out, act)


def clip(x, min, max, name=None):
    return _single_out_op("clip", "clip", {"X": [x]},
                          {"min": float(min), "max": float(max)})


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    return _single_out_op("cross_entropy", "cross_entropy",
                          {"X": [input], "Label": [label]},
                          {"soft_label": soft_label,
                           "ignore_index": ignore_index}, out_slot="Y")


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    helper = LayerHelper("softmax_with_cross_entropy")
    loss = helper.create_variable_for_type_inference(logits.dtype)
    softmax = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op(type="softmax_with_cross_entropy",
                     inputs={"Logits": [logits], "Label": [label]},
                     outputs={"Loss": [loss], "Softmax": [softmax]},
                     attrs={"soft_label": soft_label,
                            "ignore_index": ignore_index, "axis": axis,
                            "numeric_stable_mode": numeric_stable_mode})
    if return_softmax:
        return loss, softmax
    return loss


def accuracy(input, label, k=1, correct=None, total=None):
    helper = LayerHelper("accuracy")
    values, indices = topk(input, k)
    acc = helper.create_variable_for_type_inference("float32")
    correct = correct or helper.create_variable_for_type_inference("int32")
    total = total or helper.create_variable_for_type_inference("int32")
    helper.append_op(type="accuracy",
                     inputs={"Out": [values], "Indices": [indices],
                             "Label": [label]},
                     outputs={"Accuracy": [acc], "Correct": [correct],
                              "Total": [total]})
    return acc


def range(start, end, step, dtype="int64"):
    helper = LayerHelper("range")
    if not isinstance(start, Variable):
        start = fill_constant([1], dtype, start)
    if not isinstance(end, Variable):
        end = fill_constant([1], dtype, end)
    if not isinstance(step, Variable):
        step = fill_constant([1], dtype, step)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="range",
                     inputs={"Start": [start], "End": [end], "Step": [step]},
                     outputs={"Out": [out]})
    return out


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    out = x if in_place else helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="increment", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"step": float(value)})
    return out


def _cmp(name):
    def fn(x, y, cond=None):
        helper = LayerHelper(name)
        out = cond or helper.create_variable_for_type_inference("bool")
        helper.append_op(type=name, inputs={"X": [x], "Y": [y]},
                         outputs={"Out": [out]})
        return out
    fn.__name__ = name
    return fn


equal = _cmp("equal")
not_equal = _cmp("not_equal")
less_than = _cmp("less_than")
less_equal = _cmp("less_equal")
greater_than = _cmp("greater_than")
greater_equal = _cmp("greater_equal")


def where(condition, x, y):
    return _single_out_op("where", "where",
                          {"Condition": [condition], "X": [x], "Y": [y]})


def cond(pred, true_fn, false_fn, name=None):
    """Functional conditional (reference layers/control_flow cond): both
    branches are traced into sub-blocks of a `cond` op and selected by
    lax.cond; both must return vars of identical shapes/dtypes."""
    helper = LayerHelper("cond", name=name)
    program = helper.main_program
    parent = program.current_block()

    def build(fn):
        blk = program._create_block()
        res = fn()
        program._rollback()
        res_list = list(res) if isinstance(res, (list, tuple)) else [res]
        return blk, res_list

    tb, t_res = build(true_fn)
    fb, f_res = build(false_fn)
    # captured inputs: every name read in either sub-block but defined outside
    caps = set()
    for blk in (tb, fb):
        defined = set()
        for op in blk.ops:
            for n in op.input_arg_names:
                if n not in defined and not blk.has_var(n):
                    caps.add(n)
            defined.update(op.output_arg_names)
    caps = sorted(caps)
    outs = [helper.create_variable_for_type_inference(
        v.dtype or "float32") for v in t_res]
    # unify branch outputs under shared names via assigns inside blocks
    for blk, res in ((tb, t_res), (fb, f_res)):
        for o, r in zip(outs, res):
            blk.append_op(type="assign", inputs={"X": [r]},
                          outputs={"Out": [o.name]})
    parent.append_op(
        type="cond",
        inputs={"Cond": [pred], "Input": caps},
        outputs={"Out": [o.name for o in outs]},
        attrs={"sub_block_true": tb, "sub_block_false": fb,
               "capture_names": caps, "out_names": [o.name for o in outs]})
    return outs[0] if len(outs) == 1 else outs


def _detect_trip_bound(parent, blk, pre, lvs):
    """Static trip bound for the canonical counting loop:
    cond = less_than(i, fill_constant C), i initialised by fill_constant
    v0, body increments i by a positive constant step. Any bound >= the
    true trip count is safe (the scan lowering masks the tail)."""
    def producer(block, name):
        for op in reversed(block.ops):
            if name in op.output_arg_names:
                return op
        return None

    lt = producer(parent, pre.name)
    if lt is None or lt.type != "less_than":
        return 0
    xn = lt.input("X")[0]
    yp = producer(parent, lt.input("Y")[0])
    xp = producer(parent, xn)
    if yp is None or yp.type != "fill_constant" or \
            xp is None or xp.type != "fill_constant":
        return 0
    incs = [op for op in blk.ops
            if op.type == "increment" and xn in op.output_arg_names]
    if len(incs) != 1:
        return 0
    # the LAST writer of the counter in the body must be that increment
    # (or a self-assign of it): a body that returns a different value for
    # the carry would make the increment's step a lie and the scan bound
    # silently truncate the loop
    last = producer(blk, xn)
    if last is not incs[0] and not (
            last is not None and last.type == "assign"
            and last.input("X")[0] == xn):
        return 0
    step = float(incs[0].attrs.get("step", 1.0))
    if step <= 0:
        return 0
    try:
        hi = float(yp.attrs.get("value"))
        lo = float(xp.attrs.get("value"))
    except (TypeError, ValueError):
        return 0
    return max(int(-(-(hi - lo) // step)), 0)


# ops whose kernels reach outside the device program via io_callback —
# running them on a masked scan tick still fires the external effect
_SIDE_EFFECT_OPS = {"send", "recv", "geo_send", "send_barrier",
                    "fetch_barrier", "py_func", "listen_and_serv"}


def _has_side_effect_op(blk, _seen=None):
    """True if the block or any nested sub-block (cond branches, inner
    whiles) contains an io_callback-backed op."""
    _seen = _seen if _seen is not None else set()
    if id(blk) in _seen:
        return False
    _seen.add(id(blk))
    for op in blk.ops:
        if op.type in _SIDE_EFFECT_OPS:
            return True
        for key in ("sub_block", "sub_block_true", "sub_block_false"):
            sub = op.attr(key)
            if sub is not None and hasattr(sub, "ops") \
                    and _has_side_effect_op(sub, _seen):
                return True
    return False


def while_loop(cond, body, loop_vars, is_test=False, name=None,
               max_trip_count=None):
    """Functional while (reference layers/control_flow.py while_loop /
    While): `body` is traced once into a sub-block of a `while` op that
    lax.while_loop steps until `cond` is false. Loop vars must keep shape
    and dtype across iterations (the XLA carry contract); variables read
    inside but defined outside are loop-invariant captures.

    Reverse-mode gradients require a static trip bound (XLA's while has
    no vjp): the canonical `less_than(i, constant)` counting loop is
    detected automatically and lowered to a masked lax.scan; any other
    loop shape is differentiable only when `max_trip_count` is given."""
    helper = LayerHelper("while_loop", name=name)
    program = helper.main_program
    parent = program.current_block()
    single = not isinstance(loop_vars, (list, tuple))
    lvs = [loop_vars] if single else list(loop_vars)

    pre = cond(*lvs)
    blk = program._create_block()
    res = body(*lvs)
    res_list = [res] if not isinstance(res, (list, tuple)) else list(res)
    if len(res_list) != len(lvs):
        program._rollback()
        raise ValueError(
            f"body returned {len(res_list)} vars, expected {len(lvs)}")
    # write results back onto the carry names, then refresh the condition
    for lv, nv in zip(lvs, res_list):
        blk.append_op(type="assign", inputs={"X": [nv]},
                      outputs={"Out": [lv.name]})
    new_cond = cond(*lvs)
    blk.append_op(type="assign", inputs={"X": [new_cond]},
                  outputs={"Out": [pre.name]})
    program._rollback()

    carry = {lv.name for lv in lvs} | {pre.name}
    caps, defined = [], set()
    for op in blk.ops:
        for n in op.input_arg_names:
            if n not in defined and n not in carry and not blk.has_var(n) \
                    and n not in caps:
                caps.append(n)
        defined.update(op.output_arg_names)
    outs = [helper.create_variable_for_type_inference(
        lv.dtype or "float32") for lv in lvs]
    for o, lv in zip(outs, lvs):
        o.shape = lv.shape
    cond_out = helper.create_variable_for_type_inference("bool", True)
    mt = max_trip_count
    if mt is None:
        mt = _detect_trip_bound(parent, blk, pre, lvs)
        # the masked-scan lowering RUNS the body for every tick and
        # discards masked results — io_callback-backed ops (PS transport,
        # host callbacks) would duplicate external effects on masked
        # ticks. Only lower to masked scan when the caller opted in with
        # an explicit max_trip_count; auto-detected bounds fall back to
        # lax.while_loop (forward-only) for side-effecting bodies.
        if mt and _has_side_effect_op(blk):
            mt = None
    parent.append_op(
        type="while",
        inputs={"Condition": [pre], "X": [lv.name for lv in lvs],
                "Captures": caps},
        outputs={"Out": [o.name for o in outs], "CondOut": [cond_out]},
        attrs={"sub_block": blk, "cond_name": pre.name,
               "carry_names": [lv.name for lv in lvs],
               "capture_names": caps,
               "max_trip_count": int(mt or 0)})
    return outs[0] if single else outs


# ---------------------------------------------------------------------------
# LoDTensorArray layers (reference layers/control_flow.py array_write /
# array_read / array_length / create_array + tensor.py
# tensor_array_to_tensor)
# ---------------------------------------------------------------------------

def create_array(dtype="float32", max_size=0, name=None):
    """New tensor array. `max_size` pre-sizes the buffer — REQUIRED when
    writes happen inside while_loop (XLA carries cannot grow); writes at
    build-time-constant indices grow automatically. An array carried
    through while_loop must also receive one write BEFORE the loop (the
    carry needs a materialized buffer — XLA's fixed-structure contract)."""
    helper = LayerHelper("create_array", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="create_array", inputs={},
                     outputs={"Out": [out]},
                     attrs={"dtype": dtype, "max_size": max_size})
    return out


def _build_time_index(i):
    """Resolve a build-time-constant index (a fill_constant output) so
    the buffer can grow at trace time; None when genuinely dynamic.
    Only the CURRENT block is searched: a var filled in a parent block
    may be a loop carry whose runtime value diverges from its one
    build-time producer (e.g. the while counter)."""
    blk = default_main_program().current_block()
    writes = [op for op in blk.ops if i.name in op.output_arg_names]
    if len(writes) == 1 and writes[0].type == "fill_constant":
        try:
            return int(writes[0].attrs.get("value", 0))
        except (TypeError, ValueError):
            return None
    return None


def array_write(x, i, array=None, max_size=0):
    helper = LayerHelper("array_write")
    if array is None:
        array = create_array(x.dtype or "float32", max_size=max_size)
    attrs = {"max_size": max_size}
    si = _build_time_index(i)
    if si is not None:
        attrs["static_index"] = si
    helper.append_op(type="write_to_array",
                     inputs={"X": [x], "I": [i], "Array": [array]},
                     outputs={"Out": [array]},
                     attrs=attrs)
    return array


def array_read(array, i):
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(
        array.dtype or "float32")
    helper.append_op(type="read_from_array",
                     inputs={"X": [array], "I": [i]},
                     outputs={"Out": [out]})
    return out


def array_length(array):
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="lod_array_length", inputs={"X": [array]},
                     outputs={"Out": [out]})
    out.shape = (1,)
    return out


def tensor_array_to_tensor(input, axis=0, use_stack=True, name=None):
    helper = LayerHelper("tensor_array_to_tensor", name=name)
    out = helper.create_variable_for_type_inference(
        input.dtype or "float32")
    idx = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="array_to_tensor", inputs={"X": [input]},
                     outputs={"Out": [out], "OutIndex": [idx]},
                     attrs={"axis": axis, "use_stack": use_stack})
    return out, idx


# ---------------------------------------------------------------------------
# StaticRNN (reference layers/control_flow.py StaticRNN over
# operators/controlflow/recurrent_op.cc): user writes one timestep in a
# `with rnn.step()` block; it lowers to ONE `recurrent` op executed as a
# lax.scan — compile time O(1) in sequence length, autodiff through the
# scan is the backward recurrent pass.
# ---------------------------------------------------------------------------

class StaticRNN:
    def __init__(self, name=None):
        self._helper = LayerHelper("static_rnn", name=name)
        self._block = None
        self._seq_inputs = []      # (outer var, step var)
        self._memories = []        # (pre var, init var)
        self._updates = {}         # pre name -> update var
        self._outputs = []
        self._done = False

    class _Step:
        def __init__(self, rnn):
            self._rnn = rnn

        def __enter__(self):
            prog = self._rnn._helper.main_program
            self._rnn._parent = prog.current_block()
            self._rnn._block = prog._create_block()
            return self._rnn

        def __exit__(self, *exc):
            prog = self._rnn._helper.main_program
            prog._rollback()
            if exc[0] is None:
                self._rnn._complete()
            return False

    def step(self):
        return StaticRNN._Step(self)

    def step_input(self, x):
        """x [T, ...] -> the current timestep's slice [...]."""
        blk = self._block
        sv = blk.create_var(
            name=f"{x.name}@step", dtype=x.dtype,
            shape=tuple(x.shape[1:]) if x.shape else None)
        self._seq_inputs.append((x, sv))
        return sv

    def memory(self, init=None, shape=None, batch_ref=None,
               init_value=0.0, init_batch_dim_idx=0,
               ref_batch_dim_idx=1):
        if init is None:
            if shape is None:
                raise ValueError("memory() needs init= or shape=")
            # the Init input must exist in the PARENT block (memory() is
            # called inside the step sub-block, but the recurrent op
            # consumes inits from outside the scan)
            from .. import unique_name
            parent = self._parent
            init = parent.create_var(
                name=unique_name.generate("static_rnn_mem_init"),
                shape=tuple(shape), dtype="float32")
            parent.append_op(
                type="fill_constant", inputs={},
                outputs={"Out": [init.name]},
                attrs={"shape": list(shape), "dtype": "float32",
                       "value": float(init_value)})
        blk = self._block
        # unique per memory: two memories may share one init var (LSTM
        # h0/c0 from a single zeros tensor)
        pre = blk.create_var(
            name=f"{init.name}@pre_mem_{len(self._memories)}",
            dtype=init.dtype, shape=init.shape)
        self._memories.append((pre, init))
        return pre

    def update_memory(self, mem, var):
        self._updates[mem.name] = var

    def step_output(self, o):
        self._outputs.append(o)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _complete(self):
        if self._done:
            return
        self._done = True
        blk = self._block
        helper = self._helper
        parent = helper.main_program.current_block()
        pre_names = [p.name for p, _ in self._memories]
        upd_names = []
        for p, _ in self._memories:
            if p.name not in self._updates:
                raise ValueError(f"memory {p.name} never update_memory()d")
            upd_names.append(self._updates[p.name].name)
        seq_names = [sv.name for _, sv in self._seq_inputs]
        known = set(seq_names) | set(pre_names)
        caps, defined = [], set()
        for op in blk.ops:
            for n in op.input_arg_names:
                if n not in defined and n not in known \
                        and not blk.has_var(n) and n not in caps:
                    caps.append(n)
            defined.update(op.output_arg_names)
        self._caps = caps
        self._outs = []
        T = self._seq_inputs[0][0].shape[0] if self._seq_inputs and \
            self._seq_inputs[0][0].shape else -1
        for o in self._outputs:
            ov = helper.create_variable_for_type_inference(
                o.dtype or "float32")
            if o.shape is not None:
                ov.shape = (T,) + tuple(o.shape)
            self._outs.append(ov)
        finals = [helper.create_variable_for_type_inference(
            i.dtype or "float32") for _, i in self._memories]
        parent.append_op(
            type="recurrent",
            inputs={"X": [x.name for x, _ in self._seq_inputs],
                    "Init": [i.name for _, i in self._memories],
                    "Captures": caps},
            outputs={"Out": [o.name for o in self._outs],
                     "FinalStates": [f.name for f in finals]},
            attrs={"sub_block": blk,
                   "seq_input_names": seq_names,
                   "pre_mem_names": pre_names,
                   "mem_update_names": upd_names,
                   "step_output_names": [o.name for o in self._outputs],
                   "capture_names": caps})

    def __call__(self):
        if not self._done:
            raise RuntimeError("StaticRNN used before its step() block "
                               "completed")
        return self._outs[0] if len(self._outs) == 1 else self._outs
