"""fluid.layers wrappers for the round-5 parity op tier (the public
names the reference exposes in python/paddle/fluid/layers/{nn,loss,
sequence_lod,detection}.py for these kernels)."""
from __future__ import annotations

from ..layer_helper import LayerHelper

__all__ = [
    "multiplex", "crop", "crop_tensor", "hinge_loss", "log_loss",
    "cos_sim", "bpr_loss", "continuous_value_model", "reverse",
    "expand_as", "pad_constant_like", "unpool", "cholesky",
    "sequence_concat", "sequence_reshape", "dynamic_gru", "dynamic_lstm",
    "fsp_matrix", "shuffle_batch", "partial_sum", "partial_concat",
    "sigmoid_focal_loss", "yolov3_loss", "prroi_pool", "rank_attention",
    "tree_conv", "sample_logits", "batch_fc",
]


def _single(op_type, inputs, attrs=None, out_slot="Out", dtype=None,
            name=None, extra_outs=()):
    from .tensor import _single_out_op
    return _single_out_op(op_type, op_type, inputs, attrs, dtype,
                          out_slot, name=name, extra_outs=extra_outs)


def multiplex(inputs, index, name=None):
    return _single("multiplex", {"X": list(inputs), "Ids": [index]},
                   name=name)


def _crop_common(op_type, shape_slot, x, shape, offsets, name):
    ins = {"X": [x]}
    attrs = {}
    if isinstance(shape, (list, tuple)):
        attrs["shape"] = list(shape)
    elif shape is not None:
        ins[shape_slot] = [shape]
    if isinstance(offsets, (list, tuple)):
        attrs["offsets"] = list(offsets)
    elif offsets is not None:
        ins["Offsets"] = [offsets]
    return _single(op_type, ins, attrs, name=name)


def crop(x, shape=None, offsets=None, name=None):
    return _crop_common("crop", "Y", x, shape, offsets, name)


def crop_tensor(x, shape=None, offsets=None, name=None):
    return _crop_common("crop_tensor", "Shape", x, shape, offsets, name)


def hinge_loss(input, label, name=None):
    return _single("hinge_loss", {"Logits": [input], "Labels": [label]},
                   out_slot="Loss", name=name)


def log_loss(input, label, epsilon=1e-4, name=None):
    return _single("log_loss", {"Predicted": [input], "Labels": [label]},
                   {"epsilon": epsilon}, out_slot="Loss", name=name)


def cos_sim(X, Y, name=None):
    out, _, _ = _single("cos_sim", {"X": [X], "Y": [Y]}, name=name,
                        extra_outs=(("XNorm", "float32"),
                                    ("YNorm", "float32")))
    return out


def bpr_loss(input, label, name=None):
    return _single("bpr_loss", {"X": [input], "Label": [label]},
                   out_slot="Y", name=name)


def continuous_value_model(input, cvm, use_cvm=True, name=None):
    return _single("cvm", {"X": [input], "CVM": [cvm]},
                   {"use_cvm": use_cvm}, out_slot="Y", name=name)


def reverse(x, axis, name=None):
    return _single("reverse", {"X": [x]},
                   {"axis": [axis] if isinstance(axis, int) else
                    list(axis)}, name=name)


def expand_as(x, target_tensor, name=None):
    return _single("expand_as", {"X": [x],
                                 "target_tensor": [target_tensor]},
                   name=name)


def pad_constant_like(x, y, pad_value=0.0, name=None):
    return _single("pad_constant_like", {"X": [x], "Y": [y]},
                   {"pad_value": pad_value}, name=name)


def unpool(x, indices, kernel_size=2, stride=2, padding=0,
           output_size=None, name=None):
    to2 = lambda v: [v, v] if isinstance(v, int) else list(v)
    return _single("unpool", {"X": [x], "Indices": [indices]},
                   {"ksize": to2(kernel_size), "strides": to2(stride),
                    "paddings": to2(padding),
                    "output_size": list(output_size or [])}, name=name)


def cholesky(x, upper=False, name=None):
    return _single("cholesky", {"X": [x]}, {"upper": upper}, name=name)


def sequence_concat(input, seq_lens=None, name=None):
    """Dense+lengths form: with seq_lens given, returns (out, new_lens)
    — the packed tensor plus the combined valid lengths (the kernel's
    SeqLenOut; the reference's LoD carries this implicitly)."""
    ins = {"X": list(input)}
    if seq_lens:
        ins["SeqLen"] = list(seq_lens)
        return _single("sequence_concat", ins, name=name,
                       extra_outs=(("SeqLenOut", "int64"),))
    return _single("sequence_concat", ins, name=name)


def sequence_reshape(input, new_dim, seq_len=None, name=None):
    """Dense+lengths form: with seq_len given, returns (out, new_lens)."""
    ins = {"X": [input]}
    if seq_len is not None:
        ins["SeqLen"] = [seq_len]
        return _single("sequence_reshape", ins, {"new_dim": new_dim},
                       name=name, extra_outs=(("SeqLenOut", "int64"),))
    return _single("sequence_reshape", ins, {"new_dim": new_dim},
                   name=name)


def dynamic_gru(input, weight, bias=None, h_0=None, origin_mode=False,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", name=None):
    """Monolithic GRU over dense [B, T, 3D] gate inputs (the layer-level
    form of the `gru` op; reference layers/rnn dynamic_gru wraps the
    same kernel over LoD input)."""
    ins = {"Input": [input], "Weight": [weight]}
    if bias is not None:
        ins["Bias"] = [bias]
    if h_0 is not None:
        ins["H0"] = [h_0]
    helper = LayerHelper("dynamic_gru", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="gru", inputs=ins,
                     outputs={"Hidden": [out]},
                     attrs={"origin_mode": origin_mode,
                            "is_reverse": is_reverse,
                            "gate_activation": gate_activation,
                            "activation": candidate_activation})
    return out


def dynamic_lstm(input, weight, bias=None, h_0=None, c_0=None,
                 use_peepholes=False, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", name=None):
    """Monolithic LSTM over dense [B, T, 4D] gate inputs -> (hidden,
    cell)."""
    ins = {"Input": [input], "Weight": [weight]}
    if bias is not None:
        ins["Bias"] = [bias]
    if h_0 is not None:
        ins["H0"] = [h_0]
    if c_0 is not None:
        ins["C0"] = [c_0]
    helper = LayerHelper("dynamic_lstm", name=name)
    hid = helper.create_variable_for_type_inference(input.dtype)
    cell = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="lstm", inputs=ins,
                     outputs={"Hidden": [hid], "Cell": [cell]},
                     attrs={"use_peepholes": use_peepholes,
                            "is_reverse": is_reverse,
                            "gate_activation": gate_activation,
                            "cell_activation": cell_activation,
                            "candidate_activation": candidate_activation})
    return hid, cell


def fsp_matrix(x, y, name=None):
    return _single("fsp", {"X": [x], "Y": [y]}, name=name)


def shuffle_batch(x, seed=None, name=None):
    """Row shuffle with a fresh permutation per run: like the reference
    layer, a persistable seed variable is threaded through Seed ->
    SeedOut, so each executor step advances it (same var on both
    slots)."""
    helper = LayerHelper("shuffle_batch", name=name)
    if seed is None or isinstance(seed, int):
        seed_var = helper.create_global_variable(
            shape=[1], dtype="int64", persistable=True,
            value=float(seed or 0))
    else:
        seed_var = seed
    out = helper.create_variable_for_type_inference(x.dtype)
    idx = helper.create_variable_for_type_inference("int64", True)
    helper.append_op(type="shuffle_batch",
                     inputs={"X": [x], "Seed": [seed_var]},
                     outputs={"Out": [out], "ShuffleIdx": [idx],
                              "SeedOut": [seed_var]},
                     attrs={"startup_seed": 0})
    return out


def partial_sum(input, start_index=0, length=-1, name=None):
    return _single("partial_sum", {"X": list(input)},
                   {"start_index": start_index, "length": length},
                   name=name)


def partial_concat(input, start_index=0, length=-1, name=None):
    return _single("partial_concat", {"X": list(input)},
                   {"start_index": start_index, "length": length},
                   name=name)


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25,
                       name=None):
    return _single("sigmoid_focal_loss",
                   {"X": [x], "Label": [label], "FgNum": [fg_num]},
                   {"gamma": gamma, "alpha": alpha}, name=name)


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, scale_x_y=1.0, name=None):
    ins = {"X": [x], "GTBox": [gt_box], "GTLabel": [gt_label]}
    if gt_score is not None:
        ins["GTScore"] = [gt_score]
    out, _, _ = _single(
        "yolov3_loss", ins,
        {"anchors": list(anchors), "anchor_mask": list(anchor_mask),
         "class_num": class_num, "ignore_thresh": ignore_thresh,
         "downsample_ratio": downsample_ratio,
         "use_label_smooth": use_label_smooth, "scale_x_y": scale_x_y},
        out_slot="Loss", name=name,
        extra_outs=(("ObjectnessMask", "float32"),
                    ("GTMatchMask", "int32")))
    return out


def prroi_pool(input, rois, spatial_scale=1.0, pooled_height=1,
               pooled_width=1, batch_roi_nums=None, name=None):
    ins = {"X": [input], "ROIs": [rois]}
    if batch_roi_nums is not None:
        ins["BatchRoINums"] = [batch_roi_nums]
    return _single("prroi_pool", ins,
                   {"spatial_scale": spatial_scale,
                    "pooled_height": pooled_height,
                    "pooled_width": pooled_width}, name=name)


def rank_attention(input, rank_offset, rank_param, max_rank=3,
                   max_size=0, name=None):
    out, _, _ = _single(
        "rank_attention",
        {"X": [input], "RankOffset": [rank_offset],
         "RankParam": [rank_param]},
        {"MaxRank": max_rank, "MaxSize": max_size}, name=name,
        extra_outs=(("InputHelp", "float32"), ("InsRank", "float32")))
    return out


def tree_conv(nodes_vector, edge_set, filter, max_depth=2, name=None):
    return _single("tree_conv",
                   {"NodesVector": [nodes_vector], "EdgeSet": [edge_set],
                    "Filter": [filter]},
                   {"max_depth": max_depth}, name=name)


def sample_logits(logits, label, num_samples, seed=0,
                  remove_accidental_hits=True, name=None):
    helper = LayerHelper("sample_logits", name=name)
    outs = {s: [helper.create_variable_for_type_inference(d, True)]
            for s, d in (("Samples", "int64"), ("Probabilities",
                         "float32"), ("LogitsDim", "int64"),
                         ("LabelsDim", "int64"),
                         ("SampledLabels", "int64"))}
    sl = helper.create_variable_for_type_inference(logits.dtype)
    outs["SampledLogits"] = [sl]
    helper.append_op(type="sample_logits",
                     inputs={"Logits": [logits], "Labels": [label]},
                     outputs=outs,
                     attrs={"num_samples": num_samples, "seed": seed,
                            "remove_accidental_hits":
                                remove_accidental_hits,
                            "use_customized_samples": False,
                            "uniq": True})
    return outs["Samples"][0], outs["Probabilities"][0], sl


def batch_fc(input, param, bias=None, name=None):
    ins = {"Input": [input], "W": [param]}
    if bias is not None:
        ins["Bias"] = [bias]
    return _single("batch_fc", ins, name=name)
