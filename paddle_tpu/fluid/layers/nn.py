"""Static-graph layer functions (reference python/paddle/fluid/layers/nn.py).

Each function assembles ops via LayerHelper — same architecture as the
reference; the ops themselves lower to jax in the executor.
"""
from __future__ import annotations

from ..framework import Variable
from ..layer_helper import LayerHelper
from ..initializer import ConstantInitializer, XavierInitializer, NormalInitializer

__all__ = [
    "fc", "conv2d", "pool2d", "batch_norm", "layer_norm", "group_norm",
    "instance_norm", "embedding", "dropout", "relu", "softmax", "one_hot",
    "matmul", "label_smooth", "clip_by_norm", "l2_normalize", "pad", "pad2d",
    "sequence_mask", "sequence_pad", "sequence_unpad", "sequence_pool",
    "sequence_softmax", "sequence_reverse", "sequence_expand",
    "segment_pool", "dynamic_rnn", "warpctc", "linear_chain_crf",
    "crf_decoding", "nce", "hsigmoid", "conv3d", "pool3d",
]


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None):
    """Fully-connected (reference layers/nn.py fc): mul + elementwise_add."""
    helper = LayerHelper("fc", input=input, size=size, act=act, name=name)
    dtype = input.dtype or "float32"
    in_shape = input.shape
    import numpy as np
    fan_in = int(np.prod(in_shape[num_flatten_dims:]))
    w = helper.create_parameter(param_attr, shape=[fan_in, size], dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="mul", inputs={"X": [input], "Y": [w]},
                     outputs={"Out": [out]},
                     attrs={"x_num_col_dims": num_flatten_dims,
                            "y_num_col_dims": 1})
    b = helper.create_parameter(bias_attr, shape=[size], dtype=dtype,
                                is_bias=True)
    if b is not None:
        tmp = helper.create_variable_for_type_inference(dtype)
        helper.append_op(type="elementwise_add",
                         inputs={"X": [out], "Y": [b]},
                         outputs={"Out": [tmp]},
                         attrs={"axis": num_flatten_dims})
        out = tmp
    return helper.append_activation(out)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCHW"):
    helper = LayerHelper("conv2d", act=act, name=name)
    dtype = input.dtype or "float32"
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    if isinstance(stride, int):
        stride = [stride, stride]
    if isinstance(padding, int):
        padding = [padding, padding]
    if isinstance(dilation, int):
        dilation = [dilation, dilation]
    num_channels = input.shape[1]
    import math
    std = math.sqrt(2.0 / (filter_size[0] * filter_size[1] * num_channels))
    w = helper.create_parameter(
        param_attr,
        shape=[num_filters, num_channels // groups] + list(filter_size),
        dtype=dtype, default_initializer=NormalInitializer(0.0, std))
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv2d",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": stride, "paddings": padding, "dilations": dilation,
               "groups": groups, "data_format": data_format})
    b = helper.create_parameter(bias_attr, shape=[num_filters], dtype=dtype,
                                is_bias=True)
    if b is not None:
        tmp = helper.create_variable_for_type_inference(dtype)
        helper.append_op(type="elementwise_add",
                         inputs={"X": [out], "Y": [b]},
                         outputs={"Out": [tmp]}, attrs={"axis": 1})
        out = tmp
    return helper.append_activation(out)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True, data_format="NCHW"):
    helper = LayerHelper("pool2d", name=name)
    if isinstance(pool_size, int):
        pool_size = [pool_size, pool_size]
    if isinstance(pool_stride, int):
        pool_stride = [pool_stride, pool_stride]
    if isinstance(pool_padding, int):
        pool_padding = [pool_padding, pool_padding]
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="pool2d", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"pooling_type": pool_type, "ksize": pool_size,
               "strides": pool_stride, "paddings": pool_padding,
               "global_pooling": global_pooling, "ceil_mode": ceil_mode,
               "exclusive": exclusive, "data_format": data_format})
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               name=None, moving_mean_name=None, moving_variance_name=None,
               do_model_average_for_mean_and_var=True,
               use_global_stats=False):
    helper = LayerHelper("batch_norm", act=act, name=name)
    dtype = input.dtype or "float32"
    caxis = 1 if data_layout == "NCHW" else len(input.shape) - 1
    c = input.shape[caxis]
    scale = helper.create_parameter(
        param_attr, shape=[c], dtype=dtype,
        default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(bias_attr, shape=[c], dtype=dtype,
                                   is_bias=True)
    mean = helper.create_global_variable(
        name=moving_mean_name, shape=[c], dtype="float32", persistable=True,
        value=0.0)
    variance = helper.create_global_variable(
        name=moving_variance_name, shape=[c], dtype="float32",
        persistable=True, value=1.0)
    y = helper.create_variable_for_type_inference(dtype)
    saved_mean = helper.create_variable_for_type_inference("float32", True)
    saved_var = helper.create_variable_for_type_inference("float32", True)
    helper.append_op(
        type="batch_norm",
        inputs={"X": [input], "Scale": [scale], "Bias": [bias],
                "Mean": [mean], "Variance": [variance]},
        outputs={"Y": [y], "MeanOut": [mean], "VarianceOut": [variance],
                 "SavedMean": [saved_mean], "SavedVariance": [saved_var]},
        attrs={"momentum": momentum, "epsilon": epsilon, "is_test": is_test,
               "data_layout": data_layout,
               "use_global_stats": use_global_stats})
    return helper.append_activation(y)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    helper = LayerHelper("layer_norm", act=act, name=name)
    dtype = input.dtype or "float32"
    import numpy as np
    feat = int(np.prod(input.shape[begin_norm_axis:]))
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(
            param_attr, shape=[feat], dtype=dtype,
            default_initializer=ConstantInitializer(1.0))
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(bias_attr, shape=[feat], dtype=dtype,
                                    is_bias=True)
        inputs["Bias"] = [b]
    y = helper.create_variable_for_type_inference(dtype)
    mean = helper.create_variable_for_type_inference("float32", True)
    var = helper.create_variable_for_type_inference("float32", True)
    helper.append_op(type="layer_norm", inputs=inputs,
                     outputs={"Y": [y], "Mean": [mean], "Variance": [var]},
                     attrs={"epsilon": epsilon,
                            "begin_norm_axis": begin_norm_axis})
    return helper.append_activation(y)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    helper = LayerHelper("group_norm", act=act, name=name)
    dtype = input.dtype or "float32"
    c = input.shape[1]
    inputs = {"X": [input]}
    s = helper.create_parameter(param_attr, shape=[c], dtype=dtype,
                                default_initializer=ConstantInitializer(1.0))
    b = helper.create_parameter(bias_attr, shape=[c], dtype=dtype,
                                is_bias=True)
    if s is not None:
        inputs["Scale"] = [s]
    if b is not None:
        inputs["Bias"] = [b]
    y = helper.create_variable_for_type_inference(dtype)
    mean = helper.create_variable_for_type_inference("float32", True)
    var = helper.create_variable_for_type_inference("float32", True)
    helper.append_op(type="group_norm", inputs=inputs,
                     outputs={"Y": [y], "Mean": [mean], "Variance": [var]},
                     attrs={"epsilon": epsilon, "groups": groups,
                            "data_layout": data_layout})
    return helper.append_activation(y)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    helper = LayerHelper("instance_norm", name=name)
    dtype = input.dtype or "float32"
    c = input.shape[1]
    s = helper.create_parameter(param_attr, shape=[c], dtype=dtype,
                                default_initializer=ConstantInitializer(1.0))
    b = helper.create_parameter(bias_attr, shape=[c], dtype=dtype,
                                is_bias=True)
    inputs = {"X": [input]}
    if s is not None:
        inputs["Scale"] = [s]
    if b is not None:
        inputs["Bias"] = [b]
    y = helper.create_variable_for_type_inference(dtype)
    sm = helper.create_variable_for_type_inference("float32", True)
    sv = helper.create_variable_for_type_inference("float32", True)
    helper.append_op(type="instance_norm", inputs=inputs,
                     outputs={"Y": [y], "SavedMean": [sm],
                              "SavedVariance": [sv]},
                     attrs={"epsilon": epsilon})
    return y


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    helper = LayerHelper("embedding")
    w = helper.create_parameter(param_attr, shape=list(size), dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="lookup_table_v2", inputs={"Ids": [input], "W": [w]},
        outputs={"Out": [out]},
        attrs={"padding_idx": -1 if padding_idx is None else padding_idx,
               "is_sparse": is_sparse, "is_distributed": is_distributed})
    return out


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    mask = helper.create_variable_for_type_inference("uint8", True)
    helper.append_op(type="dropout", inputs={"X": [x]},
                     outputs={"Out": [out], "Mask": [mask]},
                     attrs={"dropout_prob": dropout_prob, "is_test": is_test,
                            "fix_seed": seed is not None, "seed": seed or 0,
                            "dropout_implementation": dropout_implementation})
    return out


def _unary(op_type):
    def fn(x, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(type=op_type, inputs={"X": [x]},
                         outputs={"Out": [out]})
        return out
    fn.__name__ = op_type
    return fn


relu = _unary("relu")
sigmoid = _unary("sigmoid")
tanh = _unary("tanh")
exp = _unary("exp")
sqrt = _unary("sqrt")
log = _unary("log")


def softmax(input, axis=-1, name=None, use_cudnn=False):
    helper = LayerHelper("softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="softmax", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def one_hot(input, depth, allow_out_of_range=False):
    helper = LayerHelper("one_hot")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(type="one_hot_v2", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"depth": depth,
                            "allow_out_of_range": allow_out_of_range})
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="matmul", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"transpose_X": transpose_x,
                            "transpose_Y": transpose_y, "alpha": alpha})
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    helper = LayerHelper("label_smooth", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [label]}
    if prior_dist is not None:
        inputs["PriorDist"] = [prior_dist]
    helper.append_op(type="label_smooth", inputs=inputs,
                     outputs={"Out": [out]}, attrs={"epsilon": epsilon})
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="clip_by_norm", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"max_norm": max_norm})
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    from . import tensor as t
    helper = LayerHelper("l2_normalize", name=name)
    sq = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="square", inputs={"X": [x]}, outputs={"Out": [sq]})
    ssum = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="reduce_sum", inputs={"X": [sq]},
                     outputs={"Out": [ssum]},
                     attrs={"dim": [axis], "keep_dim": True,
                            "reduce_all": False})
    rs = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="rsqrt", inputs={"X": [ssum]},
                     outputs={"Out": [rs]})
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="elementwise_mul", inputs={"X": [x], "Y": [rs]},
                     outputs={"Out": [out]}, attrs={"axis": -1})
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="pad", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"paddings": paddings, "pad_value": pad_value})
    return out


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    helper = LayerHelper("pad2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="pad2d", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"paddings": list(paddings), "mode": mode,
                            "pad_value": pad_value,
                            "data_format": data_format})
    return out


# ---------------------------------------------------------------------------
# sequence layers (LoD-free mask/segment design — SURVEY §7; reference
# fluid/layers/sequence_lod.py over operators/sequence_ops/*)
# ---------------------------------------------------------------------------

def _seq_op(type, inputs, attrs, dtype, n_out=1):
    helper = LayerHelper(type)
    outs = [helper.create_variable_for_type_inference(dtype)
            for _ in range(n_out)]
    outputs = {"Out": [outs[0]]}
    if n_out > 1:
        outputs["Length"] = [outs[1]]
    helper.append_op(type=type, inputs=inputs, outputs=outputs, attrs=attrs)
    return outs[0] if n_out == 1 else tuple(outs)


def sequence_mask(x, maxlen=-1, dtype="int64", name=None):
    """lengths [..] -> mask [.., maxlen]. `maxlen` must be static under
    jit (reference sequence_mask_op takes it dynamically from LoD)."""
    helper = LayerHelper("sequence_mask", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="sequence_mask", inputs={"X": [x]},
                     outputs={"Y": [out]},
                     attrs={"maxlen": maxlen if maxlen else -1,
                            "out_dtype": dtype})
    return out


def sequence_pad(x, pad_value=0.0, length=None, maxlen=-1, name=None):
    if length is None:
        raise ValueError(
            "sequence_pad needs `length` (per-sequence lengths) — the "
            "flat-rows input carries no LoD in this framework")
    return _seq_op("sequence_pad",
                   {"X": [x], "Length": [length]},
                   {"padded_length": maxlen, "pad_value": pad_value},
                   x.dtype, n_out=2)


def sequence_unpad(x, length, name=None):
    return _seq_op("sequence_unpad", {"X": [x], "Length": [length]}, {},
                   x.dtype)


def sequence_pool(input, pool_type="average", length=None, pad_value=0.0,
                  name=None):
    ins = {"X": [input]}
    if length is not None:
        ins["Length"] = [length]
    return _seq_op("sequence_pool", ins,
                   {"pooltype": pool_type.upper(), "pad_value": pad_value},
                   input.dtype)


def sequence_softmax(input, length=None, name=None):
    ins = {"X": [input]}
    if length is not None:
        ins["Length"] = [length]
    return _seq_op("sequence_softmax", ins, {}, input.dtype)


def sequence_reverse(x, length=None, name=None):
    ins = {"X": [x]}
    if length is not None:
        ins["Length"] = [length]
    return _seq_op("sequence_reverse", ins, {}, x.dtype)


def sequence_expand(x, ref_length, name=None):
    return _seq_op("sequence_expand",
                   {"X": [x], "RefLength": [ref_length]}, {}, x.dtype)


def segment_pool(data, segment_ids, pool_type="sum", num_segments=-1,
                 name=None):
    return _seq_op("segment_pool",
                   {"X": [data], "SegmentIds": [segment_ids]},
                   {"pooltype": pool_type.upper(),
                    "num_segments": num_segments}, data.dtype)


def dynamic_rnn(input, hidden_size, mode="LSTM", num_layers=1,
                is_bidirec=False, sequence_length=None, param_attr=None,
                name=None):
    """Static-graph fused RNN over dense [B, T, D] (replaces the
    reference's dynamic_rnn/StaticRNN LoD machinery with the single `rnn`
    op). Returns (out, final_hidden)."""
    helper = LayerHelper("dynamic_rnn", name=name)
    dtype = input.dtype or "float32"
    D = input.shape[-1]
    ndir = 2 if is_bidirec else 1
    import math as _math
    std = 1.0 / _math.sqrt(hidden_size)
    from ..initializer import UniformInitializer
    from ..ops.sequence_ops import rnn_weight_shapes
    weights = [helper.create_parameter(
        param_attr, shape=list(shape), dtype=dtype,
        default_initializer=UniformInitializer(-std, std))
        for shape in rnn_weight_shapes(mode, D, hidden_size, num_layers,
                                       ndir)]
    out = helper.create_variable_for_type_inference(dtype)
    h_n = helper.create_variable_for_type_inference(dtype)
    c_n = helper.create_variable_for_type_inference(dtype)
    ins = {"Input": [input], "WeightList": weights}
    if sequence_length is not None:
        ins["SequenceLength"] = [sequence_length]
    helper.append_op(type="rnn", inputs=ins,
                     outputs={"Out": [out], "State": [h_n, c_n]},
                     attrs={"mode": mode, "hidden_size": hidden_size,
                            "num_layers": num_layers,
                            "is_bidirec": is_bidirec, "dropout_prob": 0.0})
    return out, h_n


def warpctc(input, label, blank=0, norm_by_times=False,
            input_length=None, label_length=None):
    """CTC loss on padded-dense inputs (reference layers/loss.py warpctc;
    the op subsumes warp-ctc). input: [B, T, C] raw logits;
    label: [B, L]; lengths: [B]. Returns [B, 1] loss."""
    helper = LayerHelper("warpctc")
    loss = helper.create_variable_for_type_inference("float32")
    grad = helper.create_variable_for_type_inference("float32", True)
    ins = {"Logits": [input], "Label": [label]}
    if input_length is not None:
        ins["LogitsLength"] = [input_length]
    if label_length is not None:
        ins["LabelLength"] = [label_length]
    helper.append_op(type="warpctc", inputs=ins,
                     outputs={"Loss": [loss], "WarpCTCGrad": [grad]},
                     attrs={"blank": blank, "norm_by_times": norm_by_times})
    return loss


def linear_chain_crf(input, label, param_attr=None, length=None):
    """CRF negative training objective (reference layers/nn.py
    linear_chain_crf): creates the [num_tags+2, num_tags] transition
    param; returns the per-sequence log likelihood [B, 1]."""
    helper = LayerHelper("linear_chain_crf")
    num_tags = input.shape[-1]
    trans = helper.create_parameter(
        param_attr, shape=[num_tags + 2, num_tags], dtype="float32")
    ll = helper.create_variable_for_type_inference("float32")
    alpha = helper.create_variable_for_type_inference("float32", True)
    ee = helper.create_variable_for_type_inference("float32", True)
    te = helper.create_variable_for_type_inference("float32", True)
    ins = {"Emission": [input], "Transition": [trans], "Label": [label]}
    if length is not None:
        ins["Length"] = [length]
    helper.append_op(
        type="linear_chain_crf", inputs=ins,
        outputs={"LogLikelihood": [ll], "Alpha": [alpha],
                 "EmissionExps": [ee], "TransitionExps": [te]},
        attrs={})
    return ll


def crf_decoding(input, param_attr=None, label=None, length=None,
                 transition=None):
    """Viterbi path [B, T] (reference layers/nn.py crf_decoding). Pass
    `transition` to reuse the training CRF's parameter."""
    helper = LayerHelper("crf_decoding")
    if transition is None:
        num_tags = input.shape[-1]
        transition = helper.create_parameter(
            param_attr, shape=[num_tags + 2, num_tags], dtype="float32")
    path = helper.create_variable_for_type_inference("int64")
    ins = {"Emission": [input], "Transition": [transition]}
    if length is not None:
        ins["Length"] = [length]
    helper.append_op(type="crf_decoding", inputs=ins,
                     outputs={"ViterbiPath": [path]}, attrs={})
    return path


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation (reference layers/nn.py nce)."""
    helper = LayerHelper("nce", name=name)
    dim = input.shape[-1]
    w = helper.create_parameter(param_attr,
                                shape=[num_total_classes, dim],
                                dtype="float32")
    b = helper.create_parameter(bias_attr, shape=[num_total_classes],
                                dtype="float32", is_bias=True)
    cost = helper.create_variable_for_type_inference("float32")
    slog = helper.create_variable_for_type_inference("float32", True)
    slab = helper.create_variable_for_type_inference("int64", True)
    ins = {"Input": [input], "Label": [label], "Weight": [w]}
    if b is not None:
        ins["Bias"] = [b]
    helper.append_op(
        type="nce", inputs=ins,
        outputs={"Cost": [cost], "SampleLogits": [slog],
                 "SampleLabels": [slab]},
        attrs={"num_total_classes": num_total_classes,
               "num_neg_samples": num_neg_samples,
               "sampler": {"uniform": 0, "log_uniform": 1}.get(sampler, 0),
               "seed": seed, "is_sparse": is_sparse})
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, is_sparse=False):
    """Hierarchical sigmoid over the default complete binary tree
    (reference layers/nn.py hsigmoid)."""
    helper = LayerHelper("hierarchical_sigmoid", name=name)
    dim = input.shape[-1]
    w = helper.create_parameter(param_attr, shape=[num_classes - 1, dim],
                                dtype="float32")
    b = helper.create_parameter(bias_attr, shape=[num_classes - 1],
                                dtype="float32", is_bias=True)
    cost = helper.create_variable_for_type_inference("float32")
    pre = helper.create_variable_for_type_inference("float32", True)
    wo = helper.create_variable_for_type_inference("float32", True)
    ins = {"X": [input], "Label": [label], "W": [w]}
    if b is not None:
        ins["Bias"] = [b]
    helper.append_op(type="hierarchical_sigmoid", inputs=ins,
                     outputs={"Out": [cost], "PreOut": [pre],
                              "W_Out": [wo]},
                     attrs={"num_classes": num_classes,
                            "is_sparse": is_sparse})
    return cost


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None, data_format="NCDHW"):
    """3D convolution, NCDHW (reference layers/nn.py conv3d)."""
    helper = LayerHelper("conv3d", act=act, name=name)
    dtype = input.dtype or "float32"
    to3 = lambda v: [v] * 3 if isinstance(v, int) else list(v)
    filter_size, stride = to3(filter_size), to3(stride)
    padding, dilation = to3(padding), to3(dilation)
    num_channels = input.shape[1]
    import math
    std = math.sqrt(2.0 / (filter_size[0] * filter_size[1]
                           * filter_size[2] * num_channels))
    w = helper.create_parameter(
        param_attr,
        shape=[num_filters, num_channels // groups] + filter_size,
        dtype=dtype, default_initializer=NormalInitializer(0.0, std))
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv3d", inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": stride, "paddings": padding,
               "dilations": dilation, "groups": groups,
               "data_format": data_format})
    b = helper.create_parameter(bias_attr, shape=[num_filters],
                                dtype=dtype, is_bias=True)
    if b is not None:
        tmp = helper.create_variable_for_type_inference(dtype)
        helper.append_op(type="elementwise_add",
                         inputs={"X": [out], "Y": [b]},
                         outputs={"Out": [tmp]}, attrs={"axis": 1})
        out = tmp
    return helper.append_activation(out)


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True,
           data_format="NCDHW"):
    """3D pooling, NCDHW (reference layers/nn.py pool3d)."""
    helper = LayerHelper("pool3d", name=name)
    to3 = lambda v: [v] * 3 if isinstance(v, int) else list(v)
    out = helper.create_variable_for_type_inference(
        input.dtype or "float32")
    helper.append_op(
        type="pool3d", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"pooling_type": pool_type, "ksize": to3(pool_size),
               "strides": to3(pool_stride), "paddings": to3(pool_padding),
               "global_pooling": global_pooling, "ceil_mode": ceil_mode,
               "exclusive": exclusive, "data_format": data_format})
    return out
