"""Static-graph Variable operator sugar (reference python/paddle/fluid/
layers/math_op_patch.py monkey_patch_variable): arithmetic and comparison
dunders append the corresponding elementwise/compare ops to the current
program, so `h * 2 + b` and `mean(x) > 0` build graphs — what the
dy2static converters (jit/dy2static.py) and plain user code both rely on.
"""
from __future__ import annotations

__all__ = ["monkey_patch_variable"]


def _scalar_var(value, ref_dtype):
    from .tensor import fill_constant
    dt = ref_dtype or "float32"
    if str(dt).startswith(("int", "uint")) and \
            float(value) != int(value):
        dt = "float32"
    return fill_constant([1], dt, float(value))


def _binary(op_type, reverse=False, out_dtype=None):
    def impl(self, other):
        from ..framework import Variable
        from ..layer_helper import LayerHelper
        if not isinstance(other, Variable):
            if not isinstance(other, (int, float, bool)):
                return NotImplemented
            other = _scalar_var(other, self.dtype)
        a, b = (other, self) if reverse else (self, other)
        helper = LayerHelper(op_type)
        out = helper.create_variable_for_type_inference(
            out_dtype or a.dtype or b.dtype or "float32")
        helper.append_op(type=op_type, inputs={"X": [a], "Y": [b]},
                         outputs={"Out": [out]}, attrs={"axis": -1})
        return out
    return impl


def _unary_scale(scale, bias):
    def impl(self):
        from ..layer_helper import LayerHelper
        helper = LayerHelper("scale")
        out = helper.create_variable_for_type_inference(self.dtype)
        helper.append_op(type="scale", inputs={"X": [self]},
                         outputs={"Out": [out]},
                         attrs={"scale": float(scale),
                                "bias": float(bias),
                                "bias_after_scale": True})
        return out
    return impl


def monkey_patch_variable():
    from ..framework import Variable
    patches = {
        "__add__": _binary("elementwise_add"),
        "__radd__": _binary("elementwise_add", reverse=True),
        "__sub__": _binary("elementwise_sub"),
        "__rsub__": _binary("elementwise_sub", reverse=True),
        "__mul__": _binary("elementwise_mul"),
        "__rmul__": _binary("elementwise_mul", reverse=True),
        "__truediv__": _binary("elementwise_div"),
        "__rtruediv__": _binary("elementwise_div", reverse=True),
        "__pow__": _binary("elementwise_pow"),
        "__mod__": _binary("elementwise_mod"),
        "__floordiv__": _binary("elementwise_floordiv"),
        "__neg__": _unary_scale(-1.0, 0.0),
        "__gt__": _binary("greater_than", out_dtype="bool"),
        "__ge__": _binary("greater_equal", out_dtype="bool"),
        "__lt__": _binary("less_than", out_dtype="bool"),
        "__le__": _binary("less_equal", out_dtype="bool"),
        # NOTE: __eq__/__ne__ stay identity-based — Variables are hashed
        # as graph nodes all over the framework (the reference makes the
        # same call; layers.equal is the elementwise form)
    }
    for name, fn in patches.items():
        setattr(Variable, name, fn)
