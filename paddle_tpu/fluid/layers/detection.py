"""Detection layer functions (reference python/paddle/fluid/layers/
detection.py) over the detection op tier (fluid/ops/detection_ops.py)."""
from __future__ import annotations

from ..layer_helper import LayerHelper

__all__ = ["iou_similarity", "box_coder", "prior_box", "yolo_box",
           "roi_align", "multiclass_nms"]


def _op(op_type, inputs, attrs, out_slots):
    helper = LayerHelper(op_type)
    outs = {s: [helper.create_variable_for_type_inference(dt)]
            for s, dt in out_slots.items()}
    helper.append_op(type=op_type,
                     inputs={k: [v] for k, v in inputs.items()
                             if v is not None},
                     outputs=outs, attrs=attrs)
    vals = [outs[s][0] for s in out_slots]
    return vals[0] if len(vals) == 1 else tuple(vals)


def iou_similarity(x, y, box_normalized=True, name=None):
    return _op("iou_similarity", {"X": x, "Y": y},
               {"box_normalized": box_normalized}, {"Out": "float32"})


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None, axis=0):
    ins = {"PriorBox": prior_box, "TargetBox": target_box}
    attrs = {"code_type": code_type, "box_normalized": box_normalized,
             "axis": axis}
    if isinstance(prior_box_var, (list, tuple)):
        attrs["variance"] = [float(v) for v in prior_box_var]
    elif prior_box_var is not None:
        ins["PriorBoxVar"] = prior_box_var
    return _op("box_coder", ins, attrs, {"OutputBox": "float32"})


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    return _op("prior_box", {"Input": input, "Image": image},
               {"min_sizes": [float(v) for v in min_sizes],
                "max_sizes": [float(v) for v in (max_sizes or [])],
                "aspect_ratios": [float(v) for v in aspect_ratios],
                "variances": [float(v) for v in variance], "flip": flip,
                "clip": clip, "step_w": float(steps[0]),
                "step_h": float(steps[1]), "offset": offset,
                "min_max_aspect_ratios_order": min_max_aspect_ratios_order},
               {"Boxes": "float32", "Variances": "float32"})


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0):
    return _op("yolo_box", {"X": x, "ImgSize": img_size},
               {"anchors": [int(a) for a in anchors],
                "class_num": class_num, "conf_thresh": conf_thresh,
                "downsample_ratio": downsample_ratio,
                "clip_bbox": clip_bbox, "scale_x_y": scale_x_y},
               {"Boxes": "float32", "Scores": "float32"})


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, rois_num=None,
              name=None, aligned=False):
    return _op("roi_align",
               {"X": input, "ROIs": rois, "RoisNum": rois_num},
               {"pooled_height": pooled_height, "pooled_width": pooled_width,
                "spatial_scale": spatial_scale,
                "sampling_ratio": sampling_ratio, "aligned": aligned},
               {"Out": "float32"})


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=64,
                   keep_top_k=100, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, name=None):
    out, _idx, num = _op(
        "multiclass_nms", {"BBoxes": bboxes, "Scores": scores},
        {"score_threshold": score_threshold, "nms_top_k": nms_top_k,
         "keep_top_k": keep_top_k, "nms_threshold": nms_threshold,
         "nms_eta": nms_eta, "normalized": normalized,
         "background_label": background_label},
        {"Out": "float32", "Index": "int32", "NmsRoisNum": "int32"})
    return out, num
