"""fluid.layers namespace (reference python/paddle/fluid/layers/)."""
from . import nn, tensor, detection, parity
from .math_op_patch import monkey_patch_variable
monkey_patch_variable()
from .nn import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403
from .detection import *  # noqa: F401,F403
from .parity import *  # noqa: F401,F403

from .nn import __all__ as _nn_all
from .tensor import __all__ as _tensor_all
from .detection import __all__ as _det_all
from .parity import __all__ as _parity_all

__all__ = list(_nn_all) + list(_tensor_all) + list(_det_all) \
    + list(_parity_all)
