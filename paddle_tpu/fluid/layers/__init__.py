"""fluid.layers namespace (reference python/paddle/fluid/layers/)."""
from . import nn, tensor
from .nn import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403

from .nn import __all__ as _nn_all
from .tensor import __all__ as _tensor_all

__all__ = list(_nn_all) + list(_tensor_all)
