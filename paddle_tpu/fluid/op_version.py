"""Op version registry (reference framework/op_version_registry.h +
op_compatible_info.h + framework.proto:184-211): per-op version numbers
saved with every serialized Program; loading checks compatibility so old
binaries fail loudly on programs from newer frameworks."""
from __future__ import annotations

import logging

__all__ = ["register_op_version", "get_op_version", "get_op_version_map",
           "check_compatibility"]

logger = logging.getLogger(__name__)

_VERSIONS: dict[str, list[tuple[int, str]]] = {}


def register_op_version(op_type: str, version: int, note: str = ""):
    """Record a behavior change of `op_type` at `version` (monotonic)."""
    hist = _VERSIONS.setdefault(op_type, [])
    if hist and version <= hist[-1][0]:
        raise ValueError(
            f"op {op_type!r} version {version} must exceed "
            f"{hist[-1][0]}")
    hist.append((version, note))


def get_op_version(op_type: str) -> int:
    hist = _VERSIONS.get(op_type)
    return hist[-1][0] if hist else 0


def get_op_version_map() -> dict[str, int]:
    return {op: hist[-1][0] for op, hist in _VERSIONS.items()}


def check_compatibility(saved: dict[str, int],
                        strict: bool = False) -> list[str]:
    """Compare a loaded program's op-version map against this build.
    Newer-than-us versions are incompatible (the saved program may rely
    on semantics we don't have); older ones are fine (we keep
    backward-compatible kernels). Returns the incompatibility list."""
    problems = []
    for op, v in (saved or {}).items():
        have = get_op_version(op)
        if v > have:
            problems.append(
                f"op {op!r} saved at version {v}, this build has {have}")
    if problems:
        msg = "; ".join(problems)
        if strict:
            raise RuntimeError(f"incompatible program: {msg}")
        logger.warning("op version mismatch: %s", msg)
    return problems


# --- version history of ops whose behavior changed across rounds --------
register_op_version("dropout", 1, "rng stream switched to RBG default")
register_op_version(
    "conv2d_transpose", 1,
    "groups/output_padding honored; explicit-padding semantics fixed")
register_op_version(
    "lookup_table_v2", 1, "is_sparse emits SelectedRows gradients")
