"""Save/load of parameters and inference programs.

Parity with reference python/paddle/fluid/io.py (save_persistables,
load_persistables, save_inference_model, load_inference_model) and
paddle.static.save/load (io.py:1669,1730). Storage format: one `.pdparams`
npz-style archive for tensors + a serialised Program (paddle_tpu proto) for
inference models.

Checkpoint-store routing: with ``PADDLE_TPU_CKPT`` set, save paths write
through ``paddle_tpu.checkpoint`` (content-addressed chunks + CRC'd
manifest, atomic commit, incremental dedup across steps, no pickle on
restore — docs/CHECKPOINT.md) into a ``<name>.ckpt`` directory beside
where the legacy file would sit. Load paths AUTO-DETECT the format, so
legacy archives stay readable regardless of the env knob.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from . import core
from .executor import global_scope
from .framework import Program, Variable, default_main_program

__all__ = [
    "DataLoader",
    "save_vars", "save_params", "save_persistables", "load_vars",
    "load_params", "load_persistables", "save_inference_model",
    "load_inference_model", "save", "load", "save_train_model",
]


def _collect(program, predicate):
    return [v for v in program.list_vars() if predicate(v)]


def _ckpt_root(path: str) -> str:
    """Store-format sibling of a legacy archive path."""
    return path + ".ckpt"


def _save_blob(blob: dict, path: str):
    """One name->ndarray blob to disk: checkpoint store when
    PADDLE_TPU_CKPT is on, legacy pickle archive otherwise."""
    from .. import checkpoint as ckpt
    if ckpt.enabled():
        ckpt.CheckpointStore(_ckpt_root(path)).save(blob)
        return
    with open(path, "wb") as f:
        pickle.dump(blob, f, protocol=4)


def _prefer_store(root: str, legacy_path: str) -> bool:
    """Format auto-detection. When BOTH a committed store and a legacy
    archive exist (a job toggled PADDLE_TPU_CKPT between saves), the
    NEWER save wins — silently loading stale parameters from the older
    format is the one wrong answer."""
    from .. import checkpoint as ckpt
    manifests = ckpt.list_manifests(root)
    if not manifests:
        return False
    if not os.path.exists(legacy_path):
        return True
    store_mtime = max(os.path.getmtime(p) for _s, p in manifests)
    return store_mtime >= os.path.getmtime(legacy_path)


def _save_legacy_pickle(obj, path: str):
    """Write one legacy pickle archive (the PADDLE_TPU_CKPT=off format;
    incubate's CheckpointSaver routes here to stay import-free of
    pickle itself)."""
    with open(path, "wb") as f:
        pickle.dump(obj, f, protocol=4)


def legacy_pickle_load(path: str):
    """Read one LEGACY on-disk pickle archive (pre-store formats:
    .pdparams blobs, incubate ckpt-N/params.pkl). Deliberately the
    only pickle-deserialization entry point outside this module's own
    loaders: the wire/checkpoint trees (distributed/, checkpoint/,
    incubate/) are pickle-free by static check, and their legacy
    back-compat reads route HERE — a local disk archive the operator
    placed, never wire input."""
    with open(path, "rb") as f:
        return pickle.load(f)


def _load_blob(path: str) -> dict:
    """Auto-detecting load: the newest of {committed store dir, legacy
    archive}; else a clear FileNotFoundError (not a bare KeyError)."""
    from .. import checkpoint as ckpt
    root = _ckpt_root(path)
    if _prefer_store(root, path):
        blob, _meta = ckpt.CheckpointStore(root).restore()
        return blob
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"no parameter archive at {path} (and no checkpoint store "
            f"at {root})")
    with open(path, "rb") as f:
        return pickle.load(f)


def _is_persistable(v):
    return v.persistable and not v.is_data


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    program = main_program or default_main_program()
    if vars is None:
        vars = _collect(program, predicate or _is_persistable)
    os.makedirs(dirname, exist_ok=True)
    scope = global_scope()
    # one device sync for the whole save, not one per var (core.py
    # batched_to_numpy: the TPU tunnel charges ~1 RTT per blocked fetch)
    blob = core.batched_to_numpy_dict(
        [(v.name, val) for v in vars
         if (val := scope.find_var(v.name)) is not None])
    path = os.path.join(dirname, filename or "__all__.pdparams")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    _save_blob(blob, path)
    return path


def save_params(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program,
                     predicate=lambda v: getattr(v, "trainable", False),
                     filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program, filename=filename)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    import jax.numpy as jnp
    path = os.path.join(dirname, filename or "__all__.pdparams")
    blob = _load_blob(path)
    scope = global_scope()
    program = main_program or default_main_program()
    want = None
    if vars is not None:
        want = {v.name for v in vars}
    elif predicate is not None:
        want = {v.name for v in _collect(program, predicate)}
    if want is not None:
        missing = sorted(want - set(blob))
        if missing:
            raise ValueError(
                f"variables missing from {path}: {missing} "
                f"(archive holds {len(blob)} vars)")
    for name, arr in blob.items():
        if want is None or name in want:
            scope.set(name, jnp.asarray(arr))


def load_params(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, filename=filename)


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True,
                         program_only=False):
    """Prune program to feed→fetch path + save params
    (reference io.py:1164). Program serialisation via paddle_tpu proto."""
    from .proto import serialize_program
    program = main_program or default_main_program()
    program = program.clone(for_test=True)
    # prune to the feed->fetch slice (reference framework/prune.h via
    # io.py:1164): ops outside the path — e.g. the loss/metric branch
    # reading labels — must not survive into the deployed model
    from .executor import _prune_to_fetch
    gb = program.global_block()
    keep = _prune_to_fetch(program, [v.name for v in target_vars])
    gb.ops[:] = keep
    # prune vars too: optimizer accumulators are persistable and would
    # otherwise ship (and triple) the deployed params file
    referenced = set(feeded_var_names) | \
        {n for op in keep for n in op.input_arg_names} | \
        {n for op in keep for n in op.output_arg_names}
    for name in [n for n in gb.vars if n not in referenced]:
        del gb.vars[name]
    program._bump_version()
    os.makedirs(dirname, exist_ok=True)
    meta = {
        "feed_names": list(feeded_var_names),
        "fetch_names": [v.name for v in target_vars],
    }
    model_path = os.path.join(dirname, model_filename or "__model__")
    # model_filename may itself carry subdirectories ("deploy/__model__")
    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    with open(model_path, "wb") as f:
        f.write(serialize_program(program, meta))
    if not program_only:
        save_persistables(executor, dirname, program,
                          filename=params_filename)
    return meta["fetch_names"]


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    from .proto import deserialize_program
    model_path = os.path.join(dirname, model_filename or "__model__")
    with open(model_path, "rb") as f:
        program, meta = deserialize_program(f.read())
    load_persistables(executor, dirname, program, filename=params_filename)
    fetch_vars = [program.global_block()._var_recursive(n)
                  for n in meta["fetch_names"]]
    return program, meta["feed_names"], fetch_vars


def save(program: Program, model_path: str):
    """paddle.static.save (reference io.py:1669): params + opt state."""
    dirname = os.path.dirname(model_path) or "."
    os.makedirs(dirname, exist_ok=True)
    scope = global_scope()
    blob = core.batched_to_numpy_dict(
        [(v.name, val) for v in program.list_vars() if v.persistable
         and (val := scope.find_var(v.name)) is not None])
    _save_blob(blob, model_path + ".pdparams")


def load(program: Program, model_path: str, executor=None, var_list=None):
    import jax.numpy as jnp
    blob = _load_blob(model_path + ".pdparams")
    scope = global_scope()
    for name, arr in blob.items():
        scope.set(name, jnp.asarray(arr))


class DataLoader:
    """Static-graph data loader (reference fluid/reader.py GeneratorLoader
    / py_reader): `from_generator(feed_list, capacity)` builds an iterable
    that prefetches generator batches on a background thread and yields
    executor feed dicts — the py_reader double-buffer, minus the device-
    side queue ops XLA's async dispatch makes redundant."""

    def __init__(self, feed_list, capacity, iterable=True):
        self._feed_list = list(feed_list)
        self._capacity = max(2, int(capacity))
        self._iterable = iterable
        self._gen = None

    @staticmethod
    def from_generator(feed_list=None, capacity=16, use_double_buffer=True,
                       iterable=True, return_list=False,
                       use_multiprocess=False, drop_last=True):
        if not feed_list:
            raise ValueError("from_generator needs feed_list variables")
        return DataLoader(feed_list, capacity, iterable)

    # -- generator binding (reference set_* trio) -----------------------
    def set_sample_generator(self, reader, batch_size, drop_last=True,
                             places=None):
        def batched():
            batch = []
            for sample in reader():
                batch.append(sample if isinstance(sample, (list, tuple))
                             else (sample,))
                if len(batch) == batch_size:
                    yield [np.stack([b[i] for b in batch])
                           for i in range(len(batch[0]))]
                    batch = []
            if batch and not drop_last:
                yield [np.stack([b[i] for b in batch])
                       for i in range(len(batch[0]))]
        self._gen = batched
        return self

    def set_sample_list_generator(self, reader, places=None):
        def batched():
            for samples in reader():
                yield [np.stack([s[i] for s in samples])
                       for i in range(len(samples[0]))]
        self._gen = batched
        return self

    def set_batch_generator(self, reader, places=None):
        self._gen = reader
        return self

    def __call__(self):
        return iter(self)

    def __iter__(self):
        if self._gen is None:
            raise RuntimeError(
                "bind a generator first: set_batch_generator / "
                "set_sample_generator / set_sample_list_generator")
        import queue as _q
        import threading
        q: "_q.Queue" = _q.Queue(maxsize=self._capacity)
        _END = object()
        err = []

        def producer():
            try:
                for batch in self._gen():
                    q.put(batch)
            except BaseException as e:
                err.append(e)
            finally:
                q.put(_END)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        names = [v.name for v in self._feed_list]
        while True:
            item = q.get()
            if item is _END:
                break
            if not isinstance(item, dict):
                item = dict(zip(names, item))
            yield item
        if err:
            raise err[0]


def save_train_model(dirname, feeded_var_names, loss, executor,
                     main_program=None, startup_program=None):
    """Save a TRAINABLE program pair for language-free training hosts
    (reference fluid/train/demo/demo_trainer.cc loads exactly this:
    startup + main with backward/optimizer ops + persistables). Consumed
    by capi/train_host.py behind the PD_Trainer C ABI."""
    from .proto import serialize_program
    from . import framework as fw
    main_program = main_program or fw.default_main_program()
    startup_program = startup_program or fw.default_startup_program()
    os.makedirs(dirname, exist_ok=True)
    meta = {"feed_names": list(feeded_var_names),
            "fetch_names": [loss.name if hasattr(loss, "name") else
                            str(loss)]}
    with open(os.path.join(dirname, "main.program"), "wb") as f:
        f.write(serialize_program(main_program, meta))
    with open(os.path.join(dirname, "startup.program"), "wb") as f:
        f.write(serialize_program(startup_program))
    if executor is not None:
        pdir = os.path.join(dirname, "params")
        os.makedirs(pdir, exist_ok=True)
        save_persistables(executor, pdir, main_program)
