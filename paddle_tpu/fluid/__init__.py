"""paddle_tpu.fluid — core framework layer (reference python/paddle/fluid/).

Static-graph-first TPU-native framework core: Program IR, tracing Executor
that lowers blocks to single XLA computations, graph-level autodiff, layers,
optimizers. See SURVEY.md §7 for the design mapping.
"""
from . import core, framework, layers, initializer, regularizer, clip, \
    unique_name, io, dataset, passes, transpiler
from .transpiler import DistributeTranspiler, DistributeTranspilerConfig
from .dataset import DatasetFactory
from . import ops as _ops  # registers all built-in ops
from .core import (CPUPlace, TPUPlace, CUDAPlace, CUDAPinnedPlace, XPUPlace,
                   get_flags, set_flags)
from .executor import Executor, global_scope, scope_guard
from .framework import (Program, Variable, default_main_program,
                        default_startup_program, program_guard, name_scope,
                        device_guard, in_dygraph_mode)
from .backward import append_backward, gradients
from .param_attr import ParamAttr
from .initializer import (Constant, Uniform, Normal, TruncatedNormal, Xavier,
                          MSRA, NumpyArrayInitializer)
from . import optimizer
from .scope import Scope
from . import dygraph
from .dygraph.base import enable_dygraph, disable_dygraph, enabled
from .data_feeder import DataFeeder

__all__ = [
    "core", "framework", "layers", "initializer", "regularizer", "clip",
    "optimizer", "io", "CPUPlace", "TPUPlace", "CUDAPlace", "Executor",
    "Program", "Variable", "default_main_program", "default_startup_program",
    "program_guard", "append_backward", "gradients", "ParamAttr",
    "global_scope", "scope_guard", "Scope", "unique_name", "dygraph",
    "name_scope", "device_guard", "in_dygraph_mode", "get_flags", "set_flags",
    "DataFeeder", "enable_dygraph", "disable_dygraph",
]
