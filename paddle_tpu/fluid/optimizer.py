"""Static-graph optimizers (reference python/paddle/fluid/optimizer.py:56).

`minimize` = append_backward + apply_gradients (regularization → grad clip →
per-param optimizer op). Optimizer ops are functional on TPU: the executor
donates the old param/accumulator buffers, so updates are in-place on device.
"""
from __future__ import annotations

import contextlib

import numpy as np

from . import layers, unique_name
from .backward import append_backward
from .framework import (Parameter, Program, Variable, default_main_program,
                        default_startup_program, in_dygraph_mode,
                        program_guard)
from .initializer import ConstantInitializer
from .layer_helper import LayerHelper

__all__ = [
    "Optimizer", "SGD", "SGDOptimizer", "Momentum", "MomentumOptimizer",
    "Adagrad", "AdagradOptimizer", "Adam", "AdamOptimizer", "Adamax",
    "AdamaxOptimizer", "RMSProp", "RMSPropOptimizer", "Adadelta",
    "AdadeltaOptimizer", "Lamb", "LambOptimizer", "Ftrl", "FtrlOptimizer",
    "DecayedAdagrad", "DecayedAdagradOptimizer", "ExponentialMovingAverage",
    "RecomputeOptimizer", "GradientMergeOptimizer", "LookaheadOptimizer",
    "LarsMomentumOptimizer", "DGCMomentumOptimizer", "LocalSGDOptimizer",
    "ModelAverage",
]


class Optimizer:
    def __init__(self, learning_rate=0.001, parameter_list=None,
                 regularization=None, grad_clip=None, name=None):
        self._learning_rate = learning_rate
        self._parameter_list = parameter_list
        self.regularization = regularization
        self._grad_clip = grad_clip
        self._name = name
        self._accumulators: dict[str, dict[str, Variable]] = {}
        self._eager_state: dict[str, dict] = {}  # dygraph accumulator arrays
        self._lr_var = None
        self.type = getattr(self, "type", "sgd")

    # -- learning rate -----------------------------------------------------
    def _create_global_learning_rate(self):
        if isinstance(self._learning_rate, Variable):
            self._lr_var = self._learning_rate
            return
        if self._lr_var is not None:
            return
        helper = LayerHelper("learning_rate")
        self._lr_var = helper.create_global_variable(
            name=unique_name.generate("learning_rate"), shape=[1],
            dtype="float32", persistable=True,
            value=float(self._learning_rate))

    def _global_learning_rate(self):
        return self._lr_var

    @property
    def learning_rate_var(self):
        return self._lr_var

    def current_step_lr(self):
        return float(self._learning_rate) \
            if not isinstance(self._learning_rate, Variable) else None

    def set_lr(self, value):
        from .executor import global_scope
        import jax.numpy as jnp
        self._learning_rate = value
        if self._lr_var is not None:
            global_scope().set(self._lr_var.name,
                               jnp.full((1,), value, dtype=jnp.float32))

    # -- accumulators --------------------------------------------------------
    def _add_accumulator(self, name, param, dtype=None, fill_value=0.0,
                         shape=None):
        key = (name, param.name)
        if name in self._accumulators and \
                param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        helper = LayerHelper(name)
        var = helper.create_global_variable(
            name=unique_name.generate(f"{param.name}_{name}"),
            shape=shape or list(param.shape), dtype=dtype or "float32",
            persistable=True, value=float(fill_value))
        self._accumulators.setdefault(name, {})[param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # -- main entry points ---------------------------------------------------
    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        parameter_list = parameter_list or self._parameter_list
        return append_backward(loss, parameter_list, no_grad_set, callbacks)

    def apply_gradients(self, params_grads):
        params_grads = [pg for pg in params_grads if pg[1] is not None]
        # regularization
        block = default_main_program().current_block()
        if self.regularization is not None:
            new_pg = []
            for p, g in params_grads:
                reg = p.regularizer or self.regularization
                new_pg.append((p, reg(p, g, block) if reg else g))
            params_grads = new_pg
        else:
            new_pg = []
            for p, g in params_grads:
                new_pg.append((p, p.regularizer(p, g, block)
                               if p.regularizer else g))
            params_grads = new_pg
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        return self._apply_optimize_ops(params_grads)

    def _apply_optimize_ops(self, params_grads):
        self._create_global_learning_rate()
        self._create_accumulators(
            default_main_program().current_block(),
            [p for p, _ in params_grads])
        ops = []
        for p, g in params_grads:
            ops.append(self._append_optimize_op(
                default_main_program().current_block(), (p, g)))
        self._finish_update(default_main_program().current_block(),
                            params_grads)
        return ops

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if in_dygraph_mode():
            return self._dygraph_minimize(loss, parameter_list, no_grad_set)
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads

    def _dygraph_minimize(self, loss, parameter_list=None, no_grad_set=None):
        from .dygraph import base as dybase
        params = parameter_list or self._parameter_list
        if params is None:
            raise ValueError("dygraph optimizer needs parameter_list "
                             "(pass model.parameters())")
        params_grads = [(p, p.grad) for p in params
                        if p.trainable and p.grad is not None]
        self._dygraph_apply(params_grads)
        return None, params_grads

    def _dygraph_apply(self, params_grads):
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        for p, g in params_grads:
            self._eager_update(p, g)

    # -- dygraph step: one kernel story for both modes ----------------------
    def _eager_acc_specs(self):
        """(in_slot, out_slot_or_None, fill_value, is_scalar) for the eager
        accumulator state this optimizer's kernel consumes."""
        return ()

    def _eager_attrs(self) -> dict:
        return {}

    def _eager_attrs_for(self, p) -> dict:
        return self._eager_attrs()

    def _eager_finish(self, state: dict):
        pass

    def _current_lr_value(self):
        lr = self._learning_rate
        return lr() if callable(lr) else float(lr)

    def _eager_update(self, p, g):
        """Run this optimizer's registered op KERNEL eagerly over
        (param, grad, accumulators) — the reference dygraph path likewise
        dispatches the same per-op kernel via core.ops.<type>
        (pybind/op_function_generator.cc)."""
        import jax.numpy as jnp
        from . import registry as _registry
        opdef = _registry.require(self.type)
        specs = self._eager_acc_specs()
        state = self._eager_state.setdefault(p.name, {})
        for in_slot, _o, fill, scalar in specs:
            if in_slot not in state:
                shape = (1,) if scalar else p._value.shape
                state[in_slot] = jnp.full(shape, float(fill), jnp.float32)
        gval = g._value if hasattr(g, "_value") else jnp.asarray(g)
        ins = {"Param": [p._value], "Grad": [gval],
               "LearningRate": [jnp.asarray([self._current_lr_value()],
                                            jnp.float32)]}
        for in_slot, _o, _f, _s in specs:
            ins[in_slot] = [state[in_slot]]
        attrs: dict = {}
        opdef.fill_default_attrs(attrs)
        attrs.update(self._eager_attrs_for(p))
        outs = opdef.compute(None, ins, attrs)
        p._set_value(outs["ParamOut"][0])
        for in_slot, out_slot, _f, _s in specs:
            if out_slot is not None:
                state[in_slot] = outs[out_slot][0]
        self._eager_finish(state)

    # subclass hooks
    def _create_accumulators(self, block, parameters):
        pass

    def _finish_update(self, block, params_grads):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    # -- state dict (dygraph parity) ----------------------------------------
    def state_dict(self):
        from .executor import global_scope
        sd = {}
        for name, per_param in self._accumulators.items():
            for pname, var in per_param.items():
                val = global_scope().find_var(var.name)
                if val is not None:
                    sd[var.name] = np.asarray(val)
        return sd

    def set_state_dict(self, sd):
        from .executor import global_scope
        import jax.numpy as jnp
        for k, v in sd.items():
            global_scope().set(k, jnp.asarray(v))

    load_state_dict = set_state_dict


class SGDOptimizer(Optimizer):
    type = "sgd"

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            type="sgd",
            inputs={"Param": [p], "Grad": [g],
                    "LearningRate": [self._lr_var]},
            outputs={"ParamOut": [p.name]})



class MomentumOptimizer(Optimizer):
    type = "momentum"

    def __init__(self, learning_rate, momentum=0.9, use_nesterov=False,
                 **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        return block.append_op(
            type="momentum",
            inputs={"Param": [p], "Grad": [g], "Velocity": [v],
                    "LearningRate": [self._lr_var]},
            outputs={"ParamOut": [p.name], "VelocityOut": [v.name]},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov})

    def _eager_acc_specs(self):
        return (("Velocity", "VelocityOut", 0.0, False),)

    def _eager_attrs(self):
        return {"mu": self._momentum, "use_nesterov": self._use_nesterov}


class AdagradOptimizer(Optimizer):
    type = "adagrad"

    def __init__(self, learning_rate, epsilon=1e-6, initial_accumulator_value=0.0,
                 **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._epsilon = epsilon
        self._initial = initial_accumulator_value

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p, fill_value=self._initial)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        return block.append_op(
            type="adagrad",
            inputs={"Param": [p], "Grad": [g], "Moment": [m],
                    "LearningRate": [self._lr_var]},
            outputs={"ParamOut": [p.name], "MomentOut": [m.name]},
            attrs={"epsilon": self._epsilon})

    def _eager_acc_specs(self):
        return (("Moment", "MomentOut", self._initial, False),)

    def _eager_attrs(self):
        return {"epsilon": self._epsilon}


class AdamOptimizer(Optimizer):
    type = "adam"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_mode=False, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=1.0,
                                  shape=[1])
            self._add_accumulator("beta2_pow_acc", p, fill_value=1.0,
                                  shape=[1])

    def _adam_inputs(self, p, g):
        return {"Param": [p], "Grad": [g],
                "LearningRate": [self._lr_var],
                "Moment1": [self._get_accumulator("moment1", p)],
                "Moment2": [self._get_accumulator("moment2", p)],
                "Beta1Pow": [self._get_accumulator("beta1_pow_acc", p)],
                "Beta2Pow": [self._get_accumulator("beta2_pow_acc", p)]}

    def _adam_outputs(self, p):
        return {"ParamOut": [p.name],
                "Moment1Out": [self._get_accumulator("moment1", p).name],
                "Moment2Out": [self._get_accumulator("moment2", p).name],
                "Beta1PowOut": [self._get_accumulator("beta1_pow_acc", p).name],
                "Beta2PowOut": [self._get_accumulator("beta2_pow_acc", p).name]}

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            type="adam", inputs=self._adam_inputs(p, g),
            outputs=self._adam_outputs(p),
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon})

    def _eager_acc_specs(self):
        return (("Moment1", "Moment1Out", 0.0, False),
                ("Moment2", "Moment2Out", 0.0, False),
                ("Beta1Pow", "Beta1PowOut", 1.0, True),
                ("Beta2Pow", "Beta2PowOut", 1.0, True))

    def _eager_attrs(self):
        return {"beta1": self._beta1, "beta2": self._beta2,
                "epsilon": self._epsilon}


class AdamaxOptimizer(Optimizer):
    type = "adamax"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1,
                                  shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            type="adamax",
            inputs={"Param": [p], "Grad": [g],
                    "LearningRate": [self._lr_var],
                    "Moment": [self._get_accumulator("moment", p)],
                    "InfNorm": [self._get_accumulator("inf_norm", p)],
                    "Beta1Pow": [self._get_accumulator("beta1_pow_acc", p)]},
            outputs={"ParamOut": [p.name],
                     "MomentOut": [self._get_accumulator("moment", p).name],
                     "InfNormOut": [self._get_accumulator("inf_norm", p).name]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon})

    def _finish_update(self, block, params_grads):
        for p, g in params_grads:
            b1p = self._get_accumulator("beta1_pow_acc", p)
            block.append_op(type="scale", inputs={"X": [b1p]},
                            outputs={"Out": [b1p.name]},
                            attrs={"scale": self._beta1})

    def _eager_acc_specs(self):
        return (("Moment", "MomentOut", 0.0, False),
                ("InfNorm", "InfNormOut", 0.0, False),
                ("Beta1Pow", None, self._beta1, True))

    def _eager_attrs(self):
        return {"beta1": self._beta1, "beta2": self._beta2,
                "epsilon": self._epsilon}

    def _eager_finish(self, state):
        state["Beta1Pow"] = state["Beta1Pow"] * self._beta1


class RMSPropOptimizer(Optimizer):
    type = "rmsprop"

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("mean_square", p)
            self._add_accumulator("momentum_acc", p)
            self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        ms = self._get_accumulator("mean_square", p)
        mom = self._get_accumulator("momentum_acc", p)
        mg = self._get_accumulator("mean_grad", p)
        return block.append_op(
            type="rmsprop",
            inputs={"Param": [p], "Grad": [g], "MeanSquare": [ms],
                    "Moment": [mom], "MeanGrad": [mg],
                    "LearningRate": [self._lr_var]},
            outputs={"ParamOut": [p.name], "MeanSquareOut": [ms.name],
                     "MomentOut": [mom.name], "MeanGradOut": [mg.name]},
            attrs={"decay": self._rho, "epsilon": self._epsilon,
                   "momentum": self._momentum, "centered": self._centered})

    def _eager_acc_specs(self):
        return (("MeanSquare", "MeanSquareOut", 0.0, False),
                ("Moment", "MomentOut", 0.0, False),
                ("MeanGrad", "MeanGradOut", 0.0, False))

    def _eager_attrs(self):
        return {"decay": self._rho, "epsilon": self._epsilon,
                "momentum": self._momentum, "centered": self._centered}


class AdadeltaOptimizer(Optimizer):
    type = "adadelta"

    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._rho, self._epsilon = rho, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("avg_squared_grad", p)
            self._add_accumulator("avg_squared_update", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        asg = self._get_accumulator("avg_squared_grad", p)
        asu = self._get_accumulator("avg_squared_update", p)
        return block.append_op(
            type="adadelta",
            inputs={"Param": [p], "Grad": [g], "AvgSquaredGrad": [asg],
                    "AvgSquaredUpdate": [asu]},
            outputs={"ParamOut": [p.name], "AvgSquaredGradOut": [asg.name],
                     "AvgSquaredUpdateOut": [asu.name]},
            attrs={"rho": self._rho, "epsilon": self._epsilon})

    def _eager_acc_specs(self):
        return (("AvgSquaredGrad", "AvgSquaredGradOut", 0.0, False),
                ("AvgSquaredUpdate", "AvgSquaredUpdateOut", 0.0, False))

    def _eager_attrs(self):
        return {"rho": self._rho, "epsilon": self._epsilon}


class LambOptimizer(AdamOptimizer):
    type = "lamb"

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6,
                 exclude_from_weight_decay_fn=None, **kwargs):
        super().__init__(learning_rate, beta1, beta2, epsilon, **kwargs)
        self._weight_decay = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        wd = 0.0 if (self._exclude_fn and self._exclude_fn(p)) \
            else self._weight_decay
        return block.append_op(
            type="lamb", inputs=self._adam_inputs(p, g),
            outputs=self._adam_outputs(p),
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon, "weight_decay": wd})

    def _eager_attrs_for(self, p):
        wd = 0.0 if (self._exclude_fn and self._exclude_fn(p)) \
            else self._weight_decay
        return {"beta1": self._beta1, "beta2": self._beta2,
                "epsilon": self._epsilon, "weight_decay": wd}


class FtrlOptimizer(Optimizer):
    type = "ftrl"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        sq = self._get_accumulator("squared", p)
        lin = self._get_accumulator("linear", p)
        return block.append_op(
            type="ftrl",
            inputs={"Param": [p], "Grad": [g], "SquaredAccumulator": [sq],
                    "LinearAccumulator": [lin],
                    "LearningRate": [self._lr_var]},
            outputs={"ParamOut": [p.name], "SquaredAccumOut": [sq.name],
                     "LinearAccumOut": [lin.name]},
            attrs={"l1": self._l1, "l2": self._l2,
                   "lr_power": self._lr_power})

    def _eager_acc_specs(self):
        return (("SquaredAccumulator", "SquaredAccumOut", 0.0, False),
                ("LinearAccumulator", "LinearAccumOut", 0.0, False))

    def _eager_attrs(self):
        return {"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power}


class DecayedAdagradOptimizer(Optimizer):
    type = "decayed_adagrad"

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        return block.append_op(
            type="decayed_adagrad",
            inputs={"Param": [p], "Grad": [g], "Moment": [m],
                    "LearningRate": [self._lr_var]},
            outputs={"ParamOut": [p.name], "MomentOut": [m.name]},
            attrs={"decay": self._decay, "epsilon": self._epsilon})

    def _eager_acc_specs(self):
        return (("Moment", "MomentOut", 0.0, False),)

    def _eager_attrs(self):
        return {"decay": self._decay, "epsilon": self._epsilon}


class ExponentialMovingAverage:
    """EMA of parameters (reference optimizer.py:3416)."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._name = name or ""
        self._ema_vars = {}
        self._params = []

    def update(self):
        block = default_main_program().current_block()
        helper = LayerHelper("ema")
        for p in default_main_program().all_parameters():
            if not p.trainable:
                continue
            ema = helper.create_global_variable(
                name=unique_name.generate(f"{p.name}_ema"),
                shape=list(p.shape), dtype=p.dtype, persistable=True,
                value=0.0)
            self._ema_vars[p.name] = ema
            self._params.append(p)
            scaled_p = helper.create_variable_for_type_inference(p.dtype)
            block.append_op(type="scale", inputs={"X": [p]},
                            outputs={"Out": [scaled_p]},
                            attrs={"scale": 1 - self._decay})
            scaled_e = helper.create_variable_for_type_inference(p.dtype)
            block.append_op(type="scale", inputs={"X": [ema]},
                            outputs={"Out": [scaled_e]},
                            attrs={"scale": self._decay})
            block.append_op(type="sum",
                            inputs={"X": [scaled_e, scaled_p]},
                            outputs={"Out": [ema.name]})

    def apply(self, executor, need_restore=True):
        from .executor import global_scope
        import contextlib

        @contextlib.contextmanager
        def guard():
            scope = global_scope()
            backup = {}
            for p in self._params:
                backup[p.name] = scope.find_var(p.name)
                ema = scope.find_var(self._ema_vars[p.name].name)
                if ema is not None:
                    scope.set(p.name, ema)
            try:
                yield
            finally:
                if need_restore:
                    for name, val in backup.items():
                        scope.set(name, val)
        return guard()

    def restore(self, executor):
        pass


class RecomputeOptimizer(Optimizer):
    """Activation recompute wrapper (reference optimizer.py:4518).

    Set checkpoints with `_set_checkpoints([...vars...])`; backward then
    re-emits the forward segments between checkpoints into the backward
    region behind `recompute_barrier` ops (see append_backward), so the
    original segment activations die after forward and are rematerialised
    for the grad ops — true program-level recompute, not an XLA-CSE hope."""

    def __init__(self, optimizer):
        self._optimizer = optimizer
        self._checkpoints = None

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = checkpoints

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        if self._checkpoints is None:
            raise ValueError(
                "RecomputeOptimizer: call _set_checkpoints([...]) with the "
                "segment-boundary variables before minimize()")
        parameter_list = parameter_list or getattr(
            self._optimizer, "_parameter_list", None)
        return append_backward(loss, parameter_list, no_grad_set, callbacks,
                               checkpoints=self._checkpoints)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        optimize_ops = self._optimizer.apply_gradients(params_grads)
        return optimize_ops, params_grads


class GradientMergeOptimizer(Optimizer):
    """Gradient accumulation over k_steps micro-batches
    (reference optimizer.py:4994): accumulate grads into persistable buffers
    every step; every k-th step a `cond` sub-block applies the inner
    optimizer on the averaged accumulation and zeroes the buffers (the
    reference gates with conditional_block the same way)."""

    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        self.inner_optimizer = inner_optimizer
        self.k_steps = k_steps
        self.avg = avg

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        helper = LayerHelper("gradient_merge")
        params_grads = self.inner_optimizer.backward(
            loss, startup_program, parameter_list, no_grad_set)
        program = default_main_program()
        block = program.current_block()
        step = helper.create_global_variable(
            name=unique_name.generate("gm_step"), shape=[1], dtype="float32",
            persistable=True, value=0.0)
        block.append_op(type="increment", inputs={"X": [step]},
                        outputs={"Out": [step.name]}, attrs={"step": 1.0})
        k = layers.fill_constant([1], "float32", float(self.k_steps))
        rem = layers.elementwise_mod(step, k)
        reached = layers.equal(rem, layers.fill_constant([1], "float32", 0.0))

        accum_pg = []
        for p, g in params_grads:
            acc = helper.create_global_variable(
                name=unique_name.generate(f"{p.name}_gm_acc"),
                shape=list(p.shape), dtype=p.dtype, persistable=True,
                value=0.0)
            block.append_op(type="sum", inputs={"X": [acc, g]},
                            outputs={"Out": [acc.name]})
            accum_pg.append((p, block._var_recursive(acc.name)))

        # true branch: apply inner optimizer on (averaged) accumulation,
        # then zero the buffers
        tb = program._create_block()
        scaled = []
        for p, acc in accum_pg:
            sg = layers.scale(acc, scale=1.0 / self.k_steps) if self.avg \
                else acc
            scaled.append((p, sg))
        self.inner_optimizer.apply_gradients(scaled)
        for p, acc in accum_pg:
            tb.append_op(type="scale", inputs={"X": [acc]},
                         outputs={"Out": [acc.name]}, attrs={"scale": 0.0})
        program._rollback()
        # Only surface writes that live in the PARENT scope (params, accum
        # buffers, optimizer state — all created as global/persistable vars).
        # Branch-local temporaries (e.g. scale tmp outputs created inside the
        # true branch) stay internal: the false branch could never
        # identity-assign them (they don't exist outside the branch).
        written = sorted({n for op in tb.ops for n in op.output_arg_names
                          if n not in tb.vars})

        # false branch: identity-assign every parent-scope var the true
        # branch writes so both branches produce the same outputs for
        # lax.cond
        fb = program._create_block()
        for n in written:
            fb.append_op(type="assign", inputs={"X": [n]},
                         outputs={"Out": [n]})
        program._rollback()

        # captures: names read before being defined within each branch,
        # excluding branch-local vars (which by construction are defined
        # inside the branch before use)
        caps = set()
        for blk in (tb, fb):
            defined: set = set()
            for op in blk.ops:
                for n in op.input_arg_names:
                    if n not in defined and n not in blk.vars:
                        caps.add(n)
                defined.update(op.output_arg_names)
        caps = sorted(caps)
        block.append_op(
            type="cond",
            inputs={"Cond": [reached], "Input": caps},
            outputs={"Out": written},
            attrs={"sub_block_true": tb, "sub_block_false": fb,
                   "capture_names": caps, "out_names": written})
        return None, params_grads


class LookaheadOptimizer:
    """Lookahead (reference optimizer.py:4828): the inner ("fast")
    optimizer steps normally; every k steps the slow weights move
    slow += alpha*(fast - slow) and the fast weights reset to them.
    Dygraph-mode wrapper (slow weights live host-side per param)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        if not (0.0 <= alpha <= 1.0):
            raise ValueError("alpha should be in [0, 1]")
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = int(k)
        self._slow: dict[str, object] = {}
        self._step = 0

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if not in_dygraph_mode():
            raise NotImplementedError(
                "static-graph Lookahead: wrap the train loop with "
                "ExponentialMovingAverage or run dygraph")
        import jax.numpy as jnp
        params = parameter_list or \
            self.inner_optimizer._parameter_list or []
        # snapshot the slow weights from the INITIAL params (reference
        # Lookahead: slow state starts at phi_0, not at phi after the
        # first fast step)
        for p in params:
            if p.name not in self._slow:
                self._slow[p.name] = jnp.asarray(p._value)
        res = self.inner_optimizer.minimize(
            loss, parameter_list=parameter_list, no_grad_set=no_grad_set)
        self._step += 1
        if self._step % self.k == 0:
            for p in params:
                slow = self._slow[p.name] + self.alpha * (
                    p._value - self._slow[p.name])
                self._slow[p.name] = slow
                p._set_value(slow)
        return res

    def step(self):
        self.minimize(None)

    def clear_grad(self):
        if hasattr(self.inner_optimizer, "clear_grad"):
            self.inner_optimizer.clear_grad()


class LarsMomentumOptimizer(Optimizer):
    """LARS (reference optimizer.py:1272 LarsMomentumOptimizer /
    operators/optimizers/lars_momentum_op.cc): per-layer lr scaled by
    ||param|| / (||grad|| + wd*||param||)."""
    type = "lars_momentum"

    def __init__(self, learning_rate, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, epsilon=0.0, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay
        self._epsilon = epsilon

    def _attrs(self):
        return {"mu": self._momentum, "lars_coeff": self._lars_coeff,
                "lars_weight_decay": self._lars_weight_decay,
                "epsilon": self._epsilon}

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        return block.append_op(
            type="lars_momentum",
            inputs={"Param": [p], "Grad": [g], "Velocity": [v],
                    "LearningRate": [self._lr_var]},
            outputs={"ParamOut": [p.name], "VelocityOut": [v.name]},
            attrs=self._attrs())

    def _eager_acc_specs(self):
        return (("Velocity", "VelocityOut", 0.0, False),)

    def _eager_attrs(self):
        return self._attrs()


class DGCMomentumOptimizer(Optimizer):
    """Deep Gradient Compression momentum (reference optimizer.py:1355
    DGCMomentumOptimizer + operators/dgc_op.h): top-`1-sparsity` residual
    selection with momentum correction; vanilla momentum during rampup.

    Transport: the dgc_momentum op provides the compression/correction
    SEMANTICS; where the bytes go depends on the tier. In-mesh data
    parallelism stays a dense XLA allreduce — over ICI the dense
    collective is bandwidth-cheap and compression would only add
    latency. For the slow tier (PS/DCN) the framework PROVIDES the
    sparse exchange primitive ``PSClient.dgc_allreduce`` (O(k) wire
    bytes both ways, index-hash sharded lockstep rounds on the PS;
    tests/test_transpiler.py::test_dgc_sparse_transport) — a PS-mode
    training loop opts in by exchanging its top-k through it; the
    transpiler's default dense send/recv path is unchanged."""
    type = "dgc_momentum"

    def __init__(self, learning_rate, momentum=0.9,
                 rampup_begin_step=0, rampup_step=1,
                 sparsity=(0.999,), use_nesterov=False, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._momentum = momentum
        self._rampup_begin_step = float(rampup_begin_step)
        # keep-ratio = 1 - sparsity (reference ramps through the tuple;
        # the terminal sparsity governs steady state)
        self._ratio = 1.0 - float(sparsity[-1])
        self._use_nesterov = use_nesterov

    def _attrs(self):
        return {"mu": self._momentum, "ratio": self._ratio,
                "rampup_begin_step": self._rampup_begin_step,
                "use_nesterov": self._use_nesterov}

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("u_acc", p)
            self._add_accumulator("v_acc", p)
            self._add_accumulator("dgc_step", p, shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        u = self._get_accumulator("u_acc", p)
        v = self._get_accumulator("v_acc", p)
        st = self._get_accumulator("dgc_step", p)
        return block.append_op(
            type="dgc_momentum",
            inputs={"Param": [p], "Grad": [g], "U": [u], "V": [v],
                    "CurrentStep": [st], "LearningRate": [self._lr_var]},
            outputs={"ParamOut": [p.name], "UOut": [u.name],
                     "VOut": [v.name], "CurrentStepOut": [st.name]},
            attrs=self._attrs())

    def _eager_acc_specs(self):
        return (("U", "UOut", 0.0, False), ("V", "VOut", 0.0, False),
                ("CurrentStep", "CurrentStepOut", 0.0, True))

    def _eager_attrs(self):
        return self._attrs()


class LocalSGDOptimizer(Optimizer):
    """Local SGD (reference fleet meta_optimizers/localsgd_optimizer.py):
    workers step independently for k_steps, then average parameters
    across the data-parallel world. The averaging runs through the eager
    collective tier (multi-process regime); in single-process mesh DP
    params are replicated and the average is an identity — gradients are
    already synced every step, so plain training semantics hold."""

    def __init__(self, inner_optimizer, k_steps=1, begin_step=1):
        inner = inner_optimizer
        super().__init__(getattr(inner, "_learning_rate", 0.001),
                         parameter_list=getattr(inner, "_parameter_list",
                                                None))
        self.inner_optimizer = inner
        self.k_steps = int(k_steps)
        self.begin_step = int(begin_step)
        self._step_count = 0

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        res = self.inner_optimizer.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        if in_dygraph_mode():
            self._step_count += 1
            if (self._step_count >= self.begin_step
                    and self._step_count % self.k_steps == 0):
                self._sync_params(parameter_list
                                  or self.inner_optimizer._parameter_list)
        else:
            self._append_sync_ops(res[1] if isinstance(res, tuple)
                                  else None)
        return res

    def step(self):
        self.minimize(None)

    def clear_grad(self):
        if hasattr(self.inner_optimizer, "clear_grad"):
            self.inner_optimizer.clear_grad()

    def _sync_params(self, params):
        from ..distributed import collective as C
        from ..distributed.env import get_world_size
        world = get_world_size()
        if world <= 1 or not params:
            return
        import jax.numpy as jnp
        for p in params:
            avg = C.all_reduce(p._value)  # eager multi-process allreduce
            val = avg._value if hasattr(avg, "_value") else avg
            p._set_value(jnp.asarray(val) / float(world))

    def _append_sync_ops(self, params_grads):
        """Static path: blend each param toward the world average on every
        k-th step (mask computed from a step counter; the allreduce is an
        identity when params are mesh-replicated)."""
        if not params_grads:
            return
        block = default_main_program().current_block()
        helper = LayerHelper("localsgd")
        step = helper.create_global_variable(
            name=unique_name.generate("localsgd_step"), shape=[1],
            dtype="float32", persistable=True, value=0.0)
        block.append_op(type="increment", inputs={"X": [step]},
                        outputs={"Out": [step.name]}, attrs={"step": 1.0})
        for p, _g in params_grads:
            block.append_op(
                type="localsgd_sync", inputs={"Param": [p], "Step": [step]},
                outputs={"ParamOut": [p.name]},
                attrs={"k_steps": self.k_steps,
                       "begin_step": self.begin_step})


class ModelAverage:
    """Parameter averaging (reference optimizer.py:4228 ModelAverage +
    operators/optimizers/average_accumulates_op): every executor step the
    in-graph average_accumulates ops add the current params into running
    sums; `apply(exe)` swaps params to sum/num_accumulates inside the
    scope (with restore on exit). Construct AFTER the training optimizer's
    minimize so the accumulate ops land behind the update ops.

    The reference rotates three window sums (sum_1..3) on
    max_average_window; this build keeps one running window — the
    average over the whole accumulation span."""

    def __init__(self, average_window_rate=0.15, min_average_window=10000,
                 max_average_window=10000):
        if in_dygraph_mode():
            raise NotImplementedError(
                "ModelAverage is a static-graph tool; dygraph training "
                "uses ExponentialMovingAverage")
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self._avg_vars = []  # (param, sums..., counters...)
        block = default_main_program().global_block()
        helper = LayerHelper("model_average")

        def gvar(pname, suffix, shape, value=0.0):
            return helper.create_global_variable(
                name=unique_name.generate(f"{pname}_{suffix}"),
                shape=shape, dtype="float32", persistable=True,
                value=value)

        for p in block.all_parameters():
            s1 = gvar(p.name, "sum_1", list(p.shape))
            s2 = gvar(p.name, "sum_2", list(p.shape))
            s3 = gvar(p.name, "sum_3", list(p.shape))
            na = gvar(p.name, "num_accumulates", [1])
            ona = gvar(p.name, "old_num_accumulates", [1])
            nu = gvar(p.name, "num_updates", [1])
            block.append_op(
                type="average_accumulates",
                inputs={"param": [p], "in_sum_1": [s1], "in_sum_2": [s2],
                        "in_sum_3": [s3], "in_num_accumulates": [na],
                        "in_old_num_accumulates": [ona],
                        "in_num_updates": [nu]},
                outputs={"out_sum_1": [s1.name], "out_sum_2": [s2.name],
                         "out_sum_3": [s3.name],
                         "out_num_accumulates": [na.name],
                         "out_old_num_accumulates": [ona.name],
                         "out_num_updates": [nu.name]},
                attrs={"average_window": float(average_window_rate),
                       "min_average_window": int(min_average_window),
                       "max_average_window": int(max_average_window)})
            self._avg_vars.append((p, s1, s2, s3, na, ona))

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        """Swap params to their accumulated average inside the scope."""
        from .executor import global_scope
        import jax.numpy as jnp
        scope = global_scope()
        backup = {}
        for p, s1, s2, s3, na, ona in self._avg_vars:
            cur = scope.find_var(p.name)
            if cur is None:
                continue
            backup[p.name] = cur
            sums = (jnp.asarray(scope.find_var(s1.name))
                    + jnp.asarray(scope.find_var(s2.name))
                    + jnp.asarray(scope.find_var(s3.name)))
            n = (float(np.ravel(np.asarray(scope.find_var(na.name)))[0])
                 + float(np.ravel(np.asarray(scope.find_var(ona.name)))[0]))
            if n > 0:
                scope.set(p.name, (sums / n).astype(jnp.asarray(cur).dtype))
        try:
            yield
        finally:
            if need_restore:
                for name, val in backup.items():
                    scope.set(name, val)

    def restore(self, executor=None):
        pass  # restore happens on apply() context exit


# 2.0-style short aliases
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
RMSProp = RMSPropOptimizer
Adadelta = AdadeltaOptimizer
Lamb = LambOptimizer
Ftrl = FtrlOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
