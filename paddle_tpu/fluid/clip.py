"""Gradient clipping (reference python/paddle/fluid/clip.py)."""
from __future__ import annotations

from .layer_helper import LayerHelper
from . import layers

__all__ = ["GradientClipByValue", "GradientClipByNorm",
           "GradientClipByGlobalNorm", "ClipGradByValue", "ClipGradByNorm",
           "ClipGradByGlobalNorm"]


class GradientClipBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class GradientClipByValue(GradientClipBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        res = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                res.append((p, g))
                continue
            res.append((p, layers.clip(g, self.min, self.max)))
        return res


class GradientClipByNorm(GradientClipBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        res = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                res.append((p, g))
                continue
            res.append((p, layers.clip_by_norm(g, self.clip_norm)))
        return res


class GradientClipByGlobalNorm(GradientClipBase):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def __call__(self, params_grads):
        helper = LayerHelper("global_norm_clip")
        sq_sums = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                continue
            sq = helper.create_variable_for_type_inference(g.dtype)
            helper.append_op(type="squared_l2_norm", inputs={"X": [g]},
                             outputs={"Out": [sq]})
            sq_sums.append(sq)
        if not sq_sums:
            return params_grads
        total = layers.sums(sq_sums) if len(sq_sums) > 1 else sq_sums[0]
        global_norm = helper.create_variable_for_type_inference("float32")
        helper.append_op(type="sqrt", inputs={"X": [total]},
                         outputs={"Out": [global_norm]})
        max_norm = layers.fill_constant([1], "float32", self.clip_norm)
        denom = layers.elementwise_max(global_norm, max_norm)
        scale = layers.elementwise_div(max_norm, denom)
        res = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                res.append((p, g))
                continue
            res.append((p, layers.elementwise_mul(g, scale)))
        return res


# 2.0 aliases
ClipGradByValue = GradientClipByValue
ClipGradByNorm = GradientClipByNorm
ClipGradByGlobalNorm = GradientClipByGlobalNorm
