"""Executor: lowers a whole Program block to ONE jitted XLA computation.

The reference Executor is a per-op interpreter — `for op in ops: op->Run`
(/root/reference/paddle/fluid/framework/executor.cc:476), with kernel choice,
data transfer and shape inference on every step. On TPU that loop is the
enemy: instead we trace the Block once with jax (each op's registered compute
fn), `jit` the result, and let XLA fuse/schedule. Parameter updates become
functional: updated persistables are returned from the jitted step and
donated, so optimizer ops get in-place semantics without a mutable Scope on
device (replaces inplace_op_inference.h behaviors).

Public surface mirrors reference python/paddle/fluid/executor.py:474,915
(`Executor(place).run(program, feed, fetch_list, ...)`).
"""
from __future__ import annotations

import logging
import warnings
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..observability import (flight as _flight, perf as _perf,
                             registry as _obs)
from . import core, registry
from .framework import Block, Program, Variable, default_main_program
from .scope import Scope, global_scope

logger = logging.getLogger(__name__)

# executor telemetry: the compile cache is the recompile-storm tripwire
# — a rising miss rate with a flat run rate means feed shapes/structure
# keys churn (Operator Fusion in XLA, PAPERS.md) and every miss pays a
# full XLA compile
_EXEC_RUNS = _obs.counter(
    "paddle_tpu_executor_runs_total",
    "Executor.run invocations (one fused XLA step each)")
_EXEC_CACHE_HITS = _obs.counter(
    "paddle_tpu_executor_cache_hits_total",
    "run() served by an already-compiled program signature")
_EXEC_COMPILES = _obs.counter(
    "paddle_tpu_executor_compiles_total",
    "new program signatures traced+jitted (cache misses)")
_EXEC_RUN_SECONDS = _obs.histogram(
    "paddle_tpu_executor_run_seconds",
    "wall time of Executor.run (incl. compile on a miss)")

__all__ = ["Executor", "ExecContext", "global_scope", "scope_guard"]

from .scope import scope_guard  # re-export for API parity


class ExecContext:
    """Per-trace context handed to op compute fns.

    Carries the step RNG key (rng streams are derived per-op via fold_in on
    the op's stable `_rng_id`, so fwd and auto-vjp grad ops see identical
    randomness — the mask-saving trick of the reference's dropout grad for
    free), test/train mode, and a re-entrant block runner for control flow.
    """

    def __init__(self, rng_key, is_test: bool = False, executor=None):
        self.rng_key = rng_key
        self.is_test = is_test
        self.executor = executor
        self.mesh = None  # set by distributed executors

    def rng(self, attrs: dict):
        rid = attrs.get("_rng_id", 0)
        return jax.random.fold_in(self.rng_key, rid)

    def exec_block(self, block: Block, env: dict) -> dict:
        return trace_block(block, env, self)


def _env_get(env: dict, name: str):
    try:
        return env[name]
    except KeyError:
        raise RuntimeError(
            f"variable {name!r} is not initialised — feed it, produce it with "
            f"an op, or run the startup program first") from None


def trace_block(block: Block, env: dict, ctx: ExecContext,
                ops=None) -> dict:
    """Symbolically run every op of `block` (or the `ops` subset) against
    `env` (name -> value)."""
    for i, op in enumerate(block.ops if ops is None else ops):
        opdef = registry.require(op.type)
        ins = {slot: [_env_get(env, n) for n in names]
               for slot, names in op.inputs.items()}
        scope_name = op.attrs.get("name_scope") or op.type
        try:
            with jax.named_scope(scope_name.replace("/", ".") or op.type):
                outs = opdef.compute(ctx, ins, op.attrs)
        except (RuntimeError, ValueError, TypeError, IndexError) as e:
            from .errors import wrap_op_error
            shapes = {slot: [getattr(v, "shape", None) for v in vals]
                      for slot, vals in ins.items()}
            raise wrap_op_error(e, op.type, i,
                                extra=f"input shapes {shapes}:") from e
        for slot, names in op.outputs.items():
            vals = outs.get(slot) or []
            for name, val in zip(names, vals):
                if val is not None and name != "@EMPTY@":
                    env[name] = val
    return env


def _analyze_ops(ops):
    """Find names read before written (external inputs) and all writes."""
    written: set[str] = set()
    ext_reads: set[str] = set()

    def visit(op_list):
        for op in op_list:
            for n in op.input_arg_names:
                if n not in written:
                    ext_reads.add(n)
            for v in op.attrs.values():
                if isinstance(v, Block):
                    visit(v.ops)  # conservative: sub-block reads count here
            for n in op.output_arg_names:
                written.add(n)

    visit(ops)
    return ext_reads, written


def _block_reads(block: Block) -> set[str]:
    reads: set[str] = set()

    def visit(b):
        for op in b.ops:
            reads.update(op.input_arg_names)
            for v in op.attrs.values():
                if isinstance(v, Block):
                    visit(v)

    visit(block)
    return reads


def _prune_to_fetch(program: Program, fetch_names):
    """Backward slice: keep only ops whose outputs (transitively) feed a
    fetch target (reference framework/prune.h + Executor use_prune).
    Fetching only `loss` from a program that also contains optimizer ops
    skips the parameter updates, like the reference."""
    needed = set(fetch_names)
    keep: list = []
    for op in reversed(list(program.global_block().ops)):
        if set(op.output_arg_names) & needed:
            keep.append(op)
            needed.update(op.input_arg_names)
            for v in op.attrs.values():
                if isinstance(v, Block):
                    needed.update(_block_reads(v))
    keep.reverse()
    return keep


class Executor:
    """Reference executor.py:474 — but `run` compiles, caches and launches a
    single XLA computation per (program-structure, arg-signature)."""

    def __init__(self, place: core.Place | None = None):
        self.place = place or core.default_place()
        self._cache: dict[tuple, Any] = {}
        self._run_counter = 0
        # perf plane: compile misses time the first (compiling) call and
        # register the program's XLA cost; steady-state runs are fenced
        # and decomposed only when the sampler fires
        self._compile_missed = False
        self._perf_sampler = _perf.StepSampler("executor")
        self._perf_flops: dict[str, float] = {}

    # -- public API --------------------------------------------------------
    def run(self, program: Program | None = None, feed: dict | None = None,
            fetch_list: Sequence | None = None, scope: Scope | None = None,
            return_numpy: bool = True, use_program_cache: bool = True,
            use_prune: bool = False):
        import time as _time
        _EXEC_RUNS.inc()
        t0 = _time.perf_counter()
        try:
            return self._run_impl(program, feed, fetch_list, scope,
                                  return_numpy, use_program_cache,
                                  use_prune)
        finally:
            _EXEC_RUN_SECONDS.observe(_time.perf_counter() - t0)

    def _run_impl(self, program, feed, fetch_list, scope, return_numpy,
                  use_program_cache, use_prune):
        import time as _time
        t_host0 = _time.perf_counter()
        program = program if program is not None else default_main_program()
        # CompiledProgram.with_data_parallel → batch-axis sharding over the
        # mesh (replaces reference ParallelExecutor, parallel_executor.cc:443)
        if hasattr(program, "_program"):  # CompiledProgram wrapper
            program = program._program
        feed = dict(feed or {})
        scope = scope or global_scope()
        fetch_names = [v.name if isinstance(v, Variable) else str(v)
                       for v in (fetch_list or [])]

        # pserver programs run host-side: a blocking service loop has no
        # place inside a traced computation (reference executor runs
        # listen_and_serv the same way)
        for op in program.global_block().ops:
            if op.type == "listen_and_serv":
                from .ops.ps_ops import run_listen_and_serv
                run_listen_and_serv(op)
                return []

        run_ops = None
        if use_prune:
            # cached like _analysis_cache: pruning + analysis are O(#ops)
            # python per call otherwise
            pc = getattr(program, "_prune_cache", None)
            if pc is None:
                pc = program._prune_cache = {}
            key = tuple(fetch_names)
            if key not in pc:
                run_ops = _prune_to_fetch(program, fetch_names)
                ext_reads, written = _analyze_ops(run_ops)
                persistable = {v.name for v in program.list_vars()
                               if v.persistable}
                pc[key] = (run_ops, ext_reads, written, persistable,
                           (program._structure_key(), "prune", key))
            run_ops, ext_reads, written, persistable, skey = pc[key]
        else:
            if program._analysis_cache is None:
                ext_reads, written = _analyze_ops(
                    program.global_block().ops)
                persistable = {v.name for v in program.list_vars()
                               if v.persistable}
                program._analysis_cache = (ext_reads, written, persistable,
                                           program._structure_key())
            ext_reads, written, persistable, skey = \
                program._analysis_cache

        feed_names = sorted(feed)
        # persistables the computation must read from the scope
        ro_names, upd_names = [], []
        for n in sorted(persistable):
            is_input = n in ext_reads and n not in feed
            is_output = n in written
            if not is_input and not is_output:
                continue
            if is_output:
                upd_names.append(n)
            elif is_input:
                ro_names.append(n)
        # updated vars that are also read need their current value too
        upd_in_names = [n for n in upd_names if n in ext_reads]

        missing = [n for n in ext_reads - set(feed)
                   if n in persistable and not scope.has(n)]
        if missing:
            raise RuntimeError(
                f"persistable vars {missing[:8]} not found in scope — run the "
                f"startup program first")

        feed_vals = []
        for n in feed_names:
            var = program.global_block()._var_recursive(n)
            dtype = var.dtype if var is not None and var.dtype else None
            val = _to_array(feed[n], dtype)
            if var is not None and var.shape is not None:
                declared = var.shape
                ok = len(declared) == len(val.shape) and all(
                    d < 0 or d == s for d, s in zip(declared, val.shape))
                if not ok:
                    raise ValueError(
                        f"feed {n!r} has shape {tuple(val.shape)} but the "
                        f"graph declares {tuple(declared)}")
            feed_vals.append(val)

        upd_in_vals = [scope.find_var(n) for n in upd_in_names]
        ro_vals = [scope.find_var(n) for n in ro_names]

        mesh = self._mesh_for(program)
        if mesh is not None:
            feed_vals = [self._shard_batch(v, mesh) for v in feed_vals]

        fn = self._compile(program, skey, feed_names, feed_vals, ro_names,
                           ro_vals, upd_names, upd_in_names, upd_in_vals,
                           fetch_names, mesh, run_ops)

        self._run_counter += 1
        seed = np.uint32(
            (program.random_seed * 1000003 + self._run_counter) & 0xFFFFFFFF
            if program.random_seed
            else np.random.randint(0, 2**31))
        miss = self._compile_missed
        sample = (not miss) and self._perf_sampler.tick()
        ckey = None
        if miss or sample:
            ckey = _cost_key(feed_names, feed_vals, program._is_test)
        if miss:
            # lowering is abstract and rides the path that pays the
            # compile anyway; the buffers are still valid pre-call
            fl = _perf.register_jit_cost(
                "executor", ckey, fn, tuple(upd_in_vals), tuple(ro_vals),
                tuple(feed_vals), seed)
            if fl:
                self._perf_flops[ckey] = fl
        t_disp0 = _time.perf_counter()
        fetches, updates = fn(tuple(upd_in_vals), tuple(ro_vals),
                              tuple(feed_vals), seed)
        if miss or sample:
            t_disp1 = _time.perf_counter()
            jax.block_until_ready((fetches, updates))
            t_dev = _time.perf_counter()
            if miss:
                _perf.note_compile_seconds("executor", t_dev - t_disp0)
            else:
                fl = self._perf_flops.get(ckey)
                if fl:
                    _perf.set_mfu("executor",
                                  _perf.mfu(fl, t_dev - t_disp0))
        for n, v in zip(upd_names, updates):
            scope.set(n, v)
        if core.get_flags("FLAGS_benchmark")["FLAGS_benchmark"]:
            jax.block_until_ready(fetches)
        if core.get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"]:
            # post-step sweep over fetches + updated persistables (the
            # whole block is ONE fused computation, so the reference's
            # per-op sweep maps to a per-step output sweep; for op-level
            # isolation run dygraph eager where the tracer checks per op)
            floats = [(n, v) for n, v in
                      list(zip(fetch_names, fetches))
                      + list(zip(upd_names, updates))
                      if jnp.issubdtype(jnp.result_type(v), jnp.floating)]
            # one stacked device reduction + one host read, not one blocked
            # fetch per var (~100 ms each through the TPU tunnel)
            if floats:
                flags = core.batched_to_numpy([jnp.stack(
                    [jnp.all(jnp.isfinite(v)) for _, v in floats])])[0]
                bad = [n for (n, _), ok in zip(floats, flags) if not ok]
            else:
                bad = []
            if bad:
                raise RuntimeError(
                    f"NaN/Inf detected in {bad[:8]} after executor step "
                    f"(FLAGS_check_nan_inf)")
        if not sample:
            if return_numpy:
                return core.batched_to_numpy(fetches)
            return list(fetches)
        # sampled run: close the breakdown with the host->numpy copy as
        # the transfer phase (zero when the caller keeps device arrays)
        t_tr0 = _time.perf_counter()
        out = core.batched_to_numpy(fetches) if return_numpy \
            else list(fetches)
        _perf.record_breakdown("executor", {
            "host": t_disp0 - t_host0,
            "dispatch": t_disp1 - t_disp0,
            "device": t_dev - t_disp1,
            "transfer": (_time.perf_counter() - t_tr0)
            if return_numpy else 0.0,
        })
        return out

    # -- data-parallel sharding --------------------------------------------
    def _mesh_for(self, program):
        """Mesh when the program is marked data-parallel. Grad allreduce is
        implicit: batch-sharded inputs make XLA insert the psum in the
        sharded backward (replaces details/all_reduce_op_handle.cc).
        When `strategy.tensor_parallel` set a tp degree, the mesh gains a
        "tp" axis and persistables matching the strategy's sharding_rules
        are partitioned over it (GSPMD tensor parallelism — fresh design,
        absent in reference per SURVEY §2.9)."""
        info = getattr(program, "_sharding_info", None)
        if not info:
            return None
        import jax
        if len(jax.devices()) <= 1:
            return None
        tp = int(info.get("tp") or 1)
        if tp > 1:
            from ..distributed.mesh import make_mesh
            return make_mesh({"dp": -1, "tp": tp})
        from ..distributed.mesh import default_mesh
        return default_mesh()

    @staticmethod
    def _param_sharding(name, mesh, info, shape=None):
        """Resolve a persistable's NamedSharding from the strategy's
        tensor-parallel rules; default replicated. A matching rule is
        applied only where it fits the value: optimizer accumulators
        inherit their param's name prefix (fc_0.w_0_beta1_pow_acc_0), so a
        spec with more dims than the value is ignored (scalar beta-pows
        stay replicated, same-shaped moments pick up the param's sharding),
        and spec axes that don't divide the dim are dropped."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        if info and info.get("tp_rules"):
            from ..parallel.sharding import ShardingRules
            rules = ShardingRules(
                [(pat, P(*spec)) for pat, spec in info["tp_rules"]])
            spec = rules.spec(name, mesh)
            if shape is not None:
                if len(spec) > len(shape):
                    spec = P()
                else:
                    def fits(i, entry):
                        axes = entry if isinstance(entry, (tuple, list)) \
                            else (entry,)
                        size = int(np.prod([mesh.shape[a] for a in axes]))
                        return shape[i] % size == 0
                    spec = P(*(e if e is None or fits(i, e) else None
                               for i, e in enumerate(spec)))
            return NamedSharding(mesh, spec)
        return NamedSharding(mesh, P())

    @staticmethod
    def _val_sharding(val, mesh):
        from jax.sharding import NamedSharding, PartitionSpec as P
        ndev = mesh.shape["dp"]
        if getattr(val, "ndim", 0) >= 1 and val.shape[0] % ndev == 0:
            return NamedSharding(mesh, P("dp"))
        return NamedSharding(mesh, P())

    @classmethod
    def _shard_batch(cls, val, mesh):
        import jax
        return jax.device_put(val, cls._val_sharding(val, mesh))

    # -- compilation -------------------------------------------------------
    def _compile(self, program, skey, feed_names, feed_vals, ro_names,
                 ro_vals, upd_names, upd_in_names, upd_in_vals, fetch_names,
                 mesh=None, run_ops=None):
        sig = (
            skey,
            None if mesh is None else tuple(mesh.shape.items()),
            repr((getattr(program, "_sharding_info", None) or {})
                 .get("tp_rules")),
            tuple(ro_names), tuple(upd_names), tuple(upd_in_names),
            tuple(fetch_names),
            tuple((n, v.shape, str(jnp.result_type(v)))
                  for n, v in zip(feed_names, feed_vals)),
            tuple((v.shape, str(jnp.result_type(v)))
                  for v in list(upd_in_vals) + list(ro_vals)),
            program._is_test,
        )
        fn = self._cache.get(sig)
        if fn is not None:
            self._cache[sig] = self._cache.pop(sig)  # refresh LRU order
            _EXEC_CACHE_HITS.inc()
            self._compile_missed = False
            return fn
        _EXEC_COMPILES.inc()
        self._compile_missed = True
        # one flight event per cache miss: a burst of these in a
        # postmortem ring IS a recompile storm (feed shapes/structure
        # churning), with the feed shapes as the evidence
        _flight.record("executor", "compile",
                       feeds=[[n, list(v.shape)] for n, v
                              in zip(feed_names, feed_vals)],
                       cache_size=len(self._cache))

        is_test = program._is_test
        gb = program.global_block()

        def step(upd_in, ro, feeds, seed):
            env: dict[str, Any] = {}
            env.update(zip(upd_in_names, upd_in))
            env.update(zip(ro_names, ro))
            env.update(zip(feed_names, feeds))
            ctx = ExecContext(jax.random.PRNGKey(seed), is_test=is_test,
                              executor=self)
            trace_block(gb, env, ctx, ops=run_ops)
            fetches = tuple(_env_get(env, n) for n in fetch_names)
            updates = tuple(env[n] for n in upd_names)
            return fetches, updates

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # cpu donation warnings
            if mesh is None:
                fn = jax.jit(step, donate_argnums=(0,))
            else:
                # params/state replicated unless a tensor-parallel rule
                # matches; fetches replicated; the batch stays sharded
                # inside, grads psum automatically
                from jax.sharding import NamedSharding, PartitionSpec as P
                info = getattr(program, "_sharding_info", None)
                repl = NamedSharding(mesh, P())
                shapes = {n: getattr(v, "shape", None)
                          for n, v in list(zip(upd_in_names, upd_in_vals))
                          + list(zip(ro_names, ro_vals))}
                psh = {n: self._param_sharding(n, mesh, info,
                                               shapes.get(n))
                       for n in set(upd_in_names) | set(ro_names)
                       | set(upd_names)}
                fn = jax.jit(
                    step, donate_argnums=(0,),
                    in_shardings=(
                        tuple(psh[n] for n in upd_in_names),
                        tuple(psh[n] for n in ro_names),
                        tuple(self._val_sharding(v, mesh)
                              for v in feed_vals),
                        None),
                    out_shardings=(tuple(repl for _ in fetch_names),
                                   tuple(psh[n] for n in upd_names)))
        cap = core.get_flags(
            "FLAGS_jit_cache_size")["FLAGS_jit_cache_size"]
        while self._cache and len(self._cache) >= cap:
            self._cache.pop(next(iter(self._cache)))  # evict oldest (LRU)
        if cap > 0:
            self._cache[sig] = fn
        return fn

    # -- dataset training ---------------------------------------------------
    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           fetch_handler=None):
        """Run the program over every Dataset batch (reference
        executor.py:1597 → C++ MultiTrainer/HogwildWorker loop,
        trainer.h:85, device_worker.h:215). Here the dataset's reader
        threads keep the input queue full while one device loop feeds the
        single fused XLA step; `thread` is accepted for API parity and
        routed to the dataset's reader pool."""
        if dataset is None:
            raise ValueError("train_from_dataset needs a dataset")
        if thread:
            dataset.set_thread(thread)
        fetch_list = fetch_list or []
        fetch_info = fetch_info or [getattr(v, "name", str(v))
                                    for v in fetch_list]
        last = None
        for step_i, feed in enumerate(dataset.batch_iter()):
            res = self.run(program, feed=feed, fetch_list=fetch_list,
                           scope=scope)
            last = res
            if print_period and (step_i + 1) % print_period == 0:
                if fetch_list:
                    msg = ", ".join(
                        f"{n}={np.ravel(np.asarray(v))[0]:.6f}"
                        for n, v in zip(fetch_info, res))
                    print(f"[train_from_dataset] step {step_i + 1}: "
                          f"{msg}", flush=True)
                # fetch_handler fires on the period regardless of
                # fetch_list (reference FetchHandler runs independently
                # of printing)
                if fetch_handler is not None:
                    fetch_handler(res)
        return last

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           fetch_handler=None):
        """Like train_from_dataset but for test-mode programs (reference
        executor.py:1476)."""
        return self.train_from_dataset(program, dataset, scope, thread,
                                       debug, fetch_list, fetch_info,
                                       print_period, fetch_handler)

    def close(self):
        self._cache.clear()


def _cost_key(feed_names, feed_vals, is_test: bool) -> str:
    """Deterministic low-cardinality cost-registry key for a compiled
    program signature: mode + the first few feed shapes (what actually
    distinguishes compile buckets in practice)."""
    feeds = ";".join(
        f"{n}{'x'.join(map(str, v.shape)) or 'scalar'}"
        for n, v in list(zip(feed_names, feed_vals))[:4])
    return f"{'test' if is_test else 'train'}[{feeds}]"


def _to_array(x, dtype=None):
    if hasattr(x, "dtype") and not isinstance(x, np.ndarray):
        return x  # already a device array / Tensor value
    arr = np.asarray(x)
    if dtype is not None and arr.dtype != np.dtype(dtype):
        arr = arr.astype(dtype)
    return jnp.asarray(arr)
