"""Graph-level autodiff: append gradient ops to a Program.

Parity with the reference's `append_backward`
(/root/reference/python/paddle/fluid/backward.py:1215): walk ops in reverse,
ask each op's grad maker (registry.make_default_grad_ops ==
core.get_grad_op_desc at backward.py:924) to emit grad ops into the SAME
block, sum-accumulate fan-out gradients, honour stop_gradient/no_grad_set.

TPU-native simplification: we emit gradients for every ancestor of the loss —
unused grad ops are dead code that XLA eliminates inside the jitted step, so
the reference's pruning bookkeeping buys nothing here.
"""
from __future__ import annotations

import warnings

from . import registry
from .framework import (GRAD_SUFFIX, Operator, Parameter, Program, Variable,
                        grad_var_name)

__all__ = ["append_backward", "gradients"]


def _differentiable_ancestors(block, loss_name: str, no_grad: set[str]):
    """Vars that influence the loss through differentiable ops."""
    producers: dict[str, list[Operator]] = {}
    for op in block.ops:
        for n in op.output_arg_names:
            producers.setdefault(n, []).append(op)
    need = {loss_name}
    # iterate to fixpoint over reverse order (block is topologically ordered,
    # one reverse sweep suffices)
    for op in reversed(block.ops):
        if not any(n in need for n in op.output_arg_names):
            continue
        opdef = registry.lookup(op.type)
        if opdef is None or opdef.grad is None:
            continue
        for slot, names in op.inputs.items():
            if slot in opdef.no_grad_slots:
                continue
            for n in names:
                v = block._var_recursive(n)
                if v is not None and v.stop_gradient:
                    continue
                if n in no_grad:
                    continue
                need.add(n)
    return need


def _plan_recompute_segments(fwd_ops, checkpoints):
    """Index ranges [(start, end)] of forward ops to recompute, delimited by
    checkpoint vars (reference _append_backward_ops_with_checkpoints_,
    backward.py:629). Ops after the last checkpoint stay un-recomputed —
    their activations are immediately consumed by the first grad ops."""
    names = [c.name if isinstance(c, Variable) else str(c)
             for c in checkpoints]
    idxs = set()
    for name in names:
        prod = [i for i, op in enumerate(fwd_ops)
                if name in op.output_arg_names]
        if prod:
            idxs.add(max(prod))
    segments = []
    start = 0
    for i in sorted(idxs):
        if i >= start:
            segments.append((start, i))
            start = i + 1
    return segments


def append_backward(loss: Variable, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Append grad ops for `loss`; returns [(param, grad_var)].

    With `checkpoints`, forward segments between checkpoint vars are
    RE-EMITTED into the backward region (fresh @RC names, inputs routed
    through `recompute_barrier` so XLA CSE cannot merge them with the
    original forward) and each segment's grad ops consume the recomputed
    activations — true rematerialisation at the Program level, mirroring the
    reference's checkpoint-aware backward (backward.py:629). Layer-level
    remat for the functional path lives in paddle_tpu.distributed.recompute.
    """
    block = loss.block
    program = block.program
    no_grad = set()
    for item in (no_grad_set or ()):
        no_grad.add(item.name if isinstance(item, Variable) else str(item))

    need = _differentiable_ancestors(block, loss.name, no_grad)

    loss_idx = max(i for i, op in enumerate(block.ops)
                   if loss.name in op.output_arg_names) \
        if any(loss.name in op.output_arg_names for op in block.ops) else \
        len(block.ops) - 1

    # Seed d(loss)/d(loss) = 1
    loss_grad = grad_var_name(loss.name)
    block.append_op(
        type="fill_constant",
        outputs={"Out": [loss_grad]},
        attrs={"shape": list(loss.shape or [1]), "value": 1.0,
               "dtype": loss.dtype or "float32"})
    written = {loss_grad: 1}

    def emit(type, inputs, outputs, attrs):
        # fan-out accumulation: second writer of X@GRAD gets renamed and summed
        renames = []
        new_outputs = {}
        for slot, names in outputs.items():
            fixed = []
            for n in names:
                if n == "@EMPTY@":  # pruned stop-gradient slot entry
                    fixed.append(n)
                    continue
                if n in written:
                    rn = f"{n}@RENAME@{written[n]}"
                    written[n] += 1
                    renames.append((n, rn))
                    fixed.append(rn)
                else:
                    written[n] = 1
                    fixed.append(n)
            new_outputs[slot] = fixed
        block.append_op(type=type, inputs=inputs, outputs=new_outputs,
                        attrs=attrs)
        for orig, rn in renames:
            block.append_op(type="sum", inputs={"X": [orig, rn]},
                            outputs={"Out": [orig]})

    def emit_grads_for(orig_op, grad_src_op):
        """Emit grad ops for `orig_op` (need/registry gating on its original
        names) reading forward values from `grad_src_op` (== orig_op, or its
        @RC re-emission)."""
        if not any(n in need for n in orig_op.output_arg_names):
            return
        opdef = registry.lookup(orig_op.type)
        if opdef is None or opdef.grad is None:
            return
        # zero-fill upstream grads that nothing produced (reference
        # fill_zeros_like insertion)
        for slot, names in grad_src_op.outputs.items():
            if slot in opdef.no_grad_out_slots:
                continue
            for n in names:
                gn = grad_var_name(n)
                if gn not in written:
                    block.append_op(type="fill_zeros_like",
                                    inputs={"X": [n]}, outputs={"Out": [gn]})
                    written[gn] = 1
        if opdef.grad == "auto":
            registry.make_default_grad_ops(grad_src_op, emit)
        else:
            opdef.grad(grad_src_op, emit)

    fwd_ops = list(block.ops[: loss_idx + 1])
    segments = _plan_recompute_segments(fwd_ops, checkpoints) \
        if checkpoints else []
    seg_by_end = {e: (s, e) for s, e in segments}

    def emit_recompute_segment(seg):
        s, e = seg
        seg_ops = fwd_ops[s:e + 1]
        produced = {n for op in seg_ops for n in op.output_arg_names}
        # vars the rest of the graph reads directly (checkpoints and any
        # other segment-crossing vars) — these stay live, grads arrive under
        # their canonical names
        outside = {n for op in fwd_ops[e + 1:] for n in op.input_arg_names
                   if n in produced}
        rc = {n: f"{n}@RC{s}" for n in produced}
        externals = {n for op in seg_ops for n in op.input_arg_names
                     if n not in produced}
        bmap = {}
        for n in sorted(externals):
            v = block._var_recursive(n)
            if v is not None and v.persistable:
                continue  # params stay direct reads (always live anyway)
            bn = f"{n}@RCB{s}"
            block.append_op(type="recompute_barrier", inputs={"X": [n]},
                            outputs={"Out": [bn]})
            bmap[n] = bn
        in_map = {**rc, **bmap}
        rc_ops = []
        for op in seg_ops:
            rc_ops.append(block.append_op(
                type=op.type,
                inputs={slot: [in_map.get(n, n) for n in names]
                        for slot, names in op.inputs.items()},
                outputs={slot: [rc.get(n, n) for n in names]
                         for slot, names in op.outputs.items()},
                attrs=dict(op.attrs)))  # same _rng_id → identical randomness
        # boundary grads: anything downstream (grad ops of later segments,
        # or the loss seed itself when the checkpointed var IS the loss)
        # accumulated onto the canonical n@GRAD — seed the @RC-named grad
        # from it for every produced var with a written canonical grad
        for n in sorted(produced):
            gn, rgn = grad_var_name(n), grad_var_name(rc[n])
            if gn in written and rgn not in written:
                block.append_op(type="assign", inputs={"X": [gn]},
                                outputs={"Out": [rgn]})
                written[rgn] = 1
        for op, rc_op in reversed(list(zip(seg_ops, rc_ops))):
            emit_grads_for(op, rc_op)
        # grads that flowed to barriered externals redirect to canonical
        for n, bn in sorted(bmap.items()):
            bgn = grad_var_name(bn)
            if bgn not in written:
                continue
            gn = grad_var_name(n)
            if gn in written:
                block.append_op(type="sum", inputs={"X": [gn, bgn]},
                                outputs={"Out": [gn]})
            else:
                block.append_op(type="assign", inputs={"X": [bgn]},
                                outputs={"Out": [gn]})
                written[gn] = 1

    i = loss_idx
    while i >= 0:
        seg = seg_by_end.get(i)
        if seg is not None:
            emit_recompute_segment(seg)
            i = seg[0] - 1
            continue
        emit_grads_for(fwd_ops[i], fwd_ops[i])
        i -= 1

    # collect (param, grad) pairs
    if parameter_list is not None:
        params = [block._var_recursive(p) if not isinstance(p, Variable) else p
                  for p in parameter_list]
    else:
        params = [p for p in program.all_parameters() if p.trainable]
    param_grads = []
    for p in params:
        gn = grad_var_name(p.name)
        if gn in written:
            gv = block._var_recursive(gn) or block.create_var(
                name=gn, shape=p.shape, dtype=p.dtype)
            param_grads.append((p, gv))
        elif p.name in no_grad or p.stop_gradient:
            continue
        else:
            warnings.warn(f"parameter {p.name} receives no gradient from "
                          f"{loss.name}")
    return param_grads


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Static `paddle.static.gradients` (reference backward.py:1795).

    Multiple targets (optionally weighted by target_gradients) are combined
    into one scalar sum first so gradients through shared subgraphs
    accumulate correctly in a single backward pass.
    """
    from . import layers
    from .framework import program_guard
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    tgs = list(target_gradients) if target_gradients is not None \
        else [None] * len(targets)
    block = targets[0].block
    with program_guard(block.program):
        parts = []
        for t, tg in zip(targets, tgs):
            weighted = t if tg is None else layers.elementwise_mul(t, tg)
            parts.append(layers.reduce_sum(weighted))
        combined = parts[0] if len(parts) == 1 else layers.sums(parts)
    append_backward(combined, parameter_list=[], no_grad_set=no_grad_set)
    return [block._var_recursive(grad_var_name(v.name)) for v in inputs]
