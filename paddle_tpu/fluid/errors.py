"""Error taxonomy + enforce helpers (reference paddle/fluid/platform/
enforce.h + errors.h error codes, and operator.cc's exception re-wrap
that attaches the failing op to the message).

The reference throws EnforceNotMet carrying an error code enum; here each
code is a Python exception class (all subclass EnforceNotMet, which
subclasses RuntimeError so existing `except RuntimeError` sites keep
working). `wrap_op_error` is used by the executor/tracer to prepend
[operator < type >] context to kernel failures.
"""
from __future__ import annotations

__all__ = ["EnforceNotMet", "InvalidArgumentError", "NotFoundError",
           "OutOfRangeError", "AlreadyExistsError", "PermissionDeniedError",
           "ResourceExhaustedError", "PreconditionNotMetError",
           "UnimplementedError", "UnavailableError", "FatalError",
           "ExecutionTimeoutError", "enforce", "wrap_op_error"]


class EnforceNotMet(RuntimeError):
    """Base of all framework errors (reference EnforceNotMet)."""


class InvalidArgumentError(EnforceNotMet, ValueError):
    pass


class NotFoundError(EnforceNotMet):
    pass


class OutOfRangeError(EnforceNotMet, IndexError):
    pass


class AlreadyExistsError(EnforceNotMet):
    pass


class PermissionDeniedError(EnforceNotMet):
    pass


class ResourceExhaustedError(EnforceNotMet, MemoryError):
    pass


class PreconditionNotMetError(EnforceNotMet):
    pass


class UnimplementedError(EnforceNotMet, NotImplementedError):
    pass


class UnavailableError(EnforceNotMet):
    pass


class FatalError(EnforceNotMet):
    pass


class ExecutionTimeoutError(EnforceNotMet, TimeoutError):
    pass


def enforce(cond, message="enforce failed", error_cls=InvalidArgumentError):
    """PADDLE_ENFORCE: raise a typed framework error when cond is false."""
    if not cond:
        raise error_cls(message)


def wrap_op_error(exc: BaseException, op_type: str, op_index: int = -1,
                  extra: str = ""):
    """Re-raise `exc` with operator context prepended (reference
    operator.cc:245 RunImpl catch-and-rethrow). Keeps the original type
    when it is already a framework/JAX error class; otherwise wraps into
    EnforceNotMet so callers get one catchable base."""
    loc = f"[operator < {op_type} > #{op_index}]" if op_index >= 0 \
        else f"[operator < {op_type} >]"
    msg = f"{loc} {extra + ' ' if extra else ''}{exc}"
    cls = type(exc) if isinstance(exc, EnforceNotMet) else EnforceNotMet
    new = cls(msg)
    new.__cause__ = exc
    return new
