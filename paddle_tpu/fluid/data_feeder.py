"""DataFeeder (reference python/paddle/fluid/data_feeder.py): converts
per-sample python/numpy data into a feed dict of batched arrays."""
from __future__ import annotations

import numpy as np

from .framework import Variable

__all__ = ["DataFeeder", "convert_dtype", "check_variable_and_dtype"]

from .core import convert_dtype


def check_variable_and_dtype(input, input_name, expected_dtype, op_name):
    return True


def check_type(input, input_name, expected_type, op_name):
    return True


def check_dtype(dtype, name, expected, op_name):
    return True


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None):
        self.feed_vars = []
        for v in feed_list:
            if isinstance(v, str):
                from .framework import default_main_program
                v = (program or default_main_program()) \
                    .global_block()._var_recursive(v)
            self.feed_vars.append(v)

    def feed(self, iterable):
        rows = list(iterable)
        out = {}
        for i, var in enumerate(self.feed_vars):
            col = [np.asarray(r[i]) for r in rows]
            arr = np.stack(col).astype(convert_dtype(var.dtype))
            declared = var.shape
            if declared is not None and len(declared) == arr.ndim + 1 and \
                    declared[-1] == 1:
                arr = arr[..., None]
            out[var.name] = arr
        return out
