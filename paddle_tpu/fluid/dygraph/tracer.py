"""Eager tracer + tape autograd engine.

TPU-native replacement of the reference imperative engine:
  Tracer::TraceOp      (/root/reference/paddle/fluid/imperative/tracer.cc:48)
  BasicEngine backward (/root/reference/paddle/fluid/imperative/basic_engine.cc:161)
  GradientAccumulator  (imperative/gradient_accumulator.cc)

Ops execute eagerly through the SAME registry compute fns the static executor
uses (one kernel story, two execution modes). Each op appends a tape entry;
`run_backward` walks the tape in reverse, invoking the synthesised `<op>_grad`
kernels (jax.vjp of forward) and sum-accumulating fan-in gradients.
"""
from __future__ import annotations

import dataclasses
import weakref
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .. import core, registry


def _check_nan_inf(op_type: str, out_vals: dict):
    """FLAGS_check_nan_inf per-op sweep (reference operator.cc:1056 ->
    details/nan_inf_utils_detail.*): eager values are concrete, so every
    float output is checked after the kernel; inside a jax trace the
    values are symbolic and the sweep is skipped (use the executor's
    post-step sweep / jax_debug_nans there)."""
    for slot, vals in out_vals.items():
        for v in vals:
            if v is None or isinstance(v, jax.core.Tracer) or \
                    not isinstance(v, jax.Array) or \
                    not jnp.issubdtype(v.dtype, jnp.floating):
                continue
            if not bool(jnp.all(jnp.isfinite(v))):
                raise RuntimeError(
                    f"NaN/Inf detected in output slot {slot!r} of op "
                    f"{op_type!r} (FLAGS_check_nan_inf)")
from ..registry import GRAD_SUFFIX
from .varbase import Tensor

__all__ = ["Tracer", "default_tracer", "run_backward", "trace_single",
           "no_grad_guard"]


class _EagerCtx:
    """ExecContext clone for eager mode (see executor.ExecContext)."""

    def __init__(self, rng_key, is_test=False):
        self.rng_key = rng_key
        self.is_test = is_test
        self.mesh = None

    def rng(self, attrs):
        return jax.random.fold_in(self.rng_key, attrs.get("_rng_id", 0))

    def exec_block(self, block, env):
        raise RuntimeError("control-flow sub-blocks require static graph")


@dataclasses.dataclass
class TapeEntry:
    op_type: str
    inputs: dict      # slot -> list[Tensor | None]  (strong refs)
    outputs: dict     # slot -> list[weakref.ref[Tensor] | None]
    attrs: dict
    rng_id: int

    def live_outputs(self) -> bool:
        """Whether any output tensor is still alive. Output refs are weak so
        that forwards whose results are dropped (e.g. an eval loop without
        no_grad) don't pin activations forever — the reference's refcounted
        autograd graph frees those nodes the same way; dead entries are
        pruned from the tape periodically."""
        return any(r is not None and r() is not None
                   for lst in self.outputs.values() for r in lst)

    def output_tensors(self) -> dict:
        return {slot: [None if r is None else r() for r in lst]
                for slot, lst in self.outputs.items()}


class Tracer:
    """Eager op executor + tape recorder."""

    def __init__(self, seed: int | None = None):
        # lazy key creation: building a PRNGKey initialises the jax backend,
        # which must not happen at import time (platform selection may still
        # change — e.g. tests forcing the virtual CPU mesh)
        self._seed = np.random.randint(0, 2**31) if seed is None else seed
        self._base_key_cache = None
        self._op_counter = 0
        self._tape: list[TapeEntry] = []
        self._tape_prune_at = 1024
        self._has_grad = True
        self._amp_level = 0  # set by amp_guard
        self._amp_lists = None
        self.train_mode = True

    # -- rng ---------------------------------------------------------------
    def _next_rng_id(self) -> int:
        self._op_counter += 1
        return self._op_counter

    @property
    def _base_key(self):
        if self._base_key_cache is None:
            self._base_key_cache = jax.random.PRNGKey(self._seed)
        return self._base_key_cache

    def seed(self, s: int):
        self._seed = int(s)
        self._base_key_cache = jax.random.PRNGKey(self._seed)
        # restart the per-op stream ids too: two identically-built graphs
        # after the same seed() draw identical randomness (reference
        # Generator::SetCurrentSeed resets the philox offset)
        self._op_counter = 0

    # -- op execution ------------------------------------------------------
    def trace_op(self, op_type: str, inputs: dict, outputs: dict,
                 attrs: dict | None = None, stop_gradient: bool = False):
        """Run `op_type` eagerly. `inputs`: slot -> Tensor/list[Tensor].
        `outputs`: slot -> int (how many outputs) or list of placeholders.
        Returns dict slot -> list[Tensor]."""
        attrs = dict(attrs or {})
        opdef = registry.require(op_type)
        opdef.fill_default_attrs(attrs)
        if opdef.stochastic:
            attrs["_rng_id"] = self._next_rng_id()

        in_tensors: dict[str, list] = {}
        for slot, v in inputs.items():
            if v is None:
                continue
            lst = v if isinstance(v, (list, tuple)) else [v]
            in_tensors[slot] = [t for t in lst]

        if self._amp_level:
            from ...amp.auto_cast import _autocast_inputs
            in_tensors = _autocast_inputs(op_type, in_tensors,
                                          self._amp_level)

        ins_vals = {slot: [None if t is None else t._value for t in lst]
                    for slot, lst in in_tensors.items()}
        ctx = _EagerCtx(self._base_key, is_test=not self.train_mode)
        out_vals = opdef.compute(ctx, ins_vals, attrs)

        if core.get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"]:
            _check_nan_inf(op_type, out_vals)

        out_tensors: dict[str, list] = {}
        requires_grad = (self._has_grad and not stop_gradient and
                         opdef.grad is not None and any(
                             not t.stop_gradient
                             for lst in in_tensors.values()
                             for t in lst if t is not None))
        for slot, vals in out_vals.items():
            outs = []
            for v in vals:
                if v is None:
                    outs.append(None)
                    continue
                t = Tensor(v, stop_gradient=not requires_grad)
                outs.append(t)
            out_tensors[slot] = outs

        if requires_grad:
            out_refs = {slot: [None if t is None else weakref.ref(t)
                               for t in lst]
                        for slot, lst in out_tensors.items()}
            entry = TapeEntry(op_type, in_tensors, out_refs, attrs,
                              attrs.get("_rng_id", 0))
            for lst in out_tensors.values():
                for t in lst:
                    if t is not None:
                        t._producer = entry
            self._tape.append(entry)
            if len(self._tape) >= self._tape_prune_at:
                self._prune_tape()
        return out_tensors

    def _prune_tape(self):
        """Drop entries whose outputs were all garbage-collected — they can
        never receive an upstream gradient. Live chains survive: a live
        tensor pins its producer entry's inputs (strong refs), which pin
        THEIR producers transitively."""
        self._tape = [e for e in self._tape if e.live_outputs()]
        self._tape_prune_at = max(1024, 2 * len(self._tape))

    def reset_tape(self):
        self._tape.clear()
        self._tape_prune_at = 1024


_global_tracer: Tracer | None = None


def default_tracer() -> Tracer | None:
    from .. import framework
    return framework._dygraph_tracer_


def trace_single(op_type, inputs, attrs=None, out_slot="Out"):
    tr = default_tracer()
    if tr is None:
        raise RuntimeError("not in dygraph mode")
    res = tr.trace_op(op_type, inputs, {}, attrs or {})
    return res[out_slot][0]


import contextlib


@contextlib.contextmanager
def no_grad_guard():
    tr = default_tracer()
    if tr is None:
        yield
        return
    prev = tr._has_grad
    tr._has_grad = False
    try:
        yield
    finally:
        tr._has_grad = prev


# ---------------------------------------------------------------------------
# higher-order grad: functional tape replay
# (reference imperative/partial_grad_engine.cc create_graph path)
# ---------------------------------------------------------------------------

registry.register(
    "tape_grad",
    lambda ctx, ins, attrs: {"Out": list(attrs["_fn"](
        *[v for v in ins.get("X", [])]))},
    attrs={})


def _build_replay(tr: "Tracer", entries: list, outputs: list,
                  inputs: list):
    """Pure jax function input-values -> output-values by replaying the
    (snapshotted) tape entries that depend on `inputs`. Tensors outside
    the dependency cone enter as constants; stochastic ops replay their
    recorded _rng_id, so dropout masks are bit-identical to the forward."""
    in_ids = [id(t) for t in inputs]
    ctx = _EagerCtx(tr._base_key, is_test=not tr.train_mode)

    def f(*in_vals):
        env = dict(zip(in_ids, in_vals))
        for entry in entries:
            uses = any(t is not None and id(t) in env
                       for lst in entry.inputs.values() for t in lst)
            if not uses:
                continue
            ins_vals = {
                slot: [None if t is None else env.get(id(t), t._value)
                       for t in lst]
                for slot, lst in entry.inputs.items()}
            opdef = registry.require(entry.op_type)
            out_vals = opdef.compute(ctx, ins_vals, entry.attrs)
            for slot, lst in entry.output_tensors().items():
                for t, v in zip(lst, out_vals.get(slot, [])):
                    if t is not None:
                        env[id(t)] = v
        missing = [o.name for o in outputs if id(o) not in env]
        if missing:
            raise RuntimeError(
                f"outputs {missing} do not depend on the given inputs")
        return tuple(env[id(o)] for o in outputs)

    return f


def grad_with_graph(outputs: list, inputs: list, grad_outputs=None):
    """First-order grads recorded ON the tape (create_graph=True): the
    whole vjp runs as one composite `tape_grad` op whose auto-vjp gives
    the second order — grad-of-grad is jax's vjp-of-vjp. Every trainable
    leaf the replayed subgraph touches joins the op's inputs, so a later
    backward() of the returned grads reaches model parameters (gradient
    penalties train). grad_outputs enter as constants."""
    tr = default_tracer()
    if tr is None:
        raise RuntimeError("create_graph requires dygraph mode")
    entries = list(tr._tape)  # snapshot: later ops must not leak in
    # trainable leaves of the cone (params etc.): differentiable op
    # inputs alongside the requested `inputs`
    req_ids = {id(t) for t in inputs}
    produced = {id(t) for e in entries
                for lst in e.output_tensors().values()
                for t in lst if t is not None}
    extras, seen = [], set()
    for e in entries:
        for lst in e.inputs.values():
            for t in lst:
                if t is None or t.stop_gradient:
                    continue
                tid = id(getattr(t, "_orig", t))
                t = getattr(t, "_orig", t)
                if tid in req_ids or tid in produced or tid in seen:
                    continue
                seen.add(tid)
                extras.append(t)
    all_in = list(inputs) + extras
    f = _build_replay(tr, entries, outputs, all_in)
    seeds = tuple(
        jnp.ones_like(o._value) if go is None
        else (go._value if isinstance(go, Tensor) else jnp.asarray(go))
        for o, go in zip(outputs,
                         grad_outputs or [None] * len(outputs)))
    n_req = len(inputs)

    def grad_fn(*in_vals):
        _, vjp = jax.vjp(f, *in_vals)
        return vjp(seeds)[:n_req]

    res = tr.trace_op("tape_grad", {"X": all_in}, {}, {"_fn": grad_fn})
    return res["Out"]


# ---------------------------------------------------------------------------
# backward engine
# ---------------------------------------------------------------------------

def run_backward(loss: Tensor, grad_tensor=None, retain_graph=False,
                 targets: set | None = None):
    tr = default_tracer()
    if tr is None:
        raise RuntimeError("backward() requires dygraph mode")
    if loss.stop_gradient:
        raise RuntimeError(f"{loss.name} has stop_gradient=True")

    grads: dict[int, Any] = {}  # id(Tensor) -> accumulated grad array
    seed = grad_tensor._value if isinstance(grad_tensor, Tensor) else \
        (jnp.ones_like(loss._value) if grad_tensor is None
         else jnp.asarray(grad_tensor))
    grads[id(loss)] = seed
    keep = {id(loss): loss}

    ctx = _EagerCtx(tr._base_key, is_test=not tr.train_mode)

    for entry in reversed(tr._tape):
        outputs = entry.output_tensors()
        out_has_grad = any(
            t is not None and id(t) in grads
            for lst in outputs.values() for t in lst)
        if not out_has_grad:
            continue
        opdef = registry.require(entry.op_type)
        grad_def = registry.lookup(entry.op_type + "_grad")
        # build grad-op inputs: fwd inputs + upstream out-grads
        g_ins: dict[str, list] = {}
        for slot, lst in entry.inputs.items():
            g_ins[slot] = [None if t is None else t._value for t in lst]
        for slot, lst in outputs.items():
            if slot in opdef.no_grad_out_slots:
                continue
            g_ins[slot + GRAD_SUFFIX] = [
                None if t is None else grads.get(id(t)) for t in lst]
        if grad_def is None and callable(opdef.grad):
            raise NotImplementedError(
                f"custom graph-grad op {entry.op_type} lacks eager path")
        out_grads = grad_def.compute(ctx, g_ins, entry.attrs)
        # scatter grads onto input tensors
        for slot, lst in entry.inputs.items():
            gs = out_grads.get(slot + GRAD_SUFFIX)
            if gs is None:
                continue
            for t, g in zip(lst, gs):
                if t is None or g is None or t.stop_gradient:
                    continue
                t = getattr(t, "_orig", t)  # unwrap amp cast views
                if hasattr(g, "dtype") and g.dtype != t._value.dtype:
                    g = g.astype(t._value.dtype)
                prev = grads.get(id(t))
                grads[id(t)] = g if prev is None else prev + g
                keep[id(t)] = t
                for hook in t._hooks:
                    hv = hook(Tensor(grads[id(t)], stop_gradient=True))
                    if hv is not None:
                        grads[id(t)] = hv._value if isinstance(hv, Tensor) \
                            else hv

    # deposit .grad on leaf tensors (params) and explicitly requested targets
    for tid, t in keep.items():
        if (t._producer is None and not t.stop_gradient) or \
                (targets is not None and tid in targets):
            g = grads.get(tid)
            if g is None:
                continue
            if t.grad is None:
                t.grad = Tensor(g, stop_gradient=True)
            else:
                t.grad._set_value(t.grad._value + g)
    if not retain_graph:
        tr.reset_tape()
