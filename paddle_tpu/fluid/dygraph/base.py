"""Dygraph mode switches + helpers (reference python/paddle/fluid/dygraph/base.py)."""
from __future__ import annotations

import contextlib
import functools

import numpy as np

from .. import framework
from .tracer import Tracer, no_grad_guard
from .varbase import Tensor, to_tensor_value

__all__ = ["guard", "enable_dygraph", "disable_dygraph", "enabled",
           "to_variable", "no_grad", "grad"]


def enabled() -> bool:
    return framework.in_dygraph_mode()


def enable_dygraph(place=None):
    framework._dygraph_tracer_ = framework._dygraph_tracer_ or Tracer()


def disable_dygraph():
    framework._dygraph_tracer_ = None


@contextlib.contextmanager
def guard(place=None):
    tracer = Tracer()
    with framework._dygraph_guard(tracer):
        yield


def to_variable(value, name=None, zero_copy=None, dtype=None):
    if isinstance(value, Tensor):
        return value
    return Tensor(to_tensor_value(value, dtype), name=name,
                  stop_gradient=True)


class no_grad:
    """Both decorator and context manager (reference dygraph/base.py no_grad)."""

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with no_grad_guard():
                return fn(*args, **kwargs)
        return wrapper

    def __enter__(self):
        self._cm = no_grad_guard()
        return self._cm.__enter__()

    def __exit__(self, *exc):
        return self._cm.__exit__(*exc)


def _create_eager_param(name, shape, dtype, attr, is_bias):
    """Parameter creation in dygraph mode (used by LayerHelper)."""
    import jax
    import jax.numpy as jnp
    from ..initializer import (ConstantInitializer, XavierInitializer,
                               NormalInitializer, UniformInitializer,
                               TruncatedNormalInitializer,
                               NumpyArrayInitializer, MSRAInitializer)
    init = attr.initializer or (ConstantInitializer(0.0) if is_bias
                                else XavierInitializer())
    key = jax.random.PRNGKey(np.random.randint(0, 2**31))
    shape = [int(s) for s in shape]

    class _FakeVar:
        pass

    fv = _FakeVar()
    fv.shape = tuple(shape)
    fv.dtype = dtype

    if isinstance(init, ConstantInitializer):
        val = jnp.full(shape, init.value, dtype=dtype)
    elif isinstance(init, UniformInitializer):
        val = jax.random.uniform(key, shape, minval=init.low,
                                 maxval=init.high).astype(dtype)
    elif isinstance(init, NormalInitializer):
        val = (jax.random.normal(key, shape) * init.scale +
               init.loc).astype(dtype)
    elif isinstance(init, TruncatedNormalInitializer):
        val = (jax.random.truncated_normal(key, -2., 2., shape) * init.scale +
               init.loc).astype(dtype)
    elif isinstance(init, (XavierInitializer, MSRAInitializer)):
        fi, fo = init._fan_in_out(fv)
        import math
        if isinstance(init, XavierInitializer):
            fi = init.fan_in if init.fan_in is not None else fi
            fo = init.fan_out if init.fan_out is not None else fo
            if init.uniform:
                lim = math.sqrt(6.0 / (fi + fo))
                val = jax.random.uniform(key, shape, minval=-lim,
                                         maxval=lim).astype(dtype)
            else:
                val = (jax.random.normal(key, shape) *
                       math.sqrt(2.0 / (fi + fo))).astype(dtype)
        else:
            fi = init.fan_in if init.fan_in is not None else fi
            if init.uniform:
                lim = math.sqrt(6.0 / fi)
                val = jax.random.uniform(key, shape, minval=-lim,
                                         maxval=lim).astype(dtype)
            else:
                val = (jax.random.normal(key, shape) *
                       math.sqrt(2.0 / fi)).astype(dtype)
    elif isinstance(init, NumpyArrayInitializer):
        val = jnp.asarray(init.value).astype(dtype)
    else:
        val = jnp.zeros(shape, dtype=dtype)
    t = Tensor(val, name=name, stop_gradient=not attr.trainable,
               persistable=True, trainable=attr.trainable)
    t.optimize_attr = {"learning_rate": attr.learning_rate}
    t.regularizer = attr.regularizer
    t.need_clip = attr.need_clip
    t.is_parameter = True
    return t


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad for dygraph (reference imperative/partial_grad_engine.cc).
    create_graph=True records the grads on the tape via a functional
    replay of the forward (tracer.grad_with_graph), so a second
    backward()/grad() differentiates through them — gradient penalties
    and double grad work."""
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if create_graph:
        from .tracer import grad_with_graph
        return grad_with_graph(outputs, inputs, grad_outputs)
    # save existing .grad, run backward, read, restore
    from .tracer import run_backward
    saved = [(t, t.grad) for t in inputs]
    for t in inputs:
        t.grad = None
    targets = {id(t) for t in inputs}
    for o, go in zip(outputs, grad_outputs or [None] * len(outputs)):
        run_backward(o, go, retain_graph=True if retain_graph is None
                     else retain_graph, targets=targets)
    res = []
    for t in inputs:
        if t.grad is None and not allow_unused:
            res.append(None)
        else:
            res.append(None if t.grad is None else
                       Tensor(t.grad._value, stop_gradient=True))
    for t, g in saved:
        t.grad = g
    return res
