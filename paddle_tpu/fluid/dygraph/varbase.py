"""Eager Tensor (VarBase) — a jax.Array plus autograd metadata.

TPU-native replacement for the reference imperative VarBase/VariableWrapper
(/root/reference/paddle/fluid/imperative/layer.h, variable_wrapper.h): the
payload is an XLA device buffer; autograd metadata (grad tensor, leaf flag,
tape hooks) lives host-side. Op execution and the tape are in tracer.py.
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .. import core, unique_name

__all__ = ["Tensor", "to_tensor_value"]


def to_tensor_value(data, dtype=None):
    if isinstance(data, Tensor):
        return data._value
    if isinstance(data, (jnp.ndarray, jax.Array)):
        return data.astype(core.convert_dtype(dtype)) if dtype else data
    arr = np.asarray(data)
    if dtype is not None:
        arr = arr.astype(core.convert_dtype(dtype))
    elif arr.dtype == np.float64:
        arr = arr.astype(np.float32)  # paddle default dtype
    return jnp.asarray(arr)


class Tensor:
    """Eager tensor. `stop_gradient=True` (default for data) detaches it."""

    def __init__(self, value, name=None, stop_gradient=True,
                 persistable=False, trainable=None):
        # accept concrete jax arrays AND tracers (functionalized training
        # runs the eager model under a jax trace)
        self._value = value if isinstance(value, (jnp.ndarray, jax.Array)) \
            or hasattr(value, "aval") else to_tensor_value(value)
        self.name = name or unique_name.generate("eager_tmp")
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self.trainable = trainable if trainable is not None \
            else not stop_gradient
        self.grad: "Tensor | None" = None
        # tape linkage (set by Tracer when this tensor is an op output)
        self._producer = None
        self._hooks = []

    # -- payload access ----------------------------------------------------
    def value(self):
        return self._value

    def numpy(self) -> np.ndarray:
        return np.asarray(self._value)

    def _set_value(self, v):
        self._value = v if isinstance(v, (jnp.ndarray, jax.Array)) \
            or hasattr(v, "aval") else jnp.asarray(v)

    set_value = _set_value

    @property
    def shape(self):
        return tuple(self._value.shape)

    @property
    def dtype(self):
        return str(self._value.dtype)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def size(self):
        return int(self._value.size)

    def item(self):
        return np.asarray(self._value).item()

    def __len__(self):
        return self.shape[0] if self.shape else 0

    def __bool__(self):
        # eager truthiness of a 0/1-element tensor (reference varbase
        # __bool__/__nonzero__) — what makes `if tensor:` run in dygraph
        return bool(np.asarray(self._value).reshape(-1)[0]) \
            if self.size == 1 else self._raise_ambiguous()

    def _raise_ambiguous(self):
        raise ValueError(
            "The truth value of a multi-element Tensor is ambiguous — "
            "use paddle.all/paddle.any, or to_static for compiled "
            "control flow")

    def __repr__(self):
        return (f"Tensor(shape={self.shape}, dtype={self.dtype}, "
                f"stop_gradient={self.stop_gradient},\n{np.asarray(self._value)})")

    # -- autograd ----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        from .tracer import run_backward
        run_backward(self, grad_tensor, retain_graph)

    def clear_gradient(self):
        self.grad = None

    clear_grad = clear_gradient

    def detach(self) -> "Tensor":
        t = Tensor(self._value, stop_gradient=True)
        return t

    def register_hook(self, hook):
        self._hooks.append(hook)
        return hook

    @property
    def gradient(self):
        return None if self.grad is None else self.grad.numpy()

    # -- conversion / manipulation heads (filled by math_op_patch) ---------
    def astype(self, dtype):
        from .tracer import trace_single
        return trace_single("cast", {"X": [self]},
                            {"in_dtype": self.dtype,
                             "out_dtype": core.convert_dtype(dtype)})

    def cast(self, dtype):
        return self.astype(dtype)

    def __getitem__(self, idx):
        # direct jax indexing; differentiable path flows through slice op
        from .tracer import trace_single, default_tracer
        if default_tracer() is None or self.stop_gradient:
            return Tensor(self._value[idx], stop_gradient=True)
        n = self.shape[0] if self.shape else 0
        if isinstance(idx, int):
            i = idx % n if n else idx  # normalise negative indices
            return trace_single(
                "slice", {"Input": [self]},
                {"axes": [0], "starts": [i], "ends": [i + 1],
                 "decrease_axis": [0], "infer_flags": [1]})
        if isinstance(idx, slice):
            start = idx.start or 0
            stop = idx.stop if idx.stop is not None else n
            if start < 0:
                start += n
            if stop < 0:
                stop += n
            return trace_single("slice", {"Input": [self]},
                                {"axes": [0], "starts": [start],
                                 "ends": [stop], "decrease_axis": [],
                                 "infer_flags": [1]})
        return Tensor(self._value[idx], stop_gradient=self.stop_gradient)

    # filled in by math_op_patch at import time
