"""Operator overloads for eager Tensor (reference dygraph/math_op_patch.py).

Each Python operator traces the matching elementwise op so autograd works.
"""
from __future__ import annotations

import numpy as np

from .tracer import default_tracer, trace_single
from .varbase import Tensor, to_tensor_value


def _to_tensor(other, like: Tensor):
    if isinstance(other, Tensor):
        return other
    import jax.numpy as jnp
    arr = jnp.asarray(np.asarray(other, dtype=like.dtype))
    return Tensor(arr, stop_gradient=True)


def _binary(op_type, reverse=False):
    def fn(self: Tensor, other):
        other = _to_tensor(other, self)
        a, b = (other, self) if reverse else (self, other)
        if default_tracer() is None:
            from .. import registry
            opdef = registry.require(op_type)
            from .tracer import _EagerCtx
            import jax
            ctx = _EagerCtx(jax.random.PRNGKey(0))
            res = opdef.compute(ctx, {"X": [a._value], "Y": [b._value]},
                                dict(opdef.attrs))
            return Tensor(res["Out"][0], stop_gradient=True)
        return trace_single(op_type, {"X": [a], "Y": [b]}, {"axis": -1})
    return fn


def _unary(op_type, attrs=None):
    def fn(self: Tensor):
        return trace_single(op_type, {"X": [self]}, attrs or {})
    return fn


def monkey_patch_math():
    T = Tensor
    T.__add__ = _binary("elementwise_add")
    T.__radd__ = _binary("elementwise_add", reverse=True)
    T.__sub__ = _binary("elementwise_sub")
    T.__rsub__ = _binary("elementwise_sub", reverse=True)
    T.__mul__ = _binary("elementwise_mul")
    T.__rmul__ = _binary("elementwise_mul", reverse=True)
    T.__truediv__ = _binary("elementwise_div")
    T.__rtruediv__ = _binary("elementwise_div", reverse=True)
    T.__pow__ = _binary("elementwise_pow")
    T.__mod__ = _binary("elementwise_mod")
    T.__floordiv__ = _binary("elementwise_floordiv")
    T.__matmul__ = _binary("matmul")
    T.__neg__ = lambda self: trace_single("scale", {"X": [self]},
                                          {"scale": -1.0})
    T.__eq__ = _binary("equal")
    T.__ne__ = _binary("not_equal")
    T.__lt__ = _binary("less_than")
    T.__le__ = _binary("less_equal")
    T.__gt__ = _binary("greater_than")
    T.__ge__ = _binary("greater_equal")
    T.__hash__ = lambda self: id(self)


def monkey_patch_tensor_methods():
    """Attach every paddle.tensor function whose first argument is a tensor
    as a METHOD on both the eager Tensor and the static Variable — the
    reference does the same via monkey_patch_varbase/monkey_patch_variable
    (dygraph/varbase_patch_methods.py, fluid/layers/math_op_patch.py), so
    `x.squeeze(...)`, `x.sum(...)`, `x.reshape(...)` work in both modes.
    Deferred import: the tensor namespace itself imports dygraph."""
    from ... import tensor as tensor_ns
    from ..framework import Variable
    mods = (tensor_ns.linalg, tensor_ns.logic, tensor_ns.manipulation,
            tensor_ns.math, tensor_ns.search, tensor_ns.stat)
    for mod in mods:
        for name in mod.__all__:
            fn = getattr(mod, name)
            if not callable(fn):
                continue
            for cls in (Tensor, Variable):
                if not hasattr(cls, name):
                    setattr(cls, name, fn)
