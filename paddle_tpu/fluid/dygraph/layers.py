"""Layer — the module system (reference python/paddle/fluid/dygraph/layers.py:678).

Parameter/sublayer/buffer registries, hooks, state_dict, train/eval modes.
Works in both eager mode (parameters are eager Tensors) and under the
static-graph builders (paddle.nn reuses this class)."""
from __future__ import annotations

import collections
from typing import Iterator

import numpy as np

from .. import framework, unique_name
from ..framework import in_dygraph_mode
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr
from .varbase import Tensor

__all__ = ["Layer"]


class HookRemoveHelper:
    def __init__(self, hooks, idx):
        self._hooks, self._idx = hooks, idx

    def remove(self):
        self._hooks.pop(self._idx, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self._full_name = unique_name.generate(
            name_scope or type(self).__name__.lower())
        self._dtype = dtype
        self.training = True
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()

    # -- modes -------------------------------------------------------------
    def train(self):
        self.training = True
        tr = framework._dygraph_tracer()
        if tr is not None:
            tr.train_mode = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        tr = framework._dygraph_tracer()
        if tr is not None:
            tr.train_mode = False
        for l in self.sublayers():
            l.training = False
        return self

    def full_name(self):
        return self._full_name

    # -- registration ------------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Tensor) and getattr(value, "is_parameter", False):
            if params is None:
                raise RuntimeError("call Layer.__init__ first")
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, framework.Parameter):
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ first")
            layers[name] = value
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                del params[name]
            if layers is not None and name in layers:
                del layers[name]
            if buffers is not None and name in buffers:
                del buffers[name]
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for d in ("_parameters", "_sub_layers", "_buffers"):
            dd = self.__dict__.get(d)
            if dd is not None and name in dd:
                return dd[name]
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {name!r}")

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def add_parameter(self, name, parameter):
        if parameter is not None:
            self._parameters[str(name)] = parameter
        return parameter

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[str(name)] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(str(name))
        return tensor

    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        helper = LayerHelper(self._full_name)
        return helper.create_parameter(
            attr if attr is not None else ParamAttr(), shape,
            dtype or self._dtype, is_bias, default_initializer)

    # -- traversal ---------------------------------------------------------
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (f"{prefix}.{name}" if prefix else name), p
        if include_sublayers:
            for lname, l in self._sub_layers.items():
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                for n, p in l.named_parameters(sub_prefix, True):
                    if id(p) not in seen:
                        seen.add(id(p))
                        yield n, p

    def sublayers(self, include_self=False):
        res = [self] if include_self else []
        for l in self._sub_layers.values():
            res.append(l)
            res.extend(l.sublayers())
        return res

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if include_self:
            yield prefix, self
        for name, l in self._sub_layers.items():
            p = f"{prefix}.{name}" if prefix else name
            yield p, l
            yield from l.named_sublayers(p)

    def children(self):
        return iter(self._sub_layers.values())

    def named_children(self):
        return iter(self._sub_layers.items())

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (f"{prefix}.{name}" if prefix else name), b
        if include_sublayers:
            for lname, l in self._sub_layers.items():
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                yield from l.named_buffers(sub_prefix, True)

    # -- hooks -------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        idx = len(self._forward_pre_hooks)
        self._forward_pre_hooks[idx] = hook
        return HookRemoveHelper(self._forward_pre_hooks, idx)

    def register_forward_post_hook(self, hook):
        idx = len(self._forward_post_hooks)
        self._forward_post_hooks[idx] = hook
        return HookRemoveHelper(self._forward_post_hooks, idx)

    # -- call --------------------------------------------------------------
    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    # -- state dict --------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix=""):
        dest = destination if destination is not None \
            else collections.OrderedDict()
        for name, p in self.named_parameters():
            dest[structured_name_prefix + name] = self._param_numpy(p)
        for name, b in self.named_buffers():
            if name.split(".")[-1] not in self._non_persistable_buffer_names:
                dest[structured_name_prefix + name] = self._param_numpy(b)
        return dest

    @staticmethod
    def _param_numpy(p):
        if isinstance(p, Tensor):
            return p.numpy()
        from ..executor import global_scope
        v = global_scope().find_var(p.name)
        return None if v is None else np.asarray(v)

    def set_state_dict(self, state_dict, use_structured_name=True):
        import jax.numpy as jnp
        mapping = dict(self.named_parameters())
        for name, b in self.named_buffers():
            mapping.setdefault(name, b)
        missing = []
        for k, v in state_dict.items():
            p = mapping.get(k)
            if p is None:
                missing.append(k)
                continue
            if isinstance(p, Tensor):
                p._set_value(jnp.asarray(v))
            else:
                from ..executor import global_scope
                global_scope().set(p.name, jnp.asarray(v))
        return missing

    set_dict = set_state_dict
    load_dict = set_state_dict

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    def clear_gradients(self):
        for p in self.parameters():
            if isinstance(p, Tensor):
                p.clear_gradient()

    def to(self, device=None, dtype=None, blocking=None):
        return self

    def astype(self, dtype):
        import jax.numpy as jnp
        for p in self.parameters():
            if isinstance(p, Tensor):
                p._set_value(p._value.astype(dtype))
        return self
