"""Imperative (dygraph) mode (reference python/paddle/fluid/dygraph/)."""
from . import base, layers, tracer, varbase
from .base import (guard, enable_dygraph, disable_dygraph, enabled,
                   to_variable, no_grad, grad)
from .layers import Layer
from .varbase import Tensor
from .math_op_patch import monkey_patch_math

monkey_patch_math()

__all__ = ["guard", "enable_dygraph", "disable_dygraph", "enabled",
           "to_variable", "no_grad", "grad", "Layer", "Tensor"]
