"""Dataset / DataFeed tier (reference python/paddle/fluid/dataset.py +
framework/data_feed.h:108,293,650 + data_set.h:43,284).

The reference streams slot-format text files through C++ DataFeed channels
into per-thread Hogwild workers. TPU redesign: reader THREADS parse and
batch on the host into a bounded queue, while ONE device loop consumes
batches into the jitted step (per-op interpreters scale by threads; one
fused XLA computation doesn't need them — the threads keep the input
pipeline ahead of the device instead).

File format ("MultiSlot" equivalent): one sample per line, slots separated
by ';', values space-separated, slot order = `set_use_var` order. Slots
are padded/truncated to the declared var shape.
"""
from __future__ import annotations

import os
import queue
import random
import threading

import numpy as np

__all__ = ["DatasetFactory", "DatasetBase", "InMemoryDataset",
           "QueueDataset"]


class DatasetFactory:
    """reference dataset.py:22 — create_dataset("InMemoryDataset")."""

    def create_dataset(self, datafeed_class="QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        if datafeed_class == "QueueDataset":
            return QueueDataset()
        raise ValueError(f"unknown dataset class {datafeed_class!r}")


class DatasetBase:
    def __init__(self):
        self.batch_size = 1
        self.thread_num = 1
        self.filelist: list[str] = []
        self.use_vars = []
        self.pipe_command = None
        self._generator = None

    # -- reference config surface ---------------------------------------
    def init(self, batch_size=1, thread_num=1, use_var=None,
             pipe_command=None, fs_name="", fs_ugi="", **kw):
        self.set_batch_size(batch_size)
        self.set_thread(thread_num)
        if use_var:
            self.set_use_var(use_var)
        self.pipe_command = pipe_command
        return self

    def set_batch_size(self, batch_size):
        self.batch_size = int(batch_size)

    def set_thread(self, thread_num):
        self.thread_num = max(1, int(thread_num))

    def set_filelist(self, filelist):
        self.filelist = list(filelist)

    def set_use_var(self, var_list):
        self.use_vars = list(var_list)

    def set_pipe_command(self, cmd):
        self.pipe_command = cmd

    def set_sample_generator(self, generator):
        """Python-side samples instead of files (reference
        data_generator/): generator() yields per-sample tuples matching
        use_var order."""
        self._generator = generator

    # -- parsing ---------------------------------------------------------
    def _var_spec(self, v):
        shape = [abs(int(s)) if s and int(s) > 0 else 1
                 for s in (v.shape or [1])[1:]] or [1]
        n = int(np.prod(shape))
        dtype = np.dtype(v.dtype or "float32")
        return n, shape, dtype

    def _parse_line(self, line):
        parts = line.rstrip("\n").split(";")
        if len(parts) != len(self.use_vars):
            raise ValueError(
                f"line has {len(parts)} slots, use_var declares "
                f"{len(self.use_vars)}")
        sample = []
        for v, txt in zip(self.use_vars, parts):
            n, shape, dtype = self._var_spec(v)
            vals = np.asarray(txt.split(), dtype=dtype)
            if len(vals) < n:  # pad (ragged slot -> dense, SURVEY §7)
                vals = np.concatenate(
                    [vals, np.zeros(n - len(vals), dtype)])
            sample.append(vals[:n].reshape(shape))
        return tuple(sample)

    def _iter_samples(self):
        if self._generator is not None:
            yield from self._generator()
            return
        import subprocess
        for path in self.filelist:
            if self.pipe_command:
                with open(path, "rb") as f:
                    out = subprocess.run(
                        self.pipe_command, shell=True, stdin=f,
                        capture_output=True, check=True)
                lines = out.stdout.decode().splitlines()
            else:
                with open(path) as f:
                    lines = f.read().splitlines()
            for line in lines:
                if line.strip():
                    yield self._parse_line(line)

    def _batches_from(self, samples, drop_last=False):
        buf = []
        for s in samples:
            buf.append(s)
            if len(buf) == self.batch_size:
                yield self._collate(buf)
                buf = []
        if buf and not drop_last:
            yield self._collate(buf)

    def _collate(self, samples):
        feed = {}
        for i, v in enumerate(self.use_vars):
            feed[v.name] = np.stack([s[i] for s in samples])
        return feed

    def batch_iter(self):
        raise NotImplementedError


class InMemoryDataset(DatasetBase):
    """reference dataset.py:328: load everything, shuffle, iterate."""

    def __init__(self):
        super().__init__()
        self._samples: list | None = None

    def load_into_memory(self):
        self._samples = list(self._iter_samples())
        return self

    def release_memory(self):
        self._samples = None

    def get_memory_data_size(self):
        return len(self._samples or [])

    def local_shuffle(self):
        if self._samples is None:
            raise RuntimeError("call load_into_memory() first")
        random.shuffle(self._samples)

    def global_shuffle(self, fleet=None, thread_num=12):
        """Single-process world: global == local (the reference shuffles
        across trainers via fleet RPC)."""
        self.local_shuffle()

    def batch_iter(self):
        if self._samples is None:
            raise RuntimeError("call load_into_memory() first")
        yield from self._batches_from(self._samples)


class QueueDataset(DatasetBase):
    """reference dataset.py:852: streaming — reader threads parse files
    into a bounded queue; the consumer drains batches as they arrive."""

    _CHUNK = 256  # samples per queue item (amortises queue overhead)

    def batch_iter(self):
        if self._generator is not None or len(self.filelist) <= 1 or \
                self.thread_num <= 1:
            yield from self._batches_from(self._iter_samples())
            return
        # reader threads emit SAMPLE chunks; batching happens at the
        # single consumer so batch sizes don't depend on thread_num /
        # per-file tails (only the streaming order does)
        q: queue.Queue = queue.Queue(maxsize=64)
        files = list(self.filelist)
        lock = threading.Lock()
        errors = []

        def worker():
            while True:
                with lock:
                    if not files:
                        break
                    path = files.pop()
                sub = QueueDataset()
                sub.use_vars = self.use_vars
                sub.pipe_command = self.pipe_command
                sub.filelist = [path]
                try:
                    chunk = []
                    for s in sub._iter_samples():
                        chunk.append(s)
                        if len(chunk) >= self._CHUNK:
                            q.put(chunk)
                            chunk = []
                    if chunk:
                        q.put(chunk)
                except Exception as e:  # surfaced by the consumer
                    errors.append(e)
            q.put(None)

        n = min(self.thread_num, len(files))
        for _ in range(n):
            threading.Thread(target=worker, daemon=True).start()

        def samples():
            done = 0
            while done < n:
                item = q.get()
                if item is None:
                    done += 1
                    continue
                yield from item
            if errors:
                raise errors[0]

        yield from self._batches_from(samples())
