"""CompiledProgram / strategies (reference python/paddle/fluid/compiler.py).

On TPU the ParallelExecutor SSA machinery collapses into pjit sharding: a
CompiledProgram.with_data_parallel marks the program for batch-axis sharding
over the device mesh; the Executor shards feeds and lets sharded autodiff
insert the gradient psum (replacing AllReduceOpHandle,
details/all_reduce_op_handle.cc).
"""
from __future__ import annotations

__all__ = ["CompiledProgram", "BuildStrategy", "ExecutionStrategy"]


class BuildStrategy:
    """Knob bag kept for API parity (details/build_strategy.h). Most knobs are
    no-ops because XLA performs the equivalent passes automatically."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.fuse_all_reduce_ops = True
        self.fuse_all_optimizer_ops = False
        self.fuse_elewise_add_act_ops = False
        self.enable_inplace = True
        self.memory_optimize = True
        self.sync_batch_norm = False
        self.num_trainers = 1
        self.trainer_id = 0


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 100
        self.use_experimental_executor = False


class CompiledProgram:
    def __init__(self, program_or_graph, build_strategy=None):
        self._program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()
        self._is_data_parallel = False
        self._loss_name = None
        self._share_vars_from = None

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        self._is_data_parallel = True
        self._loss_name = loss_name
        if build_strategy is not None:
            self._build_strategy = build_strategy
        self._places = places
        # mark the underlying program: the executor shards the batch axis of
        # feeds over the mesh ("dp" axis) instead of replicating SSA graphs
        self._program._sharding_info = {"mode": "dp", "loss": loss_name}
        return self
