"""Graph IR: Program / Block / Operator / Variable.

TPU-native re-design of the reference's ProgramDesc stack:
  - ProgramDesc/BlockDesc/OpDesc/VarDesc (/root/reference/paddle/fluid/framework/framework.proto)
  - Python mirrors Program/Block/Operator/Variable
    (/root/reference/python/paddle/fluid/framework.py:3934,2472,1881,889)

Differences from the reference, by design:
  * There is no separate C++ desc layer — the Python IR *is* the source of
    truth, and the Executor lowers a whole Block to ONE jitted XLA computation
    (the reference interprets op-by-op, executor.cc:476).
  * Attr values are plain Python (ints/floats/strs/bools/lists + Block refs
    for control flow), serialised via paddle_tpu.fluid.proto.
  * LoD (ragged) tensors are deliberately absent: ragged data is expressed as
    dense + mask/segment ids, which is what XLA wants.
"""
from __future__ import annotations

import collections
import contextlib
import copy
from typing import Any, Iterable

import numpy as np

from . import core, unique_name

__all__ = [
    "Program", "Block", "Operator", "Variable", "Parameter",
    "default_main_program", "default_startup_program", "program_guard",
    "name_scope", "device_guard", "in_dygraph_mode", "grad_var_name",
]

GRAD_SUFFIX = "@GRAD"


def grad_var_name(name: str) -> str:
    return name + GRAD_SUFFIX


# ---------------------------------------------------------------------------
# dygraph-mode switch (tracer installed by paddle_tpu.fluid.dygraph)
# ---------------------------------------------------------------------------

_dygraph_tracer_ = None


def in_dygraph_mode() -> bool:
    return _dygraph_tracer_ is not None


def _dygraph_tracer():
    return _dygraph_tracer_


@contextlib.contextmanager
def _dygraph_guard(tracer):
    global _dygraph_tracer_
    prev = _dygraph_tracer_
    _dygraph_tracer_ = tracer
    try:
        yield
    finally:
        _dygraph_tracer_ = prev


# ---------------------------------------------------------------------------
# name_scope / device_guard
# ---------------------------------------------------------------------------

_name_scope_stack: list[str] = []


@contextlib.contextmanager
def name_scope(prefix: str):
    """Debug/profiling scopes; mapped to jax.named_scope at execution time."""
    _name_scope_stack.append(prefix)
    try:
        yield
    finally:
        _name_scope_stack.pop()


def _current_name_scope() -> str:
    return "/".join(_name_scope_stack)


_device_guard_stack: list[str] = []


@contextlib.contextmanager
def device_guard(device: str | None = None):
    """Annotate ops with a logical device (reference framework.py:5516).

    Used by pipeline parallelism to assign ops to stages: strings like
    "tpu:0".."tpu:k" become the `op_device` attr, consumed by the pipeline
    pass which maps stages onto a mesh axis (not onto physical queues).
    """
    _device_guard_stack.append(device or "")
    try:
        yield
    finally:
        _device_guard_stack.pop()


def _current_device() -> str:
    return _device_guard_stack[-1] if _device_guard_stack else ""


# ---------------------------------------------------------------------------
# Variable
# ---------------------------------------------------------------------------

class Variable:
    """A named tensor in a Block (reference framework.py:889).

    type: "dense" (LoDTensor equivalent — dense, static-rank array),
          "array"  (tensor array for control flow / while loops),
          "raw"    (opaque host object, e.g. RNG seed state).
    """

    def __init__(self, block: "Block", name: str, shape=None, dtype=None,
                 type: str = "dense", persistable: bool = False,
                 stop_gradient: bool = False, is_data: bool = False,
                 initializer=None, **kwargs):
        self.block = block
        self.name = name
        self.shape = tuple(int(s) for s in shape) if shape is not None else None
        self.dtype = core.convert_dtype(dtype) if dtype is not None else None
        self.type = type
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.initializer = initializer

    # -- introspection -----------------------------------------------------
    @property
    def grad_name(self) -> str:
        return grad_var_name(self.name)

    def __repr__(self):
        return (f"var {self.name} : shape={self.shape} dtype={self.dtype} "
                f"type={self.type} persistable={self.persistable} "
                f"stop_gradient={self.stop_gradient}")

    __str__ = __repr__

    @property
    def ndim(self):
        return len(self.shape) if self.shape is not None else None

    def astype(self, dtype):
        from .layers import tensor as _t
        return _t.cast(self, dtype)

    # numpy-style protocol used by layer helpers
    def numpy(self):
        raise RuntimeError(
            "Variable.numpy() is only available on eager Tensors; run the "
            "program with an Executor to materialise static-graph variables.")


class Parameter(Variable):
    """A trainable persistable Variable (reference framework.py:5186)."""

    def __init__(self, block, name, shape, dtype, trainable=True,
                 regularizer=None, do_model_average=False, need_clip=True,
                 optimize_attr=None, **kwargs):
        super().__init__(block, name, shape=shape, dtype=dtype,
                         persistable=True, stop_gradient=not trainable,
                         **kwargs)
        self.trainable = trainable
        self.regularizer = regularizer
        self.do_model_average = do_model_average
        self.need_clip = need_clip
        self.optimize_attr = optimize_attr or {"learning_rate": 1.0}

    def __repr__(self):
        return f"param {self.name} : shape={self.shape} dtype={self.dtype}"


# ---------------------------------------------------------------------------
# Operator
# ---------------------------------------------------------------------------

class Operator:
    """One node of the graph (reference framework.py:1881 / OpDesc).

    inputs/outputs map slot name -> list of variable names. attrs are plain
    Python values; Block-valued attrs (control flow sub-blocks) are stored as
    the Block object itself and serialised as the block index.
    """

    def __init__(self, block: "Block", type: str,
                 inputs: dict | None = None, outputs: dict | None = None,
                 attrs: dict | None = None):
        from . import registry
        self.block = block
        self.type = type
        self.inputs = {k: _as_name_list(v) for k, v in (inputs or {}).items()
                       if v is not None}
        self.outputs = {k: _as_name_list(v) for k, v in (outputs or {}).items()
                        if v is not None}
        self.attrs = dict(attrs or {})
        if _current_name_scope():
            self.attrs.setdefault("name_scope", _current_name_scope())
        if _current_device():
            self.attrs.setdefault("op_device", _current_device())
        opdef = registry.lookup(type)
        if opdef is not None:
            opdef.fill_default_attrs(self.attrs)
            if opdef.stochastic and "_rng_id" not in self.attrs:
                prog = block.program
                prog._rng_counter = getattr(prog, "_rng_counter", 0) + 1
                self.attrs["_rng_id"] = prog._rng_counter
            if opdef.infer_shape is not None:
                opdef.infer_shape(self)

    # -- slot access -------------------------------------------------------
    def input(self, slot: str) -> list[str]:
        return self.inputs.get(slot, [])

    def output(self, slot: str) -> list[str]:
        return self.outputs.get(slot, [])

    @property
    def input_arg_names(self) -> list[str]:
        return [n for ns in self.inputs.values() for n in ns]

    @property
    def output_arg_names(self) -> list[str]:
        return [n for ns in self.outputs.values() for n in ns]

    def attr(self, name: str, default=None):
        return self.attrs.get(name, default)

    def _set_attr(self, name: str, val):
        self.attrs[name] = val
        self.block.program._bump_version()

    def has_attr(self, name: str) -> bool:
        return name in self.attrs

    def invar(self, slot: str) -> "Variable | None":
        names = self.input(slot)
        return self.block._var_recursive(names[0]) if names else None

    def outvar(self, slot: str) -> "Variable | None":
        names = self.output(slot)
        return self.block._var_recursive(names[0]) if names else None

    def __repr__(self):
        ins = ", ".join(f"{k}={v}" for k, v in sorted(self.inputs.items()))
        outs = ", ".join(f"{k}={v}" for k, v in sorted(self.outputs.items()))
        show = {k: v for k, v in self.attrs.items()
                if k not in ("name_scope", "op_device") and
                not isinstance(v, Block)}
        return f"{{Out: {outs}}} = {self.type}(inputs={{{ins}}}, {show})"

    __str__ = __repr__


def _name_of(x) -> str:
    # Variables AND eager Tensors (jit.save's static re-trace passes layer
    # params as eager Tensors) resolve by their .name; str(x) would embed
    # the whole repr as the "name"
    n = getattr(x, "name", None)
    return n if isinstance(n, str) else str(x)


def _as_name_list(v) -> list[str]:
    if isinstance(v, (list, tuple)):
        return [_name_of(x) for x in v]
    return [_name_of(v)]


# ---------------------------------------------------------------------------
# Block
# ---------------------------------------------------------------------------

class Block:
    """Straight-line op list + symbol table (reference framework.py:2472)."""

    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: dict[str, Variable] = collections.OrderedDict()
        self.ops: list[Operator] = []

    @property
    def parent_block(self) -> "Block | None":
        return None if self.parent_idx < 0 else self.program.block(self.parent_idx)

    # -- vars --------------------------------------------------------------
    def create_var(self, name=None, **kwargs) -> Variable:
        name = name or unique_name.generate("tmp")
        # resolve through the parent chain: a sub-block op whose output names
        # an ANCESTOR var writes through to it (reference cond/while sub-block
        # semantics) — it must NOT shadow-create a block-local copy, else the
        # write never surfaces to the parent scope
        v = self._var_recursive(name)
        if v is not None:
            # refine metadata (shape inference updates placeholder vars)
            if v.shape is None and kwargs.get("shape") is not None:
                v.shape = tuple(int(s) for s in kwargs["shape"])
            if v.dtype is None and kwargs.get("dtype") is not None:
                v.dtype = core.convert_dtype(kwargs["dtype"])
            return v
        v = Variable(self, name, **kwargs)
        self.vars[name] = v
        return v

    def create_parameter(self, name, shape, dtype, **kwargs) -> Parameter:
        # Parameters always live in the top-level block (global symbol table),
        # matching reference global-block parameter placement.
        gb = self.program.global_block()
        if name in gb.vars:
            return gb.vars[name]  # type: ignore[return-value]
        p = Parameter(gb, name, shape, dtype, **kwargs)
        gb.vars[name] = p
        return p

    def var(self, name: str) -> Variable:
        v = self.vars.get(name)
        if v is None:
            raise ValueError(f"variable {name!r} not found in block {self.idx}")
        return v

    def has_var(self, name: str) -> bool:
        return name in self.vars

    def _var_recursive(self, name: str) -> Variable | None:
        b: Block | None = self
        while b is not None:
            if name in b.vars:
                return b.vars[name]
            b = b.parent_block
        return None

    def has_var_recursive(self, name: str) -> bool:
        return self._var_recursive(name) is not None

    def all_parameters(self) -> list[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    # -- ops ---------------------------------------------------------------
    def append_op(self, type: str, inputs=None, outputs=None, attrs=None,
                  stop_gradient: bool = False) -> Operator:
        if in_dygraph_mode():
            return _dygraph_tracer_.trace_op(type, inputs or {}, outputs or {},
                                             attrs or {},
                                             stop_gradient=stop_gradient)
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.append(op)
        self.program._bump_version()
        return op

    def _prepend_op(self, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(0, op)
        self.program._bump_version()
        return op

    def _insert_op(self, index: int, type: str, inputs=None, outputs=None,
                   attrs=None) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(index, op)
        self.program._bump_version()
        return op

    def _remove_op(self, index: int):
        del self.ops[index]
        self.program._bump_version()

    def __repr__(self):
        lines = [f"block idx={self.idx} parent={self.parent_idx}"]
        lines += [f"  {v}" for v in self.vars.values()]
        lines += [f"  {op}" for op in self.ops]
        return "\n".join(lines)

    __str__ = __repr__


# ---------------------------------------------------------------------------
# Program
# ---------------------------------------------------------------------------

class Program:
    """A whole computation graph (reference framework.py:3934).

    Holds a list of Blocks; block 0 is the global block. Sub-blocks belong to
    control-flow ops (while/cond) via Block-valued attrs.
    """

    def __init__(self):
        self.blocks: list[Block] = [Block(self, 0)]
        self.current_block_idx = 0
        self.random_seed = 0
        self._is_test = False
        # populated by distributed passes / optimizers
        self._pipeline_opt = None
        self._sharding_info = None
        # mutation counter → executor cache-key / analysis invalidation
        self._version = 0
        self._analysis_cache: tuple | None = None

    def _bump_version(self):
        self._version += 1
        self._analysis_cache = None
        self._prune_cache = {}  # executor's use_prune slices are stale too

    # -- block management --------------------------------------------------
    def global_block(self) -> Block:
        return self.blocks[0]

    def block(self, idx: int) -> Block:
        return self.blocks[idx]

    def current_block(self) -> Block:
        return self.blocks[self.current_block_idx]

    @property
    def num_blocks(self) -> int:
        return len(self.blocks)

    def _create_block(self, parent_idx: int | None = None) -> Block:
        parent = self.current_block_idx if parent_idx is None else parent_idx
        b = Block(self, len(self.blocks), parent)
        self.blocks.append(b)
        self.current_block_idx = b.idx
        return b

    def _rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    # -- introspection -----------------------------------------------------
    def list_vars(self) -> Iterable[Variable]:
        for b in self.blocks:
            yield from b.vars.values()

    def all_parameters(self) -> list[Parameter]:
        return self.global_block().all_parameters()

    def ops(self) -> Iterable[Operator]:
        for b in self.blocks:
            yield from b.ops

    # -- cloning -----------------------------------------------------------
    def clone(self, for_test: bool = False) -> "Program":
        """Deep copy; for_test=True flips is_test on train-sensitive ops
        (dropout/batch_norm...) like reference Program.clone (framework.py:4290)."""
        memo: dict[int, Any] = {}
        p = copy.deepcopy(self, memo)
        if for_test:
            p._is_test = True
            for op in p.ops():
                if "is_test" in op.attrs:
                    op.attrs["is_test"] = True
        return p

    def __deepcopy__(self, memo):
        p = Program.__new__(Program)
        memo[id(self)] = p
        p.random_seed = self.random_seed
        p._is_test = self._is_test
        p._pipeline_opt = None
        p._version = 0
        p._analysis_cache = None
        p._sharding_info = copy.deepcopy(self._sharding_info, memo)
        p.current_block_idx = self.current_block_idx
        p.blocks = []
        for b in self.blocks:
            nb = Block(p, b.idx, b.parent_idx)
            memo[id(b)] = nb
            p.blocks.append(nb)
        for b, nb in zip(self.blocks, p.blocks):
            for name, v in b.vars.items():
                nv = copy.copy(v)
                nv.block = nb
                nb.vars[name] = nv
            for op in b.ops:
                nop = Operator.__new__(Operator)
                nop.block = nb
                nop.type = op.type
                nop.inputs = {k: list(v) for k, v in op.inputs.items()}
                nop.outputs = {k: list(v) for k, v in op.outputs.items()}
                nop.attrs = {}
                for k, v in op.attrs.items():
                    if isinstance(v, Block):
                        nop.attrs[k] = p.blocks[v.idx]
                    else:
                        nop.attrs[k] = copy.copy(v)
                nb.ops.append(nop)
        return p

    # -- structural hash for the executor's compile cache -------------------
    def _structure_key(self) -> tuple:
        items = []
        for b in self.blocks:
            for op in b.ops:
                attrs = tuple(sorted(
                    (k, v.idx if isinstance(v, Block) else _hashable(v))
                    for k, v in op.attrs.items()))
                ins = tuple(sorted((k, tuple(v)) for k, v in op.inputs.items()))
                outs = tuple(sorted((k, tuple(v)) for k, v in op.outputs.items()))
                items.append((b.idx, op.type, ins, outs, attrs))
        return tuple(items)

    def __repr__(self):
        return "\n".join(str(b) for b in self.blocks)

    __str__ = __repr__


def _hashable(v):
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, np.ndarray):
        return (v.shape, str(v.dtype), v.tobytes())
    if isinstance(v, (dict,)):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    return v


# ---------------------------------------------------------------------------
# default programs & guards
# ---------------------------------------------------------------------------

_main_program_ = Program()
_startup_program_ = Program()


def default_main_program() -> Program:
    return _main_program_


def default_startup_program() -> Program:
    return _startup_program_


def switch_main_program(p: Program) -> Program:
    global _main_program_
    old, _main_program_ = _main_program_, p
    return old


def switch_startup_program(p: Program) -> Program:
    global _startup_program_
    old, _startup_program_ = _startup_program_, p
    return old


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Program | None = None):
    old_main = switch_main_program(main_program)
    old_start = switch_startup_program(startup_program) \
        if startup_program is not None else None
    try:
        yield
    finally:
        switch_main_program(old_main)
        if old_start is not None:
            switch_startup_program(old_start)
