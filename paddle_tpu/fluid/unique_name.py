"""Unique name generator for graph variables/ops.

Capability parity with the reference's unique-name generator
(/root/reference/python/paddle/fluid/unique_name.py), redesigned minimally:
a per-prefix counter with guard support for deterministic re-tracing.
"""
from __future__ import annotations

import contextlib


class UniqueNameGenerator:
    def __init__(self, prefix: str = ""):
        self.prefix = prefix
        self.ids: dict[str, int] = {}

    def __call__(self, key: str) -> str:
        i = self.ids.get(key, 0)
        self.ids[key] = i + 1
        return self.prefix + "_".join([key, str(i)])


generator = UniqueNameGenerator()


def generate(key: str) -> str:
    return generator(key)


@contextlib.contextmanager
def guard(new_prefix: str = ""):
    """Scope the generator so names restart (used by Program.clone, tests)."""
    global generator
    old = generator
    generator = UniqueNameGenerator(new_prefix)
    try:
        yield
    finally:
        generator = old


def switch(new_generator: UniqueNameGenerator | None = None):
    global generator
    old = generator
    generator = new_generator or UniqueNameGenerator()
    return old
