"""DistributeTranspiler (reference python/paddle/fluid/transpiler/
distribute_transpiler.py): rewrite a single-process training program into
trainer + pserver programs.

Async-PS semantics (reference a_sync / RunAsyncLoop): the transpiled
trainer replaces every optimizer op with
  send(grad, lr)   -- server applies -lr*grad on arrival
  recv(param)      -- pull the fresh server-side value
and the pserver program is one `listen_and_serv` op the Executor runs
host-side as a blocking service loop. Parameters LIVE on the servers
(large_scale_kv init rules): the first recv overwrites the trainer's
local init, so every trainer sees one consistent model without a
broadcast. Sharding across multiple pservers is row-hash routing inside
PSClient (one table per param, rows 0..m-1).

Sync mode (send_barrier/fetch_barrier rounds) is not implemented — the
mesh-collective data-parallel path covers synchronous training natively;
transpiler mode exists for the sparse/async regime.
"""
from __future__ import annotations

from . import framework
from .framework import Program

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig"]

_OPT_OPS = {"sgd", "momentum", "adam", "adamw", "adagrad", "adamax",
            "adadelta", "rmsprop", "ftrl", "lamb", "decayed_adagrad",
            "lars_momentum", "dgc_momentum"}


class DistributeTranspilerConfig:
    """Reference transpiler config bag (slice_var_up etc. — row-hash
    routing subsumes explicit var slicing)."""

    def __init__(self):
        self.slice_var_up = True
        self.split_method = None
        self.min_block_size = 8192
        self.sync_mode = False
        self.runtime_split_send_recv = False
        self.mode = "pserver"


class DistributeTranspiler:
    def __init__(self, config: DistributeTranspilerConfig | None = None):
        self.config = config or DistributeTranspilerConfig()
        self._trainer_program = None
        self._pservers = []
        self._origin_program = None

    def transpile(self, trainer_id, program=None, pservers="",
                  trainers=1, sync_mode=False, startup_program=None,
                  current_endpoint=""):
        if sync_mode or self.config.sync_mode:
            raise NotImplementedError(
                "sync PS rounds: use the mesh-collective DP path; the "
                "transpiler implements the async regime")
        program = program or framework.default_main_program()
        self._origin_program = program
        self._pservers = [e for e in pservers.split(",") if e]
        self.trainer_id = trainer_id
        self.trainer_num = trainers

        t = program.clone()
        gb = t.global_block()
        new_ops = []
        for op in gb.ops:
            if op.type not in _OPT_OPS:
                new_ops.append(op)
                continue
            param_name = op.input("Param")[0]
            grad_name = op.input("Grad")[0]
            lr_name = (op.input("LearningRate") or [None])[0]
            pvar = gb._var_recursive(param_name)
            shape = list(pvar.shape) if pvar is not None and pvar.shape \
                else []
            from .framework import Operator
            send_out = gb.create_var(
                name=f"{param_name}.send_done", persistable=False)
            ins = {"X": [grad_name]}
            if lr_name:
                ins["LearningRate"] = [lr_name]
            new_ops.append(Operator(
                gb, "send", inputs=ins, outputs={"Out": [send_out.name]},
                attrs={"table_name": param_name,
                       "endpoints": self._pservers}))
            new_ops.append(Operator(
                gb, "recv", inputs={}, outputs={"Out": [param_name]},
                attrs={"table_name": param_name,
                       "endpoints": self._pservers, "shape": shape}))
        gb.ops[:] = new_ops
        t._bump_version()
        self._trainer_program = t
        return self

    def get_trainer_program(self, wait_port=True) -> Program:
        if self._trainer_program is None:
            raise RuntimeError("call transpile() first")
        return self._trainer_program

    def get_pserver_program(self, endpoint) -> Program:
        from .framework import Operator
        p = Program()
        gb = p.global_block()
        dummy = gb.create_var(name="serv_out", persistable=False)
        gb.ops.append(Operator(
            gb, "listen_and_serv", inputs={},
            outputs={"Out": [dummy.name]},
            attrs={"endpoint": endpoint, "sync_mode": False}))
        p._bump_version()
        return p

    def get_pserver_programs(self, endpoint):
        return self.get_pserver_program(endpoint), \
            self.get_startup_program(endpoint)

    def get_startup_program(self, endpoint=None, pserver_program=None,
                            startup_program=None):
        """Server-side startup: tables init lazily on first touch
        (large_scale_kv init rules) — nothing to run."""
        return Program()
