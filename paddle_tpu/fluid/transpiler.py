"""DistributeTranspiler (reference python/paddle/fluid/transpiler/
distribute_transpiler.py): rewrite a single-process training program into
trainer + pserver programs.

Async-PS semantics (reference a_sync / RunAsyncLoop): the transpiled
trainer replaces every optimizer op with
  send(grad, lr)   -- server applies -lr*grad on arrival
  recv(param)      -- pull the fresh server-side value
and the pserver program is one `listen_and_serv` op the Executor runs
host-side as a blocking service loop. Parameters LIVE on the servers
(large_scale_kv init rules): the first recv overwrites the trainer's
local init, so every trainer sees one consistent model without a
broadcast. Sharding across multiple pservers is row-hash routing inside
PSClient (one table per param, rows 0..m-1).

Sync mode (reference distribute_transpiler.py:545,813 send_barrier/
fetch_barrier rounds + RunSyncLoop): sends only BUFFER on the server;
a `send_barrier` op blocks until every trainer pushed, the last arrival
applies the round as the mean over trainers, recvs pull the fresh
values, and a `fetch_barrier` holds the next round until everyone
pulled — one synchronous optimization step per round, equal to the
single-process full-batch step.

GEO-SGD mode (reference GeoSgdTranspiler + GeoCommunicator,
communicator.h:396): the trainer KEEPS its local optimizer ops and a
`geo_send` op per parameter pushes the accumulated local delta every
`geo_sgd_need_push_nums` steps, adopting the merged global value —
a distinct convergence behavior (local steps + periodic averaging),
not a transport detail.
"""
from __future__ import annotations

from . import framework
from .framework import Program

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig"]

_OPT_OPS = {"sgd", "momentum", "adam", "adamw", "adagrad", "adamax",
            "adadelta", "rmsprop", "ftrl", "lamb", "decayed_adagrad",
            "lars_momentum", "dgc_momentum"}


class DistributeTranspilerConfig:
    """Reference transpiler config bag (slice_var_up etc. — row-hash
    routing subsumes explicit var slicing)."""

    def __init__(self):
        self.slice_var_up = True
        self.split_method = None
        self.min_block_size = 8192
        self.sync_mode = False
        self.runtime_split_send_recv = False
        self.mode = "pserver"
        # GEO-SGD (reference GeoSgdTranspiler config)
        self.geo_sgd_mode = False
        self.geo_sgd_need_push_nums = 100


class DistributeTranspiler:
    def __init__(self, config: DistributeTranspilerConfig | None = None):
        self.config = config or DistributeTranspilerConfig()
        self._trainer_program = None
        self._pservers = []
        self._origin_program = None

    def transpile(self, trainer_id, program=None, pservers="",
                  trainers=1, sync_mode=False, startup_program=None,
                  current_endpoint=""):
        program = program or framework.default_main_program()
        self._origin_program = program
        self._pservers = [e for e in pservers.split(",") if e]
        self.trainer_id = trainer_id
        self.trainer_num = trainers
        self.sync_mode = bool(sync_mode or self.config.sync_mode)

        from .framework import Operator
        t = program.clone()
        gb = t.global_block()

        if self.config.geo_sgd_mode:
            # GEO: keep local optimizer ops; append a geo_send per param
            params = []
            for op in gb.ops:
                if op.type in _OPT_OPS:
                    params.append(op.input("Param")[0])
            for param_name in dict.fromkeys(params):
                pvar = gb._var_recursive(param_name)
                shape = list(pvar.shape) if pvar is not None and \
                    pvar.shape else []
                gb.ops.append(Operator(
                    gb, "geo_send", inputs={"X": [param_name]},
                    outputs={"Out": [param_name]},
                    attrs={"table_name": param_name,
                           "endpoints": self._pservers,
                           "k_steps": self.config.geo_sgd_need_push_nums,
                           "shape": shape,
                           "trainer_id": trainer_id}))
            t._bump_version()
            self._trainer_program = t
            return self

        new_ops = []
        recvs = []
        for op in gb.ops:
            if op.type not in _OPT_OPS:
                new_ops.append(op)
                continue
            param_name = op.input("Param")[0]
            grad_name = op.input("Grad")[0]
            lr_name = (op.input("LearningRate") or [None])[0]
            pvar = gb._var_recursive(param_name)
            shape = list(pvar.shape) if pvar is not None and pvar.shape \
                else []
            send_out = gb.create_var(
                name=f"{param_name}.send_done", persistable=False)
            ins = {"X": [grad_name]}
            if lr_name:
                ins["LearningRate"] = [lr_name]
            new_ops.append(Operator(
                gb, "send", inputs=ins, outputs={"Out": [send_out.name]},
                attrs={"table_name": param_name,
                       "endpoints": self._pservers,
                       "sync_mode": self.sync_mode,
                       "trainers": trainers}))
            recvs.append(Operator(
                gb, "recv", inputs={}, outputs={"Out": [param_name]},
                attrs={"table_name": param_name,
                       "endpoints": self._pservers, "shape": shape}))
        if self.sync_mode:
            # reference distribute_transpiler.py:545,813: one
            # send_barrier after all sends, recvs, then a fetch_barrier
            def _marker(kind):
                v = gb.create_var(name=f"{kind}.done", persistable=False)
                return Operator(
                    gb, kind, inputs={}, outputs={"Out": [v.name]},
                    attrs={"endpoints": self._pservers,
                           "trainer_id": trainer_id,
                           "trainers": trainers})
            new_ops.append(_marker("send_barrier"))
            new_ops.extend(recvs)
            new_ops.append(_marker("fetch_barrier"))
        else:
            # async: recv immediately after each send (apply-on-arrival)
            merged = []
            ri = iter(recvs)
            for op in new_ops:
                merged.append(op)
                if op.type == "send":
                    merged.append(next(ri))
            new_ops = merged
        gb.ops[:] = new_ops
        t._bump_version()
        self._trainer_program = t
        return self

    def get_trainer_program(self, wait_port=True) -> Program:
        if self._trainer_program is None:
            raise RuntimeError("call transpile() first")
        return self._trainer_program

    def get_pserver_program(self, endpoint) -> Program:
        from .framework import Operator
        p = Program()
        gb = p.global_block()
        dummy = gb.create_var(name="serv_out", persistable=False)
        gb.ops.append(Operator(
            gb, "listen_and_serv", inputs={},
            outputs={"Out": [dummy.name]},
            attrs={"endpoint": endpoint,
                   "sync_mode": getattr(self, "sync_mode", False)}))
        p._bump_version()
        return p

    def get_pserver_programs(self, endpoint):
        return self.get_pserver_program(endpoint), \
            self.get_startup_program(endpoint)

    def get_startup_program(self, endpoint=None, pserver_program=None,
                            startup_program=None):
        """Server-side startup: tables init lazily on first touch
        (large_scale_kv init rules) — nothing to run."""
        return Program()
