"""Low-level helpers: dtypes, places, global flags.

TPU-native replacements for the reference's platform layer:
  - Place variants        (/root/reference/paddle/fluid/platform/place.h:106)
  - gflags runtime knobs  (/root/reference/paddle/fluid/platform/flags.cc)
  - float16/bfloat16      (native jnp dtypes on TPU; platform/bfloat16.h)

On TPU there is no buddy allocator / device-context pool to manage: XLA owns
device memory and streams. `Place` survives as a lightweight routing tag used
by the executor to pick a jax device/backend.
"""
from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np

# ---------------------------------------------------------------------------
# dtypes
# ---------------------------------------------------------------------------

_DTYPE_ALIASES = {
    "float32": "float32", "fp32": "float32", "float": "float32",
    "float64": "float64", "fp64": "float64", "double": "float64",
    "float16": "float16", "fp16": "float16", "half": "float16",
    "bfloat16": "bfloat16", "bf16": "bfloat16",
    "int8": "int8", "uint8": "uint8", "int16": "int16",
    "int32": "int32", "int64": "int64", "bool": "bool",
    "complex64": "complex64", "complex128": "complex128",
}


def batched_to_numpy(arrays):
    """Device→host gather with ONE blocking synchronization.

    The TPU transport in this environment (axon PJRT tunnel) charges one
    relay round-trip (~100 ms) per *blocked* host read once any D2H
    transfer has completed in the process — ``np.asarray`` per fetch is
    N serial RTTs. Starting every copy async and then gathering costs a
    single RTT for the whole batch (measured: 8 fetches 975 ms → 159 ms).

    Reference bar: the predictor/executor fetch loop is zero-copy per op
    (/root/reference/paddle/fluid/inference/api/analysis_predictor.h:120);
    this is the TPU-tunnel equivalent — amortize the sync, not the copy.

    Non-jax entries (numpy arrays, scalars) pass through unchanged.
    """
    for a in arrays:
        if hasattr(a, "copy_to_host_async"):
            try:
                a.copy_to_host_async()
            except Exception:
                pass  # committed-elsewhere / deleted buffers: asarray below
    return [np.asarray(a) for a in arrays]


def batched_to_numpy_dict(named):
    """``{name: np.ndarray}`` from ``[(name, device_array), ...]`` with one
    device synchronization (see batched_to_numpy)."""
    return dict(zip([n for n, _ in named],
                    batched_to_numpy([v for _, v in named])))


def convert_dtype(dtype: Any) -> str:
    """Normalise any dtype spec (str/np/jnp) to a canonical string."""
    if dtype is None:
        return "float32"
    if isinstance(dtype, str):
        s = dtype.lower()
        if s in _DTYPE_ALIASES:
            return _DTYPE_ALIASES[s]
        raise ValueError(f"unsupported dtype string {dtype!r}")
    # VarDesc.VarType-style enums from our own namespace pass through
    name = getattr(dtype, "name", None)
    if name and name in _DTYPE_ALIASES:
        return _DTYPE_ALIASES[name]
    return np.dtype(dtype).name


def is_float_dtype(dtype: Any) -> bool:
    return convert_dtype(dtype) in ("float16", "bfloat16", "float32", "float64")


# ---------------------------------------------------------------------------
# Places — routing tags, not allocators
# ---------------------------------------------------------------------------

class Place:
    """Base device tag (reference: platform/place.h:106 PlaceBase variant)."""

    backend: str = "cpu"
    device_id: int = 0

    def __eq__(self, other):
        return (type(self) is type(other)
                and self.device_id == getattr(other, "device_id", 0))

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))

    def __repr__(self):
        return f"{type(self).__name__}({self.device_id})"

    def jax_device(self):
        devs = jax.devices(self.backend) if self.backend != "default" \
            else jax.devices()
        return devs[self.device_id % len(devs)]


class CPUPlace(Place):
    backend = "cpu"

    def __init__(self, device_id: int = 0):
        self.device_id = device_id


class TPUPlace(Place):
    """The native accelerator place (north-star `paddle.TPUPlace`)."""
    backend = "default"  # whatever accelerator jax exposes (tpu; cpu fallback)

    def __init__(self, device_id: int = 0):
        self.device_id = device_id


# Alias for API parity with reference CUDAPlace-based user code.
CUDAPlace = TPUPlace
CUDAPinnedPlace = CPUPlace
XPUPlace = TPUPlace


def default_place() -> Place:
    return TPUPlace(0)


# ---------------------------------------------------------------------------
# Global flags (reference: platform/flags.cc + global_value_getter_setter.cc)
# ---------------------------------------------------------------------------

_FLAGS: dict[str, Any] = {
    "FLAGS_check_nan_inf": False,        # per-op NaN sweep (checkify on TPU)
    "FLAGS_benchmark": False,            # force block_until_ready per run
    "FLAGS_eager_delete_tensor_gb": 0.0, # no-op: XLA owns memory
    "FLAGS_paddle_num_threads": 1,
    "FLAGS_use_system_allocator": False,
    "FLAGS_executor_log_level": 0,
    "FLAGS_jit_cache_size": 512,         # compiled-executable cache entries
    "FLAGS_tracer_amp_level": 0,
    "FLAGS_cudnn_deterministic": True,   # parity name; XLA is deterministic
    "FLAGS_profile": False,
}


def _load_env_flags():
    for k, v in os.environ.items():
        if k.startswith("FLAGS_"):
            cur = _FLAGS.get(k)
            if isinstance(cur, bool):
                _FLAGS[k] = v.lower() in ("1", "true", "yes")
            elif isinstance(cur, int):
                _FLAGS[k] = int(v)
            elif isinstance(cur, float):
                _FLAGS[k] = float(v)
            else:
                _FLAGS[k] = v


_load_env_flags()


def get_flags(keys):
    if isinstance(keys, str):
        keys = [keys]
    return {k: _FLAGS.get(k) for k in keys}


def set_flags(flags: dict):
    for k, v in flags.items():
        _FLAGS[k] = v


def globals_flags():
    return dict(_FLAGS)
