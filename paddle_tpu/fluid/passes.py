"""Program pass framework (reference paddle/fluid/framework/ir/:
Pass/PassRegistry pass.h:196, graph rewriting infrastructure).

The reference rewrites an SSA graph; here passes rewrite the Program's op
list directly — the Program IS the IR (SURVEY §2.1), and XLA performs the
instruction-level fusion the reference's fuse passes hand-roll. What
remains genuinely useful at THIS level — dead-op elimination against
fetch targets, constant folding of fill ops, redundant-cast removal,
inline assign-chain collapsing — is implemented as registered passes the
executor/CompiledProgram (build_strategy) and tools like slim
quantization can apply by name.
"""
from __future__ import annotations

from typing import Callable

__all__ = ["register_pass", "apply_pass", "apply_passes", "PassContext",
           "registered_passes"]

_PASSES: dict[str, Callable] = {}


class PassContext:
    def __init__(self, fetch_names=None, feed_names=None):
        self.fetch_names = list(fetch_names or [])
        self.feed_names = list(feed_names or [])


def register_pass(name: str):
    def deco(fn):
        if name in _PASSES:
            raise ValueError(f"pass {name!r} already registered")
        _PASSES[name] = fn
        return fn
    return deco


def registered_passes():
    return sorted(_PASSES)


def apply_pass(program, name: str, ctx: PassContext | None = None):
    """Apply one pass in place; returns the program (reference
    Pass::Apply)."""
    if name not in _PASSES:
        raise KeyError(f"unknown pass {name!r}; have {registered_passes()}")
    _PASSES[name](program, ctx or PassContext())
    program._bump_version()
    return program


def apply_passes(program, names, ctx: PassContext | None = None):
    for n in names:
        apply_pass(program, n, ctx)
    return program


# ---------------------------------------------------------------------------
# built-in passes
# ---------------------------------------------------------------------------

_SIDE_EFFECT_OPS = {
    "print", "assert", "py_func", "fetch", "save", "load",
    "c_allreduce_sum", "c_broadcast", "c_allgather", "c_reducescatter",
    "send", "recv", "average_accumulates", "while", "cond",
}


def _writes(op):
    return set(op.output_arg_names)


def _reads(op):
    return set(op.input_arg_names)


@register_pass("dead_code_elimination")
def _dce(program, ctx):
    """Drop ops whose outputs reach neither a fetch target, a persistable
    var, nor any later op (reference framework/prune.cc semantics,
    run backwards over the op list)."""
    block = program.global_block()
    live = set(ctx.fetch_names)
    for v in block.vars.values():
        if getattr(v, "persistable", False):
            live.add(v.name)
    keep = []
    for op in reversed(block.ops):
        if op.type in _SIDE_EFFECT_OPS or _writes(op) & live:
            keep.append(op)
            live |= _reads(op)
    block.ops[:] = list(reversed(keep))


@register_pass("assign_collapse")
def _assign_collapse(program, ctx):
    """Rewrite consumers of `assign` chains to read the source directly,
    then let DCE drop the assigns (reference inplace/assign passes). Only
    safe when neither name is rebound later and the target is not
    fetched/persistable."""
    block = program.global_block()
    write_counts: dict[str, int] = {}
    for op in block.ops:
        for n in op.output_arg_names:
            write_counts[n] = write_counts.get(n, 0) + 1
    protected = set(ctx.fetch_names)
    alias: dict[str, str] = {}
    for op in block.ops:
        if op.type != "assign":
            continue
        src = op.input("X")[0]
        dst = op.output("Out")[0]
        v = block._var_recursive(dst)
        if (write_counts.get(dst, 0) == 1
                and write_counts.get(src, 0) <= 1
                and dst not in protected
                and not (v is not None and v.persistable)):
            alias[dst] = alias.get(src, src)
    if not alias:
        return
    for op in block.ops:
        if op.type == "assign":
            continue
        for slot, names in op.inputs.items():
            op.inputs[slot] = [alias.get(n, n) for n in names]
    _dce(program, ctx)


@register_pass("constant_fold")
def _constant_fold(program, ctx):
    """Fold fill_constant -> scale/cast chains into single fills
    (reference constant_folding_pass). Conservative: only rank-static
    fills feeding exactly one elementwise-free consumer."""
    block = program.global_block()
    fills = {}
    for op in block.ops:
        if op.type == "fill_constant" and op.attrs.get("shape"):
            fills[op.output("Out")[0]] = op
    for op in block.ops:
        if op.type == "scale":
            src = op.input("X")[0]
            f = fills.get(src)
            if f is None:
                continue
            val = f.attrs.get("value", 0.0) * op.attrs.get("scale", 1.0) \
                + op.attrs.get("bias", 0.0)
            op.type = "fill_constant"
            op.inputs = {}
            op.attrs = {"shape": list(f.attrs["shape"]),
                        "dtype": f.attrs.get("dtype", "float32"),
                        "value": float(val)}
    _dce(program, ctx)
