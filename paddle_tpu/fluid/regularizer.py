"""Weight-decay regularizers (reference python/paddle/fluid/regularizer.py)."""
from __future__ import annotations

from .layer_helper import LayerHelper

__all__ = ["L1Decay", "L2Decay", "L1DecayRegularizer", "L2DecayRegularizer"]


class WeightDecayRegularizer:
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        helper = LayerHelper("l2_decay")
        decay = helper.create_variable_for_type_inference(param.dtype)
        block.append_op(type="scale", inputs={"X": [param]},
                        outputs={"Out": [decay]},
                        attrs={"scale": self._coeff})
        out = helper.create_variable_for_type_inference(param.dtype)
        block.append_op(type="sum", inputs={"X": [grad, decay]},
                        outputs={"Out": [out]})
        return block._var_recursive(out.name)


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        helper = LayerHelper("l1_decay")
        sign = helper.create_variable_for_type_inference(param.dtype)
        block.append_op(type="sign", inputs={"X": [param]},
                        outputs={"Out": [sign]})
        decay = helper.create_variable_for_type_inference(param.dtype)
        block.append_op(type="scale", inputs={"X": [sign]},
                        outputs={"Out": [decay]},
                        attrs={"scale": self._coeff})
        out = helper.create_variable_for_type_inference(param.dtype)
        block.append_op(type="sum", inputs={"X": [grad, decay]},
                        outputs={"Out": [out]})
        return block._var_recursive(out.name)


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
