"""Parameter initializers — append init ops to the startup program.

Parity with reference python/paddle/fluid/initializer.py (Constant, Uniform,
Normal, TruncatedNormal, Xavier, MSRA/Kaiming, NumpyArray). Initialisation
runs as ops of the startup Program, exactly like the reference, so the whole
init is one jitted XLA computation too.
"""
from __future__ import annotations

import math

import numpy as np

from . import framework

__all__ = [
    "Initializer", "Constant", "Uniform", "Normal", "TruncatedNormal",
    "Xavier", "MSRA", "NumpyArrayInitializer", "ConstantInitializer",
    "UniformInitializer", "NormalInitializer", "XavierInitializer",
    "MSRAInitializer", "TruncatedNormalInitializer",
]


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError

    def _fan_in_out(self, var):
        shape = var.shape
        if len(shape) < 2:
            return (shape[0] if shape else 1,) * 2
        recep = int(np.prod(shape[2:])) if len(shape) > 2 else 1
        return shape[1] * recep, shape[0] * recep


class ConstantInitializer(Initializer):
    def __init__(self, value: float = 0.0, force_cpu: bool = False):
        self.value = value

    def __call__(self, var, block):
        return block.append_op(
            type="fill_constant", outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "value": float(self.value),
                   "dtype": var.dtype})


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        return block.append_op(
            type="uniform_random", outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "min": self.low,
                   "max": self.high, "seed": self.seed, "dtype": var.dtype})


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            type="gaussian_random", outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "mean": self.loc,
                   "std": self.scale, "seed": self.seed, "dtype": var.dtype})


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            type="truncated_gaussian_random", outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "mean": self.loc,
                   "std": self.scale, "seed": self.seed, "dtype": var.dtype})


class XavierInitializer(Initializer):
    """Glorot. fan_in/fan_out from the param shape (conv-aware)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = \
            uniform, fan_in, fan_out, seed

    def __call__(self, var, block):
        fi, fo = self._fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        std = math.sqrt(2.0 / (fi + fo))
        return NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    """Kaiming He init."""

    def __init__(self, uniform=True, fan_in=None, seed=0,
                 negative_slope=0.0, nonlinearity="relu"):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fi, _ = self._fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        return NormalInitializer(0.0, math.sqrt(2.0 / fi), self.seed)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value: np.ndarray):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        v = self.value
        attrs = {"shape": list(v.shape), "dtype": var.dtype}
        if v.dtype in (np.float32, np.float64, np.float16):
            attrs["fp32_values"] = [float(q) for q in v.flatten()]
        else:
            attrs["int64_values"] = [int(q) for q in v.flatten()]
        return block.append_op(type="assign_value",
                               outputs={"Out": [var.name]}, attrs=attrs)


class BilinearInitializer(Initializer):
    def __call__(self, var, block):
        shape = var.shape
        if len(shape) != 4:
            raise ValueError("Bilinear initializer needs a 4-D weight")
        c, k = shape[1], shape[3]
        f = int(np.ceil(k / 2.0))
        cc = (2 * f - 1 - f % 2) / (2.0 * f)
        w = np.zeros(shape, dtype="float32")
        for i in range(int(np.prod(shape))):
            idx = np.unravel_index(i, shape)
            w[idx] = (1 - abs(idx[3] / f - cc)) * (1 - abs(idx[2] / f - cc))
        return NumpyArrayInitializer(w)(var, block)


# reference-style aliases
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer


def _global_weight_initializer():
    return XavierInitializer()


def _global_bias_initializer():
    return ConstantInitializer(0.0)
