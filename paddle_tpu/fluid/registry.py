"""Op registry: schema + shape inference + jax compute + autograd rules.

TPU-native replacement for the reference's operator registry
(/root/reference/paddle/fluid/framework/op_registry.h:223-299 and
 op_info.h). One registered `OpDef` bundles what the reference splits across
OpProtoAndCheckerMaker / InferShape / GradOpDescMaker / per-device kernels:

  - attrs schema w/ defaults        (OpProtoAndCheckerMaker)
  - infer_shape(op)                 (compile-time shape inference)
  - compute(ctx, ins, attrs)        (THE kernel — a jax function; XLA compiles
                                     it for TPU, no per-device registry needed)
  - grad maker                      (GradOpDescMaker equivalent)

Autograd: unless an op registers a custom grad maker, a generic `<type>_grad`
op is synthesised whose kernel is `jax.vjp` of the forward kernel. Inside one
jitted block XLA CSE/DCE dedupes the recomputed forward, so this costs nothing
at runtime while keeping the *graph-level* backward architecture (grad ops are
real ops in the Program that distributed passes can rewrite — same property
the reference gets from GradOpDescMaker, backward.py:924).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp

from . import core

_REGISTRY: dict[str, "OpDef"] = {}

GRAD_SUFFIX = "@GRAD"
EMPTY_VAR = "@EMPTY@"


@dataclasses.dataclass
class OpDef:
    type: str
    compute: Callable  # (ctx, ins: dict[str, list], attrs) -> dict[str, list]
    infer_shape: Callable | None = None
    attrs: dict[str, Any] = dataclasses.field(default_factory=dict)
    # grad maker: (op, emit) -> None, where emit(type, inputs, outputs, attrs)
    # appends a grad op. Sentinel "auto" = synthesise via vjp;
    # None = non-differentiable (treated as stop_gradient).
    grad: Any = "auto"
    # fwd input slots that never receive gradient (indices, masks, seeds)
    no_grad_slots: tuple = ()
    # fwd *output* slots that are non-differentiable (e.g. argmax Indices)
    no_grad_out_slots: tuple = ()
    # whether kernel consumes randomness (gets a stable per-op rng id)
    stochastic: bool = False

    def fill_default_attrs(self, attrs: dict):
        for k, v in self.attrs.items():
            attrs.setdefault(k, v)
        # NOTE: `_rng_id` for stochastic ops is assigned by the caller
        # (Operator.__init__ uses a per-Program counter so identically built
        # programs are bit-identical under the same random_seed; the eager
        # Tracer uses its per-op call counter).


def register(type: str, compute=None, *, infer_shape=None, attrs=None,
             grad="auto", no_grad_slots=(), no_grad_out_slots=(),
             stochastic=False):
    """Register an op. Usable as a decorator on the compute fn."""
    def _do(fn):
        if type in _REGISTRY:
            raise ValueError(f"op {type!r} already registered")
        _REGISTRY[type] = OpDef(
            type=type, compute=fn, infer_shape=infer_shape,
            attrs=dict(attrs or {}), grad=grad,
            no_grad_slots=tuple(no_grad_slots),
            no_grad_out_slots=tuple(no_grad_out_slots),
            stochastic=stochastic)
        return fn
    if compute is not None:
        return _do(compute)
    return _do


def lookup(type: str) -> OpDef | None:
    op = _REGISTRY.get(type)
    if op is None and type.endswith("_grad"):
        # lazily synthesise auto-vjp grad kernels
        fwd = _REGISTRY.get(type[: -len("_grad")])
        if fwd is not None and fwd.grad == "auto":
            op = _make_auto_grad_opdef(fwd)
            _REGISTRY[type] = op
    return op


def require(type: str) -> OpDef:
    op = lookup(type)
    if op is None:
        raise NotImplementedError(f"op {type!r} is not registered")
    return op


def registered_ops() -> list[str]:
    return sorted(_REGISTRY)


# ---------------------------------------------------------------------------
# auto-vjp grad synthesis
# ---------------------------------------------------------------------------

def make_default_grad_ops(op, emit):
    """Default GradOpDescMaker: one `<type>_grad` op mirroring the fwd op.

    Grad-op slots:  fwd inputs keep their slot names; for each fwd output
    slot S a slot "S@GRAD" carries the upstream gradients; outputs are
    "S@GRAD" for each differentiable fwd input slot S.
    """
    opdef = require(op.type)
    inputs = {k: list(v) for k, v in op.inputs.items()}
    for slot, names in op.outputs.items():
        if slot in opdef.no_grad_out_slots:
            continue
        inputs[slot + GRAD_SUFFIX] = [n + GRAD_SUFFIX for n in names]
    outputs = {}
    for slot, names in op.inputs.items():
        if slot in opdef.no_grad_slots:
            continue
        grad_names = []
        any_live = False
        for n in names:
            v = op.block._var_recursive(n)
            if v is not None and v.stop_gradient:
                grad_names.append(EMPTY_VAR)  # pruned (stop_gradient)
            else:
                grad_names.append(n + GRAD_SUFFIX)
                any_live = True
        if any_live:
            outputs[slot + GRAD_SUFFIX] = grad_names
    attrs = {k: v for k, v in op.attrs.items()}
    emit(op.type + "_grad", inputs, outputs, attrs)


def _make_auto_grad_opdef(fwd: OpDef) -> OpDef:
    def grad_compute(ctx, ins, attrs):
        # split grad-op inputs back into fwd inputs vs upstream out-grads
        fwd_ins = {k: v for k, v in ins.items() if not k.endswith(GRAD_SUFFIX)}
        out_grads = {k[: -len(GRAD_SUFFIX)]: v
                     for k, v in ins.items() if k.endswith(GRAD_SUFFIX)}

        # differentiable leaf selection: float arrays in non-excluded
        # slots; registered pytree containers (TensorArray) count when
        # they hold float leaves
        def _diffable(v):
            if v is None:
                return False
            try:
                return core.is_float_dtype(jnp.result_type(v))
            except TypeError:
                pass
            leaves = jax.tree_util.tree_leaves(v)
            return any(core.is_float_dtype(jnp.result_type(l))
                       for l in leaves)

        diff_keys: list[tuple[str, int]] = []
        primals: list = []
        for slot, vals in fwd_ins.items():
            if slot in fwd.no_grad_slots:
                continue
            for i, v in enumerate(vals):
                if _diffable(v):
                    diff_keys.append((slot, i))
                    primals.append(v)

        out_slots: list[tuple[str, int]] = []

        def f(*dvals):
            rebuilt = {k: list(v) for k, v in fwd_ins.items()}
            for (slot, i), val in zip(diff_keys, dvals):
                rebuilt[slot][i] = val
            outs = fwd.compute(ctx, rebuilt, attrs)
            out_slots.clear()
            flat = []
            for slot in sorted(outs):
                for i, o in enumerate(outs[slot]):
                    if o is None:
                        continue  # dummy slots (e.g. reshape2's XShape)
                    out_slots.append((slot, i))
                    flat.append(o)
            return tuple(flat)

        def _zero_ct(o):
            # cotangent zeros for an arbitrary output: float leaves get
            # float zeros, integer leaves float0 (the vjp contract for
            # non-differentiable leaves — hit by pytree outputs like
            # TensorArray, whose length is int32)
            import numpy as _np

            def z(l):
                dt = jnp.result_type(l)
                if core.is_float_dtype(dt):
                    return jnp.zeros(jnp.shape(l), dt)
                return _np.zeros(jnp.shape(l), jax.dtypes.float0)
            return jax.tree_util.tree_map(z, o)

        flat_out, vjp_fn = jax.vjp(f, *primals)
        cts = []
        for (slot, i), o in zip(out_slots, flat_out):
            g = out_grads.get(slot)
            gv = g[i] if g is not None and i < len(g) and g[i] is not None \
                else None
            if gv is None:
                try:
                    gv = jnp.zeros_like(o)
                except TypeError:
                    gv = _zero_ct(o)
            cts.append(jnp.asarray(gv, o.dtype) if hasattr(o, "dtype") else gv)
        in_grads = vjp_fn(tuple(cts))

        result: dict[str, list] = {}
        for slot, vals in fwd_ins.items():
            if slot in fwd.no_grad_slots:
                continue
            result[slot + GRAD_SUFFIX] = [None] * len(vals)
        for (slot, i), g in zip(diff_keys, in_grads):
            result[slot + GRAD_SUFFIX][i] = g
        return result

    def grad_infer_shape(op):
        # each input-grad has the shape/dtype of the corresponding fwd input
        block = op.block
        for slot, names in op.outputs.items():
            src = op.inputs.get(slot[: -len(GRAD_SUFFIX)], [])
            for name, src_name in zip(names, src):
                sv = block._var_recursive(src_name)
                if sv is not None:
                    block.create_var(name=name, shape=sv.shape, dtype=sv.dtype)

    return OpDef(type=fwd.type + "_grad", compute=grad_compute,
                 infer_shape=grad_infer_shape, attrs=dict(fwd.attrs),
                 grad=None, stochastic=False)


# ---------------------------------------------------------------------------
# shape-inference helpers shared by op definitions
# ---------------------------------------------------------------------------

def same_shape_as(in_slot: str, out_slot: str = "Out"):
    """Output mirrors shape+dtype of the (first) input in `in_slot`."""
    def _infer(op):
        v = op.invar(in_slot)
        if v is None:
            return
        for name in op.output(out_slot):
            op.block.create_var(name=name, shape=v.shape, dtype=v.dtype)
    return _infer


def elementwise_infer(op):
    x, y = op.invar("X"), op.invar("Y")
    shape, dtype = None, None
    if x is not None and x.shape is not None:
        shape, dtype = x.shape, x.dtype
    if y is not None and y.shape is not None and (
            shape is None or len(y.shape) > len(shape)):
        shape = y.shape
        dtype = dtype or y.dtype
    for name in op.output("Out"):
        op.block.create_var(name=name, shape=shape, dtype=dtype)
