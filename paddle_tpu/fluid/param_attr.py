"""ParamAttr / WeightNormParamAttr (reference python/paddle/fluid/param_attr.py)."""
from __future__ import annotations

from .initializer import Initializer

__all__ = ["ParamAttr"]


class ParamAttr:
    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=False,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(arg) -> "ParamAttr | None":
        if arg is None:
            return ParamAttr()
        if arg is False:
            return None  # no parameter (e.g. bias_attr=False)
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, Initializer):
            return ParamAttr(initializer=arg)
        raise TypeError(f"cannot convert {arg!r} to ParamAttr")
