"""LayerHelper: shared param-creation / op-append glue for all layers.

Parity with reference python/paddle/fluid/layer_helper.py: creates parameters
in the main program's global block AND emits their init ops into the startup
program; appends ops into the current block; applies activations.
"""
from __future__ import annotations

from . import framework, unique_name
from .framework import (Parameter, Variable, default_main_program,
                        default_startup_program, in_dygraph_mode)
from .initializer import (ConstantInitializer, XavierInitializer)
from .param_attr import ParamAttr

__all__ = ["LayerHelper"]


class LayerHelper:
    def __init__(self, layer_type: str, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        self.name = name or unique_name.generate(layer_type)

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    # -- params ------------------------------------------------------------
    def create_parameter(self, attr, shape, dtype="float32", is_bias=False,
                         default_initializer=None, stop_gradient=False):
        attr = ParamAttr._to_attr(attr)
        if attr is None:
            return None
        if attr.initializer is None:
            attr.initializer = default_initializer or (
                ConstantInitializer(0.0) if is_bias else XavierInitializer())
        suffix = "b" if is_bias else "w"
        name = attr.name or unique_name.generate(f"{self.name}.{suffix}")

        if in_dygraph_mode():
            from .dygraph.base import _create_eager_param
            return _create_eager_param(name, shape, dtype, attr, is_bias)

        param = self.main_program.global_block().create_parameter(
            name=name, shape=shape, dtype=dtype, trainable=attr.trainable,
            regularizer=attr.regularizer,
            do_model_average=attr.do_model_average, need_clip=attr.need_clip,
            optimize_attr={"learning_rate": attr.learning_rate})
        # mirrored var + init op in the startup program
        sb = self.startup_program.global_block()
        if not sb.has_var(name):
            sv = sb.create_var(name=name, shape=shape, dtype=dtype,
                               persistable=True)
            attr.initializer(sv, sb)
        return param

    def create_variable_for_type_inference(self, dtype="float32",
                                           stop_gradient=False):
        return self.main_program.current_block().create_var(
            name=unique_name.generate(f"{self.name}.tmp"), dtype=dtype,
            stop_gradient=stop_gradient)

    create_tmp_variable = create_variable_for_type_inference

    def create_global_variable(self, name=None, shape=(1,), dtype="float32",
                               persistable=False, value=None,
                               stop_gradient=True):
        gb = self.main_program.global_block()
        v = gb.create_var(name=name or unique_name.generate(f"{self.name}.gv"),
                          shape=shape, dtype=dtype, persistable=persistable,
                          stop_gradient=stop_gradient)
        if value is not None:
            sb = self.startup_program.global_block()
            if not sb.has_var(v.name):
                sv = sb.create_var(name=v.name, shape=shape, dtype=dtype,
                                   persistable=persistable)
                ConstantInitializer(value)(sv, sb)
        return v

    # -- ops ---------------------------------------------------------------
    def append_op(self, **kwargs):
        return self.main_program.current_block().append_op(**kwargs)

    def append_activation(self, out_var, act=None):
        act = act if act is not None else self.kwargs.get("act")
        if act is None:
            return out_var
        if isinstance(act, str):
            act = {"type": act}
        act_type = act.pop("type")
        if in_dygraph_mode():
            from .dygraph import base as dy
            res = framework._dygraph_tracer().trace_op(
                act_type, {"X": [out_var]}, {"Out": 1}, act)
            return res["Out"][0]
        tmp = self.create_variable_for_type_inference(dtype=out_var.dtype)
        self.append_op(type=act_type, inputs={"X": [out_var]},
                       outputs={"Out": [tmp]}, attrs=act)
        return tmp

    def input(self, name):
        return self.kwargs.get(name)
