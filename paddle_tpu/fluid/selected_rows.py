"""SelectedRows — the sparse-rows gradient representation.

Reference: framework/selected_rows.h (rows index + value tensor; embedding
grads become SelectedRows so the optimizer touches only the looked-up rows,
operators/lookup_table_v2_op.cc grad kernel).  TPU redesign: a pytree of two
device arrays (rows [N] int32, values [N, D]) with a static `height`, so it
flows through jit; duplicated row ids are legal — consumers use scatter-add
(`at[rows].add`), which accumulates duplicates natively on XLA, so the
reference's merge_selected_rows pass is only needed for host export.
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

__all__ = ["SelectedRows", "is_selected_rows"]


class SelectedRows:
    def __init__(self, rows, values, height: int):
        self.rows = rows
        self.values = values
        self.height = int(height)

    @property
    def shape(self):
        return (self.height,) + tuple(self.values.shape[1:])

    @property
    def dtype(self):
        return self.values.dtype

    def to_dense(self):
        z = jnp.zeros(self.shape, self.values.dtype)
        return z.at[self.rows].add(self.values)

    def merged(self):
        """Host-side duplicate-row merge (for export/inspection)."""
        import numpy as np
        rows = np.asarray(self.rows)
        vals = np.asarray(self.values)
        uniq, inv = np.unique(rows, return_inverse=True)
        out = np.zeros((len(uniq),) + vals.shape[1:], vals.dtype)
        np.add.at(out, inv, vals)
        return SelectedRows(jnp.asarray(uniq), jnp.asarray(out), self.height)

    def __repr__(self):
        return (f"SelectedRows(height={self.height}, "
                f"nnz_rows={self.rows.shape[0]}, dim={self.shape[1:]})")


def is_selected_rows(v: Any) -> bool:
    return isinstance(v, SelectedRows)


jax.tree_util.register_pytree_node(
    SelectedRows,
    lambda sr: ((sr.rows, sr.values), sr.height),
    lambda height, kids: SelectedRows(kids[0], kids[1], height))
