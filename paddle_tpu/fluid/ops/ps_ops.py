"""PS graph ops: send / recv / listen_and_serv (reference
operators/distributed_ops/send_op.cc, recv_op.cc,
listen_and_serv_op.cc:352).

send/recv run inside the jitted step via `io_callback` (ordered host
side effects) against the TCP parameter-server tier
(distributed/fleet/runtime/parameter_server_runtime.py PSClient/PSServer
— the gRPC/BRPC transport replacement). Dense params are stored as KV
rows keyed 0..m-1, one table per param; the server applies the SGD
update on arrival (reference RunAsyncLoop apply-on-arrival semantics).
`listen_and_serv` is host-only: the Executor runs it outside tracing
(a blocking server loop has no place inside an XLA computation).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..registry import register, same_shape_as
from .common import x, out

_clients: dict = {}


def _client(endpoints):
    key = tuple(endpoints)
    if key not in _clients:
        from ...distributed.fleet.runtime.parameter_server_runtime import \
            PSClient
        _clients[key] = PSClient(list(endpoints))
    return _clients[key]


@register("send", grad=None,
          no_grad_slots=("X", "LearningRate"),
          attrs={"table_name": "", "endpoints": [], "is_sparse": False,
                 "sync_mode": False, "trainers": 1})
def _send(ctx, ins, attrs):
    """Push a (dense or sparse-rows) gradient to the PS. Async mode: the
    server applies -lr * grad on arrival. Sync mode: the server only
    buffers it — the round is applied (mean over trainers) when the last
    trainer passes `send_barrier` (reference RunSyncLoop)."""
    g = x(ins, "X")
    lr = x(ins, "LearningRate")
    lr = jnp.ones((), jnp.float32) if lr is None else lr.reshape(())
    endpoints = tuple(attrs["endpoints"])
    table = attrs["table_name"]
    sync = bool(attrs.get("sync_mode", False))
    trainers = int(attrs.get("trainers", 1))

    def do_push(gv, lrv):
        gv = np.asarray(gv)
        rows = gv.reshape(gv.shape[0], -1)
        _client(endpoints).push(table, rows.shape[1],
                                np.arange(rows.shape[0], dtype=np.int64),
                                rows, float(lrv), sync=sync,
                                trainers=trainers)
        return np.zeros((1,), np.float32)

    from jax.experimental import io_callback
    done = io_callback(do_push,
                       jax.ShapeDtypeStruct((1,), jnp.float32),
                       g, lr, ordered=True)
    return {"Out": [done]}


def _barrier_op(kind):
    def impl(ctx, ins, attrs):
        endpoints = tuple(attrs["endpoints"])
        worker = int(attrs.get("trainer_id", 0))
        trainers = int(attrs.get("trainers", 1))

        def do(_):
            getattr(_client(endpoints), kind)(worker, trainers)
            return np.zeros((1,), np.float32)

        from jax.experimental import io_callback
        done = io_callback(do, jax.ShapeDtypeStruct((1,), jnp.float32),
                           np.zeros((1,), np.float32), ordered=True)
        return {"Out": [done]}
    return impl


register("send_barrier", _barrier_op("send_barrier"), grad=None,
         attrs={"endpoints": [], "trainer_id": 0, "trainers": 1})
register("fetch_barrier", _barrier_op("fetch_barrier"), grad=None,
         attrs={"endpoints": [], "trainer_id": 0, "trainers": 1})


_geo_state: dict = {}


@register("geo_send", grad=None, no_grad_slots=("X",),
          attrs={"table_name": "", "endpoints": [], "k_steps": 100,
                 "shape": [], "trainer_id": 0})
def _geo_send(ctx, ins, attrs):
    """GEO-SGD (reference GeoCommunicator, operators/distributed/
    communicator.h:396): the trainer optimizes LOCALLY; every k_steps it
    pushes the accumulated delta (local - last_synced) to the server
    (which adds it) and adopts the merged global value. On the very first
    call the trainer adopts the server-side value so all trainers start
    from one consistent model (same contract as async recv-overwrites-
    init)."""
    p = x(ins, "X")
    endpoints = tuple(attrs["endpoints"])
    table = attrs["table_name"]
    k = max(int(attrs.get("k_steps", 100)), 1)
    shape = tuple(attrs["shape"])
    skey = (endpoints, table, int(attrs.get("trainer_id", 0)))

    def do(pv):
        pv = np.asarray(pv, np.float32)
        rows = pv.reshape(pv.shape[0], -1)
        dim = rows.shape[1]
        cl = _client(endpoints)
        idx = np.arange(rows.shape[0], dtype=np.int64)
        st = _geo_state.get(skey)
        if st is None:
            fresh = cl.pull(table, dim, idx).reshape(pv.shape)
            _geo_state[skey] = {"n": 0, "old": fresh.copy()}
            return fresh
        st["n"] += 1
        if st["n"] % k:
            return pv
        delta = rows - st["old"].reshape(rows.shape)
        # server applies -lr*grad; lr=-1 turns the push into "+= delta"
        cl.push(table, dim, idx, delta, lr=-1.0)
        fresh = cl.pull(table, dim, idx).reshape(pv.shape)
        st["old"] = fresh.copy()
        return fresh

    from jax.experimental import io_callback
    val = io_callback(do, jax.ShapeDtypeStruct(shape, jnp.float32),
                      p, ordered=True)
    return {"Out": [val]}


@register("recv", grad=None, attrs={"table_name": "", "endpoints": [],
                                    "shape": [], "dtype": "float32"})
def _recv(ctx, ins, attrs):
    """Pull the current server-side value of a dense param."""
    endpoints = tuple(attrs["endpoints"])
    table = attrs["table_name"]
    shape = tuple(attrs["shape"])
    m = shape[0]
    dim = int(np.prod(shape[1:])) if len(shape) > 1 else 1

    def do_pull():
        rows = _client(endpoints).pull(
            table, dim, np.arange(m, dtype=np.int64))
        return rows.reshape(shape).astype(np.float32)

    from jax.experimental import io_callback
    val = io_callback(do_pull,
                      jax.ShapeDtypeStruct(shape, jnp.float32),
                      ordered=True)
    return {"Out": [val]}


@register("listen_and_serv", grad=None,
          attrs={"endpoint": "", "optimize_blocks": [], "Fanin": 1,
                 "sync_mode": False})
def _listen_and_serv(ctx, ins, attrs):
    raise RuntimeError(
        "listen_and_serv is a host-side blocking loop — the Executor "
        "runs it directly (it cannot live inside a traced computation)")


def run_listen_and_serv(op):
    """Host-side service loop the Executor dispatches to (reference
    listen_and_serv_op RunAsyncLoop): serve until the process is
    terminated by the launcher/fleet.stop_server()."""
    from ...distributed.fleet.runtime.parameter_server_runtime import \
        PSServer
    server = PSServer(op.attrs["endpoint"])
    t = server.serve_in_thread()
    t.join()  # blocks like the reference's server loop
