"""PS graph ops: send / recv / listen_and_serv (reference
operators/distributed_ops/send_op.cc, recv_op.cc,
listen_and_serv_op.cc:352).

send/recv run inside the jitted step via `io_callback` (ordered host
side effects) against the TCP parameter-server tier
(distributed/fleet/runtime/parameter_server_runtime.py PSClient/PSServer
— the gRPC/BRPC transport replacement). Dense params are stored as KV
rows keyed 0..m-1, one table per param; the server applies the SGD
update on arrival (reference RunAsyncLoop apply-on-arrival semantics).
`listen_and_serv` is host-only: the Executor runs it outside tracing
(a blocking server loop has no place inside an XLA computation).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..registry import register, same_shape_as
from .common import x, out

_clients: dict = {}


def _client(endpoints):
    key = tuple(endpoints)
    if key not in _clients:
        from ...distributed.fleet.runtime.parameter_server_runtime import \
            PSClient
        _clients[key] = PSClient(list(endpoints))
    return _clients[key]


@register("send", grad=None,
          no_grad_slots=("X", "LearningRate"),
          attrs={"table_name": "", "endpoints": [], "is_sparse": False})
def _send(ctx, ins, attrs):
    """Push a (dense or sparse-rows) gradient to the PS, which applies
    -lr * grad on arrival."""
    g = x(ins, "X")
    lr = x(ins, "LearningRate")
    lr = jnp.ones((), jnp.float32) if lr is None else lr.reshape(())
    endpoints = tuple(attrs["endpoints"])
    table = attrs["table_name"]

    def do_push(gv, lrv):
        gv = np.asarray(gv)
        rows = gv.reshape(gv.shape[0], -1)
        _client(endpoints).push(table, rows.shape[1],
                                np.arange(rows.shape[0], dtype=np.int64),
                                rows, float(lrv))
        return np.zeros((1,), np.float32)

    from jax.experimental import io_callback
    done = io_callback(do_push,
                       jax.ShapeDtypeStruct((1,), jnp.float32),
                       g, lr, ordered=True)
    return {"Out": [done]}


@register("recv", grad=None, attrs={"table_name": "", "endpoints": [],
                                    "shape": [], "dtype": "float32"})
def _recv(ctx, ins, attrs):
    """Pull the current server-side value of a dense param."""
    endpoints = tuple(attrs["endpoints"])
    table = attrs["table_name"]
    shape = tuple(attrs["shape"])
    m = shape[0]
    dim = int(np.prod(shape[1:])) if len(shape) > 1 else 1

    def do_pull():
        rows = _client(endpoints).pull(
            table, dim, np.arange(m, dtype=np.int64))
        return rows.reshape(shape).astype(np.float32)

    from jax.experimental import io_callback
    val = io_callback(do_pull,
                      jax.ShapeDtypeStruct(shape, jnp.float32),
                      ordered=True)
    return {"Out": [val]}


@register("listen_and_serv", grad=None,
          attrs={"endpoint": "", "optimize_blocks": [], "Fanin": 1,
                 "sync_mode": False})
def _listen_and_serv(ctx, ins, attrs):
    raise RuntimeError(
        "listen_and_serv is a host-side blocking loop — the Executor "
        "runs it directly (it cannot live inside a traced computation)")


def run_listen_and_serv(op):
    """Host-side service loop the Executor dispatches to (reference
    listen_and_serv_op RunAsyncLoop): serve until the process is
    terminated by the launcher/fleet.stop_server()."""
    from ...distributed.fleet.runtime.parameter_server_runtime import \
        PSServer
    server = PSServer(op.attrs["endpoint"])
    t = server.serve_in_thread()
    t.join()  # blocks like the reference's server loop
