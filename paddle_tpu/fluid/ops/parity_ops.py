"""Round-5 op-tail parity sweep: the remaining real gaps between the
reference `REGISTER_OPERATOR` registry and ours (VERDICT r04 missing #3).

Reference kernel families replaced (one .cc/.cu/.h group each under
/root/reference/paddle/fluid/operators/): cholesky_op, multiplex_op,
crop_tensor_op (v1 crop_op too), unpool_op, pool_with_index_op
(max_pool2d/3d_with_index), gru_op, lstm_op, lstmp_op (monolithic RNN op
forms over the dense+lengths design), sequence_ops/{sequence_concat,
sequence_reshape}_op, detection/{sigmoid_focal_loss,yolov3_loss,
prroi_pool}_op, center_loss_op, bpr_loss_op, hinge_loss_op, log_loss_op,
cos_sim_op, sample_logits_op, cvm_op, pad_constant_like_op,
expand_as_op (v1), reverse_op, partial_sum_op, partial_concat_op,
shuffle_batch_op, minus_op, l1_norm_op, fsp_op, cross_entropy2,
lod_reset_op, sync_batch_norm_op (GSPMD subsumes the NCCL stats
exchange), fake int8 {quantize,dequantize,requantize}_op (mkldnn tier's
schema), deformable_conv_v1, depthwise_conv2d_transpose, batch_fc_op,
shrink_rnn_memory_op, filter_by_instag_op, correlation_op, inplace_abn,
save/load(_combine)_op, run_program_op, conditional_block_op,
split_selected_rows_op, linear_interp(_v2), max_pool3d_with_index.

Dense-over-LoD convention (SURVEY §3): variable-length ops take padded
[B, T, ...] plus a SeqLen vector where the reference used LoD.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..registry import register, same_shape_as
from .common import x, out

F32 = jnp.float32


def _xs(ins, slot="X"):
    return list(ins.get(slot) or [])


# ---------------------------------------------------------------------------
# small math / tensor ops
# ---------------------------------------------------------------------------

register("minus",
         lambda ctx, ins, attrs: out(x(ins, "X") - x(ins, "Y")),
         infer_shape=same_shape_as("X"))

register("l1_norm",
         lambda ctx, ins, attrs: out(jnp.sum(jnp.abs(x(ins)))))


@register("cholesky", infer_shape=same_shape_as("X"),
          attrs={"upper": False})
def _cholesky(ctx, ins, attrs):
    l = jnp.linalg.cholesky(x(ins))
    if attrs.get("upper"):
        l = jnp.swapaxes(l, -1, -2)
    return out(l)


@register("multiplex", no_grad_slots=("Ids",))
def _multiplex(ctx, ins, attrs):
    """Out[i] = X[Ids[i]][i] (reference multiplex_op.cc)."""
    ids = x(ins, "Ids").reshape(-1).astype(jnp.int32)
    stack = jnp.stack(_xs(ins), axis=0)          # [K, N, ...]
    n = stack.shape[1]
    return out(stack[ids, jnp.arange(n)])


@register("reverse", infer_shape=same_shape_as("X"),
          attrs={"axis": []})
def _reverse(ctx, ins, attrs):
    axes = attrs.get("axis") or [0]
    return out(jnp.flip(x(ins), axis=[int(a) for a in axes]))


def _crop_common(v, offsets, shape):
    # offsets may be traced (dynamic_slice supports that); shape is
    # static and LITERAL here — callers resolve any 0/-1 expansion
    # before calling (ADVICE: expanding to the full input dim under a
    # nonzero offset made dynamic_slice clamp the start and silently
    # return a shifted window)
    return jax.lax.dynamic_slice(v, list(offsets),
                                 [int(s) for s in shape])


def _expand_crop_shape(v, shape, offsets, what):
    """Resolve 0/-1 shape entries to the REMAINING extent
    (dim - offset). Needs compile-time offsets: with a traced offset
    the output shape would be dynamic, which XLA cannot express —
    reject instead of returning a shifted window."""
    if not any(s in (-1, 0) for s in shape):
        return [int(s) for s in shape]
    static = []
    for o in offsets:
        if isinstance(o, jax.core.Tracer):
            raise NotImplementedError(
                f"{what}: shape entries 0/-1 need compile-time offsets "
                "(static output shapes on TPU)")
        static.append(int(o))
    return [v.shape[i] - static[i] if s in (-1, 0) and i < v.ndim
            else int(s) for i, s in enumerate(shape)]


def _static_ints(t, what):
    """Shape-determining tensor inputs must be trace-time constants (XLA
    static shapes); runtime tracers get a clear error, matching the
    tail_ops.py:284 guard convention."""
    if isinstance(t, jax.core.Tracer):
        raise NotImplementedError(
            f"{what} must be a compile-time constant on TPU (static "
            "shapes); pass it as an attr or a non-traced tensor")
    return [int(s) for s in np.asarray(t)]


@register("crop", no_grad_slots=("Y", "Offsets"),
          attrs={"offsets": [], "shape": []})
def _crop(ctx, ins, attrs):
    """crop_op.cc. Offsets may be a RUNTIME tensor (lax.dynamic_slice
    takes traced starts); the output shape must be static."""
    v = x(ins)
    ref = x(ins, "Y")
    shape = list(ref.shape) if ref is not None else attrs["shape"]
    offs = x(ins, "Offsets")
    offsets = list(offs.ravel()) if offs is not None \
        else (attrs["offsets"] or [0] * v.ndim)
    shape = _expand_crop_shape(v, shape, offsets, "crop")
    return out(_crop_common(v, offsets, shape))


@register("crop_tensor", no_grad_slots=("Shape", "Offsets"),
          attrs={"offsets": [], "shape": []})
def _crop_tensor(ctx, ins, attrs):
    v = x(ins)
    st = x(ins, "Shape")
    shape = _static_ints(st, "crop_tensor Shape") if st is not None \
        else attrs["shape"]
    offs = x(ins, "Offsets")
    offsets = list(offs.ravel()) if offs is not None \
        else (attrs["offsets"] or [0] * v.ndim)
    # 0/-1 entries expand to the remaining extent (dim - offset), same
    # resolution as v1 crop — needs compile-time offsets
    shape = _expand_crop_shape(v, shape, offsets, "crop_tensor")
    return out(_crop_common(v, offsets, shape))


@register("pad_constant_like", infer_shape=same_shape_as("X"),
          no_grad_slots=("X",),
          attrs={"pad_value": 0.0})
def _pad_constant_like(ctx, ins, attrs):
    big, small = x(ins, "X"), x(ins, "Y")
    pads = [(0, b - s) for b, s in zip(big.shape, small.shape)]
    return out(jnp.pad(small, pads,
                       constant_values=attrs.get("pad_value", 0.0)))


@register("expand_as", no_grad_slots=("target_tensor",))
def _expand_as(ctx, ins, attrs):
    """v1 expand_as (expand_as_op.cc): tile X to the target's shape —
    each target dim must be a multiple of X's."""
    v = x(ins)
    tgt = ins.get("target_tensor") or ins.get("Y")
    tshape = tgt[0].shape
    reps = [t // s for t, s in zip(tshape, v.shape)]
    return out(jnp.tile(v, reps))


@register("partial_sum", attrs={"start_index": 0, "length": -1})
def _partial_sum(ctx, ins, attrs):
    """Sum of X[i][:, start:start+length] over the input list
    (partial_sum_op.cc)."""
    s = int(attrs.get("start_index", 0))
    ln = int(attrs.get("length", -1))
    xs = _xs(ins)
    e = xs[0].shape[1] if ln < 0 else s + ln
    return out(sum(v[:, s:e] for v in xs))


@register("partial_concat", attrs={"start_index": 0, "length": -1})
def _partial_concat(ctx, ins, attrs):
    s = int(attrs.get("start_index", 0))
    ln = int(attrs.get("length", -1))
    xs = _xs(ins)
    e = xs[0].shape[1] if ln < 0 else s + ln
    return out(jnp.concatenate([v[:, s:e] for v in xs], axis=1))


@register("shuffle_batch", no_grad_slots=("Seed",),
          no_grad_out_slots=("ShuffleIdx", "SeedOut"),
          attrs={"startup_seed": 0}, stochastic=True)
def _shuffle_batch(ctx, ins, attrs):
    """Row shuffle with recorded permutation (shuffle_batch_op.cc).
    ShuffleIdx lets callers un-shuffle labels the same way. The seed
    tensor may be a tracer under the jitted executor — PRNGKey accepts
    traced ints, so the whole path stays jittable."""
    v = x(ins)
    sd = x(ins, "Seed")
    seed = jnp.asarray(sd).ravel()[0].astype(jnp.int32) \
        if sd is not None \
        else jnp.int32(attrs.get("startup_seed", 0))
    perm = jax.random.permutation(jax.random.PRNGKey(seed), v.shape[0])
    # int32 on purpose (ADVICE): without jax_enable_x64 an int64
    # request silently truncates to int32 with a per-call UserWarning;
    # the dense design controls both producer and consumer
    return {"Out": [v[perm]], "ShuffleIdx": [perm.astype(jnp.int32)],
            "SeedOut": [(seed + 1).astype(jnp.int32).reshape(1)]}


# shuffle_batch's backward (un-permute by ShuffleIdx, reference
# ShuffleBatchGradOp) falls out of the auto-vjp: the stochastic rng
# stream replays the same permutation in the grad op, and d(v[perm]) is
# exactly the scatter-back.


@register("fsp")
def _fsp(ctx, ins, attrs):
    """FSP (flow of solution procedure) matrix for distillation
    (fsp_op.cc): Out[n,i,j] = mean_hw X[n,i,h,w] * Y[n,j,h,w]."""
    a, b = x(ins, "X"), x(ins, "Y")
    n, c1, h, w = a.shape
    c2 = b.shape[1]
    r = jnp.einsum("nihw,njhw->nij", a.astype(F32), b.astype(F32))
    return out((r / (h * w)).astype(a.dtype))


@register("batch_fc")
def _batch_fc(ctx, ins, attrs):
    """Per-slot fc (batch_fc_op.cc): Input [S, N, D] x W [S, D, O] + b
    [S, O] -> [S, N, O]."""
    v, w, b = x(ins, "Input"), x(ins, "W"), x(ins, "Bias")
    r = jnp.einsum("snd,sdo->sno", v, w)
    if b is not None:
        r = r + b[:, None, :]
    return out(r)


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------

@register("hinge_loss", infer_shape=same_shape_as("Logits", "Loss"))
def _hinge_loss(ctx, ins, attrs):
    """loss = max(0, 1 - (2y-1) * logit) (hinge_loss_op.cc)."""
    logits, y = x(ins, "Logits"), x(ins, "Labels")
    return out(jnp.maximum(0.0, 1.0 - (2.0 * y - 1.0) * logits),
               slot="Loss")


@register("log_loss", infer_shape=same_shape_as("Predicted", "Loss"),
          attrs={"epsilon": 1e-4})
def _log_loss(ctx, ins, attrs):
    p, y = x(ins, "Predicted"), x(ins, "Labels")
    eps = attrs.get("epsilon", 1e-4)
    return out(-y * jnp.log(p + eps) - (1.0 - y) * jnp.log(1.0 - p + eps),
               slot="Loss")


@register("bpr_loss", no_grad_slots=("Label",))
def _bpr_loss(ctx, ins, attrs):
    """Bayesian personalized ranking (bpr_loss_op.h): per row i with
    label l: -mean_{j != l} log sigmoid(x_il - x_ij)."""
    v = x(ins)
    lab = x(ins, "Label").reshape(-1)
    n, c = v.shape
    pos = jnp.take_along_axis(v, lab[:, None].astype(jnp.int32), axis=1)
    # -log(1 + exp(x_j - x_pos)) summed over j != label
    t = -jnp.logaddexp(0.0, v - pos)
    t = jnp.where(jax.nn.one_hot(lab, c, dtype=bool), 0.0, t)
    return out((-jnp.sum(t, axis=1, keepdims=True) / (c - 1)), slot="Y")


@register("cos_sim")
def _cos_sim(ctx, ins, attrs):
    """cos similarity row-wise; Y may be [1, D] broadcast
    (cos_sim_op.cc). Outputs XNorm/YNorm for the reference grad."""
    a, b = x(ins, "X"), x(ins, "Y")
    xn = jnp.sqrt(jnp.sum(jnp.square(a), axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(b), axis=-1, keepdims=True))
    sim = jnp.sum(a * b, axis=-1, keepdims=True) / (xn * yn)
    return {"Out": [sim], "XNorm": [xn], "YNorm": [yn]}


@register("sigmoid_focal_loss", infer_shape=same_shape_as("X"),
          no_grad_slots=("Label", "FgNum"),
          attrs={"gamma": 2.0, "alpha": 0.25})
def _sigmoid_focal_loss(ctx, ins, attrs):
    """detection/sigmoid_focal_loss_op: per-class focal BCE where Label
    holds 1-based foreground class (0 = background), normalized by the
    foreground count FgNum."""
    v = x(ins)                              # [N, C] logits
    lab = x(ins, "Label").reshape(-1)       # [N] int, 0 = background
    fg = jnp.maximum(x(ins, "FgNum").reshape(()).astype(F32), 1.0)
    gamma, alpha = attrs["gamma"], attrs["alpha"]
    n, c = v.shape
    # target[i, j] = 1 iff lab[i] == j+1
    tgt = (lab[:, None] == (jnp.arange(c)[None, :] + 1)).astype(F32)
    p = jax.nn.sigmoid(v)
    ce = -(tgt * jax.nn.log_sigmoid(v)
           + (1 - tgt) * jax.nn.log_sigmoid(-v))
    w = tgt * alpha * jnp.power(1 - p, gamma) \
        + (1 - tgt) * (1 - alpha) * jnp.power(p, gamma)
    return out(w * ce / fg)


@register("center_loss",
          no_grad_slots=("Label", "Centers", "CenterUpdateRate"),
          no_grad_out_slots=("SampleCenterDiff", "CentersOut"))
def _center_loss(ctx, ins, attrs):
    """center_loss_op.h: loss = 0.5 * |x - center[label]|^2; centers
    updated by the averaged per-class diff * alpha. The auto-vjp of the
    loss output reproduces the reference backward (dX = dLoss * diff);
    the stats outputs carry no gradient."""
    v = x(ins).astype(F32)
    lab = x(ins, "Label").reshape(-1).astype(jnp.int32)
    centers = x(ins, "Centers").astype(F32)
    alpha = x(ins, "CenterUpdateRate").reshape(()).astype(F32)
    need_update = attrs.get("need_update", True)
    diff = v - centers[lab]                       # [N, D]
    loss = 0.5 * jnp.sum(jnp.square(diff), axis=1, keepdims=True)
    if need_update:
        cnum = centers.shape[0]
        ones = jnp.ones_like(lab, F32)
        cnt = jnp.zeros((cnum,), F32).at[lab].add(ones) + 1.0
        acc = jnp.zeros_like(centers).at[lab].add(diff)
        centers = centers + alpha * acc / cnt[:, None]
    return {"Loss": [loss], "SampleCenterDiff": [diff],
            "CentersOut": [centers]}




@register("cross_entropy2", no_grad_slots=("Label",),
          attrs={"ignore_index": -100})
def _cross_entropy2(ctx, ins, attrs):
    """cross_entropy2 (cross_entropy_op.cc second form): hard-label CE
    over probabilities (not logits), with MatchX/XShape aux outputs."""
    p = x(ins)
    lab = x(ins, "Label")
    ig = attrs.get("ignore_index", -100)
    li = lab.reshape(lab.shape[0], -1)[:, 0].astype(jnp.int32)
    match = jnp.take_along_axis(p, li[:, None], axis=1)
    loss = jnp.where(li[:, None] == ig, 0.0,
                     -jnp.log(jnp.maximum(match, 1e-20)))
    # shape metadata as int32 (ADVICE: jnp int64 truncates + warns
    # without x64; shapes here are far below 2**31)
    return {"Y": [loss], "MatchX": [match],
            "XShape": [jnp.asarray(p.shape, jnp.int32)]}


@register("cvm", no_grad_slots=("CVM",), attrs={"use_cvm": True})
def _cvm(ctx, ins, attrs):
    """cvm_op.h: first two columns are show/click counters; use_cvm
    keeps them log-transformed, otherwise drops them."""
    v = x(ins)
    if attrs.get("use_cvm", True):
        c0 = jnp.log(v[:, :1] + 1.0)
        c1 = jnp.log(v[:, 1:2] + 1.0) - c0
        return {"Y": [jnp.concatenate([c0, c1, v[:, 2:]], axis=1)]}
    return {"Y": [v[:, 2:]]}


# ---------------------------------------------------------------------------
# pooling with indices / unpool / prroi
# ---------------------------------------------------------------------------

def _adaptive_pool_with_index(v, osize):
    """Adaptive max pool with argmax: bin i covers
    [floor(i*H/oh), ceil((i+1)*H/oh)) — membership-mask formulation
    keeps shapes static for any bin split."""
    n, c, h, w = v.shape
    oh, ow = osize

    def masks(inn, onn):
        i = jnp.arange(onn)
        lo = (i * inn) // onn
        hi = -((-(i + 1) * inn) // onn)   # ceil
        t = jnp.arange(inn)
        return (t[None, :] >= lo[:, None]) & (t[None, :] < hi[:, None])

    mh = masks(h, oh)                      # [oh, H]
    mw = masks(w, ow)                      # [ow, W]
    m = mh[:, None, :, None] & mw[None, :, None, :]  # [oh, ow, H, W]
    win = jnp.where(m[None, None], v[:, :, None, None, :, :], -jnp.inf)
    flat = win.reshape(n, c, oh, ow, h * w)
    idx = jnp.argmax(flat, axis=-1).astype(jnp.int32)
    return jnp.max(flat, axis=-1), idx


def _pool_with_index(v, ksize, strides, paddings, adaptive=False):
    """[N,C,H,W] max pool returning flat h*w argmax per window
    (pool_with_index_op.cc convention)."""
    if adaptive:
        return _adaptive_pool_with_index(v, ksize) + (ksize[0], ksize[1])
    n, c, h, w = v.shape
    kh, kw = ksize
    sh, sw = strides
    ph, pw = paddings
    oh = (h + 2 * ph - kh) // sh + 1
    ow = (w + 2 * pw - kw) // sw + 1
    # window gather: [N, C, OH, OW, kh, kw]
    hy = (jnp.arange(oh) * sh - ph)[:, None] + jnp.arange(kh)[None, :]
    wx = (jnp.arange(ow) * sw - pw)[:, None] + jnp.arange(kw)[None, :]
    valid = ((hy >= 0) & (hy < h))[:, None, :, None] \
        & ((wx >= 0) & (wx < w))[None, :, None, :]       # [OH,OW,kh,kw]
    hyc = jnp.clip(hy, 0, h - 1)
    wxc = jnp.clip(wx, 0, w - 1)
    win = v[:, :, hyc[:, None, :, None], wxc[None, :, None, :]]
    win = jnp.where(valid[None, None], win, -jnp.inf)
    flat = win.reshape(n, c, oh, ow, kh * kw)
    arg = jnp.argmax(flat, axis=-1)
    mx = jnp.max(flat, axis=-1)
    ky, kx = arg // kw, arg % kw
    # absolute index = hy*w + wx at the argmax tap
    ay = (jnp.arange(oh) * sh - ph)[None, None, :, None] + ky
    ax = (jnp.arange(ow) * sw - pw)[None, None, None, :] + kx
    idx = (ay * w + ax).astype(jnp.int32)
    return mx, idx, oh, ow


@register("max_pool2d_with_index", no_grad_out_slots=("Mask",),
          attrs={"ksize": [2, 2], "strides": [1, 1], "paddings": [0, 0],
                 "global_pooling": False, "adaptive": False})
def _max_pool2d_with_index(ctx, ins, attrs):
    v = x(ins)
    ks = list(attrs["ksize"])
    if attrs.get("global_pooling"):
        ks = [v.shape[2], v.shape[3]]
    mx, idx, _, _ = _pool_with_index(
        v, ks, attrs["strides"], attrs["paddings"],
        adaptive=attrs.get("adaptive", False))
    return {"Out": [mx], "Mask": [idx]}


@register("max_pool3d_with_index", no_grad_out_slots=("Mask",),
          attrs={"ksize": [2, 2, 2], "strides": [1, 1, 1],
                 "paddings": [0, 0, 0], "global_pooling": False,
                 "adaptive": False})
def _max_pool3d_with_index(ctx, ins, attrs):
    v = x(ins)   # [N, C, D, H, W]
    n, c, d, h, w = v.shape
    kd, kh, kw = (attrs["ksize"] if not attrs.get("global_pooling")
                  else [d, h, w])
    sd, sh, sw = attrs["strides"]
    pd, ph, pw = attrs["paddings"]
    od = (d + 2 * pd - kd) // sd + 1
    # 2-D pool every depth slice, then a 1-D max over the depth window;
    # the flat 3-D index is d*h*w + (2-D index)
    mx2, idx2, oh, ow = _pool_with_index(
        v.reshape(n, c * d, h, w), [kh, kw], [sh, sw], [ph, pw])
    mx2 = mx2.reshape(n, c, d, oh, ow)
    idx2 = idx2.reshape(n, c, d, oh, ow)
    dz = (jnp.arange(od) * sd - pd)[:, None] + jnp.arange(kd)[None, :]
    validz = (dz >= 0) & (dz < d)
    dzc = jnp.clip(dz, 0, d - 1)
    win = mx2[:, :, dzc]                       # [N, C, od, kd, oh, ow]
    win = jnp.where(validz[None, None, :, :, None, None], win, -jnp.inf)
    argd = jnp.argmax(win, axis=3)             # [N, C, od, oh, ow]
    mx = jnp.max(win, axis=3)
    dsel = dzc[jnp.arange(od)[None, None, :, None, None], argd]
    idx = dsel * (h * w) + jnp.take_along_axis(idx2, dsel, axis=2)
    return {"Out": [mx], "Mask": [idx.astype(jnp.int32)]}


@register("unpool", no_grad_slots=("Indices",),
          attrs={"unpooling_type": "max", "ksize": [2, 2],
                 "strides": [2, 2], "paddings": [0, 0],
                 "output_size": []})
def _unpool(ctx, ins, attrs):
    """unpool_op.cc: scatter pooled values back to the argmax positions
    recorded by max_pool2d_with_index."""
    v, idx = x(ins), x(ins, "Indices")
    n, c, h, w = v.shape
    osz = attrs.get("output_size") or []
    if len(osz) >= 2 and osz[-2] > 0:
        oh, ow = int(osz[-2]), int(osz[-1])
    else:
        sh, sw = attrs["strides"]
        kh, kw = attrs["ksize"]
        oh = (h - 1) * sh - 2 * attrs["paddings"][0] + kh
        ow = (w - 1) * sw - 2 * attrs["paddings"][1] + kw
    flat = jnp.zeros((n, c, oh * ow), v.dtype)
    r = flat.at[
        jnp.arange(n)[:, None, None],
        jnp.arange(c)[None, :, None],
        idx.reshape(n, c, -1)].add(v.reshape(n, c, -1))
    return out(r.reshape(n, c, oh, ow))


@register("prroi_pool", no_grad_slots=("ROIs", "BatchRoINums"),
          attrs={"spatial_scale": 1.0, "pooled_height": 1,
                 "pooled_width": 1})
def _prroi_pool(ctx, ins, attrs):
    """Precise RoI pooling (detection/prroi_pool_op): exact integral of
    the bilinearly-interpolated feature over each bin (no sampling
    points). Computed per (bin, feature-pixel) overlap weights — the
    closed form of the PrRoIPooling integral."""
    feat = x(ins)                         # [N, C, H, W]
    rois = x(ins, "ROIs")                 # [R, 4] (x1,y1,x2,y2)
    n, c, h, w = feat.shape
    scale = attrs["spatial_scale"]
    ph_, pw_ = attrs["pooled_height"], attrs["pooled_width"]
    bi = x(ins, "BatchRoINums")           # [N] rois per image
    if bi is not None:
        # roi r belongs to image i where cumsum(bi) first exceeds r —
        # searchsorted keeps shapes static so this jits
        bounds = jnp.cumsum(bi.astype(jnp.int32))
        roi_batch = jnp.searchsorted(
            bounds, jnp.arange(rois.shape[0], dtype=jnp.int32),
            side="right").astype(jnp.int32)
    else:
        roi_batch = jnp.zeros((rois.shape[0],), jnp.int32)

    ih = jnp.arange(h, dtype=F32)
    iw = jnp.arange(w, dtype=F32)

    def one(roi, b):
        x1, y1, x2, y2 = [r * scale for r in roi]
        bh = jnp.maximum((y2 - y1) / ph_, 1e-6)
        bw = jnp.maximum((x2 - x1) / pw_, 1e-6)
        # integral of the bilinear interpolant over [a, b] in 1-D
        # decomposes into per-source-pixel triangular-kernel overlap
        # weights: w_i = integral over bin of max(0, 1 - |t - i|) dt
        def wts(lo, hi, grid, size):
            # antiderivative of the hat function around center i
            def F(t, i):
                u = t - i
                return jnp.where(
                    u <= -1, 0.0,
                    jnp.where(u <= 0, 0.5 * (u + 1) ** 2,
                              jnp.where(u <= 1, 0.5 + u - 0.5 * u * u,
                                        1.0)))
            return F(hi[:, None], grid[None, :]) \
                - F(lo[:, None], grid[None, :])   # [bins, size]
        ylo = y1 + jnp.arange(ph_, dtype=F32) * bh
        xlo = x1 + jnp.arange(pw_, dtype=F32) * bw
        wy = wts(ylo, ylo + bh, ih, h)            # [ph, H]
        wx = wts(xlo, xlo + bw, iw, w)            # [pw, W]
        f = feat[b].astype(F32)                    # [C, H, W]
        s = jnp.einsum("ph,chw,qw->cpq", wy, f, wx)
        return s / (bh * bw)

    r = jax.vmap(one)(rois.astype(F32), roi_batch.astype(jnp.int32))
    return out(r)


# ---------------------------------------------------------------------------
# monolithic RNN op forms (gru_op.cc, lstm_op.cc, lstmp_op.cc)
#
# Dense convention: Input is the pre-projected gate tensor [B, T, G*D]
# (the reference feeds LoD-packed x@Wx through a preceding mul op — same
# contract), Weight is the recurrent weight, outputs are [B, T, D].
# ---------------------------------------------------------------------------

_ACTS = {"sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh, "relu": jax.nn.relu,
         "identity": lambda v: v}




def _rnn_hidden_infer(gates_per):
    """Hidden shape = [B, T, G/gates_per] from the projected Input."""
    def _infer(op):
        v = op.invar("Input")
        if v is None or not v.shape:
            return
        b, t, g = v.shape
        d = g // gates_per if isinstance(g, int) and g > 0 else -1
        for name in op.output("Hidden"):
            op.block.create_var(name=name, shape=(b, t, d), dtype=v.dtype)
        for name in op.output("Cell"):
            op.block.create_var(name=name, shape=(b, t, d), dtype=v.dtype)
    return _infer


@register("gru", infer_shape=_rnn_hidden_infer(3),
          no_grad_slots=("SeqLen",),
          attrs={"activation": "tanh", "gate_activation": "sigmoid",
                 "is_reverse": False, "origin_mode": False})
def _gru(ctx, ins, attrs):
    """GRU over dense [B, T, 3D] gate inputs (gru_op.cc + math/detail/
    gru_kernel.h). Gate layout [update, reset, candidate]; Weight [D, 3D]
    packs W_uz|W_r (first 2D) and W_c (last D)."""
    g = x(ins, "Input")
    w = x(ins, "Weight")
    b = x(ins, "Bias")
    h0 = x(ins, "H0")
    act = _ACTS[attrs.get("activation", "tanh")]
    gact = _ACTS[attrs.get("gate_activation", "sigmoid")]
    origin = attrs.get("origin_mode", False)
    B, T, G = g.shape
    D = G // 3
    if b is not None:
        g = g + b.reshape(1, 1, G)
    if attrs.get("is_reverse"):
        g = jnp.flip(g, axis=1)
    hprev = h0 if h0 is not None else jnp.zeros((B, D), g.dtype)

    wur, wc = w[:, :2 * D], w[:, 2 * D:]

    def step(h, gt):
        ur = gact(gt[:, :2 * D] + h @ wur)
        u, r = ur[:, :D], ur[:, D:]
        c = act(gt[:, 2 * D:] + (r * h) @ wc)
        h2 = u * h + c - u * c if origin else h - u * h + u * c
        return h2, h2

    _, hs = jax.lax.scan(step, hprev, jnp.swapaxes(g, 0, 1))
    hs = jnp.swapaxes(hs, 0, 1)
    if attrs.get("is_reverse"):
        hs = jnp.flip(hs, axis=1)
    return {"Hidden": [hs]}


def _lstm_scan(g, h0, c0, w, proj, use_peepholes, checks, acts, clip=0.0):
    B, T, G = g.shape
    D = G // 4
    act_c, act_g, act_s = acts
    P = proj.shape[1] if proj is not None else D
    h = h0 if h0 is not None else jnp.zeros((B, P), g.dtype)
    c = c0 if c0 is not None else jnp.zeros((B, D), g.dtype)
    ci, cf, co = checks

    def step(carry, gt):
        h, c = carry
        gt = gt + h @ w                       # recurrent term
        cin = act_c(gt[:, :D])                # candidate first (lstm_kernel.h)
        ig = act_g(gt[:, D:2 * D] + (c * ci if ci is not None else 0.0))
        fg = act_g(gt[:, 2 * D:3 * D] + (c * cf if cf is not None else 0.0))
        c2 = cin * ig + c * fg
        if clip > 0.0:
            c2 = jnp.clip(c2, -clip, clip)
        og = act_g(gt[:, 3 * D:] + (c2 * co if co is not None else 0.0))
        h2 = og * act_s(c2)
        if proj is not None:
            h2 = h2 @ proj
        return (h2, c2), (h2, c2)

    _, (hs, cs) = jax.lax.scan(step, (h, c), jnp.swapaxes(g, 0, 1))
    return jnp.swapaxes(hs, 0, 1), jnp.swapaxes(cs, 0, 1)


def _lstm_common(ins, attrs, with_proj):
    g = x(ins, "Input")                       # [B, T, 4D]
    w = x(ins, "Weight")                      # [P|D, 4D]
    b = x(ins, "Bias")
    h0, c0 = x(ins, "H0"), x(ins, "C0")
    proj = x(ins, "ProjWeight") if with_proj else None
    B, T, G = g.shape
    D = G // 4
    use_peep = attrs.get("use_peepholes", False)
    checks = (None, None, None)
    if b is not None:
        g = g + b[..., :4 * D].reshape(1, 1, 4 * D)
        if use_peep and b.size >= 7 * D:
            flat = b.reshape(-1)
            checks = (flat[4 * D:5 * D], flat[5 * D:6 * D],
                      flat[6 * D:7 * D])
    acts = (_ACTS[attrs.get("candidate_activation", "tanh")],
            _ACTS[attrs.get("gate_activation", "sigmoid")],
            _ACTS[attrs.get("cell_activation", "tanh")])
    if attrs.get("is_reverse"):
        g = jnp.flip(g, axis=1)
    hs, cs = _lstm_scan(g, h0, c0, w, proj, use_peep, checks, acts,
                        attrs.get("cell_clip", 0.0))
    if attrs.get("is_reverse"):
        hs, cs = jnp.flip(hs, axis=1), jnp.flip(cs, axis=1)
    return hs, cs


@register("lstm", infer_shape=_rnn_hidden_infer(4),
          no_grad_slots=("SeqLen",),
          attrs={"use_peepholes": False, "is_reverse": False,
                 "gate_activation": "sigmoid",
                 "cell_activation": "tanh",
                 "candidate_activation": "tanh", "cell_clip": 0.0})
def _lstm(ctx, ins, attrs):
    """Monolithic LSTM (lstm_op.cc): gate layout [candidate, input,
    forget, output] with optional peephole weights packed after the 4D
    bias (math/detail/lstm_kernel.h)."""
    hs, cs = _lstm_common(ins, attrs, with_proj=False)
    return {"Hidden": [hs], "Cell": [cs]}


@register("lstmp", no_grad_slots=("SeqLen",),
          attrs={"use_peepholes": False, "is_reverse": False,
                 "gate_activation": "sigmoid",
                 "cell_activation": "tanh",
                 "candidate_activation": "tanh",
                 "proj_activation": "identity", "cell_clip": 0.0,
                 "proj_clip": 0.0})
def _lstmp(ctx, ins, attrs):
    """LSTM with recurrent projection (lstmp_op.cc): h_t = act_p(
    o*act(c)) @ ProjWeight feeds back as the recurrent state."""
    hs, cs = _lstm_common(ins, attrs, with_proj=True)
    pact = _ACTS[attrs.get("proj_activation", "identity")]
    hs = pact(hs)
    pc = attrs.get("proj_clip", 0.0)
    if pc > 0.0:
        hs = jnp.clip(hs, -pc, pc)
    return {"Projection": [hs], "Cell": [cs]}


@register("shrink_rnn_memory", no_grad_slots=("RankTable", "I"),
          attrs={})
def _shrink_rnn_memory(ctx, ins, attrs):
    """shrink_rnn_memory_op.cc: keep the first K rows, where K comes from
    the rank table at step I — dense form: K passed via the RankTable
    vector (sorted sequence lengths). The output SHAPE depends on the
    data, so I/RankTable must be trace-time constants on TPU (the dense
    StaticRNN path never emits this op; it exists for deserialized
    reference graphs run eagerly)."""
    v = x(ins)
    iv, tbl = x(ins, "I"), x(ins, "RankTable")
    if isinstance(iv, jax.core.Tracer) or isinstance(tbl, jax.core.Tracer):
        raise NotImplementedError(
            "shrink_rnn_memory produces a data-dependent shape — not "
            "expressible in a jitted XLA program; run the block eagerly "
            "(dygraph) or use the StaticRNN/scan lowering instead")
    i = int(np.asarray(iv).ravel()[0])
    k = int((np.asarray(tbl).ravel() > i).sum())
    return out(v[:max(k, 1)])


# ---------------------------------------------------------------------------
# sequence tail (dense + SeqLen design)
# ---------------------------------------------------------------------------

@register("sequence_concat", no_grad_slots=("SeqLen",))
def _sequence_concat(ctx, ins, attrs):
    """sequence_ops/sequence_concat_op: concatenate the VALID prefixes of
    each input sequence per row; dense form packs the result and returns
    the combined lengths."""
    xs = _xs(ins)
    lens = list(ins.get("SeqLen") or [])
    if not lens:
        return {"Out": [jnp.concatenate(xs, axis=1)],
                "SeqLenOut": [jnp.asarray(
                    [sum(v.shape[1] for v in xs)] * xs[0].shape[0],
                    jnp.int64)]}
    B = xs[0].shape[0]
    Ttot = sum(v.shape[1] for v in xs)
    total = sum(
        (l.astype(jnp.int32) for l in lens),
        jnp.zeros((B,), jnp.int32))
    # scatter each input's valid prefix to offset[k] + t, where offset[k]
    # is the running sum of earlier inputs' valid lengths; invalid slots
    # target index Ttot, which mode="drop" discards
    D = xs[0].shape[2:]
    flat = jnp.zeros((B, Ttot) + D, xs[0].dtype)
    offs = jnp.zeros((B,), jnp.int32)
    for v, ln in zip(xs, lens):
        T = v.shape[1]
        t = jnp.arange(T)[None, :]
        valid = t < ln.astype(jnp.int32)[:, None]
        tgt = jnp.where(valid, offs[:, None] + t, Ttot)
        flat = flat.at[jnp.arange(B)[:, None], tgt].set(v, mode="drop")
        offs = offs + ln.astype(jnp.int32)
    return {"Out": [flat], "SeqLenOut": [total.astype(jnp.int64)]}


@register("sequence_reshape", no_grad_slots=("SeqLen",),
          attrs={"new_dim": 1})
def _sequence_reshape(ctx, ins, attrs):
    """sequence_ops/sequence_reshape_op: re-chunk each sequence's
    elements into rows of new_dim. Dense form: valid data is contiguous
    per row, so [B, T, D] -> [B, T*D/new, new] with lengths scaled."""
    v = x(ins)
    new = int(attrs["new_dim"])
    B, T, D = v.shape
    assert (T * D) % new == 0, "sequence_reshape: indivisible new_dim"
    r = v.reshape(B, T * D // new, new)
    ln = x(ins, "SeqLen")
    outs = {"Out": [r]}
    if ln is not None:
        outs["SeqLenOut"] = [(ln * D // new).astype(jnp.int64)]
    return outs


@register("lod_reset", no_grad_slots=("Y",), attrs={"target_lod": []})
def _lod_reset(ctx, ins, attrs):
    """lod_reset_op: data passes through; the length metadata is
    replaced (dense design: lengths ride as a separate output)."""
    v = x(ins)
    y = x(ins, "Y")
    tgt = attrs.get("target_lod") or []
    if y is not None:
        lens = y.astype(jnp.int64)
    else:
        lod = np.asarray(tgt, np.int64)
        lens = jnp.asarray(np.diff(lod) if lod.ndim == 1 and len(lod) > 1
                           else lod)
    return {"Out": [v], "SeqLenOut": [lens]}


@register("filter_by_instag", grad=None,
          no_grad_slots=("Ins_tag", "Filter_tag"),
          attrs={"is_lod": True, "out_val_if_empty": 0})
def _filter_by_instag(ctx, ins, attrs):
    """filter_by_instag_op: keep rows whose tag set intersects the
    filter tags; dense form returns the filtered rows compacted to the
    front (zero-padded), a row map, and the loss weight."""
    v = x(ins, "Ins")
    tags = x(ins, "Ins_tag")           # [N, K] int64 (padded with -1)
    filt = x(ins, "Filter_tag")        # [F]
    hit = (tags[:, :, None] == filt[None, None, :]).any(axis=(1, 2))
    n = v.shape[0]
    order = jnp.argsort(~hit, stable=True)      # kept rows first
    kept = hit.sum()
    rows = v[order]
    keep_mask = (jnp.arange(n) < kept)
    rows = jnp.where(keep_mask.reshape((-1,) + (1,) * (v.ndim - 1)),
                     rows, attrs.get("out_val_if_empty", 0))
    idx = jnp.where(keep_mask, order, -1)
    w = keep_mask.astype(F32)[:, None]
    return {"Out": [rows], "LossWeight": [w],
            "IndexMap": [idx.astype(jnp.int64)]}


# ---------------------------------------------------------------------------
# sampled softmax helper (sample_logits_op)
# ---------------------------------------------------------------------------

@register("sample_logits",
          no_grad_slots=("Labels", "CustomizedSamples",
                         "CustomizedProbabilities"),
          no_grad_out_slots=("Samples", "Probabilities", "SampledLabels",
                             "LogitsDim", "LabelsDim"),
          stochastic=True,
          attrs={"use_customized_samples": False, "uniq": True,
                 "remove_accidental_hits": True, "num_samples": 1,
                 "seed": 0})
def _sample_logits(ctx, ins, attrs):
    """sample_logits_op.h: gather label logits + num_samples log-uniform
    negative samples per row; sampled logits are corrected by -log(prob)
    (sampled-softmax bias correction) and accidental hits masked."""
    logits = x(ins, "Logits")               # [N, C]
    labels = x(ins, "Labels")               # [N, NT]
    n, c = logits.shape
    nt = labels.shape[1]
    s = int(attrs["num_samples"])
    if attrs.get("use_customized_samples"):
        samples = x(ins, "CustomizedSamples")
        probs = x(ins, "CustomizedProbabilities")
    else:
        # fresh negatives every call: fold the static seed into the
        # step's RNG stream (ctx.rng varies per step/op)
        key = jax.random.fold_in(ctx.rng(attrs),
                                 int(attrs.get("seed", 0)))
        # log-uniform (Zipf) sampler, the reference's LogUniformSampler
        u = jax.random.uniform(key, (n, s))
        # int32 ids (ADVICE: an int64 request without x64 truncates to
        # int32 anyway, with a UserWarning per call; vocab ids on this
        # path are far below 2**31)
        neg = (jnp.exp(u * jnp.log(float(c + 1))) - 1.0).astype(jnp.int32)
        neg = jnp.clip(neg, 0, c - 1)
        samples = jnp.concatenate([labels.astype(jnp.int32), neg], axis=1)
        p = (jnp.log((samples + 2.0) / (samples + 1.0))
             / jnp.log(float(c + 1)))
        probs = p
    si = samples.astype(jnp.int32)
    sl = jnp.take_along_axis(logits, si, axis=1)
    sl = sl - jnp.log(jnp.maximum(probs.astype(F32), 1e-20))
    if attrs.get("remove_accidental_hits", True):
        # a negative that equals one of the row's true labels is masked
        neg_part = samples[:, nt:]
        acc = (neg_part[:, :, None] == labels[:, None, :]).any(-1)
        sl = sl.at[:, nt:].add(jnp.where(acc, -1e20, 0.0))
    sampled_labels = jnp.tile(jnp.arange(nt, dtype=jnp.int32)[None, :],
                              (n, 1))
    return {"Samples": [samples], "Probabilities": [probs],
            "SampledLogits": [sl], "SampledLabels": [sampled_labels],
            # int32 shape metadata (ADVICE: int64 truncates + warns
            # without jax_enable_x64)
            "LogitsDim": [jnp.asarray(logits.shape, jnp.int32)],
            "LabelsDim": [jnp.asarray(labels.shape, jnp.int32)]}


# ---------------------------------------------------------------------------
# yolov3_loss (detection/yolov3_loss_op.h) — vectorised re-derivation
# ---------------------------------------------------------------------------

def _box_iou_xywh(x1, y1, w1, h1, x2, y2, w2, h2):
    l1, r1 = x1 - w1 / 2, x1 + w1 / 2
    l2, r2 = x2 - w2 / 2, x2 + w2 / 2
    t1, b1 = y1 - h1 / 2, y1 + h1 / 2
    t2, b2 = y2 - h2 / 2, y2 + h2 / 2
    iw = jnp.minimum(r1, r2) - jnp.maximum(l1, l2)
    ih = jnp.minimum(b1, b2) - jnp.maximum(t1, t2)
    inter = jnp.where((iw > 0) & (ih > 0), iw * ih, 0.0)
    union = w1 * h1 + w2 * h2 - inter
    return inter / jnp.maximum(union, 1e-10)


def _sce(logit, tgt):
    # SigmoidCrossEntropy of the reference helpers
    return jnp.maximum(logit, 0.0) - logit * tgt \
        + jnp.log1p(jnp.exp(-jnp.abs(logit)))


@register("yolov3_loss", grad="auto",
          no_grad_slots=("GTBox", "GTLabel", "GTScore"),
          no_grad_out_slots=("ObjectnessMask", "GTMatchMask"),
          attrs={"anchors": [], "anchor_mask": [], "class_num": 1,
                 "ignore_thresh": 0.7, "downsample_ratio": 32,
                 "use_label_smooth": True, "scale_x_y": 1.0})
def _yolov3_loss(ctx, ins, attrs):
    """YOLOv3 loss (detection/yolov3_loss_op.h), vectorised: per-cell
    best-IoU ignore mask, per-gt best-anchor positive matching, box
    location SCE/L1, objectness SCE and per-class SCE — autodiff
    replaces the hand-written grad kernel (the stats outputs are
    stop-gradiented)."""
    v = x(ins).astype(F32)                 # [N, C, H, W]
    gtbox = x(ins, "GTBox").astype(F32)    # [N, B, 4] cx,cy,w,h in [0,1]
    gtlab = x(ins, "GTLabel").astype(jnp.int32)     # [N, B]
    gts = x(ins, "GTScore")
    anchors = list(attrs["anchors"])
    amask = list(attrs["anchor_mask"])
    cnum = int(attrs["class_num"])
    ignore = float(attrs["ignore_thresh"])
    down = int(attrs["downsample_ratio"])
    smooth = attrs.get("use_label_smooth", True)
    scale = float(attrs.get("scale_x_y", 1.0))
    bias = -0.5 * (scale - 1.0)
    n, c, h, w = v.shape
    m = len(amask)
    bnum = gtbox.shape[1]
    input_size = down * h
    an_num = len(anchors) // 2
    if gts is None:
        gts = jnp.ones((n, bnum), F32)
    gts = gts.astype(F32)
    pos_lab, neg_lab = 1.0, 0.0
    if smooth:
        sw = min(1.0 / cnum, 1.0 / 40)
        pos_lab, neg_lab = 1.0 - sw, sw

    # reshape predictions to [N, m, 5+cnum, H, W]
    p = v.reshape(n, m, 5 + cnum, h, w)
    gx = (jnp.arange(w, dtype=F32)[None, None, None, :]
          + jax.nn.sigmoid(p[:, :, 0]) * scale + bias) / w
    gy = (jnp.arange(h, dtype=F32)[None, None, :, None]
          + jax.nn.sigmoid(p[:, :, 1]) * scale + bias) / h
    aw = jnp.asarray([anchors[2 * i] for i in amask], F32)
    ah = jnp.asarray([anchors[2 * i + 1] for i in amask], F32)
    gw = jnp.exp(p[:, :, 2]) * aw[None, :, None, None] / input_size
    gh = jnp.exp(p[:, :, 3]) * ah[None, :, None, None] / input_size

    valid = (gtbox[:, :, 2] > 0) & (gtbox[:, :, 3] > 0)   # [N, B]
    # per-cell best IoU against every valid gt -> ignore mask
    iou = _box_iou_xywh(
        gx[:, :, :, :, None], gy[:, :, :, :, None],
        gw[:, :, :, :, None], gh[:, :, :, :, None],
        gtbox[:, None, None, None, :, 0], gtbox[:, None, None, None, :, 1],
        gtbox[:, None, None, None, :, 2], gtbox[:, None, None, None, :, 3])
    iou = jnp.where(valid[:, None, None, None, :], iou, 0.0)
    best_iou = jnp.max(iou, axis=-1)                      # [N, m, H, W]
    # objness mask: -1 = ignored, 0 = negative, score = positive
    obj_mask = jnp.where(best_iou > ignore, -1.0, 0.0)

    # per-gt best anchor over ALL anchors (shape-only IoU at origin)
    aw_all = jnp.asarray(anchors[0::2], F32) / input_size
    ah_all = jnp.asarray(anchors[1::2], F32) / input_size
    g0 = jnp.zeros_like(gtbox[:, :, 0])
    aiou = _box_iou_xywh(
        g0[:, :, None], g0[:, :, None],
        gtbox[:, :, 2:3], gtbox[:, :, 3:4],
        jnp.zeros((an_num,), F32)[None, None, :],
        jnp.zeros((an_num,), F32)[None, None, :],
        aw_all[None, None, :], ah_all[None, None, :])
    best_n = jnp.argmax(aiou, axis=-1)                    # [N, B]
    # map best anchor id into the mask list (-1 when not in this head)
    amask_arr = jnp.asarray(amask, jnp.int32)
    match = jnp.where(
        best_n[:, :, None] == amask_arr[None, None, :],
        jnp.arange(m, dtype=jnp.int32)[None, None, :], -1).max(-1)
    match = jnp.where(valid, match, -1)                   # GTMatchMask

    gi = jnp.clip((gtbox[:, :, 0] * w).astype(jnp.int32), 0, w - 1)
    gj = jnp.clip((gtbox[:, :, 1] * h).astype(jnp.int32), 0, h - 1)
    act = match >= 0
    mi = jnp.where(act, match, 0)

    bidx = jnp.arange(n)[:, None]
    # gather predicted entries at matched cells: [N, B, 5+cnum]
    pred_at = p[bidx, mi, :, gj, gi]
    tx = gtbox[:, :, 0] * w - gi
    ty = gtbox[:, :, 1] * h - gj
    anchors_w = jnp.asarray(anchors[0::2], F32)
    anchors_h = jnp.asarray(anchors[1::2], F32)
    tw = jnp.log(jnp.maximum(
        gtbox[:, :, 2] * input_size / anchors_w[best_n], 1e-10))
    th = jnp.log(jnp.maximum(
        gtbox[:, :, 3] * input_size / anchors_h[best_n], 1e-10))
    lscale = (2.0 - gtbox[:, :, 2] * gtbox[:, :, 3]) * gts
    loc = (_sce(pred_at[:, :, 0], tx) + _sce(pred_at[:, :, 1], ty)
           + jnp.abs(pred_at[:, :, 2] - tw)
           + jnp.abs(pred_at[:, :, 3] - th)) * lscale
    loc = jnp.where(act, loc, 0.0)

    # class loss at matched cells
    tgt_cls = jnp.where(
        gtlab[:, :, None] == jnp.arange(cnum)[None, None, :],
        pos_lab, neg_lab)
    cls = jnp.sum(_sce(pred_at[:, :, 5:], tgt_cls), axis=-1) * gts
    cls = jnp.where(act, cls, 0.0)

    # positive objness: scatter scores into the mask (positives override
    # the ignore flag, as in the reference write order)
    obj_mask = obj_mask.at[bidx, mi, gj, gi].set(
        jnp.where(act, gts, obj_mask[bidx, mi, gj, gi]), mode="drop")
    objness = p[:, :, 4]
    obj_loss = jnp.where(
        obj_mask > 1e-5, _sce(objness, 1.0) * obj_mask,
        jnp.where(obj_mask > -0.5, _sce(objness, 0.0), 0.0))

    loss = jnp.sum(loc + cls, axis=1) + jnp.sum(obj_loss, axis=(1, 2, 3))
    return {"Loss": [loss],
            "ObjectnessMask": [jax.lax.stop_gradient(obj_mask)],
            "GTMatchMask": [jax.lax.stop_gradient(match)]}


# ---------------------------------------------------------------------------
# int8 quant trio (mkldnn-tier {quantize,dequantize,requantize}_op schema)
# ---------------------------------------------------------------------------

@register("quantize", grad=None, attrs={"Scale": 1.0, "Shift": 0.0,
                                        "is_negative_input": True,
                                        "output_format": "NCHW",
                                        "bfloat16": False})
def _quantize(ctx, ins, attrs):
    s, sh = attrs.get("Scale", 1.0), attrs.get("Shift", 0.0)
    v = x(ins, "Input")
    q = jnp.round(v * s + sh)
    if attrs.get("is_negative_input", True):
        return {"Output": [jnp.clip(q, -128, 127).astype(jnp.int8)]}
    return {"Output": [jnp.clip(q, 0, 255).astype(jnp.uint8)]}


@register("dequantize", grad=None, attrs={"Scale": 1.0, "Shift": 0.0})
def _dequantize(ctx, ins, attrs):
    s, sh = attrs.get("Scale", 1.0), attrs.get("Shift", 0.0)
    v = x(ins, "Input")
    return {"Output": [(v.astype(F32) - sh) / s]}


@register("requantize", grad=None, attrs={"Scale_in": 1.0, "Scale_out": 1.0,
                                          "Shift_in": 0.0, "Shift_out": 0.0})
def _requantize(ctx, ins, attrs):
    v = x(ins, "Input").astype(F32)
    si, so = attrs.get("Scale_in", 1.0), attrs.get("Scale_out", 1.0)
    shi, sho = attrs.get("Shift_in", 0.0), attrs.get("Shift_out", 0.0)
    q = jnp.round((v - shi) / si * so + sho)
    return {"Output": [jnp.clip(q, -128, 127).astype(jnp.int8)]}


# ---------------------------------------------------------------------------
# conv variants / norm aliases
# ---------------------------------------------------------------------------

@register("deformable_conv_v1", no_grad_slots=(),
          attrs={"strides": [1, 1], "paddings": [0, 0],
                 "dilations": [1, 1], "groups": 1,
                 "deformable_groups": 1, "im2col_step": 64})
def _deformable_conv_v1(ctx, ins, attrs):
    """v1 = deformable conv without modulation mask
    (deformable_conv_v1_op.cc)."""
    from ..registry import require
    ins2 = dict(ins)
    ins2.pop("Mask", None)
    return require("deformable_conv").compute(ctx, ins2, dict(attrs))


@register("depthwise_conv2d_transpose",
          attrs={"strides": [1, 1], "paddings": [0, 0],
                 "dilations": [1, 1], "groups": 1,
                 "output_size": [], "output_padding": [],
                 "data_format": "NCHW"})
def _depthwise_conv2d_transpose(ctx, ins, attrs):
    from ..registry import require
    return require("conv2d_transpose").compute(ctx, dict(ins), dict(attrs))


@register("sync_batch_norm", infer_shape=None,
          attrs={"momentum": 0.9, "epsilon": 1e-5, "is_test": False,
                 "data_layout": "NCHW", "use_global_stats": False,
                 "trainable_statistics": False},
          no_grad_out_slots=("MeanOut", "VarianceOut", "SavedMean",
                             "SavedVariance", "ReserveSpace"))
def _sync_batch_norm(ctx, ins, attrs):
    """sync_batch_norm_op.cu's NCCL stats exchange is subsumed by GSPMD:
    under dp sharding the batch axis is GLOBAL inside the jitted program,
    so batch_norm's jnp.mean/var already reduce over every replica's rows
    (XLA inserts the cross-replica all-reduce). Single-device: identical
    to batch_norm."""
    from ..registry import require
    return require("batch_norm").compute(ctx, dict(ins), dict(attrs))


@register("inplace_abn",
          attrs={"momentum": 0.9, "epsilon": 1e-5, "is_test": False,
                 "data_layout": "NCHW", "use_global_stats": False,
                 "activation": "identity", "alpha": 0.01,
                 "trainable_statistics": False},
          no_grad_out_slots=("MeanOut", "VarianceOut", "SavedMean",
                             "SavedVariance", "ReserveSpace"))
def _inplace_abn(ctx, ins, attrs):
    """inplace_abn_op: batch norm + in-place activation (XLA's buffer
    reuse supplies the 'inplace'; we fuse bn+act functionally)."""
    from ..registry import require
    r = require("batch_norm").compute(ctx, dict(ins), dict(attrs))
    act = attrs.get("activation", "identity")
    y = r["Y"][0]
    if act == "leaky_relu":
        y = jax.nn.leaky_relu(y, attrs.get("alpha", 0.01))
    elif act == "elu":
        y = jax.nn.elu(y, attrs.get("alpha", 1.0))
    elif act != "identity":
        y = _ACTS[act](y)
    r["Y"] = [y]
    return r


# ---------------------------------------------------------------------------
# framework / program ops (save_op, load_op, run_program_op,
# conditional_block_op, split_selected_rows_op)
# ---------------------------------------------------------------------------

def _host_dump(path, fp16, combine=False):
    import os
    import pickle

    def do(*vals):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        arrs = [np.asarray(v).astype(np.float16) if fp16 else np.asarray(v)
                for v in vals]
        with open(path, "wb") as f:
            pickle.dump(arrs if combine else arrs[0], f, protocol=4)
        return np.zeros((1,), np.float32)
    return do


@register("save", grad=None, attrs={"file_path": "",
                                    "save_as_fp16": False,
                                    "overwrite": True})
def _save_op(ctx, ins, attrs):
    """save_op.cc: persist one variable to file_path. Reference-built
    save programs contain these; the write happens via an ORDERED
    io_callback so it runs (and survives DCE) inside the jitted block."""
    from jax.experimental import io_callback
    io_callback(_host_dump(attrs["file_path"],
                           attrs.get("save_as_fp16", False)),
                jax.ShapeDtypeStruct((1,), F32), x(ins), ordered=True)
    return {}


@register("load", grad=None, attrs={"file_path": "",
                                    "load_as_fp16": False})
def _load_op(ctx, ins, attrs):
    import pickle
    with open(attrs["file_path"], "rb") as f:
        v = pickle.load(f)
    v = np.asarray(v)
    if attrs.get("load_as_fp16"):
        v = v.astype(np.float16)
    return {"Out": [jnp.asarray(v)]}


@register("save_combine", grad=None, attrs={"file_path": "",
                                            "save_as_fp16": False,
                                            "overwrite": True})
def _save_combine(ctx, ins, attrs):
    from jax.experimental import io_callback
    vals = _xs(ins)
    io_callback(_host_dump(attrs["file_path"],
                           attrs.get("save_as_fp16", False), combine=True),
                jax.ShapeDtypeStruct((1,), F32), *vals, ordered=True)
    return {}


@register("load_combine", grad=None, attrs={"file_path": "",
                                            "load_as_fp16": False})
def _load_combine(ctx, ins, attrs):
    import pickle
    with open(attrs["file_path"], "rb") as f:
        vals = pickle.load(f)
    return {"Out": [jnp.asarray(np.asarray(v)) for v in vals]}


@register("run_program", grad=None, attrs={})
def _run_program(ctx, ins, attrs):
    """run_program_op.cc: execute a captured sub-block (the dy2static
    fallback path). Inputs bind by the block's feed names attr."""
    blk = attrs["sub_block"]
    feed_names = list(attrs.get("feed_names", []))
    fetch_names = list(attrs.get("fetch_names", []))
    env = dict(zip(feed_names, _xs(ins)))
    ctx.exec_block(blk, env)
    return {"Out": [env[n] for n in fetch_names]}


@register("conditional_block", grad=None, attrs={"is_scalar_condition":
                                                 True})
def _conditional_block(ctx, ins, attrs):
    """conditional_block_op.cc single-branch conditional: run the
    sub-block when Cond is true, else produce ZEROS of the recorded
    output shapes (the reference leaves outputs untouched; a functional
    program needs a defined else-value, and zero matches the reference's
    zero-initialised scope vars)."""
    blk = attrs["sub_block"]
    cond = x(ins, "Cond")
    out_names = list(attrs.get("out_names", []))
    cap_names = list(attrs.get("capture_names", []))
    caps = list(ins.get("Input") or [])

    def true_fn(*caps_v):
        env = dict(zip(cap_names, caps_v))
        ctx.exec_block(blk, env)
        return tuple(env[n] for n in out_names)

    # trace once to learn output shapes for the zero branch
    shaped = jax.eval_shape(true_fn, *caps)

    def false_fn(*caps_v):
        return tuple(jnp.zeros(s.shape, s.dtype) for s in shaped)

    pred = jnp.asarray(cond).reshape(()).astype(bool)
    outs = jax.lax.cond(pred, true_fn, false_fn, *caps)
    return {"Out": list(outs)}


@register("split_selected_rows", grad=None,
          attrs={"height_sections": []})
def _split_selected_rows(ctx, ins, attrs):
    """split_selected_rows_op.cc: partition a SelectedRows' rows by
    height sections (dense form: the rows tensor plus Rows ids)."""
    v = x(ins)
    rows = x(ins, "Rows")
    secs = list(attrs["height_sections"])
    bounds = np.cumsum([0] + secs)
    outs = []
    for lo, hi in zip(bounds[:-1], bounds[1:]):
        m = (rows >= lo) & (rows < hi)
        outs.append(jnp.where(m.reshape((-1,) + (1,) * (v.ndim - 1)),
                              v, 0))
    return {"Out": outs}


# ---------------------------------------------------------------------------
# PS sparse-table op forms (pull_sparse/push_sparse/
# distributed_lookup_table) over the fleet KV tier
# ---------------------------------------------------------------------------

_FLEET = None


def _fleet_kv():
    global _FLEET
    if _FLEET is None:
        from ...distributed.fleet.fleet_wrapper import FleetWrapper
        _FLEET = FleetWrapper()
    return _FLEET


@register("pull_sparse", grad=None, no_grad_slots=("Ids",),
          attrs={"EmbeddingDim": 8, "TableId": 0, "table_name": ""})
def _pull_sparse(ctx, ins, attrs):
    """pull_sparse_op.cc over the FleetWrapper KV (fleet_wrapper.h
    PullSparseVarsSync): host-side table fetch via io_callback."""
    from jax.experimental import io_callback
    dim = int(attrs.get("EmbeddingDim", 8))
    table = attrs.get("table_name") or f"table_{attrs.get('TableId', 0)}"
    ids = x(ins, "Ids")

    def do(ids_v):
        fw = _fleet_kv()
        return fw.pull_sparse(table, np.asarray(ids_v).ravel(), dim
                              ).astype(np.float32).reshape(
            ids_v.shape + (dim,))

    r = io_callback(do, jax.ShapeDtypeStruct(ids.shape + (dim,), F32),
                    ids, ordered=True)
    return {"Out": [r]}


register("pull_sparse_v2", _pull_sparse, grad=None,
         no_grad_slots=("Ids",),
         attrs={"EmbeddingDim": 8, "TableId": 0, "table_name": ""})


register("push_sparse_v2",
         lambda ctx, ins, attrs: __import__(
             "paddle_tpu.fluid.registry", fromlist=["require"]
         ).require("push_sparse").compute(ctx, dict(ins), dict(attrs)),
         grad=None, no_grad_slots=("Ids", "Grad"),
         attrs={"EmbeddingDim": 8, "TableId": 0, "table_name": ""})


@register("push_sparse", grad=None, no_grad_slots=("Ids", "Grad"),
          attrs={"EmbeddingDim": 8, "TableId": 0, "table_name": ""})
def _push_sparse(ctx, ins, attrs):
    from jax.experimental import io_callback
    dim = int(attrs.get("EmbeddingDim", 8))
    table = attrs.get("table_name") or f"table_{attrs.get('TableId', 0)}"
    ids = x(ins, "Ids")
    g = x(ins, "Grad") if x(ins, "Grad") is not None else x(ins, "Out")

    def do(ids_v, g_v):
        fw = _fleet_kv()
        fw.push_sparse(table, np.asarray(ids_v).ravel(),
                       np.asarray(g_v).reshape(-1, dim), dim)
        return np.zeros((1,), np.float32)

    done = io_callback(do, jax.ShapeDtypeStruct((1,), F32), ids, g,
                       ordered=True)
    return {"Out": [done]}


@register("distributed_lookup_table", grad=None, no_grad_slots=("Ids",),
          attrs={"table_id": 0, "is_distributed": True,
                 "lookup_table_version": "lookup_table",
                 "table_name": "", "dim": 8})
def _distributed_lookup_table(ctx, ins, attrs):
    """distributed_lookup_table_op.cc: sparse-table lookups routed to the
    PS tier; shares the pull_sparse transport."""
    from ..registry import require
    ids_list = list(ins.get("Ids") or [])
    dim = int(attrs.get("dim", attrs.get("EmbeddingDim", 8)))
    a = {"EmbeddingDim": dim, "TableId": attrs.get("table_id", 0),
         "table_name": attrs.get("table_name", "")}
    outs = []
    for ids in ids_list:
        r = require("pull_sparse").compute(ctx, {"Ids": [ids]}, dict(a))
        outs.append(r["Out"][0])
    return {"Outputs": outs}


# ---------------------------------------------------------------------------
# nms variants, linear interp, correlation
# ---------------------------------------------------------------------------

def _nms_variant(extra_index):
    def impl(ctx, ins, attrs):
        from ..registry import require
        r = require("multiclass_nms").compute(ctx, dict(ins), dict(attrs))
        outv = r["Out"][0]
        n, k = outv.shape[0], outv.shape[1]
        # Index: flat row index of each kept det in the padded output
        idx = (jnp.arange(n)[:, None] * k
               + jnp.arange(k)[None, :]).astype(jnp.int32)
        idx = jnp.where(outv[:, :, 0] >= 0, idx, -1)
        r["Index"] = [idx.reshape(-1, 1)]
        if extra_index:
            r.setdefault("NmsRoisNum", [jnp.sum(
                (outv[:, :, 0] >= 0).astype(jnp.int32), axis=1)])
        return r
    return impl


register("multiclass_nms2", _nms_variant(False), grad=None,
         attrs={"score_threshold": 0.05, "nms_top_k": 64,
                "keep_top_k": 100, "nms_threshold": 0.3, "nms_eta": 1.0,
                "normalized": True, "background_label": 0})
register("multiclass_nms3", _nms_variant(True), grad=None,
         attrs={"score_threshold": 0.05, "nms_top_k": 64,
                "keep_top_k": 100, "nms_threshold": 0.3, "nms_eta": 1.0,
                "normalized": True, "background_label": 0})


def _linear_interp_impl(ctx, ins, attrs):
    """linear_interp(_v2): 1-D linear resample on [N, C, L]
    (interpolate_op's linear mode)."""
    from .tail_ops import _interp_axis_linear
    v = x(ins)
    ow = attrs.get("out_w", 0) or 0
    if not ow:
        scale = attrs.get("scale") or [1.0]
        if isinstance(scale, (int, float)):
            scale = [scale]
        ow = int(round(v.shape[2] * scale[0]))
    ac = bool(attrs.get("align_corners", True))
    am = int(attrs.get("align_mode", 1))
    dt = v.dtype
    r = _interp_axis_linear(v.astype(F32), 2, int(ow), ac, am)
    return out(r.astype(dt))


for _n in ("linear_interp", "linear_interp_v2"):
    register(_n, _linear_interp_impl, no_grad_slots=("OutSize", "Scale"),
             attrs={"out_w": 0, "scale": [], "align_corners": True,
                    "align_mode": 1, "data_layout": "NCHW"})


@register("correlation", attrs={"pad_size": 0, "kernel_size": 1,
                                "max_displacement": 1, "stride1": 1,
                                "stride2": 1, "corr_type_multiply": 1})
def _correlation(ctx, ins, attrs):
    """FlowNet correlation (correlation_op.cu): mean over channels of
    dot products between x1 patches and displaced x2 patches."""
    a, b = x(ins, "Input1").astype(F32), x(ins, "Input2").astype(F32)
    n, c, h, w = a.shape
    d = int(attrs["max_displacement"])
    s2 = int(attrs["stride2"])
    disp = list(range(-d, d + 1, s2))
    pads = [(0, 0), (0, 0), (d, d), (d, d)]
    bp = jnp.pad(b, pads)
    rows = []
    for dy in disp:
        for dx in disp:
            shifted = bp[:, :, d + dy:d + dy + h, d + dx:d + dx + w]
            rows.append(jnp.mean(a * shifted, axis=1))
    return out(jnp.stack(rows, axis=1).astype(x(ins, "Input1").dtype))


# ---------------------------------------------------------------------------
# tree_conv (TBCNN) + rank_attention
# ---------------------------------------------------------------------------

def _tree_patches(edges: np.ndarray, n_nodes: int, max_depth: int):
    """Tree2ColUtil (math/tree2col.cc): per-root DFS patch of nodes
    within max_depth, each weighted by the continuous-binary-tree etas
    (tree2col.h TreeNode). Returns dense (A_l, A_r, A_t) [n, n] maps so
    the conv becomes three constant matmuls — linear in the features,
    so autodiff covers the backward."""
    tr: dict[int, list[int]] = {}
    node_count = 0
    for u, v in edges:
        if u == 0 or v == 0:
            break
        tr.setdefault(int(u), []).append(int(v))
        node_count += 1
    node_count += 1
    node_count = min(node_count, n_nodes)
    al = np.zeros((n_nodes, n_nodes), np.float32)
    ar = np.zeros_like(al)
    at = np.zeros_like(al)
    rows = 0
    md = float(max_depth)
    for root in range(1, node_count + 1):
        # iterative DFS mirroring construct_patch: (node, index, pclen,
        # depth); root = (root, 1, 1, 0)
        patch = [(root, 1, 1, 0)]
        stack = [(root, 1, 1, 0)]
        visited = {root}
        while stack:
            node, _, _, depth = stack.pop()
            kids = tr.get(node, [])
            for i, v in enumerate(kids):
                if v not in visited and depth + 1 < max_depth:
                    visited.add(v)
                    item = (v, i + 1, len(kids), depth + 1)
                    stack.append(item)
                    patch.append(item)
        for node, index, pclen, depth in patch:
            eta_t = (md - depth) / md
            tmp = 0.5 if pclen == 1 else (index - 1.0) / (pclen - 1.0)
            eta_l = (1.0 - eta_t) * tmp
            # reference tree2col.h: eta_r scales by (1 - eta_l) with the
            # FULL eta_l (which already carries the (1-eta_t) factor)
            eta_r = (1.0 - eta_t) * (1.0 - eta_l)
            al[rows, node - 1] += eta_l
            ar[rows, node - 1] += eta_r
            at[rows, node - 1] += eta_t
        rows += 1
    return al, ar, at


@register("tree_conv", no_grad_slots=("EdgeSet",),
          attrs={"max_depth": 2})
def _tree_conv(ctx, ins, attrs):
    """TBCNN tree convolution (tree_conv_op.h + math/tree2col): patches
    gathered per root with continuous-binary-tree eta weights, then one
    GEMM against the [F, 3, out, filters] filter. The tree structure
    (EdgeSet) must be a trace-time constant — it determines the sparse
    linear maps; features and filters stay fully differentiable."""
    emb = x(ins, "NodesVector")        # [B, n, F]
    edges = x(ins, "EdgeSet")          # [B, E, 2] int
    flt = x(ins, "Filter")             # [F, 3, out, nf]
    if isinstance(edges, jax.core.Tracer):
        raise NotImplementedError(
            "tree_conv: EdgeSet (the tree structure) must be a "
            "compile-time constant — it defines the patch gather maps")
    md = int(attrs.get("max_depth", 2))
    B, n, F = emb.shape
    Fd, three, out_sz, nf = flt.shape
    w2 = flt.reshape(F * 3, out_sz * nf)
    ed = np.asarray(edges)
    outs = []
    for b in range(B):
        al, ar, at = _tree_patches(ed[b], n, md)
        e = emb[b].astype(F32)
        # interleaved (f0l, f0r, f0t, f1l, ...) per tree2col row layout
        pl = jnp.asarray(al) @ e
        pr = jnp.asarray(ar) @ e
        pt = jnp.asarray(at) @ e
        patch = jnp.stack([pl, pr, pt], axis=-1).reshape(n, F * 3)
        outs.append((patch @ w2.astype(F32)).reshape(n, out_sz, nf))
    return out(jnp.stack(outs).astype(emb.dtype))


@register("rank_attention", no_grad_slots=("RankOffset",),
          no_grad_out_slots=("InputHelp", "InsRank"),
          attrs={"MaxRank": 3, "MaxSize": 0})
def _rank_attention(ctx, ins, attrs):
    """CTR rank attention (rank_attention_op.cc + rank_attention.cu.h):
    per instance, gather up to MaxRank rank-neighbors' feature rows and
    the per-(ins_rank, neighbor_rank) parameter blocks, then contract —
    out[i] = sum_k X[idx_k] @ P[(lower_i-1)*MaxRank + (faster_k-1)].
    Pure gathers + einsum: differentiable in X and RankParam, jittable
    with RankOffset as runtime data."""
    v = x(ins, "X").astype(F32)               # [N, d]
    ro = x(ins, "RankOffset").astype(jnp.int32)   # [N, 1+2*MaxRank]
    par = x(ins, "RankParam").astype(F32)     # [MaxRank^2 * d, pc]
    mr = int(attrs.get("MaxRank", 3))
    N, d = v.shape
    pc = par.shape[1]
    pblocks = par.reshape(mr * mr, d, pc)
    lower = ro[:, 0] - 1                      # [N] ins rank (may be -1)
    faster = ro[:, 1::2] - 1                  # [N, mr] neighbor ranks
    index = ro[:, 2::2]                       # [N, mr] row indices
    valid = (lower[:, None] >= 0) & (faster >= 0)
    xin = jnp.where(valid[..., None],
                    v[jnp.clip(index, 0, N - 1)], 0.0)      # [N, mr, d]
    bsel = jnp.clip(lower[:, None] * mr + faster, 0, mr * mr - 1)
    psel = jnp.where(valid[..., None, None],
                     pblocks[bsel], 0.0)      # [N, mr, d, pc]
    r = jnp.einsum("nkd,nkdp->np", xin, psel)
    return {"Out": [r.astype(x(ins, "X").dtype)],
            "InputHelp": [xin.reshape(N, mr * d)],
            "InsRank": [(lower + 1).astype(jnp.float32).reshape(N, 1)]}
