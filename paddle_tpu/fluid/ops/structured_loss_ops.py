"""Structured / sampled losses: CTC, linear-chain CRF, NCE, hsigmoid.

TPU-native equivalents of the reference's
  operators/warpctc_op.cc            (wraps baidu warp-ctc)
  operators/linear_chain_crf_op.cc / crf_decoding_op.cc
  operators/nce_op.cc
  operators/hierarchical_sigmoid_op.cc
Each is a jax compute: the dynamic-programming recursions (CTC alpha, CRF
forward, Viterbi) are `lax.scan`s over time — one compiled loop, static
shapes, grads via auto-vjp through the scan. Variable lengths come in as
dense Length tensors (the LoD-free design, SURVEY §7).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..registry import register, same_shape_as
from .common import x

_NEG = -1e30


# ---------------------------------------------------------------------------
# CTC (warpctc parity)
# ---------------------------------------------------------------------------

def _ctc_loss_batch(logp, labels, logit_len, label_len, blank):
    """logp: [T, B, C] log-softmax; labels: [B, L]; returns [B] neg log lik.

    Standard CTC alpha recursion over the extended label sequence
    z = [blank, l1, blank, l2, ..., blank] (length S = 2L+1), log domain.
    """
    T, B, C = logp.shape
    L = labels.shape[1]
    S = 2 * L + 1
    # extended labels: even positions blank, odd positions the labels
    ext = jnp.full((B, S), blank, labels.dtype)
    ext = ext.at[:, 1::2].set(labels)
    # skip-transition allowed into odd position s when ext[s] != ext[s-2]
    skip_ok = jnp.concatenate(
        [jnp.zeros((B, 2), bool), ext[:, 2:] != ext[:, :-2]], axis=1)

    def emit(t):
        return jnp.take_along_axis(logp[t], ext, axis=1)  # [B, S]

    alpha0 = jnp.full((B, S), _NEG, jnp.float32)
    alpha0 = alpha0.at[:, 0].set(logp[0, :, blank])
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(L > 0, jnp.take_along_axis(
            logp[0], ext[:, 1:2], axis=1)[:, 0], _NEG))

    def lse(a, b):
        m = jnp.maximum(a, b)
        safe = jnp.maximum(m, _NEG)
        return jnp.where((a <= _NEG) & (b <= _NEG), _NEG,
                         safe + jnp.log(jnp.exp(a - safe)
                                        + jnp.exp(b - safe)))

    def step(alpha, t):
        stay = alpha
        from_prev = jnp.concatenate(
            [jnp.full((B, 1), _NEG), alpha[:, :-1]], axis=1)
        from_skip = jnp.concatenate(
            [jnp.full((B, 2), _NEG), alpha[:, :-2]], axis=1)
        from_skip = jnp.where(skip_ok, from_skip, _NEG)
        a = lse(lse(stay, from_prev), from_skip) + emit(t)
        # past this sample's input length the alphas freeze
        a = jnp.where((t < logit_len)[:, None], a, alpha)
        return a, None

    alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
    # final: sum of alpha at S-1 and S-2 where S = 2*label_len+1
    send = 2 * label_len  # index of last blank
    a_last = jnp.take_along_axis(alpha, send[:, None], axis=1)[:, 0]
    a_prev = jnp.take_along_axis(
        alpha, jnp.maximum(send - 1, 0)[:, None], axis=1)[:, 0]
    a_prev = jnp.where(label_len > 0, a_prev, _NEG)
    return -lse(a_last, a_prev)


def _warpctc_infer(op):
    lv = op.invar("LogitsLength")
    if lv is not None and lv.shape:
        b = lv.shape[0]
        for name in op.output("Loss"):
            op.block.create_var(name=name, shape=(b, 1), dtype="float32")


@register("warpctc", infer_shape=_warpctc_infer,
          no_grad_slots=("Label", "LogitsLength", "LabelLength"),
          no_grad_out_slots=("WarpCTCGrad",),
          attrs={"blank": 0, "norm_by_times": False})
def _warpctc(ctx, ins, attrs):
    """Padded-dense CTC (reference warpctc_op with Length inputs):
    Logits [B, T, C] raw (softmax applied inside, like warp-ctc);
    Label [B, L]; LogitsLength, LabelLength [B]."""
    logits = x(ins, "Logits").astype(jnp.float32)
    labels = x(ins, "Label")
    llen = x(ins, "LogitsLength").reshape(-1).astype(jnp.int32)
    tlen = x(ins, "LabelLength").reshape(-1).astype(jnp.int32)
    logp = jax.nn.log_softmax(logits, axis=-1).transpose(1, 0, 2)
    nll = _ctc_loss_batch(logp, labels, llen, tlen, attrs["blank"])
    if attrs.get("norm_by_times"):
        # warp-ctc applies time normalization to the GRADIENT only; the
        # reported loss stays unnormalized. Value = nll, gradient =
        # d(nll/T): value-from-A-grad-from-B via stop_gradient algebra.
        scaled = nll / jnp.maximum(llen.astype(jnp.float32), 1.0)
        nll = jax.lax.stop_gradient(nll) + scaled - \
            jax.lax.stop_gradient(scaled)
    return {"Loss": [nll[:, None]],
            "WarpCTCGrad": [jnp.zeros((1,), jnp.float32)]}


# ---------------------------------------------------------------------------
# linear-chain CRF
# ---------------------------------------------------------------------------

def _crf_unpack(trans):
    """Paddle transition layout [num_tags+2, num_tags]: row 0 start
    weights, row 1 stop weights, rows 2.. the [from, to] matrix."""
    return trans[0], trans[1], trans[2:]


def _crf_ll_infer(op):
    ev = op.invar("Emission")
    if ev is not None and ev.shape:
        b = ev.shape[0]
        for name in op.output("LogLikelihood"):
            op.block.create_var(name=name, shape=(b, 1), dtype="float32")


@register("linear_chain_crf", infer_shape=_crf_ll_infer,
          no_grad_slots=("Label", "Length"),
          no_grad_out_slots=("Alpha", "EmissionExps", "TransitionExps"),
          attrs={})
def _linear_chain_crf(ctx, ins, attrs):
    """Emission [B, T, N] + Label [B, T] + Length [B] -> LogLikelihood
    [B, 1] (reference linear_chain_crf_op.cc, padded/Length form). The
    forward (partition) recursion is a lax.scan; grads flow by vjp —
    the reference's hand-written backward computing marginal expectations
    is exactly d(logZ)/d(emission), which autodiff supplies."""
    em = x(ins, "Emission").astype(jnp.float32)      # [B, T, N]
    lab = x(ins, "Label").astype(jnp.int32)          # [B, T]
    if lab.ndim == 3:
        lab = lab[..., 0]
    length = x(ins, "Length")
    B, T, N = em.shape
    if length is None:
        length = jnp.full((B,), T, jnp.int32)
    length = length.reshape(-1).astype(jnp.int32)
    start_w, stop_w, trans = _crf_unpack(x(ins, "Transition")
                                         .astype(jnp.float32))

    # ---- partition function: log-domain forward over time
    a0 = start_w[None, :] + em[:, 0]                  # [B, N]

    def step(a, t):
        # a[b, i] + trans[i, j] + em[b, t, j]
        nxt = jax.nn.logsumexp(a[:, :, None] + trans[None], axis=1) \
            + em[:, t]
        a = jnp.where((t < length)[:, None], nxt, a)
        return a, None

    a, _ = jax.lax.scan(step, a0, jnp.arange(1, T))
    logz = jax.nn.logsumexp(a + stop_w[None, :], axis=1)      # [B]

    # ---- gold path score
    t_idx = jnp.arange(T)
    mask = (t_idx[None, :] < length[:, None]).astype(jnp.float32)
    em_gold = jnp.take_along_axis(em, lab[:, :, None], axis=2)[:, :, 0]
    gold = jnp.sum(em_gold * mask, axis=1)
    gold = gold + start_w[lab[:, 0]]
    last = jnp.take_along_axis(lab, (length - 1)[:, None], axis=1)[:, 0]
    gold = gold + stop_w[last]
    pair = trans[lab[:, :-1], lab[:, 1:]]             # [B, T-1]
    gold = gold + jnp.sum(pair * mask[:, 1:], axis=1)
    z1 = jnp.zeros((1,), jnp.float32)  # reference exposes exp buffers for
    return {"LogLikelihood": [(gold - logz)[:, None]],  # its hand backward;
            "Alpha": [a], "EmissionExps": [z1],  # vjp needs none of that
            "TransitionExps": [z1]}


def _crf_decode_infer(op):
    ev = op.invar("Emission")
    if ev is not None and ev.shape:
        for name in op.output("ViterbiPath"):
            op.block.create_var(name=name, shape=ev.shape[:2],
                                dtype="int64")


@register("crf_decoding", grad=None, infer_shape=_crf_decode_infer,
          no_grad_slots=("Emission", "Transition", "Label", "Length"))
def _crf_decoding(ctx, ins, attrs):
    """Viterbi decode (reference crf_decoding_op.cc): forward scan keeps
    backpointers, reverse scan reads the best path; positions past Length
    are 0."""
    em = x(ins, "Emission").astype(jnp.float32)
    length = x(ins, "Length")
    B, T, N = em.shape
    if length is None:
        length = jnp.full((B,), T, jnp.int32)
    length = length.reshape(-1).astype(jnp.int32)
    start_w, stop_w, trans = _crf_unpack(x(ins, "Transition")
                                         .astype(jnp.float32))

    v0 = start_w[None, :] + em[:, 0]

    def fwd(v, t):
        scores = v[:, :, None] + trans[None]          # [B, i, j]
        best = jnp.max(scores, axis=1) + em[:, t]
        bp = jnp.argmax(scores, axis=1)               # [B, j]
        live = (t < length)[:, None]
        return jnp.where(live, best, v), jnp.where(live, bp, -1)

    v, bps = jax.lax.scan(fwd, v0, jnp.arange(1, T))  # bps: [T-1, B, N]
    last_tag = jnp.argmax(v + stop_w[None, :], axis=1)  # [B]

    def back(tag, t):
        bp_t = bps[t]                                  # [B, N]
        prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
        # only positions t+1 <= length-1 are real transitions
        tag_new = jnp.where(t + 1 < length, prev, tag)
        return tag_new, tag

    tag0, path_rev = jax.lax.scan(back, last_tag,
                                  jnp.arange(T - 2, -1, -1))
    path = jnp.concatenate(
        [tag0[None, :], path_rev[::-1]], axis=0).T      # [B, T]
    t_idx = jnp.arange(T)
    path = jnp.where(t_idx[None, :] < length[:, None], path, 0)
    return {"ViterbiPath": [path.astype(jnp.int64)]}


# ---------------------------------------------------------------------------
# NCE
# ---------------------------------------------------------------------------

def _nce_infer(op):
    iv = op.invar("Input")
    if iv is not None and iv.shape:
        for name in op.output("Cost"):
            op.block.create_var(name=name, shape=(iv.shape[0], 1),
                                dtype="float32")


@register("nce", infer_shape=_nce_infer, stochastic=True,
          no_grad_slots=("Label", "SampleWeight"),
          no_grad_out_slots=("SampleLogits", "SampleLabels"),
          attrs={"num_total_classes": -1, "num_neg_samples": 10,
                 "sampler": 0, "seed": 0, "is_sparse": False})
def _nce(ctx, ins, attrs):
    """Noise-contrastive estimation (reference nce_op.h): binary logistic
    discrimination of the true class against `num_neg_samples` classes
    drawn from the (log-)uniform noise distribution. Sampling uses the
    op's stable rng stream; the noise probability correction q(y) follows
    the reference (uniform sampler: q = 1/num_classes)."""
    inp = x(ins, "Input").astype(jnp.float32)          # [B, D]
    lab = x(ins, "Label").reshape(-1).astype(jnp.int32)  # [B]
    w = x(ins, "Weight").astype(jnp.float32)           # [num_classes, D]
    b = x(ins, "Bias")
    B = inp.shape[0]
    num_classes = attrs["num_total_classes"]
    if num_classes <= 0:
        num_classes = w.shape[0]
    k = attrs["num_neg_samples"]
    key = ctx.rng(attrs) if ctx is not None \
        else jax.random.PRNGKey(attrs.get("_rng_id", 0) or 0)
    if attrs.get("sampler", 0) == 1:  # log-uniform (Zipf)
        u = jax.random.uniform(key, (B, k))
        neg = (jnp.exp(u * math.log(num_classes + 1)) - 1.0) \
            .astype(jnp.int32)
        neg = jnp.clip(neg, 0, num_classes - 1)
        logq = jnp.log((jnp.log1p(1.0 / (neg + 1.0)))
                       / math.log(num_classes + 1))
    else:  # uniform
        neg = jax.random.randint(key, (B, k), 0, num_classes)
        logq = jnp.full((B, k), -math.log(num_classes))
    logq_pos = jnp.where(
        attrs.get("sampler", 0) == 1,
        jnp.log(jnp.log1p(1.0 / (lab + 1.0)) / math.log(num_classes + 1)),
        jnp.full((B,), -math.log(num_classes)))

    def score(cls):                                    # cls [B, k']
        wv = w[cls]                                    # [B, k', D]
        s = jnp.einsum("bkd,bd->bk", wv, inp)
        if b is not None:
            s = s + b.reshape(-1)[cls]
        return s

    s_pos = score(lab[:, None])[:, 0]                  # [B]
    s_neg = score(neg)                                 # [B, k]
    # NCE logits: s - log(k*q)
    l_pos = s_pos - (math.log(k) + logq_pos)
    l_neg = s_neg - (math.log(k) + logq)
    cost = jax.nn.softplus(-l_pos) \
        + jnp.sum(jax.nn.softplus(l_neg), axis=1)
    return {"Cost": [cost[:, None]], "SampleLogits": [s_neg],
            "SampleLabels": [neg]}


# ---------------------------------------------------------------------------
# hierarchical sigmoid (complete binary tree)
# ---------------------------------------------------------------------------

def _hsig_paths(num_classes: int):
    """Heap paths of the default complete binary tree (reference
    framework/... SimpleCode): class c maps to heap node c+num_classes;
    internal node at depth d is (c+num_classes) >> (depth-d), its code bit
    the next bit down. Returns (node_ids, codes, mask) as numpy
    [num_classes, max_depth] — static tables baked into the graph."""
    max_depth = int(math.floor(math.log2(num_classes))) + 1
    ids = np.zeros((num_classes, max_depth), np.int32)
    codes = np.zeros((num_classes, max_depth), np.float32)
    mask = np.zeros((num_classes, max_depth), np.float32)
    for c in range(num_classes):
        n = c + num_classes
        depth = n.bit_length() - 1
        for d in range(depth):
            node = n >> (depth - d)
            bit = (n >> (depth - d - 1)) & 1
            ids[c, d] = node - 1          # internal nodes 1.. -> row 0..
            codes[c, d] = float(bit)
            mask[c, d] = 1.0
    return ids, codes, mask


def _hsig_infer(op):
    iv = op.invar("X")
    if iv is not None and iv.shape:
        for name in op.output("Out"):
            op.block.create_var(name=name, shape=(iv.shape[0], 1),
                                dtype="float32")


@register("hierarchical_sigmoid", infer_shape=_hsig_infer,
          no_grad_slots=("Label",),
          no_grad_out_slots=("PreOut", "W_Out"),
          attrs={"num_classes": 2, "is_sparse": False})
def _hierarchical_sigmoid(ctx, ins, attrs):
    """Reference hierarchical_sigmoid_op.cc (default complete-binary-tree
    codes): cost = sum over the label's root path of
    softplus((1-2*code)*(x @ w_node + b_node)) — log-time softmax."""
    inp = x(ins, "X").astype(jnp.float32)              # [B, D]
    lab = x(ins, "Label").reshape(-1).astype(jnp.int32)
    w = x(ins, "W").astype(jnp.float32)                # [num_classes-1, D]
    b = x(ins, "Bias")
    num_classes = attrs["num_classes"]
    ids_np, codes_np, mask_np = _hsig_paths(num_classes)
    ids = jnp.asarray(ids_np)[lab]                     # [B, depth]
    codes = jnp.asarray(codes_np)[lab]
    mask = jnp.asarray(mask_np)[lab]
    wn = w[ids]                                        # [B, depth, D]
    pre = jnp.einsum("bkd,bd->bk", wn, inp)
    if b is not None:
        pre = pre + b.reshape(-1)[ids]
    # code bit 1 => sigmoid(pre), 0 => sigmoid(-pre); nll = softplus(∓pre)
    sign = 1.0 - 2.0 * codes
    cost = jnp.sum(jax.nn.softplus(sign * pre) * mask, axis=1)
    return {"Out": [cost[:, None]], "PreOut": [pre],
            "W_Out": [jnp.zeros((1,), jnp.float32)]}
