"""Shared helpers for op compute/infer functions."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def x(ins, slot="X"):
    v = ins.get(slot)
    return v[0] if v else None


def out(val, slot="Out"):
    return {slot: [val]}


def bcast_to_x(xv, yv, axis: int):
    """Paddle elementwise broadcast: align y's dims to x starting at `axis`
    (reference operators/elementwise/elementwise_op_function.h)."""
    if axis == -1 or xv.ndim == yv.ndim:
        return yv
    axis = int(axis)
    new_shape = (1,) * axis + yv.shape + (1,) * (xv.ndim - axis - yv.ndim)
    return yv.reshape(new_shape)


def normalize_axes(dim, ndim):
    if dim is None:
        return tuple(range(ndim))
    if isinstance(dim, int):
        dim = [dim]
    return tuple(sorted(d % ndim for d in dim))


def static_reduce_shape(shape, dim, keep_dim, reduce_all):
    if shape is None:
        return None
    nd = len(shape)
    axes = set(range(nd)) if reduce_all or not dim else {d % nd for d in dim}
    if keep_dim:
        return tuple(1 if i in axes else s for i, s in enumerate(shape))
    kept = tuple(s for i, s in enumerate(shape) if i not in axes)
    return kept if kept else (1,)


def np_dtype(dtype) -> np.dtype:
    import paddle_tpu.fluid.core as core
    return np.dtype(core.convert_dtype(dtype))


def astype(v, dtype):
    return v.astype(np_dtype(dtype)) if v is not None else None
