"""Dense math ops: elementwise, matmul, activations, reductions.

Replaces the reference kernel families:
  operators/elementwise/* (broadcast engine elementwise_op_function.h)
  operators/matmul_op.cc, matmul_v2_op.cc, mul_op.cc
  operators/activation_op.* (~40 functors)
  operators/reduce_ops/*, mean_op, sum_op, scale_op, cast_op, clip_op
All are jnp/lax expressions — XLA maps matmuls onto the MXU and fuses the
elementwise neighbourhood automatically.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import registry
from ..registry import register, same_shape_as, elementwise_infer
from .common import x, out, bcast_to_x, static_reduce_shape, np_dtype


# ---------------------------------------------------------------------------
# elementwise binary ops (axis-broadcast semantics of the reference)
# ---------------------------------------------------------------------------

def _ew(name, fn):
    def compute(ctx, ins, attrs, _fn=fn):
        a, b = x(ins, "X"), x(ins, "Y")
        b = bcast_to_x(a, b, attrs.get("axis", -1))
        return out(_fn(a, b))
    register("elementwise_" + name, compute, attrs={"axis": -1},
             infer_shape=elementwise_infer)


_ew("add", jnp.add)
_ew("sub", jnp.subtract)
_ew("mul", jnp.multiply)
_ew("div", jnp.divide)
_ew("max", jnp.maximum)
_ew("min", jnp.minimum)
_ew("pow", jnp.power)
_ew("mod", jnp.mod)
_ew("floordiv", jnp.floor_divide)


# ---------------------------------------------------------------------------
# matmul family → XLA dot_general on the MXU
# ---------------------------------------------------------------------------

def _matmul_infer(op):
    xv, yv = op.invar("X"), op.invar("Y")
    if xv is None or yv is None or xv.shape is None or yv.shape is None:
        return
    tx = op.attr("transpose_X", op.attr("trans_x", False))
    ty = op.attr("transpose_Y", op.attr("trans_y", False))
    xs, ys = list(xv.shape), list(yv.shape)
    if len(xs) == 1:
        xs = [1, xs[0]]
    if len(ys) == 1:
        ys = [ys[0], 1]
    if tx:
        xs[-1], xs[-2] = xs[-2], xs[-1]
    if ty:
        ys[-1], ys[-2] = ys[-2], ys[-1]
    batch = xs[:-2] if len(xs) >= len(ys) else ys[:-2]
    shape = tuple(batch + [xs[-2], ys[-1]])
    for n in op.output("Out"):
        op.block.create_var(name=n, shape=shape, dtype=xv.dtype)


def _matmul(ctx, ins, attrs):
    a, b = x(ins, "X"), x(ins, "Y")
    if attrs.get("transpose_X") or attrs.get("trans_x"):
        a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
    if attrs.get("transpose_Y") or attrs.get("trans_y"):
        b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
    r = jnp.matmul(a, b)
    alpha = attrs.get("alpha", 1.0)
    if alpha != 1.0:
        r = r * alpha
    return out(r)


register("matmul", _matmul, infer_shape=_matmul_infer,
         attrs={"transpose_X": False, "transpose_Y": False, "alpha": 1.0})
register("matmul_v2", _matmul, infer_shape=_matmul_infer,
         attrs={"trans_x": False, "trans_y": False})


def _mul_infer(op):
    xv, yv = op.invar("X"), op.invar("Y")
    if xv is None or xv.shape is None or yv is None or yv.shape is None:
        return
    xn = op.attr("x_num_col_dims", 1)
    yn = op.attr("y_num_col_dims", 1)
    shape = tuple(list(xv.shape[:xn]) + list(yv.shape[yn:]))
    for n in op.output("Out"):
        op.block.create_var(name=n, shape=shape, dtype=xv.dtype)


@register("mul", infer_shape=_mul_infer,
          attrs={"x_num_col_dims": 1, "y_num_col_dims": 1})
def _mul(ctx, ins, attrs):
    # reference mul_op: flatten x to 2-D at x_num_col_dims, same for y
    a, b = x(ins, "X"), x(ins, "Y")
    xn, yn = attrs["x_num_col_dims"], attrs["y_num_col_dims"]
    import math as _math
    a2 = a.reshape((_math.prod(a.shape[:xn]), -1)) \
        if a.ndim > 2 or xn != 1 else a
    b2 = b.reshape((_math.prod(b.shape[:yn]), -1)) \
        if b.ndim > 2 or yn != 1 else b
    r = a2 @ b2
    out_shape = a.shape[:xn] + b.shape[yn:]
    return out(r.reshape(out_shape))


# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------

def _act(name, fn, extra_attrs=None):
    def compute(ctx, ins, attrs, _fn=fn):
        return out(_fn(x(ins), attrs))
    register(name, compute, attrs=extra_attrs or {},
             infer_shape=same_shape_as("X"))


_act("relu", lambda v, a: jax.nn.relu(v))
_act("relu6", lambda v, a: jnp.clip(v, 0, a.get("threshold", 6.0)),
     {"threshold": 6.0})
_act("sigmoid", lambda v, a: jax.nn.sigmoid(v))
_act("tanh", lambda v, a: jnp.tanh(v))
_act("exp", lambda v, a: jnp.exp(v))
_act("log", lambda v, a: jnp.log(v))
_act("log2", lambda v, a: jnp.log2(v))
_act("log10", lambda v, a: jnp.log10(v))
_act("log1p", lambda v, a: jnp.log1p(v))
_act("sqrt", lambda v, a: jnp.sqrt(v))
_act("rsqrt", lambda v, a: jax.lax.rsqrt(v))
_act("square", lambda v, a: jnp.square(v))
_act("abs", lambda v, a: jnp.abs(v))
_act("ceil", lambda v, a: jnp.ceil(v))
_act("floor", lambda v, a: jnp.floor(v))
_act("round", lambda v, a: jnp.round(v))
_act("reciprocal", lambda v, a: 1.0 / v)
_act("sin", lambda v, a: jnp.sin(v))
_act("cos", lambda v, a: jnp.cos(v))
_act("tan", lambda v, a: jnp.tan(v))
_act("asin", lambda v, a: jnp.arcsin(v))
_act("acos", lambda v, a: jnp.arccos(v))
_act("atan", lambda v, a: jnp.arctan(v))
_act("sinh", lambda v, a: jnp.sinh(v))
_act("cosh", lambda v, a: jnp.cosh(v))
_act("gelu", lambda v, a: jax.nn.gelu(v, approximate=a.get("approximate", False)),
     {"approximate": False})
_act("leaky_relu", lambda v, a: jax.nn.leaky_relu(v, a.get("alpha", 0.02)),
     {"alpha": 0.02})
_act("elu", lambda v, a: jax.nn.elu(v, a.get("alpha", 1.0)), {"alpha": 1.0})
_act("selu", lambda v, a: jax.nn.selu(v),
     {"scale": 1.0507009873554805, "alpha": 1.6732632423543772})
_act("softplus", lambda v, a: jax.nn.softplus(v))
_act("softsign", lambda v, a: jax.nn.soft_sign(v))
_act("silu", lambda v, a: jax.nn.silu(v))
_act("swish", lambda v, a: v * jax.nn.sigmoid(a.get("beta", 1.0) * v),
     {"beta": 1.0})
_act("mish", lambda v, a: v * jnp.tanh(jax.nn.softplus(v)))
_act("hard_sigmoid",
     lambda v, a: jnp.clip(a.get("slope", 0.2) * v + a.get("offset", 0.5), 0, 1),
     {"slope": 0.2, "offset": 0.5})
_act("hard_swish",
     lambda v, a: v * jnp.clip(v + a.get("offset", 3.0), 0,
                               a.get("threshold", 6.0)) / a.get("scale", 6.0),
     {"threshold": 6.0, "scale": 6.0, "offset": 3.0})
_act("hard_tanh",
     lambda v, a: jnp.clip(v, a.get("t_min", -1.0), a.get("t_max", 1.0)),
     {"t_min": -1.0, "t_max": 1.0})
_act("logsigmoid", lambda v, a: jax.nn.log_sigmoid(v))
_act("erf", lambda v, a: jax.scipy.special.erf(v))
_act("tanh_shrink", lambda v, a: v - jnp.tanh(v))
_act("softshrink",
     lambda v, a: jnp.where(v > a.get("lambda", 0.5), v - a.get("lambda", 0.5),
                            jnp.where(v < -a.get("lambda", 0.5),
                                      v + a.get("lambda", 0.5), 0.0)),
     {"lambda": 0.5})
_act("hard_shrink",
     lambda v, a: jnp.where(jnp.abs(v) > a.get("threshold", 0.5), v, 0.0),
     {"threshold": 0.5})
_act("thresholded_relu",
     lambda v, a: jnp.where(v > a.get("threshold", 1.0), v, 0.0),
     {"threshold": 1.0})
_act("stanh",
     lambda v, a: a.get("scale_b", 1.7159) * jnp.tanh(a.get("scale_a", 0.67) * v),
     {"scale_a": 0.67, "scale_b": 1.7159})


_act("sign", lambda v, a: jnp.sign(v))


@register("pow", infer_shape=same_shape_as("X"), attrs={"factor": 1.0})
def _pow(ctx, ins, attrs):
    f = x(ins, "FactorTensor")
    return out(jnp.power(x(ins), f if f is not None else attrs["factor"]))


@register("clip", infer_shape=same_shape_as("X"),
          attrs={"min": float("-inf"), "max": float("inf")})
def _clip(ctx, ins, attrs):
    lo = x(ins, "Min")
    hi = x(ins, "Max")
    lo = attrs["min"] if lo is None else lo
    hi = attrs["max"] if hi is None else hi
    return out(jnp.clip(x(ins), lo, hi))


@register("scale", infer_shape=same_shape_as("X"),
          attrs={"scale": 1.0, "bias": 0.0, "bias_after_scale": True})
def _scale(ctx, ins, attrs):
    v = x(ins)
    s = x(ins, "ScaleTensor")
    s = attrs["scale"] if s is None else s
    if attrs["bias_after_scale"]:
        return out(v * s + attrs["bias"])
    return out((v + attrs["bias"]) * s)


@register("sum", infer_shape=same_shape_as("X"))
def _sum(ctx, ins, attrs):
    vals = [v for v in ins.get("X", []) if v is not None]
    from ..selected_rows import SelectedRows
    srs = [v for v in vals if isinstance(v, SelectedRows)]
    if srs:
        if len(srs) == len(vals):
            # all-sparse fan-out: concatenation IS accumulation (consumers
            # scatter-add; reference math/selected_rows_functor.cc add)
            return out(SelectedRows(
                jnp.concatenate([s.rows for s in srs]),
                jnp.concatenate([s.values for s in srs]), srs[0].height))
        vals = [v.to_dense() if isinstance(v, SelectedRows) else v
                for v in vals]
    from .control_ops import TensorArray
    if isinstance(vals[0], TensorArray):
        # TensorArray cotangent fan-in (e.g. two array_reads of one
        # array): add the buffers; length is carried, not summed
        buf = vals[0].buffer
        for v in vals[1:]:
            buf = buf + v.buffer
        return out(TensorArray(buf, vals[0].length, vals[0].static_len))
    r = vals[0]
    for v in vals[1:]:
        r = r + v
    return out(r)


@register("merge_selected_rows", grad=None)
def _merge_selected_rows(ctx, ins, attrs):
    """Reference operators/merge_selected_rows_op.cc: combine duplicate
    rows. Under jit the row count is static, so tracing is identity
    (consumers scatter-add, which already accumulates duplicates); on
    concrete host values the real merge runs."""
    sr = x(ins)
    from ..selected_rows import SelectedRows
    if isinstance(sr, SelectedRows) and \
            not isinstance(sr.rows, jax.core.Tracer):
        return out(sr.merged())
    return out(sr)


@register("get_tensor_from_selected_rows", grad=None)
def _get_tensor_from_selected_rows(ctx, ins, attrs):
    sr = x(ins)
    from ..selected_rows import SelectedRows
    return out(sr.to_dense() if isinstance(sr, SelectedRows) else sr)


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------

def _reduce(name, fn):
    def infer(op):
        v = op.invar("X")
        if v is None or v.shape is None:
            return
        shape = static_reduce_shape(v.shape, op.attr("dim"),
                                    op.attr("keep_dim", False),
                                    op.attr("reduce_all", False))
        for n in op.output("Out"):
            op.block.create_var(name=n, shape=shape, dtype=v.dtype)

    def compute(ctx, ins, attrs, _fn=fn):
        v = x(ins)
        axes = None if attrs.get("reduce_all") or not attrs.get("dim") \
            else tuple(d % v.ndim for d in attrs["dim"])
        r = _fn(v, axis=axes, keepdims=attrs.get("keep_dim", False))
        if r.ndim == 0:
            r = r.reshape((1,))
        return out(r)

    register(name, compute, infer_shape=infer,
             attrs={"dim": [0], "keep_dim": False, "reduce_all": False})


_reduce("reduce_sum", jnp.sum)
_reduce("reduce_mean", jnp.mean)
_reduce("reduce_max", jnp.max)
_reduce("reduce_min", jnp.min)
_reduce("reduce_prod", jnp.prod)
_reduce("reduce_any", jnp.any)
_reduce("reduce_all", jnp.all)


def _mean_infer(op):
    v = op.invar("X")
    for n in op.output("Out"):
        op.block.create_var(name=n, shape=(1,),
                            dtype=v.dtype if v is not None else "float32")


@register("mean", infer_shape=_mean_infer)
def _mean(ctx, ins, attrs):
    return out(jnp.mean(x(ins)).reshape((1,)))


@register("squared_l2_norm", infer_shape=_mean_infer)
def _squared_l2_norm(ctx, ins, attrs):
    return out(jnp.sum(jnp.square(x(ins))).reshape((1,)))


@register("frobenius_norm", infer_shape=_mean_infer)
def _frobenius_norm(ctx, ins, attrs):
    return out(jnp.sqrt(jnp.sum(jnp.square(x(ins)))).reshape((1,)))


@register("p_norm", infer_shape=_mean_infer,
          attrs={"porder": 2.0, "axis": -1, "epsilon": 1e-12, "keepdim": False,
                 "asvector": False})
def _p_norm(ctx, ins, attrs):
    v = x(ins)
    p = attrs["porder"]
    if attrs.get("asvector"):
        r = jnp.sum(jnp.abs(v) ** p) ** (1.0 / p)
        return out(r.reshape((1,)))
    r = jnp.sum(jnp.abs(v) ** p, axis=attrs["axis"],
                keepdims=attrs["keepdim"]) ** (1.0 / p)
    return out(r)


# ---------------------------------------------------------------------------
# comparison / logical (non-differentiable)
# ---------------------------------------------------------------------------

def _cmp(name, fn):
    def infer(op):
        v = op.invar("X")
        if v is None:
            return
        for n in op.output("Out"):
            op.block.create_var(name=n, shape=v.shape, dtype="bool")

    def compute(ctx, ins, attrs, _fn=fn):
        return out(_fn(x(ins, "X"), x(ins, "Y")))
    register(name, compute, grad=None, infer_shape=infer, attrs={"axis": -1})


_cmp("equal", jnp.equal)
_cmp("not_equal", jnp.not_equal)
_cmp("less_than", jnp.less)
_cmp("less_equal", jnp.less_equal)
_cmp("greater_than", jnp.greater)
_cmp("greater_equal", jnp.greater_equal)
_cmp("logical_and", jnp.logical_and)
_cmp("logical_or", jnp.logical_or)
_cmp("logical_xor", jnp.logical_xor)


@register("logical_not", grad=None, infer_shape=same_shape_as("X"))
def _logical_not(ctx, ins, attrs):
    return out(jnp.logical_not(x(ins)))


@register("isfinite", grad=None, infer_shape=_mean_infer)
def _isfinite(ctx, ins, attrs):
    return out(jnp.all(jnp.isfinite(x(ins))).reshape((1,)))


@register("isfinite_v2", grad=None, infer_shape=same_shape_as("X"))
def _isfinite_v2(ctx, ins, attrs):
    return out(jnp.isfinite(x(ins)))


@register("isnan_v2", grad=None, infer_shape=same_shape_as("X"))
def _isnan(ctx, ins, attrs):
    return out(jnp.isnan(x(ins)))


@register("isinf_v2", grad=None, infer_shape=same_shape_as("X"))
def _isinf(ctx, ins, attrs):
    return out(jnp.isinf(x(ins)))


# ---------------------------------------------------------------------------
# misc math
# ---------------------------------------------------------------------------

@register("maximum", infer_shape=elementwise_infer)
def _maximum(ctx, ins, attrs):
    return out(jnp.maximum(x(ins, "X"), x(ins, "Y")))


@register("minimum", infer_shape=elementwise_infer)
def _minimum(ctx, ins, attrs):
    return out(jnp.minimum(x(ins, "X"), x(ins, "Y")))


@register("dot", infer_shape=_mean_infer)
def _dot(ctx, ins, attrs):
    a, b = x(ins, "X"), x(ins, "Y")
    return out(jnp.sum(a * b, axis=-1, keepdims=True))


@register("bmm", infer_shape=_matmul_infer)
def _bmm(ctx, ins, attrs):
    return out(jnp.matmul(x(ins, "X"), x(ins, "Y")))


@register("addmm", attrs={"Alpha": 1.0, "Beta": 1.0})
def _addmm(ctx, ins, attrs):
    inp, a, b = x(ins, "Input"), x(ins, "X"), x(ins, "Y")
    return out(attrs["Beta"] * inp + attrs["Alpha"] * (a @ b))


@register("cumsum", infer_shape=same_shape_as("X"),
          attrs={"axis": -1, "flatten": False, "exclusive": False,
                 "reverse": False})
def _cumsum(ctx, ins, attrs):
    v = x(ins)
    if attrs.get("flatten"):
        v = v.reshape(-1)
    axis = attrs["axis"]
    if attrs.get("reverse"):
        v = jnp.flip(v, axis)
    r = jnp.cumsum(v, axis=axis)
    if attrs.get("exclusive"):
        r = r - v
    if attrs.get("reverse"):
        r = jnp.flip(r, axis)
    return out(r)
