"""Control-flow ops: cond / while with sub-blocks, print/assert, feed/fetch.

Replaces reference operators/controlflow/ (while_op, conditional_block_op —
sub-block attrs per framework.proto:34 AttrType BLOCK). TPU-native mechanism:
the sub-Block is traced into the SAME jitted computation through
`lax.cond` / `lax.while_loop` — no step-scopes, no host interpreter.
Constraint inherited from XLA (and embraced): loop-carried vars keep fixed
shape/dtype across iterations.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..registry import register
from .common import x, out


@register("feed", grad=None, attrs={"col": 0})
def _feed(ctx, ins, attrs):
    return out(x(ins))


@register("fetch", grad=None, attrs={"col": 0})
def _fetch(ctx, ins, attrs):
    return out(x(ins))


@register("print", attrs={"first_n": -1, "message": "", "summarize": 20,
                          "print_tensor_name": True, "print_tensor_type": True,
                          "print_tensor_shape": True, "print_tensor_lod": False,
                          "print_phase": "BOTH"})
def _print(ctx, ins, attrs):
    v = x(ins, "In") or x(ins, "X")
    jax.debug.print(attrs.get("message", "") + " {v}", v=v)
    return out(v)


@register("assert", grad=None, attrs={"summarize": -1})
def _assert(ctx, ins, attrs):
    c = x(ins, "Cond")
    jax.debug.print("assert cond={c}", c=c)
    return {}


@register("recompute_barrier", grad=None)
def _recompute_barrier(ctx, ins, attrs):
    """Identity guarded by an XLA optimization barrier. Recomputed forward
    segments (append_backward checkpoints) read their inputs through this so
    common-subexpression elimination cannot merge the recomputation back
    into the original forward — which would keep the original activations
    live and undo the rematerialisation (the whole point of recompute)."""
    return out(jax.lax.optimization_barrier(x(ins)))


@register("select_input", grad=None)
def _select_input(ctx, ins, attrs):
    mask = x(ins, "Mask").reshape(()).astype(jnp.int32)
    xs = ins["X"]
    if len(xs) == 2:
        return out(jax.lax.select(mask == 1, xs[1], xs[0]))
    return out(jax.lax.switch(mask, [lambda i=i: xs[i]
                                     for i in range(len(xs))]))


@register("select_output", grad=None)
def _select_output(ctx, ins, attrs):
    # with functional cond this degenerates to identity fan-out
    return {"Out": [x(ins, "X")]}


# ---------------------------------------------------------------------------
# cond: attrs {sub_block_true, sub_block_false}, inputs Cond + Input (captured)
# outputs Out = vars produced by the chosen branch (same names both branches)
# ---------------------------------------------------------------------------

@register("cond")
def _cond(ctx, ins, attrs):
    from ..framework import Block
    bt: Block = attrs["sub_block_true"]
    bf: Block = attrs["sub_block_false"]
    pred = x(ins, "Cond").reshape(()).astype(bool)
    cap_names = attrs.get("capture_names", [])
    caps = ins.get("Input", [])
    out_names = attrs["out_names"]

    def run(block):
        def f(cap_vals):
            env = dict(zip(cap_names, cap_vals))
            ctx.exec_block(block, env)
            return tuple(env[n] for n in out_names)
        return f

    res = jax.lax.cond(pred, run(bt), run(bf), tuple(caps))
    return {"Out": list(res)}


# ---------------------------------------------------------------------------
# while: attrs {sub_block, cond_name, carry_names}, inputs Condition + X
# Loop semantics of reference while_op (operators/controlflow/while_op.cc):
# run sub-block until cond var (recomputed inside the block) is false.
# ---------------------------------------------------------------------------

@register("while")
def _while(ctx, ins, attrs):
    from ..framework import Block
    body: Block = attrs["sub_block"]
    cond_name: str = attrs["cond_name"]
    carry_names: list = attrs["carry_names"]
    cap_names: list = attrs.get("capture_names", [])
    caps = list(ins.get("Captures", []))
    init = [x(ins, "Condition")] + list(ins.get("X", []))

    def cond_fn(state):
        return state[0].reshape(()).astype(bool)

    def body_fn(state):
        env = dict(zip([cond_name] + carry_names, state))
        # captured externals are loop-invariant: closure constants, not
        # carried state (XLA hoists them out of the loop)
        env.update(zip(cap_names, caps))
        ctx.exec_block(body, env)
        new = tuple(env[n] for n in [cond_name] + carry_names)
        # XLA while requires carry dtype/shape stability
        return tuple(jnp.broadcast_to(n_, o.shape).astype(o.dtype)
                     if hasattr(o, "shape") else n_
                     for n_, o in zip(new, state))

    final = jax.lax.while_loop(cond_fn, body_fn, tuple(init))
    return {"Out": list(final[1:]), "CondOut": [final[0]]}


# ---------------------------------------------------------------------------
# py_func: host python callback (reference operators/py_func_op)
# ---------------------------------------------------------------------------

@register("py_func", grad=None, attrs={"forward_callable_id": 0})
def _py_func(ctx, ins, attrs):
    fn = attrs["_callable"]
    xs = ins.get("X", [])
    result_shapes = attrs.get("result_shapes")
    if result_shapes is None:
        res = fn(*[jnp.asarray(v) for v in xs])
        return {"Out": list(res) if isinstance(res, (list, tuple)) else [res]}
    import jax.experimental
    res = jax.pure_callback(
        fn, [jax.ShapeDtypeStruct(tuple(s), d) for s, d in result_shapes], *xs)
    return {"Out": list(res)}
