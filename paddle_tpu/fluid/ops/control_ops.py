"""Control-flow ops: cond / while with sub-blocks, print/assert, feed/fetch.

Replaces reference operators/controlflow/ (while_op, conditional_block_op —
sub-block attrs per framework.proto:34 AttrType BLOCK). TPU-native mechanism:
the sub-Block is traced into the SAME jitted computation through
`lax.cond` / `lax.while_loop` — no step-scopes, no host interpreter.
Constraint inherited from XLA (and embraced): loop-carried vars keep fixed
shape/dtype across iterations.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..registry import register
from .common import x, out


@register("feed", grad=None, attrs={"col": 0})
def _feed(ctx, ins, attrs):
    return out(x(ins))


@register("fetch", grad=None, attrs={"col": 0})
def _fetch(ctx, ins, attrs):
    return out(x(ins))


@register("print", attrs={"first_n": -1, "message": "", "summarize": 20,
                          "print_tensor_name": True, "print_tensor_type": True,
                          "print_tensor_shape": True, "print_tensor_lod": False,
                          "print_phase": "BOTH"})
def _print(ctx, ins, attrs):
    v = x(ins, "In") or x(ins, "X")
    jax.debug.print(attrs.get("message", "") + " {v}", v=v)
    return out(v)


@register("assert", grad=None, attrs={"summarize": -1})
def _assert(ctx, ins, attrs):
    c = x(ins, "Cond")
    jax.debug.print("assert cond={c}", c=c)
    return {}


@register("recompute_barrier", grad=None)
def _recompute_barrier(ctx, ins, attrs):
    """Identity guarded by an XLA optimization barrier. Recomputed forward
    segments (append_backward checkpoints) read their inputs through this so
    common-subexpression elimination cannot merge the recomputation back
    into the original forward — which would keep the original activations
    live and undo the rematerialisation (the whole point of recompute)."""
    return out(jax.lax.optimization_barrier(x(ins)))


@register("select_input", grad=None)
def _select_input(ctx, ins, attrs):
    mask = x(ins, "Mask").reshape(()).astype(jnp.int32)
    xs = ins["X"]
    if len(xs) == 2:
        return out(jax.lax.select(mask == 1, xs[1], xs[0]))
    return out(jax.lax.switch(mask, [lambda i=i: xs[i]
                                     for i in range(len(xs))]))


@register("select_output", grad=None)
def _select_output(ctx, ins, attrs):
    # with functional cond this degenerates to identity fan-out
    return {"Out": [x(ins, "X")]}


# ---------------------------------------------------------------------------
# cond: attrs {sub_block_true, sub_block_false}, inputs Cond + Input (captured)
# outputs Out = vars produced by the chosen branch (same names both branches)
# ---------------------------------------------------------------------------

@register("cond")
def _cond(ctx, ins, attrs):
    from ..framework import Block
    bt: Block = attrs["sub_block_true"]
    bf: Block = attrs["sub_block_false"]
    pred = x(ins, "Cond").reshape(()).astype(bool)
    cap_names = attrs.get("capture_names", [])
    caps = ins.get("Input", [])
    out_names = attrs["out_names"]

    def run(block):
        def f(cap_vals):
            env = dict(zip(cap_names, cap_vals))
            ctx.exec_block(block, env)
            return tuple(env[n] for n in out_names)
        return f

    res = jax.lax.cond(pred, run(bt), run(bf), tuple(caps))
    return {"Out": list(res)}


# ---------------------------------------------------------------------------
# while: attrs {sub_block, cond_name, carry_names}, inputs Condition + X
# Loop semantics of reference while_op (operators/controlflow/while_op.cc):
# run sub-block until cond var (recomputed inside the block) is false.
# ---------------------------------------------------------------------------

@register("while")
def _while(ctx, ins, attrs):
    from ..framework import Block
    body: Block = attrs["sub_block"]
    cond_name: str = attrs["cond_name"]
    carry_names: list = attrs["carry_names"]
    cap_names: list = attrs.get("capture_names", [])
    caps = list(ins.get("Captures", []))
    init = [x(ins, "Condition")] + list(ins.get("X", []))

    def cond_fn(state):
        return state[0].reshape(()).astype(bool)

    def body_fn(state):
        env = dict(zip([cond_name] + carry_names, state))
        # captured externals are loop-invariant: closure constants, not
        # carried state (XLA hoists them out of the loop)
        env.update(zip(cap_names, caps))
        ctx.exec_block(body, env)
        new = tuple(env[n] for n in [cond_name] + carry_names)
        # XLA while requires carry dtype/shape stability
        return tuple(jnp.broadcast_to(n_, o.shape).astype(o.dtype)
                     if hasattr(o, "shape") else n_
                     for n_, o in zip(new, state))

    mt = int(attrs.get("max_trip_count", 0) or 0)
    if mt > 0:
        # bounded loop -> masked lax.scan of exactly mt ticks: iterations
        # past the cond are computed but discarded. This is the
        # REVERSE-DIFFERENTIABLE lowering (lax.while_loop has no vjp);
        # the bound comes from the canonical `less_than(i, const)` +
        # `increment` pattern or an explicit while_loop(max_trip_count=).
        def tick(state, _):
            pred = state[0].reshape(()).astype(bool)
            new = body_fn(state)
            sel = tuple(
                jax.tree_util.tree_map(
                    lambda n_, o_: jnp.where(pred, n_, o_), n, o)
                for n, o in zip(new, state))
            return sel, None

        final, _ = jax.lax.scan(tick, tuple(init), None, length=mt)
        return {"Out": list(final[1:]), "CondOut": [final[0]]}

    final = jax.lax.while_loop(cond_fn, body_fn, tuple(init))
    return {"Out": list(final[1:]), "CondOut": [final[0]]}


# ---------------------------------------------------------------------------
# py_func: host python callback (reference operators/py_func_op)
# ---------------------------------------------------------------------------

@register("py_func", grad=None, attrs={"forward_callable_id": 0})
def _py_func(ctx, ins, attrs):
    fn = attrs["_callable"]
    xs = ins.get("X", [])
    result_shapes = attrs.get("result_shapes")
    if result_shapes is None:
        res = fn(*[jnp.asarray(v) for v in xs])
        return {"Out": list(res) if isinstance(res, (list, tuple)) else [res]}
    import jax.experimental
    res = jax.pure_callback(
        fn, [jax.ShapeDtypeStruct(tuple(s), d) for s, d in result_shapes], *xs)
    return {"Out": list(res)}


# ---------------------------------------------------------------------------
# LoDTensorArray tier (reference operators/controlflow/
# lod_tensor_array ops + recurrent_op.cc). TPU design: an array is a
# fixed-capacity stacked dense buffer + a length scalar, registered as a
# jax pytree so it rides through while-loop carries and autodiff; writes
# are dynamic_update_slice (growing at trace time only while the index is
# still concrete — inside lax loops the capacity is fixed, the XLA carry
# contract).
# ---------------------------------------------------------------------------

@jax.tree_util.register_pytree_node_class
class TensorArray:
    """(buffer [CAP, ...] | None, length int32). Functional: every write
    returns a new TensorArray. `static_len` mirrors `length` while every
    write index has been build-time-constant (None once a traced index
    is written) — it lets array_to_tensor produce a static shape."""

    def __init__(self, buffer, length, static_len=0):
        self.buffer = buffer
        self.length = length
        self.static_len = static_len

    def tree_flatten(self):
        # static_len is deliberately NOT part of the pytree (neither leaf
        # nor aux): aux must match exactly across while-loop carries, and
        # a traced leaf could never be read statically. It survives only
        # while the object flows through the op env unflattened — exactly
        # the build-time-constant regime it describes.
        if self.buffer is None:
            return (self.length,), False
        return (self.buffer, self.length), True

    @classmethod
    def tree_unflatten(cls, has_buf, leaves):
        if has_buf:
            return cls(leaves[0], leaves[1], None)
        return cls(None, leaves[0], None)

    def __repr__(self):
        shp = None if self.buffer is None else self.buffer.shape
        return f"TensorArray(cap={shp}, len={self.length})"


def _concrete_int(v):
    try:
        return int(v)
    except Exception:
        return None


@register("create_array", grad=None, attrs={"dtype": "float32",
                                            "max_size": 0})
def _create_array(ctx, ins, attrs):
    return {"Out": [TensorArray(None, jnp.zeros((), jnp.int32))]}


@register("write_to_array", no_grad_slots=("I",),
          attrs={"max_size": 0, "static_index": None})
def _write_to_array(ctx, ins, attrs):
    v, i = x(ins, "X"), x(ins, "I")
    arr = x(ins, "Array") or TensorArray(None, jnp.zeros((), jnp.int32))
    iv = jnp.asarray(i).reshape(()).astype(jnp.int32)
    ci = _concrete_int(iv)
    if ci is None:
        # the layer resolved a build-time fill_constant index (the whole
        # block is traced, so even constants arrive as tracers here)
        ci = attrs.get("static_index")
    buf = arr.buffer
    if buf is None:
        cap = int(attrs.get("max_size") or 0)
        if not cap:
            if ci is None:
                raise ValueError(
                    "write_to_array with a traced index needs a "
                    "pre-sized array: create_array(..., max_size=N) "
                    "(XLA buffers cannot grow inside compiled loops)")
            cap = max(ci + 1, 8)
        buf = jnp.zeros((cap,) + tuple(jnp.shape(v)), jnp.asarray(v).dtype)
    elif ci is not None and ci >= buf.shape[0]:
        grow = jnp.zeros((max(ci + 1, 2 * buf.shape[0]),) + buf.shape[1:],
                         buf.dtype)
        buf = grow.at[:buf.shape[0]].set(buf)
    cap = buf.shape[0]
    if ci is not None and ci >= cap:
        raise ValueError(f"write_to_array index {ci} >= capacity {cap}")
    buf2 = jax.lax.dynamic_update_index_in_dim(buf, jnp.asarray(v), iv, 0)
    if jnp.issubdtype(buf.dtype, jnp.floating):
        # a traced index past capacity would otherwise be silently
        # CLAMPED by dynamic_update_slice (XLA semantics) and corrupt the
        # last slot; poisoning the whole buffer with NaN turns that into
        # an unmissable failure (reference LoDTensorArray raises)
        buf2 = jnp.where(iv < cap, buf2, jnp.full_like(buf2, jnp.nan))
    buf = buf2
    length = jnp.maximum(arr.length, iv + 1)
    sl = None if (ci is None or arr.static_len is None) \
        else max(arr.static_len, ci + 1)
    return {"Out": [TensorArray(buf, length, sl)]}


@register("read_from_array", no_grad_slots=("I",))
def _read_from_array(ctx, ins, attrs):
    arr, i = x(ins, "X"), x(ins, "I")
    if arr is None or arr.buffer is None:
        raise ValueError("read_from_array on an empty TensorArray")
    iv = jnp.asarray(i).reshape(()).astype(jnp.int32)
    return {"Out": [jax.lax.dynamic_index_in_dim(arr.buffer, iv, 0,
                                                 keepdims=False)]}


@register("lod_array_length", grad=None)
def _lod_array_length(ctx, ins, attrs):
    arr = x(ins, "X")
    ln = jnp.zeros((), jnp.int32) if arr is None else arr.length
    return {"Out": [jnp.asarray(ln).reshape((1,)).astype(jnp.int64)]}


@register("array_to_tensor", attrs={"axis": 0, "use_stack": True},
          no_grad_out_slots=("OutIndex",))
def _array_to_tensor(ctx, ins, attrs):
    """Stack the written prefix ([length, ...]); length must be concrete
    at trace time (static shapes) — inside loops keep the TensorArray."""
    arr = x(ins, "X")
    ln = _concrete_int(arr.length)
    if ln is None:
        ln = arr.static_len
    if not ln:
        raise ValueError(
            "array_to_tensor needs a static length: either all writes at "
            "build-time-constant indices, or slice the buffer explicitly "
            "after the loop (XLA shapes are static)")
    buf = arr.buffer[:ln]
    if not attrs.get("use_stack", True):
        buf = jnp.concatenate(list(buf), axis=attrs.get("axis", 0))
    return {"Out": [buf], "OutIndex": [jnp.full((ln,), 1, jnp.int64)]}


# ---------------------------------------------------------------------------
# recurrent: StaticRNN's op (reference operators/controlflow/
# recurrent_op.cc) — one lax.scan over the step sub-block.
# ---------------------------------------------------------------------------

@register("recurrent")
def _recurrent(ctx, ins, attrs):
    from ..framework import Block
    block: Block = attrs["sub_block"]
    seq_names = attrs["seq_input_names"]
    pre_names = attrs["pre_mem_names"]
    upd_names = attrs["mem_update_names"]
    out_names = attrs["step_output_names"]
    cap_names = attrs.get("capture_names", [])
    seqs = list(ins.get("X", []))
    inits = list(ins.get("Init", []))
    caps = list(ins.get("Captures", []))

    def body(carry, xs):
        env = dict(zip(cap_names, caps))
        env.update(zip(seq_names, xs))
        env.update(zip(pre_names, carry))
        ctx.exec_block(block, env)
        new_carry = tuple(
            jnp.asarray(env[n]).astype(o.dtype).reshape(o.shape)
            for n, o in zip(upd_names, carry))
        return new_carry, tuple(env[n] for n in out_names)

    carry, ys = jax.lax.scan(body, tuple(inits), tuple(seqs))
    return {"Out": list(ys), "FinalStates": list(carry)}
