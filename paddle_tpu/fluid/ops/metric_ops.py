"""In-graph metric ops (reference operators/metrics/: accuracy_op, auc_op)."""
from __future__ import annotations

import jax.numpy as jnp

from ..registry import register
from .common import x


def _acc_infer(op):
    for name in op.output("Accuracy") + op.output("Correct") + op.output("Total"):
        op.block.create_var(name=name, shape=(1,), dtype="float32")


@register("accuracy", grad=None, infer_shape=_acc_infer)
def _accuracy(ctx, ins, attrs):
    """Inputs: Out (topk values), Indices (topk indices), Label."""
    idx, label = x(ins, "Indices"), x(ins, "Label")
    lab = label.reshape(label.shape[0], -1)[:, :1].astype(jnp.int64)
    hit = jnp.any(idx.reshape(idx.shape[0], -1) == lab, axis=1)
    total = jnp.asarray(idx.shape[0], jnp.float32)
    correct = jnp.sum(hit.astype(jnp.float32))
    return {"Accuracy": [(correct / total).reshape((1,))],
            "Correct": [correct.reshape((1,)).astype(jnp.int32)],
            "Total": [total.reshape((1,)).astype(jnp.int32)]}


@register("auc", grad=None,
          attrs={"curve": "ROC", "num_thresholds": 4095, "slide_steps": 1})
def _auc(ctx, ins, attrs):
    """Streaming AUC with stat buffers carried as persistable vars
    (reference operators/metrics/auc_op.cc)."""
    preds, label = x(ins, "Predict"), x(ins, "Label")
    stat_pos, stat_neg = x(ins, "StatPos"), x(ins, "StatNeg")
    nt = attrs["num_thresholds"]
    p1 = preds[:, 1] if preds.ndim == 2 and preds.shape[1] == 2 else \
        preds.reshape(-1)
    lab = label.reshape(-1).astype(bool)
    bins = jnp.clip((p1 * nt).astype(jnp.int32), 0, nt)
    pos = jnp.zeros(nt + 1, jnp.int64).at[bins].add(lab.astype(jnp.int64))
    neg = jnp.zeros(nt + 1, jnp.int64).at[bins].add((~lab).astype(jnp.int64))
    new_pos = stat_pos.reshape(-1) + pos
    new_neg = stat_neg.reshape(-1) + neg
    # integrate (trapezoid over descending threshold)
    tp = jnp.cumsum(new_pos[::-1])
    fp = jnp.cumsum(new_neg[::-1])
    tot_pos, tot_neg = tp[-1], fp[-1]
    tp_prev = jnp.concatenate([jnp.zeros(1, tp.dtype), tp[:-1]])
    fp_prev = jnp.concatenate([jnp.zeros(1, fp.dtype), fp[:-1]])
    area = jnp.sum((fp - fp_prev) * (tp + tp_prev) / 2.0)
    auc = jnp.where((tot_pos > 0) & (tot_neg > 0),
                    area / jnp.maximum(tot_pos * tot_neg, 1), 0.0)
    return {"AUC": [auc.reshape((1,)).astype(jnp.float64)
                    if auc.dtype == jnp.float64 else auc.reshape((1,))],
            "StatPosOut": [new_pos.reshape(stat_pos.shape)],
            "StatNegOut": [new_neg.reshape(stat_neg.shape)]}
