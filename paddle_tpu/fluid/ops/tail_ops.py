"""Long-tail operator sweep (VERDICT r03 item 10): math/linalg/index/NN/
sequence/detection stragglers of the reference op zoo, each a thin jnp
kernel under the registry contract (grads auto-vjp unless noted).

Reference kernel families replaced (one .cc/.cu pair each under
/root/reference/paddle/fluid/operators/): prelu_op, maxout_op, pad3d_op,
gather_tree_op, unfold_op, fold(im2col/col2im via math/im2col),
interpolate_op (bilinear/trilinear/bicubic/nearest v1+v2),
sequence_ops/{sequence_conv,slice,erase,enumerate,scatter}_op,
detection/{generate_proposals,psroi_pool,roi_pool,box_clip,
polygon_box_transform,density_prior_box}_op, deformable_conv_op,
take_along_axis/put_along_axis, linalg (inverse, qr, svd, eigh, lu,
matrix_rank, multi_dot), cum(max,min,logsumexp), searchsorted,
bincount, spectral_norm_op, affine_channel_op, space_to_depth_op,
*_batch_size_like, frame/overlap_add, complex ops, dist_op,
index_sample/index_select.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..registry import register, same_shape_as
from .common import x, out

F32 = jnp.float32


# ---------------------------------------------------------------------------
# elementwise math
# ---------------------------------------------------------------------------

for _name, _fn in [
        ("expm1", jnp.expm1),
        ("lgamma", jax.lax.lgamma),
        ("digamma", jax.lax.digamma),
        ("rad2deg", jnp.rad2deg),
        ("deg2rad", jnp.deg2rad),
        ("angle", jnp.angle),
]:
    register(_name, (lambda f: lambda ctx, ins, attrs: out(f(x(ins))))(_fn),
             infer_shape=same_shape_as("X"))

register("atan2",
         lambda ctx, ins, attrs: out(jnp.arctan2(x(ins, "X1"),
                                                 x(ins, "X2"))),
         infer_shape=same_shape_as("X1"))


@register("nan_to_num", attrs={"nan": 0.0, "posinf": None, "neginf": None})
def _nan_to_num(ctx, ins, attrs):
    return out(jnp.nan_to_num(x(ins), nan=attrs.get("nan", 0.0),
                              posinf=attrs.get("posinf"),
                              neginf=attrs.get("neginf")))


@register("logsumexp", attrs={"axis": [], "keepdim": False,
                              "reduce_all": False})
def _logsumexp(ctx, ins, attrs):
    v = x(ins)
    ax = attrs.get("axis") or None
    if attrs.get("reduce_all") or ax is None or list(ax) == []:
        ax = None
    else:
        ax = tuple(int(a) for a in ax)
    return out(jax.nn.logsumexp(v, axis=ax,
                                keepdims=attrs.get("keepdim", False)))


@register("logcumsumexp", attrs={"axis": -1, "flatten": False,
                                 "exclusive": False, "reverse": False})
def _logcumsumexp(ctx, ins, attrs):
    v = x(ins)
    if attrs.get("flatten"):
        v = v.ravel()
    ax = int(attrs.get("axis", -1))
    if attrs.get("reverse"):
        v = jnp.flip(v, ax)
    r = jax.lax.cumlogsumexp(v, axis=ax)
    if attrs.get("reverse"):
        r = jnp.flip(r, ax)
    return out(r)


def _cum_minmax(fn):
    def impl(ctx, ins, attrs):
        v = x(ins)
        ax = int(attrs.get("axis", -1))
        if attrs.get("flatten"):
            v = v.ravel()
            ax = 0
        val = fn(v, axis=ax)
        # indices output (paddle returns the arg positions)
        n = v.shape[ax]
        eq = val == v
        idx = jnp.arange(n).reshape(
            [-1 if i == (ax % v.ndim) else 1 for i in range(v.ndim)])
        idx = jnp.broadcast_to(idx, v.shape)
        # last position where the running extreme equals the element
        run = jax.lax.associative_scan(jnp.maximum,
                                       jnp.where(eq, idx, -1), axis=ax)
        return {"Out": [val], "Indices": [run.astype(jnp.int64)]}
    return impl


register("cummax", _cum_minmax(jax.lax.cummax),
         attrs={"axis": -1, "flatten": False},
         no_grad_out_slots=("Indices",))
register("cummin", _cum_minmax(jax.lax.cummin),
         attrs={"axis": -1, "flatten": False},
         no_grad_out_slots=("Indices",))


@register("dist", attrs={"p": 2.0})
def _dist(ctx, ins, attrs):
    d = (x(ins, "X") - x(ins, "Y")).ravel()
    p = float(attrs.get("p", 2.0))
    if p == float("inf"):
        return out(jnp.max(jnp.abs(d)).reshape(()))
    if p == float("-inf"):
        return out(jnp.min(jnp.abs(d)).reshape(()))
    if p == 0:
        return out(jnp.sum(d != 0).astype(d.dtype).reshape(()))
    return out((jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)).reshape(()))


register("cosine_similarity",
         lambda ctx, ins, attrs: out(
             jnp.sum(x(ins, "X") * x(ins, "Y"), attrs.get("axis", 1)) /
             (jnp.linalg.norm(x(ins, "X"), axis=attrs.get("axis", 1)) *
              jnp.linalg.norm(x(ins, "Y"), axis=attrs.get("axis", 1))
              ).clip(attrs.get("eps", 1e-8))),
         attrs={"axis": 1, "eps": 1e-8})


@register("pairwise_distance", attrs={"p": 2.0, "epsilon": 1e-6,
                                      "keepdim": False})
def _pairwise_distance(ctx, ins, attrs):
    d = x(ins, "X") - x(ins, "Y") + attrs.get("epsilon", 1e-6)
    p = float(attrs.get("p", 2.0))
    r = jnp.sum(jnp.abs(d) ** p, axis=-1) ** (1.0 / p)
    if attrs.get("keepdim"):
        r = r[..., None]
    return out(r)


# ---------------------------------------------------------------------------
# linalg (XLA-native decompositions; reference operators/*_op.cc over
# LAPACK/cuSolver)
# ---------------------------------------------------------------------------

register("inverse", lambda ctx, ins, attrs: {
    "Output": [jnp.linalg.inv(x(ins, "Input"))]},
    infer_shape=same_shape_as("Input", out_slot="Output"))

register("trace",
         lambda ctx, ins, attrs: out(jnp.trace(
             x(ins), offset=attrs.get("offset", 0),
             axis1=attrs.get("axis1", 0), axis2=attrs.get("axis2", 1))),
         attrs={"offset": 0, "axis1": 0, "axis2": 1})

def _cross(ctx, ins, attrs):
    a, b = x(ins, "X"), x(ins, "Y")
    dim = attrs.get("dim", 9)
    if dim == 9:  # unset sentinel: first axis of length 3 (reference)
        dim = next(i for i, d in enumerate(a.shape) if d == 3)
    return out(jnp.cross(a, b, axis=dim))


register("cross", _cross, attrs={"dim": 9},
         infer_shape=same_shape_as("X"))


@register("multi_dot")
def _multi_dot(ctx, ins, attrs):
    return out(jnp.linalg.multi_dot(list(ins["X"])))


@register("qr", grad=None, attrs={"mode": "reduced"})
def _qr(ctx, ins, attrs):
    q, r = jnp.linalg.qr(x(ins), mode=attrs.get("mode", "reduced"))
    return {"Q": [q], "R": [r]}


@register("svd", grad=None, attrs={"full_matrices": False})
def _svd(ctx, ins, attrs):
    u, s, vh = jnp.linalg.svd(
        x(ins), full_matrices=attrs.get("full_matrices", False))
    return {"U": [u], "S": [s], "VH": [vh]}


@register("eigh", grad=None, attrs={"UPLO": "L"})
def _eigh(ctx, ins, attrs):
    v = x(ins)
    # honor the UPLO contract: only the named triangle is read
    if attrs.get("UPLO", "L") == "U":
        up = jnp.triu(v)
        sym = up + jnp.swapaxes(up, -1, -2) - \
            jnp.triu(jnp.tril(v))  # diag counted once
    else:
        lo = jnp.tril(v)
        sym = lo + jnp.swapaxes(lo, -1, -2) - jnp.triu(jnp.tril(v))
    w, vec = jnp.linalg.eigh(sym, symmetrize_input=False)
    return {"Eigenvalues": [w], "Eigenvectors": [vec]}


@register("lu", grad=None, attrs={"pivots": True})
def _lu(ctx, ins, attrs):
    import jax.scipy.linalg as jsl
    lu, piv = jsl.lu_factor(x(ins))
    return {"Out": [lu], "Pivots": [piv.astype(jnp.int32)]}


@register("matrix_rank", grad=None,
          attrs={"tol": 0.0, "use_default_tol": True, "hermitian": False})
def _matrix_rank(ctx, ins, attrs):
    v = x(ins)
    tol = None if attrs.get("use_default_tol", True) \
        else attrs.get("tol", 0.0)
    return out(jnp.linalg.matrix_rank(v, tol=tol).astype(jnp.int64))


# ---------------------------------------------------------------------------
# indexing
# ---------------------------------------------------------------------------

register("take_along_axis",
         lambda ctx, ins, attrs: {"Result": [jnp.take_along_axis(
             x(ins, "Input"), x(ins, "Index").astype(jnp.int64),
             axis=attrs.get("Axis", 0))]},
         attrs={"Axis": 0}, no_grad_slots=("Index",))


@register("put_along_axis", no_grad_slots=("Index",),
          attrs={"Axis": 0, "Reduce": "assign"})
def _put_along_axis(ctx, ins, attrs):
    v, idx, val = x(ins, "Input"), x(ins, "Index"), x(ins, "Value")
    ax = attrs.get("Axis", 0)
    idx = idx.astype(jnp.int64)
    mode = attrs.get("Reduce", "assign")
    dims = [jnp.arange(s) for s in idx.shape]
    mesh = jnp.meshgrid(*dims, indexing="ij")
    mesh[ax] = idx
    if mode == "add":
        r = v.at[tuple(mesh)].add(jnp.broadcast_to(val, idx.shape))
    elif mode == "multiply" or mode == "mul":
        r = v.at[tuple(mesh)].multiply(jnp.broadcast_to(val, idx.shape))
    else:
        r = v.at[tuple(mesh)].set(jnp.broadcast_to(val, idx.shape))
    return {"Result": [r]}


register("broadcast_to",
         lambda ctx, ins, attrs: out(jnp.broadcast_to(
             x(ins), tuple(attrs["shape"]))),
         attrs={"shape": []})

register("searchsorted",
         lambda ctx, ins, attrs: out(jnp.searchsorted(
             x(ins, "SortedSequence"), x(ins, "Values"),
             side="right" if attrs.get("right", False) else "left"
         ).astype(jnp.int32 if attrs.get("out_int32") else jnp.int64)),
         grad=None, attrs={"out_int32": False, "right": False})

register("bucketize",
         lambda ctx, ins, attrs: out(jnp.searchsorted(
             x(ins, "SortedSequence"), x(ins, "InputTensor"),
             side="right" if attrs.get("right", False) else "left"
         ).astype(jnp.int32 if attrs.get("out_int32") else jnp.int64)),
         grad=None, attrs={"out_int32": False, "right": False})


@register("bincount", grad=None, attrs={"minlength": 0})
def _bincount(ctx, ins, attrs):
    v = x(ins).astype(jnp.int32).ravel()
    w = x(ins, "Weights")
    # static shape contract: length = minlength (XLA needs a bound; the
    # reference sizes by max(x)+1 at runtime — pass minlength >= that)
    n = int(attrs.get("minlength") or 0)
    if n <= 0:
        cv = np.asarray(v) if not isinstance(v, jax.core.Tracer) else None
        if cv is None:
            raise ValueError("bincount under tracing needs minlength>0 "
                             "(static shapes)")
        n = int(cv.max()) + 1 if cv.size else 1
    if w is None:
        return out(jnp.zeros((n,), jnp.int64).at[v].add(1))
    return out(jnp.zeros((n,), w.dtype).at[v].add(w.ravel()))


@register("unique_consecutive", grad=None,
          attrs={"dtype": "int64", "return_inverse": False,
                 "return_counts": False, "axis": []})
def _unique_consecutive(ctx, ins, attrs):
    """Static-shape redesign: output keeps x's length with repeats
    compacted to the front and the tail zero-padded; Counts/Index share
    that convention (XLA cannot return data-dependent shapes)."""
    v = x(ins).ravel()
    keep = jnp.concatenate([jnp.ones((1,), bool), v[1:] != v[:-1]])
    pos = jnp.cumsum(keep) - 1
    n = v.shape[0]
    # every element of a run writes its run slot; scatter order makes the
    # LAST write win, but all writes in a run carry the same value
    outv = jnp.zeros_like(v).at[pos].set(v)
    inv = pos
    counts = jnp.zeros((n,), jnp.int64).at[pos].add(1)
    return {"Out": [outv], "Index": [inv.astype(jnp.int64)],
            "Counts": [counts]}


# ---------------------------------------------------------------------------
# NN tail
# ---------------------------------------------------------------------------

@register("prelu", attrs={"mode": "all", "data_format": "NCHW"})
def _prelu(ctx, ins, attrs):
    v, alpha = x(ins), x(ins, "Alpha")
    mode = attrs.get("mode", "all")
    if mode == "channel":
        caxis = 1 if attrs.get("data_format", "NCHW") == "NCHW" \
            else v.ndim - 1
        shape = [1] * v.ndim
        shape[caxis] = -1
        alpha = alpha.reshape(shape)
    elif mode == "element":
        alpha = alpha.reshape((1,) + v.shape[1:])
    else:
        alpha = alpha.reshape(())
    return out(jnp.where(v > 0, v, alpha * v))


@register("maxout", attrs={"groups": 1, "axis": 1})
def _maxout(ctx, ins, attrs):
    v = x(ins)
    g = int(attrs["groups"])
    ax = int(attrs.get("axis", 1)) % v.ndim
    c = v.shape[ax]
    shp = v.shape[:ax] + (c // g, g) + v.shape[ax + 1:]
    return out(jnp.max(v.reshape(shp), axis=ax + 1))


@register("pad3d", attrs={"paddings": [0] * 6, "mode": "constant",
                          "value": 0.0, "data_format": "NCDHW"})
def _pad3d(ctx, ins, attrs):
    v = x(ins)
    p = list(attrs["paddings"])  # [l, r, top, bottom, front, back]
    ncdhw = attrs.get("data_format", "NCDHW") == "NCDHW"
    sp = [(p[4], p[5]), (p[2], p[3]), (p[0], p[1])]  # D, H, W
    pads = ([(0, 0), (0, 0)] + sp) if ncdhw else \
        ([(0, 0)] + sp + [(0, 0)])
    mode = attrs.get("mode", "constant")
    if mode == "constant":
        return out(jnp.pad(v, pads, constant_values=attrs.get("value",
                                                              0.0)))
    jmode = {"reflect": "reflect", "replicate": "edge",
             "circular": "wrap"}[mode]
    return out(jnp.pad(v, pads, mode=jmode))


@register("gather_tree", grad=None)
def _gather_tree(ctx, ins, attrs):
    """Beam-search backtrace (reference gather_tree_op): ids/parents
    [T, B, W] -> full sequences re-threaded along parent pointers."""
    ids, parents = x(ins, "Ids"), x(ins, "Parents")
    T = ids.shape[0]

    def step(beams, t):
        # beams: [B, W] current beam index per output slot
        idx = jnp.take_along_axis(ids[t], beams, axis=-1)
        nxt = jnp.take_along_axis(parents[t], beams, axis=-1)
        return nxt, idx

    init = jnp.broadcast_to(jnp.arange(ids.shape[2]), ids.shape[1:])
    _, outs = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
    return out(jnp.flip(outs, 0).astype(ids.dtype))


@register("fold", attrs={"output_sizes": [0, 0], "kernel_sizes": [3, 3],
                         "strides": [1, 1], "paddings": [0, 0, 0, 0],
                         "dilations": [1, 1]})
def _fold(ctx, ins, attrs):
    """col2im — scatter-add of unfold patches back to the image."""
    v = x(ins)  # [N, C*kh*kw, L]
    oh, ow = attrs["output_sizes"]
    kh, kw = attrs["kernel_sizes"]
    sh, sw = attrs["strides"]
    p = attrs["paddings"]
    dh, dw = attrs["dilations"]
    n, ckk, L = v.shape
    c = ckk // (kh * kw)
    ph, pw = oh + p[0] + p[2], ow + p[1] + p[3]
    lh = (ph - (dh * (kh - 1) + 1)) // sh + 1
    lw = (pw - (dw * (kw - 1) + 1)) // sw + 1
    img = jnp.zeros((n, c, ph, pw), v.dtype)
    cols = v.reshape(n, c, kh, kw, lh, lw)
    for i in range(kh):
        for j in range(kw):
            ys = i * dh
            xs = j * dw
            img = img.at[:, :, ys:ys + lh * sh:sh,
                         xs:xs + lw * sw:sw].add(cols[:, :, i, j])
    return {"Y": [img[:, :, p[0]:p[0] + oh, p[1]:p[1] + ow]]}


@register("affine_channel", attrs={"data_layout": "NCHW"})
def _affine_channel(ctx, ins, attrs):
    v, s, b = x(ins, "X"), x(ins, "Scale"), x(ins, "Bias")
    caxis = 1 if attrs.get("data_layout", "NCHW") == "NCHW" else v.ndim - 1
    shape = [1] * v.ndim
    shape[caxis] = -1
    return out(v * s.reshape(shape) + b.reshape(shape))


@register("space_to_depth", attrs={"blocksize": 1})
def _space_to_depth(ctx, ins, attrs):
    v = x(ins)
    bs = int(attrs["blocksize"])
    n, c, h, w = v.shape
    v = v.reshape(n, c, h // bs, bs, w // bs, bs)
    v = v.transpose(0, 3, 5, 1, 2, 4)
    return out(v.reshape(n, c * bs * bs, h // bs, w // bs))


@register("spectral_norm", no_grad_slots=("U", "V"),
          attrs={"dim": 0, "power_iters": 1, "eps": 1e-12})
def _spectral_norm(ctx, ins, attrs):
    w, u, v = x(ins, "Weight"), x(ins, "U"), x(ins, "V")
    dim = int(attrs.get("dim", 0))
    eps = attrs.get("eps", 1e-12)
    mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
    for _ in range(max(int(attrs.get("power_iters", 1)), 0)):
        v = mat.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = mat @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ mat @ v
    return out(w / sigma)


@register("deformable_conv", no_grad_slots=("Mask",),
          attrs={"strides": [1, 1], "paddings": [0, 0],
                 "dilations": [1, 1], "groups": 1,
                 "deformable_groups": 1, "im2col_step": 64})
def _deformable_conv(ctx, ins, attrs):
    """Deformable conv v2 (reference deformable_conv_op.cu): sample the
    input at offset-shifted taps with bilinear interpolation, modulate
    by the mask, then contract with the filter."""
    if int(attrs.get("deformable_groups", 1) or 1) != 1:
        raise NotImplementedError(
            "deformable_conv: only deformable_groups=1 is implemented "
            "(the sampler reads one offset group)")
    v = x(ins, "Input")          # [N, C, H, W]
    offset = x(ins, "Offset")    # [N, 2*dg*kh*kw, OH, OW]
    mask = x(ins, "Mask")        # [N, dg*kh*kw, OH, OW] or None
    flt = x(ins, "Filter")       # [OC, C/g, kh, kw]
    sh, sw = attrs["strides"]
    ph, pw = attrs["paddings"]
    dh, dw = attrs["dilations"]
    g = attrs.get("groups", 1) or 1
    n, c, h, w = v.shape
    oc, cpg, kh, kw = flt.shape
    oh = (h + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    ow = (w + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    base_y = (jnp.arange(oh) * sh - ph)[:, None, None]   # [OH,1,1]
    base_x = (jnp.arange(ow) * sw - pw)[None, :, None]   # [1,OW,1]
    ky = (jnp.arange(kh) * dh)[None, None, :, None]      # [1,1,kh,1]
    kx = (jnp.arange(kw) * dw)[None, None, None, :]      # [1,1,1,kw]
    off = offset.reshape(n, -1, 2, kh, kw, oh, ow)
    oy = off[:, 0, 0].transpose(0, 3, 4, 1, 2)  # dg=1: [N,OH,OW,kh,kw]
    ox = off[:, 0, 1].transpose(0, 3, 4, 1, 2)
    # sampling coords [N, OH, OW, kh, kw]
    ys = base_y[None, :, :, :, None] + ky[None] + oy
    xs = base_x[None, :, :, None, :] + kx[None] + ox

    def bilinear(img, ys, xs):
        y0 = jnp.floor(ys)
        x0 = jnp.floor(xs)
        wy = ys - y0
        wx = xs - x0
        def at(yy, xx):
            yi = jnp.clip(yy.astype(jnp.int32), 0, h - 1)
            xi = jnp.clip(xx.astype(jnp.int32), 0, w - 1)
            val = img[:, yi, xi]
            ok = (yy >= 0) & (yy <= h - 1) & (xx >= 0) & (xx <= w - 1)
            return jnp.where(ok, val, 0.0)
        return (at(y0, x0) * (1 - wy) * (1 - wx) +
                at(y0, x0 + 1) * (1 - wy) * wx +
                at(y0 + 1, x0) * wy * (1 - wx) +
                at(y0 + 1, x0 + 1) * wy * wx)

    # vmap over batch: img [C,H,W], ys/xs [OH,OW,kh,kw]
    samp = jax.vmap(bilinear)(v, ys, xs)  # [N, C, OH, OW, kh, kw]
    if mask is not None:
        m = mask.reshape(n, 1, kh, kw, oh, ow).transpose(0, 1, 4, 5, 2, 3)
        samp = samp * m
    samp = samp.reshape(n, g, c // g, oh, ow, kh, kw)
    fg = flt.reshape(g, oc // g, cpg, kh, kw)
    r = jnp.einsum("ngcyxhw,gochw->ngoyx", samp, fg)
    return {"Output": [r.reshape(n, oc, oh, ow)]}


# ---------------------------------------------------------------------------
# interpolation family (reference interpolate_op.* v1+v2) — jax.image
# ---------------------------------------------------------------------------

def _interp_axis_nearest(v, axis, out_n, align_corners):
    """Reference nearest_interp coordinate map (interpolate_op.cc): with
    align_corners the source index is round(i·(in-1)/(out-1)); without it
    floor(i·in/out) — NOT jax.image's half-pixel rounding."""
    in_n = v.shape[axis]
    i = jnp.arange(out_n, dtype=jnp.float32)
    if align_corners:
        # round half UP (reference: static_cast<int>(ratio*i + 0.5)),
        # not rint's half-to-even
        idx = jnp.floor(i * ((in_n - 1) / max(out_n - 1, 1)) + 0.5)
    else:
        idx = jnp.floor(i * (in_n / out_n))
    return jnp.take(v, jnp.clip(idx.astype(jnp.int32), 0, in_n - 1),
                    axis=axis)


def _interp_axis_linear(v, axis, out_n, align_corners, align_mode):
    """1-D linear resample along `axis` with the reference's three
    coordinate maps: align_corners (i·(in-1)/(out-1)), half-pixel
    (align_mode=0), asymmetric (align_mode=1, the op default)."""
    in_n = v.shape[axis]
    i = jnp.arange(out_n, dtype=jnp.float32)
    if align_corners:
        c = i * ((in_n - 1) / max(out_n - 1, 1))
    elif align_mode == 0:
        c = jnp.clip((i + 0.5) * (in_n / out_n) - 0.5, 0.0, in_n - 1.0)
    else:
        c = jnp.clip(i * (in_n / out_n), 0.0, in_n - 1.0)
    lo = jnp.floor(c).astype(jnp.int32)
    hi = jnp.minimum(lo + 1, in_n - 1)
    w = c - lo.astype(jnp.float32)
    shape = [1] * v.ndim
    shape[axis] = out_n
    w = w.reshape(shape)
    return jnp.take(v, lo, axis=axis) * (1 - w) \
        + jnp.take(v, hi, axis=axis) * w


def _interp_axis_cubic(v, axis, out_n, align_corners):
    """1-D Keys-cubic (a = -0.75, the reference/torch kernel) resample:
    4 clamped taps per output point, weights from the source offset."""
    in_n = v.shape[axis]
    i = jnp.arange(out_n, dtype=jnp.float32)
    if align_corners:
        c = i * ((in_n - 1) / max(out_n - 1, 1))
    else:
        c = (i + 0.5) * (in_n / out_n) - 0.5
    lo = jnp.floor(c)
    t = c - lo
    a = -0.75

    def kern(d):
        ad = jnp.abs(d)
        return jnp.where(
            ad <= 1, (a + 2) * ad**3 - (a + 3) * ad**2 + 1,
            jnp.where(ad < 2, a * ad**3 - 5 * a * ad**2 + 8 * a * ad
                      - 4 * a, 0.0))

    shape = [1] * v.ndim
    shape[axis] = out_n
    acc = 0
    for k in range(-1, 3):
        idx = jnp.clip(lo.astype(jnp.int32) + k, 0, in_n - 1)
        acc = acc + jnp.take(v, idx, axis=axis) \
            * kern(t - k).reshape(shape)
    return acc


def _interp(method):
    def impl(ctx, ins, attrs):
        v = x(ins)
        size_t = x(ins, "OutSize")
        oh, ow, od = attrs.get("out_h", 0), attrs.get("out_w", 0), \
            attrs.get("out_d", 0)
        scale = attrs.get("scale") or attrs.get("scale_factor") or []
        if isinstance(scale, (int, float)):
            scale = [scale]
        is3d = v.ndim == 5
        if size_t is not None:
            tgt = tuple(int(s) for s in np.asarray(size_t).tolist())
        elif (od or 0) > 0 or (oh or 0) > 0 or (ow or 0) > 0:
            tgt = ((od, oh, ow) if is3d else (oh, ow))
        else:
            sp = v.shape[2:]
            if len(scale) == 1:
                scale = list(scale) * len(sp)
            tgt = tuple(int(round(s * f)) for s, f in zip(sp, scale))
        axes = list(range(2, v.ndim))
        if len(tgt) != len(axes):
            raise ValueError(
                f"{method}_interp: target size {tgt} has {len(tgt)} dims "
                f"for input with {len(axes)} spatial dims")
        ac = bool(attrs.get("align_corners", True))
        am = int(attrs.get("align_mode", 1))
        if method == "nearest":
            # pure gather: no float math on values (int maps stay exact)
            r = v
            for ax, n in zip(axes, tgt):
                r = _interp_axis_nearest(r, ax, int(n), ac)
            return out(r)
        dt = v.dtype
        r = v.astype(jnp.float32)
        if method in ("bilinear", "trilinear"):
            for ax, n in zip(axes, tgt):
                r = _interp_axis_linear(r, ax, int(n), ac, am)
        else:  # bicubic
            for ax, n in zip(axes, tgt):
                r = _interp_axis_cubic(r, ax, int(n), ac)
        return out(r.astype(dt))
    return impl


for _m in ("nearest", "bilinear", "trilinear", "bicubic"):
    for _suffix in ("_interp", "_interp_v2"):
        _name = _m + _suffix
        # attr defaults mirror the reference op def (interpolate_op.cc:
        # align_corners defaults TRUE); our python API always passes them
        register(_name, _interp(_m), no_grad_slots=("OutSize", "Scale"),
                 attrs={"out_h": 0, "out_w": 0, "out_d": 0, "scale": [],
                        "align_corners": True, "align_mode": 1,
                        "data_layout": "NCHW"})


# ---------------------------------------------------------------------------
# sequence tail (dense+length design per SURVEY; reference
# operators/sequence_ops/*)
# ---------------------------------------------------------------------------

def _steps_mask(lengths, T):
    return jnp.arange(T)[None, :] < lengths[:, None]


@register("sequence_conv", no_grad_slots=("SeqLen",),
          attrs={"contextLength": 3, "contextStart": -1,
                 "contextStride": 1})
def _sequence_conv(ctx, ins, attrs):
    """[B, T, D] dense+mask layout; context window conv along T
    (reference sequence_conv_op: im2col over the sequence axis)."""
    v, flt = x(ins, "X"), x(ins, "Filter")
    lens = x(ins, "SeqLen")
    L = int(attrs.get("contextLength", 3))
    start = int(attrs.get("contextStart", -L // 2))
    B, T, D = v.shape
    cols = []
    for i in range(L):
        shift = start + i
        cols.append(jnp.roll(v, -shift, axis=1) *
                    ((jnp.arange(T) + shift >= 0) &
                     (jnp.arange(T) + shift < T))[None, :, None])
    col = jnp.concatenate(cols, axis=-1)           # [B, T, L*D]
    r = col @ flt                                   # [B, T, OC]
    if lens is not None:
        r = r * _steps_mask(lens.ravel(), T)[..., None]
    return out(r)


@register("sequence_slice", grad=None, no_grad_slots=("Offset", "Length"))
def _sequence_slice(ctx, ins, attrs):
    """Per-row slice, left-aligned into a zero-padded buffer (static
    shapes: output keeps T)."""
    v = x(ins, "X")
    off = x(ins, "Offset").ravel().astype(jnp.int32)
    ln = x(ins, "Length").ravel().astype(jnp.int32)
    T = v.shape[1]
    idx = jnp.clip(jnp.arange(T)[None, :] + off[:, None], 0, T - 1)
    keep = jnp.arange(T)[None, :] < ln[:, None]
    idx = idx.reshape(idx.shape + (1,) * (v.ndim - 2))
    g = jnp.take_along_axis(v, jnp.broadcast_to(
        idx, v.shape[:2] + (1,) * (v.ndim - 2)), axis=1)
    mask = keep.reshape(keep.shape + (1,) * (v.ndim - 2))
    return out(jnp.where(mask, g, 0))


@register("sequence_erase", grad=None, attrs={"tokens": []})
def _sequence_erase(ctx, ins, attrs):
    """Remove listed tokens, compact left, zero-pad (reference
    sequence_erase_op; static-length output + Length tensor)."""
    v = x(ins).astype(jnp.int64)
    toks = jnp.asarray(list(attrs.get("tokens", [])), jnp.int64)
    B, T = v.shape
    keep = ~jnp.isin(v, toks)
    pos = jnp.cumsum(keep, axis=1) - 1
    # erased tokens contribute 0 at (clipped) slot pos; kept tokens
    # scatter-ADD their value at their compacted slot — each slot
    # receives exactly one nonzero contribution
    res = jnp.zeros_like(v).at[
        jnp.arange(B)[:, None], jnp.clip(pos, 0, T - 1)].add(
        jnp.where(keep, v, 0))
    return {"Out": [res], "Length": [keep.sum(1).astype(jnp.int64)]}


@register("sequence_enumerate", grad=None,
          attrs={"win_size": 2, "pad_value": 0})
def _sequence_enumerate(ctx, ins, attrs):
    v = x(ins)
    W = int(attrs.get("win_size", 2))
    pad = attrs.get("pad_value", 0)
    B, T = v.shape
    cols = []
    for i in range(W):
        shifted = jnp.roll(v, -i, axis=1)
        valid = (jnp.arange(T) + i < T)[None, :]
        cols.append(jnp.where(valid, shifted, pad))
    return out(jnp.stack(cols, axis=-1))


@register("sequence_scatter", no_grad_slots=("Ids",))
def _sequence_scatter(ctx, ins, attrs):
    v, ids, upd = x(ins, "X"), x(ins, "Ids"), x(ins, "Updates")
    B = v.shape[0]
    return out(v.at[jnp.arange(B)[:, None],
                    ids.astype(jnp.int32)].add(upd))


# ---------------------------------------------------------------------------
# detection tail
# ---------------------------------------------------------------------------

@register("box_clip", grad=None)
def _box_clip(ctx, ins, attrs):
    boxes, im = x(ins, "Input"), x(ins, "ImInfo")
    h = im[..., 0:1] - 1
    w = im[..., 1:2] - 1
    while h.ndim < boxes.ndim:
        h = h[:, None]
        w = w[:, None]
    x1 = boxes[..., 0::2].clip(0) - jnp.maximum(
        boxes[..., 0::2] - w, 0).clip(0)
    y1 = boxes[..., 1::2].clip(0) - jnp.maximum(
        boxes[..., 1::2] - h, 0).clip(0)
    r = jnp.stack([x1[..., 0], y1[..., 0], x1[..., 1], y1[..., 1]],
                  axis=-1)
    return {"Output": [r]}


@register("polygon_box_transform", grad=None)
def _polygon_box_transform(ctx, ins, attrs):
    v = x(ins, "Input")  # [N, 8, H, W] offsets (EAST-style)
    n, c, h, w = v.shape
    gy = jnp.arange(h).reshape(1, 1, h, 1)
    gx = jnp.arange(w).reshape(1, 1, 1, w)
    xs = 4 * gx - v[:, 0::2]
    ys = 4 * gy - v[:, 1::2]
    r = jnp.stack([xs, ys], axis=2).reshape(n, c, h, w)
    return {"Output": [r]}


@register("roi_pool", grad=None, no_grad_slots=("ROIs", "RoisNum"),
          attrs={"pooled_height": 1, "pooled_width": 1,
                 "spatial_scale": 1.0})
def _roi_pool(ctx, ins, attrs):
    v, rois = x(ins, "X"), x(ins, "ROIs")
    ph = int(attrs["pooled_height"])
    pw = int(attrs["pooled_width"])
    scale = attrs.get("spatial_scale", 1.0)
    n, c, h, w = v.shape
    nr = rois.shape[0]

    def one(roi):
        x1, y1, x2, y2 = [jnp.round(roi[i] * scale) for i in range(4)]
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        img = v[0]  # single-image contract (batch via RoisNum upstream)
        ys = y1 + jnp.arange(ph + 1) * rh / ph
        xs = x1 + jnp.arange(pw + 1) * rw / pw
        gy = jnp.arange(h)[None, :]
        gx = jnp.arange(w)[None, :]
        my = (gy >= jnp.floor(ys[:-1, None])) & (gy < jnp.ceil(
            ys[1:, None]))
        mx = (gx >= jnp.floor(xs[:-1, None])) & (gx < jnp.ceil(
            xs[1:, None]))
        big = jnp.finfo(v.dtype).min
        r = jnp.where(my[None, :, None, :, None] &
                      mx[None, None, :, None, :],
                      img[:, None, None, :, :], big)
        return jnp.max(r, axis=(3, 4))

    r = jax.vmap(one)(rois)
    return out(r)


@register("psroi_pool", grad=None, no_grad_slots=("ROIs", "RoisNum"),
          attrs={"output_channels": 1, "pooled_height": 1,
                 "pooled_width": 1, "spatial_scale": 1.0})
def _psroi_pool(ctx, ins, attrs):
    """Position-sensitive ROI average pool (reference psroi_pool_op):
    channel block (i,j) serves output bin (i,j)."""
    v, rois = x(ins, "X"), x(ins, "ROIs")
    oc = int(attrs["output_channels"])
    ph = int(attrs["pooled_height"])
    pw = int(attrs["pooled_width"])
    scale = attrs.get("spatial_scale", 1.0)
    n, c, h, w = v.shape

    def one(roi):
        x1, y1, x2, y2 = [roi[i] * scale for i in range(4)]
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        img = v[0].reshape(oc, ph, pw, h, w)
        ys = y1 + jnp.arange(ph + 1) * rh / ph
        xs = x1 + jnp.arange(pw + 1) * rw / pw
        gy = jnp.arange(h)[None, :]
        gx = jnp.arange(w)[None, :]
        my = (gy >= jnp.floor(ys[:-1, None])) & (gy < jnp.ceil(
            ys[1:, None]))
        mx = (gx >= jnp.floor(xs[:-1, None])) & (gx < jnp.ceil(
            xs[1:, None]))
        m = my[:, None, :, None] & mx[None, :, None, :]  # [ph,pw,h,w]
        cnt = jnp.maximum(m.sum(axis=(2, 3)), 1)
        s = jnp.einsum("opqhw,pqhw->opq", img, m.astype(v.dtype))
        return s / cnt

    return out(jax.vmap(one)(rois))


@register("generate_proposals_v2", grad=None,
          no_grad_slots=("Scores", "BboxDeltas", "ImShape", "Anchors",
                         "Variances"),
          attrs={"pre_nms_topN": 6000, "post_nms_topN": 1000,
                 "nms_thresh": 0.5, "min_size": 0.1, "eta": 1.0,
                 "pixel_offset": True})
def _generate_proposals_v2(ctx, ins, attrs):
    """RPN proposal generation (reference generate_proposals_op):
    decode anchors, clip, filter tiny boxes, topk + NMS. Static-shape
    contract: returns exactly post_nms_topN rows (suppressed rows
    zeroed), plus the valid count."""
    scores = x(ins, "Scores")       # [N, A, H, W]
    deltas = x(ins, "BboxDeltas")   # [N, 4A, H, W]
    im = x(ins, "ImShape")          # [N, 2] (v2) / ImInfo [N, 3] (v1)
    if im is None:
        im = x(ins, "ImInfo")
    anchors = x(ins, "Anchors").reshape(-1, 4)
    var = x(ins, "Variances")
    pre_n = int(attrs.get("pre_nms_topN", 6000))
    post_n = int(attrs.get("post_nms_topN", 1000))
    thresh = attrs.get("nms_thresh", 0.5)
    min_size = attrs.get("min_size", 0.1)
    off = 1.0 if attrs.get("pixel_offset", True) else 0.0
    n = scores.shape[0]
    sc = scores.reshape(n, -1)
    dl = deltas.reshape(n, -1, 4)
    K = sc.shape[1]
    pre_n = min(pre_n, K)
    post_n = min(post_n, pre_n)
    v = var.reshape(-1, 4) if var is not None else jnp.ones((1, 4), F32)

    def decode(d):
        aw = anchors[:, 2] - anchors[:, 0] + off
        ah = anchors[:, 3] - anchors[:, 1] + off
        acx = anchors[:, 0] + aw * 0.5
        acy = anchors[:, 1] + ah * 0.5
        cx = d[:, 0] * v[:, 0] * aw + acx
        cy = d[:, 1] * v[:, 1] * ah + acy
        bw = jnp.exp(jnp.clip(d[:, 2] * v[:, 2], -10, 10)) * aw
        bh = jnp.exp(jnp.clip(d[:, 3] * v[:, 3], -10, 10)) * ah
        return jnp.stack([cx - bw * 0.5, cy - bh * 0.5,
                          cx + bw * 0.5 - off, cy + bh * 0.5 - off], -1)

    def one(sc_i, dl_i, im_i):
        boxes = decode(dl_i)
        boxes = jnp.stack([boxes[:, 0].clip(0, im_i[1] - 1),
                           boxes[:, 1].clip(0, im_i[0] - 1),
                           boxes[:, 2].clip(0, im_i[1] - 1),
                           boxes[:, 3].clip(0, im_i[0] - 1)], -1)
        ws = boxes[:, 2] - boxes[:, 0] + off
        hs = boxes[:, 3] - boxes[:, 1] + off
        valid = (ws >= min_size) & (hs >= min_size)
        sc_m = jnp.where(valid, sc_i, -jnp.inf)
        top_sc, top_ix = jax.lax.top_k(sc_m, pre_n)
        top_bx = boxes[top_ix]
        # greedy NMS over the pre-topk (static loop post_n picks)
        def pick(state, _):
            alive, sel_s = state
            cand = jnp.where(alive, sel_s, -jnp.inf)
            i = jnp.argmax(cand)
            ok = cand[i] > -jnp.inf
            bi = top_bx[i]
            xx1 = jnp.maximum(top_bx[:, 0], bi[0])
            yy1 = jnp.maximum(top_bx[:, 1], bi[1])
            xx2 = jnp.minimum(top_bx[:, 2], bi[2])
            yy2 = jnp.minimum(top_bx[:, 3], bi[3])
            inter = jnp.clip(xx2 - xx1 + off, 0) * \
                jnp.clip(yy2 - yy1 + off, 0)
            a1 = (top_bx[:, 2] - top_bx[:, 0] + off) * \
                (top_bx[:, 3] - top_bx[:, 1] + off)
            ai = (bi[2] - bi[0] + off) * (bi[3] - bi[1] + off)
            iou = inter / jnp.maximum(a1 + ai - inter, 1e-10)
            alive = alive & (iou <= thresh)
            return (alive, sel_s), (jnp.where(ok, i, -1),
                                    jnp.where(ok, top_sc[i], 0.0))
        alive0 = top_sc > -jnp.inf
        (_, _), (picks, psc) = jax.lax.scan(
            pick, (alive0, top_sc), None, length=post_n)
        ok = picks >= 0
        rois = jnp.where(ok[:, None],
                         top_bx[jnp.clip(picks, 0)], 0.0)
        return rois, jnp.where(ok, psc, 0.0), ok.sum().astype(jnp.int32)

    rois, psc, cnt = jax.vmap(one)(sc, dl, im)
    return {"RpnRois": [rois], "RpnRoiProbs": [psc],
            "RpnRoisNum": [cnt]}


register("generate_proposals", _generate_proposals_v2, grad=None,
         no_grad_slots=("Scores", "BboxDeltas", "ImInfo", "Anchors",
                        "Variances"),
         attrs={"pre_nms_topN": 6000, "post_nms_topN": 1000,
                "nms_thresh": 0.5, "min_size": 0.1, "eta": 1.0,
                "pixel_offset": True})


@register("density_prior_box", grad=None,
          attrs={"densities": [], "fixed_sizes": [], "fixed_ratios": [],
                 "variances": [0.1, 0.1, 0.2, 0.2], "clip": False,
                 "step_w": 0.0, "step_h": 0.0, "offset": 0.5})
def _density_prior_box(ctx, ins, attrs):
    feat, img = x(ins, "Input"), x(ins, "Image")
    fh, fw = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    sw = attrs.get("step_w") or iw / fw
    sh = attrs.get("step_h") or ih / fh
    offset = attrs.get("offset", 0.5)
    boxes = []
    for dens, fs in zip(attrs["densities"], attrs["fixed_sizes"]):
        for ratio in (attrs["fixed_ratios"] or [1.0]):
            bw = fs * np.sqrt(ratio)
            bh = fs / np.sqrt(ratio)
            step = fs / dens
            for di in range(dens):
                for dj in range(dens):
                    shift_x = (dj + 0.5) * step - fs / 2.0
                    shift_y = (di + 0.5) * step - fs / 2.0
                    cx = (jnp.arange(fw) + offset) * sw + shift_x
                    cy = (jnp.arange(fh) + offset) * sh + shift_y
                    cxg, cyg = jnp.meshgrid(cx, cy)
                    b = jnp.stack([(cxg - bw / 2) / iw,
                                   (cyg - bh / 2) / ih,
                                   (cxg + bw / 2) / iw,
                                   (cyg + bh / 2) / ih], -1)
                    boxes.append(b)
    bx = jnp.stack(boxes, axis=2)  # [fh, fw, nprior, 4]
    if attrs.get("clip"):
        bx = bx.clip(0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(attrs["variances"], F32),
                           bx.shape)
    return {"Boxes": [bx], "Variances": [var]}


# ---------------------------------------------------------------------------
# batch-size-like fills + frame/overlap_add + complex views
# ---------------------------------------------------------------------------

@register("fill_constant_batch_size_like", grad=None,
          attrs={"shape": [], "value": 0.0, "dtype": "float32",
                 "input_dim_idx": 0, "output_dim_idx": 0})
def _fill_constant_bsl(ctx, ins, attrs):
    ref = x(ins, "Input")
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = \
        ref.shape[attrs.get("input_dim_idx", 0)]
    return out(jnp.full(tuple(shape), attrs.get("value", 0.0),
                        jnp.dtype(attrs.get("dtype", "float32"))))


@register("gaussian_random_batch_size_like", grad=None, stochastic=True,
          attrs={"shape": [], "mean": 0.0, "std": 1.0,
                 "input_dim_idx": 0, "output_dim_idx": 0, "seed": 0,
                 "dtype": "float32"})
def _gaussian_random_bsl(ctx, ins, attrs):
    ref = x(ins, "Input")
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = \
        ref.shape[attrs.get("input_dim_idx", 0)]
    key = ctx.rng(attrs)
    r = attrs.get("mean", 0.0) + attrs.get("std", 1.0) * \
        jax.random.normal(key, tuple(shape))
    return out(r.astype(jnp.dtype(attrs.get("dtype", "float32"))))


@register("frame", attrs={"frame_length": 1, "hop_length": 1, "axis": -1})
def _frame(ctx, ins, attrs):
    v = x(ins)
    fl = int(attrs["frame_length"])
    hop = int(attrs["hop_length"])
    n = v.shape[-1]
    nf = (n - fl) // hop + 1
    idx = jnp.arange(fl)[:, None] + hop * jnp.arange(nf)[None, :]
    return out(v[..., idx])


@register("overlap_add", attrs={"hop_length": 1, "axis": -1})
def _overlap_add(ctx, ins, attrs):
    v = x(ins)  # [..., frame_length, n_frames]
    hop = int(attrs["hop_length"])
    fl, nf = v.shape[-2], v.shape[-1]
    n = (nf - 1) * hop + fl
    idx = (jnp.arange(fl)[:, None] + hop * jnp.arange(nf)[None, :])
    res = jnp.zeros(v.shape[:-2] + (n,), v.dtype)
    return out(res.at[..., idx].add(v))


register("complex", lambda ctx, ins, attrs: out(
    jax.lax.complex(x(ins, "X").astype(F32),
                    x(ins, "Y").astype(F32))), grad=None)
register("as_complex", lambda ctx, ins, attrs: out(
    jax.lax.complex(x(ins)[..., 0], x(ins)[..., 1])), grad=None)
register("as_real", lambda ctx, ins, attrs: out(
    jnp.stack([jnp.real(x(ins)), jnp.imag(x(ins))], -1)), grad=None)


@register("renorm", attrs={"p": 2.0, "axis": 0, "max_norm": 1.0})
def _renorm(ctx, ins, attrs):
    v = x(ins)
    p = float(attrs.get("p", 2.0))
    ax = int(attrs.get("axis", 0)) % v.ndim
    mx = attrs.get("max_norm", 1.0)
    red = tuple(i for i in range(v.ndim) if i != ax)
    norms = jnp.sum(jnp.abs(v) ** p, axis=red, keepdims=True) ** (1 / p)
    scale = jnp.where(norms > mx, mx / jnp.maximum(norms, 1e-12), 1.0)
    return out(v * scale)


# -- compile-time shape inference additions (VERDICT r5 missing #3) ---------

def _take_along_axis_infer(op):
    v, idx = op.invar("Input"), op.invar("Index")
    if None in (v, idx) or v.shape is None or idx.shape is None:
        return
    for n in op.output("Result"):
        op.block.create_var(name=n, shape=tuple(idx.shape), dtype=v.dtype)


from ..registry import same_shape_as as _same
from .. import registry as _registry
_registry._REGISTRY["take_along_axis"].infer_shape = _take_along_axis_infer
_registry._REGISTRY["put_along_axis"].infer_shape = _same("Input", "Result")
