"""Optimizer + gradient-utility ops.

Replaces reference operators/optimizers/* (sgd, momentum, adam, adamw, lamb,
rmsprop, adagrad, ...) and grad utilities (clip_by_norm, amp ops,
coalesce_tensor — SURVEY §2.3). Each op is functional: it returns the updated
param/accumulator arrays; the executor donates the old buffers so the update
is in-place on device.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..registry import register, same_shape_as
from .common import x


def _lr(ins):
    v = x(ins, "LearningRate")
    return v.reshape(()) if v is not None and getattr(v, "ndim", 0) else v


def _is_sr(g):
    from ..selected_rows import SelectedRows
    return isinstance(g, SelectedRows)


@register("sgd", grad=None, no_grad_slots=("Param", "Grad", "LearningRate"))
def _sgd(ctx, ins, attrs):
    p, g = x(ins, "Param"), x(ins, "Grad")
    lr = _lr(ins)
    if _is_sr(g):
        # sparse rows: touch only the looked-up rows (reference sgd_op.cc
        # SelectedRows kernel); duplicate rows accumulate via scatter-add
        return {"ParamOut": [p.at[g.rows].add(
            (-lr * g.values).astype(p.dtype))]}
    return {"ParamOut": [p - lr * g.astype(p.dtype)]}


@register("momentum", grad=None, attrs={"mu": 0.9, "use_nesterov": False,
                                        "regularization_method": "",
                                        "regularization_coeff": 0.0})
def _momentum(ctx, ins, attrs):
    p, g, v = x(ins, "Param"), x(ins, "Grad"), x(ins, "Velocity")
    lr = _lr(ins)
    mu = attrs["mu"]
    if _is_sr(g):
        # exact dense semantics (sparse grad is zero off-rows): decay the
        # whole velocity, scatter-add the sparse grad
        if attrs.get("regularization_method") == "l2_decay":
            raise NotImplementedError(
                "l2_decay with sparse momentum grads — densify the grad or "
                "use weight decay on the dense path")
        v_new = (mu * v).at[g.rows].add(g.values.astype(v.dtype))
        if attrs.get("use_nesterov"):
            # dense rule p - lr*(g + mu*v_new) with g zero off-rows
            p_new = (p - lr * mu * v_new).at[g.rows].add(
                (-lr * g.values).astype(p.dtype))
        else:
            p_new = p - lr * v_new
        return {"ParamOut": [p_new], "VelocityOut": [v_new]}
    if attrs.get("regularization_method") == "l2_decay":
        g = g + attrs["regularization_coeff"] * p
    v_new = mu * v + g
    if attrs.get("use_nesterov"):
        p_new = p - (g + mu * v_new) * lr
    else:
        p_new = p - lr * v_new
    return {"ParamOut": [p_new], "VelocityOut": [v_new]}


@register("adam", grad=None,
          attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8,
                 "lazy_mode": False, "min_row_size_to_use_multithread": 1000})
def _adam(ctx, ins, attrs):
    p, g = x(ins, "Param"), x(ins, "Grad")
    m1, m2 = x(ins, "Moment1"), x(ins, "Moment2")
    b1p, b2p = x(ins, "Beta1Pow"), x(ins, "Beta2Pow")
    lr = _lr(ins)
    if _is_sr(g):
        if attrs.get("lazy_mode"):
            return _adam_sparse_lazy(p, g, m1, m2, b1p, b2p, lr, attrs)
        g = g.to_dense()  # exact adam semantics decay ALL moments
    b1 = x(ins, "Beta1Tensor")
    b2 = x(ins, "Beta2Tensor")
    b1 = attrs["beta1"] if b1 is None else b1.reshape(())
    b2 = attrs["beta2"] if b2 is None else b2.reshape(())
    eps = attrs["epsilon"]
    g = g.astype(jnp.float32)
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * jnp.square(g)
    b1pn, b2pn = b1p * b1, b2p * b2
    lr_t = lr * jnp.sqrt(1 - b2pn.reshape(())) / (1 - b1pn.reshape(()))
    p_new = p - lr_t * (m1n / (jnp.sqrt(m2n) + eps)).astype(p.dtype)
    return {"ParamOut": [p_new], "Moment1Out": [m1n], "Moment2Out": [m2n],
            "Beta1PowOut": [b1pn], "Beta2PowOut": [b2pn]}


def _adam_sparse_lazy(p, g, m1, m2, b1p, b2p, lr, attrs):
    """Reference adam lazy_mode (operators/optimizers/adam_op.h SelectedRows
    path): duplicate rows are merged first (scatter::MergeAdd), then
    moments and param update touch only the grad's rows.

    The merge keeps static shapes under jit: sort rows, segment-sum the
    values, broadcast each segment's sum back to every duplicate (so all
    duplicates write identical moment values), and apply the param step
    once per segment via a first-occurrence mask."""
    b1, b2, eps = attrs["beta1"], attrs["beta2"], attrs["epsilon"]
    order = jnp.argsort(g.rows)
    rows = g.rows[order]
    vals = g.values.astype(jnp.float32)[order]
    first = jnp.concatenate(
        [jnp.ones((1,), bool), rows[1:] != rows[:-1]])
    seg = jnp.cumsum(first) - 1                      # segment index per pos
    merged = jnp.zeros_like(vals).at[seg].add(vals)  # per-segment sums
    gv = merged[seg]                                 # merged grad per pos
    m1r, m2r = m1[rows], m2[rows]
    m1n = b1 * m1r + (1 - b1) * gv
    m2n = b2 * m2r + (1 - b2) * jnp.square(gv)
    b1pn, b2pn = b1p * b1, b2p * b2
    lr_t = lr * jnp.sqrt(1 - b2pn.reshape(())) / (1 - b1pn.reshape(()))
    upd = (lr_t * m1n / (jnp.sqrt(m2n) + eps)).astype(p.dtype)
    upd = jnp.where(first[:, None], upd, 0)          # one step per row
    return {"ParamOut": [p.at[rows].add(-upd)],
            "Moment1Out": [m1.at[rows].set(m1n)],
            "Moment2Out": [m2.at[rows].set(m2n)],
            "Beta1PowOut": [b1pn], "Beta2PowOut": [b2pn]}


@register("adamw", grad=None,
          attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8,
                 "coeff": 0.01, "lr_ratio": 1.0, "with_decay": True,
                 "lazy_mode": False})
def _adamw(ctx, ins, attrs):
    p = x(ins, "Param")
    lr = _lr(ins)
    if attrs.get("with_decay", True):
        p = p * (1.0 - lr * attrs["coeff"] * attrs.get("lr_ratio", 1.0))
    ins2 = dict(ins)
    ins2["Param"] = [p]
    return _adam(ctx, ins2, attrs)


@register("adamax", grad=None,
          attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8})
def _adamax(ctx, ins, attrs):
    p, g = x(ins, "Param"), x(ins, "Grad")
    m, inf = x(ins, "Moment"), x(ins, "InfNorm")
    b1p = x(ins, "Beta1Pow")
    lr = _lr(ins)
    b1, b2, eps = attrs["beta1"], attrs["beta2"], attrs["epsilon"]
    m_new = b1 * m + (1 - b1) * g
    inf_new = jnp.maximum(b2 * inf, jnp.abs(g))
    lr_t = lr / (1 - b1p.reshape(()))
    p_new = p - lr_t * m_new / (inf_new + eps)
    return {"ParamOut": [p_new], "MomentOut": [m_new], "InfNormOut": [inf_new]}


@register("adagrad", grad=None, attrs={"epsilon": 1e-6})
def _adagrad(ctx, ins, attrs):
    p, g, mom = x(ins, "Param"), x(ins, "Grad"), x(ins, "Moment")
    lr = _lr(ins)
    mom_new = mom + jnp.square(g)
    p_new = p - lr * g / (jnp.sqrt(mom_new) + attrs["epsilon"])
    return {"ParamOut": [p_new], "MomentOut": [mom_new]}


@register("adadelta", grad=None, attrs={"rho": 0.95, "epsilon": 1e-6})
def _adadelta(ctx, ins, attrs):
    p, g = x(ins, "Param"), x(ins, "Grad")
    avg_sq, avg_upd = x(ins, "AvgSquaredGrad"), x(ins, "AvgSquaredUpdate")
    rho, eps = attrs["rho"], attrs["epsilon"]
    sq = rho * avg_sq + (1 - rho) * jnp.square(g)
    upd = g * jnp.sqrt(avg_upd + eps) / jnp.sqrt(sq + eps)
    upd_acc = rho * avg_upd + (1 - rho) * jnp.square(upd)
    return {"ParamOut": [p - upd], "AvgSquaredGradOut": [sq],
            "AvgSquaredUpdateOut": [upd_acc]}


@register("rmsprop", grad=None,
          attrs={"epsilon": 1e-10, "decay": 0.9, "momentum": 0.0,
                 "centered": False})
def _rmsprop(ctx, ins, attrs):
    p, g = x(ins, "Param"), x(ins, "Grad")
    ms, mom = x(ins, "MeanSquare"), x(ins, "Moment")
    mg = x(ins, "MeanGrad")
    lr = _lr(ins)
    rho, eps, mu = attrs["decay"], attrs["epsilon"], attrs["momentum"]
    ms_new = rho * ms + (1 - rho) * jnp.square(g)
    if attrs.get("centered"):
        mg_new = rho * mg + (1 - rho) * g
        denom = ms_new - jnp.square(mg_new) + eps
    else:
        mg_new = mg
        denom = ms_new + eps
    mom_new = mu * mom + lr * g / jnp.sqrt(denom)
    outs = {"ParamOut": [p - mom_new], "MeanSquareOut": [ms_new],
            "MomentOut": [mom_new]}
    if mg is not None:
        outs["MeanGradOut"] = [mg_new]
    return outs


@register("lamb", grad=None,
          attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-6,
                 "weight_decay": 0.01})
def _lamb(ctx, ins, attrs):
    p, g = x(ins, "Param"), x(ins, "Grad")
    m1, m2 = x(ins, "Moment1"), x(ins, "Moment2")
    b1p, b2p = x(ins, "Beta1Pow"), x(ins, "Beta2Pow")
    lr = _lr(ins)
    b1, b2, eps = attrs["beta1"], attrs["beta2"], attrs["epsilon"]
    wd = attrs["weight_decay"]
    g = g.astype(jnp.float32)
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * jnp.square(g)
    # bias-correct with the POST-update pows (like the adam kernel above):
    # pow accumulators start at 1.0, so correcting with the pre-update value
    # would divide by zero on the first step
    b1pn, b2pn = b1p * b1, b2p * b2
    mhat = m1n / (1 - b1pn.reshape(()))
    vhat = m2n / (1 - b2pn.reshape(()))
    r = mhat / (jnp.sqrt(vhat) + eps) + wd * p.astype(jnp.float32)
    p_norm = jnp.linalg.norm(p.astype(jnp.float32))
    r_norm = jnp.linalg.norm(r)
    trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
    p_new = p - (lr * trust * r).astype(p.dtype)
    return {"ParamOut": [p_new], "Moment1Out": [m1n], "Moment2Out": [m2n],
            "Beta1PowOut": [b1pn], "Beta2PowOut": [b2pn]}


@register("ftrl", grad=None, attrs={"l1": 0.0, "l2": 0.0, "lr_power": -0.5})
def _ftrl(ctx, ins, attrs):
    p, g = x(ins, "Param"), x(ins, "Grad")
    sq, lin = x(ins, "SquaredAccumulator"), x(ins, "LinearAccumulator")
    lr = _lr(ins)
    l1, l2, lrp = attrs["l1"], attrs["l2"], attrs["lr_power"]
    new_sq = sq + jnp.square(g)
    sigma = (new_sq ** -lrp - sq ** -lrp) / lr
    new_lin = lin + g - sigma * p
    pre = jnp.clip(new_lin, -l1, l1) - new_lin
    denom = new_sq ** -lrp / lr + 2 * l2
    return {"ParamOut": [pre / denom], "SquaredAccumOut": [new_sq],
            "LinearAccumOut": [new_lin]}


@register("dpsgd", grad=None, stochastic=True,
          attrs={"clip": 10.0, "batch_size": 16.0, "sigma": 1.0})
def _dpsgd(ctx, ins, attrs):
    p, g = x(ins, "Param"), x(ins, "Grad")
    lr = _lr(ins)
    gn = jnp.linalg.norm(g)
    scale = jnp.minimum(1.0, attrs["clip"] / jnp.maximum(gn, 1e-12))
    noise = jax.random.normal(ctx.rng(attrs), g.shape) * \
        attrs["sigma"] * attrs["clip"] / attrs["batch_size"]
    return {"ParamOut": [p - lr * (g * scale + noise)]}


@register("decayed_adagrad", grad=None,
          attrs={"decay": 0.95, "epsilon": 1e-6})
def _decayed_adagrad(ctx, ins, attrs):
    p, g, mom = x(ins, "Param"), x(ins, "Grad"), x(ins, "Moment")
    lr = _lr(ins)
    mom_new = attrs["decay"] * mom + (1 - attrs["decay"]) * jnp.square(g)
    return {"ParamOut": [p - lr * g / (jnp.sqrt(mom_new) + attrs["epsilon"])],
            "MomentOut": [mom_new]}


# ---------------------------------------------------------------------------
# gradient utilities
# ---------------------------------------------------------------------------

@register("clip_by_norm", attrs={"max_norm": 1.0},
          infer_shape=same_shape_as("X"))
def _clip_by_norm(ctx, ins, attrs):
    v = x(ins)
    n = jnp.sqrt(jnp.sum(jnp.square(v)))
    mx = attrs["max_norm"]
    return {"Out": [jnp.where(n > mx, v * (mx / jnp.maximum(n, 1e-12)), v)]}


@register("lerp")
def _lerp(ctx, ins, attrs):
    a, b, w = x(ins, "X"), x(ins, "Y"), x(ins, "Weight")
    return {"Out": [a + w * (b - a)]}


@register("check_finite_and_unscale", grad=None)
def _check_finite_and_unscale(ctx, ins, attrs):
    """AMP: outs = ins/scale; FoundInfinite = any nonfinite
    (reference operators/amp/check_finite_and_unscale_op.cc)."""
    scale = x(ins, "Scale").reshape(())
    xs = ins.get("X", [])
    found = jnp.zeros((), dtype=bool)
    outs = []
    for v in xs:
        found = found | ~jnp.all(jnp.isfinite(v))
        outs.append(v / scale)
    return {"Out": outs, "FoundInfinite": [found.reshape((1,))]}


@register("update_loss_scaling", grad=None,
          attrs={"incr_every_n_steps": 1000, "decr_every_n_nan_or_inf": 2,
                 "incr_ratio": 2.0, "decr_ratio": 0.5,
                 "stop_update": False})
def _update_loss_scaling(ctx, ins, attrs):
    """AMP dynamic loss-scale state machine
    (reference operators/amp/update_loss_scaling_op.cc)."""
    found = x(ins, "FoundInfinite").reshape(()).astype(bool)
    scale = x(ins, "PrevLossScaling").reshape(())
    good = x(ins, "InGoodSteps").reshape(()).astype(jnp.int32)
    bad = x(ins, "InBadSteps").reshape(()).astype(jnp.int32)
    incr_n = attrs["incr_every_n_steps"]
    decr_n = attrs["decr_every_n_nan_or_inf"]
    bad_new = jnp.where(found, bad + 1, 0)
    good_new = jnp.where(found, 0, good + 1)
    scale_up = good_new >= incr_n
    scale_dn = bad_new >= decr_n
    new_scale = jnp.where(
        scale_dn, jnp.maximum(scale * attrs["decr_ratio"], 1.0),
        jnp.where(scale_up, scale * attrs["incr_ratio"], scale))
    good_new = jnp.where(scale_up, 0, good_new)
    bad_new = jnp.where(scale_dn, 0, bad_new)
    outs = []
    for v in ins.get("X", []):
        outs.append(jnp.where(found, jnp.zeros_like(v), v))
    return {"Out": outs, "LossScaling": [new_scale.reshape((1,))],
            "OutGoodSteps": [good_new.reshape((1,))],
            "OutBadSteps": [bad_new.reshape((1,))]}


@register("coalesce_tensor", grad=None,
          attrs={"copy_data": True, "use_align": True, "dtype": "float32"})
def _coalesce_tensor(ctx, ins, attrs):
    """Fuse N tensors into one flat buffer (reference coalesce_tensor_op.cc).
    Under XLA this is only needed for API parity — fusion of collectives is
    handled by the compiler."""
    xs = ins.get("Input", [])
    flat = jnp.concatenate([v.reshape(-1) for v in xs])
    outs = []
    off = 0
    for v in xs:
        outs.append(flat[off:off + v.size].reshape(v.shape))
        off += v.size
    return {"Output": outs, "FusedOutput": [flat]}


@register("average_accumulates", grad=None,
          attrs={"average_window": 10000.0, "max_average_window": 10000,
                 "min_average_window": 10000})
def _average_accumulates(ctx, ins, attrs):
    param = x(ins, "param")
    s1 = x(ins, "in_sum_1")
    n = x(ins, "in_num_accumulates").reshape(()).astype(jnp.int64)
    return {"out_sum_1": [s1 + param],
            "out_sum_2": [x(ins, "in_sum_2")],
            "out_sum_3": [x(ins, "in_sum_3")],
            "out_num_accumulates": [(n + 1).reshape((1,))],
            "out_old_num_accumulates": [x(ins, "in_old_num_accumulates")],
            "out_num_updates": [x(ins, "in_num_updates")]}


@register("lars_momentum", grad=None,
          attrs={"mu": 0.9, "lars_coeff": 0.001,
                 "lars_weight_decay": 0.0005, "epsilon": 0.0})
def _lars_momentum(ctx, ins, attrs):
    """Layer-wise adaptive rate scaling (reference
    operators/optimizers/lars_momentum_op.cc): the local lr of each param
    scales with ||param|| / (||grad|| + wd*||param||)."""
    p, g, v = x(ins, "Param"), x(ins, "Grad"), x(ins, "Velocity")
    lr = _lr(ins)
    mu, coeff = attrs["mu"], attrs["lars_coeff"]
    wd, eps = attrs["lars_weight_decay"], attrs["epsilon"]
    g = g.astype(jnp.float32)
    p32 = p.astype(jnp.float32)
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    local_lr = jnp.where(
        (p_norm > 0) & (g_norm > 0),
        lr * coeff * p_norm / (g_norm + wd * p_norm + eps), lr)
    v_new = mu * v + local_lr * (g + wd * p32)
    return {"ParamOut": [(p32 - v_new).astype(p.dtype)],
            "VelocityOut": [v_new]}


@register("dgc_momentum", grad=None,
          attrs={"mu": 0.9, "ratio": 0.001, "rampup_begin_step": 0.0,
                 "use_nesterov": False})
def _dgc_momentum(ctx, ins, attrs):
    """Deep Gradient Compression (reference operators/dgc_op.h +
    dgc_momentum_op.h, fused): momentum correction (u), local residual
    accumulation (v), top-ratio selection by |v| — the selected slice
    updates the param, the rest stays local. The reference sends the
    selected values through a sparse allgather; under GSPMD the grads
    arriving here are already mesh-reduced, so the selection keeps DGC's
    *convergence semantics* (its bandwidth saving is an artifact of the
    NCCL transport the TPU build replaces)."""
    p, g = x(ins, "Param"), x(ins, "Grad")
    u, v = x(ins, "U"), x(ins, "V")
    step = x(ins, "CurrentStep").reshape(())
    lr = _lr(ins)
    mu, ratio = attrs["mu"], attrs["ratio"]
    g = g.astype(jnp.float32)
    # momentum correction: momentum accumulates BEFORE compression
    u_new = mu * u + g
    v_acc = v + u_new
    flat = jnp.abs(v_acc.reshape(-1))
    thr = jnp.quantile(flat, jnp.clip(1.0 - ratio, 0.0, 1.0)) \
        if flat.size > 1 else jnp.zeros((), jnp.float32)
    mask = (jnp.abs(v_acc) >= thr).astype(jnp.float32)
    encoded = v_acc * mask
    in_rampup = step < attrs["rampup_begin_step"]
    # pre-rampup: vanilla momentum (no compression, no residual)
    p_dgc = p.astype(jnp.float32) - lr * encoded
    p_mom = p.astype(jnp.float32) - lr * u_new
    p_new = jnp.where(in_rampup, p_mom, p_dgc)
    v_new = jnp.where(in_rampup, v, v_acc * (1.0 - mask))
    return {"ParamOut": [p_new.astype(p.dtype)], "UOut": [u_new],
            "VOut": [v_new],
            "CurrentStepOut": [(step + 1.0).reshape((1,))]}


@register("localsgd_sync", grad=None,
          attrs={"k_steps": 1, "begin_step": 1})
def _localsgd_sync(ctx, ins, attrs):
    """LocalSGD parameter averaging tick (reference fleet
    meta_optimizers/localsgd_optimizer.py inserted c_allreduce block):
    on every k-th step blend the param to its data-parallel world
    average. Under traced mesh execution the average rides lax.pmean over
    the dp axis when one is ambient; otherwise (params replicated /
    single process) it is the identity and only the mask logic runs."""
    p = x(ins, "Param")
    step = x(ins, "Step").reshape(())
    k, begin = attrs["k_steps"], attrs["begin_step"]
    try:
        avg = jax.lax.pmean(p, "dp")
    except NameError:  # no ambient dp axis: replicated params, identity
        avg = p
    do_sync = (step >= begin) & (jnp.mod(step, float(k)) == 0.0)
    return {"ParamOut": [jnp.where(do_sync, avg, p)]}


# ---------------------------------------------------------------------------
# compile-time shape inference: every optimizer output mirrors the slot
# it updates (ParamOut ~ Param, Moment1Out ~ Moment1, ...) — build-time
# Programs can then shape-check whole train steps (VERDICT r5 missing #3)
# ---------------------------------------------------------------------------

def _optimizer_infer(op):
    for slot, names in op.outputs.items():
        src_slot = slot[:-3] if slot.endswith("Out") else slot
        src = op.invar(src_slot)
        if src is None or src.shape is None:
            continue
        for n in names:
            op.block.create_var(name=n, shape=tuple(src.shape),
                                dtype=src.dtype)


from .. import registry as _registry
for _name in ("sgd", "momentum", "adam", "adamw", "adamax", "adagrad",
              "adadelta", "rmsprop", "lamb", "ftrl", "dpsgd",
              "decayed_adagrad", "lars_momentum", "proximal_gd",
              "proximal_adagrad"):
    if _name in _registry._REGISTRY:
        _registry._REGISTRY[_name].infer_shape = _optimizer_infer
