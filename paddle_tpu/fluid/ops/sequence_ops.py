"""Sequence ops — the LoD-free mask/segment tier (SURVEY §7).

The reference represents ragged batches as LoDTensors and ships 13
sequence_* ops over them (operators/sequence_ops/sequence_pool_op.cc,
sequence_pad_op.cc, sequence_softmax_op.cc, sequence_reverse_op.h,
sequence_expand_op.cc; LoD itself at framework/lod_tensor.h:52).  LoD's
dynamic offsets don't fit XLA's static shapes, so here every sequence is
dense [B, T, ...] plus either a `lengths` vector or segment ids — masks are
computed on the fly, shapes stay static, everything jits.  The `rnn` op
(reference operators/rnn_op + cudnn_lstm_op.cu, math/lstm_compute.*) is a
single lax.scan over time, multi-layer and bidirectional, with
per-sequence-length masking replacing LoD-sorted batching.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..registry import register, same_shape_as
from .common import out, x


def _len_mask(lengths, maxlen):
    """[B] lengths -> [B, maxlen] bool mask."""
    return jnp.arange(maxlen)[None, :] < lengths.reshape(-1, 1)


# ---------------------------------------------------------------------------
# masking / padding
# ---------------------------------------------------------------------------

def _seq_mask_infer(op):
    v = op.invar("X")
    maxlen = op.attr("maxlen", -1)
    if v is None or v.shape is None or maxlen is None or maxlen < 0:
        return
    for name in op.output("Y"):
        op.block.create_var(name=name, shape=tuple(v.shape) + (maxlen,),
                            dtype=op.attr("out_dtype", "int64"))


@register("sequence_mask", infer_shape=_seq_mask_infer, grad=None,
          attrs={"maxlen": -1, "out_dtype": "int64"})
def _sequence_mask(ctx, ins, attrs):
    lens = x(ins)
    maxlen = attrs.get("maxlen", -1)
    if maxlen is None or maxlen < 0:
        if isinstance(lens, jax.core.Tracer):
            raise ValueError(
                "sequence_mask under jit needs a static maxlen attr "
                "(dynamic max(lengths) would be a dynamic shape)")
        maxlen = int(jnp.max(lens))
    m = jnp.arange(maxlen) < lens[..., None]
    from .. import core
    return {"Y": [m.astype(core.convert_dtype(
        attrs.get("out_dtype", "int64")))]}


@register("sequence_pad", no_grad_slots=("Length",),
          no_grad_out_slots=("Length",))
def _sequence_pad(ctx, ins, attrs):
    """Flat rows [sum(len), D] + lengths -> [B, maxlen, D] (+ Length out).
    attrs: padded_length (static), pad_value."""
    v, lens = x(ins, "X"), x(ins, "Length")
    maxlen = attrs.get("padded_length", -1)
    if maxlen is None or maxlen < 0:
        if isinstance(v, jax.core.Tracer):
            raise ValueError("sequence_pad under jit needs a static "
                             "padded_length attr")
        maxlen = int(jnp.max(lens))
    pad = attrs.get("pad_value", 0.0)
    B = lens.shape[0]
    starts = jnp.cumsum(lens) - lens
    pos = jnp.arange(maxlen)[None, :]                   # [1, T]
    idx = starts[:, None] + pos                          # [B, T]
    valid = pos < lens[:, None]
    idx = jnp.clip(idx, 0, v.shape[0] - 1)
    rows = jnp.take(v, idx.reshape(-1), axis=0).reshape(
        (B, maxlen) + v.shape[1:])
    rows = jnp.where(valid.reshape(B, maxlen, *([1] * (v.ndim - 1))),
                     rows, pad)
    return {"Out": [rows], "Length": [lens]}


@register("sequence_unpad", grad=None, no_grad_slots=("Length",))
def _sequence_unpad(ctx, ins, attrs):
    """[B, T, ...] + lengths -> flat [sum(len), ...]. The output length is
    data-dependent, so this op is eager/host-only (the mask-native design
    keeps jitted graphs padded; unpad only at the host boundary)."""
    v, lens = x(ins, "X"), x(ins, "Length")
    if isinstance(v, jax.core.Tracer) or isinstance(lens, jax.core.Tracer):
        raise ValueError(
            "sequence_unpad has a data-dependent output shape and cannot "
            "run under jit — keep data padded+masked on device and unpad "
            "at the host boundary")
    import numpy as np
    vn, ln = np.asarray(v), np.asarray(lens)
    return out(jnp.asarray(np.concatenate(
        [vn[b, :ln[b]] for b in range(len(ln))], axis=0)))


# ---------------------------------------------------------------------------
# masked reductions / transforms
# ---------------------------------------------------------------------------

def _seq_pool_infer(op):
    v = op.invar("X")
    if v is None or v.shape is None:
        return
    for name in op.output("Out"):
        op.block.create_var(name=name, shape=(v.shape[0],) + tuple(
            v.shape[2:]), dtype=v.dtype)


@register("sequence_pool", infer_shape=_seq_pool_infer,
          no_grad_slots=("Length",),
          attrs={"pooltype": "AVERAGE", "pad_value": 0.0})
def _sequence_pool(ctx, ins, attrs):
    """[B, T, ...] (+ optional Length) -> [B, ...] by SUM/AVERAGE/SQRT/
    MAX/MIN/LAST/FIRST over the valid prefix."""
    v = x(ins, "X")
    lens = x(ins, "Length")
    T = v.shape[1]
    if lens is None:
        lens = jnp.full((v.shape[0],), T, jnp.int32)
    m = _len_mask(lens, T).reshape(v.shape[0], T, *([1] * (v.ndim - 2)))
    pt = attrs.get("pooltype", "AVERAGE").upper()
    denom = jnp.maximum(lens, 1).reshape(-1, *([1] * (v.ndim - 2)))
    if pt == "SUM":
        r = jnp.sum(jnp.where(m, v, 0), axis=1)
    elif pt == "AVERAGE":
        r = jnp.sum(jnp.where(m, v, 0), axis=1) / denom
    elif pt == "SQRT":
        r = jnp.sum(jnp.where(m, v, 0), axis=1) / jnp.sqrt(
            denom.astype(v.dtype))
    elif pt == "MAX":
        r = jnp.max(jnp.where(m, v, -jnp.inf), axis=1)
    elif pt == "MIN":
        r = jnp.min(jnp.where(m, v, jnp.inf), axis=1)
    elif pt == "LAST":
        idx = jnp.maximum(lens - 1, 0)
        r = jnp.take_along_axis(
            v, idx.reshape(-1, 1, *([1] * (v.ndim - 2))), axis=1)[:, 0]
    elif pt == "FIRST":
        r = v[:, 0]
    else:
        raise ValueError(f"unknown pooltype {pt!r}")
    # empty sequences produce pad_value, not ±inf / stale rows (reference
    # sequence_pool_op.cc pad_value semantics)
    empty = (lens == 0).reshape(-1, *([1] * (v.ndim - 2)))
    r = jnp.where(empty, jnp.asarray(attrs.get("pad_value", 0.0), v.dtype),
                  r)
    return out(r)


@register("sequence_softmax", no_grad_slots=("Length",))
def _sequence_softmax(ctx, ins, attrs):
    """Masked softmax over the time dim of [B, T] (or [B, T, ...])."""
    v = x(ins, "X")
    lens = x(ins, "Length")
    T = v.shape[1]
    if lens is None:
        lens = jnp.full((v.shape[0],), T, jnp.int32)
    m = _len_mask(lens, T).reshape(v.shape[0], T, *([1] * (v.ndim - 2)))
    z = jnp.where(m, v, -jnp.inf)
    r = jax.nn.softmax(z, axis=1)
    return out(jnp.where(m, r, 0))


@register("sequence_reverse", infer_shape=same_shape_as("X"),
          no_grad_slots=("Length",))
def _sequence_reverse(ctx, ins, attrs):
    """Reverse each sequence's valid prefix; padding stays in place."""
    v = x(ins, "X")
    lens = x(ins, "Length")
    T = v.shape[1]
    if lens is None:
        return out(v[:, ::-1])
    pos = jnp.arange(T)[None, :]
    idx = jnp.where(pos < lens[:, None], lens[:, None] - 1 - pos, pos)
    return out(jnp.take_along_axis(
        v, idx.reshape(v.shape[0], T, *([1] * (v.ndim - 2))), axis=1))


@register("sequence_expand", grad=None, no_grad_slots=("RefLength",))
def _sequence_expand(ctx, ins, attrs):
    """Repeat row b of X RefLength[b] times (host-only: output length is
    data-dependent — reference sequence_expand_op.cc)."""
    v, ref = x(ins, "X"), x(ins, "RefLength")
    if isinstance(v, jax.core.Tracer) or isinstance(ref, jax.core.Tracer):
        raise ValueError("sequence_expand has a data-dependent output "
                         "shape and cannot run under jit")
    import numpy as np
    return out(jnp.asarray(np.repeat(np.asarray(v), np.asarray(ref),
                                     axis=0)))


# ---------------------------------------------------------------------------
# segment ops (TPU-native replacement for LoD grouping)
# ---------------------------------------------------------------------------

@register("segment_pool", no_grad_slots=("SegmentIds",),
          attrs={"pooltype": "SUM", "num_segments": -1})
def _segment_pool(ctx, ins, attrs):
    """Pool rows of X [N, ...] by SegmentIds [N] into [num_segments, ...]
    (jit-able: num_segments is a static attr)."""
    v, seg = x(ins, "X"), x(ins, "SegmentIds")
    n = attrs.get("num_segments", -1)
    if n is None or n < 0:
        if isinstance(seg, jax.core.Tracer):
            raise ValueError("segment_pool under jit needs a static "
                             "num_segments attr")
        n = int(jnp.max(seg)) + 1
    seg = seg.astype(jnp.int32)
    pt = attrs.get("pooltype", "SUM").upper()
    if pt == "SUM":
        r = jax.ops.segment_sum(v, seg, num_segments=n)
    elif pt == "MEAN":
        s = jax.ops.segment_sum(v, seg, num_segments=n)
        c = jax.ops.segment_sum(jnp.ones((v.shape[0],), v.dtype), seg,
                                num_segments=n)
        r = s / jnp.maximum(c, 1).reshape(-1, *([1] * (v.ndim - 1)))
    elif pt == "MAX":
        r = jax.ops.segment_max(v, seg, num_segments=n)
    elif pt == "MIN":
        r = jax.ops.segment_min(v, seg, num_segments=n)
    else:
        raise ValueError(f"unknown pooltype {pt!r}")
    return out(r)


# ---------------------------------------------------------------------------
# rnn op: lax.scan over time
# ---------------------------------------------------------------------------

def rnn_weight_shapes(mode, input_size, hidden_size, num_layers=1,
                      ndir=1):
    """Shapes of the `rnn` op's WeightList, in slot order — the single
    source of truth consumed by nn.LSTM/GRU/SimpleRNN and
    layers.dynamic_rnn: per (layer, direction) four arrays
    (w_ih [G*H, in], w_hh [G*H, H], b_ih [G*H], b_hh [G*H])."""
    G = {"LSTM": 4, "GRU": 3}.get(mode, 1)
    H = hidden_size
    shapes = []
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else H * ndir
        for _ in range(ndir):
            shapes += [(G * H, in_sz), (G * H, H), (G * H,), (G * H,)]
    return shapes


def _lstm_step(xw, h, c, w_hh, b_hh):
    g = xw + h @ w_hh.T + b_hh
    i, f, gg, o = jnp.split(g, 4, axis=-1)
    c2 = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(gg)
    h2 = jax.nn.sigmoid(o) * jnp.tanh(c2)
    return h2, c2


def _gru_step(xw, h, w_hh, b_hh):
    # gate layout r|z|n (torch convention; self-consistent weights)
    hw = h @ w_hh.T + b_hh
    xr, xz, xn = jnp.split(xw, 3, axis=-1)
    hr, hz, hn = jnp.split(hw, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    n = jnp.tanh(xn + r * hn)
    return (1 - z) * n + z * h


def _rnn_single(v, lens, h0, c0, w_ih, w_hh, b_ih, b_hh, mode, reverse):
    """One direction of one layer. v [B,T,D] -> (out [B,T,H], h_n, c_n)."""
    B, T, _ = v.shape
    if reverse:
        v = _sequence_reverse(None, {"X": [v], "Length": [lens]}, {})[
            "Out"][0]
    # hoist the input projection out of the scan (one big MXU matmul)
    xw = jnp.moveaxis(v @ w_ih.T + b_ih, 1, 0)           # [T, B, G*H]
    mask = (jnp.ones((T, B, 1), bool) if lens is None
            else _len_mask(lens, T).T[..., None])        # [T, B, 1]

    def step(carry, xs):
        h, c = carry
        xt, keep = xs
        if mode == "LSTM":
            h2, c2 = _lstm_step(xt, h, c, w_hh, b_hh)
        elif mode == "GRU":
            h2, c2 = _gru_step(xt, h, w_hh, b_hh), c
        elif mode == "RNN_RELU":
            h2, c2 = jax.nn.relu(xt + h @ w_hh.T + b_hh), c
        else:  # RNN_TANH
            h2, c2 = jnp.tanh(xt + h @ w_hh.T + b_hh), c
        h2 = jnp.where(keep, h2, h)
        c2 = jnp.where(keep, c2, c)
        return (h2, c2), jnp.where(keep, h2, 0)

    (h_n, c_n), ys = jax.lax.scan(step, (h0, c0), (xw, mask))
    outp = jnp.moveaxis(ys, 0, 1)                       # [B, T, H]
    if reverse:
        outp = _sequence_reverse(None, {"X": [outp], "Length": [lens]},
                                 {})["Out"][0]
    return outp, h_n, c_n


def _rnn_infer(op):
    v = op.invar("Input")
    if v is None or v.shape is None:
        return
    H = op.attr("hidden_size", 0)
    L = op.attr("num_layers", 1)
    ndir = 2 if op.attr("is_bidirec", False) else 1
    B, T = v.shape[0], v.shape[1]
    for name in op.output("Out"):
        op.block.create_var(name=name, shape=(B, T, H * ndir),
                            dtype=v.dtype)
    for name in op.output("State"):
        op.block.create_var(name=name, shape=(L * ndir, B, H),
                            dtype=v.dtype)


@register("rnn", infer_shape=_rnn_infer, no_grad_slots=("SequenceLength",),
          stochastic=True,
          attrs={"mode": "LSTM", "hidden_size": 0, "num_layers": 1,
                 "is_bidirec": False, "dropout_prob": 0.0, "is_test": False})
def _rnn(ctx, ins, attrs):
    """Multi-layer (bi)directional recurrent net (reference rnn_op /
    cudnn_lstm): Input [B,T,D], WeightList = per (layer,direction) four
    arrays (w_ih [G*H, in], w_hh [G*H, H], b_ih, b_hh), PreState h0 (+c0)
    each [L*ndir, B, H]."""
    v = x(ins, "Input")
    lens = x(ins, "SequenceLength")
    weights = ins.get("WeightList") or []
    pre = ins.get("PreState") or []
    mode = attrs.get("mode", "LSTM")
    L = attrs.get("num_layers", 1)
    bi = attrs.get("is_bidirec", False)
    ndir = 2 if bi else 1
    p = attrs.get("dropout_prob", 0.0)
    is_test = attrs.get("is_test", False) or (ctx is not None and
                                              ctx.is_test)
    B = v.shape[0]
    H = attrs["hidden_size"] or weights[1].shape[-1]
    h0 = pre[0] if pre else jnp.zeros((L * ndir, B, H), v.dtype)
    c0 = pre[1] if len(pre) > 1 else jnp.zeros_like(h0)

    inp = v
    h_out, c_out = [], []
    for layer in range(L):
        outs = []
        for d in range(ndir):
            k = layer * ndir + d
            w_ih, w_hh, b_ih, b_hh = weights[4 * k: 4 * k + 4]
            o, hn, cn = _rnn_single(inp, lens, h0[k], c0[k], w_ih, w_hh,
                                    b_ih, b_hh, mode, reverse=(d == 1))
            outs.append(o)
            h_out.append(hn)
            c_out.append(cn)
        inp = jnp.concatenate(outs, axis=-1) if bi else outs[0]
        if p and not is_test and layer < L - 1 and ctx is not None:
            key = jax.random.fold_in(ctx.rng(attrs), layer)
            keep = jax.random.bernoulli(key, 1.0 - p, inp.shape)
            inp = jnp.where(keep, inp / (1.0 - p), 0.0)
    state = [jnp.stack(h_out)]
    if mode == "LSTM":
        state.append(jnp.stack(c_out))
    else:
        state.append(jnp.zeros_like(state[0]))
    return {"Out": [inp], "State": state}
