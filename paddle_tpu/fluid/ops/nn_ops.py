"""NN ops: conv, pool, norm, softmax/losses, dropout, embedding.

Replaces reference kernel families:
  operators/conv_op.* + conv_cudnn (algo search)  -> lax.conv_general_dilated
  operators/pool_op.*                             -> lax.reduce_window
  operators/{batch,layer,instance,group}_norm_*   -> jnp (XLA fuses)
  operators/softmax_*, cross_entropy, bce, ...    -> jax.nn
  operators/dropout_op.*                          -> threefry rng via ctx.rng
  operators/lookup_table_v2 (SelectedRows grads)  -> dense take; sharded
                                                     embedding lives in
                                                     paddle_tpu.distributed
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..registry import register, same_shape_as
from .common import x, out


# ---------------------------------------------------------------------------
# convolution
# ---------------------------------------------------------------------------

def _conv_pad(paddings, algorithm, ksize, dilations):
    if algorithm == "SAME":
        return "SAME"
    if algorithm == "VALID":
        return "VALID"
    if len(paddings) == 2:
        return [(paddings[0], paddings[0]), (paddings[1], paddings[1])]
    if len(paddings) == 4:
        return [(paddings[0], paddings[1]), (paddings[2], paddings[3])]
    raise ValueError(f"bad paddings {paddings}")


def _conv2d_infer(op):
    iv, fv = op.invar("Input"), op.invar("Filter")
    if iv is None or iv.shape is None or fv is None or fv.shape is None:
        return
    s = op.attr("strides", [1, 1])
    p = op.attr("paddings", [0, 0])
    d = op.attr("dilations", [1, 1])
    algo = op.attr("padding_algorithm", "EXPLICIT")
    nhwc = op.attr("data_format", "NCHW") == "NHWC"
    if nhwc:
        n, h, w, _ = iv.shape
    else:
        n, _, h, w = iv.shape
    oc, _, kh, kw = fv.shape
    if algo == "SAME":
        oh = -(-h // s[0]) if h > 0 else h
        ow = -(-w // s[1]) if w > 0 else w
    else:
        if algo == "VALID":
            ph0 = ph1 = pw0 = pw1 = 0
        elif len(p) == 2:
            ph0 = ph1 = p[0]; pw0 = pw1 = p[1]
        else:
            ph0, ph1, pw0, pw1 = p
        ekh, ekw = (kh - 1) * d[0] + 1, (kw - 1) * d[1] + 1
        oh = (h + ph0 + ph1 - ekh) // s[0] + 1 if h > 0 else h
        ow = (w + pw0 + pw1 - ekw) // s[1] + 1 if w > 0 else w
    oshape = (n, oh, ow, oc) if nhwc else (n, oc, oh, ow)
    for name in op.output("Output"):
        op.block.create_var(name=name, shape=oshape, dtype=iv.dtype)


def _conv2d(ctx, ins, attrs):
    inp, flt = x(ins, "Input"), x(ins, "Filter")
    strides = attrs.get("strides", [1, 1])
    dilations = attrs.get("dilations", [1, 1])
    pad = _conv_pad(attrs.get("paddings", [0, 0]),
                    attrs.get("padding_algorithm", "EXPLICIT"),
                    flt.shape[2:], dilations)
    # no preferred_element_type: the MXU accumulates bf16 convs in f32 by
    # hardware, and jax's conv transpose rule can't mix a f32 cotangent
    # with bf16 operands (broke amp O1 ResNet backward)
    # data_format=NHWC keeps the activation channel minor — the layout the
    # TPU conv expects — so XLA inserts no transposes (the ResNet-50 NCHW
    # path measured 8.5% MFU from exactly those transposes). Filter stays
    # OIHW at the API (reference filter layout) and is permuted to HWIO
    # here; weights are tiny next to activations.
    nhwc = attrs.get("data_format", "NCHW") == "NHWC"
    if nhwc:
        dn = ("NHWC", "HWIO", "NHWC")
        flt = jnp.transpose(flt, (2, 3, 1, 0))
    else:
        dn = ("NCHW", "OIHW", "NCHW")
    r = jax.lax.conv_general_dilated(
        inp, flt, window_strides=strides, padding=pad,
        rhs_dilation=dilations,
        dimension_numbers=dn,
        feature_group_count=attrs.get("groups", 1) or 1)
    return {"Output": [r]}


register("conv2d", _conv2d, infer_shape=_conv2d_infer,
         attrs={"strides": [1, 1], "paddings": [0, 0], "dilations": [1, 1],
                "groups": 1, "padding_algorithm": "EXPLICIT",
                "data_format": "NCHW", "use_cudnn": False})
register("depthwise_conv2d", _conv2d, infer_shape=_conv2d_infer,
         attrs={"strides": [1, 1], "paddings": [0, 0], "dilations": [1, 1],
                "groups": 1, "padding_algorithm": "EXPLICIT",
                "data_format": "NCHW", "use_cudnn": False})


def _conv2d_transpose(ctx, ins, attrs):
    """Gradient-style transpose conv as one conv_general_dilated with
    lhs_dilation = stride (supports groups + output_padding, which
    jax.lax.conv_transpose does not). Reference
    operators/conv_transpose_op semantics:
    out = (i-1)*s + k_eff - 2p + output_padding."""
    inp, flt = x(ins, "Input"), x(ins, "Filter")
    strides = attrs.get("strides", [1, 1])
    dil = attrs.get("dilations", [1, 1])
    g = attrs.get("groups", 1) or 1
    out_pad = attrs.get("output_padding") or [0, 0]
    if not out_pad:
        out_pad = [0, 0]
    p = attrs.get("paddings", [0, 0])
    pads = _conv_pad(p, attrs.get("padding_algorithm", "EXPLICIT"),
                     flt.shape[2:], dil)
    in_c, opg, kh, kw = flt.shape
    k_eff = [dil[0] * (kh - 1) + 1, dil[1] * (kw - 1) + 1]
    if isinstance(pads, str):
        if pads == "VALID":
            pads = [(0, 0), (0, 0)]
        else:  # SAME: out = i*s  =>  total crop = k_eff - s
            pads = [((k_eff[i] - strides[i]) // 2,
                     k_eff[i] - strides[i] - (k_eff[i] - strides[i]) // 2)
                    for i in (0, 1)]
    # paddle pad crops the full transpose output; in dilated-input conv
    # terms the edge padding is k_eff-1-p (+output_padding on the high
    # side)
    jpads = [(k_eff[i] - 1 - lo, k_eff[i] - 1 - hi + out_pad[i])
             for i, (lo, hi) in enumerate(pads)]
    # filter (in, out/g, kh, kw) -> grouped-OIHW (out, in/g, kh, kw),
    # spatially flipped (the transpose of the forward conv's kernel)
    w = flt.reshape(g, in_c // g, opg, kh, kw)
    w = jnp.swapaxes(w, 1, 2).reshape(g * opg, in_c // g, kh, kw)
    w = w[:, :, ::-1, ::-1]
    if attrs.get("data_format", "NCHW") == "NHWC":
        dn = ("NHWC", "HWIO", "NHWC")
        w = jnp.transpose(w, (2, 3, 1, 0))
    else:
        dn = ("NCHW", "OIHW", "NCHW")
    r = jax.lax.conv_general_dilated(
        inp, w, window_strides=(1, 1), padding=jpads,
        lhs_dilation=strides, rhs_dilation=dil,
        dimension_numbers=dn,
        feature_group_count=g)
    return {"Output": [r]}


register("conv2d_transpose", _conv2d_transpose,
         attrs={"strides": [1, 1], "paddings": [0, 0], "dilations": [1, 1],
                "groups": 1, "padding_algorithm": "EXPLICIT",
                "output_padding": [], "data_format": "NCHW",
                "output_size": [], "use_cudnn": False})


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------

def _pool2d_infer(op):
    v = op.invar("X")
    if v is None or v.shape is None:
        return
    nhwc = op.attr("data_format", "NCHW") == "NHWC"
    if nhwc:
        n, h, w, c = v.shape
    else:
        n, c, h, w = v.shape
    if op.attr("global_pooling", False) or op.attr("adaptive", False) and \
            list(op.attr("ksize", [1, 1])) == [1, 1]:
        oh = ow = 1
    elif op.attr("adaptive", False):
        oh, ow = op.attr("ksize")
    else:
        k = op.attr("ksize", [2, 2]); s = op.attr("strides", [2, 2])
        p = op.attr("paddings", [0, 0])
        if op.attr("ceil_mode", False):
            oh = -(-(h + 2 * p[0] - k[0]) // s[0]) + 1 if h > 0 else h
            ow = -(-(w + 2 * p[1] - k[1]) // s[1]) + 1 if w > 0 else w
        else:
            oh = (h + 2 * p[0] - k[0]) // s[0] + 1 if h > 0 else h
            ow = (w + 2 * p[1] - k[1]) // s[1] + 1 if w > 0 else w
    oshape = (n, oh, ow, c) if nhwc else (n, c, oh, ow)
    for name in op.output("Out"):
        op.block.create_var(name=name, shape=oshape, dtype=v.dtype)


@register("pool2d", infer_shape=_pool2d_infer,
          attrs={"pooling_type": "max", "ksize": [2, 2], "strides": [2, 2],
                 "paddings": [0, 0], "global_pooling": False,
                 "ceil_mode": False, "exclusive": True, "adaptive": False,
                 "data_format": "NCHW", "use_cudnn": False})
def _pool2d(ctx, ins, attrs):
    v = x(ins)
    ptype = attrs["pooling_type"]
    nhwc = attrs.get("data_format", "NCHW") == "NHWC"
    sp = (1, 2) if nhwc else (2, 3)  # spatial axes
    if attrs.get("global_pooling") or (attrs.get("adaptive") and
                                       list(attrs["ksize"]) == [1, 1]):
        fn = jnp.max if ptype == "max" else jnp.mean
        return out(fn(v, axis=sp, keepdims=True))
    if attrs.get("adaptive"):
        oh, ow = attrs["ksize"]
        h, w = v.shape[sp[0]], v.shape[sp[1]]
        if h % oh == 0 and w % ow == 0:
            fn = jnp.max if ptype == "max" else jnp.mean
            if nhwc:
                r = v.reshape(v.shape[0], oh, h // oh, ow, w // ow,
                              v.shape[3])
                return out(fn(r, axis=(2, 4)))
            r = v.reshape(v.shape[0], v.shape[1], oh, h // oh, ow, w // ow)
            return out(fn(r, axis=(3, 5)))
        if nhwc:
            # rare non-divisible adaptive bins: reuse the NCHW bin-matrix
            # path through one transpose pair
            sub = dict(attrs, data_format="NCHW")
            r = _pool2d(ctx, {"X": [jnp.transpose(v, (0, 3, 1, 2))]},
                        sub)["Out"][0]
            return out(jnp.transpose(r, (0, 2, 3, 1)))
        # non-divisible bins (torch semantics: bin i spans
        # [floor(i*n/o), ceil((i+1)*n/o)) ) via static per-axis bin
        # matrices — one einsum per axis, fully differentiable
        import numpy as _np

        def bins(n, o):
            m = _np.zeros((o, n), _np.float32)
            for i in range(o):
                lo, hi = (i * n) // o, -((-(i + 1) * n) // o)
                m[i, lo:hi] = 1.0
            return m

        bh, bw = bins(h, oh), bins(w, ow)
        if ptype == "max":
            big = jnp.finfo(v.dtype).min if jnp.issubdtype(
                v.dtype, jnp.floating) else jnp.iinfo(v.dtype).min
            mh = jnp.asarray(bh) > 0  # [oh, H]
            mw = jnp.asarray(bw) > 0  # [ow, W]
            r = jnp.max(jnp.where(mh[None, None, :, :, None],
                                  v[:, :, None, :, :], big), axis=3)
            r = jnp.max(jnp.where(mw[None, None, None, :, :],
                                  r[:, :, :, None, :], big), axis=4)
            return out(r)
        wh = jnp.asarray(bh / bh.sum(1, keepdims=True))
        ww = jnp.asarray(bw / bw.sum(1, keepdims=True))
        r = jnp.einsum("nchw,oh,pw->ncop", v, wh, ww)
        return out(r.astype(v.dtype))
    k = list(attrs["ksize"]); s = list(attrs["strides"])
    p = list(attrs["paddings"])
    if nhwc:
        dims = (1, k[0], k[1], 1)
        strides = (1, s[0], s[1], 1)
        pads = ((0, 0), (p[0], p[0]), (p[1], p[1]), (0, 0))
    else:
        dims = (1, 1, k[0], k[1])
        strides = (1, 1, s[0], s[1])
        pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(v.dtype, jnp.floating) \
            else jnp.iinfo(v.dtype).min
        r = jax.lax.reduce_window(v, init, jax.lax.max, dims, strides, pads)
    else:
        ssum = jax.lax.reduce_window(v, 0.0, jax.lax.add, dims, strides, pads)
        if attrs.get("exclusive", True) and (p[0] or p[1]):
            ones = jnp.ones_like(v)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims, strides,
                                        pads)
            r = ssum / cnt
        else:
            r = ssum / (k[0] * k[1])
    return out(r)


# ---------------------------------------------------------------------------
# normalisation
# ---------------------------------------------------------------------------

def _bn_infer(op):
    v = op.invar("X")
    if v is None:
        return
    for name in op.output("Y"):
        op.block.create_var(name=name, shape=v.shape, dtype=v.dtype)
    sv = op.invar("Scale")
    cshape = sv.shape if sv is not None else None
    for slot in ("MeanOut", "VarianceOut", "SavedMean", "SavedVariance"):
        for name in op.output(slot):
            op.block.create_var(name=name, shape=cshape, dtype="float32")


def _bn_train_impl(v, scale, bias, shift, eps, caxis):
    axes = tuple(i for i in range(v.ndim) if i != caxis)
    n = float(np.prod([v.shape[i] for i in axes]))
    f32 = jnp.float32
    bshape = [1] * v.ndim
    bshape[caxis] = v.shape[caxis]
    # single-pass statistics, shifted by the running mean: the raw
    # E[x^2]-E[x]^2 form cancels catastrophically in f32 when |mean| >>
    # std; with the shift (which converges to the batch mean) the centered
    # moments stay accurate while x is still read only once
    sh = shift.astype(f32).reshape(bshape)
    vc = v.astype(f32) - sh
    s = jnp.sum(vc, axis=axes)
    ss = jnp.sum(jnp.square(vc), axis=axes)
    d = s / n
    mean = d + shift.astype(f32)
    var = jnp.maximum(ss / n - jnp.square(d), 0.0)
    inv = jax.lax.rsqrt(var + eps)
    se = inv * scale.astype(f32)
    be = bias.astype(f32) - mean * se
    y = (v.astype(f32) * se.reshape(bshape) +
         be.reshape(bshape)).astype(v.dtype)
    return y, mean, var, inv


@partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _bn_train(v, scale, bias, shift, eps, caxis):
    """Training batch norm with a hand-derived VJP.

    jax's autodiff of the naive mean/var formulation materialises
    activation-sized f32 intermediates in backward (the broadcast
    cotangents of the reductions) — on a ResNet-50 step that was ~2/3 of
    the HBM traffic and pinned the conv path at ~10% MFU. The fused
    formulas keep every activation-sized pass in the input dtype; only
    per-channel vectors are f32 (reference batch_norm_op.cu uses the same
    dbias/dscale/dx fusion). `shift` is a statistics-shift (the running
    mean); it is mathematically inert and carries zero gradient."""
    return _bn_train_impl(v, scale, bias, shift, eps, caxis)


def _bn_train_fwd(v, scale, bias, shift, eps, caxis):
    y, mean, var, inv = _bn_train_impl(v, scale, bias, shift, eps, caxis)
    return (y, mean, var, inv), (v, scale, mean, inv)


def _bn_train_bwd(eps, caxis, res, cts):
    dy = cts[0]  # stats outputs are non-differentiable (running buffers)
    v, scale, mean, inv = res
    f32 = jnp.float32
    axes = tuple(i for i in range(v.ndim) if i != caxis)
    n = float(np.prod([v.shape[i] for i in axes]))
    bshape = [1] * v.ndim
    bshape[caxis] = v.shape[caxis]
    dyf = dy.astype(f32)
    xhat = (v.astype(f32) - mean.reshape(bshape)) * inv.reshape(bshape)
    dbias = jnp.sum(dyf, axis=axes)
    dscale = jnp.sum(dyf * xhat, axis=axes)
    k = (inv * scale.astype(f32)).reshape(bshape)
    dx = (k * (dyf - (dbias / n).reshape(bshape)
               - xhat * (dscale / n).reshape(bshape))).astype(v.dtype)
    return (dx, dscale.astype(scale.dtype), dbias.astype(scale.dtype),
            jnp.zeros_like(mean))


_bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)


@register("batch_norm", infer_shape=_bn_infer,
          attrs={"momentum": 0.9, "epsilon": 1e-5, "is_test": False,
                 "data_layout": "NCHW", "use_global_stats": False,
                 "trainable_statistics": False},
          no_grad_out_slots=("MeanOut", "VarianceOut", "SavedMean",
                             "SavedVariance", "ReserveSpace"))
def _batch_norm(ctx, ins, attrs):
    v = x(ins)
    scale, bias = x(ins, "Scale"), x(ins, "Bias")
    mean, var = x(ins, "Mean"), x(ins, "Variance")
    layout = attrs.get("data_layout", "NCHW")
    caxis = 1 if layout == "NCHW" else v.ndim - 1
    axes = tuple(i for i in range(v.ndim) if i != caxis)
    bshape = [1] * v.ndim
    bshape[caxis] = v.shape[caxis]
    is_test = attrs.get("is_test", False) or ctx.is_test
    use_global = attrs.get("use_global_stats", False) or is_test
    eps = attrs["epsilon"]
    m = attrs["momentum"]
    if use_global:
        bm, bv = mean, var
        mean_out, var_out = mean, var
        inv = jax.lax.rsqrt(bv.astype(jnp.float32) + eps)
        y = (v - bm.reshape(bshape).astype(v.dtype)) * \
            (inv.reshape(bshape) * scale.reshape(bshape)).astype(v.dtype) + \
            bias.reshape(bshape).astype(v.dtype)
        return {"Y": [y], "MeanOut": [mean_out], "VarianceOut": [var_out],
                "SavedMean": [bm], "SavedVariance": [inv]}
    y, bm, bv, inv = _bn_train(v, scale, bias,
                               jax.lax.stop_gradient(mean), eps, caxis)
    mean_out = m * mean + (1 - m) * bm
    var_out = m * var + (1 - m) * bv
    return {"Y": [y], "MeanOut": [mean_out], "VarianceOut": [var_out],
            "SavedMean": [bm], "SavedVariance": [inv]}


def _ln_infer(op):
    v = op.invar("X")
    if v is None:
        return
    for name in op.output("Y"):
        op.block.create_var(name=name, shape=v.shape, dtype=v.dtype)
    if v.shape is not None:
        ax = op.attr("begin_norm_axis", 1)
        rows = int(np.prod([s for s in v.shape[:ax]])) \
            if all(s >= 0 for s in v.shape[:ax]) else -1
        for slot in ("Mean", "Variance"):
            for name in op.output(slot):
                op.block.create_var(name=name, shape=(rows,), dtype="float32")


@register("layer_norm", infer_shape=_ln_infer,
          attrs={"epsilon": 1e-5, "begin_norm_axis": 1},
          no_grad_out_slots=("Mean", "Variance"))
def _layer_norm(ctx, ins, attrs):
    v = x(ins)
    scale, bias = x(ins, "Scale"), x(ins, "Bias")
    ax = attrs.get("begin_norm_axis", 1)

    # Pallas fused single-pass kernel on TPU (paddle_tpu/ops/pallas_layer_norm)
    from ...ops.pallas_layer_norm import (can_use_fused_ln,
                                          fused_layer_norm, ln_wins)
    rows = int(np.prod(v.shape[:ax])) if v.ndim > ax else 1
    cols = int(np.prod(v.shape[ax:]))
    if can_use_fused_ln(rows, cols, scale is not None, bias is not None) \
            and ln_wins(rows, cols, v.dtype, attrs["epsilon"]):
        y2, mean, rstd = fused_layer_norm(
            v.reshape(rows, cols), scale.reshape(cols), bias.reshape(cols),
            attrs["epsilon"])
        var = 1.0 / jnp.square(rstd) - attrs["epsilon"]
        return {"Y": [y2.reshape(v.shape)], "Mean": [mean],
                "Variance": [var]}

    axes = tuple(range(ax, v.ndim))
    fp = v.astype(jnp.float32)
    mean = jnp.mean(fp, axis=axes, keepdims=True)
    var = jnp.var(fp, axis=axes, keepdims=True)
    y = (fp - mean) * jax.lax.rsqrt(var + attrs["epsilon"])
    feat = v.shape[ax:]
    if scale is not None:
        y = y * scale.reshape(feat).astype(jnp.float32)
    if bias is not None:
        y = y + bias.reshape(feat).astype(jnp.float32)
    rows = int(np.prod(v.shape[:ax])) if v.ndim > ax else 1
    return {"Y": [y.astype(v.dtype)], "Mean": [mean.reshape(rows)],
            "Variance": [var.reshape(rows)]}


@register("instance_norm", attrs={"epsilon": 1e-5},
          no_grad_out_slots=("SavedMean", "SavedVariance"))
def _instance_norm(ctx, ins, attrs):
    v = x(ins)
    scale, bias = x(ins, "Scale"), x(ins, "Bias")
    axes = tuple(range(2, v.ndim))
    mean = jnp.mean(v, axis=axes, keepdims=True)
    var = jnp.var(v, axis=axes, keepdims=True)
    y = (v - mean) * jax.lax.rsqrt(var + attrs["epsilon"])
    cshape = (1, -1) + (1,) * (v.ndim - 2)
    if scale is not None:
        y = y * scale.reshape(cshape)
    if bias is not None:
        y = y + bias.reshape(cshape)
    return {"Y": [y], "SavedMean": [mean.reshape(-1)],
            "SavedVariance": [var.reshape(-1)]}


@register("group_norm", attrs={"epsilon": 1e-5, "groups": 1,
                               "data_layout": "NCHW"},
          no_grad_out_slots=("Mean", "Variance"))
def _group_norm(ctx, ins, attrs):
    v = x(ins)
    scale, bias = x(ins, "Scale"), x(ins, "Bias")
    g = attrs["groups"]
    n, c = v.shape[0], v.shape[1]
    rest = v.shape[2:]
    r = v.reshape((n, g, c // g) + rest)
    axes = tuple(range(2, r.ndim))
    mean = jnp.mean(r, axis=axes, keepdims=True)
    var = jnp.var(r, axis=axes, keepdims=True)
    y = ((r - mean) * jax.lax.rsqrt(var + attrs["epsilon"])).reshape(v.shape)
    cshape = (1, c) + (1,) * (v.ndim - 2)
    if scale is not None:
        y = y * scale.reshape(cshape)
    if bias is not None:
        y = y + bias.reshape(cshape)
    return {"Y": [y], "Mean": [mean.reshape(n, g)],
            "Variance": [var.reshape(n, g)]}


# ---------------------------------------------------------------------------
# softmax & losses
# ---------------------------------------------------------------------------

@register("softmax", infer_shape=same_shape_as("X"),
          attrs={"axis": -1, "use_cudnn": False})
def _softmax(ctx, ins, attrs):
    return out(jax.nn.softmax(x(ins), axis=attrs["axis"]))


@register("log_softmax", infer_shape=same_shape_as("X"), attrs={"axis": -1})
def _log_softmax(ctx, ins, attrs):
    return out(jax.nn.log_softmax(x(ins), axis=attrs["axis"]))


def _xent_infer(op):
    v = op.invar("X") or op.invar("Logits")
    if v is None or v.shape is None:
        return
    shape = tuple(list(v.shape[:-1]) + [1])
    for name in op.output("Y") + op.output("Loss"):
        op.block.create_var(name=name, shape=shape, dtype=v.dtype)
    for name in op.output("Softmax"):
        op.block.create_var(name=name, shape=v.shape, dtype=v.dtype)


@register("cross_entropy", infer_shape=_xent_infer,
          attrs={"soft_label": False, "ignore_index": -100},
          no_grad_slots=("Label",))
def _cross_entropy(ctx, ins, attrs):
    probs, label = x(ins, "X"), x(ins, "Label")
    logp = jnp.log(jnp.clip(probs, 1e-20, None))
    if attrs.get("soft_label"):
        loss = -jnp.sum(label * logp, axis=-1, keepdims=True)
    else:
        lab = label.reshape(label.shape[:-1]) if label.shape[-1] == 1 \
            else label
        picked = jnp.take_along_axis(logp, lab[..., None].astype(jnp.int32),
                                     axis=-1)
        loss = -picked
        ii = attrs.get("ignore_index", -100)
        loss = jnp.where(lab[..., None] == ii, 0.0, loss)
    return {"Y": [loss]}


@register("softmax_with_cross_entropy", infer_shape=_xent_infer,
          attrs={"soft_label": False, "ignore_index": -100, "axis": -1,
                 "numeric_stable_mode": True},
          no_grad_slots=("Label",), no_grad_out_slots=("Softmax",))
def _softmax_xent(ctx, ins, attrs):
    logits, label = x(ins, "Logits"), x(ins, "Label")
    axis = attrs.get("axis", -1)
    logp = jax.nn.log_softmax(logits, axis=axis)
    sm = jnp.exp(logp)
    if attrs.get("soft_label"):
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        lab = label.reshape(label.shape[:-1]) if label.shape[-1] == 1 \
            else label
        picked = jnp.take_along_axis(logp, lab[..., None].astype(jnp.int32),
                                     axis=axis)
        loss = -picked
        ii = attrs.get("ignore_index", -100)
        loss = jnp.where(lab[..., None] == ii, 0.0, loss)
    return {"Loss": [loss], "Softmax": [sm]}


@register("mse_loss", infer_shape=same_shape_as("X"))
def _mse(ctx, ins, attrs):
    d = x(ins, "X") - x(ins, "Y")
    return out(jnp.square(d))


@register("bce_loss", infer_shape=same_shape_as("X"),
          no_grad_slots=("Label",))
def _bce(ctx, ins, attrs):
    p, lab = x(ins, "X"), x(ins, "Label")
    p = jnp.clip(p, 1e-12, 1 - 1e-12)
    return out(-(lab * jnp.log(p) + (1 - lab) * jnp.log1p(-p)))


@register("sigmoid_cross_entropy_with_logits",
          infer_shape=same_shape_as("X"),
          attrs={"ignore_index": -100, "normalize": False},
          no_grad_slots=("Label",))
def _sce_logits(ctx, ins, attrs):
    z, lab = x(ins, "X"), x(ins, "Label")
    loss = jnp.maximum(z, 0) - z * lab + jnp.log1p(jnp.exp(-jnp.abs(z)))
    ii = attrs.get("ignore_index", -100)
    loss = jnp.where(lab == ii, 0.0, loss)
    if attrs.get("normalize"):
        denom = jnp.maximum(jnp.sum((lab != ii).astype(loss.dtype)), 1.0)
        loss = loss / denom
    return out(loss)


@register("huber_loss", attrs={"delta": 1.0}, no_grad_slots=("Y",),
          infer_shape=same_shape_as("X", "Out"),
          no_grad_out_slots=("Residual",))
def _huber(ctx, ins, attrs):
    pred, lab = x(ins, "X"), x(ins, "Y")
    d = attrs["delta"]
    r = lab - pred
    a = jnp.abs(r)
    loss = jnp.where(a <= d, 0.5 * r * r, d * (a - 0.5 * d))
    return {"Out": [loss], "Residual": [r]}


@register("kldiv_loss", attrs={"reduction": "mean"}, no_grad_slots=("Target",))
def _kldiv(ctx, ins, attrs):
    logp, target = x(ins, "X"), x(ins, "Target")
    loss = target * (jnp.log(jnp.clip(target, 1e-20, None)) - logp)
    red = attrs.get("reduction", "mean")
    if red == "mean":
        return out(jnp.mean(loss).reshape((1,)))
    if red == "sum":
        return out(jnp.sum(loss).reshape((1,)))
    if red == "batchmean":
        return out((jnp.sum(loss) / loss.shape[0]).reshape((1,)))
    return out(loss)


@register("nll_loss", attrs={"reduction": "mean", "ignore_index": -100},
          no_grad_slots=("Label",), no_grad_out_slots=("Total_weight",))
def _nll(ctx, ins, attrs):
    logp, lab = x(ins, "X"), x(ins, "Label")
    w = x(ins, "Weight")
    picked = jnp.take_along_axis(logp, lab[:, None].astype(jnp.int32),
                                 axis=1)[:, 0]
    wt = w[lab] if w is not None else jnp.ones_like(picked)
    loss = -picked * wt
    red = attrs.get("reduction", "mean")
    tot = jnp.sum(wt)
    if red == "mean":
        return {"Out": [(jnp.sum(loss) / tot).reshape((1,))],
                "Total_weight": [tot.reshape((1,))]}
    if red == "sum":
        return {"Out": [jnp.sum(loss).reshape((1,))],
                "Total_weight": [tot.reshape((1,))]}
    return {"Out": [loss], "Total_weight": [tot.reshape((1,))]}


@register("smooth_l1_loss", no_grad_slots=("Y",),
          no_grad_out_slots=("Diff",), attrs={"sigma": 1.0})
def _smooth_l1(ctx, ins, attrs):
    pred, lab = x(ins, "X"), x(ins, "Y")
    sigma2 = attrs["sigma"] ** 2
    d = pred - lab
    a = jnp.abs(d)
    loss = jnp.where(a < 1.0 / sigma2, 0.5 * d * d * sigma2, a - 0.5 / sigma2)
    return {"Out": [jnp.sum(loss, axis=tuple(range(1, loss.ndim)),
                            keepdims=False)[..., None]], "Diff": [d]}


@register("squared_error_cost", infer_shape=same_shape_as("X"),
          no_grad_slots=("Y",))
def _squared_error(ctx, ins, attrs):
    d = x(ins, "X") - x(ins, "Y")
    return out(jnp.square(d))


# ---------------------------------------------------------------------------
# dropout (stochastic — stable per-op rng stream via ctx.rng)
# ---------------------------------------------------------------------------

@register("dropout", infer_shape=same_shape_as("X"), stochastic=True,
          attrs={"dropout_prob": 0.5, "is_test": False, "fix_seed": False,
                 "seed": 0, "dropout_implementation": "downgrade_in_infer"},
          no_grad_out_slots=("Mask",))
def _dropout(ctx, ins, attrs):
    v = x(ins)
    p = attrs["dropout_prob"]
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    is_test = attrs.get("is_test", False) or ctx.is_test
    if is_test:
        y = v * (1.0 - p) if impl == "downgrade_in_infer" else v
        return {"Out": [y], "Mask": [None]}
    if p >= 1.0:
        return {"Out": [jnp.zeros_like(v)], "Mask": [jnp.zeros_like(v)]}
    key = ctx.rng(attrs)
    mask = jax.random.bernoulli(key, 1.0 - p, v.shape)
    if impl == "upscale_in_train":
        y = jnp.where(mask, v / (1.0 - p), 0.0)
    else:
        y = jnp.where(mask, v, 0.0)
    return {"Out": [y], "Mask": [mask.astype(jnp.uint8)]}


# ---------------------------------------------------------------------------
# embedding
# ---------------------------------------------------------------------------

def _embed_infer(op):
    ids, w = op.invar("Ids"), op.invar("W")
    if ids is None or w is None or ids.shape is None or w.shape is None:
        return
    idshape = ids.shape
    if op.type == "lookup_table" and idshape and idshape[-1] == 1:
        idshape = idshape[:-1]
    for name in op.output("Out"):
        op.block.create_var(name=name, shape=tuple(idshape) + (w.shape[-1],),
                            dtype=w.dtype)


def _lookup(ctx, ins, attrs, squeeze_last):
    ids, w = x(ins, "Ids"), x(ins, "W")
    if squeeze_last and ids.shape and ids.shape[-1] == 1:
        ids = ids.reshape(ids.shape[:-1])
    pad = attrs.get("padding_idx", -1)
    r = jnp.take(w, ids.astype(jnp.int32), axis=0)
    if pad is not None and pad != -1:
        r = jnp.where((ids == pad)[..., None], 0.0, r)
    return out(r)


register("lookup_table_v2",
         lambda ctx, ins, attrs: _lookup(ctx, ins, attrs, False),
         infer_shape=_embed_infer, no_grad_slots=("Ids",),
         attrs={"padding_idx": -1, "is_sparse": False, "is_distributed": False})
register("lookup_table",
         lambda ctx, ins, attrs: _lookup(ctx, ins, attrs, True),
         infer_shape=_embed_infer, no_grad_slots=("Ids",),
         attrs={"padding_idx": -1, "is_sparse": False, "is_distributed": False})


def _lookup_grad(ctx, ins, attrs, squeeze_last):
    """W@GRAD: SelectedRows when is_sparse (reference
    operators/lookup_table_v2_op.cc grad kernel emits SelectedRows), else
    dense scatter-add."""
    from ..selected_rows import SelectedRows
    ids, w = x(ins, "Ids"), x(ins, "W")
    og = x(ins, "Out@GRAD")
    if squeeze_last and ids.shape and ids.shape[-1] == 1:
        ids = ids.reshape(ids.shape[:-1])
    rows = ids.astype(jnp.int32).reshape(-1)
    vals = og.reshape(-1, og.shape[-1]).astype(jnp.float32)
    pad = attrs.get("padding_idx", -1)
    if pad is not None and pad != -1:
        vals = jnp.where((rows == pad)[:, None], 0.0, vals)
    if attrs.get("is_sparse"):
        return {"W@GRAD": [SelectedRows(rows, vals, w.shape[0])]}
    dense = jnp.zeros(w.shape, vals.dtype).at[rows].add(vals)
    return {"W@GRAD": [dense.astype(w.dtype)]}


register("lookup_table_v2_grad",
         lambda ctx, ins, attrs: _lookup_grad(ctx, ins, attrs, False),
         grad=None, no_grad_slots=("Ids", "W", "Out@GRAD"))
register("lookup_table_grad",
         lambda ctx, ins, attrs: _lookup_grad(ctx, ins, attrs, True),
         grad=None, no_grad_slots=("Ids", "W", "Out@GRAD"))


@register("one_hot_v2", grad=None, attrs={"depth": -1, "dtype": "float32",
                                          "allow_out_of_range": False})
def _one_hot(ctx, ins, attrs):
    ids = x(ins)
    return out(jax.nn.one_hot(ids.astype(jnp.int32), attrs["depth"],
                              dtype=jnp.dtype(attrs.get("dtype", "float32"))))


register("one_hot", lambda ctx, ins, attrs: _one_hot(ctx, ins, attrs),
         grad=None, attrs={"depth": -1, "dtype": "float32",
                           "allow_out_of_range": False})


# ---------------------------------------------------------------------------
# misc nn
# ---------------------------------------------------------------------------

@register("label_smooth", attrs={"epsilon": 0.1})
def _label_smooth(ctx, ins, attrs):
    lab = x(ins)
    eps = attrs["epsilon"]
    prior = x(ins, "PriorDist")
    k = lab.shape[-1]
    if prior is None:
        return out((1 - eps) * lab + eps / k)
    return out((1 - eps) * lab + eps * prior)


@register("pad", attrs={"paddings": [], "pad_value": 0.0})
def _pad(ctx, ins, attrs):
    v = x(ins)
    p = attrs["paddings"]
    cfg = [(p[2 * i], p[2 * i + 1]) for i in range(v.ndim)]
    return out(jnp.pad(v, cfg, constant_values=attrs.get("pad_value", 0.0)))


@register("pad2d", attrs={"paddings": [0, 0, 0, 0], "mode": "constant",
                          "pad_value": 0.0, "data_format": "NCHW"})
def _pad2d(ctx, ins, attrs):
    v = x(ins)
    p = attrs["paddings"]
    mode = {"constant": "constant", "reflect": "reflect",
            "edge": "edge"}[attrs.get("mode", "constant")]
    cfg = ((0, 0), (0, 0), (p[0], p[1]), (p[2], p[3]))
    if mode == "constant":
        return out(jnp.pad(v, cfg, constant_values=attrs.get("pad_value", 0.0)))
    return out(jnp.pad(v, cfg, mode=mode))


@register("interp_nearest", grad="auto",
          attrs={"out_h": -1, "out_w": -1, "scale": 0.0,
                 "data_layout": "NCHW", "align_corners": False})
def _interp_nearest(ctx, ins, attrs):
    v = x(ins)
    oh, ow = attrs["out_h"], attrs["out_w"]
    if oh <= 0:
        oh = int(v.shape[2] * attrs["scale"])
        ow = int(v.shape[3] * attrs["scale"])
    return out(jax.image.resize(v, v.shape[:2] + (oh, ow), method="nearest"))


# ---------------------------------------------------------------------------
# quantization simulation ops (reference operators/fake_quantize_op.cc;
# used by the slim post-training pass — SURVEY §2.6 contrib slim)
# ---------------------------------------------------------------------------

def _fq_scale(ins, attrs, v):
    """Calibrated scale: InScale tensor (reference op layout) beats the
    scale attr; 0/absent falls back to per-batch abs_max."""
    in_scale = x(ins, "InScale")
    if in_scale is not None:
        return in_scale.reshape(())
    scale = attrs.get("scale", 0.0)
    if scale:
        return jnp.asarray(scale, jnp.float32)
    return jnp.maximum(jnp.max(jnp.abs(v)), 1e-8)


def _fake_quant_dequant(ctx, ins, attrs):
    v = x(ins)
    bits = attrs.get("bit_length", 8)
    qmax = float(2 ** (bits - 1) - 1)
    scale = _fq_scale(ins, attrs, v)
    q = jnp.clip(jnp.round(v / scale * qmax), -qmax, qmax)
    return {"Out": [(q * scale / qmax).astype(v.dtype)],
            "OutScale": [scale.reshape((1,))]}


def _fake_quant_grad(ctx, ins, attrs):
    """Straight-through estimator (reference fake_quantize grad):
    gradient passes through where |x| <= scale, zero where clipped."""
    v, og = x(ins, "X"), x(ins, "Out@GRAD")
    scale = _fq_scale(ins, attrs, v)
    return {"X@GRAD": [jnp.where(jnp.abs(v) <= scale, og, 0.0)
                       .astype(og.dtype)]}


for _fq_name in ("fake_quantize_dequantize_abs_max",
                 "fake_quantize_dequantize_moving_average_abs_max"):
    register(_fq_name, _fake_quant_dequant,
             infer_shape=same_shape_as("X"),
             no_grad_slots=("InScale",), no_grad_out_slots=("OutScale",),
             attrs={"scale": 0.0, "bit_length": 8, "moving_rate": 0.9})
    register(_fq_name + "_grad", _fake_quant_grad, grad=None,
             no_grad_slots=("X", "InScale", "Out@GRAD"))


# ---------------------------------------------------------------------------
# Mixture-of-Experts fused feed-forward (parallel/moe.py kernel; the
# reference's strategy bag ships the expert_parallel flag with no op tier —
# SURVEY §2.9 mandates the fresh EP design). Grad is auto-vjp.
# ---------------------------------------------------------------------------

def _moe_infer(op):
    v = op.invar("X")
    if v is not None:
        for name in op.output("Out"):
            op.block.create_var(name=name, shape=v.shape, dtype=v.dtype)
    for name in op.output("AuxLoss"):
        op.block.create_var(name=name, shape=(1,), dtype="float32")


@register("moe_ffn", infer_shape=_moe_infer,
          attrs={"top_k": 1, "capacity_factor": 1.25})
def _moe_ffn(ctx, ins, attrs):
    from ...parallel.moe import moe_ffn
    y, aux = moe_ffn(
        x(ins, "X"), x(ins, "Gate"), x(ins, "WUp"), x(ins, "BUp"),
        x(ins, "WDown"), x(ins, "BDown"),
        capacity_factor=attrs["capacity_factor"], top_k=attrs["top_k"])
    return {"Out": [y], "AuxLoss": [aux.reshape((1,))]}


# ---------------------------------------------------------------------------
# 3D convolution / pooling (reference operators/conv_op.cc conv3d kernels,
# conv_transpose_op.cc, pool_op.cc pool3d) — NCDHW layout
# ---------------------------------------------------------------------------

def _conv3d_infer(op):
    iv, fv = op.invar("Input"), op.invar("Filter")
    if iv is None or iv.shape is None or fv is None or fv.shape is None:
        return
    s = op.attr("strides", [1, 1, 1])
    p = op.attr("paddings", [0, 0, 0])
    d = op.attr("dilations", [1, 1, 1])
    n = iv.shape[0]
    oc = fv.shape[0]
    sp = []
    for i, (x_, k_) in enumerate(zip(iv.shape[2:], fv.shape[2:])):
        ek = (k_ - 1) * d[i] + 1
        sp.append((x_ + 2 * p[i] - ek) // s[i] + 1 if x_ > 0 else x_)
    for name in op.output("Output"):
        op.block.create_var(name=name, shape=(n, oc, *sp), dtype=iv.dtype)


@register("conv3d", infer_shape=_conv3d_infer,
          attrs={"strides": [1, 1, 1], "paddings": [0, 0, 0],
                 "dilations": [1, 1, 1], "groups": 1,
                 "padding_algorithm": "EXPLICIT", "data_format": "NCDHW",
                 "use_cudnn": False})
def _conv3d(ctx, ins, attrs):
    inp, flt = x(ins, "Input"), x(ins, "Filter")
    algo = attrs.get("padding_algorithm", "EXPLICIT")
    p = attrs.get("paddings", [0, 0, 0])
    pad = algo if algo in ("SAME", "VALID") else [(q, q) for q in p]
    r = jax.lax.conv_general_dilated(
        inp, flt, window_strides=attrs.get("strides", [1, 1, 1]),
        padding=pad, rhs_dilation=attrs.get("dilations", [1, 1, 1]),
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=attrs.get("groups", 1) or 1)
    return {"Output": [r]}


@register("conv3d_transpose",
          attrs={"strides": [1, 1, 1], "paddings": [0, 0, 0],
                 "dilations": [1, 1, 1], "groups": 1,
                 "padding_algorithm": "EXPLICIT", "output_padding": [],
                 "data_format": "NCDHW", "output_size": [],
                 "use_cudnn": False})
def _conv3d_transpose(ctx, ins, attrs):
    """out = (i-1)*s + k_eff - 2p + output_padding, via input-dilated conv
    with the spatially-flipped swapped-IO kernel (same construction as
    conv2d_transpose above, one more spatial dim)."""
    inp, flt = x(ins, "Input"), x(ins, "Filter")
    strides = attrs.get("strides", [1, 1, 1])
    dil = attrs.get("dilations", [1, 1, 1])
    g = attrs.get("groups", 1) or 1
    out_pad = attrs.get("output_padding") or [0, 0, 0]
    p = attrs.get("paddings", [0, 0, 0])
    in_c, opg = flt.shape[0], flt.shape[1]
    ks = flt.shape[2:]
    k_eff = [dil[i] * (ks[i] - 1) + 1 for i in range(3)]
    jpads = [(k_eff[i] - 1 - p[i], k_eff[i] - 1 - p[i] + out_pad[i])
             for i in range(3)]
    w = flt.reshape(g, in_c // g, opg, *ks)
    w = jnp.swapaxes(w, 1, 2).reshape(g * opg, in_c // g, *ks)
    w = w[:, :, ::-1, ::-1, ::-1]
    r = jax.lax.conv_general_dilated(
        inp, w, window_strides=(1, 1, 1), padding=jpads,
        lhs_dilation=strides, rhs_dilation=dil,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=g)
    return {"Output": [r]}


def _pool3d_infer(op):
    v = op.invar("X")
    if v is None or v.shape is None:
        return
    n, c = v.shape[:2]
    if op.attr("global_pooling", False):
        sp = [1, 1, 1]
    else:
        k = op.attr("ksize", [2, 2, 2])
        s = op.attr("strides", [2, 2, 2])
        p = op.attr("paddings", [0, 0, 0])
        sp = [(v.shape[2 + i] + 2 * p[i] - k[i]) // s[i] + 1
              if v.shape[2 + i] > 0 else v.shape[2 + i] for i in range(3)]
    for name in op.output("Out"):
        op.block.create_var(name=name, shape=(n, c, *sp), dtype=v.dtype)


@register("pool3d", infer_shape=_pool3d_infer,
          attrs={"pooling_type": "max", "ksize": [2, 2, 2],
                 "strides": [2, 2, 2], "paddings": [0, 0, 0],
                 "global_pooling": False, "ceil_mode": False,
                 "exclusive": True, "adaptive": False,
                 "data_format": "NCDHW", "use_cudnn": False})
def _pool3d(ctx, ins, attrs):
    v = x(ins)
    ptype = attrs["pooling_type"]
    if attrs.get("global_pooling") or (attrs.get("adaptive") and
                                       list(attrs["ksize"]) == [1, 1, 1]):
        fn = jnp.max if ptype == "max" else jnp.mean
        return out(fn(v, axis=(2, 3, 4), keepdims=True))
    k, s, p = (list(attrs["ksize"]), list(attrs["strides"]),
               list(attrs["paddings"]))
    dims = (1, 1, *k)
    strides = (1, 1, *s)
    pads = ((0, 0), (0, 0), *[(q, q) for q in p])
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(v.dtype, jnp.floating) \
            else jnp.iinfo(v.dtype).min
        r = jax.lax.reduce_window(v, init, jax.lax.max, dims, strides,
                                  pads)
    else:
        ssum = jax.lax.reduce_window(v, 0.0, jax.lax.add, dims, strides,
                                     pads)
        if attrs.get("exclusive", True) and any(p):
            cnt = jax.lax.reduce_window(jnp.ones_like(v), 0.0, jax.lax.add,
                                        dims, strides, pads)
            r = ssum / cnt
        else:
            r = ssum / (k[0] * k[1] * k[2])
    return out(r)


# ---------------------------------------------------------------------------
# fused dropout + residual-add + layer_norm (Pallas,
# ops/pallas_fused_residual.py; reference skip_layernorm_fuse_pass tier).
# The transformer sublayer epilogue as ONE kernel each way.
# ---------------------------------------------------------------------------

@register("fused_dropout_add_ln", infer_shape=same_shape_as("X", "Out"),
          stochastic=True,
          attrs={"dropout_p": 0.0, "epsilon": 1e-5})
def _fused_dropout_add_ln(ctx, ins, attrs):
    v, res = x(ins, "X"), x(ins, "Residual")
    scale, bias = x(ins, "Scale"), x(ins, "Bias")
    p = attrs["dropout_p"]
    if ctx is not None and ctx.is_test:
        p = 0.0
    eps = attrs["epsilon"]
    shape = v.shape
    c = shape[-1]
    r = 1
    for s in shape[:-1]:
        r *= s
    from ...ops.pallas_fused_residual import (
        can_use_fused_dropout_add_ln, dropout_add_ln_wins,
        fused_dropout_add_ln)
    if can_use_fused_dropout_add_ln(r, c) \
            and dropout_add_ln_wins(r, c, v.dtype, float(p), float(eps)):
        seed = _op_seed(ctx, attrs, p)
        y = fused_dropout_add_ln(v.reshape(r, c), res.reshape(r, c),
                                 scale, bias, seed, float(p), float(eps))
        return out(y.reshape(shape))
    # composed fallback (non-aligned dims / pallas disabled)
    if p > 0.0:
        key = ctx.rng(attrs) if ctx is not None else jax.random.PRNGKey(0)
        keep = jax.random.bernoulli(key, 1.0 - p, v.shape)
        v = jnp.where(keep, v / (1.0 - p), 0.0)
    z = (v + res).astype(jnp.float32)
    mean = jnp.mean(z, -1, keepdims=True)
    var = jnp.mean(jnp.square(z - mean), -1, keepdims=True)
    zhat = (z - mean) * jax.lax.rsqrt(var + eps)
    return out((zhat * scale + bias).astype(res.dtype))


# ---------------------------------------------------------------------------
# fused transformer FFN: act(x@W1+b1)@W2+b2 with the 4H intermediate in
# VMEM (Pallas, ops/pallas_ffn.py; reference fused_feedforward_op tier).
# ---------------------------------------------------------------------------

@register("fused_ffn", infer_shape=same_shape_as("X", "Out"),
          attrs={"activation": "gelu"})
def _fused_ffn_op(ctx, ins, attrs):
    v = x(ins, "X")
    w1, b1 = x(ins, "W1"), x(ins, "B1")
    w2, b2 = x(ins, "W2"), x(ins, "B2")
    act = attrs.get("activation", "gelu")
    h = v.shape[-1]
    i = w1.shape[1]
    m = 1
    for s in v.shape[:-1]:
        m *= s
    from ...ops.pallas_ffn import can_use_fused_ffn, ffn_wins, fused_ffn
    if act in ("gelu", "relu") and can_use_fused_ffn(
            m, h, i, itemsize=v.dtype.itemsize) \
            and ffn_wins(m, h, i, v.dtype, act):
        return out(fused_ffn(v, w1, b1, w2, b2, act))
    # composed fallback (non-aligned dims / pallas disabled / other act)
    hid = v.reshape(m, h) @ w1 + b1
    if act == "gelu":
        hid = jax.nn.gelu(hid.astype(jnp.float32),
                          approximate=False).astype(v.dtype)
    else:
        from ..registry import require
        hid = require(act).compute(ctx, {"X": [hid]}, {})["Out"][0]
    return out((hid @ w2 + b2).astype(v.dtype).reshape(v.shape))


# ---------------------------------------------------------------------------
# epilogue-fused decoder sub-blocks (Pallas, ops/pallas_block.py — CODA
# style GEMM-epilogue programs; PR-7 tentpole). Both ops carry the full
# sub-block: GEMM(s) + bias + dropout + residual-add + layernorm in one
# kernel each way, behind the measured autobench gate with a composed
# fallback of identical semantics (the dropout mask is the same counter
# hash on both paths, so fused and fallback agree bit-for-bit-ish).
# ---------------------------------------------------------------------------

def _op_seed(ctx, attrs, p):
    import jax
    seed = jnp.zeros((1,), jnp.int32)
    if p > 0.0:
        key = ctx.rng(attrs) if ctx is not None else jax.random.PRNGKey(0)
        kd = key if jnp.issubdtype(key.dtype, jnp.integer) \
            else jax.random.key_data(key)
        seed = kd.ravel()[-1:].astype(jnp.int32)
    return seed


@register("fused_out_ln", infer_shape=same_shape_as("Residual"),
          stochastic=True,
          attrs={"epsilon": 1e-5, "dropout_p": 0.0})
def _fused_out_ln_op(ctx, ins, attrs):
    """Out = LN(Residual + dropout(X @ W + B)) * Scale + Bias — the
    attention-out projection GEMM with the whole post-LN sublayer
    epilogue carried in the kernel."""
    from ...ops.pallas_block import (can_use_fused_out_ln, fused_out_ln,
                                     out_ln_reference, out_ln_wins)
    v, w, b = x(ins, "X"), x(ins, "W"), x(ins, "B")
    res = x(ins, "Residual")
    scale, bias = x(ins, "Scale"), x(ins, "Bias")
    p = attrs["dropout_p"]
    if ctx is not None and ctx.is_test:
        p = 0.0
    p, eps = float(p), float(attrs["epsilon"])
    din = v.shape[-1]
    dout = w.shape[1]
    m = 1
    for s in v.shape[:-1]:
        m *= s
    if scale is None:
        scale = jnp.ones((dout,), jnp.float32)
    if bias is None:
        bias = jnp.zeros((dout,), jnp.float32)
    seed = _op_seed(ctx, attrs, p)
    v2 = v.reshape(m, din)
    res2 = res.reshape(m, dout)
    if can_use_fused_out_ln(m, din, dout, v.dtype.itemsize) \
            and out_ln_wins(m, din, dout, v.dtype, p, eps):
        _z, h = fused_out_ln(v2, w, b, res2, scale, bias, seed, p, eps)
    else:
        _z, h = out_ln_reference(v2, w, b, res2, scale, bias, seed, p,
                                 eps)
    return out(h.astype(res.dtype).reshape(res.shape))


@register("fused_ffn_block", infer_shape=same_shape_as("Residual"),
          stochastic=True,
          attrs={"activation": "gelu", "epsilon": 1e-5,
                 "dropout_p": 0.0, "norm": "post"})
def _fused_ffn_block_op(ctx, ins, attrs):
    """Out = [LN]( Residual + dropout( act(X' @ W1 + B1) @ W2 + B2 ) )
    with X' = LN(X) for norm="pre" — the FFN sub-block as ONE
    GEMM-epilogue program (norm: "pre" | "post" | "none")."""
    from ...ops.pallas_block import (can_use_fused_ffn_ln, ffn_ln_wins,
                                     ffn_ln_reference, fused_ffn_ln)
    v = x(ins, "X")
    w1, b1 = x(ins, "W1"), x(ins, "B1")
    w2, b2 = x(ins, "W2"), x(ins, "B2")
    res = x(ins, "Residual")
    scale, bias = x(ins, "Scale"), x(ins, "Bias")
    act = attrs.get("activation", "gelu")
    norm = attrs.get("norm", "post")
    p = attrs["dropout_p"]
    if ctx is not None and ctx.is_test:
        p = 0.0
    p, eps = float(p), float(attrs["epsilon"])
    h = v.shape[-1]
    i = w1.shape[1]
    m = 1
    for s in v.shape[:-1]:
        m *= s
    if scale is None:
        scale = jnp.ones((h,), jnp.float32)
    if bias is None:
        bias = jnp.zeros((h,), jnp.float32)
    seed = _op_seed(ctx, attrs, p)
    v2 = v.reshape(m, h)
    res2 = res.reshape(m, h)
    if act not in ("gelu", "gelu_tanh", "relu"):
        raise ValueError(
            f"fused_ffn_block supports gelu/gelu_tanh/relu, got {act!r}"
            " (use the composed linear/activation ops instead)")
    if can_use_fused_ffn_ln(m, h, i, v.dtype.itemsize,
                            norm == "pre") \
            and ffn_ln_wins(m, h, i, v.dtype, act, norm, p, eps):
        y = fused_ffn_ln(v2, w1, b1, w2, b2, res2, scale, bias, seed,
                         act, norm, p, eps)
    else:
        y = ffn_ln_reference(v2, w1, b1, w2, b2, res2, scale, bias,
                             seed, act, norm, p, eps)
    return out(y.astype(res.dtype).reshape(res.shape))


# -- compile-time shape inference additions (VERDICT r5 missing #3) ---------

def _one_hot_infer(op):
    v = op.invar("X")
    if v is None or v.shape is None:
        return
    shape = tuple(v.shape) + (op.attr("depth", -1),)
    for n in op.output("Out"):
        op.block.create_var(name=n, shape=shape,
                            dtype=op.attr("dtype", "float32"))


def _pad_infer(op):
    v = op.invar("X")
    if v is None or v.shape is None:
        return
    p = op.attr("paddings", [])
    shape = [s + p[2 * i] + p[2 * i + 1] if s >= 0 else s
             for i, s in enumerate(v.shape)]
    for n in op.output("Out"):
        op.block.create_var(name=n, shape=tuple(shape), dtype=v.dtype)


from .. import registry as _registry
_registry._REGISTRY["one_hot_v2"].infer_shape = _one_hot_infer
_registry._REGISTRY["one_hot"].infer_shape = _one_hot_infer
_registry._REGISTRY["pad"].infer_shape = _pad_infer
