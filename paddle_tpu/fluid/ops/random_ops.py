"""Random ops — threefry-keyed, per-op stable streams.

Replaces reference operators/{gaussian,uniform,truncated_gaussian}_random,
randint, randperm, bernoulli (SURVEY §2.3 "Fill/random") and the Philox
Generator (framework/generator.h). The executor hands every stochastic op a
key folded from (step_key, op._rng_id) so runs are reproducible under
program.random_seed and identical between forward and auto-vjp grad replay.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..registry import register
from .common import x, out, np_dtype


def _shape_from(ins, attrs):
    st = x(ins, "ShapeTensor")
    if st is not None:
        return [int(s) for s in np.asarray(st)]
    return list(attrs.get("shape", []))


def _rand_infer(op):
    shape = tuple(op.attr("shape", []))
    for name in op.output("Out"):
        op.block.create_var(name=name, shape=shape,
                            dtype=op.attr("dtype", "float32"))


@register("gaussian_random", grad=None, stochastic=True,
          infer_shape=_rand_infer,
          attrs={"shape": [], "mean": 0.0, "std": 1.0, "seed": 0,
                 "dtype": "float32"})
def _gaussian(ctx, ins, attrs):
    shape = _shape_from(ins, attrs)
    r = jax.random.normal(ctx.rng(attrs), shape,
                          dtype=np_dtype(attrs["dtype"]))
    return out(r * attrs["std"] + attrs["mean"])


@register("uniform_random", grad=None, stochastic=True,
          infer_shape=_rand_infer,
          attrs={"shape": [], "min": -1.0, "max": 1.0, "seed": 0,
                 "dtype": "float32"})
def _uniform(ctx, ins, attrs):
    shape = _shape_from(ins, attrs)
    return out(jax.random.uniform(
        ctx.rng(attrs), shape, dtype=np_dtype(attrs["dtype"]),
        minval=attrs["min"], maxval=attrs["max"]))


@register("uniform_random_batch_size_like", grad=None, stochastic=True,
          attrs={"shape": [], "min": -1.0, "max": 1.0, "seed": 0,
                 "dtype": "float32", "input_dim_idx": 0, "output_dim_idx": 0})
def _uniform_bsl(ctx, ins, attrs):
    v = x(ins, "Input")
    shape = list(attrs["shape"])
    shape[attrs["output_dim_idx"]] = v.shape[attrs["input_dim_idx"]]
    return out(jax.random.uniform(
        ctx.rng(attrs), shape, dtype=np_dtype(attrs["dtype"]),
        minval=attrs["min"], maxval=attrs["max"]))


@register("truncated_gaussian_random", grad=None, stochastic=True,
          infer_shape=_rand_infer,
          attrs={"shape": [], "mean": 0.0, "std": 1.0, "seed": 0,
                 "dtype": "float32"})
def _trunc_gaussian(ctx, ins, attrs):
    r = jax.random.truncated_normal(
        ctx.rng(attrs), -2.0, 2.0, attrs["shape"],
        dtype=np_dtype(attrs["dtype"]))
    return out(r * attrs["std"] + attrs["mean"])


@register("randint", grad=None, stochastic=True, infer_shape=_rand_infer,
          attrs={"shape": [], "low": 0, "high": 100, "seed": 0,
                 "dtype": "int64"})
def _randint(ctx, ins, attrs):
    return out(jax.random.randint(
        ctx.rng(attrs), _shape_from(ins, attrs), attrs["low"], attrs["high"],
        dtype=np_dtype(attrs["dtype"])))


@register("randperm", grad=None, stochastic=True,
          attrs={"n": 0, "seed": 0, "dtype": "int64"})
def _randperm(ctx, ins, attrs):
    return out(jax.random.permutation(ctx.rng(attrs), attrs["n"])
               .astype(np_dtype(attrs["dtype"])))


@register("bernoulli", grad=None, stochastic=True)
def _bernoulli(ctx, ins, attrs):
    v = x(ins)
    return out(jax.random.bernoulli(ctx.rng(attrs), v).astype(v.dtype))


@register("multinomial", grad=None, stochastic=True,
          attrs={"num_samples": 1, "replacement": False})
def _multinomial(ctx, ins, attrs):
    v = x(ins)
    logits = jnp.log(jnp.clip(v, 1e-20, None))
    n = attrs["num_samples"]
    if attrs.get("replacement", False):
        return out(jax.random.categorical(
            ctx.rng(attrs), logits, axis=-1,
            shape=(n,) + logits.shape[:-1]).T.astype(jnp.int64))
    # without replacement (reference multinomial_op semantics): Gumbel
    # top-k — argsort of logits + iid Gumbel noise yields a sample of k
    # distinct categories with the right distribution
    gumbel = jax.random.gumbel(ctx.rng(attrs), logits.shape,
                               dtype=logits.dtype)
    _, idx = jax.lax.top_k(logits + gumbel, n)
    return out(idx.astype(jnp.int64))


@register("sampling_id", grad=None, stochastic=True,
          attrs={"min": 0.0, "max": 1.0, "seed": 0})
def _sampling_id(ctx, ins, attrs):
    v = x(ins)
    logits = jnp.log(jnp.clip(v, 1e-20, None))
    return out(jax.random.categorical(ctx.rng(attrs), logits, axis=-1))


@register("seed", grad=None, attrs={"seed": 0})
def _seed(ctx, ins, attrs):
    return out(jnp.asarray([attrs["seed"]], dtype=jnp.int32))
