"""Built-in op definitions.

TPU-native equivalents of /root/reference/paddle/fluid/operators/*: each op is
a jax compute fn registered in paddle_tpu.fluid.registry; XLA compiles and
fuses them (no per-device kernel files, no Eigen/cuBLAS dispatch).
"""
from . import (math_ops, nn_ops, tensor_ops, random_ops, optimizer_ops,
               control_ops, metric_ops, sequence_ops,
               structured_loss_ops, detection_ops, misc_ops,
               ps_ops)  # noqa: F401
from . import tail_ops  # noqa: F401,E402
from . import parity_ops  # noqa: F401,E402
