"""Long-tail operator groups: conv/vision extras, sequence extras, rnn
step units, ranking losses, proximal optimizers, PS id ops, metrics.

Reference files (all under /root/reference/paddle/fluid/operators/):
  conv_shift_op.cc, lrn_op.cc, data_norm_op.cc, pixel_shuffle_op.cc,
  shuffle_channel_op.cc, temporal_shift_op.cc, grid_sampler_op.cc,
  affine_grid_op.cc, unfold_op.cc, spp_op.cc, norm_op.cc,
  edit_distance_op.cc, ctc_align_op.cc, im2sequence_op.cc, row_conv_op.cc,
  gru_unit_op.cc, lstm_unit_op.cc, add_position_encoding_op.cc,
  margin_rank_loss_op.cc, rank_loss_op.cc,
  teacher_student_sigmoid_loss_op.cc, optimizers/proximal_gd_op.cc,
  optimizers/proximal_adagrad_op.cc, dgc_clip_by_norm_op.cc,
  metrics/precision_recall_op.cc, detection/anchor_generator_op.cc,
  histogram_op.cc, masked_select_op.cc, diag_v2 (diag_op.cc),
  distributed_ops/split_ids_op.cc, merge_ids_op.cc.
All are jnp compute fns; grads come from auto-vjp unless grad=None.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..registry import register, same_shape_as
from .common import x, out


# ---------------------------------------------------------------------------
# conv / vision extras
# ---------------------------------------------------------------------------

@register("conv_shift", infer_shape=same_shape_as("X"))
def _conv_shift(ctx, ins, attrs):
    """Circular correlation (reference conv_shift_op): Out[i,j] =
    sum_k X[i, (j+k-M//2) mod N] * Y[i, k]."""
    a, b = x(ins, "X"), x(ins, "Y")
    N, M = a.shape[1], b.shape[1]
    idx = (jnp.arange(N)[:, None] + jnp.arange(M)[None, :]
           - M // 2) % N                                  # [N, M]
    return out(jnp.einsum("bnm,bm->bn", a[:, idx], b))


@register("lrn", infer_shape=same_shape_as("X"),
          no_grad_out_slots=("MidOut",),
          attrs={"n": 5, "k": 2.0, "alpha": 1e-4, "beta": 0.75})
def _lrn(ctx, ins, attrs):
    """Local response normalisation across channels (reference
    lrn_op.cc)."""
    v = x(ins, "X")
    n, k, alpha, beta = (attrs["n"], attrs["k"], attrs["alpha"],
                         attrs["beta"])
    sq = jnp.square(v)
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, n - 1 - half), (0, 0), (0, 0)))
    acc = sum(pad[:, i:i + v.shape[1]] for i in range(n))
    mid = k + alpha * acc
    return {"Out": [v / mid ** beta], "MidOut": [mid]}


@register("data_norm", no_grad_slots=("BatchSize", "BatchSum",
                                      "BatchSquareSum"),
          no_grad_out_slots=("Means", "Scales"),
          attrs={"epsilon": 1e-4, "slot_dim": -1})
def _data_norm(ctx, ins, attrs):
    """Global-stats normalisation for CTR models (reference
    data_norm_op.cc): y = (x - mean) / scale from running batch
    sum/square-sum counters (PS-updated in the reference)."""
    v = x(ins, "X").astype(jnp.float32)
    bsz = x(ins, "BatchSize").astype(jnp.float32)
    bsum = x(ins, "BatchSum").astype(jnp.float32)
    bsq = x(ins, "BatchSquareSum").astype(jnp.float32)
    means = bsum / jnp.maximum(bsz, 1e-4)
    scales = jnp.sqrt(jnp.maximum(bsz, 1e-4)
                      / jnp.maximum(bsq, attrs["epsilon"]))
    return {"Y": [(v - means) * scales], "Means": [means],
            "Scales": [scales]}


@register("pixel_shuffle", attrs={"upscale_factor": 1,
                                  "data_format": "NCHW"})
def _pixel_shuffle(ctx, ins, attrs):
    v = x(ins, "X")
    r = attrs["upscale_factor"]
    N, C, H, W = v.shape
    v = v.reshape(N, C // (r * r), r, r, H, W)
    v = v.transpose(0, 1, 4, 2, 5, 3)
    return out(v.reshape(N, C // (r * r), H * r, W * r))


@register("shuffle_channel", attrs={"group": 1})
def _shuffle_channel(ctx, ins, attrs):
    v = x(ins, "X")
    g = attrs["group"]
    N, C, H, W = v.shape
    return out(v.reshape(N, g, C // g, H, W).swapaxes(1, 2)
               .reshape(N, C, H, W))


@register("temporal_shift", attrs={"seg_num": 1, "shift_ratio": 0.25})
def _temporal_shift(ctx, ins, attrs):
    """TSM shift (reference temporal_shift_op): within each segment,
    shift the first C*ratio channels back one step in time and the next
    C*ratio forward."""
    v = x(ins, "X")
    T = attrs["seg_num"]
    NT, C, H, W = v.shape
    c1 = int(C * attrs["shift_ratio"])
    c2 = int(C * 2 * attrs["shift_ratio"])
    v = v.reshape(NT // T, T, C, H, W)
    back = jnp.concatenate(
        [v[:, 1:, :c1], jnp.zeros_like(v[:, :1, :c1])], axis=1)
    fwd = jnp.concatenate(
        [jnp.zeros_like(v[:, :1, c1:c2]), v[:, :-1, c1:c2]], axis=1)
    return out(jnp.concatenate([back, fwd, v[:, :, c2:]], axis=2)
               .reshape(NT, C, H, W))


@register("grid_sampler", attrs={"mode": "bilinear",
                                 "padding_mode": "zeros",
                                 "align_corners": True})
def _grid_sampler(ctx, ins, attrs):
    """Bilinear grid sample (reference grid_sampler_op): X [N,C,H,W] +
    Grid [N,Ho,Wo,2] in [-1,1] -> [N,C,Ho,Wo]; zero padding outside."""
    v = x(ins, "X").astype(jnp.float32)
    grid = x(ins, "Grid").astype(jnp.float32)
    N, C, H, W = v.shape
    if attrs.get("align_corners", True):
        gx = (grid[..., 0] + 1) * (W - 1) / 2
        gy = (grid[..., 1] + 1) * (H - 1) / 2
    else:
        gx = ((grid[..., 0] + 1) * W - 1) / 2
        gy = ((grid[..., 1] + 1) * H - 1) / 2

    def sample_one(img, yy, xx):
        y0 = jnp.floor(yy).astype(jnp.int32)
        x0 = jnp.floor(xx).astype(jnp.int32)

        def tap(yi, xi, wgt):
            inb = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
            yi = jnp.clip(yi, 0, H - 1)
            xi = jnp.clip(xi, 0, W - 1)
            val = img[:, yi, xi]                          # [C, Ho, Wo]
            return val * (wgt * inb)[None]
        wy1 = yy - y0
        wx1 = xx - x0
        return (tap(y0, x0, (1 - wy1) * (1 - wx1))
                + tap(y0, x0 + 1, (1 - wy1) * wx1)
                + tap(y0 + 1, x0, wy1 * (1 - wx1))
                + tap(y0 + 1, x0 + 1, wy1 * wx1))

    return {"Output": [jax.vmap(sample_one)(v, gy, gx)]}


@register("affine_grid", no_grad_slots=("OutputShape",),
          attrs={"align_corners": True, "output_shape": []})
def _affine_grid(ctx, ins, attrs):
    """Theta [N,2,3] -> sampling grid [N,H,W,2] (reference
    affine_grid_op)."""
    theta = x(ins, "Theta").astype(jnp.float32)
    shape_v = x(ins, "OutputShape")
    if shape_v is not None:
        _, _, H, W = [int(s) for s in np.asarray(shape_v)]
    else:
        _, _, H, W = attrs["output_shape"]
    if attrs.get("align_corners", True):
        ys = jnp.linspace(-1, 1, H)
        xs = jnp.linspace(-1, 1, W)
    else:
        ys = (jnp.arange(H) * 2 + 1) / H - 1
        xs = (jnp.arange(W) * 2 + 1) / W - 1
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [H, W, 3]
    return {"Output": [jnp.einsum("hwk,njk->nhwj", base, theta)]}


@register("unfold", attrs={"kernel_sizes": [3, 3], "strides": [1, 1],
                           "paddings": [0, 0, 0, 0], "dilations": [1, 1]})
def _unfold(ctx, ins, attrs):
    """im2col (reference unfold_op): [N,C,H,W] ->
    [N, C*kh*kw, L]."""
    v = x(ins, "X")
    kh, kw = attrs["kernel_sizes"]
    sh, sw = attrs["strides"]
    p = attrs["paddings"]
    dh, dw = attrs["dilations"]
    v = jnp.pad(v, ((0, 0), (0, 0), (p[0], p[2] if len(p) > 2 else p[0]),
                    (p[1], p[3] if len(p) > 3 else p[1])))
    N, C, H, W = v.shape
    oh = (H - (dh * (kh - 1) + 1)) // sh + 1
    ow = (W - (dw * (kw - 1) + 1)) // sw + 1
    cols = []
    for i in range(kh):
        for j in range(kw):
            patch = v[:, :, i * dh:i * dh + oh * sh:sh,
                      j * dw:j * dw + ow * sw:sw]
            cols.append(patch.reshape(N, C, -1))
    colm = jnp.stack(cols, axis=2)                # [N, C, kh*kw, L]
    return {"Y": [colm.reshape(N, C * kh * kw, -1)]}


@register("spp", attrs={"pyramid_height": 2, "pooling_type": "max"})
def _spp(ctx, ins, attrs):
    """Spatial pyramid pooling (reference spp_op): concat adaptive pools
    at 1x1, 2x2, ... 2^(h-1) bins."""
    v = x(ins, "X")
    N, C = v.shape[:2]
    outs = []
    from .nn_ops import _pool2d
    for lvl in range(attrs["pyramid_height"]):
        bins = 2 ** lvl
        r = _pool2d(ctx, {"X": [v]},
                    {"pooling_type": attrs["pooling_type"],
                     "ksize": [bins, bins], "adaptive": True,
                     "global_pooling": False, "strides": [1, 1],
                     "paddings": [0, 0], "exclusive": True,
                     "ceil_mode": False})["Out"][0]
        outs.append(r.reshape(N, -1))
    return out(jnp.concatenate(outs, axis=1))


@register("norm", no_grad_out_slots=("Norm",),
          attrs={"axis": 1, "epsilon": 1e-10})
def _norm(ctx, ins, attrs):
    """L2-normalise along axis (reference norm_op); Norm output carries
    the magnitudes."""
    v = x(ins, "X")
    nrm = jnp.sqrt(jnp.sum(jnp.square(v), axis=attrs["axis"],
                           keepdims=True) + attrs["epsilon"])
    return {"Out": [v / nrm], "Norm": [nrm]}


# ---------------------------------------------------------------------------
# sequence extras
# ---------------------------------------------------------------------------

@register("edit_distance", grad=None,
          no_grad_slots=("Hyps", "Refs", "HypsLength", "RefsLength"),
          attrs={"normalized": False})
def _edit_distance(ctx, ins, attrs):
    """Levenshtein distance per pair (reference edit_distance_op), dense
    [B, L] + lengths. DP over the reference sequence via scan."""
    hyp = x(ins, "Hyps").astype(jnp.int32)
    ref = x(ins, "Refs").astype(jnp.int32)
    hlen = x(ins, "HypsLength")
    rlen = x(ins, "RefsLength")
    B, HL = hyp.shape
    RL = ref.shape[1]
    hlen = (jnp.full((B,), HL, jnp.int32) if hlen is None
            else hlen.reshape(-1).astype(jnp.int32))
    rlen = (jnp.full((B,), RL, jnp.int32) if rlen is None
            else rlen.reshape(-1).astype(jnp.int32))

    def one(h, r, hl, rl):
        row0 = jnp.minimum(jnp.arange(HL + 1), hl).astype(jnp.float32)

        def step(row, j):
            # row = distances for ref[:j]; compute for ref[:j+1]
            ins_cost = row[:-1] + jnp.where(h != r[j], 1.0, 0.0)

            def inner(carry, t):
                left_new = carry
                diag, up, sub = t
                val = jnp.minimum(jnp.minimum(up + 1.0, left_new + 1.0),
                                  sub)
                return val, val
            first = row[0] + 1.0
            _, rest = jax.lax.scan(
                inner, first, (row[:-1], row[1:], ins_cost))
            new = jnp.concatenate([first[None], rest])
            new = jnp.where(j < rl, new, row)
            return new, None
        final, _ = jax.lax.scan(step, row0, jnp.arange(RL))
        d = final[hl]
        return jnp.where(attrs["normalized"],
                         d / jnp.maximum(rl.astype(jnp.float32), 1.0), d)

    dist = jax.vmap(one)(hyp, ref, hlen, rlen)
    return {"Out": [dist[:, None]],
            "SequenceNum": [jnp.asarray([B], jnp.int64)]}


@register("ctc_align", grad=None, attrs={"blank": 0, "merge_repeated": True,
                                         "padding_value": 0})
def _ctc_align(ctx, ins, attrs):
    """Collapse CTC paths: drop repeats then blanks (reference
    ctc_align_op), padded-dense output."""
    v = x(ins, "Input").astype(jnp.int32)
    blank = attrs["blank"]
    pad = attrs["padding_value"]
    B, T = v.shape
    prev = jnp.concatenate([jnp.full((B, 1), -1, jnp.int32), v[:, :-1]],
                           axis=1)
    keep = (v != blank)
    if attrs["merge_repeated"]:
        keep &= (v != prev)
    pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    out_ = jnp.full((B, T), pad, jnp.int32)
    b_idx = jnp.repeat(jnp.arange(B)[:, None], T, 1)
    out_ = out_.at[b_idx, jnp.where(keep, pos, T - 1)].set(
        jnp.where(keep, v, out_[b_idx, jnp.where(keep, pos, T - 1)]))
    lens = jnp.sum(keep.astype(jnp.int32), axis=1)
    return {"Output": [out_], "OutputLength": [lens[:, None]]}


@register("im2sequence", grad=None,
          attrs={"kernels": [1, 1], "strides": [1, 1],
                 "paddings": [0, 0, 0, 0], "out_stride": [1, 1]})
def _im2sequence(ctx, ins, attrs):
    """Image -> patch rows (reference im2sequence_op): [N,C,H,W] ->
    [N*oh*ow, C*kh*kw] (dense, batch-major — LoD designed away)."""
    r = _unfold(ctx, {"X": ins["X"]},
                {"kernel_sizes": attrs["kernels"],
                 "strides": attrs["strides"],
                 "paddings": attrs["paddings"], "dilations": [1, 1]})
    y = r["Y"][0]                                  # [N, C*kh*kw, L]
    N, CK, L = y.shape
    return out(y.transpose(0, 2, 1).reshape(N * L, CK))


@register("row_conv", attrs={})
def _row_conv(ctx, ins, attrs):
    """Lookahead row convolution (reference row_conv_op, DeepSpeech2):
    Out[t] = sum_{k} X[t+k] * W[k] over a [future_len, D] filter."""
    v = x(ins, "X")                                # [B, T, D]
    w = x(ins, "Filter")                           # [K, D]
    K = w.shape[0]
    B, T, D = v.shape
    pad = jnp.pad(v, ((0, 0), (0, K - 1), (0, 0)))
    acc = sum(pad[:, k:k + T] * w[k][None, None, :] for k in range(K))
    return out(acc)


# ---------------------------------------------------------------------------
# rnn step units
# ---------------------------------------------------------------------------

@register("gru_unit", no_grad_out_slots=("ResetHiddenPrev", "Gate"),
          attrs={"activation": "tanh", "gate_activation": "sigmoid",
                 "origin_mode": False})
def _gru_unit(ctx, ins, attrs):
    """One GRU step (reference gru_unit_op): Input [B, 3D] (pre-projected
    x), HiddenPrev [B, D], Weight [D, 3D], Bias [1, 3D]."""
    xin = x(ins, "Input")
    h = x(ins, "HiddenPrev")
    w = x(ins, "Weight")
    b = x(ins, "Bias")
    D = h.shape[1]
    gates_x = xin if b is None else xin + b.reshape(-1)
    ru_x, c_x = gates_x[:, :2 * D], gates_x[:, 2 * D:]
    ru = jax.nn.sigmoid(ru_x + h @ w[:, :2 * D])
    r, u = ru[:, :D], ru[:, D:]
    rh = r * h
    c = jnp.tanh(c_x + rh @ w[:, 2 * D:])
    if attrs.get("origin_mode"):
        new_h = u * h + (1 - u) * c
    else:
        new_h = (1 - u) * h + u * c
    return {"Hidden": [new_h], "ResetHiddenPrev": [rh],
            "Gate": [jnp.concatenate([ru, c], axis=1)]}


@register("lstm_unit", attrs={"forget_bias": 0.0})
def _lstm_unit(ctx, ins, attrs):
    """One LSTM step (reference lstm_unit_op): X [B, 4D] pre-activations
    (i, f, c~, o order), C_prev [B, D]."""
    xin = x(ins, "X")
    c_prev = x(ins, "C_prev")
    D = c_prev.shape[1]
    i = jax.nn.sigmoid(xin[:, :D])
    f = jax.nn.sigmoid(xin[:, D:2 * D] + attrs["forget_bias"])
    g = jnp.tanh(xin[:, 2 * D:3 * D])
    o = jax.nn.sigmoid(xin[:, 3 * D:])
    c = f * c_prev + i * g
    return {"C": [c], "H": [o * jnp.tanh(c)]}


@register("add_position_encoding", attrs={"alpha": 1.0, "beta": 1.0})
def _add_position_encoding(ctx, ins, attrs):
    """Sinusoidal position encoding add (reference
    add_position_encoding_op)."""
    v = x(ins, "X")                                # [B, T, D]
    B, T, D = v.shape
    pos = jnp.arange(T, dtype=jnp.float32)[:, None]
    div = jnp.exp(jnp.arange(0, D, 2, dtype=jnp.float32)
                  * (-math.log(10000.0) / D))
    pe = jnp.zeros((T, D), jnp.float32)
    pe = pe.at[:, 0::2].set(jnp.sin(pos * div))
    # odd D: there are only D//2 odd (cos) columns; div has ceil(D/2)
    # entries, so slice to the cos-column count
    pe = pe.at[:, 1::2].set(jnp.cos(pos * div[:D // 2]))
    return out(attrs["alpha"] * v + attrs["beta"] * pe[None])


# ---------------------------------------------------------------------------
# ranking / distillation losses
# ---------------------------------------------------------------------------

@register("margin_rank_loss", no_grad_slots=("Label",),
          no_grad_out_slots=("Activated",),
          attrs={"margin": 0.0})
def _margin_rank_loss(ctx, ins, attrs):
    lab = x(ins, "Label")
    a, b = x(ins, "X1"), x(ins, "X2")
    act = jnp.maximum(0.0, -lab * (a - b) + attrs["margin"])
    return {"Out": [act], "Activated": [(act > 0).astype(a.dtype)]}


@register("rank_loss", no_grad_slots=("Label",))
def _rank_loss(ctx, ins, attrs):
    """RankNet pairwise loss (reference rank_loss_op)."""
    lab = x(ins, "Label")
    l, r = x(ins, "Left"), x(ins, "Right")
    d = l - r
    return out(jax.nn.softplus(d) - lab * d)


@register("teacher_student_sigmoid_loss", no_grad_slots=("Label",),
          attrs={"soft_max_up_bound": 15.0, "soft_max_lower_bound": -15.0})
def _ts_sigmoid_loss(ctx, ins, attrs):
    """CTR distillation loss (reference
    teacher_student_sigmoid_loss_op): label<0 => teacher soft target
    -label; else hard sigmoid CE."""
    z = x(ins, "X").reshape(-1)
    lab = x(ins, "Label").reshape(-1).astype(jnp.float32)
    ce_hard = jax.nn.softplus(z) - lab * z
    soft = -lab
    ce_soft = jax.nn.softplus(z) - soft * z
    return out(jnp.where(lab < 0, ce_soft, ce_hard)[:, None])


# ---------------------------------------------------------------------------
# optimizers / grad utils
# ---------------------------------------------------------------------------

def _lr_of(ins):
    return x(ins, "LearningRate").reshape(())


@register("proximal_gd", grad=None,
          attrs={"l1": 0.0, "l2": 0.0})
def _proximal_gd(ctx, ins, attrs):
    """Proximal GD with L1/L2 shrinkage (reference proximal_gd_op)."""
    p, g = x(ins, "Param"), x(ins, "Grad")
    lr = _lr_of(ins)
    prox = p - lr * g
    l1, l2 = attrs["l1"], attrs["l2"]
    new = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0) \
        / (1.0 + lr * l2)
    return {"ParamOut": [new]}


@register("proximal_adagrad", grad=None,
          attrs={"l1": 0.0, "l2": 0.0})
def _proximal_adagrad(ctx, ins, attrs):
    p, g, m = x(ins, "Param"), x(ins, "Grad"), x(ins, "Moment")
    lr = _lr_of(ins)
    m_new = m + g * g
    eff = lr / jnp.sqrt(m_new)
    prox = p - eff * g
    l1, l2 = attrs["l1"], attrs["l2"]
    new = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - eff * l1, 0.0) \
        / (1.0 + eff * l2)
    return {"ParamOut": [new], "MomentOut": [m_new]}


@register("dgc_clip_by_norm", attrs={"max_norm": 1.0, "rampup_begin_step":
                                     0.0})
def _dgc_clip_by_norm(ctx, ins, attrs):
    """clip_by_norm gated on the DGC rampup step (reference
    dgc_clip_by_norm_op)."""
    v = x(ins, "X")
    step = x(ins, "current_step").reshape(())
    nrm = jnp.sqrt(jnp.sum(jnp.square(v)))
    clipped = v * jnp.minimum(1.0, attrs["max_norm"]
                              / jnp.maximum(nrm, 1e-12))
    return out(jnp.where(step < attrs["rampup_begin_step"], v, clipped))


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

@register("precision_recall", grad=None,
          attrs={"class_number": 2})
def _precision_recall(ctx, ins, attrs):
    """Macro/micro precision/recall/F1 (reference
    metrics/precision_recall_op): MaxProbs+Indices (or predictions) vs
    Labels. Emits [macro P, R, F1, micro P, R, F1]."""
    idx = x(ins, "Indices").reshape(-1).astype(jnp.int32)
    lab = x(ins, "Labels").reshape(-1).astype(jnp.int32)
    C = attrs["class_number"]
    pred_oh = jax.nn.one_hot(idx, C, dtype=jnp.float32)
    lab_oh = jax.nn.one_hot(lab, C, dtype=jnp.float32)
    tp = jnp.sum(pred_oh * lab_oh, axis=0)
    fp = jnp.sum(pred_oh, axis=0) - tp
    fn = jnp.sum(lab_oh, axis=0) - tp
    prec = tp / jnp.maximum(tp + fp, 1e-12)
    rec = tp / jnp.maximum(tp + fn, 1e-12)
    f1 = 2 * prec * rec / jnp.maximum(prec + rec, 1e-12)
    macro = jnp.stack([jnp.mean(prec), jnp.mean(rec), jnp.mean(f1)])
    stp, sfp, sfn = jnp.sum(tp), jnp.sum(fp), jnp.sum(fn)
    mp = stp / jnp.maximum(stp + sfp, 1e-12)
    mr = stp / jnp.maximum(stp + sfn, 1e-12)
    micro = jnp.stack([mp, mr, 2 * mp * mr / jnp.maximum(mp + mr, 1e-12)])
    states = jnp.stack([tp, fp, fn, tp + fn], axis=1)
    return {"BatchMetrics": [jnp.concatenate([macro, micro])],
            "AccumMetrics": [jnp.concatenate([macro, micro])],
            "AccumStatesInfo": [states]}


@register("positive_negative_pair", grad=None, attrs={})
def _pos_neg_pair(ctx, ins, attrs):
    """Counts correctly-ordered (pos) vs mis-ordered (neg) score pairs
    within each query (reference positive_negative_pair_op)."""
    score = x(ins, "Score").reshape(-1)
    lab = x(ins, "Label").reshape(-1).astype(jnp.float32)
    qid = x(ins, "QueryID").reshape(-1)
    same_q = qid[:, None] == qid[None, :]
    better = lab[:, None] > lab[None, :]
    pos = jnp.sum(same_q & better & (score[:, None] > score[None, :]))
    neg = jnp.sum(same_q & better & (score[:, None] < score[None, :]))
    neu = jnp.sum(same_q & better & (score[:, None] == score[None, :]))
    asf = lambda v: v.astype(jnp.float32).reshape(1, 1)
    return {"PositivePair": [asf(pos)], "NegativePair": [asf(neg)],
            "NeutralPair": [asf(neu)]}


# ---------------------------------------------------------------------------
# detection extras / tensor extras / PS id ops
# ---------------------------------------------------------------------------

@register("anchor_generator", grad=None,
          attrs={"anchor_sizes": [64.0], "aspect_ratios": [1.0],
                 "variances": [0.1, 0.1, 0.2, 0.2], "stride": [16.0, 16.0],
                 "offset": 0.5})
def _anchor_generator(ctx, ins, attrs):
    """RPN anchors (reference detection/anchor_generator_op):
    [H, W, A, 4] in input-image pixel coords."""
    feat = x(ins, "Input")
    H, W = feat.shape[2], feat.shape[3]
    sw, sh = attrs["stride"]
    whs = []
    for size in attrs["anchor_sizes"]:
        area = float(size) ** 2
        for ar in attrs["aspect_ratios"]:
            w = math.sqrt(area / ar)
            whs.append((w, w * ar))
    whs = np.asarray(whs, np.float32)
    cx = (np.arange(W, dtype=np.float32) + attrs["offset"]) * sw
    cy = (np.arange(H, dtype=np.float32) + attrs["offset"]) * sh
    cxg, cyg = np.meshgrid(cx, cy)
    anchors = np.stack([
        cxg[:, :, None] - whs[None, None, :, 0] / 2,
        cyg[:, :, None] - whs[None, None, :, 1] / 2,
        cxg[:, :, None] + whs[None, None, :, 0] / 2,
        cyg[:, :, None] + whs[None, None, :, 1] / 2], axis=-1)
    var = np.broadcast_to(np.asarray(attrs["variances"], np.float32),
                          anchors.shape).copy()
    return {"Anchors": [jnp.asarray(anchors)],
            "Variances": [jnp.asarray(var)]}


@register("histogram", grad=None, attrs={"bins": 100, "min": 0, "max": 0})
def _histogram(ctx, ins, attrs):
    v = x(ins, "X").reshape(-1).astype(jnp.float32)
    lo, hi = float(attrs["min"]), float(attrs["max"])
    if lo == 0 and hi == 0:
        lo, hi = jnp.min(v), jnp.max(v)
    h, _ = jnp.histogram(v, bins=attrs["bins"], range=(lo, hi))
    return out(h.astype(jnp.int64))


@register("masked_select", grad=None, no_grad_slots=("Mask",))
def _masked_select(ctx, ins, attrs):
    """Dynamic-shape op: eager-only (concrete values), like the
    reference's CPU kernel. Under jit the result shape would be
    data-dependent — use where/gather instead there."""
    v, m = x(ins, "X"), x(ins, "Mask")
    if isinstance(v, jax.core.Tracer) or isinstance(m, jax.core.Tracer):
        raise NotImplementedError(
            "masked_select has a data-dependent output shape — not "
            "jittable; use paddle.where or boolean-mask host-side")
    return out(jnp.asarray(np.asarray(v)[np.asarray(m).astype(bool)]))


@register("split_ids", grad=None, attrs={})
def _split_ids(ctx, ins, attrs):
    """Route ids to PS shards by id % n_shards (reference
    distributed_ops/split_ids_op); dense padded outputs."""
    ids = x(ins, "Ids").reshape(-1)
    n = len(ins.get("Out", [])) or attrs.get("num_shards", 1)
    outs = []
    for s in range(n):
        sel = np.asarray(ids)[np.asarray(ids % n) == s] \
            if not isinstance(ids, jax.core.Tracer) else None
        if sel is None:
            raise NotImplementedError("split_ids is an eager/host op")
        outs.append(jnp.asarray(sel))
    return {"Out": outs}


@register("merge_ids", grad=None, attrs={})
def _merge_ids(ctx, ins, attrs):
    """Inverse of split_ids: scatter shard rows back to the original id
    order (reference distributed_ops/merge_ids_op)."""
    ids = x(ins, "Ids").reshape(-1)
    shard_ids = ins.get("X", [])
    rows = ins.get("Rows", [])
    if isinstance(ids, jax.core.Tracer):
        raise NotImplementedError("merge_ids is an eager/host op")
    ids_np = np.asarray(ids)
    D = np.asarray(rows[0]).shape[-1]
    out_np = np.zeros((len(ids_np), D), np.asarray(rows[0]).dtype)
    for sid, r in zip(shard_ids, rows):
        sid_np = np.asarray(sid).reshape(-1)
        r_np = np.asarray(r)
        for i, v in enumerate(sid_np):
            out_np[ids_np == v] = r_np[i]
    return out(jnp.asarray(out_np))
