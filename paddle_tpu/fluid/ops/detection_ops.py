"""Detection ops: IoU, box coding, priors, YOLO decode, RoIAlign, NMS.

TPU-native equivalents of the reference's operators/detection/* —
  iou_similarity_op.cc, box_coder_op.cc, prior_box_op.cc, yolo_box_op.cc,
  roi_align_op.cc, multiclass_nms_op.cc.
Everything is dense/vectorized jnp with STATIC output shapes: NMS returns a
fixed keep_top_k-padded [K, 6] block (invalid rows get label -1) instead of
the reference's LoD output — the LoD-free design of SURVEY §7 applied to
detection heads. RoIAlign is differentiable (auto-vjp through the bilinear
gathers); the decode/NMS tier is inference post-processing (grad=None).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..registry import register
from .common import x


def _iou_matrix(a, b, normalized=True):
    """a [N, 4], b [M, 4] (x1, y1, x2, y2) -> [N, M]."""
    off = 0.0 if normalized else 1.0
    area = lambda q: jnp.maximum(q[:, 2] - q[:, 0] + off, 0.0) * \
        jnp.maximum(q[:, 3] - q[:, 1] + off, 0.0)
    ix1 = jnp.maximum(a[:, None, 0], b[None, :, 0])
    iy1 = jnp.maximum(a[:, None, 1], b[None, :, 1])
    ix2 = jnp.minimum(a[:, None, 2], b[None, :, 2])
    iy2 = jnp.minimum(a[:, None, 3], b[None, :, 3])
    iw = jnp.maximum(ix2 - ix1 + off, 0.0)
    ih = jnp.maximum(iy2 - iy1 + off, 0.0)
    inter = iw * ih
    union = area(a)[:, None] + area(b)[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


@register("iou_similarity", grad=None,
          attrs={"box_normalized": True})
def _iou_similarity(ctx, ins, attrs):
    a, b = x(ins, "X").astype(jnp.float32), x(ins, "Y").astype(jnp.float32)
    return {"Out": [_iou_matrix(a, b, attrs["box_normalized"])]}


@register("box_coder", grad=None, no_grad_slots=("PriorBox", "PriorBoxVar"),
          attrs={"code_type": "encode_center_size", "box_normalized": True,
                 "axis": 0, "variance": []})
def _box_coder(ctx, ins, attrs):
    """SSD box coding (reference box_coder_op.h). encode: corner target
    boxes [N,4] vs priors [M,4] -> [N,M,4] offsets; decode: offsets
    [N,M,4] (or [N,1,4] broadcast) + priors -> corner boxes."""
    prior = x(ins, "PriorBox").astype(jnp.float32)      # [M, 4]
    pvar = x(ins, "PriorBoxVar")
    tb = x(ins, "TargetBox").astype(jnp.float32)
    norm = attrs["box_normalized"]
    off = 0.0 if norm else 1.0
    pw = prior[:, 2] - prior[:, 0] + off
    ph = prior[:, 3] - prior[:, 1] + off
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    if pvar is None and attrs.get("variance"):
        pvar = jnp.asarray(attrs["variance"], jnp.float32)[None, :]
    if attrs["code_type"] == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + off
        th = tb[:, 3] - tb[:, 1] + off
        tcx = tb[:, 0] + tw * 0.5
        tcy = tb[:, 1] + th * 0.5
        ox = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        oy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        ow = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
        oh = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
        out_ = jnp.stack([ox, oy, ow, oh], axis=-1)     # [N, M, 4]
        if pvar is not None:
            out_ = out_ / jnp.broadcast_to(pvar.astype(jnp.float32),
                                           out_.shape)
        return {"OutputBox": [out_]}
    # decode_center_size: TargetBox [N, M, 4]
    t = tb if tb.ndim == 3 else tb[:, None, :]
    if pvar is not None:
        t = t * jnp.broadcast_to(pvar.astype(jnp.float32), t.shape)
    axis = attrs.get("axis", 0)
    # axis 0: priors broadcast over rows; axis 1: over cols
    ex = (None, slice(None)) if axis == 0 else (slice(None), None)
    pw_, ph_, pcx_, pcy_ = (q[ex] for q in (pw, ph, pcx, pcy))
    cx = t[..., 0] * pw_ + pcx_
    cy = t[..., 1] * ph_ + pcy_
    w = jnp.exp(t[..., 2]) * pw_
    h = jnp.exp(t[..., 3]) * ph_
    out_ = jnp.stack([cx - w * 0.5, cy - h * 0.5,
                      cx + w * 0.5 - off, cy + h * 0.5 - off], axis=-1)
    return {"OutputBox": [out_]}


@register("prior_box", grad=None,
          attrs={"min_sizes": [], "max_sizes": [], "aspect_ratios": [1.0],
                 "variances": [0.1, 0.1, 0.2, 0.2], "flip": False,
                 "clip": False, "step_w": 0.0, "step_h": 0.0,
                 "offset": 0.5, "min_max_aspect_ratios_order": False})
def _prior_box(ctx, ins, attrs):
    """SSD anchors (reference prior_box_op.h): one box per
    (min_size x expanded aspect ratio) + sqrt(min*max) per cell."""
    feat = x(ins, "Input")
    img = x(ins, "Image")
    H, W = feat.shape[2], feat.shape[3]
    IH, IW = img.shape[2], img.shape[3]
    step_w = attrs["step_w"] or IW / W
    step_h = attrs["step_h"] or IH / H
    ars = [1.0]
    for ar in attrs["aspect_ratios"]:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(float(ar))
            if attrs["flip"]:
                ars.append(1.0 / float(ar))
    whs = []
    for ms in attrs["min_sizes"]:
        if attrs.get("min_max_aspect_ratios_order"):
            whs.append((ms, ms))
            if attrs["max_sizes"]:
                mx = attrs["max_sizes"][len(whs) and
                                        attrs["min_sizes"].index(ms)]
                whs.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        else:
            for ar in ars:
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            if attrs["max_sizes"]:
                mx = attrs["max_sizes"][attrs["min_sizes"].index(ms)]
                whs.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
    whs = np.asarray(whs, np.float32)                   # [P, 2]
    P = len(whs)
    cx = (np.arange(W, dtype=np.float32) + attrs["offset"]) * step_w
    cy = (np.arange(H, dtype=np.float32) + attrs["offset"]) * step_h
    cxg, cyg = np.meshgrid(cx, cy)                      # [H, W]
    boxes = np.stack([
        (cxg[:, :, None] - whs[None, None, :, 0] / 2) / IW,
        (cyg[:, :, None] - whs[None, None, :, 1] / 2) / IH,
        (cxg[:, :, None] + whs[None, None, :, 0] / 2) / IW,
        (cyg[:, :, None] + whs[None, None, :, 1] / 2) / IH,
    ], axis=-1).astype(np.float32)                      # [H, W, P, 4]
    if attrs["clip"]:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(attrs["variances"], np.float32),
                          boxes.shape).copy()
    return {"Boxes": [jnp.asarray(boxes)], "Variances": [jnp.asarray(var)]}


@register("yolo_box", grad=None, no_grad_slots=("ImgSize",),
          attrs={"anchors": [], "class_num": 1, "conf_thresh": 0.01,
                 "downsample_ratio": 32, "clip_bbox": True,
                 "scale_x_y": 1.0})
def _yolo_box(ctx, ins, attrs):
    """YOLOv3 head decode (reference yolo_box_op.h): X [N, A*(5+C), H, W]
    -> Boxes [N, H*W*A, 4] (x1y1x2y2 in image pixels), Scores
    [N, H*W*A, C]. Boxes under conf_thresh are zeroed like the
    reference."""
    v = x(ins, "X").astype(jnp.float32)
    imgsize = x(ins, "ImgSize").astype(jnp.float32)     # [N, 2] (h, w)
    anchors = np.asarray(attrs["anchors"], np.float32).reshape(-1, 2)
    A = anchors.shape[0]
    C = attrs["class_num"]
    N, _, H, W = v.shape
    ds = attrs["downsample_ratio"]
    sxy = attrs.get("scale_x_y", 1.0)
    bias = -0.5 * (sxy - 1.0)
    v = v.reshape(N, A, 5 + C, H, W)
    gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
    bx = (jax.nn.sigmoid(v[:, :, 0]) * sxy + bias + gx) / W
    by = (jax.nn.sigmoid(v[:, :, 1]) * sxy + bias + gy) / H
    input_w, input_h = W * ds, H * ds
    bw = jnp.exp(v[:, :, 2]) * anchors[None, :, 0, None, None] / input_w
    bh = jnp.exp(v[:, :, 3]) * anchors[None, :, 1, None, None] / input_h
    conf = jax.nn.sigmoid(v[:, :, 4])
    probs = jax.nn.sigmoid(v[:, :, 5:]) * conf[:, :, None]
    imh = imgsize[:, 0][:, None, None, None]
    imw = imgsize[:, 1][:, None, None, None]
    x1 = (bx - bw / 2) * imw
    y1 = (by - bh / 2) * imh
    x2 = (bx + bw / 2) * imw
    y2 = (by + bh / 2) * imh
    if attrs.get("clip_bbox", True):
        x1 = jnp.clip(x1, 0.0, imw - 1)
        y1 = jnp.clip(y1, 0.0, imh - 1)
        x2 = jnp.clip(x2, 0.0, imw - 1)
        y2 = jnp.clip(y2, 0.0, imh - 1)
    keep = (conf > attrs["conf_thresh"]).astype(jnp.float32)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1) * keep[..., None]
    scores = probs * keep[:, :, None]
    # [N, A, H, W, .] -> [N, H*W*A, .] (reference iteration order: an
    # outer, then h, w — kept for parity)
    boxes = boxes.transpose(0, 1, 2, 3, 4).reshape(N, A * H * W, 4)
    scores = scores.transpose(0, 1, 3, 4, 2).reshape(N, A * H * W, C)
    return {"Boxes": [boxes], "Scores": [scores]}


@register("roi_align", no_grad_slots=("ROIs", "RoisNum"),
          attrs={"pooled_height": 1, "pooled_width": 1,
                 "spatial_scale": 1.0, "sampling_ratio": -1,
                 "aligned": False})
def _roi_align(ctx, ins, attrs):
    """RoIAlign (reference roi_align_op.h): average of bilinear samples on
    a regular grid inside each bin. Differentiable via vjp through the
    gathers. ROIs [R, 4] + RoisNum [N] (dense replacement of the LoD
    batch mapping)."""
    feat = x(ins, "X").astype(jnp.float32)              # [N, C, H, W]
    rois = x(ins, "ROIs").astype(jnp.float32)           # [R, 4]
    rois_num = x(ins, "RoisNum")
    N, Cc, H, W = feat.shape
    R = rois.shape[0]
    ph, pw = attrs["pooled_height"], attrs["pooled_width"]
    scale = attrs["spatial_scale"]
    sr = attrs["sampling_ratio"]
    sr = sr if sr > 0 else 2
    aligned = attrs.get("aligned", False)
    roi_off = 0.5 if aligned else 0.0
    if rois_num is not None:
        rn = rois_num.reshape(-1).astype(jnp.int32)
        batch_idx = jnp.repeat(jnp.arange(rn.shape[0]), rn,
                               total_repeat_length=R)
    else:
        batch_idx = jnp.zeros((R,), jnp.int32)

    x1 = rois[:, 0] * scale - roi_off
    y1 = rois[:, 1] * scale - roi_off
    x2 = rois[:, 2] * scale - roi_off
    y2 = rois[:, 3] * scale - roi_off
    rw = x2 - x1
    rh = y2 - y1
    if not aligned:
        rw = jnp.maximum(rw, 1.0)
        rh = jnp.maximum(rh, 1.0)
    bin_w = rw / pw
    bin_h = rh / ph
    # sample grid: [ph, sr] x [pw, sr] offsets per roi
    iy = (jnp.arange(ph)[:, None] + (jnp.arange(sr)[None, :] + 0.5) / sr) \
        .reshape(-1)                                    # [ph*sr]
    ix = (jnp.arange(pw)[:, None] + (jnp.arange(sr)[None, :] + 0.5) / sr) \
        .reshape(-1)                                    # [pw*sr]
    sy = y1[:, None] + iy[None, :] * bin_h[:, None]     # [R, ph*sr]
    sx = x1[:, None] + ix[None, :] * bin_w[:, None]     # [R, pw*sr]

    def bilinear(img, yy, xx):
        """img [C, H, W]; yy [P], xx [Q] -> [C, P, Q]."""
        yy = jnp.clip(yy, 0.0, H - 1.0)
        xx = jnp.clip(xx, 0.0, W - 1.0)
        y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, H - 1)
        x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, W - 1)
        y1_ = jnp.clip(y0 + 1, 0, H - 1)
        x1_ = jnp.clip(x0 + 1, 0, W - 1)
        wy = yy - y0
        wx = xx - x0
        g = lambda yi, xi: img[:, yi][:, :, xi]          # [C, P, Q]
        top = g(y0, x0) * (1 - wx)[None, None, :] \
            + g(y0, x1_) * wx[None, None, :]
        bot = g(y1_, x0) * (1 - wx)[None, None, :] \
            + g(y1_, x1_) * wx[None, None, :]
        return top * (1 - wy)[None, :, None] + bot * wy[None, :, None]

    def one_roi(b, yy, xx):
        img = feat[b]                                   # [C, H, W]
        s = bilinear(img, yy, xx)                       # [C, ph*sr, pw*sr]
        s = s.reshape(Cc, ph, sr, pw, sr)
        return jnp.mean(s, axis=(2, 4))                 # [C, ph, pw]

    out_ = jax.vmap(one_roi)(batch_idx, sy, sx)
    return {"Out": [out_]}


@register("multiclass_nms", grad=None,
          attrs={"score_threshold": 0.05, "nms_top_k": 64,
                 "keep_top_k": 100, "nms_threshold": 0.3, "nms_eta": 1.0,
                 "normalized": True, "background_label": 0})
def _multiclass_nms(ctx, ins, attrs):
    """Greedy per-class NMS with STATIC shapes (reference
    multiclass_nms_op.cc). BBoxes [N, M, 4], Scores [N, C, M] ->
    Out [N, keep_top_k, 6] rows (label, score, x1, y1, x2, y2), padded
    with label -1; NmsRoisNum [N]."""
    boxes = x(ins, "BBoxes").astype(jnp.float32)
    scores = x(ins, "Scores").astype(jnp.float32)
    N, M, _ = boxes.shape
    C = scores.shape[1]
    topk = min(attrs["nms_top_k"], M) if attrs["nms_top_k"] > 0 else M
    keep_k = attrs["keep_top_k"] if attrs["keep_top_k"] > 0 else C * topk
    thr = attrs["score_threshold"]
    nms_thr = attrs["nms_threshold"]
    bg = attrs["background_label"]

    def nms_one_class(sc, bx):
        """sc [M], bx [M, 4] -> kept score [topk] (suppressed -> 0)."""
        val, idx = jax.lax.top_k(sc, topk)
        cand = bx[idx]                                  # [topk, 4]
        iou = _iou_matrix(cand, cand, attrs["normalized"])

        def body(i, alive):
            sup = (iou[i] > nms_thr) & (jnp.arange(topk) > i) & alive[i]
            return alive & ~sup
        alive = jax.lax.fori_loop(0, topk, body,
                                  jnp.ones((topk,), bool))
        keep = alive & (val > thr)
        return jnp.where(keep, val, 0.0), idx

    def one_image(bx, sc):
        per = jax.vmap(lambda c: nms_one_class(sc[c], bx))(jnp.arange(C))
        vals, idxs = per                                 # [C, topk]
        cls = jnp.broadcast_to(jnp.arange(C)[:, None], (C, topk))
        if bg >= 0:
            vals = jnp.where(cls == bg, 0.0, vals)
        flat_v = vals.reshape(-1)
        flat_i = idxs.reshape(-1)
        flat_c = cls.reshape(-1)
        k = min(keep_k, flat_v.shape[0])
        top_v, sel = jax.lax.top_k(flat_v, k)
        out_rows = jnp.concatenate([
            flat_c[sel][:, None].astype(jnp.float32),
            top_v[:, None], bx[flat_i[sel]]], axis=1)    # [k, 6]
        valid = top_v > 0.0
        out_rows = jnp.where(valid[:, None], out_rows,
                             jnp.full((1, 6), -1.0))
        return out_rows, jnp.sum(valid.astype(jnp.int32))

    out_, num = jax.vmap(one_image)(boxes, scores)
    return {"Out": [out_], "Index": [jnp.zeros((1, 1), jnp.int32)],
            "NmsRoisNum": [num]}


# ---------------------------------------------------------------------------
# round-5 detection tier: matrix_nms, bipartite_match, target_assign,
# distribute/collect_fpn_proposals, box_decoder_and_assign
# ---------------------------------------------------------------------------

@register("matrix_nms", grad=None,
          attrs={"background_label": 0, "score_threshold": 0.05,
                 "post_threshold": 0.0, "nms_top_k": 64,
                 "keep_top_k": 100, "normalized": True,
                 "use_gaussian": False, "gaussian_sigma": 2.0})
def _matrix_nms(ctx, ins, attrs):
    """Matrix NMS (detection/matrix_nms_op.cc, SOLOv2): suppression by a
    DECAY MATRIX instead of sequential greedy removal — per class, box i
    keeps score * min_j<i decay(iou_ij, iou_max_j). All-matrix math, so
    unlike greedy NMS it maps perfectly onto the TPU (no sequential
    dependency). Static shapes: Out [N, keep_top_k, 6] padded with
    label -1, RoisNum [N]."""
    boxes = x(ins, "BBoxes").astype(jnp.float32)     # [N, M, 4]
    scores = x(ins, "Scores").astype(jnp.float32)    # [N, C, M]
    N, M, _ = boxes.shape
    C = scores.shape[1]
    bg = int(attrs["background_label"])
    topk = min(int(attrs["nms_top_k"]), M) if attrs["nms_top_k"] > 0 \
        else M
    keep_k = int(attrs["keep_top_k"]) if attrs["keep_top_k"] > 0 \
        else C * topk
    st = float(attrs["score_threshold"])
    pt = float(attrs["post_threshold"])
    sigma = float(attrs["gaussian_sigma"])
    use_g = bool(attrs["use_gaussian"])

    def per_class(sc, bx):                 # sc [M], bx [M, 4]
        sc = jnp.where(sc > st, sc, 0.0)
        order = jnp.argsort(-sc)[:topk]
        s = sc[order]
        b = bx[order]
        iou = _iou_matrix(b, b, attrs.get("normalized", True))
        tri = jnp.tril(iou, k=-1)           # iou(i, j<i)
        iou_max = jnp.max(tri, axis=1)      # max overlap of j vs better
        if use_g:
            # reference decay_score<gaussian>: exp((max^2 - iou^2) * sigma)
            decay = jnp.exp((iou_max[None, :] ** 2 - tri ** 2) * sigma)
        else:
            decay = (1.0 - tri) / jnp.maximum(1.0 - iou_max[None, :],
                                              1e-10)
        decay = jnp.where(
            jnp.arange(topk)[None, :] < jnp.arange(topk)[:, None],
            decay, 1.0)
        ds = jnp.min(decay, axis=1) * s
        ds = jnp.where(ds > pt, ds, 0.0)
        return ds, order

    def per_image(img_i, sc_img, bx_img):  # scalar, [C, M], [M, 4]
        cls_ids = jnp.arange(C)
        dss, orders = jax.vmap(lambda c: per_class(sc_img[c], bx_img))(
            cls_ids)
        valid_cls = (cls_ids != bg)[:, None]
        dss = jnp.where(valid_cls, dss, 0.0)      # [C, topk]
        flat = dss.reshape(-1)
        # pad so Out is ALWAYS [keep_top_k, 6] (the documented static
        # shape) even when C*topk < keep_top_k
        pad = max(keep_k - C * topk, 0)
        flat = jnp.concatenate([flat, jnp.zeros((pad,))])
        sel = jnp.argsort(-flat)[:keep_k]
        cls = (sel // topk).astype(jnp.float32)
        box_idx = jnp.take(
            jnp.concatenate([orders.reshape(-1),
                             jnp.zeros((pad,), orders.dtype)]), sel)
        out_rows = jnp.concatenate(
            [jnp.where(flat[sel] > 0, cls, -1.0)[:, None],
             flat[sel][:, None], bx_img[box_idx]], axis=1)
        # Index rows carry the per-image batch offset (reference:
        # start = i * num_boxes) so a flat [N*M, 4] gather works
        return out_rows, (flat[sel] > 0).sum().astype(jnp.int32), \
            (img_i * M + box_idx).astype(jnp.int32)

    rows, nums, idx = jax.vmap(per_image)(jnp.arange(N), scores, boxes)
    return {"Out": [rows], "Index": [idx.reshape(-1, 1)],
            "RoisNum": [nums]}


@register("bipartite_match", grad=None,
          attrs={"match_type": "bipartite", "dist_threshold": 0.5})
def _bipartite_match(ctx, ins, attrs):
    """Greedy global bipartite matching (detection/bipartite_match_op.cc):
    repeatedly take the largest remaining (row, col) entry, binding one
    row to one col, min(R, C) rounds via fori_loop; optional
    per_prediction pass assigns remaining cols whose best dist >=
    threshold. DistMat [N, R, C] dense (LoD batch in the reference) ->
    ColToRowMatchIndices / ColToRowMatchDist [N, C]."""
    dist = x(ins, "DistMat").astype(jnp.float32)
    if dist.ndim == 2:
        dist = dist[None]
    N, R, C = dist.shape
    per_pred = attrs.get("match_type", "bipartite") == "per_prediction"
    thr = float(attrs.get("dist_threshold", 0.5))

    def one(d):
        eps = 1e-6

        def body(_, carry):
            match, mdist, mask = carry
            flat = jnp.where(mask, d, -jnp.inf).reshape(-1)
            k = jnp.argmax(flat)
            i, j = k // C, k % C
            # zero-distance pairs stay UNMATCHED (reference skips
            # dist < kEPS)
            ok = flat[k] > eps
            match = jnp.where(ok, match.at[j].set(i.astype(jnp.int32)),
                              match)
            mdist = jnp.where(ok, mdist.at[j].set(d[i, j]), mdist)
            mask = jnp.where(ok, mask.at[i, :].set(False), mask)
            mask = jnp.where(ok, mask.at[:, j].set(False), mask)
            return match, mdist, mask

        init = (jnp.full((C,), -1, jnp.int32), jnp.zeros((C,)),
                jnp.ones((R, C), bool))
        match, mdist, _ = jax.lax.fori_loop(0, min(R, C), body, init)
        if per_pred:
            best = jnp.max(d, axis=0)
            arg = jnp.argmax(d, axis=0).astype(jnp.int32)
            fill = (match == -1) & (best >= thr) & (best > eps)
            match = jnp.where(fill, arg, match)
            mdist = jnp.where(fill, best, mdist)
        return match, mdist

    match, mdist = jax.vmap(one)(dist)
    return {"ColToRowMatchIndices": [match],
            "ColToRowMatchDist": [mdist.astype(jnp.float32)]}


@register("target_assign", grad=None,
          no_grad_slots=("MatchIndices", "NegIndices"),
          attrs={"mismatch_value": 0})
def _target_assign(ctx, ins, attrs):
    """detection/target_assign_op.h over the dense design: X [N, L, K]
    per-image candidate targets, MatchIndices [N, M] (-1 = unmatched) ->
    Out [N, M, K] gathered rows (mismatch_value where unmatched),
    OutWeight [N, M, 1]. NegIndices [N, Q] rows additionally get weight
    1 with the mismatch value (negative mining)."""
    v = x(ins, "X")
    mi = x(ins, "MatchIndices").astype(jnp.int32)     # [N, M]
    mv = attrs.get("mismatch_value", 0)
    N, M = mi.shape
    K = v.shape[-1]
    matched = mi >= 0
    gathered = jnp.take_along_axis(
        v, jnp.clip(mi, 0, v.shape[1] - 1)[..., None], axis=1)
    outv = jnp.where(matched[..., None], gathered,
                     jnp.asarray(mv, v.dtype))
    w = matched.astype(jnp.float32)[..., None]
    neg = x(ins, "NegIndices")
    if neg is not None:
        neg = neg.astype(jnp.int32)
        hit = (jnp.arange(M)[None, :, None]
               == neg[:, None, :]).any(-1)             # [N, M]
        outv = jnp.where(hit[..., None], jnp.asarray(mv, v.dtype), outv)
        w = jnp.maximum(w, hit.astype(jnp.float32)[..., None])
    return {"Out": [outv], "OutWeight": [w]}


@register("distribute_fpn_proposals", grad=None,
          attrs={"min_level": 2, "max_level": 5, "refer_level": 4,
                 "refer_scale": 224, "pixel_offset": True})
def _distribute_fpn_proposals(ctx, ins, attrs):
    """detection/distribute_fpn_proposals_op.cc: route each RoI to the
    FPN level floor(log2(sqrt(area)/refer_scale)) + refer_level, clipped
    to [min, max]. Static shapes: every per-level output is [R, 4] with
    that level's rois compacted to the front (stable order) and
    MultiLevelRoIsNum giving the live counts; RestoreIndex maps the
    level-sorted order back to the input order."""
    rois = x(ins, "FpnRois").astype(jnp.float32)      # [R, 4]
    lo, hi = int(attrs["min_level"]), int(attrs["max_level"])
    refer_l, refer_s = int(attrs["refer_level"]), int(attrs["refer_scale"])
    off = 1.0 if attrs.get("pixel_offset", True) else 0.0
    R = rois.shape[0]
    w = rois[:, 2] - rois[:, 0] + off
    h = rois[:, 3] - rois[:, 1] + off
    scale = jnp.sqrt(jnp.maximum(w * h, 1e-10))
    lvl = jnp.floor(jnp.log2(scale / refer_s + 1e-6)) + refer_l
    lvl = jnp.clip(lvl, lo, hi).astype(jnp.int32)
    order = jnp.argsort(lvl, stable=True)             # level-major
    restore = jnp.argsort(order, stable=True).astype(jnp.int32)
    outs = {"RestoreIndex": [restore.reshape(-1, 1)]}
    multi, nums = [], []
    for level in range(lo, hi + 1):
        m = lvl == level
        cnt = m.sum().astype(jnp.int32)
        sel = jnp.argsort(~m, stable=True)            # level rois first
        padded = jnp.where((jnp.arange(R) < cnt)[:, None], rois[sel],
                           0.0)
        multi.append(padded)
        nums.append(cnt.reshape(1))
    outs["MultiFpnRois"] = multi
    # one RoisNum var PER LEVEL (matches the op's plural output slot);
    # a single declared output still works — it receives level-min's
    outs["MultiLevelRoIsNum"] = nums
    return outs


@register("collect_fpn_proposals", grad=None,
          no_grad_slots=("MultiLevelRoIsNum",),
          attrs={"post_nms_topN": 100})
def _collect_fpn_proposals(ctx, ins, attrs):
    """detection/collect_fpn_proposals_op.cc: concat per-level rois +
    scores, keep the post_nms_topN best by score. MultiLevelRois list of
    [Ri, 4], MultiLevelScores list of [Ri, 1]; the optional per-level
    MultiLevelRoIsNum marks the LIVE prefix of each level (the static
    padding distribute_fpn_proposals emits) — dead rows never reach the
    top-k and RoisNum reports the live count."""
    level_rois = [r.astype(jnp.float32)
                  for r in ins.get("MultiLevelRois", [])]
    level_scores = [s.astype(jnp.float32).reshape(-1)
                    for s in ins.get("MultiLevelScores", [])]
    rois = jnp.concatenate(level_rois, 0)
    scores = jnp.concatenate(level_scores, 0)
    nums = ins.get("MultiLevelRoIsNum")
    if nums:
        live = jnp.concatenate([
            jnp.arange(r.shape[0]) < n.reshape(()).astype(jnp.int32)
            for r, n in zip(level_rois, nums)])
        scores = jnp.where(live, scores, -jnp.inf)
    k = min(int(attrs["post_nms_topN"]), scores.shape[0])
    sel = jnp.argsort(-scores)[:k]
    n_live = (scores[sel] > -jnp.inf).sum().astype(jnp.int32)
    return {"FpnRois": [rois[sel]],
            "RoisNum": [n_live.reshape(1)]}


@register("box_decoder_and_assign", grad=None,
          no_grad_slots=("PriorBox", "PriorBoxVar"),
          attrs={"box_clip": 4.135166556742356})
def _box_decoder_and_assign(ctx, ins, attrs):
    """detection/box_decoder_and_assign_op.cc: decode per-class deltas
    against the prior (center-size form, variance-scaled, dw/dh clipped
    at box_clip) and assign each roi the decoded box of its best
    non-background class."""
    prior = x(ins, "PriorBox").astype(jnp.float32)     # [M, 4]
    pvar = x(ins, "PriorBoxVar")
    tb = x(ins, "TargetBox").astype(jnp.float32)       # [M, 4*C]
    sc = x(ins, "BoxScore").astype(jnp.float32)        # [M, C]
    M = prior.shape[0]
    C = sc.shape[1]
    clip = float(attrs.get("box_clip", 4.135166556742356))
    pw = prior[:, 2] - prior[:, 0] + 1.0
    ph = prior[:, 3] - prior[:, 1] + 1.0
    pcx = prior[:, 0] + 0.5 * pw
    pcy = prior[:, 1] + 0.5 * ph
    d = tb.reshape(M, C, 4)
    if pvar is not None:
        d = d * pvar.astype(jnp.float32).reshape(1, 1, 4)
    dx, dy, dw, dh = d[..., 0], d[..., 1], d[..., 2], d[..., 3]
    dw = jnp.clip(dw, -clip, clip)
    dh = jnp.clip(dh, -clip, clip)
    cx = dx * pw[:, None] + pcx[:, None]
    cy = dy * ph[:, None] + pcy[:, None]
    w = jnp.exp(dw) * pw[:, None]
    h = jnp.exp(dh) * ph[:, None]
    dec = jnp.stack([cx - 0.5 * w, cy - 0.5 * h,
                     cx + 0.5 * w - 1.0, cy + 0.5 * h - 1.0], axis=-1)
    best = jnp.argmax(sc[:, 1:], axis=1) + 1           # skip background
    assign = jnp.take_along_axis(
        dec, best[:, None, None].repeat(4, -1), axis=1)[:, 0]
    return {"DecodeBox": [dec.reshape(M, C * 4)],
            "OutputAssignBox": [assign]}


@register("mine_hard_examples", grad=None,
          no_grad_slots=("MatchIndices", "MatchDist"),
          attrs={"neg_pos_ratio": 3.0, "neg_dist_threshold": 0.5,
                 "sample_size": 0, "mining_type": "max_negative"})
def _mine_hard_examples(ctx, ins, attrs):
    """SSD OHEM (detection/mine_hard_examples_op.cc): rank eligible
    priors by loss, keep the hardest negatives — max_negative caps at
    neg_pos_ratio x positives, hard_example at sample_size (and demotes
    unselected positives). Dense outputs: NegIndices [N, P] compacted,
    -1 padded, NegRoisNum live counts, UpdatedMatchIndices [N, P]."""
    cls = x(ins, "ClsLoss").astype(jnp.float32)        # [N, P]
    loc = x(ins, "LocLoss")
    mi = x(ins, "MatchIndices").astype(jnp.int32)      # [N, P]
    dist = x(ins, "MatchDist").astype(jnp.float32)
    kind = attrs.get("mining_type", "max_negative")
    ratio = float(attrs.get("neg_pos_ratio", 3.0))
    ndt = float(attrs.get("neg_dist_threshold", 0.5))
    ssz = int(attrs.get("sample_size", 0))
    if kind == "hard_example" and ssz <= 0:
        # reference PADDLE_ENFORCE_GT(sample_size, 0): selecting nothing
        # would silently demote every positive
        raise ValueError(
            "mine_hard_examples: mining_type='hard_example' requires "
            "sample_size > 0")
    N, P = mi.shape
    loss = cls
    if kind == "hard_example" and loc is not None:
        loss = cls + loc.astype(jnp.float32)
    if kind == "max_negative":
        elig = (mi == -1) & (dist < ndt)
    else:
        elig = jnp.ones_like(mi, bool)
    masked = jnp.where(elig, loss, -jnp.inf)
    order = jnp.argsort(-masked, axis=1)               # hardest first
    rank = jnp.argsort(order, axis=1)                  # rank per prior
    n_elig = elig.sum(axis=1)
    if kind == "max_negative":
        n_pos = (mi != -1).sum(axis=1)
        n_sel = jnp.minimum((n_pos * ratio).astype(jnp.int32), n_elig)
    else:
        n_sel = jnp.minimum(ssz, n_elig).astype(jnp.int32)
    selected = elig & (rank < n_sel[:, None])
    neg = selected & (mi == -1)
    # compact negative indices to the front, -1 padded
    neg_order = jnp.argsort(~neg, axis=1, stable=True)
    n_neg = neg.sum(axis=1).astype(jnp.int32)
    neg_idx = jnp.where(jnp.arange(P)[None, :] < n_neg[:, None],
                        neg_order, -1).astype(jnp.int32)
    upd = mi
    if kind == "hard_example":
        upd = jnp.where((mi > -1) & ~selected, -1, mi)
    return {"NegIndices": [neg_idx], "NegRoisNum": [n_neg],
            "UpdatedMatchIndices": [upd]}


@register("retinanet_detection_output", grad=None,
          no_grad_slots=("Anchors", "ImInfo"),
          attrs={"score_threshold": 0.05, "nms_top_k": 1000,
                 "keep_top_k": 100, "nms_threshold": 0.3,
                 "nms_eta": 1.0})
def _retinanet_detection_output(ctx, ins, attrs):
    """RetinaNet head postprocess (detection/
    retinanet_detection_output_op.cc): per FPN level, keep the
    nms_top_k best (anchor, class) scores above score_threshold, decode
    the deltas against the level's anchors (center-size, +1 pixel
    convention, im_scale unscaling, image clip), pool levels and run the
    class-wise greedy NMS via the multiclass_nms kernel. Sigmoid scores,
    no background column; Out [N, keep_top_k, 6] padded label -1."""
    from ..registry import require
    bbox_levels = [b.astype(jnp.float32) for b in ins.get("BBoxes", [])]
    score_levels = [s.astype(jnp.float32) for s in ins.get("Scores", [])]
    anchor_levels = [a.astype(jnp.float32).reshape(-1, 4)
                     for a in ins.get("Anchors", [])]
    iminfo = x(ins, "ImInfo").astype(jnp.float32)      # [N, 3] h, w, scale
    st = float(attrs["score_threshold"])
    topk = int(attrs["nms_top_k"])

    def decode_level(deltas, anchors, info):
        # deltas [M, 4], anchors [M, 4]
        ih = jnp.round(info[0] / info[2])
        iw = jnp.round(info[1] / info[2])
        aw = anchors[:, 2] - anchors[:, 0] + 1.0
        ah = anchors[:, 3] - anchors[:, 1] + 1.0
        acx = anchors[:, 0] + aw / 2
        acy = anchors[:, 1] + ah / 2
        cx = deltas[:, 0] * aw + acx
        cy = deltas[:, 1] * ah + acy
        w = jnp.exp(deltas[:, 2]) * aw
        h = jnp.exp(deltas[:, 3]) * ah
        box = jnp.stack([cx - w / 2, cy - h / 2,
                         cx + w / 2 - 1, cy + h / 2 - 1], -1) / info[2]
        lo = jnp.zeros((4,))
        hi = jnp.stack([iw - 1, ih - 1, iw - 1, ih - 1])
        return jnp.clip(box, lo, hi)

    def per_image(boxes_i, scores_i, info):
        all_boxes, all_scores = [], []
        for deltas, sc, anchors in zip(boxes_i, scores_i, anchor_levels):
            dec = decode_level(deltas, anchors, info)      # [M, 4]
            scm = jnp.where(sc > st, sc, 0.0)              # [M, C]
            k = min(topk, scm.size)
            flat = scm.reshape(-1)
            sel = jnp.argsort(-flat)[:k]
            a_idx = sel // scm.shape[1]
            all_boxes.append(dec[a_idx])
            all_scores.append(
                flat[sel][:, None]        # sub-threshold entries are 0
                * jax.nn.one_hot(sel % scm.shape[1], scm.shape[1]))
        return jnp.concatenate(all_boxes, 0), \
            jnp.concatenate(all_scores, 0).T               # [C, total]

    # one vmapped pass over the batch (multiclass_nms is batch-vmapped
    # itself — per-image python calls would trace the NMS N times)
    bx, sc = jax.vmap(per_image)(
        tuple(bbox_levels), tuple(score_levels), iminfo)
    nms = require("multiclass_nms")
    r = nms.compute(ctx, {"BBoxes": [bx], "Scores": [sc]},
                    {"score_threshold": st,
                     "nms_top_k": topk,
                     "keep_top_k": int(attrs["keep_top_k"]),
                     "nms_threshold": float(attrs["nms_threshold"]),
                     "nms_eta": float(attrs["nms_eta"]),
                     "normalized": False,
                     "background_label": -1})
    return {"Out": [r["Out"][0]],
            "NmsedNum": [jnp.asarray(r["NmsRoisNum"][0]).reshape(-1)]}
