"""Detection ops: IoU, box coding, priors, YOLO decode, RoIAlign, NMS.

TPU-native equivalents of the reference's operators/detection/* —
  iou_similarity_op.cc, box_coder_op.cc, prior_box_op.cc, yolo_box_op.cc,
  roi_align_op.cc, multiclass_nms_op.cc.
Everything is dense/vectorized jnp with STATIC output shapes: NMS returns a
fixed keep_top_k-padded [K, 6] block (invalid rows get label -1) instead of
the reference's LoD output — the LoD-free design of SURVEY §7 applied to
detection heads. RoIAlign is differentiable (auto-vjp through the bilinear
gathers); the decode/NMS tier is inference post-processing (grad=None).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..registry import register
from .common import x


def _iou_matrix(a, b, normalized=True):
    """a [N, 4], b [M, 4] (x1, y1, x2, y2) -> [N, M]."""
    off = 0.0 if normalized else 1.0
    area = lambda q: jnp.maximum(q[:, 2] - q[:, 0] + off, 0.0) * \
        jnp.maximum(q[:, 3] - q[:, 1] + off, 0.0)
    ix1 = jnp.maximum(a[:, None, 0], b[None, :, 0])
    iy1 = jnp.maximum(a[:, None, 1], b[None, :, 1])
    ix2 = jnp.minimum(a[:, None, 2], b[None, :, 2])
    iy2 = jnp.minimum(a[:, None, 3], b[None, :, 3])
    iw = jnp.maximum(ix2 - ix1 + off, 0.0)
    ih = jnp.maximum(iy2 - iy1 + off, 0.0)
    inter = iw * ih
    union = area(a)[:, None] + area(b)[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


@register("iou_similarity", grad=None,
          attrs={"box_normalized": True})
def _iou_similarity(ctx, ins, attrs):
    a, b = x(ins, "X").astype(jnp.float32), x(ins, "Y").astype(jnp.float32)
    return {"Out": [_iou_matrix(a, b, attrs["box_normalized"])]}


@register("box_coder", grad=None, no_grad_slots=("PriorBox", "PriorBoxVar"),
          attrs={"code_type": "encode_center_size", "box_normalized": True,
                 "axis": 0, "variance": []})
def _box_coder(ctx, ins, attrs):
    """SSD box coding (reference box_coder_op.h). encode: corner target
    boxes [N,4] vs priors [M,4] -> [N,M,4] offsets; decode: offsets
    [N,M,4] (or [N,1,4] broadcast) + priors -> corner boxes."""
    prior = x(ins, "PriorBox").astype(jnp.float32)      # [M, 4]
    pvar = x(ins, "PriorBoxVar")
    tb = x(ins, "TargetBox").astype(jnp.float32)
    norm = attrs["box_normalized"]
    off = 0.0 if norm else 1.0
    pw = prior[:, 2] - prior[:, 0] + off
    ph = prior[:, 3] - prior[:, 1] + off
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    if pvar is None and attrs.get("variance"):
        pvar = jnp.asarray(attrs["variance"], jnp.float32)[None, :]
    if attrs["code_type"] == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + off
        th = tb[:, 3] - tb[:, 1] + off
        tcx = tb[:, 0] + tw * 0.5
        tcy = tb[:, 1] + th * 0.5
        ox = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        oy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        ow = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
        oh = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
        out_ = jnp.stack([ox, oy, ow, oh], axis=-1)     # [N, M, 4]
        if pvar is not None:
            out_ = out_ / jnp.broadcast_to(pvar.astype(jnp.float32),
                                           out_.shape)
        return {"OutputBox": [out_]}
    # decode_center_size: TargetBox [N, M, 4]
    t = tb if tb.ndim == 3 else tb[:, None, :]
    if pvar is not None:
        t = t * jnp.broadcast_to(pvar.astype(jnp.float32), t.shape)
    axis = attrs.get("axis", 0)
    # axis 0: priors broadcast over rows; axis 1: over cols
    ex = (None, slice(None)) if axis == 0 else (slice(None), None)
    pw_, ph_, pcx_, pcy_ = (q[ex] for q in (pw, ph, pcx, pcy))
    cx = t[..., 0] * pw_ + pcx_
    cy = t[..., 1] * ph_ + pcy_
    w = jnp.exp(t[..., 2]) * pw_
    h = jnp.exp(t[..., 3]) * ph_
    out_ = jnp.stack([cx - w * 0.5, cy - h * 0.5,
                      cx + w * 0.5 - off, cy + h * 0.5 - off], axis=-1)
    return {"OutputBox": [out_]}


@register("prior_box", grad=None,
          attrs={"min_sizes": [], "max_sizes": [], "aspect_ratios": [1.0],
                 "variances": [0.1, 0.1, 0.2, 0.2], "flip": False,
                 "clip": False, "step_w": 0.0, "step_h": 0.0,
                 "offset": 0.5, "min_max_aspect_ratios_order": False})
def _prior_box(ctx, ins, attrs):
    """SSD anchors (reference prior_box_op.h): one box per
    (min_size x expanded aspect ratio) + sqrt(min*max) per cell."""
    feat = x(ins, "Input")
    img = x(ins, "Image")
    H, W = feat.shape[2], feat.shape[3]
    IH, IW = img.shape[2], img.shape[3]
    step_w = attrs["step_w"] or IW / W
    step_h = attrs["step_h"] or IH / H
    ars = [1.0]
    for ar in attrs["aspect_ratios"]:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(float(ar))
            if attrs["flip"]:
                ars.append(1.0 / float(ar))
    whs = []
    for ms in attrs["min_sizes"]:
        if attrs.get("min_max_aspect_ratios_order"):
            whs.append((ms, ms))
            if attrs["max_sizes"]:
                mx = attrs["max_sizes"][len(whs) and
                                        attrs["min_sizes"].index(ms)]
                whs.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        else:
            for ar in ars:
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            if attrs["max_sizes"]:
                mx = attrs["max_sizes"][attrs["min_sizes"].index(ms)]
                whs.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
    whs = np.asarray(whs, np.float32)                   # [P, 2]
    P = len(whs)
    cx = (np.arange(W, dtype=np.float32) + attrs["offset"]) * step_w
    cy = (np.arange(H, dtype=np.float32) + attrs["offset"]) * step_h
    cxg, cyg = np.meshgrid(cx, cy)                      # [H, W]
    boxes = np.stack([
        (cxg[:, :, None] - whs[None, None, :, 0] / 2) / IW,
        (cyg[:, :, None] - whs[None, None, :, 1] / 2) / IH,
        (cxg[:, :, None] + whs[None, None, :, 0] / 2) / IW,
        (cyg[:, :, None] + whs[None, None, :, 1] / 2) / IH,
    ], axis=-1).astype(np.float32)                      # [H, W, P, 4]
    if attrs["clip"]:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(attrs["variances"], np.float32),
                          boxes.shape).copy()
    return {"Boxes": [jnp.asarray(boxes)], "Variances": [jnp.asarray(var)]}


@register("yolo_box", grad=None, no_grad_slots=("ImgSize",),
          attrs={"anchors": [], "class_num": 1, "conf_thresh": 0.01,
                 "downsample_ratio": 32, "clip_bbox": True,
                 "scale_x_y": 1.0})
def _yolo_box(ctx, ins, attrs):
    """YOLOv3 head decode (reference yolo_box_op.h): X [N, A*(5+C), H, W]
    -> Boxes [N, H*W*A, 4] (x1y1x2y2 in image pixels), Scores
    [N, H*W*A, C]. Boxes under conf_thresh are zeroed like the
    reference."""
    v = x(ins, "X").astype(jnp.float32)
    imgsize = x(ins, "ImgSize").astype(jnp.float32)     # [N, 2] (h, w)
    anchors = np.asarray(attrs["anchors"], np.float32).reshape(-1, 2)
    A = anchors.shape[0]
    C = attrs["class_num"]
    N, _, H, W = v.shape
    ds = attrs["downsample_ratio"]
    sxy = attrs.get("scale_x_y", 1.0)
    bias = -0.5 * (sxy - 1.0)
    v = v.reshape(N, A, 5 + C, H, W)
    gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
    bx = (jax.nn.sigmoid(v[:, :, 0]) * sxy + bias + gx) / W
    by = (jax.nn.sigmoid(v[:, :, 1]) * sxy + bias + gy) / H
    input_w, input_h = W * ds, H * ds
    bw = jnp.exp(v[:, :, 2]) * anchors[None, :, 0, None, None] / input_w
    bh = jnp.exp(v[:, :, 3]) * anchors[None, :, 1, None, None] / input_h
    conf = jax.nn.sigmoid(v[:, :, 4])
    probs = jax.nn.sigmoid(v[:, :, 5:]) * conf[:, :, None]
    imh = imgsize[:, 0][:, None, None, None]
    imw = imgsize[:, 1][:, None, None, None]
    x1 = (bx - bw / 2) * imw
    y1 = (by - bh / 2) * imh
    x2 = (bx + bw / 2) * imw
    y2 = (by + bh / 2) * imh
    if attrs.get("clip_bbox", True):
        x1 = jnp.clip(x1, 0.0, imw - 1)
        y1 = jnp.clip(y1, 0.0, imh - 1)
        x2 = jnp.clip(x2, 0.0, imw - 1)
        y2 = jnp.clip(y2, 0.0, imh - 1)
    keep = (conf > attrs["conf_thresh"]).astype(jnp.float32)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1) * keep[..., None]
    scores = probs * keep[:, :, None]
    # [N, A, H, W, .] -> [N, H*W*A, .] (reference iteration order: an
    # outer, then h, w — kept for parity)
    boxes = boxes.transpose(0, 1, 2, 3, 4).reshape(N, A * H * W, 4)
    scores = scores.transpose(0, 1, 3, 4, 2).reshape(N, A * H * W, C)
    return {"Boxes": [boxes], "Scores": [scores]}


@register("roi_align", no_grad_slots=("ROIs", "RoisNum"),
          attrs={"pooled_height": 1, "pooled_width": 1,
                 "spatial_scale": 1.0, "sampling_ratio": -1,
                 "aligned": False})
def _roi_align(ctx, ins, attrs):
    """RoIAlign (reference roi_align_op.h): average of bilinear samples on
    a regular grid inside each bin. Differentiable via vjp through the
    gathers. ROIs [R, 4] + RoisNum [N] (dense replacement of the LoD
    batch mapping)."""
    feat = x(ins, "X").astype(jnp.float32)              # [N, C, H, W]
    rois = x(ins, "ROIs").astype(jnp.float32)           # [R, 4]
    rois_num = x(ins, "RoisNum")
    N, Cc, H, W = feat.shape
    R = rois.shape[0]
    ph, pw = attrs["pooled_height"], attrs["pooled_width"]
    scale = attrs["spatial_scale"]
    sr = attrs["sampling_ratio"]
    sr = sr if sr > 0 else 2
    aligned = attrs.get("aligned", False)
    roi_off = 0.5 if aligned else 0.0
    if rois_num is not None:
        rn = rois_num.reshape(-1).astype(jnp.int32)
        batch_idx = jnp.repeat(jnp.arange(rn.shape[0]), rn,
                               total_repeat_length=R)
    else:
        batch_idx = jnp.zeros((R,), jnp.int32)

    x1 = rois[:, 0] * scale - roi_off
    y1 = rois[:, 1] * scale - roi_off
    x2 = rois[:, 2] * scale - roi_off
    y2 = rois[:, 3] * scale - roi_off
    rw = x2 - x1
    rh = y2 - y1
    if not aligned:
        rw = jnp.maximum(rw, 1.0)
        rh = jnp.maximum(rh, 1.0)
    bin_w = rw / pw
    bin_h = rh / ph
    # sample grid: [ph, sr] x [pw, sr] offsets per roi
    iy = (jnp.arange(ph)[:, None] + (jnp.arange(sr)[None, :] + 0.5) / sr) \
        .reshape(-1)                                    # [ph*sr]
    ix = (jnp.arange(pw)[:, None] + (jnp.arange(sr)[None, :] + 0.5) / sr) \
        .reshape(-1)                                    # [pw*sr]
    sy = y1[:, None] + iy[None, :] * bin_h[:, None]     # [R, ph*sr]
    sx = x1[:, None] + ix[None, :] * bin_w[:, None]     # [R, pw*sr]

    def bilinear(img, yy, xx):
        """img [C, H, W]; yy [P], xx [Q] -> [C, P, Q]."""
        yy = jnp.clip(yy, 0.0, H - 1.0)
        xx = jnp.clip(xx, 0.0, W - 1.0)
        y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, H - 1)
        x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, W - 1)
        y1_ = jnp.clip(y0 + 1, 0, H - 1)
        x1_ = jnp.clip(x0 + 1, 0, W - 1)
        wy = yy - y0
        wx = xx - x0
        g = lambda yi, xi: img[:, yi][:, :, xi]          # [C, P, Q]
        top = g(y0, x0) * (1 - wx)[None, None, :] \
            + g(y0, x1_) * wx[None, None, :]
        bot = g(y1_, x0) * (1 - wx)[None, None, :] \
            + g(y1_, x1_) * wx[None, None, :]
        return top * (1 - wy)[None, :, None] + bot * wy[None, :, None]

    def one_roi(b, yy, xx):
        img = feat[b]                                   # [C, H, W]
        s = bilinear(img, yy, xx)                       # [C, ph*sr, pw*sr]
        s = s.reshape(Cc, ph, sr, pw, sr)
        return jnp.mean(s, axis=(2, 4))                 # [C, ph, pw]

    out_ = jax.vmap(one_roi)(batch_idx, sy, sx)
    return {"Out": [out_]}


@register("multiclass_nms", grad=None,
          attrs={"score_threshold": 0.05, "nms_top_k": 64,
                 "keep_top_k": 100, "nms_threshold": 0.3, "nms_eta": 1.0,
                 "normalized": True, "background_label": 0})
def _multiclass_nms(ctx, ins, attrs):
    """Greedy per-class NMS with STATIC shapes (reference
    multiclass_nms_op.cc). BBoxes [N, M, 4], Scores [N, C, M] ->
    Out [N, keep_top_k, 6] rows (label, score, x1, y1, x2, y2), padded
    with label -1; NmsRoisNum [N]."""
    boxes = x(ins, "BBoxes").astype(jnp.float32)
    scores = x(ins, "Scores").astype(jnp.float32)
    N, M, _ = boxes.shape
    C = scores.shape[1]
    topk = min(attrs["nms_top_k"], M) if attrs["nms_top_k"] > 0 else M
    keep_k = attrs["keep_top_k"] if attrs["keep_top_k"] > 0 else C * topk
    thr = attrs["score_threshold"]
    nms_thr = attrs["nms_threshold"]
    bg = attrs["background_label"]

    def nms_one_class(sc, bx):
        """sc [M], bx [M, 4] -> kept score [topk] (suppressed -> 0)."""
        val, idx = jax.lax.top_k(sc, topk)
        cand = bx[idx]                                  # [topk, 4]
        iou = _iou_matrix(cand, cand, attrs["normalized"])

        def body(i, alive):
            sup = (iou[i] > nms_thr) & (jnp.arange(topk) > i) & alive[i]
            return alive & ~sup
        alive = jax.lax.fori_loop(0, topk, body,
                                  jnp.ones((topk,), bool))
        keep = alive & (val > thr)
        return jnp.where(keep, val, 0.0), idx

    def one_image(bx, sc):
        per = jax.vmap(lambda c: nms_one_class(sc[c], bx))(jnp.arange(C))
        vals, idxs = per                                 # [C, topk]
        cls = jnp.broadcast_to(jnp.arange(C)[:, None], (C, topk))
        if bg >= 0:
            vals = jnp.where(cls == bg, 0.0, vals)
        flat_v = vals.reshape(-1)
        flat_i = idxs.reshape(-1)
        flat_c = cls.reshape(-1)
        k = min(keep_k, flat_v.shape[0])
        top_v, sel = jax.lax.top_k(flat_v, k)
        out_rows = jnp.concatenate([
            flat_c[sel][:, None].astype(jnp.float32),
            top_v[:, None], bx[flat_i[sel]]], axis=1)    # [k, 6]
        valid = top_v > 0.0
        out_rows = jnp.where(valid[:, None], out_rows,
                             jnp.full((1, 6), -1.0))
        return out_rows, jnp.sum(valid.astype(jnp.int32))

    out_, num = jax.vmap(one_image)(boxes, scores)
    return {"Out": [out_], "Index": [jnp.zeros((1, 1), jnp.int32)],
            "NmsRoisNum": [num]}
