"""Tensor manipulation + fill ops.

Replaces reference operators: reshape/squeeze/unsqueeze/transpose/concat/
split/stack/slice/gather/scatter/expand/tile/... and fill_constant family
(/root/reference/paddle/fluid/operators/, SURVEY §2.3 "Tensor manipulation").
XLA handles these as free layout ops or fused gathers.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..registry import register, same_shape_as
from .common import x, out, np_dtype


# ---------------------------------------------------------------------------
# shape manipulation
# ---------------------------------------------------------------------------

def _resolve_shape(shape, total):
    shape = list(shape)
    if -1 in shape:
        i = shape.index(-1)
        known = int(np.prod([s for s in shape if s != -1])) or 1
        shape[i] = total // known
    return shape


def _reshape_infer(op):
    v = op.invar("X")
    if v is None or v.shape is None:
        return
    shape = list(op.attr("shape", []))
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = v.shape[i]
    if -1 in shape and all(s >= 0 for s in v.shape):
        total = int(np.prod(v.shape))
        shape = _resolve_shape(shape, total)
    for name in op.output("Out"):
        op.block.create_var(name=name, shape=tuple(shape), dtype=v.dtype)


def _reshape(ctx, ins, attrs):
    v = x(ins)
    st = x(ins, "ShapeTensor") or x(ins, "Shape")
    shape = list(attrs.get("shape", []))
    if st is not None:
        shape = [int(s) for s in np.asarray(st)]
    shape = [v.shape[i] if s == 0 else s for i, s in enumerate(shape)] \
        if 0 in shape else shape
    return {"Out": [v.reshape(shape)], "XShape": [None]}


register("reshape2", _reshape, infer_shape=_reshape_infer,
         attrs={"shape": []}, no_grad_out_slots=("XShape",))
register("reshape", _reshape, infer_shape=_reshape_infer, attrs={"shape": []})


def _transpose_infer(op):
    v = op.invar("X")
    if v is None or v.shape is None:
        return
    perm = op.attr("axis", [])
    shape = tuple(v.shape[p] for p in perm)
    for name in op.output("Out"):
        op.block.create_var(name=name, shape=shape, dtype=v.dtype)


def _transpose(ctx, ins, attrs):
    return {"Out": [jnp.transpose(x(ins), attrs["axis"])], "XShape": [None]}


register("transpose2", _transpose, infer_shape=_transpose_infer,
         attrs={"axis": []}, no_grad_out_slots=("XShape",))
register("transpose", _transpose, infer_shape=_transpose_infer,
         attrs={"axis": []})


def _squeeze_infer(op):
    v = op.invar("X")
    if v is None or v.shape is None:
        return
    axes = op.attr("axes", [])
    if axes:
        shape = tuple(s for i, s in enumerate(v.shape)
                      if not (i in axes or i - v.ndim in axes) or s != 1)
    else:
        shape = tuple(s for s in v.shape if s != 1)
    for name in op.output("Out"):
        op.block.create_var(name=name, shape=shape, dtype=v.dtype)


def _squeeze(ctx, ins, attrs):
    v = x(ins)
    axes = attrs.get("axes", [])
    if not axes:
        r = jnp.squeeze(v)
    else:
        axes = tuple(a % v.ndim for a in axes if v.shape[a % v.ndim] == 1)
        r = jnp.squeeze(v, axis=axes) if axes else v
    return {"Out": [r], "XShape": [None]}


register("squeeze2", _squeeze, attrs={"axes": []},
         infer_shape=_squeeze_infer, no_grad_out_slots=("XShape",))
register("squeeze", _squeeze, attrs={"axes": []}, infer_shape=_squeeze_infer)


def _unsqueeze_infer(op):
    v = op.invar("X")
    if v is None or v.shape is None:
        return
    shape = list(v.shape)
    for a in sorted(op.attr("axes", [])):
        shape.insert(a if a >= 0 else a + len(shape) + 1, 1)
    for name in op.output("Out"):
        op.block.create_var(name=name, shape=tuple(shape), dtype=v.dtype)


def _unsqueeze(ctx, ins, attrs):
    v = x(ins)
    for a in sorted(attrs["axes"]):
        v = jnp.expand_dims(v, a)
    return {"Out": [v], "XShape": [None]}


register("unsqueeze2", _unsqueeze, attrs={"axes": []},
         infer_shape=_unsqueeze_infer, no_grad_out_slots=("XShape",))
register("unsqueeze", _unsqueeze, attrs={"axes": []},
         infer_shape=_unsqueeze_infer)


def _flatten_infer(op):
    v = op.invar("X")
    if v is None or v.shape is None:
        return
    if op.type.startswith("flatten_contiguous"):
        start = op.attr("start_axis", 1)
        stop = op.attr("stop_axis", -1) % len(v.shape)
        mid = v.shape[start:stop + 1]
        mid_n = -1 if any(s < 0 for s in mid) else int(np.prod(mid))
        shape = v.shape[:start] + (mid_n,) + v.shape[stop + 1:]
    else:
        ax = op.attr("axis", 1)
        lead, tail = v.shape[:ax], v.shape[ax:]
        l = -1 if any(s < 0 for s in lead) else int(np.prod(lead)) if lead else 1
        t = -1 if any(s < 0 for s in tail) else int(np.prod(tail)) if tail else 1
        shape = (l, t)
    for name in op.output("Out"):
        op.block.create_var(name=name, shape=shape, dtype=v.dtype)


def _flatten_range(ctx, ins, attrs):
    v = x(ins)
    start = attrs.get("start_axis", 1)
    stop = attrs.get("stop_axis", -1) % v.ndim
    shape = v.shape[:start] + (-1,) + v.shape[stop + 1:]
    return {"Out": [v.reshape(shape)], "XShape": [None]}


register("flatten_contiguous_range", _flatten_range,
         attrs={"start_axis": 1, "stop_axis": -1},
         infer_shape=_flatten_infer, no_grad_out_slots=("XShape",))


def _flatten2(ctx, ins, attrs):
    v = x(ins)
    ax = attrs.get("axis", 1)
    r = v.reshape((int(np.prod(v.shape[:ax])) if ax else 1, -1))
    return {"Out": [r], "XShape": [None]}


register("flatten2", _flatten2, attrs={"axis": 1},
         infer_shape=_flatten_infer, no_grad_out_slots=("XShape",))
register("flatten", lambda ctx, ins, attrs: {"Out": _flatten2(ctx, ins, attrs)["Out"]},
         attrs={"axis": 1}, infer_shape=_flatten_infer)


# ---------------------------------------------------------------------------
# concat / split / stack
# ---------------------------------------------------------------------------

def _concat_infer(op):
    vs = [op.block._var_recursive(n) for n in op.input("X")]
    if not vs or any(v is None or v.shape is None for v in vs):
        return
    ax = op.attr("axis", 0) % len(vs[0].shape)
    shape = list(vs[0].shape)
    shape[ax] = sum(v.shape[ax] for v in vs) \
        if all(v.shape[ax] >= 0 for v in vs) else -1
    for name in op.output("Out"):
        op.block.create_var(name=name, shape=tuple(shape), dtype=vs[0].dtype)


@register("concat", infer_shape=_concat_infer, attrs={"axis": 0})
def _concat(ctx, ins, attrs):
    ax = x(ins, "AxisTensor")
    axis = int(np.asarray(ax)) if ax is not None else attrs["axis"]
    return out(jnp.concatenate(ins["X"], axis=axis))


def _split_infer(op):
    v = op.invar("X")
    if v is None or v.shape is None:
        return
    ax = op.attr("axis", 0) % len(v.shape)
    num = op.attr("num", 0)
    sections = op.attr("sections", [])
    names = op.output("Out")
    if sections:
        sizes = sections
    else:
        n = num or len(names)
        sizes = [v.shape[ax] // n] * n if v.shape[ax] >= 0 else [-1] * n
    for name, s in zip(names, sizes):
        shape = list(v.shape)
        shape[ax] = s
        op.block.create_var(name=name, shape=tuple(shape), dtype=v.dtype)


@register("split", infer_shape=_split_infer,
          attrs={"axis": 0, "num": 0, "sections": []})
def _split(ctx, ins, attrs):
    v = x(ins)
    ax = attrs["axis"]
    sections = attrs.get("sections") or []
    if sections:
        idx = np.cumsum(sections)[:-1].tolist()
        parts = jnp.split(v, idx, axis=ax)
    else:
        parts = jnp.split(v, attrs.get("num") or 1, axis=ax)
    return {"Out": list(parts)}


def _stack_infer(op):
    vs = [op.block._var_recursive(n) for n in op.input("X")]
    if not vs or any(v is None or v.shape is None for v in vs):
        return
    ax = op.attr("axis", 0)
    shape = list(vs[0].shape)
    shape.insert(ax if ax >= 0 else ax + len(shape) + 1, len(vs))
    for name in op.output("Y"):
        op.block.create_var(name=name, shape=tuple(shape), dtype=vs[0].dtype)


@register("stack", infer_shape=_stack_infer, attrs={"axis": 0})
def _stack(ctx, ins, attrs):
    return {"Y": [jnp.stack(ins["X"], axis=attrs["axis"])]}


@register("unstack", attrs={"axis": 0, "num": 0})
def _unstack(ctx, ins, attrs):
    v = x(ins)
    parts = [jnp.squeeze(p, attrs["axis"])
             for p in jnp.split(v, v.shape[attrs["axis"]], axis=attrs["axis"])]
    return {"Y": parts}


# ---------------------------------------------------------------------------
# slicing / gather / scatter
# ---------------------------------------------------------------------------

def _slice_infer(op):
    v = op.invar("Input")
    if v is None or v.shape is None:
        return
    axes = op.attr("axes", [])
    starts, ends = op.attr("starts", []), op.attr("ends", [])
    shape = list(v.shape)
    for a, s, e in zip(axes, starts, ends):
        if shape[a] < 0:
            continue
        s2 = s if s >= 0 else s + shape[a]
        e2 = min(e if e >= 0 else e + shape[a], shape[a])
        shape[a] = max(e2 - s2, 0)
    for d in sorted(op.attr("decrease_axis", []), reverse=True):
        shape.pop(d)
    for name in op.output("Out"):
        op.block.create_var(name=name, shape=tuple(shape), dtype=v.dtype)


@register("slice", infer_shape=_slice_infer,
          attrs={"axes": [], "starts": [], "ends": [], "decrease_axis": [],
                 "infer_flags": []})
def _slice(ctx, ins, attrs):
    v = x(ins, "Input")
    idx = [slice(None)] * v.ndim
    for a, s, e in zip(attrs["axes"], attrs["starts"], attrs["ends"]):
        idx[a] = slice(s, e)
    r = v[tuple(idx)]
    dec = attrs.get("decrease_axis", [])
    if dec:
        r = r.reshape([d for i, d in enumerate(r.shape) if i not in dec])
    return out(r)


@register("strided_slice",
          attrs={"axes": [], "starts": [], "ends": [], "strides": [],
                 "infer_flags": [], "decrease_axis": []})
def _strided_slice(ctx, ins, attrs):
    v = x(ins, "Input")
    idx = [slice(None)] * v.ndim
    for a, s, e, st in zip(attrs["axes"], attrs["starts"], attrs["ends"],
                           attrs["strides"]):
        idx[a] = slice(s, e, st)
    r = v[tuple(idx)]
    dec = attrs.get("decrease_axis", [])
    if dec:  # reference semantics (same as the slice kernel above)
        r = r.reshape([d for i, d in enumerate(r.shape) if i not in dec])
    return out(r)


def _gather_infer(op):
    v, ids = op.invar("X"), op.invar("Index")
    if v is None or v.shape is None or ids is None or ids.shape is None:
        return
    shape = tuple(list(ids.shape[:1]) + list(v.shape[1:]))
    for name in op.output("Out"):
        op.block.create_var(name=name, shape=shape, dtype=v.dtype)


@register("gather", infer_shape=_gather_infer, no_grad_slots=("Index",),
          attrs={"axis": 0})
def _gather(ctx, ins, attrs):
    v, idx = x(ins), x(ins, "Index")
    ax = x(ins, "Axis")
    axis = int(np.asarray(ax)) if ax is not None else attrs.get("axis", 0)
    if idx.ndim == 2 and idx.shape[1] == 1:
        idx = idx[:, 0]
    return out(jnp.take(v, idx.astype(jnp.int32), axis=axis))


@register("gather_nd", no_grad_slots=("Index",))
def _gather_nd(ctx, ins, attrs):
    v, idx = x(ins), x(ins, "Index")
    idx = idx.astype(jnp.int32)
    k = idx.shape[-1]
    flat_idx = tuple(idx[..., i] for i in range(k))
    return out(v[flat_idx])


@register("index_select", no_grad_slots=("Index",), attrs={"dim": 0})
def _index_select(ctx, ins, attrs):
    v, idx = x(ins), x(ins, "Index")
    return out(jnp.take(v, idx.astype(jnp.int32), axis=attrs["dim"]))


@register("index_sample", no_grad_slots=("Index",))
def _index_sample(ctx, ins, attrs):
    v, idx = x(ins), x(ins, "Index")
    return out(jnp.take_along_axis(v, idx.astype(jnp.int32), axis=1))


@register("scatter", no_grad_slots=("Ids",), attrs={"overwrite": True})
def _scatter(ctx, ins, attrs):
    v, ids, upd = x(ins), x(ins, "Ids"), x(ins, "Updates")
    ids = ids.astype(jnp.int32)
    if ids.ndim == 2 and ids.shape[1] == 1:
        ids = ids[:, 0]
    if attrs.get("overwrite", True):
        return out(v.at[ids].set(upd))
    return out(v.at[ids].add(upd))


@register("scatter_nd_add", no_grad_slots=("Index",))
def _scatter_nd_add(ctx, ins, attrs):
    v, idx, upd = x(ins), x(ins, "Index"), x(ins, "Updates")
    idx = idx.astype(jnp.int32)
    k = idx.shape[-1]
    return out(v.at[tuple(idx[..., i] for i in range(k))].add(upd))


@register("where", no_grad_slots=("Condition",))
def _where(ctx, ins, attrs):
    return out(jnp.where(x(ins, "Condition"), x(ins, "X"), x(ins, "Y")))


@register("masked_fill", no_grad_slots=("Mask",), attrs={"value": 0.0})
def _masked_fill(ctx, ins, attrs):
    return out(jnp.where(x(ins, "Mask"), attrs["value"], x(ins, "X")))


# ---------------------------------------------------------------------------
# expand / tile / repeat
# ---------------------------------------------------------------------------

def _expand_v2_infer(op):
    v = op.invar("X")
    if v is None or v.shape is None:
        return
    shape = list(op.attr("shape", []))
    nd = len(shape)
    xs = [1] * (nd - len(v.shape)) + list(v.shape)
    final = [xs[i] if shape[i] == -1 else shape[i] for i in range(nd)]
    for name in op.output("Out"):
        op.block.create_var(name=name, shape=tuple(final), dtype=v.dtype)


@register("expand_v2", infer_shape=_expand_v2_infer, attrs={"shape": []})
def _expand_v2(ctx, ins, attrs):
    v = x(ins)
    shape = list(attrs["shape"])
    xs = [1] * (len(shape) - v.ndim) + list(v.shape)
    v = v.reshape(xs)
    final = [xs[i] if s == -1 else s for i, s in enumerate(shape)]
    return out(jnp.broadcast_to(v, final))


@register("expand", attrs={"expand_times": []})
def _expand(ctx, ins, attrs):
    return out(jnp.tile(x(ins), attrs["expand_times"]))


@register("tile", attrs={"repeat_times": []})
def _tile(ctx, ins, attrs):
    return out(jnp.tile(x(ins), attrs["repeat_times"]))


@register("expand_as_v2", no_grad_slots=("target_tensor", "Y"))
def _expand_as(ctx, ins, attrs):
    tgt = x(ins, "target_tensor")
    if tgt is None:
        tgt = x(ins, "Y")
    return out(jnp.broadcast_to(x(ins), tgt.shape))


# ---------------------------------------------------------------------------
# fill / creation ops
# ---------------------------------------------------------------------------

def _fill_constant_infer(op):
    shape = tuple(op.attr("shape", []))
    for name in op.output("Out"):
        op.block.create_var(name=name, shape=shape,
                            dtype=op.attr("dtype", "float32"))


@register("fill_constant", grad=None, infer_shape=_fill_constant_infer,
          attrs={"shape": [], "value": 0.0, "dtype": "float32",
                 "force_cpu": False})
def _fill_constant(ctx, ins, attrs):
    st = x(ins, "ShapeTensor")
    shape = [int(s) for s in np.asarray(st)] if st is not None \
        else list(attrs["shape"])
    vt = x(ins, "ValueTensor")
    value = vt if vt is not None else attrs["value"]
    return out(jnp.full(shape, value, dtype=np_dtype(attrs["dtype"])))


@register("fill_zeros_like", grad=None, infer_shape=same_shape_as("X"))
def _fill_zeros_like(ctx, ins, attrs):
    return out(jnp.zeros_like(x(ins)))


@register("fill_any_like", grad=None, infer_shape=same_shape_as("X"),
          attrs={"value": 0.0, "dtype": -1})
def _fill_any_like(ctx, ins, attrs):
    v = x(ins)
    dt = attrs.get("dtype", -1)
    dtype = v.dtype if dt in (-1, None) else np_dtype(dt)
    return out(jnp.full(v.shape, attrs["value"], dtype=dtype))


@register("assign", infer_shape=same_shape_as("X"))
def _assign(ctx, ins, attrs):
    return out(x(ins))


@register("assign_value", grad=None, infer_shape=_fill_constant_infer,
          attrs={"shape": [], "dtype": "float32", "fp32_values": [],
                 "int32_values": [], "int64_values": [], "bool_values": []})
def _assign_value(ctx, ins, attrs):
    vals = attrs.get("fp32_values") or attrs.get("int32_values") or \
        attrs.get("int64_values") or attrs.get("bool_values")
    return out(jnp.asarray(
        np.array(vals, dtype=np_dtype(attrs["dtype"])).reshape(attrs["shape"])))


@register("shape", grad=None)
def _shape(ctx, ins, attrs):
    v = x(ins, "Input")
    return out(jnp.asarray(v.shape, dtype=jnp.int32))


@register("eye", grad=None, attrs={"num_rows": 0, "num_columns": -1,
                                   "dtype": "float32"})
def _eye(ctx, ins, attrs):
    nc = attrs["num_columns"]
    return out(jnp.eye(attrs["num_rows"], nc if nc > 0 else None,
                       dtype=np_dtype(attrs["dtype"])))


@register("linspace", grad=None, attrs={"dtype": "float32"})
def _linspace(ctx, ins, attrs):
    start = x(ins, "Start")
    stop = x(ins, "Stop")
    num = int(np.asarray(x(ins, "Num")))
    return out(jnp.linspace(jnp.reshape(start, ()), jnp.reshape(stop, ()),
                            num, dtype=np_dtype(attrs["dtype"])))


def _range_infer(op):
    """Static length when Start/End/Step are fill_constant-produced (the
    common arange(0, seq_len, 1) pattern) — without this the whole
    downstream graph loses shapes."""
    def const_of(name):
        # fold only when the SOLE producer so far is an attr-valued
        # fill_constant (a later assign/increment or a ValueTensor-fed
        # fill would make the attr stale)
        val = None
        for p in op.block.ops:
            if name not in p.output_arg_names:
                continue
            if p.type == "fill_constant" and not p.input("ValueTensor"):
                val = p.attr("value")
            else:
                return None
        return val

    vals = [const_of(op.input(slot)[0])
            for slot in ("Start", "End", "Step")]
    if any(v is None for v in vals):
        return
    n = len(np.arange(vals[0], vals[1], vals[2]))
    # fold for the jitted compute: under trace the inputs are tracers and
    # arange needs static bounds
    op.attrs["_folded_range"] = [float(v) for v in vals]
    dv = op.block._var_recursive(op.input("Start")[0])
    for name in op.output("Out"):
        op.block.create_var(name=name, shape=(n,),
                            dtype=dv.dtype if dv is not None else "int64")


@register("range", grad=None, infer_shape=_range_infer,
          attrs={"_folded_range": []})
def _range(ctx, ins, attrs):
    sv = x(ins, "Start")
    if isinstance(sv, jax.core.Tracer):
        folded = attrs.get("_folded_range")
        if not folded:
            raise ValueError(
                "range with non-constant bounds under jit — the output "
                "shape would be dynamic")
        s, e, st = folded
        return out(jnp.arange(s, e, st).astype(sv.dtype))
    s = np.asarray(sv).item()
    e = np.asarray(x(ins, "End")).item()
    st = np.asarray(x(ins, "Step")).item()
    return out(jnp.arange(s, e, st))


@register("cast", infer_shape=None, attrs={"in_dtype": "float32",
                                           "out_dtype": "float32"})
def _cast(ctx, ins, attrs):
    v = x(ins)
    return out(v.astype(np_dtype(attrs["out_dtype"])))


def _cast_infer(op):
    v = op.invar("X")
    if v is None:
        return
    for name in op.output("Out"):
        op.block.create_var(name=name, shape=v.shape,
                            dtype=op.attr("out_dtype", "float32"))


from .. import registry as _registry
_registry._REGISTRY["cast"].infer_shape = _cast_infer


# ---------------------------------------------------------------------------
# search / sort (non-differentiable outputs are ints)
# ---------------------------------------------------------------------------

def _argminmax_infer(op):
    v = op.invar("X")
    if v is None or v.shape is None:
        return
    ax = op.attr("axis", -1) % len(v.shape)
    shape = list(v.shape)
    if op.attr("keepdims", False):
        shape[ax] = 1
    else:
        shape.pop(ax)
    for name in op.output("Out"):
        op.block.create_var(name=name, shape=tuple(shape),
                            dtype=op.attr("dtype", "int64"))


@register("arg_max", grad=None, infer_shape=_argminmax_infer,
          attrs={"axis": -1, "keepdims": False, "dtype": "int64",
                 "flatten": False})
def _arg_max(ctx, ins, attrs):
    v = x(ins)
    if attrs.get("flatten"):
        v = v.reshape(-1)
    r = jnp.argmax(v, axis=attrs["axis"], keepdims=attrs.get("keepdims", False))
    return out(r.astype(np_dtype(attrs.get("dtype", "int64"))))


@register("arg_min", grad=None, infer_shape=_argminmax_infer,
          attrs={"axis": -1, "keepdims": False, "dtype": "int64",
                 "flatten": False})
def _arg_min(ctx, ins, attrs):
    v = x(ins)
    if attrs.get("flatten"):
        v = v.reshape(-1)
    r = jnp.argmin(v, axis=attrs["axis"], keepdims=attrs.get("keepdims", False))
    return out(r.astype(np_dtype(attrs.get("dtype", "int64"))))


@register("argsort", grad=None, attrs={"axis": -1, "descending": False})
def _argsort(ctx, ins, attrs):
    v = x(ins)
    ax = attrs["axis"]
    idx = jnp.argsort(-v if attrs["descending"] else v, axis=ax)
    srt = jnp.take_along_axis(v, idx, axis=ax)
    return {"Out": [srt], "Indices": [idx.astype(jnp.int64)]}


def _topk_infer(op):
    v = op.invar("X")
    if v is None or v.shape is None:
        return
    k = op.attr("k", 1)
    ax = op.attr("axis", -1) % len(v.shape)
    shape = list(v.shape)
    shape[ax] = k
    for name in op.output("Out"):
        op.block.create_var(name=name, shape=tuple(shape), dtype=v.dtype)
    for name in op.output("Indices"):
        op.block.create_var(name=name, shape=tuple(shape), dtype="int64")


def _topk(ctx, ins, attrs):
    v = x(ins)
    kt = x(ins, "K")
    k = int(np.asarray(kt)) if kt is not None else attrs.get("k", 1)
    ax = attrs.get("axis", -1)
    if ax not in (-1, v.ndim - 1):
        v2 = jnp.moveaxis(v, ax, -1)
        vals, idx = jax.lax.top_k(v2, k)
        if attrs.get("largest", True) is False:
            vals, idx = jax.lax.top_k(-v2, k)
            vals = -vals
        return {"Out": [jnp.moveaxis(vals, -1, ax)],
                "Indices": [jnp.moveaxis(idx, -1, ax).astype(jnp.int64)]}
    if attrs.get("largest", True) is False:
        vals, idx = jax.lax.top_k(-v, k)
        vals = -vals
    else:
        vals, idx = jax.lax.top_k(v, k)
    return {"Out": [vals], "Indices": [idx.astype(jnp.int64)]}


register("top_k", _topk, infer_shape=_topk_infer,
         attrs={"k": 1, "axis": -1, "largest": True},
         no_grad_out_slots=("Indices",))
register("top_k_v2", _topk, infer_shape=_topk_infer,
         attrs={"k": 1, "axis": -1, "largest": True, "sorted": True},
         no_grad_out_slots=("Indices",))


# ---------------------------------------------------------------------------
# misc
# ---------------------------------------------------------------------------

@register("flip", attrs={"axis": []})
def _flip(ctx, ins, attrs):
    return out(jnp.flip(x(ins), axis=tuple(attrs["axis"])))


@register("roll", attrs={"shifts": [], "axis": []})
def _roll(ctx, ins, attrs):
    ax = attrs.get("axis") or None
    return out(jnp.roll(x(ins), attrs["shifts"],
                        axis=tuple(ax) if ax else None))


@register("tril_triu", attrs={"diagonal": 0, "lower": True})
def _tril_triu(ctx, ins, attrs):
    v = x(ins)
    if attrs.get("lower", True):
        return out(jnp.tril(v, attrs.get("diagonal", 0)))
    return out(jnp.triu(v, attrs.get("diagonal", 0)))


@register("meshgrid")
def _meshgrid(ctx, ins, attrs):
    return {"Out": list(jnp.meshgrid(*ins["X"], indexing="ij"))}


@register("kron")
def _kron(ctx, ins, attrs):
    return out(jnp.kron(x(ins, "X"), x(ins, "Y")))


@register("diag_v2", attrs={"offset": 0, "padding_value": 0.0})
def _diag_v2(ctx, ins, attrs):
    v = x(ins)
    if v.ndim == 1:
        r = jnp.diag(v, k=attrs["offset"])
        pv = attrs.get("padding_value", 0.0)
        if pv:
            mask = jnp.diag(jnp.ones_like(v), k=attrs["offset"])
            r = jnp.where(mask > 0, r, pv)
        return out(r)
    return out(jnp.diagonal(v, offset=attrs["offset"]))


@register("unbind", attrs={"axis": 0})
def _unbind(ctx, ins, attrs):
    v = x(ins)
    ax = attrs["axis"]
    return {"Out": [jnp.squeeze(p, ax)
                    for p in jnp.split(v, v.shape[ax], axis=ax)]}


@register("unique", grad=None, attrs={"dtype": "int64"})
def _unique(ctx, ins, attrs):
    # static-shape constrained: returns padded unique with count
    v = x(ins)
    u, idx = jnp.unique(v, return_inverse=True, size=v.size)
    return {"Out": [u], "Index": [idx.astype(jnp.int64)]}


@register("shard_index", grad=None,
          attrs={"index_num": 0, "nshards": 1, "shard_id": 0,
                 "ignore_value": -1})
def _shard_index(ctx, ins, attrs):
    v = x(ins)
    shard_size = (attrs["index_num"] + attrs["nshards"] - 1) // attrs["nshards"]
    sid = attrs["shard_id"]
    in_shard = (v // shard_size) == sid
    return out(jnp.where(in_shard, v % shard_size, attrs["ignore_value"]))


@register("increment", attrs={"step": 1.0})
def _increment(ctx, ins, attrs):
    return out(x(ins) + attrs["step"])


# ---------------------------------------------------------------------------
# compile-time shape inference for the serving-decode / op-bench tier
# (VERDICT r5 missing #3: most registry entries deferred to trace time;
# these post-hoc assignments follow the `cast` precedent above so the
# kernel registrations stay uncluttered)
# ---------------------------------------------------------------------------

def _set_infer(name, fn):
    _registry._REGISTRY[name].infer_shape = fn


def _mk_out(op, shape, dtype, slot="Out"):
    for n in op.output(slot):
        op.block.create_var(name=n, shape=None if shape is None
                            else tuple(shape), dtype=dtype)


def _drop_axis_infer(slot):
    """unstack/unbind: every output is X's shape minus the split axis."""
    def _infer(op):
        v = op.invar("X")
        if v is None or v.shape is None:
            return
        ax = op.attr("axis", 0) % v.ndim
        _mk_out(op, tuple(s for i, s in enumerate(v.shape) if i != ax),
                v.dtype, slot=slot)
    return _infer


def _strided_slice_infer(op):
    v = op.invar("Input")
    if v is None or v.shape is None:
        return
    shape = list(v.shape)
    for a, s, e, st in zip(op.attr("axes", []), op.attr("starts", []),
                           op.attr("ends", []), op.attr("strides", [])):
        if shape[a] < 0:
            continue
        # exact parity with the kernel's v[slice(s, e, st)] (python
        # slice normalization handles negative starts/ends/strides)
        shape[a] = len(range(*slice(s, e, st).indices(shape[a])))
    for d in sorted(op.attr("decrease_axis", []), reverse=True):
        shape.pop(d)
    _mk_out(op, shape, v.dtype)


def _gather_nd_infer(op):
    v, idx = op.invar("X"), op.invar("Index")
    if None in (v, idx) or v.shape is None or idx.shape is None:
        return
    k = idx.shape[-1]
    _mk_out(op, tuple(idx.shape[:-1]) + tuple(v.shape[k:]), v.dtype)


def _index_select_infer(op):
    v, idx = op.invar("X"), op.invar("Index")
    if None in (v, idx) or v.shape is None or idx.shape is None:
        return
    shape = list(v.shape)
    shape[op.attr("dim", 0)] = idx.shape[0]
    _mk_out(op, shape, v.dtype)


def _index_sample_infer(op):
    v, idx = op.invar("X"), op.invar("Index")
    if None in (v, idx) or v.shape is None or idx.shape is None:
        return
    _mk_out(op, (v.shape[0], idx.shape[1]), v.dtype)


def _tile_infer(op):
    v = op.invar("X")
    if v is None or v.shape is None:
        return
    rep = list(op.attr("repeat_times", []) or op.attr("expand_times", []))
    shape = [1] * (len(rep) - v.ndim) + list(v.shape)
    rep = [1] * (len(shape) - len(rep)) + rep
    # dims < 0 are dynamic markers: keep them dynamic, never scale them
    _mk_out(op, [s * r if s >= 0 else s for s, r in zip(shape, rep)],
            v.dtype)


def _shape_infer(op):
    v = op.invar("Input")
    if v is None or v.shape is None:
        return
    _mk_out(op, (v.ndim,), "int32")


def _eye_infer(op):
    r = op.attr("num_rows", 0)
    c = op.attr("num_columns", -1)
    _mk_out(op, (r, c if c > 0 else r), op.attr("dtype", "float32"))


def _argsort_infer(op):
    v = op.invar("X")
    if v is None or v.shape is None:
        return
    _mk_out(op, v.shape, v.dtype)
    _mk_out(op, v.shape, "int64", slot="Indices")


def _meshgrid_infer(op):
    vs = [op.block._var_recursive(n) for n in op.input("X")]
    if any(v is None or v.shape is None for v in vs):
        return
    shape = tuple(v.shape[0] for v in vs)
    for n in op.output("Out"):
        op.block.create_var(name=n, shape=shape, dtype=vs[0].dtype)


def _kron_infer(op):
    a, b = op.invar("X"), op.invar("Y")
    if None in (a, b) or a.shape is None or b.shape is None:
        return
    sa = [1] * (b.ndim - a.ndim) + list(a.shape)
    sb = [1] * (a.ndim - b.ndim) + list(b.shape)
    _mk_out(op, [i * j if i >= 0 and j >= 0 else -1
                 for i, j in zip(sa, sb)], a.dtype)


_set_infer("unstack", _drop_axis_infer("Y"))
_set_infer("strided_slice", _strided_slice_infer)
_set_infer("gather_nd", _gather_nd_infer)
_set_infer("index_select", _index_select_infer)
_set_infer("index_sample", _index_sample_infer)
_set_infer("scatter", same_shape_as("X"))
_set_infer("scatter_nd_add", same_shape_as("X"))
_set_infer("where", same_shape_as("X"))
_set_infer("masked_fill", same_shape_as("X"))
_set_infer("tile", _tile_infer)
_set_infer("expand", _tile_infer)
_set_infer("shape", _shape_infer)
_set_infer("eye", _eye_infer)
_set_infer("argsort", _argsort_infer)
_set_infer("flip", same_shape_as("X"))
_set_infer("roll", same_shape_as("X"))
_set_infer("tril_triu", same_shape_as("X"))
_set_infer("unbind", _drop_axis_infer("Out"))
_set_infer("meshgrid", _meshgrid_infer)
_set_infer("kron", _kron_infer)
