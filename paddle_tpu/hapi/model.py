"""hapi Model — high-level fit/evaluate/predict
(reference python/paddle/hapi/model.py:788 fit, :1243 evaluate, :1443
predict, :1539 save).

Both execution modes serve through ONE surface (reference
_has_fluid/_run_static split at hapi/model.py:788): in dygraph,
train_batch runs the eager tape (every op kernel is a jax fn, so XLA
still fuses the per-op graphs); under paddle.enable_static() at
prepare() time, the network + loss + optimizer build train/eval/predict
Programs from the `inputs`/`labels` InputSpecs and batches run through
the whole-block-jit Executor. `prepare` wires a 2.0 optimizer + loss +
paddle.metric metrics. Callbacks mirror hapi/callbacks.py (ProgBarLogger,
ModelCheckpoint, EarlyStopping).
"""
from __future__ import annotations

import os
import time

import numpy as np

from ..fluid.dygraph.varbase import Tensor

__all__ = ["Model"]


def _to_tensor(x):
    if isinstance(x, Tensor):
        return x
    return Tensor(np.asarray(x), stop_gradient=True)


def _as_batch_list(data):
    return list(data) if isinstance(data, (list, tuple)) else [data]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False

    # -- setup ----------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        ms = metrics or []
        self._metrics = ms if isinstance(ms, (list, tuple)) else [ms]
        from ..fluid.framework import in_dygraph_mode
        self._static = not in_dygraph_mode()
        if self._static:
            self._build_static()
        return self

    def _build_static(self):
        """Static-graph mode (reference hapi/model.py static adapter):
        InputSpecs -> feed vars, the network traces into a Program, the
        optimizer's minimize builds the train program; eval/predict are
        test-mode clones taken BEFORE backward ops are appended."""
        if not self._inputs:
            raise ValueError("static-mode Model needs inputs=[InputSpec]")
        from ..fluid import framework, unique_name
        from ..fluid.executor import Executor
        from ..fluid.scope import Scope

        def _data(spec):
            shape = tuple(-1 if d is None else d for d in spec.shape)
            return framework.default_main_program().current_block() \
                .create_var(name=spec.name, shape=shape,
                            dtype=spec.dtype, is_data=True,
                            stop_gradient=True)

        self._scope = Scope()
        main, startup = framework.Program(), framework.Program()
        with framework.program_guard(main, startup), unique_name.guard():
            # the network's Parameters were registered in the session's
            # default program at layer construction; declare them here so
            # the Executor seeds them from the scope (persistable)
            blk = main.global_block()
            for p in self.network.parameters():
                if not blk.has_var(p.name):
                    blk.create_var(name=p.name,
                                   shape=tuple(p.shape or ()),
                                   dtype=str(getattr(p, "dtype", None)
                                             or "float32"),
                                   persistable=True)
            feed_ins = [_data(s) for s in self._inputs]
            outs = _as_batch_list(self.network(*feed_ins))
            self._static_fetch_outs = [o.name for o in outs]
            self._predict_prog = main.clone(for_test=True)
            loss_var = None
            lab_vars = []
            if self._labels:
                lab_vars = [_data(s) for s in self._labels]
                if self._loss is not None:
                    loss_var = self._loss(*outs, *lab_vars)
            self._eval_prog = main.clone(for_test=True)
            if loss_var is not None and self._optimizer is not None:
                self._optimizer.minimize(loss_var)
        self._train_prog, self._startup_prog = main, startup
        self._static_loss_name = loss_var.name if loss_var is not None \
            else None
        self._feed_names = [s.name for s in self._inputs]
        self._label_names = [s.name for s in (self._labels or [])]
        self._exe = Executor()
        from ..fluid.scope import scope_guard
        with scope_guard(self._scope):
            # the network's layers were constructed BEFORE prepare(), so
            # their parameter-init ops live in the session's default
            # startup program; run both
            self._exe.run(framework.default_startup_program())
            self._exe.run(startup)

    def _static_feed(self, inputs, labels):
        feed = {n: np.asarray(getattr(v, "numpy", lambda: v)())
                for n, v in zip(self._feed_names,
                                _as_batch_list(inputs))}
        if labels is not None:
            for n, v in zip(self._label_names, _as_batch_list(labels)):
                feed[n] = np.asarray(getattr(v, "numpy", lambda: v)())
        return feed

    def _static_batch(self, prog, inputs, labels, with_loss):
        from ..fluid.scope import scope_guard
        fetch = list(self._static_fetch_outs)
        if with_loss and self._static_loss_name:
            fetch = [self._static_loss_name] + fetch
        with scope_guard(self._scope):
            res = self._exe.run(prog,
                                feed=self._static_feed(inputs, labels),
                                fetch_list=fetch)
        metrics = {}
        outs = res
        if with_loss and self._static_loss_name:
            metrics["loss"] = float(np.ravel(res[0])[0])
            outs = res[1:]
        if labels is not None and self._metrics:
            outs_t = [Tensor(np.asarray(o), stop_gradient=True)
                      for o in outs]
            labs_t = [Tensor(np.asarray(getattr(v, "numpy",
                                                lambda: v)()),
                             stop_gradient=True)
                      for v in _as_batch_list(labels)]
            self._update_metrics(outs_t, labs_t, metrics)
        return metrics, [np.asarray(o) for o in outs]

    # -- per-batch ------------------------------------------------------
    def train_batch(self, inputs, labels=None):
        if getattr(self, "_static", False):
            return self._static_batch(self._train_prog, inputs, labels,
                                      with_loss=True)[0]
        self.network.train()
        ins = [_to_tensor(v) for v in _as_batch_list(inputs)]
        outs = self.network(*ins)
        outs_l = _as_batch_list(outs)
        metrics = {}
        if labels is not None:
            labs = [_to_tensor(v) for v in _as_batch_list(labels)]
            loss = self._loss(*outs_l, *labs) if self._loss else outs_l[0]
            loss.backward()
            self._optimizer.step()
            self._optimizer.clear_grad()
            metrics["loss"] = float(np.ravel(loss.numpy())[0])
            self._update_metrics(outs_l, labs, metrics)
        return metrics

    def eval_batch(self, inputs, labels=None):
        if getattr(self, "_static", False):
            return self._static_batch(self._eval_prog, inputs, labels,
                                      with_loss=self._loss is not None
                                      and labels is not None)[0]
        self.network.eval()
        from ..fluid.dygraph.base import no_grad
        with no_grad():
            ins = [_to_tensor(v) for v in _as_batch_list(inputs)]
            outs = _as_batch_list(self.network(*ins))
            metrics = {}
            if labels is not None:
                labs = [_to_tensor(v) for v in _as_batch_list(labels)]
                if self._loss:
                    loss = self._loss(*outs, *labs)
                    metrics["loss"] = float(np.ravel(loss.numpy())[0])
                self._update_metrics(outs, labs, metrics)
        return metrics

    def predict_batch(self, inputs):
        if getattr(self, "_static", False):
            return self._static_batch(self._predict_prog, inputs, None,
                                      with_loss=False)[1]
        self.network.eval()
        from ..fluid.dygraph.base import no_grad
        with no_grad():
            ins = [_to_tensor(v) for v in _as_batch_list(inputs)]
            outs = _as_batch_list(self.network(*ins))
        return [o.numpy() for o in outs]

    def _update_metrics(self, outs, labs, metrics):
        for m in self._metrics:
            r = m.compute(*outs, *labs)
            m.update(*[np.asarray(v.numpy() if hasattr(v, "numpy") else v)
                       for v in _as_batch_list(r)])
            names, vals = m.name(), m.accumulate()
            if isinstance(names, (list, tuple)):  # e.g. Accuracy topk
                for k, v in zip(names, _as_batch_list(vals)):
                    metrics[k] = v
            else:
                metrics[names] = vals

    # -- loops ----------------------------------------------------------
    def _loader(self, data, batch_size, shuffle):
        from ..io import DataLoader
        if data is None or hasattr(data, "batch_sampler") or \
                hasattr(data, "__next__"):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle)

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, shuffle=True, callbacks=None):
        from .callbacks import CallbackList, ModelCheckpoint, ProgBarLogger
        loader = self._loader(train_data, batch_size, shuffle)
        cbs = list(callbacks or [])
        if verbose and not any(isinstance(c, ProgBarLogger) for c in cbs):
            cbs.insert(0, ProgBarLogger(log_freq, verbose=verbose))
        if save_dir and not any(isinstance(c, ModelCheckpoint)
                                for c in cbs):
            cbs.append(ModelCheckpoint(save_freq, save_dir))
        if hasattr(loader, "__next__"):
            # one-shot iterator: materialise so every epoch sees data
            # (else epochs after the first would silently train nothing)
            loader = list(loader)
        cblist = CallbackList(cbs, model=self)
        self.stop_training = False
        from ..distributed import elastic
        elastic.start_heartbeat()  # no-op unless the launcher asked
        global_step = 0
        cblist.on_train_begin()
        logs = {}
        for epoch in range(epochs):
            cblist.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            n_batches = 0
            for step, batch in enumerate(loader):
                ins, labs = self._split_batch(batch)
                # per-step progress for the elastic watchdog (hang vs
                # slow) + the deterministic trainer fault hooks
                elastic.note_step(global_step)
                global_step += 1
                cblist.on_train_batch_begin(step)
                logs = self.train_batch(ins, labs)
                cblist.on_train_batch_end(step, logs)
                n_batches += 1
            if n_batches == 0:
                raise ValueError("fit() got an empty data source")
            logs = dict(logs)
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                logs["eval"] = self.evaluate(eval_data, batch_size,
                                             verbose=0)
            cblist.on_epoch_end(epoch, logs)
            if self.stop_training:
                break
        cblist.on_train_end(logs)
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 callbacks=None):
        loader = self._loader(eval_data, batch_size, shuffle=False)
        for m in self._metrics:
            m.reset()
        logs = {}
        n = 0
        loss_sum = 0.0
        for batch in loader:
            ins, labs = self._split_batch(batch)
            logs = self.eval_batch(ins, labs)
            if "loss" in logs:
                bs = len(np.asarray(
                    ins[0].numpy() if hasattr(ins[0], "numpy")
                    else ins[0]))  # sample-weighted mean: a partial tail
                # batch must not be overweighted
                loss_sum += logs["loss"] * bs
                n += bs
        if n:
            logs["loss"] = loss_sum / n
        if verbose:
            print("Eval:", {k: round(float(v), 4)
                            for k, v in logs.items()})
        return logs

    def predict(self, test_data, batch_size=1, stack_outputs=False,
                callbacks=None):
        loader = self._loader(test_data, batch_size, shuffle=False)
        outs = []
        for batch in loader:
            # labeled datasets work too: trailing label slots are split
            # off and ignored (reference predict honors the _labels spec)
            ins, _ = self._split_batch(batch)
            outs.append(self.predict_batch(ins))
        if not outs:
            return []
        n_out = len(outs[0])
        per_slot = [[b[i] for b in outs] for i in range(n_out)]
        if stack_outputs:
            per_slot = [np.concatenate(s, axis=0) for s in per_slot]
        return per_slot

    def _split_batch(self, batch, has_label=True):
        batch = _as_batch_list(batch)
        if not has_label or len(batch) == 1:
            return batch, None
        # the inputs/labels specs passed to Model(...) take precedence;
        # otherwise convention (reference model.py _update_inputs):
        # inputs first, one label last
        if self._inputs is not None:
            n_in = len(_as_batch_list(self._inputs))
            return batch[:n_in], (batch[n_in:] or None)
        n_lab = len(_as_batch_list(self._labels)) if self._labels else 1
        return batch[:-n_lab], batch[-n_lab:]

    # -- save/load ------------------------------------------------------
    def _state_blobs(self, training=True):
        """(param arrays, optimizer slot arrays, optimizer json dicts)
        — the three pieces every save format persists. Slot arrays are
        the momentum/adam-moment accumulators (optimizer.state_dict),
        keyed ``<param>_<slot>``."""
        state = self.network.state_dict()
        params = {k: np.asarray(v.numpy() if hasattr(v, "numpy") else v)
                  for k, v in state.items()}
        opt_arrs, opt_dicts = {}, {}
        if training and self._optimizer is not None and \
                hasattr(self._optimizer, "state_dict"):
            opt = self._optimizer.state_dict()
            opt_arrs = {k: np.asarray(v) for k, v in opt.items()
                        if v is not None and not isinstance(v, dict)}
            opt_dicts = {k: v for k, v in opt.items()
                         if isinstance(v, dict)}
        return params, opt_arrs, opt_dicts

    def save(self, path, training=True):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        params, opt_arrs, opt_dicts = self._state_blobs(training)
        from .. import checkpoint as ckpt
        if ckpt.enabled():
            # checkpoint-store format (docs/CHECKPOINT.md): params +
            # optimizer slot state in ONE atomically-committed
            # manifest; unchanged tensors dedup against the previous
            # step's chunks
            arrays = {f"p:{k}": v for k, v in params.items()}
            arrays.update({f"o:{k}": v for k, v in opt_arrs.items()})
            ckpt.CheckpointStore(path + ".ckpt").save(
                arrays, meta={"kind": "hapi.Model",
                              "has_opt": bool(opt_arrs or opt_dicts),
                              "opt_json": opt_dicts})
            return
        np.savez(path + ".pdparams", **params)
        if training and self._optimizer is not None and \
                hasattr(self._optimizer, "state_dict"):
            import json
            arrs = dict(opt_arrs)
            if opt_dicts:  # e.g. LR_Scheduler state
                arrs["__json__"] = np.frombuffer(
                    json.dumps(opt_dicts).encode(), dtype=np.uint8)
            np.savez(path + ".pdopt", **arrs)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from .. import checkpoint as ckpt
        from ..fluid.io import _prefer_store
        if _prefer_store(path + ".ckpt", path + ".pdparams.npz"):
            blob, meta = ckpt.CheckpointStore(path + ".ckpt").restore()
            params = {k[2:]: v for k, v in blob.items()
                      if k.startswith("p:")}
            state = self.network.state_dict()
            missing = [k for k in state if k not in params]
            if missing and not skip_mismatch:
                raise KeyError(
                    f"parameters {missing[:5]} missing from {path}")
            self.network.set_state_dict(params)
            if not reset_optimizer and self._optimizer is not None \
                    and hasattr(self._optimizer, "set_state_dict"):
                sd = {k[2:]: v for k, v in blob.items()
                      if k.startswith("o:")}
                sd.update((meta or {}).get("opt_json") or {})
                if sd:
                    self._optimizer.set_state_dict(sd)
            return self
        blob = np.load(path + ".pdparams.npz", allow_pickle=False)
        state = self.network.state_dict()
        missing = [k for k in state if k not in blob.files]
        if missing and not skip_mismatch:
            raise KeyError(f"parameters {missing[:5]} missing from {path}")
        self.network.set_state_dict(
            {k: blob[k] for k in blob.files})
        opt_path = path + ".pdopt.npz"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path) and \
                hasattr(self._optimizer, "set_state_dict"):
            oblob = np.load(opt_path, allow_pickle=False)
            sd = {k: oblob[k] for k in oblob.files if k != "__json__"}
            if "__json__" in oblob.files:
                import json
                sd.update(json.loads(bytes(oblob["__json__"]).decode()))
            self._optimizer.set_state_dict(sd)
        return self

    # -- introspection --------------------------------------------------
    def parameters(self):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .summary import summary
        return summary(self.network, input_size)
