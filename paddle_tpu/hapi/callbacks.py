"""hapi callbacks (reference python/paddle/hapi/callbacks.py):
ProgBarLogger, ModelCheckpoint, EarlyStopping, LRScheduler."""
from __future__ import annotations

import os
import time

import numpy as np

__all__ = ["Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
           "EarlyStopping", "LRScheduler"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks, model=None):
        self.callbacks = list(callbacks)
        for c in self.callbacks:
            c.set_model(model)

    def _call(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def on_train_begin(self, logs=None):
        self._call("on_train_begin", logs)

    def on_train_end(self, logs=None):
        self._call("on_train_end", logs)

    def on_epoch_begin(self, epoch, logs=None):
        self._call("on_epoch_begin", epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._call("on_epoch_end", epoch, logs)

    def on_train_batch_begin(self, step, logs=None):
        self._call("on_train_batch_begin", step, logs)

    def on_train_batch_end(self, step, logs=None):
        self._call("on_train_batch_end", step, logs)


class ProgBarLogger(Callback):
    """Prints step metrics every `log_freq` steps + an epoch summary."""

    def __init__(self, log_freq=10, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = 0
        self._t0 = time.time()

    def on_train_batch_end(self, step, logs=None):
        self.steps += 1
        self._last = logs or {}
        if self.verbose >= 2 and self.log_freq and \
                (step + 1) % self.log_freq == 0:
            msg = " - ".join(f"{k}: {float(v):.4f}"
                             for k, v in (logs or {}).items()
                             if np.isscalar(v))
            print(f"Epoch {self.epoch} step {step + 1}: {msg}",
                  flush=True)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            flat = {}
            for k, v in (logs or {}).items():
                if isinstance(v, dict):
                    flat.update({f"eval_{k2}": v2 for k2, v2 in v.items()})
                elif np.isscalar(v):
                    flat[k] = v
            msg = " - ".join(f"{k}: {float(v):.4f}"
                             for k, v in flat.items())
            print(f"Epoch {epoch} done ({self.steps} steps, {dt:.1f}s): "
                  f"{msg}", flush=True)


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir="checkpoint"):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if (epoch + 1) % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="min", patience=0,
                 min_delta=0.0, baseline=None, save_best_model=False,
                 save_dir="best_model"):
        super().__init__()
        self.monitor = monitor
        self.sign = -1.0 if mode == "min" else 1.0
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.best = baseline
        self.wait = 0
        self.save_best_model = save_best_model
        self.save_dir = save_dir

    def _value(self, logs):
        v = (logs or {}).get(self.monitor)
        if v is None and isinstance((logs or {}).get("eval"), dict):
            v = logs["eval"].get(self.monitor)
        return v

    def on_epoch_end(self, epoch, logs=None):
        v = self._value(logs)
        if v is None:
            return
        if self.best is None or \
                self.sign * (v - self.best) > self.min_delta:
            self.best = v
            self.wait = 0
            if self.save_best_model:
                self.model.save(os.path.join(self.save_dir, "best_model"))
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    """Steps an lr scheduler attached to the optimizer each epoch."""

    def __init__(self, by_step=False, by_epoch=True):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        lr = getattr(self.model._optimizer, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s:
            s.step()
