"""Model summary (reference python/paddle/hapi/model_summary.py)."""
from __future__ import annotations

import numpy as np

__all__ = ["summary"]


def summary(net, input_size=None, dtypes=None):
    """Print a per-layer parameter table; returns
    {'total_params': N, 'trainable_params': M}."""
    rows = []
    total = trainable = 0
    seen: set[int] = set()  # tied/shared params count once

    def tally(name, layer):
        nonlocal total, trainable
        own = [p for p in layer._parameters.values() if p is not None]
        fresh = [p for p in own if id(p) not in seen]
        seen.update(id(p) for p in fresh)
        if not own:
            return
        n = sum(int(np.prod(p.shape)) for p in fresh)
        trainable_n = sum(int(np.prod(p.shape)) for p in fresh
                          if p.trainable)
        shapes = ", ".join(str(tuple(p.shape)) for p in own)
        tag = "" if len(fresh) == len(own) else " (shared)"
        rows.append((name, type(layer).__name__ + tag, shapes, n))
        total += n
        trainable += trainable_n

    tally("(root)", net)
    for name, layer in net.named_sublayers():
        tally(name, layer)
    if rows and rows[0][3] == 0 and rows[0][0] == "(root)":
        rows.pop(0)
    w = max([len(r[0]) for r in rows] + [10])
    print(f"{'Layer':<{w}}  {'Type':<18} {'Param shapes':<32} {'#Params'}")
    print("-" * (w + 62))
    for name, ty, shapes, n in rows:
        print(f"{name:<{w}}  {ty:<18} {shapes[:32]:<32} {n}")
    print("-" * (w + 62))
    print(f"Total params: {total}  Trainable: {trainable}")
    return {"total_params": total, "trainable_params": trainable}
