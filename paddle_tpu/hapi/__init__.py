"""paddle.hapi — high-level Model API (reference python/paddle/hapi/)."""
from . import callbacks
from .model import Model
from .summary import summary

__all__ = ["Model", "callbacks", "summary"]
