"""Mixture-of-Experts with expert parallelism over an "ep" mesh axis.

The reference ships the `expert_parallel` strategy flag in fleet's
DistributedStrategy but (at its vintage) no MoE runtime; SURVEY §2.9 lists
EP/MoE among the parallelism strategies the TPU build must design fresh.
Design follows GShard/Switch-Transformer, shaped for the MXU:

  * top-k routing with a STATIC per-expert capacity (no dynamic shapes —
    overflow tokens are dropped, their residual path carries them),
  * dense one-hot dispatch/combine einsums (batched matmuls, not scatters),
  * experts stacked on a leading E dim; sharding E over the "ep" mesh axis
    makes GSPMD lower the dispatch/combine einsums to all_to_all over ep,
  * router maths in float32 regardless of the compute dtype.

`moe_context(mesh, axis)` marks the ambient mesh so `moe_ffn` can pin the
[E, C, D] expert buffers to the ep axis with a sharding constraint
(mirrors sequence_parallel.ring_context).
"""
from __future__ import annotations

import contextlib
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["moe_capacity", "topk_gating", "moe_ffn", "moe_context",
           "current_moe_mesh"]

_moe_stack: list[tuple[Mesh, str]] = []


@contextlib.contextmanager
def moe_context(mesh: Mesh, axis: str = "ep"):
    """Marks the mesh axis expert buffers should shard over (consumed by
    moe_ffn; models/gpt.py enters it when the hybrid step has an ep axis)."""
    _moe_stack.append((mesh, axis))
    try:
        yield
    finally:
        _moe_stack.pop()


def current_moe_mesh():
    return _moe_stack[-1] if _moe_stack else None


def moe_capacity(n_tokens: int, n_experts: int,
                 capacity_factor: float = 1.25, top_k: int = 1,
                 multiple_of: int = 8) -> int:
    """Static per-expert buffer length C: tokens beyond it are dropped
    (their residual connection still carries them forward)."""
    c = math.ceil(capacity_factor * top_k * n_tokens / n_experts)
    return max(multiple_of, multiple_of * math.ceil(c / multiple_of))


def topk_gating(logits, top_k: int, capacity: int):
    """GShard-style router.

    Args:
      logits: [N, E] router scores (any float dtype; softmax runs fp32).
      top_k: experts per token (1 = Switch, 2 = GShard).
      capacity: static per-expert buffer length C.

    Returns:
      dispatch: [N, E, C] 0/1 float32 — token n occupies slot c of expert e.
      combine:  [N, E, C] float32 — dispatch weighted by (normalised) gates.
      aux: scalar load-balance loss (Switch eq. 4: E * Σ_e f_e · P_e),
        differentiable through the router probabilities.
    """
    N, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    masks, gates = [], []
    remaining = probs
    for _ in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)
        m = jax.nn.one_hot(idx, E, dtype=jnp.float32)       # [N, E]
        masks.append(m)
        gates.append(jnp.sum(probs * m, axis=-1))           # [N]
        remaining = remaining * (1.0 - m)

    # aux loss on the FIRST choice (Switch definition): fraction routed vs
    # mean router prob, per expert.
    f = jnp.mean(masks[0], axis=0)                          # [N,E] -> [E]
    p = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(f * p)

    # normalise kept gates so the combine weights of a token sum to 1
    denom = sum(gates)
    gates = [g / jnp.maximum(denom, 1e-9) for g in gates]

    # slot positions: k-th choices queue up after all earlier choices
    dispatch = jnp.zeros((N, E, capacity), jnp.float32)
    combine = jnp.zeros((N, E, capacity), jnp.float32)
    offset = jnp.zeros((E,), jnp.float32)
    for m, g in zip(masks, gates):
        pos = jnp.cumsum(m, axis=0) - 1.0 + offset[None, :]  # [N, E]
        offset = offset + jnp.sum(m, axis=0)
        keep = m * (pos < capacity)                          # [N, E]
        slot = jnp.sum(pos * keep, axis=-1).astype(jnp.int32)      # [N]
        slot_oh = jax.nn.one_hot(slot, capacity, dtype=jnp.float32)
        d = keep[:, :, None] * slot_oh[:, None, :]           # [N, E, C]
        dispatch = dispatch + d
        combine = combine + d * g[:, None, None]
    return dispatch, combine, aux


def moe_ffn(x, wg, we_up, be_up, we_down, be_down, *,
            capacity_factor: float = 1.25, top_k: int = 1,
            act=None):
    """MoE feed-forward: route, dispatch, expert FFN, combine.

    Args:
      x: [B, T, D] (or [N, D]) activations.
      wg: [D, E] router weights.
      we_up/be_up: [E, D, F] / [E, F] expert up-projections.
      we_down/be_down: [E, F, D] / [E, D] expert down-projections.

    Returns (y, aux): y shaped like x; aux the load-balance scalar.
    """
    if act is None:
        act = lambda u: jax.nn.gelu(u, approximate=True)
    shape = x.shape
    D = shape[-1]
    E = we_up.shape[0]
    xf = x.reshape(-1, D)
    N = xf.shape[0]
    C = moe_capacity(N, E, capacity_factor, top_k)

    logits = xf.astype(jnp.float32) @ wg.astype(jnp.float32)
    dispatch, combine, aux = topk_gating(logits, top_k, C)

    ctx = current_moe_mesh()

    def pin(a, spec):
        if ctx is None:
            return a
        mesh, axis = ctx
        if axis not in mesh.axis_names or mesh.shape[axis] == 1:
            return a
        named = P(*[axis if s == "ep" else None for s in spec])
        return jax.lax.with_sharding_constraint(
            a, NamedSharding(mesh, named))

    xin = jnp.einsum("nec,nd->ecd", dispatch.astype(x.dtype), xf)
    xin = pin(xin, ("ep", None, None))            # all_to_all over ep
    h = act(jnp.einsum("ecd,edf->ecf", xin, we_up.astype(x.dtype))
            + be_up[:, None, :].astype(x.dtype))
    out = (jnp.einsum("ecf,efd->ecd", h, we_down.astype(x.dtype))
           + be_down[:, None, :].astype(x.dtype))
    out = pin(out, ("ep", None, None))
    y = jnp.einsum("nec,ecd->nd", combine.astype(x.dtype), out)
    return y.reshape(shape), aux
