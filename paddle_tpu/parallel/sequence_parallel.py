"""Ring attention — sequence/context parallelism for long sequences.

Absent from the reference (SURVEY §5 "Long-context / sequence parallelism:
Absent... The TPU build must design long-context support fresh: context-
parallel mesh axis, ring attention via ppermute/shard_map") — this module
supplies it natively.

Design: the sequence dim is sharded over an "sp" mesh axis. Each shard
holds its q block permanently and an online-softmax accumulator; k/v
blocks rotate around the ring with `ppermute`, one hop per step, so every
shard sees the full sequence in n_sp steps while HBM holds only 1/n_sp of
the K/V at a time — O(S) memory per chip for O(S^2) attention.  The loop
is a `lax.scan`, so `jax.grad` differentiates straight through it (the
transpose of ppermute is the reverse rotation — the backward pass is the
reverse ring for free).  Everything outside attention is per-token and
stays GSPMD-sharded on the sequence dim with no code changes.
"""
from __future__ import annotations

import contextlib
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ._compat import shard_map as _shard_map

__all__ = ["ring_attention", "ring_context", "current_ring"]

_NEG = -1e30

_ring_stack: list[tuple[Mesh, str]] = []


@contextlib.contextmanager
def ring_context(mesh: Mesh, axis: str = "sp"):
    """Marks the mesh axis model code should ring-attend over (consumed by
    models/gpt.py when cfg.attn_impl == "ring")."""
    _ring_stack.append((mesh, axis))
    try:
        yield
    finally:
        _ring_stack.pop()


def current_ring():
    return _ring_stack[-1] if _ring_stack else None


def ring_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                   causal: bool = False, scale=None):
    """q, k, v: [B, H, S, D] with S sharded over `axis` (global
    S = n_sp * S_local). Returns [B, H, S, D], same sharding.

    Inside each ring step the local scores block is [S_loc, S_loc]; causal
    masking uses GLOBAL row/col ids, so fully-future blocks contribute
    nothing and the result matches dense causal attention exactly."""
    n = mesh.shape[axis]
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if n == 1:
        return _dense(q, k, v, causal, scale)

    perm = [(j, (j + 1) % n) for j in range(n)]

    def spmd(q, k, v):
        idx = jax.lax.axis_index(axis)
        B, H, S_loc, D = q.shape
        rows = idx * S_loc + jnp.arange(S_loc)

        def update(acc, m, l, kb, vb, src):
            s = jnp.einsum("bhqd,bhkd->bhqk", q, kb,
                           preferred_element_type=jnp.float32) * scale
            if causal:
                cols = src * S_loc + jnp.arange(S_loc)
                s = jnp.where(rows[:, None] >= cols[None, :], s, _NEG)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            p = jnp.exp(s - m_new[..., None])
            alpha = jnp.exp(m - m_new)
            l = alpha * l + jnp.sum(p, axis=-1)
            acc = acc * alpha[..., None] + jnp.einsum(
                "bhqk,bhkd->bhqd", p.astype(vb.dtype), vb,
                preferred_element_type=jnp.float32)
            return acc, m_new, l

        # hop 0 is the LOCAL block — fold it in before the scan so the
        # loop does exactly n-1 rotations (a rotate-after-use loop would
        # waste the final K+V ppermute pair per call)
        acc, m, l = update(jnp.zeros(q.shape, jnp.float32),
                           jnp.full(q.shape[:3], _NEG, jnp.float32),
                           jnp.zeros(q.shape[:3], jnp.float32), k, v, idx)

        def step(carry, i):
            acc, m, l, k_cur, v_cur = carry
            k_cur = jax.lax.ppermute(k_cur, axis, perm)
            v_cur = jax.lax.ppermute(v_cur, axis, perm)
            src = (idx - i) % n  # owner of the k/v block we now hold
            if causal:
                # fully-future block (every col id > every row id):
                # contributes nothing — skip the whole scores/softmax
                # block instead of computing it and masking (saves ~2x
                # attention FLOPs at large sp; the ppermute still runs,
                # the ring stays lockstep)
                acc, m, l = jax.lax.cond(
                    src <= idx,
                    lambda ops: update(*ops, k_cur, v_cur, src),
                    lambda ops: ops,
                    (acc, m, l))
            else:
                acc, m, l = update(acc, m, l, k_cur, v_cur, src)
            return (acc, m, l, k_cur, v_cur), None

        (acc, m, l, _, _), _ = jax.lax.scan(
            step, (acc, m, l, k, v), jnp.arange(1, n))
        l = jnp.where(l == 0.0, 1.0, l)
        return (acc / l[..., None]).astype(q.dtype)

    spec = P(None, None, axis, None)
    # nested-in-manual support (sp x pp): when this runs inside another
    # shard_map's manual region (the 1F1B engine manual over "pp"), the
    # inner shard_map must be built on the CONTEXT abstract mesh — the
    # one where pp is already Manual — not the original device mesh
    use_mesh = mesh
    try:  # AxisType/get_abstract_mesh only exist on newer jax; on the
        # 0.4.x API nested manual regions resolve against the device
        # mesh directly, so skipping the rebind is the correct fallback
        from jax.sharding import AxisType, get_abstract_mesh
        ctx_mesh = get_abstract_mesh()
        if getattr(ctx_mesh, "axis_names", ()) and \
                AxisType.Manual in tuple(getattr(ctx_mesh,
                                                 "axis_types", ())):
            use_mesh = ctx_mesh
    except ImportError:
        pass
    return _shard_map(spmd, mesh=use_mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names=frozenset({axis}),
                         check_vma=False)(q, k, v)


def _dense(q, k, v, causal, scale):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        T = q.shape[2]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)
