"""1F1B pipeline schedule as one lockstep SPMD computation.

The reference's 1F1B is a host-side thread schedule: SectionWorker threads
per stage pull microbatches from blocking queues and interleave one forward
with one backward so only ~pp microbatch activations stay live
(/root/reference/paddle/fluid/framework/device_worker.h:415,
/root/reference/python/paddle/fluid/optimizer.py:3666 PipelineOptimizer).

On TPU the schedule becomes data: a trace-time event simulator
(`simulate_1f1b`) produces, for every clock tick and stage, which action
(Forward on microbatch i / Backward on microbatch j / idle) the stage takes
and which buffer slots it touches.  A `lax.scan` steps the clock inside a
`shard_map` that is manual only over the "pp" axis (dp/tp/sp stay in GSPMD
auto mode), `lax.ppermute` moves activations forward and cotangents
backward each tick, and `lax.cond` masks the idle slots.

Backward is **rematerialised**: a stage stores only its per-microbatch
*inputs* (at most pp in flight, the 1F1B bound) and re-runs the stage
forward inside `jax.vjp` at its B-tick — the GPipe-by-autodiff engine in
parallel/pipeline.py instead stashes every residual of all M microbatches.
The last stage owns head+loss, so each microbatch's cotangent seeds as soon
as its activations arrive — no full-batch forward barrier.

Because grads are produced *by the schedule itself* (not by differentiating
it), the public entry returns (loss, block-grads, shared-grads, d(input));
`HybridParallelTrainStep` splices those into the same clip/Adam update used
by the autodiff paths and routes the embedding cotangent through an outer
`jax.vjp` of the (cheap) embed.

Dropout is supported: per-(stage, microbatch) keys are re-derived with
`jax.random.fold_in` at both F- and B-ticks, so the rematerialised backward
sees the identical masks (this is what lifts the GPipe path's dropout=0
restriction).  MoE load-balance aux flows too: each stage's B returns its
per-microbatch aux and its cotangent seeds with aux_weight/M — lifting the
MoE x pp restriction.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ._compat import shard_map as _shard_map

__all__ = ["simulate_1f1b", "pipeline_1f1b_grads"]


# ---------------------------------------------------------------------------
# trace-time schedule simulation
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Schedule:
    """Static per-tick schedule tables, each [n_ticks, n_stages] int32.

    f_on/f_micro/f_slot: forward action (slot = x-buffer slot to read;
      stage 0 reads the resident microbatch inputs instead).
    b_on/b_micro/b_xslot/b_dxslot: backward action (xslot = stored input,
      dxslot = arrived cotangent; the last stage seeds its own cotangent).
    recv_on/recv_slot: an activation permuted in at the END of tick t-1 is
      committed into the x-buffer at the START of tick t.
    drecv_on/drecv_slot: same for cotangents.
    """
    n_ticks: int
    n_xslots: int
    n_dxslots: int
    f_on: Any; f_micro: Any; f_slot: Any
    b_on: Any; b_micro: Any; b_xslot: Any; b_dxslot: Any
    recv_on: Any; recv_slot: Any
    drecv_on: Any; drecv_slot: Any


def simulate_1f1b(n_stages: int, n_micro: int,
                  both_per_tick: bool = False) -> Schedule:
    """Event-driven lockstep 1F1B: B-priority, one-tick communication
    latency, the last stage runs no separate forward (its B rematerialises
    blocks+head in one vjp).

    both_per_tick=False: one action per stage per tick (used with the
    lax.cond executor — a stage's tick costs only its taken action).
    both_per_tick=True: a stage may run one F AND one B in the same tick
    (used with the cond-free uniform executor, which computes both bodies
    every tick anyway — denser packing halves the tick count).

    Deterministic and purely host-side — runs at trace time; the result is
    baked into the compiled program as constant tables."""
    S, M = n_stages, n_micro
    assert S >= 2, "1F1B needs pp >= 2"
    # per stage state
    f_ready = [dict() for _ in range(S)]   # micro -> tick available
    b_ready = [dict() for _ in range(S)]
    x_slot = [dict() for _ in range(S)]    # micro -> xbuf slot
    dx_slot = [dict() for _ in range(S)]
    x_free = [set() for _ in range(S)]
    dx_free = [set() for _ in range(S)]
    x_hwm = [0] * S                        # slot high-water mark
    dx_hwm = [0] * S
    f_done = [0] * S
    b_done = [0] * S
    for m in range(M):
        f_ready[0][m] = 0                  # stage 0 inputs resident
    rows = []
    t = 0
    while sum(b_done) < S * M or sum(f_done) < (S - 1) * M:
        assert t < 8 * (M + S) + 64, "1F1B schedule failed to converge"
        row = {k: [0] * S for k in
               ("f_on", "f_micro", "f_slot", "b_on", "b_micro", "b_xslot",
                "b_dxslot", "recv_on", "recv_slot", "drecv_on",
                "drecv_slot")}
        acts = []
        for s in range(S):
            bs = [m for m, tk in b_ready[s].items() if tk <= t]
            # 1F1B admission cap: stage s keeps at most S-s microbatches
            # in flight (the warmup depth), so stored activations stay
            # O(pp) — B-priority alone lets warmup overfill downstream
            # buffers (Megatron num_warmup_microbatches semantics)
            fs = [m for m, tk in f_ready[s].items() if tk <= t] \
                if s < S - 1 and f_done[s] - b_done[s] < S - s else []
            did_b = False
            if bs:                         # 1F1B: backward has priority
                m = min(bs)
                row["b_on"][s] = 1
                row["b_micro"][s] = m
                row["b_xslot"][s] = x_slot[s].get(m, 0)
                row["b_dxslot"][s] = dx_slot[s].get(m, 0)
                acts.append(("B", s, m))
                did_b = True
            if fs and (both_per_tick or not did_b):
                m = min(fs)
                row["f_on"][s] = 1
                row["f_micro"][s] = m
                row["f_slot"][s] = x_slot[s].get(m, 0)
                acts.append(("F", s, m))
        # commit effects (arrivals land at t+1)
        for kind, s, m in acts:
            if kind == "F":
                del f_ready[s][m]
                f_done[s] += 1
                if s + 1 < S:
                    # allocate the receiver's x slot now; receiver commits
                    # the permuted activation at the start of t+1
                    free = x_free[s + 1]
                    slot = min(free) if free else x_hwm[s + 1]
                    if free and slot in free:
                        free.discard(slot)
                    else:
                        x_hwm[s + 1] += 1
                    x_slot[s + 1][m] = slot
                    if s + 1 == S - 1:
                        b_ready[S - 1][m] = t + 1   # last stage: B = remat
                    else:
                        f_ready[s + 1][m] = t + 1
            else:
                del b_ready[s][m]
                b_done[s] += 1
                if m in x_slot[s]:
                    x_free[s].add(x_slot[s][m])
                if m in dx_slot[s]:
                    dx_free[s].add(dx_slot[s][m])
                if s > 0:
                    free = dx_free[s - 1]
                    slot = min(free) if free else dx_hwm[s - 1]
                    if free and slot in free:
                        free.discard(slot)
                    else:
                        dx_hwm[s - 1] += 1
                    dx_slot[s - 1][m] = slot
                    b_ready[s - 1][m] = t + 1
        rows.append(row)
        t += 1
    # receive tables: stage s commits at tick t what was sent at t-1
    n_ticks = len(rows)
    for t in range(1, n_ticks):
        prev = rows[t - 1]
        for s in range(S):
            if s > 0 and prev["f_on"][s - 1] and s < S:
                m = prev["f_micro"][s - 1]
                rows[t]["recv_on"][s] = 1
                rows[t]["recv_slot"][s] = x_slot[s].get(m, 0)
            if s < S - 1 and prev["b_on"][s + 1]:
                m = prev["b_micro"][s + 1]
                rows[t]["drecv_on"][s] = 1
                rows[t]["drecv_slot"][s] = dx_slot[s].get(m, 0)
    tab = {k: np.asarray([r[k] for r in rows], np.int32)
           for k in rows[0]}
    return Schedule(n_ticks=n_ticks,
                    n_xslots=max(max(x_hwm), 1),
                    n_dxslots=max(max(dx_hwm), 1), **tab)


# ---------------------------------------------------------------------------
# SPMD executor
# ---------------------------------------------------------------------------

def pipeline_1f1b_grads(stage_fn: Callable, last_fn: Callable,
                        stage_params: Any, shared_params: Any,
                        mb_inputs, mb_ids, mesh, axis_name: str = "pp",
                        aux_weight: float = 0.0, key=None,
                        uniform_last: bool = False,
                        uniform_all: bool = False):
    """Run the 1F1B schedule and return grads directly.

    Args:
      stage_fn: (local_params, x, key) -> (y, aux). One stage's layers.
      last_fn: (local_params, shared_params, x, ids_mb, key)
        -> (y, loss_mb, aux). The final stage: layers + head + loss for
        ONE microbatch — y is the stage output activation (its cotangent
        is seeded by the executor), loss_mb that microbatch's mean loss.
      stage_params: pytree, leaves stacked [S, ...], sharded P(axis, ...).
      shared_params: pytree replicated over the pp axis (head/LN weights).
      mb_inputs: [M, mb, T, H] microbatched, pp-replicated activations.
      mb_ids: [M, mb, T] microbatched token ids (labels for the loss).
      aux_weight: weight of the per-stage aux (MoE load balance) in the
        total loss.
      key: dropout PRNG key or None.
      uniform_last: run blocks+head with cotangent-masked seeds on EVERY
        stage's B-tick instead of lax.cond-ing last vs middle. XLA's SPMD
        partitioner Check-fails on conditionals whose branches carry
        collectives when TWO auto mesh axes (e.g. dp and tp) are active
        beside the manual pp axis; the uniform body avoids the per-stage
        cond at the price of re-running the head on non-final stages'
        B-ticks.
      uniform_all: additionally drop the f_on/b_on scheduling conds —
        EVERY stage runs the F and B bodies on EVERY tick with the
        results where-masked. Required when the stage bodies carry
        EXPLICIT in-body collectives (sp x pp: ring attention's
        ppermutes over "sp" inside the stage functions) — a collective
        inside a stage-divergent lax.cond deadlocks the ring at runtime
        (half the devices enter the rendezvous, half take the other
        branch). Costs bubble-tick compute; correctness-identical.

    Returns (loss, d_stage_params [S,...], d_shared, d_mb_inputs):
      loss = mean over microbatches of loss_mb + aux_weight * sum of aux.
    """
    if uniform_all:
        uniform_last = True   # the cond-free B body is the uniform one
    S = mesh.shape[axis_name]
    M = mb_inputs.shape[0]
    if M < S:
        raise ValueError(f"need microbatches >= stages, got {M} < {S}")
    sched = simulate_1f1b(S, M)
    tabs = {k: jnp.asarray(getattr(sched, k)) for k in
            ("f_on", "f_micro", "f_slot", "b_on", "b_micro", "b_xslot",
             "b_dxslot", "recv_on", "recv_slot", "drecv_on", "drecv_slot")}
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [((i + 1) % S, i) for i in range(S)]
    inv_m = 1.0 / M
    if key is None:
        key = jax.random.PRNGKey(0)

    def spmd(params, shared, mbs, ids):
        stage = jax.lax.axis_index(axis_name)
        local = jax.tree_util.tree_map(lambda x: x[0], params)
        mb_shape = mbs.shape[1:]
        act_dt = mbs.dtype
        zero_act = jnp.zeros(mb_shape, act_dt)

        def stage_key(m):
            return jax.random.fold_in(jax.random.fold_in(key, stage), m)

        def f_mid(l, x, m):
            y, _ = stage_fn(l, x, stage_key(m))
            return y

        carry = dict(
            xbuf=jnp.zeros((sched.n_xslots,) + mb_shape, act_dt),
            dxbuf=jnp.zeros((sched.n_dxslots,) + mb_shape, act_dt),
            y_in=zero_act, dx_in=zero_act,
            gl=jax.tree_util.tree_map(
                lambda v: jnp.zeros(v.shape, jnp.float32), local),
            gsh=jax.tree_util.tree_map(
                lambda v: jnp.zeros(v.shape, jnp.float32), shared),
            dx0=jnp.zeros((M,) + mb_shape, act_dt),
            loss=jnp.zeros((), jnp.float32),
        )

        def tick(carry, t):
            row = {k: v[t] for k, v in tabs.items()}
            my = {k: row[k][stage] for k in row}
            # commit last tick's arrivals into the slot buffers
            xbuf = jnp.where(
                my["recv_on"] > 0,
                jax.lax.dynamic_update_index_in_dim(
                    carry["xbuf"], carry["y_in"], my["recv_slot"], 0),
                carry["xbuf"])
            dxbuf = jnp.where(
                my["drecv_on"] > 0,
                jax.lax.dynamic_update_index_in_dim(
                    carry["dxbuf"], carry["dx_in"], my["drecv_slot"], 0),
                carry["dxbuf"])

            # ---- forward action (never fires on the last stage) -------
            fm = my["f_micro"]
            fx_own = jax.lax.dynamic_index_in_dim(mbs, fm, 0,
                                                  keepdims=False)
            fx_buf = jax.lax.dynamic_index_in_dim(xbuf, my["f_slot"], 0,
                                                  keepdims=False)
            fx = jnp.where(stage == 0, fx_own, fx_buf)
            if uniform_all:
                # cond-free: collectives inside f_mid must execute on
                # every device every tick (see uniform_all docstring)
                y_live = f_mid(local, fx, fm)
                y_out = jnp.where(my["f_on"] > 0, y_live, zero_act)
            else:
                y_out = jax.lax.cond(my["f_on"] > 0,
                                     lambda _: f_mid(local, fx, fm),
                                     lambda _: zero_act, None)

            # ---- backward action --------------------------------------
            # buffer reads/updates and grad accumulation stay OUTSIDE the
            # conds (where-masked): sharded-state updates inside a cond
            # under (dp auto) x (pp manual) x (tp auto) trip the XLA SPMD
            # partitioner's group bookkeeping; only the vjp compute is
            # conditional
            bm = my["b_micro"]
            bx_own = jax.lax.dynamic_index_in_dim(mbs, bm, 0,
                                                  keepdims=False)
            bx_buf = jax.lax.dynamic_index_in_dim(xbuf, my["b_xslot"], 0,
                                                  keepdims=False)
            bx = jnp.where(stage == 0, bx_own, bx_buf)
            bdy = jax.lax.dynamic_index_in_dim(dxbuf, my["b_dxslot"], 0,
                                               keepdims=False)
            bids = jax.lax.dynamic_index_in_dim(ids, bm, 0, keepdims=False)

            def do_b(_):
                if uniform_last:
                    # no per-stage cond: the B body runs blocks+head with
                    # the cotangent seeds masked by stage role
                    def f(l, sh, xx):
                        return last_fn(l, sh, xx, bids, stage_key(bm))
                    (yy, lm, aux), vjp = jax.vjp(f, local, shared, bx)
                    is_last = stage == S - 1
                    dy_eff = jnp.where(is_last, jnp.zeros_like(bdy), bdy)
                    lm_ct = jnp.where(is_last, inv_m,
                                      0.0).astype(lm.dtype)
                    dl, dsh, dx = vjp(
                        (dy_eff, lm_ct,
                         jnp.asarray(aux_weight * inv_m, aux.dtype)))
                    dloss = jnp.where(is_last, lm * inv_m, 0.0) + \
                        aux_weight * inv_m * aux
                    return dl, dsh, dx, dloss.astype(jnp.float32)

                def b_last(_):
                    def f(l, sh, xx):
                        return last_fn(l, sh, xx, bids, stage_key(bm))
                    (yy, lm, aux), vjp = jax.vjp(f, local, shared, bx)
                    dl, dsh, dx = vjp((jnp.zeros_like(yy),
                                       jnp.asarray(inv_m, lm.dtype),
                                       jnp.asarray(aux_weight * inv_m,
                                                   aux.dtype)))
                    return (dl, dsh, dx,
                            (lm * inv_m +
                             aux_weight * inv_m * aux).astype(jnp.float32))

                def b_mid(_):
                    def f(l, xx):
                        return stage_fn(l, xx, stage_key(bm))
                    (yy, aux), vjp = jax.vjp(f, local, bx)
                    dl, dx = vjp((bdy, jnp.asarray(aux_weight * inv_m,
                                                   aux.dtype)))
                    dsh = jax.tree_util.tree_map(jnp.zeros_like, shared)
                    return (dl, dsh, dx,
                            (aux_weight * inv_m * aux).astype(jnp.float32))

                return jax.lax.cond(stage == S - 1, b_last, b_mid, None)

            def no_b(_):
                return (jax.tree_util.tree_map(jnp.zeros_like, local),
                        jax.tree_util.tree_map(jnp.zeros_like, shared),
                        zero_act, jnp.zeros((), jnp.float32))

            if uniform_all:
                dl, dsh, dx_out, dloss = do_b(None)
            else:
                dl, dsh, dx_out, dloss = jax.lax.cond(
                    my["b_on"] > 0, do_b, no_b, None)
            bon = my["b_on"] > 0
            gl = jax.tree_util.tree_map(
                lambda a, b: a + jnp.where(bon, b.astype(jnp.float32), 0),
                carry["gl"], dl)
            gsh = jax.tree_util.tree_map(
                lambda a, b: a + jnp.where(bon, b.astype(jnp.float32), 0),
                carry["gsh"], dsh)
            dx0 = jnp.where(
                jnp.logical_and(bon, stage == 0),
                jax.lax.dynamic_update_index_in_dim(
                    carry["dx0"], dx_out.astype(carry["dx0"].dtype), bm, 0),
                carry["dx0"])
            loss = carry["loss"] + jnp.where(
                bon, dloss.astype(jnp.float32), 0.0)

            # ---- ring communication (uniform across stages) -----------
            y_next = jax.lax.ppermute(y_out, axis_name, fwd_perm)
            dx_next = jax.lax.ppermute(dx_out, axis_name, bwd_perm)
            new_carry = dict(xbuf=xbuf, dxbuf=dxbuf, y_in=y_next,
                             dx_in=dx_next, gl=gl, gsh=gsh, dx0=dx0,
                             loss=loss)
            return new_carry, None

        carry, _ = jax.lax.scan(tick, carry, jnp.arange(sched.n_ticks))
        # no collectives here: per-stage partials come back stacked over
        # the pp axis and are reduced OUTSIDE the manual region (a psum
        # over the manual axis on tp-auto-sharded operands trips XLA's
        # SPMD partitioner group bookkeeping)
        gl = jax.tree_util.tree_map(lambda g: g[None], carry["gl"])
        gsh = jax.tree_util.tree_map(lambda g: g[None], carry["gsh"])
        return carry["loss"][None], gl, gsh, carry["dx0"][None]

    loss, gl, gsh, dx0 = _shard_map(
        spmd, mesh=mesh,
        in_specs=(P(axis_name), P(), P(), P()),
        out_specs=(P(axis_name), P(axis_name), P(axis_name), P(axis_name)),
        axis_names=frozenset({axis_name}),
        check_vma=False,
    )(stage_params, shared_params, mb_inputs, mb_ids)
    gsh = jax.tree_util.tree_map(lambda g: jnp.sum(g, axis=0), gsh)
    return jnp.sum(loss), gl, gsh, dx0[0]
