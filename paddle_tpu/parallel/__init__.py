"""Hybrid-parallel engine: dp x tp x pp (x sp) over a named device mesh.

TPU-native replacement for the reference's parallelism mechanisms:

- tensor parallel  -> GSPMD PartitionSpec rules on params (sharding.py);
  XLA inserts the all-reduces Megatron-style col/row-parallel layers would
  (absent in the reference, supplied fresh per SURVEY SS2.9).
- pipeline parallel -> microbatch GPipe schedule as lax.scan + ppermute
  inside a partial-manual shard_map over the "pp" mesh axis (pipeline.py);
  replaces reference PipelineOptimizer program-splitting + SectionWorker
  threads (/root/reference/python/paddle/fluid/optimizer.py:3666,
  /root/reference/paddle/fluid/framework/device_worker.h:415).
- data parallel    -> batch-dim sharding; grad psum is implicit in XLA's
  sharded autodiff.
"""
from . import pipeline, sequence_parallel, sharding
from .hybrid import HybridParallelTrainStep
from .embedding import ShardedEmbedding, sharded_embedding_lookup
from .sequence_parallel import ring_attention

__all__ = ["pipeline", "sharding", "sequence_parallel",
           "HybridParallelTrainStep", "ShardedEmbedding",
           "sharded_embedding_lookup", "ring_attention"]
