"""Mesh-sharded embedding tables — the TPU-native sparse tier.

The reference serves huge embedding tables from a parameter-server runtime
(operators/distributed/large_scale_kv.h, distributed_lookup_table_op,
communicator.h:180).  On TPU the idiomatic design keeps the table IN HBM,
row-sharded over a mesh axis, and turns the lookup into collectives
(SURVEY §7 "sharded embedding tables + all_to_all on the mesh"):

  * each shard owns a contiguous row range [idx*V/n, (idx+1)*V/n);
  * a lookup gathers local hits and psums partial rows over the axis —
    one all-reduce of [B, S, D] replaces the PS pull RPC;
  * the gradient transposes to a local scatter-add (the "push").

The host-resident KV path for beyond-HBM tables stays in
distributed/fleet/runtime/parameter_server_runtime.py.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ._compat import shard_map as _shard_map

__all__ = ["ShardedEmbedding", "sharded_embedding_lookup"]


def sharded_embedding_lookup(table, ids, mesh: Mesh, axis: str = "mp"):
    """table: [V, D] sharded P(axis, None); ids: int [...] replicated over
    `axis` (may be dp-sharded on batch dims). Returns [..., D] embeddings.

    Differentiable: grad wrt table is the scatter-add transpose, sharded
    like the table."""
    n = mesh.shape[axis]
    V = table.shape[0]
    if V % n:
        raise ValueError(f"vocab {V} not divisible by {axis}={n}")
    per = V // n

    def spmd(tbl, ids):
        lo = jax.lax.axis_index(axis) * per
        loc = ids.astype(jnp.int32) - lo
        hit = (loc >= 0) & (loc < per)
        rows = jnp.take(tbl, jnp.clip(loc, 0, per - 1), axis=0)
        rows = jnp.where(hit[..., None], rows, 0)
        return jax.lax.psum(rows, axis)

    return _shard_map(
        spmd, mesh=mesh, in_specs=(P(axis, None), P()), out_specs=P(),
        axis_names=frozenset({axis}), check_vma=False)(table, ids)


class ShardedEmbedding:
    """Row-sharded table + lookup. `spec`/`sharding` expose the layout so
    trainers shard optimizer state identically."""

    def __init__(self, vocab_size: int, dim: int, mesh: Mesh,
                 axis: str = "mp", init_std: float = 0.01, seed: int = 0,
                 dtype=jnp.float32):
        self.vocab_size, self.dim = vocab_size, dim
        self.mesh, self.axis = mesh, axis
        self.spec = P(axis, None)
        self.sharding = NamedSharding(mesh, self.spec)
        rng = np.random.RandomState(seed)
        self.table = jax.device_put(
            jnp.asarray(rng.normal(0, init_std, (vocab_size, dim))
                        .astype(np.float32), dtype=dtype), self.sharding)

    def __call__(self, ids, table=None):
        return sharded_embedding_lookup(
            self.table if table is None else table, ids, self.mesh,
            self.axis)
