"""Sharded embedding — placeholder, filled in with the sparse tier."""
from __future__ import annotations

__all__ = ["ShardedEmbedding", "sharded_embedding_lookup"]


def sharded_embedding_lookup(*a, **k):  # pragma: no cover
    raise NotImplementedError


class ShardedEmbedding:  # pragma: no cover
    def __init__(self, *a, **k):
        raise NotImplementedError
