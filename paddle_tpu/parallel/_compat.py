"""jax version compat for shard_map.

`jax.shard_map` (with `axis_names` / `check_vma`) only exists in newer
jax; this image ships 0.4.37 where the API is
`jax.experimental.shard_map.shard_map` with `check_rep`. Every manual-
SPMD call site routes through this wrapper so the parallel tier runs on
both. On the old API `axis_names` is dropped — the call sites only
reference their named axis inside the body and leave the other mesh
axes unmentioned in the specs (replicated), which is exactly the
semantics full-manual shard_map gives them; `check_vma=False` maps to
`check_rep=False`.
"""
from __future__ import annotations

import jax

__all__ = ["shard_map"]


def shard_map(f, mesh, in_specs, out_specs, axis_names=None,
              check_vma=False):
    new = getattr(jax, "shard_map", None)
    if new is not None:
        kw = {"check_vma": check_vma}
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return new(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   **kw)
    from jax.experimental.shard_map import shard_map as old
    # `axis_names` is dropped: the old API's partial-auto spelling
    # (auto=complement) cannot differentiate through gather/psum on
    # 0.4.x, while full-manual matches these call sites' semantics —
    # each body only references its named axis and leaves the others
    # unmentioned in the specs (replicated)
    return old(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=bool(check_vma))
