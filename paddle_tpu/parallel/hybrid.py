"""HybridParallelTrainStep: GPT training over a (dp, pp, tp) mesh.

The TPU-native hybrid-parallel engine consumed by
`fleet.distributed_optimizer` when `DistributedStrategy.pipeline` /
`tensor_parallel` are on (reference chain: fluid PipelineOptimizer
optimizer.py:3666 + fleet meta_optimizers/pipeline_optimizer.py:24; TP has
no reference equivalent — SURVEY SS2.9 mandates a fresh pjit design).

One jitted step = fwd (+ pipeline schedule) + bwd + AdamW update:
  * dp: batch dim sharded; grad psum implicit in sharded autodiff.
  * tp: megatron-style PartitionSpecs on params (models/gpt.py
    `gpt_param_specs`); GSPMD partitions matmuls and inserts collectives.
  * pp: stacked per-stage block params + scan/ppermute GPipe
    (parallel/pipeline.py); autodiff yields the reverse schedule.
Optimizer state is sharded exactly like its param (ZeRO-free but
TP/PP-partitioned), donated every step.
"""
from __future__ import annotations

from functools import partial
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import gpt as G
from .pipeline import pipeline_apply
from .sharding import _restrict

__all__ = ["HybridParallelTrainStep", "make_hybrid_mesh"]

_DECAY = {"wte", "wpe", "wq", "wk", "wv", "wo", "w_up", "w_down",
          "we_up", "we_down"}


def make_hybrid_mesh(dp: int = 1, pp: int = 1, tp: int = 1, sp: int = 1,
                     ep: int = 1, devices=None) -> Mesh:
    """("pp","dp","sp","ep","tp") mesh — tp innermost so its collectives
    ride the fastest ICI links; ep next (MoE all_to_all dispatch); sp next
    (ring attention's ppermute hops); pp outermost (cheapest traffic: one
    activation per microbatch tick)."""
    devs = np.array(devices if devices is not None else jax.devices())
    n = dp * pp * tp * sp * ep
    if devs.size < n:
        raise ValueError(f"need {n} devices, have {devs.size}")
    return Mesh(devs[:n].reshape(pp, dp, sp, ep, tp),
                ("pp", "dp", "sp", "ep", "tp"))


class HybridParallelTrainStep:
    """step(ids[B, T]) -> loss; B must divide by dp (and by
    n_microbatches*dp when pp>1)."""

    def __init__(self, cfg: G.GPTConfig, mesh: Mesh | None = None,
                 dp: int = 1, pp: int = 1, tp: int = 1, sp: int = 1,
                 ep: int = 1, n_microbatches: int | None = None, lr=1e-4,
                 weight_decay: float = 0.01, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-8,
                 grad_clip_norm: float | None = 1.0, seed: int = 0,
                 sharding: bool = False, devices=None):
        if mesh is None:
            mesh = make_hybrid_mesh(dp, pp, tp, sp, ep, devices)
        self.sp = mesh.shape.get("sp", 1)
        self.pp = mesh.shape.get("pp", 1)
        self.ep = mesh.shape.get("ep", 1)
        if self.ep > 1 and cfg.num_experts <= 0:
            raise ValueError("ep>1 needs a MoE model (cfg.num_experts>0)")
        if cfg.num_experts > 0:
            if self.pp > 1:
                raise NotImplementedError(
                    "MoE x pipeline: the stage scan drops the per-layer "
                    "load-balance aux — shard experts OR layers (yet)")
            if self.ep > 1 and cfg.num_experts % self.ep:
                raise ValueError(
                    f"num_experts={cfg.num_experts} not divisible by "
                    f"ep={self.ep}")
        if self.sp > 1:
            if self.pp > 1:  # judged off the MESH, not the ctor args
                raise NotImplementedError(
                    "sp x pp nests two manual mesh axes — shard the "
                    "sequence OR the layers, not both (yet)")
            # sequence parallel => ring attention over the sp axis
            import dataclasses as _dc
            cfg = _dc.replace(cfg, attn_impl="ring")
        self.cfg = cfg
        self.mesh = mesh
        self.n_micro = n_microbatches or max(2 * self.pp, 1)
        if self.pp > 1 and cfg.dropout:
            raise NotImplementedError(
                "pipeline path is deterministic (dropout=0); the stage scan "
                "carries no rng")
        if cfg.num_layers % self.pp:
            raise ValueError(
                f"num_layers={cfg.num_layers} not divisible by pp={self.pp}")
        self._lr = lr
        self._seed = seed
        self._hyper = dict(beta1=beta1, beta2=beta2, epsilon=epsilon)
        self._wd = weight_decay
        self._clip = grad_clip_norm

        params = jax.tree_util.tree_map(jnp.asarray,
                                        G.init_gpt_params(cfg, seed))
        if self.pp > 1:
            lps = cfg.num_layers // self.pp
            params["blocks"] = {
                k: v.reshape(self.pp, lps, *v.shape[1:])
                for k, v in params["blocks"].items()}
        specs = G.gpt_param_specs(pp_stacked=self.pp > 1,
                                  moe=cfg.num_experts > 0)
        self._specs = jax.tree_util.tree_map(
            lambda s: _restrict(s, mesh), specs,
            is_leaf=lambda s: isinstance(s, P))
        self._shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), self._specs,
            is_leaf=lambda s: isinstance(s, P))
        self.params = jax.tree_util.tree_map(jax.device_put, params,
                                             self._shardings)
        names = {"wte": "wte", "wpe": "wpe", "lnf_s": "lnf_s",
                 "lnf_b": "lnf_b",
                 "blocks": {k: f"blocks.{k}" for k in params["blocks"]}}
        self._names = names
        # ZeRO-1 (strategy.sharding): optimizer moments shard over the dp
        # axis on a free divisible dim — each dp rank owns 1/dp of the
        # Adam state and computes its slice of the update; GSPMD inserts
        # the param all-gather (reference sharding/ZeRO stage-1
        # semantics, fleet sharding_configs)
        self.zero_sharding = bool(sharding) and mesh.shape.get("dp", 1) > 1

        def _opt_sharding(v, spec):
            if not self.zero_sharding:
                return NamedSharding(mesh, spec)
            ndp = mesh.shape["dp"]
            entries = list(spec) + [None] * (v.ndim - len(spec))
            for i in range(v.ndim):
                if entries[i] is None and v.shape[i] % ndp == 0:
                    entries[i] = "dp"
                    break
            return NamedSharding(mesh, P(*entries))

        self._opt_shardings = jax.tree_util.tree_map(
            lambda v, s: {"m1": _opt_sharding(v, s),
                          "m2": _opt_sharding(v, s)},
            self.params, self._specs,
            is_leaf=lambda s: isinstance(s, P))
        self.opt_state = jax.tree_util.tree_map(
            lambda v, sh: {"m1": jax.device_put(
                               jnp.zeros(v.shape, jnp.float32), sh["m1"]),
                           "m2": jax.device_put(
                               jnp.zeros(v.shape, jnp.float32), sh["m2"])},
            self.params, self._opt_shardings)
        repl = NamedSharding(mesh, P())
        self._pows = (jax.device_put(jnp.ones((1,), jnp.float32), repl),
                      jax.device_put(jnp.ones((1,), jnp.float32), repl))
        self._batch_sharding = NamedSharding(
            mesh, P("dp", "sp") if self.sp > 1 else P("dp"))
        self._jit_step = self._build(mesh)

    # ------------------------------------------------------------------
    def loss_fn(self, params, ids, key=None):
        cfg, mesh = self.cfg, self.mesh
        if cfg.num_experts > 0:
            from .moe import moe_context
            with moe_context(mesh, "ep"):
                return self._loss_inner(params, ids, key)
        return self._loss_inner(params, ids, key)

    def _loss_inner(self, params, ids, key=None):
        cfg, mesh = self.cfg, self.mesh
        if self.sp > 1:
            from .sequence_parallel import ring_context
            ids = jax.lax.with_sharding_constraint(
                ids, NamedSharding(mesh, P("dp", "sp")))
            with ring_context(mesh, "sp"):
                return G.gpt_loss(params, ids, cfg, key=key)
        if self.pp == 1:
            return G.gpt_loss(params, ids, cfg, key=key)
        M = self.n_micro
        B, T = ids.shape
        if B % M:
            raise ValueError(f"batch {B} not divisible by {M} microbatches")
        x = G._embed(params, ids, cfg)
        x = x.reshape(M, B // M, T, cfg.hidden_size)
        x = jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(None, "dp")))
        def stage_fn(blk, h):
            out, _ = jax.lax.scan(G.block_body(cfg), h, blk)
            return out

        out = pipeline_apply(stage_fn, params["blocks"], x, mesh, "pp")
        out = out.reshape(B, T, cfg.hidden_size)
        logits = G._head(params, out, cfg)
        return G.gpt_loss(params, ids, cfg, logits=logits)

    def _build(self, mesh):
        from ..fluid import registry
        opdef = registry.require("adamw")
        hyper = dict(self._hyper)
        opdef.fill_default_attrs(hyper)
        wd, clip = self._wd, self._clip
        names = self._names

        def step(params, opt_state, pows, ids, lr, key):
            loss, grads = jax.value_and_grad(self.loss_fn)(params, ids, key)
            if clip:
                leaves = jax.tree_util.tree_leaves(grads)
                gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(
                    jnp.float32))) for g in leaves))
                scale = jnp.minimum(1.0, clip / jnp.maximum(gnorm, 1e-12))
                grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
            lr_arr = jnp.asarray([lr], jnp.float32)
            b1p, b2p = pows

            def upd(p, g, st, name):
                ins = {"Param": [p], "Grad": [g], "LearningRate": [lr_arr],
                       "Moment1": [st["m1"]], "Moment2": [st["m2"]],
                       "Beta1Pow": [b1p], "Beta2Pow": [b2p]}
                attrs = dict(hyper)
                attrs["coeff"] = wd if name.split(".")[-1] in _DECAY else 0.0
                outs = opdef.compute(None, ins, attrs)
                return (outs["ParamOut"][0],
                        {"m1": outs["Moment1Out"][0],
                         "m2": outs["Moment2Out"][0]},
                        outs["Beta1PowOut"][0], outs["Beta2PowOut"][0])

            flat_p, tdef = jax.tree_util.tree_flatten(params)
            flat_g = jax.tree_util.tree_leaves(grads)
            flat_s = tdef.flatten_up_to(opt_state)
            flat_n = tdef.flatten_up_to(names)
            new_p, new_s = [], []
            for p, g, st, n in zip(flat_p, flat_g, flat_s, flat_n):
                np_, ns_, b1n, b2n = upd(p, g, st, n)
                new_p.append(np_)
                new_s.append(ns_)
            return (loss,
                    jax.tree_util.tree_unflatten(tdef, new_p),
                    jax.tree_util.tree_unflatten(tdef, new_s),
                    (b1n, b2n))

        repl = NamedSharding(mesh, P())
        return jax.jit(
            step, donate_argnums=(0, 1, 2),
            out_shardings=(repl, self._shardings, self._opt_shardings,
                           (repl, repl)))

    # ------------------------------------------------------------------
    def __call__(self, ids):
        ids = jax.device_put(jnp.asarray(ids), self._batch_sharding)
        lr = self._lr() if callable(self._lr) else float(self._lr)
        self._step_no = getattr(self, "_step_no", 0) + 1
        key = jax.random.fold_in(jax.random.PRNGKey(self._seed),
                                 self._step_no)
        loss, self.params, self.opt_state, self._pows = self._jit_step(
            self.params, self.opt_state, self._pows, ids,
            np.float32(lr), key)
        return loss

    def unstacked_params(self):
        """Params with block leaves back at [L, ...] (for parity checks /
        checkpoint export)."""
        p = jax.tree_util.tree_map(lambda x: x, self.params)
        if self.pp > 1:
            p["blocks"] = {k: v.reshape(-1, *v.shape[2:])
                           for k, v in p["blocks"].items()}
        return p
