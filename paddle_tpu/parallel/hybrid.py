"""HybridParallelTrainStep: GPT training over a (dp, pp, tp) mesh.

The TPU-native hybrid-parallel engine consumed by
`fleet.distributed_optimizer` when `DistributedStrategy.pipeline` /
`tensor_parallel` are on (reference chain: fluid PipelineOptimizer
optimizer.py:3666 + fleet meta_optimizers/pipeline_optimizer.py:24; TP has
no reference equivalent — SURVEY SS2.9 mandates a fresh pjit design).

One jitted step = fwd (+ pipeline schedule) + bwd + AdamW update:
  * dp: batch dim sharded; grad psum implicit in sharded autodiff.
  * tp: megatron-style PartitionSpecs on params (models/gpt.py
    `gpt_param_specs`); GSPMD partitions matmuls and inserts collectives.
  * pp: stacked per-stage block params + scan/ppermute GPipe
    (parallel/pipeline.py); autodiff yields the reverse schedule.
Optimizer state is sharded exactly like its param (ZeRO-free but
TP/PP-partitioned), donated every step.
"""
from __future__ import annotations

from functools import partial
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import gpt as G
from .pipeline import pipeline_apply
from .sharding import _restrict

__all__ = ["HybridParallelTrainStep", "make_hybrid_mesh"]

_DECAY = {"wte", "wpe", "wq", "wk", "wv", "wo", "w_up", "w_down",
          "we_up", "we_down"}


def make_hybrid_mesh(dp: int = 1, pp: int = 1, tp: int = 1, sp: int = 1,
                     ep: int = 1, devices=None) -> Mesh:
    """("pp","dp","sp","ep","tp") mesh — tp innermost so its collectives
    ride the fastest ICI links; ep next (MoE all_to_all dispatch); sp next
    (ring attention's ppermute hops); pp outermost (cheapest traffic: one
    activation per microbatch tick)."""
    devs = np.array(devices if devices is not None else jax.devices())
    n = dp * pp * tp * sp * ep
    if devs.size < n:
        raise ValueError(f"need {n} devices, have {devs.size}")
    return Mesh(devs[:n].reshape(pp, dp, sp, ep, tp),
                ("pp", "dp", "sp", "ep", "tp"))


class HybridParallelTrainStep:
    """step(ids[B, T]) -> loss; B must divide by dp (and by
    n_microbatches*dp when pp>1)."""

    def __init__(self, cfg: G.GPTConfig, mesh: Mesh | None = None,
                 dp: int = 1, pp: int = 1, tp: int = 1, sp: int = 1,
                 ep: int = 1, n_microbatches: int | None = None, lr=1e-4,
                 weight_decay: float = 0.01, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-8,
                 grad_clip_norm: float | None = 1.0, seed: int = 0,
                 sharding: bool = False, devices=None,
                 pipeline_schedule: str = "1F1B"):
        if mesh is None:
            mesh = make_hybrid_mesh(dp, pp, tp, sp, ep, devices)
        self.sp = mesh.shape.get("sp", 1)
        self.pp = mesh.shape.get("pp", 1)
        self.ep = mesh.shape.get("ep", 1)
        # reference schedule_mode values: "1F1B" (SectionWorker interleave,
        # here parallel/pipeline_1f1b.py) and "F-then-B" (GPipe, here the
        # differentiable scan in parallel/pipeline.py)
        if pipeline_schedule not in ("1F1B", "F-then-B", "gpipe"):
            raise ValueError(f"unknown pipeline_schedule "
                             f"{pipeline_schedule!r}")
        self._schedule = "1F1B" if pipeline_schedule == "1F1B" else "gpipe"
        if self.ep > 1 and cfg.num_experts <= 0:
            raise ValueError("ep>1 needs a MoE model (cfg.num_experts>0)")
        if cfg.num_experts > 0:
            if self.pp > 1 and self._schedule != "1F1B":
                raise NotImplementedError(
                    "MoE x pipeline needs schedule_mode='1F1B' (the GPipe "
                    "scan drops the per-layer load-balance aux; the 1F1B "
                    "engine threads it through each stage's vjp)")
            if self.ep > 1 and cfg.num_experts % self.ep:
                raise ValueError(
                    f"num_experts={cfg.num_experts} not divisible by "
                    f"ep={self.ep}")
        if self.sp > 1:
            if self.pp > 1 and self._schedule != "1F1B":
                raise NotImplementedError(
                    "sp x pp needs schedule_mode='1F1B': the ring "
                    "attention rides INSIDE the 1F1B stage functions "
                    "(sp stays a GSPMD axis with the ring's shard_map "
                    "nested in the pp-manual region); the GPipe scan "
                    "has no per-stage function to host it")
            # sequence parallel => ring attention over the sp axis
            import dataclasses as _dc
            cfg = _dc.replace(cfg, attn_impl="ring")
        self.cfg = cfg
        self.mesh = mesh
        self.n_micro = n_microbatches or max(2 * self.pp, 1)
        if self.pp > 1 and cfg.dropout and self._schedule != "1F1B":
            raise NotImplementedError(
                "pipeline dropout needs schedule_mode='1F1B' (its stage "
                "functions re-derive per-(stage, microbatch) rng keys; the "
                "GPipe scan carries no rng)")
        if cfg.num_layers % self.pp:
            raise ValueError(
                f"num_layers={cfg.num_layers} not divisible by pp={self.pp}")
        self._lr = lr
        self._seed = seed
        self._hyper = dict(beta1=beta1, beta2=beta2, epsilon=epsilon)
        self._wd = weight_decay
        self._clip = grad_clip_norm

        params = jax.tree_util.tree_map(jnp.asarray,
                                        G.init_gpt_params(cfg, seed))
        if self.pp > 1:
            lps = cfg.num_layers // self.pp
            params["blocks"] = {
                k: v.reshape(self.pp, lps, *v.shape[1:])
                for k, v in params["blocks"].items()}
        specs = G.gpt_param_specs(pp_stacked=self.pp > 1,
                                  moe=cfg.num_experts > 0)
        self._specs = jax.tree_util.tree_map(
            lambda s: _restrict(s, mesh), specs,
            is_leaf=lambda s: isinstance(s, P))
        self._shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), self._specs,
            is_leaf=lambda s: isinstance(s, P))
        self.params = jax.tree_util.tree_map(jax.device_put, params,
                                             self._shardings)
        names = {"wte": "wte", "wpe": "wpe", "lnf_s": "lnf_s",
                 "lnf_b": "lnf_b",
                 "blocks": {k: f"blocks.{k}" for k in params["blocks"]}}
        self._names = names
        # ZeRO-1 (strategy.sharding): optimizer moments shard over the dp
        # axis on a free divisible dim — each dp rank owns 1/dp of the
        # Adam state and computes its slice of the update; GSPMD inserts
        # the param all-gather (reference sharding/ZeRO stage-1
        # semantics, fleet sharding_configs)
        self.zero_sharding = bool(sharding) and mesh.shape.get("dp", 1) > 1

        def _opt_sharding(v, spec):
            if not self.zero_sharding:
                return NamedSharding(mesh, spec)
            ndp = mesh.shape["dp"]
            entries = list(spec) + [None] * (v.ndim - len(spec))
            for i in range(v.ndim):
                if entries[i] is None and v.shape[i] % ndp == 0:
                    entries[i] = "dp"
                    break
            return NamedSharding(mesh, P(*entries))

        self._opt_shardings = jax.tree_util.tree_map(
            lambda v, s: {"m1": _opt_sharding(v, s),
                          "m2": _opt_sharding(v, s)},
            self.params, self._specs,
            is_leaf=lambda s: isinstance(s, P))
        self.opt_state = jax.tree_util.tree_map(
            lambda v, sh: {"m1": jax.device_put(
                               jnp.zeros(v.shape, jnp.float32), sh["m1"]),
                           "m2": jax.device_put(
                               jnp.zeros(v.shape, jnp.float32), sh["m2"])},
            self.params, self._opt_shardings)
        repl = NamedSharding(mesh, P())
        self._pows = (jax.device_put(jnp.ones((1,), jnp.float32), repl),
                      jax.device_put(jnp.ones((1,), jnp.float32), repl))
        self._batch_sharding = NamedSharding(
            mesh, P("dp", "sp") if self.sp > 1 else P("dp"))
        self._jit_step = self._build(mesh)

    # ------------------------------------------------------------------
    def loss_fn(self, params, ids, key=None):
        cfg, mesh = self.cfg, self.mesh
        if cfg.num_experts > 0:
            from .moe import moe_context
            with moe_context(mesh, "ep"):
                return self._loss_inner(params, ids, key)
        return self._loss_inner(params, ids, key)

    def _loss_inner(self, params, ids, key=None):
        cfg, mesh = self.cfg, self.mesh
        if self.sp > 1:
            from .sequence_parallel import ring_context
            ids = jax.lax.with_sharding_constraint(
                ids, NamedSharding(mesh, P("dp", "sp")))
            with ring_context(mesh, "sp"):
                return G.gpt_loss(params, ids, cfg, key=key)
        if self.pp == 1:
            return G.gpt_loss(params, ids, cfg, key=key)
        M = self.n_micro
        B, T = ids.shape
        if B % M:
            raise ValueError(f"batch {B} not divisible by {M} microbatches")
        x = G._embed(params, ids, cfg)
        x = x.reshape(M, B // M, T, cfg.hidden_size)
        x = jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(None, "dp")))
        def stage_fn(blk, h):
            out, _ = jax.lax.scan(G.block_body(cfg), h, blk)
            return out

        out = pipeline_apply(stage_fn, params["blocks"], x, mesh, "pp")
        out = out.reshape(B, T, cfg.hidden_size)
        logits = G._head(params, out, cfg)
        return G.gpt_loss(params, ids, cfg, logits=logits)

    # ------------------------------------------------------------------
    def _loss_and_grads_1f1b(self, params, ids, key):
        """pp>1 1F1B path: loss/grads come from the schedule engine
        (parallel/pipeline_1f1b.py), not from differentiating the forward;
        the embedding is kept under outer autodiff via jax.vjp and its
        cotangent routed from stage 0's input grads."""
        cfg, mesh = self.cfg, self.mesh
        M = self.n_micro
        B, T = ids.shape
        if B % M:
            raise ValueError(f"batch {B} not divisible by {M} microbatches")
        ids_mb = ids.reshape(M, B // M, T)
        lps = cfg.num_layers // self.pp
        use_drop = bool(cfg.dropout) and key is not None
        from .pipeline_1f1b import pipeline_1f1b_grads
        n_auto = sum(1 for ax in ("dp", "tp", "sp", "ep")
                     if mesh.shape.get(ax, 1) > 1)
        if n_auto >= 2:
            # see the partitioner-workaround comment below: the embedding
            # table is consumed replicated throughout this step (its grad
            # is resharded to the tp spec by the jit out_shardings), and
            # the per-layer jax.checkpoint inside the stage scan is
            # dropped (also a partitioner trigger on this combo) — the
            # 1F1B engine already remats at stage granularity, so only
            # the within-B-tick residual footprint grows
            import dataclasses as _dc
            cfg = _dc.replace(cfg, remat=False)
            params = dict(params)
            params["wte"] = jax.lax.with_sharding_constraint(
                params["wte"], NamedSharding(mesh, P()))

        def emb_fn(embp):
            x = jnp.take(embp["wte"], ids_mb, axis=0) + embp["wpe"][:T]
            if cfg.amp_dtype:
                x = x.astype(jnp.dtype(cfg.amp_dtype))
            if use_drop:
                x = G._dropout(x, cfg.dropout,
                               jax.random.fold_in(key, 0x5eed))
            return x

        embp = {"wte": params["wte"], "wpe": params["wpe"]}
        x0, emb_vjp = jax.vjp(emb_fn, embp)
        x0 = jax.lax.with_sharding_constraint(
            x0, NamedSharding(mesh, P(None, "dp", "sp")
                              if self.sp > 1 else P(None, "dp")))

        def stage_fn(local, x, k):
            if use_drop:
                lkeys = jax.random.split(k, lps)
                y, auxs = jax.lax.scan(G.block_body_keyed(cfg), x,
                                       (local, lkeys))
            else:
                y, auxs = jax.lax.scan(G.block_body(cfg), x, local)
            return y, jnp.sum(auxs)

        def last_fn(local, sh, x, ids_one, k):
            y, aux = stage_fn(local, x, k)
            logits = G._head({"wte": sh["wte"], "lnf_s": sh["lnf_s"],
                              "lnf_b": sh["lnf_b"]}, y, cfg)
            loss = G.gpt_loss(None, ids_one, cfg, logits=logits)
            return y, loss, aux

        # XLA's SPMD partitioner Check-fails (spmd_partitioner_util.cc
        # group bookkeeping) when TWO auto mesh axes (e.g. dp and tp) are
        # active beside the manual pp axis and either (a) lax.cond
        # branches carry tp collectives or (b) the tp-vocab-sharded head
        # matmul sits inside the manual region. For that combo: run the
        # cond-free uniform executor (blocks+head every B-tick, cotangent-
        # masked) AND consume the embedding/head table replicated (one
        # wte all-gather per step, applied above). Verified exact-loss/
        # grad parity vs the sharded-head cond executor on
        # single-auto-axis meshes.
        shared = {"wte": params["wte"], "lnf_s": params["lnf_s"],
                  "lnf_b": params["lnf_b"]}
        aux_w = cfg.moe_aux_weight if cfg.num_experts > 0 else 0.0
        import contextlib
        ring_cm = contextlib.nullcontext()
        if self.sp > 1:
            # sp x pp: the sequence stays a GSPMD ("auto") axis inside
            # the pp-manual region; attention drops into the ring's own
            # shard_map over "sp" NESTED in the 1F1B engine's manual
            # region — the manual axes sets are disjoint, which jax's
            # shard_map supports
            from .sequence_parallel import ring_context
            ring_cm = ring_context(mesh, "sp")
        with ring_cm:
            loss, gblocks, gshared, dx0 = pipeline_1f1b_grads(
                stage_fn, last_fn, params["blocks"], shared, x0, ids_mb,
                mesh, "pp", aux_weight=aux_w, key=key,
                uniform_last=n_auto >= 2,
                uniform_all=self.sp > 1)
        (gemb,) = emb_vjp(dx0)
        grads = {"wte": gshared["wte"] + gemb["wte"].astype(jnp.float32),
                 "wpe": gemb["wpe"].astype(jnp.float32),
                 "lnf_s": gshared["lnf_s"], "lnf_b": gshared["lnf_b"],
                 "blocks": gblocks}
        return loss, grads

    def _build(self, mesh):
        from ..fluid import registry
        opdef = registry.require("adamw")
        hyper = dict(self._hyper)
        opdef.fill_default_attrs(hyper)
        wd, clip = self._wd, self._clip
        names = self._names
        use_1f1b = self.pp > 1 and self._schedule == "1F1B"

        def grads_1f1b(params, ids, key):
            if self.cfg.num_experts > 0:
                from .moe import moe_context
                with moe_context(mesh, "ep"):
                    return self._loss_and_grads_1f1b(params, ids, key)
            return self._loss_and_grads_1f1b(params, ids, key)

        def apply_update(params, opt_state, pows, grads, lr):
            if clip:
                leaves = jax.tree_util.tree_leaves(grads)
                gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(
                    jnp.float32))) for g in leaves))
                scale = jnp.minimum(1.0, clip / jnp.maximum(gnorm, 1e-12))
                grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
            lr_arr = jnp.asarray([lr], jnp.float32)
            b1p, b2p = pows

            def upd(p, g, st, name):
                ins = {"Param": [p], "Grad": [g], "LearningRate": [lr_arr],
                       "Moment1": [st["m1"]], "Moment2": [st["m2"]],
                       "Beta1Pow": [b1p], "Beta2Pow": [b2p]}
                attrs = dict(hyper)
                attrs["coeff"] = wd if name.split(".")[-1] in _DECAY else 0.0
                outs = opdef.compute(None, ins, attrs)
                return (outs["ParamOut"][0],
                        {"m1": outs["Moment1Out"][0],
                         "m2": outs["Moment2Out"][0]},
                        outs["Beta1PowOut"][0], outs["Beta2PowOut"][0])

            flat_p, tdef = jax.tree_util.tree_flatten(params)
            flat_g = jax.tree_util.tree_leaves(grads)
            flat_s = tdef.flatten_up_to(opt_state)
            flat_n = tdef.flatten_up_to(names)
            new_p, new_s = [], []
            for p, g, st, n in zip(flat_p, flat_g, flat_s, flat_n):
                np_, ns_, b1n, b2n = upd(p, g, st, n)
                new_p.append(np_)
                new_s.append(ns_)
            return (jax.tree_util.tree_unflatten(tdef, new_p),
                    jax.tree_util.tree_unflatten(tdef, new_s),
                    (b1n, b2n))

        repl = NamedSharding(mesh, P())
        if use_1f1b:
            # TWO dispatches: the schedule+grads program, then the
            # clip+AdamW program. Fusing them into one jit Check-fails
            # XLA's SPMD partitioner when the pipeline's manual region,
            # dropout rng and the global-norm reduction meet on a
            # multi-auto-axis mesh; split programs compile clean and the
            # extra dispatch is noise next to a pipeline step.
            jit_grads = jax.jit(grads_1f1b, out_shardings=None)
            jit_update = jax.jit(
                apply_update, donate_argnums=(0, 1, 2, 3),
                out_shardings=(self._shardings, self._opt_shardings,
                               (repl, repl)))

            def step2(params, opt_state, pows, ids, lr, key):
                loss, grads = jit_grads(params, ids, key)
                new_p, new_s, new_pows = jit_update(params, opt_state,
                                                    pows, grads, lr)
                return loss, new_p, new_s, new_pows

            step2._jit_grads = jit_grads      # introspection (tests)
            step2._jit_update = jit_update
            return step2

        def step(params, opt_state, pows, ids, lr, key):
            loss, grads = jax.value_and_grad(self.loss_fn)(
                params, ids, key)
            new_p, new_s, new_pows = apply_update(params, opt_state, pows,
                                                  grads, lr)
            return loss, new_p, new_s, new_pows

        return jax.jit(
            step, donate_argnums=(0, 1, 2),
            out_shardings=(repl, self._shardings, self._opt_shardings,
                           (repl, repl)))

    # ------------------------------------------------------------------
    def __call__(self, ids):
        ids = jax.device_put(jnp.asarray(ids), self._batch_sharding)
        lr = self._lr() if callable(self._lr) else float(self._lr)
        self._step_no = getattr(self, "_step_no", 0) + 1
        key = jax.random.fold_in(jax.random.PRNGKey(self._seed),
                                 self._step_no)
        loss, self.params, self.opt_state, self._pows = self._jit_step(
            self.params, self.opt_state, self._pows, ids,
            np.float32(lr), key)
        return loss

    def unstacked_params(self):
        """Params with block leaves back at [L, ...] (for parity checks /
        checkpoint export)."""
        p = jax.tree_util.tree_map(lambda x: x, self.params)
        if self.pp > 1:
            p["blocks"] = {k: v.reshape(-1, *v.shape[2:])
                           for k, v in p["blocks"].items()}
        return p
