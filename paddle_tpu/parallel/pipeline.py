"""GPipe pipeline parallelism as one differentiable XLA computation.

The reference implements PP by splitting the ProgramDesc into per-device
section programs (fluid/optimizer.py:3790 `_split_program`) executed by
SectionWorker threads streaming microbatches through blocking queues
(framework/device_worker.h:415).  On TPU the whole schedule becomes a single
SPMD computation instead: every stage's weights live on its "pp" mesh slice,
a `lax.scan` steps the clock, and `lax.ppermute` rotates activations around
the stage ring.  `jax.grad` differentiates straight through the scan +
ppermute, which *is* the reverse pipeline schedule — no hand-written 1F1B
bookkeeping, no host threads, no queues.

The shard_map is partial-manual: only the "pp" axis is manual; data- and
tensor-parallel axes stay in GSPMD "auto" mode, so the per-stage compute is
still partitioned over dp/tp by XLA.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ._compat import shard_map as _shard_map

__all__ = ["pipeline_apply", "num_ticks"]


def num_ticks(n_micro: int, n_stages: int) -> int:
    """GPipe clock length: M microbatches through S stages."""
    return n_micro + n_stages - 1


def pipeline_apply(stage_fn: Callable, stage_params: Any, mb_inputs,
                   mesh, axis_name: str = "pp"):
    """Run microbatches through a ring of pipeline stages.

    Args:
      stage_fn: (params_leafslice, x) -> y with y.shape == x.shape; applies
        one stage's worth of layers. Runs under GSPMD for non-pp axes.
      stage_params: pytree whose leaves are stacked per-stage [S, ...] and
        sharded P(axis_name, ...) on dim 0.
      mb_inputs: [M, mb, ...] microbatched activations, replicated over pp
        (other dims may be dp/tp-sharded; GSPMD keeps them sharded inside).
      mesh: jax.sharding.Mesh containing axis_name.
      axis_name: the pipeline mesh axis.

    Returns:
      [M, mb, ...] outputs of the final stage (same sharding as mb_inputs).
    """
    n_stages = mesh.shape[axis_name]
    n_micro = mb_inputs.shape[0]
    if n_stages == 1:
        params0 = jax.tree_util.tree_map(lambda x: x[0], stage_params)

        def body(carry, x):
            return carry, stage_fn(params0, x)

        _, out = jax.lax.scan(body, 0, mb_inputs)
        return out
    if n_micro < n_stages:
        raise ValueError(
            f"need microbatches >= pipeline stages, got {n_micro} < "
            f"{n_stages} (bubble would dominate; reference asserts the same "
            f"in PipelineOptimizer)")

    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def spmd(params, mbs):
        stage = jax.lax.axis_index(axis_name)
        local = jax.tree_util.tree_map(lambda x: x[0], params)
        state = jnp.zeros_like(mbs[0])
        outbuf = jnp.zeros_like(mbs)

        def tick(carry, t):
            state, outbuf = carry
            inject = mbs[jnp.minimum(t, n_micro - 1)]
            x = jnp.where(stage == 0, inject, state)
            y = stage_fn(local, x)
            # final stage completes microbatch t-(S-1) at tick t
            om = t - (n_stages - 1)
            is_out = jnp.logical_and(stage == n_stages - 1, om >= 0)
            upd = jax.lax.dynamic_update_index_in_dim(
                outbuf, y, jnp.maximum(om, 0), 0)
            outbuf = jnp.where(is_out, upd, outbuf)
            state = jax.lax.ppermute(y, axis_name, perm)
            return (state, outbuf), None

        (state, outbuf), _ = jax.lax.scan(
            tick, (state, outbuf), jnp.arange(num_ticks(n_micro, n_stages)))
        # only the last stage's buffer is real; stack stages and let the
        # caller's slice of [-1] compile to a plain shard read
        return outbuf[None]

    stacked = _shard_map(
        spmd, mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(axis_name),
        axis_names=frozenset({axis_name}),
        check_vma=False,
    )(stage_params, mb_inputs)
    return stacked[-1]
