"""Name-pattern -> PartitionSpec sharding rules (tensor parallelism).

Tensor parallel is absent from the reference (SURVEY SS2.9) and designed
fresh here the TPU way: instead of col/row-parallel layer classes that
hand-insert collectives (Megatron style), parameters are annotated with
`PartitionSpec`s and GSPMD partitions the matmuls and inserts the
all-reduces.  A rule table maps parameter-name regexes to specs, so the same
model code runs unsharded, dp-only, or dp x tp by swapping the rule set.
"""
from __future__ import annotations

import re
from typing import Any, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["ShardingRules", "shard_tree", "spec_for"]


class ShardingRules:
    """Ordered (regex, PartitionSpec) table; first match wins.

    Axis names appearing in a spec but absent from the mesh are dropped at
    resolution time, so one rule set serves tp=1 and tp>1 meshes.
    """

    def __init__(self, rules: Sequence[tuple[str, P]] | None = None,
                 default: P = P()):
        self.rules = [(re.compile(pat), spec) for pat, spec in (rules or [])]
        self.default = default

    def spec(self, name: str, mesh: Mesh | None = None,
             ndim: int | None = None) -> P:
        spec = self.default
        for pat, s in self.rules:
            if pat.search(name):
                spec = s
                break
        if mesh is not None:
            spec = _restrict(spec, mesh)
        if ndim is not None and len(spec) > ndim:
            raise ValueError(
                f"spec {spec} for {name!r} has more dims than the {ndim}-d "
                f"param")
        return spec

    def sharding(self, name: str, mesh: Mesh, ndim: int | None = None):
        return NamedSharding(mesh, self.spec(name, mesh, ndim))


def _restrict(spec: P, mesh: Mesh) -> P:
    """Drop axis names the mesh doesn't have (or that have size 1)."""
    out = []
    for entry in spec:
        if entry is None:
            out.append(None)
        elif isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry
                         if a in mesh.shape and mesh.shape[a] > 1)
            out.append(kept if kept else None)
        else:
            out.append(entry if entry in mesh.shape and
                       mesh.shape[entry] > 1 else None)
    return P(*out)


def spec_for(tree_of_names: Any, rules: ShardingRules, mesh: Mesh):
    """Map a pytree of param names to a pytree of NamedShardings."""
    return jax.tree_util.tree_map(
        lambda n: rules.sharding(n, mesh), tree_of_names)


def shard_tree(params: Any, names: Any, rules: ShardingRules, mesh: Mesh):
    """device_put every leaf with its resolved rule sharding."""
    return jax.tree_util.tree_map(
        lambda v, n: jax.device_put(v, rules.sharding(n, mesh, v.ndim)),
        params, names)
