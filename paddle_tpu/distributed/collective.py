"""Collective communication API (reference python/paddle/distributed/collective.py:59-419).

Replaces c_allreduce_*/c_broadcast/... NCCL ops (operators/collective/) with
XLA collectives. Two regimes:
  * inside a sharded computation (shard_map/pjit trace): ops lower to
    lax.psum/all_gather/ppermute over a named mesh axis — this is the ICI
    fast path used by the static executor and fleet;
  * eager cross-process: jax.experimental.multihost_utils (DCN) for the
    dygraph API-parity path.
Also registers the c_* op types so transpiled Programs keep working.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..fluid.registry import register, same_shape_as
from ..fluid.ops.common import x, out

__all__ = ["ReduceOp", "all_reduce", "all_gather", "broadcast", "reduce",
           "scatter", "barrier", "split", "current_axis"]


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3


# name of the mesh axis collectives act on while tracing a sharded program;
# set by the executor / shard_map wrappers (replaces ring_id)
_axis_stack: list[str] = []


def current_axis() -> str | None:
    return _axis_stack[-1] if _axis_stack else None


import contextlib


@contextlib.contextmanager
def collective_axis(name: str):
    _axis_stack.append(name)
    try:
        yield
    finally:
        _axis_stack.pop()


def _eager_value(t):
    return t._value if hasattr(t, "_value") else t


def _wrap_like(t, val):
    from ..fluid.dygraph.varbase import Tensor
    if hasattr(t, "_value"):
        if isinstance(t, Tensor):
            t._set_value(val)
            return t
    return val


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """In-place all-reduce across processes (dygraph) or axis (traced)."""
    ax = current_axis()
    val = _eager_value(tensor)
    if ax is not None:
        fn = {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
              ReduceOp.MIN: jax.lax.pmin}.get(op)
        if fn is None:
            raise NotImplementedError("PROD allreduce on mesh")
        return _wrap_like(tensor, fn(val, ax))
    if jax.process_count() == 1:
        return tensor
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(val)
    red = {ReduceOp.SUM: jnp.sum, ReduceOp.MAX: jnp.max,
           ReduceOp.MIN: jnp.min, ReduceOp.PROD: jnp.prod}[op]
    return _wrap_like(tensor, red(gathered, axis=0))


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    ax = current_axis()
    val = _eager_value(tensor)
    if ax is not None:
        g = jax.lax.all_gather(val, ax)
        parts = [g[i] for i in range(g.shape[0])]
    elif jax.process_count() == 1:
        parts = [val]
    else:
        from jax.experimental import multihost_utils
        g = multihost_utils.process_allgather(val)
        parts = [g[i] for i in range(g.shape[0])]
    from ..fluid.dygraph.varbase import Tensor
    tensor_list.extend(Tensor(p, stop_gradient=True) for p in parts)
    return tensor_list


def broadcast(tensor, src=0, group=None, sync_op=True):
    ax = current_axis()
    val = _eager_value(tensor)
    if ax is not None:
        idx = jax.lax.axis_index(ax)
        src_val = jax.lax.psum(
            jnp.where(idx == src, val, jnp.zeros_like(val)), ax)
        return _wrap_like(tensor, src_val)
    if jax.process_count() == 1:
        return tensor
    from jax.experimental import multihost_utils
    return _wrap_like(tensor,
                      multihost_utils.broadcast_one_to_all(
                          val, jax.process_index() == src))


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    """Reduce to `dst`: only the destination ends up with the reduced
    value; other ranks keep their input (reference c_reduce_* semantics —
    previously this was a plain all_reduce, leaving the result on every
    rank)."""
    ax = current_axis()
    orig = _eager_value(tensor)
    if ax is not None:
        reduced = _eager_value(all_reduce(
            jnp.asarray(orig), op, group, sync_op))
        idx = jax.lax.axis_index(ax)
        return _wrap_like(tensor, jnp.where(idx == dst, reduced, orig))
    if jax.process_count() == 1:
        return tensor
    reduced = _eager_value(all_reduce(jnp.asarray(orig), op, group,
                                      sync_op))
    if jax.process_index() == dst:
        return _wrap_like(tensor, reduced)
    return _wrap_like(tensor, orig)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if jax.process_count() == 1 and current_axis() is None:
        if tensor_list:
            return _wrap_like(tensor, _eager_value(tensor_list[0]))
        return tensor
    raise NotImplementedError("scatter across processes lands with fleet PS")


def barrier(group=None):
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("paddle_tpu_barrier")


def split(x_, num_partitions, axis=0):
    from .. import tensor as T
    return T.split(x_, num_partitions, axis)


# ---------------------------------------------------------------------------
# c_* collective OPS for static programs (operators/collective/ parity).
# In a mesh-sharded execution these trace to axis collectives; in single
# process single-shard execution they are identities.
# ---------------------------------------------------------------------------

def _c_allreduce(fn):
    def compute(ctx, ins, attrs):
        v = x(ins)
        ax = attrs.get("axis_name") or current_axis() or \
            (getattr(ctx, "mesh_axis", None))
        if ax:
            return out(fn(v, ax))
        return out(v)
    return compute


register("c_allreduce_sum", _c_allreduce(jax.lax.psum),
         infer_shape=same_shape_as("X"),
         attrs={"ring_id": 0, "use_calc_stream": True, "axis_name": ""})
register("c_allreduce_max", _c_allreduce(jax.lax.pmax),
         infer_shape=same_shape_as("X"),
         attrs={"ring_id": 0, "use_calc_stream": True, "axis_name": ""})
register("c_allreduce_min", _c_allreduce(jax.lax.pmin),
         infer_shape=same_shape_as("X"),
         attrs={"ring_id": 0, "use_calc_stream": True, "axis_name": ""})


@register("c_allgather", attrs={"ring_id": 0, "nranks": 1,
                                "use_calc_stream": True, "axis_name": ""})
def _c_allgather(ctx, ins, attrs):
    v = x(ins)
    ax = attrs.get("axis_name") or current_axis()
    if ax:
        g = jax.lax.all_gather(v, ax)
        return out(g.reshape((-1,) + v.shape[1:]))
    return out(v)


@register("c_broadcast", attrs={"ring_id": 0, "root": 0,
                                "use_calc_stream": True, "axis_name": ""})
def _c_broadcast(ctx, ins, attrs):
    v = x(ins)
    ax = attrs.get("axis_name") or current_axis()
    if ax:
        idx = jax.lax.axis_index(ax)
        return out(jax.lax.psum(
            jnp.where(idx == attrs.get("root", 0), v, jnp.zeros_like(v)), ax))
    return out(v)


@register("c_reducescatter", attrs={"ring_id": 0, "nranks": 1,
                                    "use_calc_stream": True, "axis_name": ""})
def _c_reducescatter(ctx, ins, attrs):
    v = x(ins)
    ax = attrs.get("axis_name") or current_axis()
    if ax:
        return out(jax.lax.psum_scatter(v, ax, tiled=True))
    return out(v)


@register("c_concat", attrs={"ring_id": 0, "nranks": 1, "rank": 0,
                             "axis_name": ""})
def _c_concat(ctx, ins, attrs):
    v = x(ins)
    ax = attrs.get("axis_name") or current_axis()
    if ax:
        g = jax.lax.all_gather(v, ax)
        return out(jnp.concatenate(
            [g[i] for i in range(g.shape[0])], axis=-1))
    return out(v)


@register("c_identity", infer_shape=same_shape_as("X"),
          attrs={"ring_id": 0, "use_calc_stream": True})
def _c_identity(ctx, ins, attrs):
    return out(x(ins))


@register("c_split", attrs={"ring_id": 0, "nranks": 1, "rank": 0,
                            "axis_name": ""})
def _c_split(ctx, ins, attrs):
    v = x(ins)
    ax = attrs.get("axis_name") or current_axis()
    n = attrs.get("nranks", 1)
    if ax:
        idx = jax.lax.axis_index(ax)
        size = v.shape[-1] // n
        return out(jax.lax.dynamic_slice_in_dim(v, idx * size, size, -1))
    return out(v)


@register("c_sync_calc_stream", grad=None, infer_shape=same_shape_as("X"))
def _c_sync_calc(ctx, ins, attrs):
    return out(x(ins))


@register("c_sync_comm_stream", grad=None, infer_shape=same_shape_as("X"))
def _c_sync_comm(ctx, ins, attrs):
    return out(x(ins))


@register("c_comm_init_all", grad=None, attrs={"ring_id": 0, "devices": []})
def _c_comm_init_all(ctx, ins, attrs):
    return {}  # comm setup is XLA's job; kept for program parity


@register("c_gen_nccl_id", grad=None, attrs={"rank": 0})
def _c_gen_nccl_id(ctx, ins, attrs):
    return {}  # obsolete under jax.distributed bootstrap


@register("c_comm_init", grad=None, attrs={"ring_id": 0, "rank": 0,
                                           "nranks": 1})
def _c_comm_init(ctx, ins, attrs):
    return {}


@register("c_wait_calc_stream", grad=None, infer_shape=same_shape_as("X"))
def _c_wait_calc(ctx, ins, attrs):
    return out(x(ins))


@register("barrier", grad=None)
def _barrier_op(ctx, ins, attrs):
    return out(x(ins)) if ins.get("X") else {}
