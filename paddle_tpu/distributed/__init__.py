"""paddle.distributed — mesh-collective distribution layer.

Replaces the reference's NCCL/Gloo/gRPC triple stack (SURVEY §5) with XLA
collectives over jax.sharding Mesh axes. Filled out across:
  env.py         — rank/world bootstrap (jax.distributed)
  collective.py  — all_reduce/all_gather/... API parity
  mesh.py        — global device mesh management
  fleet/         — fleet 2.0 facade + DistributedStrategy
  parallel.py    — init_parallel_env / DataParallel
"""
from .env import (get_rank, get_world_size, init_parallel_env, ParallelEnv)
from .mesh import (get_mesh, set_mesh, default_mesh)
from .collective import (all_reduce, all_gather, broadcast, reduce, scatter,
                         barrier, split, ReduceOp)
from .parallel import DataParallel
from . import fleet
from .spawn import spawn

__all__ = ["get_rank", "get_world_size", "init_parallel_env", "ParallelEnv",
           "all_reduce", "all_gather", "broadcast", "reduce", "scatter",
           "barrier", "split", "ReduceOp", "fleet", "DataParallel", "spawn",
           "get_mesh", "set_mesh", "default_mesh"]
