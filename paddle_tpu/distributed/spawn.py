"""paddle.distributed.spawn (reference python/paddle/distributed/spawn.py).

On TPU a single process drives all local chips through the mesh, so spawn
degenerates to running `func` once; multi-host launch goes through
`python -m paddle_tpu.distributed.launch` (fleetrun) instead.
"""
from __future__ import annotations

__all__ = ["spawn"]


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    func(*args)
    return None
