"""paddle.distributed.spawn (reference python/paddle/distributed/spawn.py).

Forks `nprocs` worker processes with fleetrun-style PADDLE_* env and runs
`func(*args)` in each — the in-Python twin of
`python -m paddle_tpu.distributed.launch`.  Note the TPU stance: a single
process already drives all local chips through the mesh, so spawn is for
multi-process semantics (PS tests, DCN simulation), not for per-device
workers like the reference's per-GPU processes.
"""
from __future__ import annotations

import multiprocessing as mp
import os

from .launch import get_cluster_env

__all__ = ["spawn"]


def _worker(rank, endpoints, func, args):
    os.environ.update(get_cluster_env(rank, endpoints))
    func(*args)


def _free_ports(n: int) -> list[int]:
    import socket
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def spawn(func, args=(), nprocs=-1, join=True, daemon=False,
          started_port=None, timeout=None, **options):
    """Run func in `nprocs` processes (nprocs<=1: run inline).

    Ports default to freshly-bound free ports (a fixed base would collide
    across concurrent spawns on one host). One worker failing terminates
    the rest — joining a blocked sibling of a dead rank would hang
    forever."""
    if nprocs is None or nprocs <= 1:
        func(*args)
        return None
    if started_port is None:
        ports = _free_ports(nprocs)
    else:
        ports = [started_port + i for i in range(nprocs)]
    endpoints = [f"127.0.0.1:{p}" for p in ports]
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        p = ctx.Process(target=_worker,
                        args=(rank, endpoints, func, args), daemon=daemon)
        p.start()
        procs.append(p)
    if not join:
        return procs
    import time
    deadline = None if timeout is None else time.time() + timeout
    failed = []
    while True:
        codes = [p.exitcode for p in procs]
        failed = [(r, c) for r, c in enumerate(codes)
                  if c is not None and c != 0]
        if failed or all(c == 0 for c in codes):
            break
        if deadline is not None and time.time() > deadline:
            failed = [(r, "timeout") for r, c in enumerate(codes)
                      if c is None]
            break
        time.sleep(0.05)
    if failed:
        for p in procs:
            if p.is_alive():
                p.terminate()
        for p in procs:
            p.join(5)
        raise RuntimeError(f"spawn workers failed: {failed}")
    return None
