"""Heterogeneous PS training tier: CPU sparse workers + device dense
worker.

TPU-native rebuild of the reference's heterogeneous trainer stack —
HeterWrapper (/root/reference/paddle/fluid/framework/fleet/
heter_wrapper.h:54), the heter service tensor RPC
(framework/heter_service.h) and HeterXpuTrainer
(framework/trainer.h:149). The reference splits a CTR job so cheap
host-CPU machines run IO + embedding lookup while accelerator workers
run the dense net, exchanging activations/gradients over an RPC bridge.

Here the split is functional and explicit:

  HeterCpuWorker  (role "cpu", N processes)
      owns the SPARSE tier — pulls embedding rows from the PS/KV
      (TCP PSClient or in-process LargeScaleKV), gathers + flattens the
      batch's sparse features host-side, ships the activation bundle to
      the dense worker, receives activation gradients back, scatters
      them into per-row sparse grads and pushes them to the PS.

  HeterDenseWorker  (role "device", 1 process)
      owns the DENSE net — a single jitted train step
      (value_and_grad w.r.t. params AND the incoming activations) on
      whatever jax device is present (TPU in prod, CPU in tests),
      applies local SGD to the dense params, and returns (loss, d_emb,
      d_wide) to the requesting CPU worker. Serves all CPU workers
      concurrently over the same fault-tolerant transport the PS tier
      uses (async/Downpour semantics: no cross-worker barrier).

The wire protocol reuses runtime/rpc.py's data-only framing (no pickle
on the receive path; optional PADDLE_PS_SECRET handshake), so the whole
topology (PS shards + dense worker + N cpu workers) is plain TCP on
localhost in tests and across hosts in deployment — and a retried
"step" is applied exactly once (the dense server dedups request ids, so
a reply lost to the network cannot double-apply an SGD update).
"""
from __future__ import annotations

import socketserver
import threading

import numpy as np

from .runtime.parameter_server_runtime import LargeScaleKV, PSClient
from .runtime.rpc import RpcClient, RpcServerState, serve_connection

__all__ = ["HeterDenseWorker", "HeterCpuWorker"]


class HeterDenseWorker(socketserver.ThreadingTCPServer):
    """Accelerator-side dense trainer (HeterXpuTrainer parity).

    Protocol (request -> reply):
      {"op": "step", "emb": [B,S*D], "wide": [B,1], "dense": [B,F],
       "label": [B,1]}
          -> {"loss": float, "d_emb": [B,S*D], "d_wide": [B,1]}
      {"op": "params"} -> {"mlp": ..., "wide_dense": ..., "bias": ...}
      {"op": "stop"} -> {"ok": True}
    """

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, cfg, endpoint: str = "127.0.0.1:0",
                 lr: float = 1e-2, seed: int = 0):
        import jax
        import jax.numpy as jnp

        from ...models.wide_deep import init_widedeep_params

        host, port = endpoint.rsplit(":", 1)
        self.cfg = cfg
        self.lr = lr
        ref = init_widedeep_params(cfg, seed)
        self.params = {"mlp": ref["mlp"],
                       "wide_dense": ref["wide_dense"],
                       "bias": ref["bias"]}
        self._plock = threading.Lock()
        self.losses: list[float] = []
        self._stop = threading.Event()

        def dense_loss(params, emb_flat, wide_sum, dense, label):
            h = jnp.concatenate([emb_flat, dense], axis=-1)
            for i, layer in enumerate(params["mlp"]):
                h = h @ layer["w"] + layer["b"]
                if i < len(params["mlp"]) - 1:
                    h = jax.nn.relu(h)
            z = h + wide_sum + dense @ params["wide_dense"] \
                + params["bias"]
            lab = label.astype(jnp.float32).reshape(z.shape)
            return jnp.mean(jnp.maximum(z, 0) - z * lab
                            + jnp.log1p(jnp.exp(-jnp.abs(z))))

        # grads w.r.t. params (local update) AND the sparse-side
        # activations (shipped back — heter_service.h's grad tensors)
        self._grad_fn = jax.jit(
            jax.value_and_grad(dense_loss, argnums=(0, 1, 2)))

        # "params" is the only read op; "step"/"stop" mutate and are
        # deduped by request id (exactly-once across client retries)
        self._rpc = RpcServerState(read_ops={"params", "ping"})
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                serve_connection(self.request, outer._dispatch,
                                 outer._rpc)

        super().__init__((host, int(port)), Handler)

    @property
    def endpoint(self) -> str:
        return f"{self.server_address[0]}:{self.server_address[1]}"

    def _dispatch(self, req: dict) -> dict:
        op = req.get("op")
        if op == "step":
            return self._step(req)
        if op == "params":
            with self._plock:
                return {k: np.asarray(v) if not isinstance(v, list) else
                        [{kk: np.asarray(vv) for kk, vv in l.items()}
                         for l in v]
                        for k, v in self.params.items()}
        if op == "stop":
            self._stop.set()
            threading.Thread(target=self.shutdown, daemon=True).start()
            return {"ok": True}
        if op == "ping":
            return "pong"
        raise ValueError(f"unknown heter op {op!r}")

    def _step(self, req: dict) -> dict:
        import jax.numpy as jnp
        emb = jnp.asarray(req["emb"], jnp.float32)
        wide = jnp.asarray(req["wide"], jnp.float32)
        dense = jnp.asarray(req["dense"], jnp.float32)
        label = jnp.asarray(req["label"], jnp.float32)
        with self._plock:
            params = {"mlp": [{k: jnp.asarray(v) for k, v in l.items()}
                              for l in self.params["mlp"]],
                      "wide_dense": jnp.asarray(self.params["wide_dense"]),
                      "bias": jnp.asarray(self.params["bias"])}
        loss, (gp, d_emb, d_wide) = self._grad_fn(params, emb, wide,
                                                  dense, label)
        with self._plock:
            # local SGD on the dense side (the reference's device-side
            # optimizer in HeterXpuTrainer); sparse updates happen on
            # the CPU/PS side. The delta applies to the CURRENT params,
            # not the pre-grad snapshot — concurrent workers' updates
            # compose (Hogwild) instead of overwriting each other.
            self.params["wide_dense"] = self.params["wide_dense"] \
                - self.lr * np.asarray(gp["wide_dense"])
            self.params["bias"] = self.params["bias"] \
                - self.lr * np.asarray(gp["bias"])
            self.params["mlp"] = [
                {"w": l["w"] - self.lr * np.asarray(g["w"]),
                 "b": l["b"] - self.lr * np.asarray(g["b"])}
                for l, g in zip(self.params["mlp"], gp["mlp"])]
            self.losses.append(float(loss))
        return {"loss": float(loss), "d_emb": np.asarray(d_emb),
                "d_wide": np.asarray(d_wide)}

    def serve_in_thread(self) -> threading.Thread:
        th = threading.Thread(target=self.serve_forever, daemon=True)
        th.start()
        return th


class HeterCpuWorker:
    """Host-CPU sparse worker (reference HeterCpuWorker +
    HeterWrapper::SerializeToReq): embedding IO against the PS tier,
    dense compute delegated to a HeterDenseWorker over TCP."""

    def __init__(self, cfg, dense_endpoint: str,
                 ps_endpoints: list[str] | None = None,
                 lr: float = 1e-2, init_std: float = 0.01):
        self.cfg = cfg
        self.lr = lr
        self.init_std = init_std
        if ps_endpoints:
            self._kv = PSClient(ps_endpoints)
        else:
            self._local: dict[str, LargeScaleKV] = {}
            self._kv = None
        # fault-tolerant channel to the dense tier: retries/reconnects
        # with a stable request id, deduped server-side, so a lost
        # reply never double-applies a dense SGD step
        self._dense = RpcClient(dense_endpoint)
        self.losses: list[float] = []

    @property
    def transport_stats(self) -> dict:
        """Dense-channel + (when remote) PS-channel retry counters."""
        stats = {"dense": self._dense.stats.as_dict()}
        if self._kv is not None:
            stats["ps"] = self._kv.stats.as_dict()
        return stats

    # -- sparse tier ----------------------------------------------------
    def _pull(self, table: str, ids: np.ndarray, dim: int) -> np.ndarray:
        if self._kv is not None:
            return self._kv.pull(table, dim, ids, init_std=self.init_std)
        t = self._local.setdefault(
            table, LargeScaleKV(dim, init_std=self.init_std))
        return t.pull(ids)

    def _push(self, table: str, ids: np.ndarray, grads: np.ndarray,
              dim: int):
        if self._kv is not None:
            self._kv.push(table, dim, ids, grads, self.lr,
                          init_std=self.init_std)
        else:
            self._local[table].push(ids, grads.reshape(len(ids), dim),
                                    self.lr)

    # -- one async step -------------------------------------------------
    def train_one_batch(self, ids, dense, label) -> float:
        cfg = self.cfg
        ids = np.asarray(ids, np.int64)
        B, S = ids.shape
        uids, inv = np.unique(ids.ravel(), return_inverse=True)
        emb_rows = self._pull("embed", uids, cfg.embed_dim)   # [U, D]
        wide_rows = self._pull("wide", uids, 1)               # [U, 1]
        # host-side gather + flatten (the CPU side of the heter split)
        emb = emb_rows[inv].reshape(B, S * cfg.embed_dim)
        wide_sum = wide_rows[inv].reshape(B, S, 1).sum(axis=1)
        rep = self._dense.call({
            "op": "step", "emb": emb.astype(np.float32),
            "wide": wide_sum.astype(np.float32),
            "dense": np.asarray(dense, np.float32),
            "label": np.asarray(label, np.float32)})
        # scatter activation grads back to rows: d_row accumulates over
        # every (b, s) occurrence of the id
        d_emb = np.asarray(rep["d_emb"]).reshape(B * S, cfg.embed_dim)
        d_wide = np.repeat(np.asarray(rep["d_wide"]), S, axis=0)  # [B*S,1]
        g_emb = np.zeros_like(emb_rows)
        np.add.at(g_emb, inv, d_emb)
        g_wide = np.zeros_like(wide_rows)
        np.add.at(g_wide, inv, d_wide)
        self._push("embed", uids, g_emb, cfg.embed_dim)
        self._push("wide", uids, g_wide, 1)
        self.losses.append(rep["loss"])
        return rep["loss"]

    def dense_params(self) -> dict:
        return self._dense.call({"op": "params"})

    def stop_dense(self):
        try:
            self._dense.call({"op": "stop"}, deadline=10.0)
        except (ConnectionError, OSError):
            pass

    def close(self):
        self._dense.close()
        if self._kv is not None:
            self._kv.close()
