"""fleet 2.0 facade (reference python/paddle/distributed/fleet/).

`fleet.init` + `DistributedStrategy` + `distributed_optimizer` — the strategy
bag selects meta-optimizers (amp/recompute/gradient-merge/...) which rewrite
the Program or wrap the optimizer, and the collective runtime maps data
parallelism onto the device mesh.
"""
from .base.distributed_strategy import DistributedStrategy
from .fleet_wrapper import DownpourWorker, FleetWrapper
from .heter_worker import HeterCpuWorker, HeterDenseWorker
from .boxps_cache import BoxPSWrapper
from .base.fleet_base import (Fleet, init, is_first_worker, worker_index,
                              worker_num, is_worker, worker_endpoints,
                              server_num, server_index, server_endpoints,
                              is_server, barrier_worker, init_worker,
                              init_server, run_server, stop_worker,
                              distributed_optimizer, minimize)
from .base.role_maker import PaddleCloudRoleMaker, UserDefinedRoleMaker, Role

__all__ = ["DistributedStrategy", "FleetWrapper", "DownpourWorker", "HeterCpuWorker", "HeterDenseWorker", "BoxPSWrapper", "init", "is_first_worker", "worker_index",
           "worker_num", "is_worker", "worker_endpoints", "server_num",
           "server_index", "server_endpoints", "is_server", "barrier_worker",
           "init_worker", "init_server", "run_server", "stop_worker",
           "distributed_optimizer", "minimize", "PaddleCloudRoleMaker",
           "UserDefinedRoleMaker", "Role", "Fleet"]
