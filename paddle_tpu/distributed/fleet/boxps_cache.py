"""BoxPS-style hot-row sparse cache (reference
/root/reference/paddle/fluid/framework/fleet/box_wrapper.h:1).

The reference's BoxPS keeps the hottest embedding rows resident in GPU
memory in front of the external PS, serving pulls device-side and
exchanging only aggregated deltas with the PS. The TPU-native analog:
the worker keeps a hot-vocab cache resident near the compute (HBM on a
TPU host, plain RAM for CPU-role workers), applies its own updates
locally for read-your-writes semantics, accumulates the deltas, and
flushes the aggregate to the PS every `flush_every` batches — the same
traffic shape as BoxPS's BeginPass/EndPass pull-push cycle.

`BoxPSWrapper` exposes the FleetWrapper sparse/dense surface, so
`DownpourWorker(BoxPSWrapper(fw), ...)` upgrades any PS job to the
cached path without touching the trainer.
"""
from __future__ import annotations

import numpy as np

from .runtime.rpc import PSRemoteError

__all__ = ["BoxPSWrapper"]


class _TableCache:
    """Vectorised hot-row store: a direct-index id->slot map (ids below
    `id_space`) over preallocated row/delta arrays — python-loop-free on
    the 100k-ids-per-batch CTR path."""

    def __init__(self, dim: int, capacity: int, id_space: int):
        self.dim = dim
        self.capacity = capacity
        self.id_space = id_space
        self.slot_of = np.full(id_space, -1, np.int32)
        self.ids = np.zeros(capacity, np.int64)
        self.data = np.zeros((capacity, dim), np.float32)
        self.delta = np.zeros((capacity, dim), np.float32)
        self.dirty = np.zeros(capacity, bool)   # slots touched since flush
        self.stale = np.zeros(capacity, bool)   # invalidated by the PS
        self.n = 0

    def ensure(self, kv_pull, uids: np.ndarray):
        """Admit missing (in-space) ids up to capacity with one PS pull."""
        uids = uids[uids < self.id_space]
        missing = uids[self.slot_of[uids] < 0]
        room = self.capacity - self.n
        missing = missing[:max(room, 0)]
        if len(missing):
            rows = kv_pull(missing)
            idx = np.arange(self.n, self.n + len(missing), dtype=np.int32)
            self.slot_of[missing] = idx
            self.ids[idx] = missing
            self.data[idx] = rows
            self.n += len(missing)

    def lookup(self, ids: np.ndarray) -> np.ndarray:
        idx = np.full(len(ids), -1, np.int32)
        ok = ids < self.id_space
        idx[ok] = self.slot_of[ids[ok]]
        return idx

    def invalidate(self, keys: np.ndarray | None = None):
        """Mark cached rows stale (keys=None: the whole table). Safe to
        call from the PS subscription thread concurrently with pulls:
        it only SETS per-slot flags — the worst interleaving refreshes
        a row one pull later, never serves it as fresh."""
        if keys is None:
            self.stale[:self.n] = True
            return
        keys = np.asarray(keys, np.int64).ravel()
        keys = keys[keys < self.id_space]
        slots = self.slot_of[keys]
        self.stale[slots[slots >= 0]] = True

    def refresh_stale(self, kv_pull) -> int:
        """Re-pull every stale row; re-apply the locally-buffered
        (unflushed) delta on top so read-your-writes holds: the local
        view is authoritative-PS-value minus the pending delta."""
        sl = np.flatnonzero(self.stale[:self.n])
        if not len(sl):
            return 0
        fresh = kv_pull(self.ids[sl])
        self.data[sl] = fresh - self.delta[sl]
        self.stale[sl] = False
        return len(sl)


class BoxPSWrapper:
    """FleetWrapper facade with a hot-row cache on the sparse tables."""

    def __init__(self, fleet_wrapper, capacity: int = 1 << 20,
                 flush_every: int = 8, id_space: int = 1 << 22):
        self.fw = fleet_wrapper
        self.capacity = capacity
        self.flush_every = flush_every
        self.id_space = id_space
        self._tables: dict[str, _TableCache] = {}
        self._batches = 0
        self._first_table = None
        self.cache_hits = 0
        self.cache_misses = 0
        self.stale_refreshes = 0   # rows re-pulled after invalidation
        self._inval_stop = None

    def _table(self, name: str, dim: int) -> _TableCache:
        t = self._tables.get(name)
        if t is None:
            t = self._tables[name] = _TableCache(dim, self.capacity,
                                                 self.id_space)
        return t

    # -- sparse (cached) ------------------------------------------------
    def pull_sparse(self, table: str, ids, dim: int,
                    init_std: float = 0.01) -> np.ndarray:
        ids = np.asarray(ids, np.int64).ravel()
        t = self._table(table, dim)
        # batch accounting: a new batch starts when the FIRST-registered
        # table is pulled again (DownpourWorker pulls every table once
        # per batch); the flush runs at batch boundaries so flush_every
        # counts BATCHES, not push calls
        if self._first_table is None:
            self._first_table = table
        if table == self._first_table:
            self._batches += 1
            if self._batches > 1 and (self._batches - 1) \
                    % self.flush_every == 0:
                self.flush()
        t.ensure(lambda m: self.fw.pull_sparse(table, m, dim,
                                               init_std=init_std),
                 np.unique(ids))
        # PS-pushed invalidations (other workers' flushed updates) land
        # as stale flags; refresh them before serving from the cache
        self.stale_refreshes += t.refresh_stale(
            lambda m: self.fw.pull_sparse(table, m, dim,
                                          init_std=init_std))
        idx = t.lookup(ids)
        hit = idx >= 0
        self.cache_hits += int(hit.sum())
        self.cache_misses += int((~hit).sum())
        out = np.empty((len(ids), dim), np.float32)
        out[hit] = t.data[idx[hit]]
        if (~hit).any():  # over-capacity ids pass through uncached
            out[~hit] = self.fw.pull_sparse(table, ids[~hit], dim,
                                            init_std=init_std)
        return out

    def push_sparse(self, table: str, ids, grads, dim: int,
                    lr: float = 1.0, init_std: float = 0.01):
        ids = np.asarray(ids, np.int64).ravel()
        grads = np.asarray(grads, np.float32).reshape(len(ids), dim)
        t = self._table(table, dim)
        idx = t.lookup(ids)
        hit = idx >= 0
        if hit.any():
            # local apply (read-your-writes) + delta accumulation for
            # the periodic PS flush — BoxPS device-side update semantics.
            # delta carries the lr-scaled update so the flush is lr-free
            # (pushes with mixed lrs accumulate correctly)
            np.add.at(t.data, idx[hit], -lr * grads[hit])
            np.add.at(t.delta, idx[hit], lr * grads[hit])
            t.dirty[idx[hit]] = True
        if (~hit).any():
            self.fw.push_sparse(table, ids[~hit], grads[~hit], dim,
                                lr=lr, init_std=init_std)

    def flush(self, refresh: bool = True):
        """Push accumulated deltas, then (BoxPS EndPass) re-pull the
        dirty rows so the cache picks up other workers' merged updates.
        Only the per-interval aggregate crosses the wire — 1/flush_every
        of the uncached pull+push traffic.

        Fault tolerance: a table whose push fails past the transport's
        retry deadline KEEPS its delta/dirty state so the update is not
        silently lost (the next flush re-sends it; within a single push
        the transport's request-id dedup keeps retries exactly-once —
        only a deadline-exceeded push abandoned mid-fanout can
        double-apply on shards that already committed, see
        docs/PS_WIRE_PROTOCOL.md), and the remaining tables still
        flush; the first error re-raises at the end so the caller sees
        the degraded shard."""
        first_err: Exception | None = None
        for name, t in self._tables.items():
            dirty = np.flatnonzero(t.dirty[:t.n])
            if not len(dirty):
                continue
            try:
                self.fw.push_sparse(name, t.ids[dirty], t.delta[dirty],
                                    t.dim, lr=1.0)
            except (ConnectionError, OSError, PSRemoteError) as e:
                # transport outage OR a server-side dispatch error on
                # this table: either way the other tables still flush
                first_err = first_err or e
                continue
            t.delta[dirty] = 0.0
            t.dirty[dirty] = False
            if refresh:
                try:
                    t.data[dirty] = self.fw.pull_sparse(
                        name, t.ids[dirty], t.dim)
                except (ConnectionError, OSError, PSRemoteError) as e:
                    # push landed; only the EndPass refresh failed —
                    # the rows stay locally-consistent (stale vs other
                    # workers until the next successful refresh) and
                    # the remaining tables still flush
                    first_err = first_err or e
        if first_err is not None:
            raise first_err

    # -- PS-pushed invalidation wiring (PR 11) --------------------------
    def invalidate(self, table: str, keys=None):
        """Invalidation callback: mark cached rows of ``table`` stale
        (keys=None invalidates the whole table). Shaped to plug
        straight into PSClient.subscribe_invalidations."""
        t = self._tables.get(table)
        if t is not None:
            t.invalidate(keys)

    def attach_invalidations(self, ps_client=None) -> bool:
        """Subscribe this cache to the PS shards' push-invalidation
        stream, so other workers' flushed updates stop being served
        stale between this worker's own flushes. Defaults to the
        wrapped FleetWrapper's own PSClient; returns False when there
        is none (local mode)."""
        if ps_client is None:
            ps_client = getattr(self.fw, "_client", None)
        if ps_client is None:
            return False
        self._inval_stop = ps_client.subscribe_invalidations(
            self.invalidate)
        return True

    def detach_invalidations(self):
        if self._inval_stop is not None:
            self._inval_stop.set()
            self._inval_stop = None

    # -- dense + misc pass-through --------------------------------------
    def pull_dense(self, name, shape):
        return self.fw.pull_dense(name, shape)

    def push_dense(self, name, grad, lr: float = 1.0):
        return self.fw.push_dense(name, grad, lr=lr)

    def __getattr__(self, item):
        return getattr(self.fw, item)
