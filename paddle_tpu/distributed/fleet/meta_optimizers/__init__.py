"""Meta-optimizer stack (reference distributed/fleet/meta_optimizers/ +
base/strategy_compiler.py:41).

Each meta-optimizer wraps the inner optimizer when its strategy flag is on;
compatible ones compose (amp ∘ recompute ∘ gradient_merge ∘ base)."""
from __future__ import annotations

__all__ = ["apply_meta_optimizers"]


def apply_meta_optimizers(optimizer, strategy, role_maker):
    from ....fluid import optimizer as fopt
    opt = optimizer
    if strategy is None:
        return opt
    if getattr(opt, "_static_optimizer", None):
        opt = opt._static_optimizer()  # unwrap 2.0 wrapper to fluid opt
    if strategy.lamb and hasattr(opt, "_learning_rate"):
        cfg = strategy.lamb_configs
        opt = fopt.LambOptimizer(
            learning_rate=opt._learning_rate,
            lamb_weight_decay=cfg["lamb_weight_decay"])
    if strategy.lars and hasattr(opt, "_learning_rate"):
        cfg = strategy.lars_configs
        opt = fopt.LarsMomentumOptimizer(
            learning_rate=opt._learning_rate,
            momentum=getattr(opt, "_momentum", 0.9),
            lars_coeff=cfg.get("lars_coeff", 0.001),
            lars_weight_decay=cfg.get("lars_weight_decay", 0.0005),
            epsilon=cfg.get("epsilon", 0.0),
            parameter_list=getattr(opt, "_parameter_list", None))
    if strategy.dgc and hasattr(opt, "_learning_rate"):
        cfg = strategy.dgc_configs
        opt = fopt.DGCMomentumOptimizer(
            learning_rate=opt._learning_rate,
            momentum=getattr(opt, "_momentum", 0.9),
            rampup_begin_step=cfg.get("rampup_begin_step", 0),
            rampup_step=cfg.get("rampup_step", 1),
            sparsity=tuple(cfg.get("sparsity", (0.999,))),
            parameter_list=getattr(opt, "_parameter_list", None))
    if strategy.recompute:
        opt = fopt.RecomputeOptimizer(opt)
        opt._set_checkpoints(strategy.recompute_configs.get("checkpoints"))
    if strategy.gradient_merge:
        cfg = strategy.gradient_merge_configs
        opt = fopt.GradientMergeOptimizer(opt, cfg["k_steps"], cfg["avg"])
    if strategy.localsgd:
        cfg = strategy.localsgd_configs
        opt = fopt.LocalSGDOptimizer(opt, k_steps=cfg.get("k_steps", 1),
                                     begin_step=cfg.get("begin_step", 1))
    if strategy.amp:
        from ....amp.static_decorator import decorate_static
        opt = decorate_static(opt, strategy.amp_configs)
    return opt
