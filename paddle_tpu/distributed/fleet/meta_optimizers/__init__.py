"""Meta-optimizer stack (reference distributed/fleet/meta_optimizers/ +
base/strategy_compiler.py:41).

Each meta-optimizer wraps the inner optimizer when its strategy flag is on;
compatible ones compose (amp ∘ recompute ∘ gradient_merge ∘ base)."""
from __future__ import annotations

__all__ = ["apply_meta_optimizers"]


def apply_meta_optimizers(optimizer, strategy, role_maker):
    from ....fluid import optimizer as fopt
    opt = optimizer
    if strategy is None:
        return opt
    if getattr(opt, "_static_optimizer", None):
        opt = opt._static_optimizer()  # unwrap 2.0 wrapper to fluid opt
    if strategy.lamb and hasattr(opt, "_learning_rate"):
        cfg = strategy.lamb_configs
        opt = fopt.LambOptimizer(
            learning_rate=opt._learning_rate,
            lamb_weight_decay=cfg["lamb_weight_decay"])
    if strategy.recompute:
        opt = fopt.RecomputeOptimizer(opt)
        opt._set_checkpoints(strategy.recompute_configs.get("checkpoints"))
    if strategy.gradient_merge:
        cfg = strategy.gradient_merge_configs
        opt = fopt.GradientMergeOptimizer(opt, cfg["k_steps"], cfg["avg"])
    if strategy.amp:
        from ....amp.static_decorator import decorate_static
        opt = decorate_static(opt, strategy.amp_configs)
    return opt
