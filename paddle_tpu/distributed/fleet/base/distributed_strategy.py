"""DistributedStrategy (reference distributed/fleet/base/distributed_strategy.py:101).

The same strategy-bag surface (amp, recompute, pipeline, gradient_merge,
lamb/lars, localsgd, a_sync...) plus TPU-native extensions the reference
lacks: sharding_degree/mp_degree (tensor parallel), sp_degree (sequence/
context parallel) — SURVEY §2.9 flags these as "absent in reference; supply
natively".  Serialisable to dict for job configs.
"""
from __future__ import annotations

import copy

__all__ = ["DistributedStrategy"]

_DEFAULTS = {
    # execution
    "auto": False,
    "a_sync": False,
    "a_sync_configs": {"k_steps": -1, "max_merge_var_num": 20,
                       "send_queue_size": 20,
                       "independent_recv_thread": False,
                       "thread_pool_size": 1, "send_wait_times": 1,
                       "runtime_split_send_recv": False, "launch_barrier": True,
                       "geo_sgd_mode": False, "geo_sgd_need_push_nums": 100},
    # amp
    "amp": False,
    "amp_configs": {"init_loss_scaling": 32768.0,
                    "incr_every_n_steps": 1000,
                    "decr_every_n_nan_or_inf": 2, "incr_ratio": 2.0,
                    "decr_ratio": 0.5, "use_dynamic_loss_scaling": True,
                    "custom_white_list": [], "custom_black_list": [],
                    "use_pure_bf16": True},
    # recompute
    "recompute": False,
    "recompute_configs": {"checkpoints": []},
    # pipeline
    "pipeline": False,
    "pipeline_configs": {"micro_batch": 1, "accumulate_steps": 1,
                         "schedule_mode": "1F1B"},
    # gradient merge
    "gradient_merge": False,
    "gradient_merge_configs": {"k_steps": 1, "avg": True},
    # optimizers
    "lamb": False,
    "lamb_configs": {"lamb_weight_decay": 0.01,
                     "exclude_from_weight_decay": []},
    "lars": False,
    "lars_configs": {"lars_coeff": 0.001, "lars_weight_decay": 0.0005,
                     "epsilon": 0.0, "exclude_from_weight_decay": []},
    "localsgd": False,
    "localsgd_configs": {"k_steps": 1, "begin_step": 1},
    "dgc": False,
    "dgc_configs": {"rampup_begin_step": 0, "rampup_step": 1,
                    "sparsity": [0.999]},
    # collective tuning (kept for parity; XLA handles fusion/rings)
    "fuse_all_reduce_ops": True,
    "fuse_grad_size_in_MB": 32,
    "nccl_comm_num": 1,
    "sync_nccl_allreduce": True,
    "use_hierarchical_allreduce": False,
    "hierarchical_allreduce_inter_nranks": 1,
    "sync_batch_norm": False,
    "fuse_grad_merge": False,
    "cudnn_exhaustive_search": False,
    "conv_workspace_size_limit": 512,
    "cudnn_batchnorm_spatial_persistent": False,
    # TPU-native extensions (absent in reference — SURVEY §2.9 TP/SP/EP rows)
    "tensor_parallel": False,
    # sharding_rules: [(param-name-regex, partition-spec-tuple)], e.g.
    # ("fc_.*\\.w_0", (None, "tp")) — consumed by the static Executor, which
    # device_puts matching persistables with NamedSharding over the
    # ("dp","tp") mesh and lets GSPMD insert the collectives.
    "tensor_parallel_configs": {"tensor_parallel_degree": 1,
                                "sharding_rules": []},
    # hybrid dp x pp x tp for the functional engine
    # (parallel.HybridParallelTrainStep via fleet.hybrid_train_step)
    "hybrid_configs": {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                       "sp_degree": 1, "micro_batches": None},
    "sharding": False,
    "sharding_configs": {"sharding_degree": 1, "stage": 1},
    "sequence_parallel": False,
    "sequence_parallel_configs": {"sp_degree": 1, "ring_attention": True},
    "expert_parallel": False,
    "expert_parallel_configs": {"ep_degree": 1},
}


class DistributedStrategy:
    def __init__(self):
        self.__dict__["_d"] = copy.deepcopy(_DEFAULTS)

    def __getattr__(self, k):
        d = self.__dict__["_d"]
        if k in d:
            return d[k]
        raise AttributeError(k)

    def __setattr__(self, k, v):
        d = self.__dict__["_d"]
        if k not in d:
            raise ValueError(f"unknown strategy field {k!r}")
        if k.endswith("_configs"):
            merged = dict(_DEFAULTS[k])
            merged.update(v)
            d[k] = merged
        else:
            d[k] = v

    def to_dict(self) -> dict:
        return copy.deepcopy(self.__dict__["_d"])

    @classmethod
    def from_dict(cls, d: dict) -> "DistributedStrategy":
        s = cls()
        s.__dict__["_d"].update(copy.deepcopy(d))
        return s

    def save_to_prototxt(self, path):
        import json
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2)

    def load_from_prototxt(self, path):
        import json
        with open(path) as f:
            self.__dict__["_d"].update(json.load(f))

    def __repr__(self):
        on = [k for k, v in self.__dict__["_d"].items()
              if isinstance(v, bool) and v]
        return f"DistributedStrategy(enabled={on})"
